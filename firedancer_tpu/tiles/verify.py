"""The TPU sig-verify bridge tile — this build's analog of the reference's
verify tile (src/app/fdctl/run/tiles/fd_verify.c) and of the wiredancer
FPGA offload (src/wiredancer/c/wd_f1.c).

Round-3 redesign: ASYNCHRONOUS push-request / push-result dispatch, the
defining wiredancer property (src/wiredancer/README.md "Pipeline Design":
the ring never waits on the accelerator).  The mux loop stages host-side
work (gather, trailer parse, lane expansion) and pushes prepared batches
to a device worker thread; the worker keeps several batches in flight
(dispatch N+1 while N computes — JAX dispatch is async, the only true
sync on this platform is the device-to-host copy) and lands results on a
lock-free deque; the mux loop publishes landed results downstream as
credits allow.  Upstream backpressure propagates through `in_budget`:
when the request queue is full the tile stops draining its in-ring and
the ring's credit model takes over — exactly the reference's flow-control
discipline, with the device behind the same tile/link boundary.

Round-6 scale-out: the single worker became a DEVICE POOL (`_DevicePool`)
— one worker thread (with its own in-flight pipeline, i.e. the double
buffer) per local accelerator, a least-in-flight scheduler with
round-robin tie-break, an in-flight cap per device, and an in-order
landing buffer so results still publish in arrival-seq order across
devices.  Each device is its own FAULT DOMAIN (`DevicePolicy`): a device
that errors or stalls past its patience is quarantined with capped
backoff and its in-flight batches are resubmitted to healthy devices;
the strict host path (ops/ed25519/hostpath.py) remains the last resort
when every device is out.  This is the layer that converts the ALU-bound
per-chip ceiling (PROFILE.md round 5: ~390K verifies/s/chip) into a
linear-in-devices aggregate — the same conclusion that drove the
reference to scale sig-verify across tiles and wiredancer FPGA lanes.

Batch discipline: lane counts are padded up to power-of-two buckets so
XLA compiles a handful of static shapes, then reuses them forever.  All
per-frag work is vectorized numpy; the Python loop body is O(1) per batch.
"""

from __future__ import annotations

import collections
import queue
import threading
import time

import numpy as np

from firedancer_tpu.disco import trace as SPAN
from firedancer_tpu.disco.metrics import MetricsSchema, device_counters
from firedancer_tpu.disco.mux import MuxCtx, Tile, now_ts
from firedancer_tpu.tango import rings as R

from . import wire

#: reference: VERIFY_TCACHE_DEPTH 16 (fd_verify.h:6) — a tiny per-tile
#: pre-dedup catching back-to-back duplicates before they burn device time
PRE_DEDUP_DEPTH = 16

_STOP = object()


class FallbackPolicy:
    """Graceful degradation for the batched device-verify path.

    Wraps the device dispatch in a catch → host-retry → circuit-trip
    state machine: a TPU/Pallas dispatch (or D2H sync) error reroutes
    THAT batch through the strict host verifier
    (ops/ed25519/hostpath.py) instead of killing the tile; `trip_after`
    consecutive device failures latch host-only mode, and every
    `reprobe_every` batches one batch re-probes the device so a
    recovered accelerator is picked back up automatically.

    `fault_hook` is the faultinj device_error injection point — called
    once per device-batch attempt, raising a scripted DeviceFault that
    exercises exactly the production failure path.

    Counter attributes are mirrored into the tile's shared metrics
    (fallback_batches etc.) by VerifyTile so a monitor process sees the
    degradation state live.
    """

    #: set by the pool's stall watchdog while a device call is wedged
    #: past its patience (DevicePolicy only; the classic single-device
    #: policy never stalls — its worker's host fallback is in-line)
    stalled = False

    def __init__(
        self,
        device_fn,
        host_fn,
        *,
        trip_after: int = 3,
        reprobe_every: int = 64,
        fault_hook=None,
    ):
        self.device_fn = device_fn
        self.host_fn = host_fn
        self.trip_after = max(trip_after, 1)
        self.reprobe_every = max(reprobe_every, 1)
        self.fault_hook = fault_hook
        self.consec_failures = 0
        self.tripped = False  # latched host-only mode
        self._since_trip = 0
        # counters (mirrored into metrics by the owning tile)
        self.fallback_batches = 0
        self.device_errors = 0
        self.device_trips = 0
        self.host_reprobes = 0

    def healthy(self, now: float | None = None) -> bool:
        """Schedulable by the pool.  The classic policy always is — it
        degrades to the host path internally, per batch."""
        return True

    def _try_device(self) -> bool:
        if self.device_fn is None:
            return False
        if not self.tripped:
            return True
        self._since_trip += 1
        if self._since_trip >= self.reprobe_every:
            self._since_trip = 0
            self.host_reprobes += 1
            return True
        return False

    def _device_failed(self) -> None:
        self.device_errors += 1
        self.consec_failures += 1
        if (
            not self.tripped
            and self.consec_failures >= self.trip_after
        ):
            self.tripped = True
            self.device_trips += 1
            self._since_trip = 0

    def dispatch(self, args):
        """Start a batch.  Device dispatch is async (returns a future);
        the host path defers all work to land()."""
        if self._try_device():
            try:
                if self.fault_hook is not None:
                    self.fault_hook()
                return ("dev", self.device_fn(*args))
            except Exception:
                self._device_failed()
        return ("host", None)

    def land(self, fut, args, lanes: int | None = None) -> np.ndarray:
        """Finish a batch: sync the device future (where JAX's async
        dispatch surfaces runtime errors) or run the host verifier."""
        kind, val = fut
        if kind == "dev":
            try:
                out = np.asarray(val)
                self.consec_failures = 0
                if self.tripped:
                    self.tripped = False  # re-probe succeeded: recovered
                return out
            except Exception:
                self._device_failed()
        if self.device_fn is not None:
            # fallback_batches measures DEGRADATION — batches a
            # configured device failed to serve.  An intentional
            # host-only tile (device="off") is healthy, not degraded:
            # counting it would leave monitors alarming forever on
            # CPU-only deployments.
            self.fallback_batches += 1
        return self.host_fn(*args, lanes=lanes)


class DevicePolicy(FallbackPolicy):
    """One device's FAULT DOMAIN inside a multi-device pool.

    Differs from the classic FallbackPolicy in who owns recovery: the
    classic policy reroutes a failed batch to the host path itself; a
    pool domain hands the batch BACK (dispatch/land return a failure)
    so the scheduler can resubmit it to a HEALTHY device first and only
    fall to the host when every device is out.  The breaker is
    time-based: `trip_after` consecutive failures quarantine the device
    for a capped-exponential backoff (`backoff_base_s`..`backoff_max_s`),
    after which the next scheduled batch re-probes it.

    `stall_patience_s` is the round-5 "120 s tunnel stall" patience,
    moved from the global pipeline into this per-device breaker: a
    device call wedged past the patience degrades only ITS device (the
    pool marks `stalled`, quarantines, and redistributes its in-flight
    batches); the other devices keep verifying.
    """

    def __init__(
        self,
        device_fn,
        host_fn,
        *,
        index: int = 0,
        trip_after: int = 3,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 30.0,
        stall_patience_s: float = 120.0,
        fault_hook=None,
    ):
        super().__init__(
            device_fn, host_fn, trip_after=trip_after, fault_hook=fault_hook
        )
        self.index = index
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.stall_patience_s = stall_patience_s
        self.backoff_s = 0.0
        self.quarantined_until = 0.0
        self.stalled = False
        self.device_stalls = 0

    def healthy(self, now: float | None = None) -> bool:
        if self.stalled or self.device_fn is None:
            return False
        if not self.tripped:
            return True
        if now is None:
            now = time.monotonic()
        return now >= self.quarantined_until  # backoff expired: re-probe

    def _try_device(self) -> bool:
        if self.device_fn is None or self.stalled:
            return False
        if not self.tripped:
            return True
        if time.monotonic() >= self.quarantined_until:
            self.host_reprobes += 1  # (re-)probe of a quarantined device
            return True
        return False

    def _quarantine(self) -> None:
        """Trip the breaker with capped exponential backoff: each failed
        (re-)probe doubles the backoff, a success (in land) resets it."""
        if not self.tripped:
            self.device_trips += 1
        self.tripped = True
        self.backoff_s = (
            self.backoff_base_s
            if not self.backoff_s
            else min(self.backoff_s * 2.0, self.backoff_max_s)
        )
        self.quarantined_until = time.monotonic() + self.backoff_s

    def _device_failed(self) -> None:
        self.device_errors += 1
        self.consec_failures += 1
        if self.consec_failures >= self.trip_after:
            self._quarantine()

    def mark_stalled(self) -> None:
        """Pool stall watchdog: the device call is wedged past patience.
        Quarantine so the scheduler routes around it; the flag clears
        when the wedged call finally returns (the worker owns that)."""
        self.stalled = True
        self.device_stalls += 1
        self._quarantine()

    def dispatch(self, args):
        if self._try_device():
            try:
                if self.fault_hook is not None:
                    self.fault_hook(self.index)
                return ("dev", self.device_fn(*args))
            except Exception:
                self._device_failed()
                return ("fail", None)
        return ("fail", None)  # quarantined: the pool redistributes

    def land(self, fut, args, lanes: int | None = None):
        kind, val = fut
        if kind == "dev":
            try:
                out = np.asarray(val)
                self.consec_failures = 0
                self.tripped = False
                self.backoff_s = 0.0
                return out
            except Exception:
                self._device_failed()
                return None  # the pool resubmits elsewhere
        if kind == "host":
            if self.device_fn is not None:
                self.fallback_batches += 1
            return self.host_fn(*args, lanes=lanes)
        return None  # "fail": never dispatched (quarantine raced)


class _DeviceWorker:
    """Push-request/push-result engine (the wd_f1.c interface shape).

    One dedicated thread owns all interaction with ONE device.  `depth`
    batches ride in flight: the thread dispatches every queued request
    before it blocks on the oldest result's D2H copy, so transfer and
    compute of batch N+1 overlap the sync of batch N (the double
    buffer).  All dispatch/land calls go through the policy, so a device
    failure degrades (classic) or surfaces to the pool (DevicePolicy)
    instead of killing this thread.

    Accounting contract: every submitted batch is exactly one of
    landed (a results entry), still queued/in flight (visible in
    `reqq`/`pending`), or drained back by `abort()` — never silently
    dropped.  `pending` entries are appended BEFORE dispatch and popped
    only AFTER their land completes, so a wedge inside a device call
    keeps that batch recoverable.
    """

    def __init__(self, policy: FallbackPolicy, depth: int = 3,
                 name: str = "verify-dev"):
        self.policy = policy
        self.depth = depth
        self.reqq: queue.Queue = queue.Queue(maxsize=depth)
        self.results: collections.deque = collections.deque()
        self.pending: collections.deque = collections.deque()
        self.error: BaseException | None = None
        self.aborted = False
        #: single-writer counters: submitted_n by the submitting (mux)
        #: thread, completed_n by this worker thread; the difference is
        #: the in-flight load the scheduler balances on
        self.submitted_n = 0
        self.completed_n = 0
        #: landed batches accepted by the pool (pool/mux thread only)
        self.landed_n = 0
        #: monotonic timestamp while inside a device call — dispatch
        #: (the H2D put can wedge in the tunnel) or land (the D2H sync)
        #: — read by the pool's stall watchdog; 0.0 = not in a call
        self.land_t0 = 0.0
        self.thread = threading.Thread(
            target=self._main, name=name, daemon=True
        )
        self.thread.start()

    def inflight(self) -> int:
        return self.submitted_n - self.completed_n

    def alive(self) -> bool:
        return self.error is None and self.thread.is_alive()

    def submit(self, meta, args, mode: str = "auto") -> None:
        """Single-submitter (mux thread); the caller checks reqq.full()
        first, so this never blocks."""
        self.reqq.put_nowait((meta, args, mode))
        self.submitted_n += 1

    def stop(self, timeout_s: float | None = None) -> None:
        deadline = (
            None if timeout_s is None else time.monotonic() + timeout_s
        )
        while self.thread.is_alive():
            try:
                self.reqq.put(_STOP, timeout=0.1)
                break
            except queue.Full:
                # a dead worker never drains: is_alive re-checks.  A
                # WEDGED worker never drains either — the deadline must
                # bound this loop too, or a stop under a full queue
                # spins forever and the halt path never returns
                if deadline is not None and time.monotonic() >= deadline:
                    break
        self.thread.join(
            None if deadline is None
            else max(deadline - time.monotonic(), 0.0)
        )

    def abort(self, timeout_s: float = 10.0) -> list[tuple]:
        """Teardown that cannot orphan work: stop (or abandon, if
        wedged) the thread, then drain every batch it never landed —
        queued submissions AND the in-flight `pending` entries (a land
        wedged inside a device call keeps its batch there) — back to
        the caller for resubmission or deliberate discard."""
        self.aborted = True
        try:
            self.reqq.put_nowait(_STOP)
        except queue.Full:
            pass
        self.thread.join(timeout=timeout_s)
        drained: list[tuple] = []
        while True:
            try:
                item = self.reqq.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP:
                drained.append(item)
        # liveness BEFORE the pending snapshot: a slow-but-not-wedged
        # worker can finish its in-flight land right after the join
        # timeout — snapshotting first would count that batch in both
        # completed_n and drained and fire the assert spuriously.  Once
        # dead here, counters and pending are final.  A still-alive
        # thread (wedged, or merely slower than the join timeout) can
        # popleft/append concurrently, so the snapshot retries on the
        # deque's mutated-during-iteration error rather than letting it
        # escape into the crash-recovery path.
        alive = self.thread.is_alive()
        while True:
            try:
                snap = [(m, a, md) for m, a, md, _ in self.pending]
                break
            except RuntimeError:
                continue
        drained.extend(snap)
        if not alive:
            # the thread exited: counters are final — prove no batch
            # was silently dropped (the pre-fix abort lost queued metas
            # when a land wedged)
            assert self.submitted_n == self.completed_n + len(drained), (
                f"device worker dropped batches: submitted "
                f"{self.submitted_n} != landed {self.completed_n} + "
                f"drained {len(drained)}"
            )
        return drained

    def _main(self) -> None:
        pending = self.pending
        stopped = False
        try:
            while not (stopped and not pending):
                if self.aborted:
                    return
                while not stopped and len(pending) < self.depth:
                    try:
                        item = self.reqq.get(
                            block=not pending, timeout=0.02
                        )
                    except queue.Empty:
                        break
                    if item is _STOP:
                        stopped = True
                        break
                    meta, args, mode = item
                    # enter the accounting BEFORE dispatch: a dispatch
                    # that wedges must leave the batch recoverable
                    slot = [meta, args, mode, None]
                    pending.append(slot)
                    # span timestamps ride the meta dict (plain writes on
                    # this worker thread); the MUX thread turns them into
                    # DISPATCH/LAND span events when the batch lands —
                    # the span ring itself stays single-writer
                    meta["t_disp"] = now_ts()
                    meta["t_dev"] = getattr(self.policy, "index", 0)
                    if mode == "host":
                        slot[3] = ("host", None)
                    else:
                        # async dispatch: returns immediately — but the
                        # H2D put inside it can wedge (tunnel stall), so
                        # the watchdog window covers it too
                        self.land_t0 = time.monotonic()
                        slot[3] = self.policy.dispatch(args)
                        self.land_t0 = 0.0
                if pending:
                    meta, args, mode, fut = pending[0]
                    if fut is None:  # pragma: no cover - abort raced
                        fut = ("fail", None)
                    # D2H copy is the only reliable sync on this platform
                    self.land_t0 = time.monotonic()
                    ok = self.policy.land(fut, args, meta["lanes"])
                    self.land_t0 = 0.0
                    meta["t_land"] = now_ts()
                    self.policy.stalled = False  # the call returned
                    self.completed_n += 1
                    pending.popleft()
                    self.results.append((meta, ok))
        except BaseException as e:  # noqa: BLE001 — surfaced by the tile
            self.error = e


class _DevicePool:
    """N per-device workers behind one submit/land facade.

    Scheduler: least-in-flight across healthy domains, ties broken
    round-robin; per-device in-flight cap = the worker queue depth.
    When no device is healthy, batches go out in `mode="host"` — the
    strict host path as last resort — on any responsive worker.

    Landing is IN ORDER: every batch gets a monotonically increasing
    `pool_seq` at first submit; completed batches park in a reorder
    buffer and `ready` hands them out strictly by seq, so downstream
    publish order is identical to a single serialized stream no matter
    how devices interleave.

    Fault handling: a failed batch (device error) or a quarantined/
    stalled/dead domain's in-flight work is resubmitted — same seq —
    to another domain.  Late results from a domain a batch was moved
    away from are dropped by an assignment check, which is what makes
    "zero lost, zero duplicated" hold through stall recovery races.

    Thread model: submit/poll/abort run on the owning tile's mux
    thread only; workers touch only their own queues/results.
    """

    def __init__(self, policies: list, depth: int = 3, name: str = "verify"):
        self.policies = policies
        self.workers = [
            _DeviceWorker(p, depth, name=f"{name}-dev{i}")
            for i, p in enumerate(policies)
        ]
        self.aborted = False
        self.next_seq = 0
        self.landed_seq = 0
        self.reorder: dict[int, tuple] = {}
        #: seq -> [meta, args, mode, domain_idx]; the live assignment
        self.outstanding: dict[int, list] = {}
        #: evicted batches waiting for a domain with room
        self.retryq: collections.deque = collections.deque()
        #: in-order completed batches, consumed by the tile
        self.ready: collections.deque = collections.deque()
        self.rr = 0
        self.resubmits = 0
        self.late_results = 0
        self._evicted: set[int] = set()
        self._stopping = False

    # ---- scheduling -----------------------------------------------------

    def _domain_ok(self, i: int) -> bool:
        w = self.workers[i]
        return w.alive() and not self.policies[i].stalled

    def _pick(self, peek: bool = False) -> tuple[int | None, str]:
        now = time.monotonic()
        n = len(self.workers)
        cand = [
            i for i in range(n)
            if self._domain_ok(i) and self.policies[i].healthy(now)
        ]
        mode = "auto"
        if not cand:
            # every device quarantined/stalled/dead: strict host path on
            # any still-responsive worker is the last resort
            mode = "host"
            cand = [i for i in range(n) if self._domain_ok(i)]
        open_ = [i for i in cand if not self.workers[i].reqq.full()]
        if not open_:
            return None, mode
        best, best_load = None, None
        for j in range(len(open_)):
            i = open_[(self.rr + j) % len(open_)]
            load = self.workers[i].inflight()
            if best is None or load < best_load:
                best, best_load = i, load
        if not peek:
            self.rr = (self.rr + 1) % max(n, 1)
        return best, mode

    def can_accept(self) -> bool:
        """Room for NEW work: evicted batches retry first (publishing is
        seq-ordered, so head-of-line seqs must not starve)."""
        if self.retryq:
            return False
        return self._pick(peek=True)[0] is not None

    def submit(self, meta, args) -> bool:
        """Schedule one new batch; False = no capacity (caller holds it
        staged and retries — ring backpressure does the rest)."""
        self.pump()
        if self.retryq:
            return False
        tgt, mode = self._pick()
        if tgt is None:
            return False
        seq = self.next_seq
        self.next_seq += 1
        meta["pool_seq"] = seq
        self.outstanding[seq] = [meta, args, mode, tgt]
        self.workers[tgt].submit(meta, args, mode)
        return True

    def pump(self) -> None:
        """Re-place evicted batches as capacity frees up."""
        while self.retryq:
            tgt, mode = self._pick()
            if tgt is None:
                return
            seq = self.retryq.popleft()
            ent = self.outstanding.get(seq)
            if ent is None:  # pragma: no cover - landed while queued
                continue
            ent[2], ent[3] = mode, tgt
            self.workers[tgt].submit(ent[0], ent[1], mode)

    def _resubmit(self, seq: int) -> None:
        ent = self.outstanding[seq]
        self.resubmits += 1
        tgt, mode = self._pick()
        if tgt is None:
            ent[3] = -1  # unassigned: parked until capacity frees
            self.retryq.append(seq)
            return
        ent[2], ent[3] = mode, tgt
        self.workers[tgt].submit(ent[0], ent[1], mode)

    def _evict(self, i: int) -> None:
        """Move every batch assigned to domain i elsewhere (quarantine /
        dead worker).  Late results from i are dropped by the
        assignment check in poll()."""
        for seq, ent in list(self.outstanding.items()):
            if ent[3] == i:
                self._resubmit(seq)

    # ---- landing --------------------------------------------------------

    def _drain_results(self, i: int, w: _DeviceWorker) -> None:
        while w.results:
            meta, ok = w.results.popleft()
            seq = meta["pool_seq"]
            ent = self.outstanding.get(seq)
            if ent is None or ent[3] != i:
                # a batch this domain lost to resubmission landed
                # anyway (stall recovered): first landing won
                self.late_results += 1
                continue
            if ok is None:
                self._resubmit(seq)  # device failed it: try elsewhere
                continue
            del self.outstanding[seq]
            w.landed_n += 1
            self.reorder[seq] = (meta, ok)

    def poll(self) -> None:
        """Drain worker results into the in-order ready queue; watchdog
        stalled/dead domains; resubmit failed batches.  Mux-thread only."""
        now = time.monotonic()
        for i, w in enumerate(self.workers):
            p = self.policies[i]
            # drain completed results BEFORE any eviction below: a
            # worker that landed S1..Sk and then wedged/died on S(k+1)
            # must not have its finished batches reassigned and re-run
            # (eviction-first turned them into dropped late results)
            self._drain_results(i, w)
            patience = getattr(p, "stall_patience_s", 0.0)
            t0 = w.land_t0
            if (
                patience
                and t0
                and now - t0 > patience
                and not p.stalled
            ):
                # round-5's global tunnel-stall patience, now per device:
                # only THIS device degrades; its batches move on
                p.mark_stalled()
                self._evict(i)
            if (
                p.stalled
                and not w.land_t0
                and not w.pending
                and w.reqq.empty()
            ):
                # watchdog/return race: the wedged call came back (the
                # worker cleared the flag) and THEN a stale mark_stalled
                # re-set it.  Nothing is in flight on this worker, so no
                # land will ever clear it again — clear it here or the
                # domain is out of the pool forever.  The quarantine
                # backoff from the mark still gates the re-probe.
                p.stalled = False
            if (
                not self._stopping
                and i not in self._evicted
                and (w.error is not None or not w.thread.is_alive())
            ):
                self._evicted.add(i)
                self._evict(i)
        self.pump()
        while self.landed_seq in self.reorder:
            self.ready.append(self.reorder.pop(self.landed_seq))
            self.landed_seq += 1

    def idle(self) -> bool:
        return not self.outstanding and not self.ready

    def check_fatal(self) -> None:
        """Every domain dead -> surface the first error (the supervisor
        restarts the tile).  A partial failure is handled by eviction."""
        errs = [w.error for w in self.workers]
        if errs and all(e is not None for e in errs):
            raise errs[0]

    # ---- lifecycle ------------------------------------------------------

    def stop(self, timeout_s: float | None = 30.0) -> None:
        self._stopping = True
        for w in self.workers:
            w.stop(timeout_s)

    def abort(self, timeout_s: float = 10.0) -> tuple[list[int], int]:
        """Crash teardown: abort every worker, drain their unlanded
        batches (the caller deliberately discards them — the
        supervisor's ring replay re-delivers), and report which domains
        are wedged zombies (their policies must be detached)."""
        self.aborted = True
        self._stopping = True
        zombies: list[int] = []
        dropped = 0
        for i, w in enumerate(self.workers):
            dropped += len(w.abort(timeout_s))
            if w.thread.is_alive():
                zombies.append(i)
        return zombies, dropped


class VerifyTile(Tile):
    def __init__(
        self,
        *,
        msg_width: int = 1232,
        max_lanes: int = 4096,
        pre_dedup: bool = True,
        pad_full: bool = False,
        shard: tuple[int, int] | None = None,
        async_depth: int = 3,
        device: str = "auto",
        device_fn=None,
        devices: int | str | list | None = 1,
        device_universe: list | None = None,
        fallback_trip: int = 3,
        fallback_reprobe: int = 64,
        dev_backoff_base_s: float = 0.5,
        dev_backoff_max_s: float = 30.0,
        stall_patience_s: float = 120.0,
        name: str = "verify",
    ):
        """pad_full: always pad sub-batches to max_lanes (one compiled
        shape; right for steady full-rate ingress).  False pads to
        power-of-two buckets (log2(max_lanes) compiled shapes; cheaper on
        trickle traffic).

        shard=(idx, cnt): horizontal scaling — this replica only processes
        frags with seq % cnt == idx (reference: round-robin seq sharding
        across verify tiles, fd_verify.c:46); the others are skipped
        without gathering payloads.

        async_depth: device batches in flight PER DEVICE (the wiredancer
        request pipe depth); 1 degenerates to synchronous dispatch.

        device: "auto" jits the batched kernel; "off" never touches JAX
        and verifies every batch on the strict host path (CPU-only tests,
        chaos harnesses, degraded deploys).  device_fn overrides the
        jitted kernel outright (fault-injection stubs).  fallback_trip /
        fallback_reprobe parameterize the FallbackPolicy.

        devices: the pool width — 1 (default: today's single serialized
        stream, bit-identical), an int N (domains 0..N-1), an explicit
        list of local device ordinals, or "auto" (every jax local
        device; resolves to 1 off-device).  With N > 1 each domain is
        its own fault domain: dev_backoff_base_s/dev_backoff_max_s cap
        the quarantine backoff and stall_patience_s is the per-device
        stall patience (round 5's global 120 s, now per device).

        device_universe: elastic shard members only — the kind-wide
        device-ordinal list shared by EVERY member.  Instead of keeping
        a boot-time partition forever, the member recomputes its slice
        from the LIVE active mask at every epoch flip
        (elastic.device_partition): scale-out recruits the ordinals the
        smaller active set left spare, scale-in returns them to the
        survivors.  The pool is rebuilt only at a quiet boundary (no
        in-flight device batches), so repartition never strands work.
        Metrics rows are sized for the full universe (the region is
        fixed at build).  Overrides `devices` when set."""
        assert max_lanes & (max_lanes - 1) == 0, (
            "max_lanes must be a power of two (pad buckets + warm compiles "
            "assume it)"
        )
        self.name = name
        self.msg_width = msg_width
        self.max_lanes = max_lanes
        self.pre_dedup = pre_dedup
        self.pad_full = pad_full
        self.shard = shard
        self.async_depth = max(async_depth, 1)
        self.device = device
        self._device_fn_override = device_fn
        self.device_universe = (
            [int(d) for d in device_universe] if device_universe else None
        )
        if self.device_universe is not None:
            # boot with the full universe (metrics rows size to it);
            # on_boot / the first epoch flip narrows to the live slice
            self.device_indices = list(self.device_universe)
        else:
            self.device_indices = _resolve_devices(devices, device, device_fn)
        self.n_devices = len(self.device_indices)
        self._pending_devices: list[int] | None = None
        self._fault_hook = None
        self.fallback_trip = fallback_trip
        self.fallback_reprobe = fallback_reprobe
        self.dev_backoff_base_s = dev_backoff_base_s
        self.dev_backoff_max_s = dev_backoff_max_s
        self.stall_patience_s = stall_patience_s
        # per-instance schema: the per-device health/throughput rows are
        # sized by the pool width at declaration time (the topology
        # allocates the metrics region before boot)
        self.schema = MetricsSchema(
            counters=(
                "verify_fail_txns",
                "dedup_drop_txns",
                "verified_sigs",
                "device_batches",
                # FallbackPolicy state, mirrored each loop so monitors
                # see degradation live (sums across the pool's domains)
                "fallback_batches",
                "device_errors",
                "device_trips",
                "host_reprobes",
                "pool_resubmits",
                "pool_late_results",
            )
            + device_counters(self.n_devices),
            hists=("lane_batch",),
        )
        self._tc: R.TCache | None = None
        self._fns: list | None = None
        self._policies: list[FallbackPolicy] | None = None
        self._pool: _DevicePool | None = None
        self._interrupt = None  # ctx.interrupt, bound at boot
        self._tracer = None  # ctx.tracer, bound at boot
        self._prev_fallback = 0  # FALLBACK span edge detector
        self._prev_degraded: dict[int, int] = {}  # QUARANTINE edges
        self._mirror_tick = 0
        #: staged host-prepared lanes not yet submitted (list of dicts)
        self._staged: collections.deque = collections.deque()
        self._staged_lanes = 0
        #: results processed into publish-ready arrays, awaiting credits
        self._outq: collections.deque = collections.deque()
        self._outq_txns = 0

    @property
    def _policy(self) -> FallbackPolicy | None:
        """Compat view for single-device callers/tests."""
        return self._policies[0] if self._policies else None

    def wksp_footprint(self) -> int:
        if not self.pre_dedup:
            return 0
        return R.TCache.footprint(
            PRE_DEDUP_DEPTH, R.TCache.map_cnt_for(PRE_DEDUP_DEPTH)
        )

    def _make_device_fns(self) -> list:
        """One verify executable per pool domain.  With real devices
        (device="auto", n>1) each is pinned to its own accelerator —
        inputs commit there, so one domain's H2D put overlaps the other
        domains' compute (round-3 measurement: a device_put progresses
        while an execution runs)."""
        n = self.n_devices
        if self._device_fn_override is not None:
            return [self._device_fn_override] * n
        if self.device != "auto":
            return [None] * n
        if self._fns is None:
            import jax

            from firedancer_tpu.ops.ed25519 import verify as fver

            # digest-input variant: host hashes SHA512(R||A||M) during
            # lane expansion, so each lane ships 160 device bytes
            # (digest+sig+pub) instead of msg_width+100 — the pipeline is
            # host->device bandwidth bound, not compute bound (PROFILE.md)
            if self.device_indices == [0]:
                # the default single-stream tile: plain jit on the
                # default device — bit-identical to the pre-pool path
                self._fns = [jax.jit(fver.verify_batch_digest)]
            else:
                local = jax.local_devices()
                bad = [d for d in self.device_indices if d >= len(local)]
                if bad:
                    # aliasing d % len(local) would silently pin two
                    # pool domains to one chip and report N healthy
                    # independent devices — surface the misconfig instead
                    raise ValueError(
                        f"{self.name}: devices {bad} out of range — host "
                        f"has {len(local)} local device(s)"
                    )
                self._fns = [
                    fver.verify_batch_digest_on(local[d])
                    for d in self.device_indices
                ]
            # warm the full-batch shape (per device) so the steady state
            # never compiles; smaller pow2 buckets (trickle traffic)
            # compile on first use — warming every bucket cost minutes
            # of boot on CPU hosts.  The persistent compilation cache
            # makes devices 1..n-1 near-free after device 0.
            for f in self._fns:
                np.asarray(
                    f(
                        np.zeros((self.max_lanes, 64), dtype=np.uint8),
                        np.zeros((self.max_lanes, 64), np.uint8),
                        np.zeros((self.max_lanes, 32), np.uint8),
                    )
                )
        return self._fns

    def on_boot(self, ctx: MuxCtx) -> None:
        from firedancer_tpu.ops.ed25519 import hostpath

        self._interrupt = ctx.interrupt
        self._tracer = ctx.tracer
        # warm the strict host path once per process: its first call
        # pays field-table setup (~100 ms on this host) that must not
        # land inside the first production batch's tail latency — the
        # device path warms its compiled shape the same way below, and
        # the host path is every fallback's last resort
        hostpath.verify_batch_digest_host(
            np.zeros((1, 64), np.uint8), np.zeros((1, 64), np.uint8),
            np.zeros((1, 32), np.uint8),
        )
        if self.pre_dedup:
            depth = PRE_DEDUP_DEPTH
            map_cnt = R.TCache.map_cnt_for(depth)
            fp = R.TCache.footprint(depth, map_cnt)
            # re-initialized (join=False) even on restart: a replayed
            # frag the dead incarnation consumed but never forwarded
            # must NOT be swallowed by a stale pre-dedup entry — the
            # real dedup tile downstream keeps the durable history
            self._tc = R.TCache(ctx.alloc("tcache", fp), depth, map_cnt)
        self._fault_hook = (
            ctx.faults.device_error if ctx.faults is not None else None
        )
        eb = self.elastic
        if (
            self.device_universe is not None
            and eb is not None
            and eb.role == "member"
        ):
            # shard-count-aware partition: this member's slice of the
            # kind's device universe under the LIVE mask, not the
            # boot-time ordinal list (repartition drops the cached
            # fns/policies; an elastic member's degradation counters
            # reset with its device set, deliberately)
            from firedancer_tpu.disco.elastic import device_partition

            part = device_partition(
                self.device_universe, eb.bind(ctx).mask(eb.slot), eb.index
            )
            if part and part != self.device_indices:
                self.device_indices = part
                self.n_devices = len(part)
                self._fns = None
                self._policies = None
        if self._policies is None:
            # policies (and their degradation counters) persist across
            # supervisor restarts; only the worker threads are per-life
            self._policies = self._build_policies()
        self._pool = _DevicePool(
            self._policies, self.async_depth, name=self.name
        )

    def _build_policies(self) -> list:
        from firedancer_tpu.ops.ed25519 import hostpath

        fns = self._make_device_fns()
        hook = self._fault_hook
        if self.n_devices == 1:
            return [
                FallbackPolicy(
                    fns[0],
                    hostpath.verify_batch_digest_host,
                    trip_after=self.fallback_trip,
                    reprobe_every=self.fallback_reprobe,
                    fault_hook=hook,
                )
            ]
        return [
            DevicePolicy(
                fns[i],
                hostpath.verify_batch_digest_host,
                index=i,
                trip_after=self.fallback_trip,
                backoff_base_s=self.dev_backoff_base_s,
                backoff_max_s=self.dev_backoff_max_s,
                stall_patience_s=self.stall_patience_s,
                fault_hook=hook,
            )
            for i in range(self.n_devices)
        ]

    # ---- elastic device repartition (fdt_upgrade satellite) -------------

    def on_epoch(self, ctx: MuxCtx) -> None:
        super().on_epoch(ctx)
        eb = self.elastic
        if (
            self.device_universe is None
            or eb is None
            or eb.role != "member"
        ):
            return
        from firedancer_tpu.disco.elastic import device_partition

        part = device_partition(
            self.device_universe, eb.bind(ctx).mask(eb.slot), eb.index
        )
        if part and part != self.device_indices:
            self._pending_devices = part
            self._maybe_repartition()

    def _maybe_repartition(self) -> None:
        """Apply a pending device repartition at a QUIET boundary: the
        pool must be idle (submitted work lands on the devices it was
        scheduled to — a mid-flight swap would strand results), so a
        busy pool retries from after_credit until its pipelines drain."""
        part = self._pending_devices
        if part is None:
            return
        if part == self.device_indices:
            self._pending_devices = None
            return
        pool = self._pool
        if pool is not None:
            if not pool.idle():
                return
            pool.stop(timeout_s=30.0)
        self.device_indices = list(part)
        self.n_devices = len(part)
        self._pending_devices = None
        self._fns = None
        self._policies = self._build_policies()
        self._pool = _DevicePool(
            self._policies, self.async_depth, name=self.name
        )

    # ---- ingress: host prep + staging -----------------------------------

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        il = ctx.ins[in_idx]
        if self.elastic is not None:
            # elastic seq sharding (disco/elastic.py): assignment is a
            # pure function of (seq, flip journal) — the producer's
            # flip entries are sequenced before the frags they govern,
            # so every member resolves the same owner for every seq
            # regardless of when it observed the epoch flip
            frags = frags[self.elastic.assign(ctx, frags["seq"])]
            if not len(frags):
                return
        elif self.shard is not None:
            idx, cnt = self.shard
            frags = frags[frags["seq"] % cnt == idx]
            if not len(frags):
                return
        if self._tc is not None:
            dup = self._tc.dedup(frags["sig"])
            if dup.any():
                ctx.metrics.inc("dedup_drop_txns", int(dup.sum()))
                frags = frags[~dup]
        if not len(frags):
            return
        # one GIL-released native call: dcache gather + trailer parse +
        # per-sig lane expansion + k-digests + dedup tags; the device
        # gets digests, so the message copy is skipped outright
        b = wire.expand_native(il.dcache, frags, self.msg_width,
                               with_digests=True, with_msgs=False)
        lanes = len(b["sigs"])
        b.pop("txn_idx")
        b["tsorigs"] = frags["tsorig"].copy()
        # ring seq per txn, carried through staging -> device -> publish
        # so ack_floor can hold the fseq at the oldest unflushed frag
        b["seqs"] = frags["seq"].copy()
        self._staged.append(b)
        self._staged_lanes += lanes
        # submit only while the pool has room: a full pool means every
        # device pipe is behind, and the right response is to hold frags
        # in the RING (in_budget -> credit backpressure), not to block
        # this thread past its heartbeat deadline
        while (
            self._staged_lanes >= self.max_lanes
            and self._pool.can_accept()
        ):
            self._submit_front(self.max_lanes)

    def elastic_drained(self, ctx: MuxCtx) -> bool:
        """Retirement drain contract (disco/elastic.py): beyond the
        ring-cursor checks the binding performs, this replica holds
        in-flight work in its staging deque, its device pool (dispatch
        pipelines + the in-order reorder buffer), and its credit-gated
        publish queue — ALL must land and publish before the drained
        marker may be written (zero-loss handover)."""
        p = self._pool
        return (
            self._staged_lanes == 0
            and not self._staged
            and self._outq_txns == 0
            and not self._outq
            and (p is None or p.idle())
        )

    def ack_floor(self, ctx: MuxCtx, in_idx: int) -> int | None:
        """Oldest in-ring frag seq still riding the async pipeline
        (staged -> device pool -> credit-gated publish queue).  The mux
        holds the fseq here so the producer cannot overwrite a consumed
        -but-unpublished frag — a crash anywhere in the pipeline is
        then recoverable by rejoin replay (the drop/landing of a txn
        releases its seq, so the floor only ever advances)."""
        floor = None
        batches = [b["seqs"] for q in (self._outq, self._staged) for b in q]
        pool = self._pool
        if pool is not None:
            batches += [ent[0]["seqs"] for ent in pool.outstanding.values()]
            batches += [meta["seqs"] for meta, _ok in pool.reorder.values()]
            batches += [meta["seqs"] for meta, _ok in pool.ready]
        for seqs in batches:
            s = int(seqs[0])
            # wrap-safe min (fdtmc finding, PR 3: plain-int min picks
            # the wrapped-to-tiny seq across a 2^64 crossing)
            floor = s if floor is None else R.seq_min(floor, s)
        return floor

    def in_budget(self, ctx: MuxCtx) -> int | None:
        # stop draining the ring when the device pool is full or results
        # are waiting on downstream credits — backpressure flows upstream
        # through the ring's credit model, not an unbounded host buffer
        p = self._pool
        if p is not None and not p.can_accept():
            return 0
        if self._staged_lanes >= 2 * self.max_lanes:
            return 0
        if self._outq_txns >= 4 * self.max_lanes:
            return 0
        return None

    # ---- device submit ---------------------------------------------------

    def _submit_front(self, lanes_cap: int) -> None:
        """Concatenate staged chunks into one device batch of <= lanes_cap
        lanes (whole txns only) and push it to the pool."""
        take, lanes = [], 0
        while self._staged:
            chunk = self._staged[0]
            n = len(chunk["sigs"])
            if lanes + n > lanes_cap:
                # split the chunk on a txn boundary
                cnt = chunk["sig_cnt"]
                ends = np.cumsum(cnt)
                k = int(np.searchsorted(ends, lanes_cap - lanes, "right"))
                if k == 0:
                    if lanes == 0:
                        # a single txn with more lanes than the cap: take
                        # it alone (the kernel pads to any pow2 bucket) —
                        # never stall with zero progress
                        k = 1
                    else:
                        break
                head, tail = _split_chunk(chunk, k, int(ends[k - 1]))
                take.append(head)
                lanes += int(ends[k - 1])
                if len(tail["sigs"]):
                    self._staged[0] = tail
                else:
                    self._staged.popleft()
                break
            take.append(self._staged.popleft())
            lanes += n
        if not take:
            return
        self._staged_lanes -= lanes
        if len(take) == 1:
            b = take[0]
        else:
            b = {
                k: np.concatenate([c[k] for c in take])
                for k in take[0]
            }
        pad = (
            self.max_lanes
            if self.pad_full
            else 1 << max(lanes - 1, 0).bit_length()
        )
        meta = dict(
            rows=b["rows"], szs=b["szs"], tsorigs=b["tsorigs"],
            sig_cnt=b["sig_cnt"], tags=b["tags"], seqs=b["seqs"],
            lanes=lanes,
        )
        self._submit(
            meta,
            (
                _pad2(b["digests"], pad),
                _pad2(b["sigs"], pad),
                _pad2(b["pubs"], pad),
            ),
        )

    def _submit(self, meta, args) -> None:
        """Interruptible submit: a full pool behind a slow host path
        must not turn into an unbounded blocking put — the supervisor's
        interrupt (stall recovery) and dead workers both have to be
        able to unwedge the loop thread."""
        pool = self._pool
        while True:
            pool.check_fatal()
            if pool.aborted:
                return  # crash teardown: ring replay re-delivers
            if self._interrupt is not None and self._interrupt.is_set():
                from firedancer_tpu.disco.mux import TileInterrupted

                raise TileInterrupted(f"{self.name}: submit abandoned")
            if pool.submit(meta, args):
                if self._tracer is not None:
                    self._tracer.point(
                        SPAN.ENQUEUE,
                        seq=meta["pool_seq"],
                        aux16=min(meta["lanes"], 0xFFFF),
                    )
                return
            # no capacity anywhere: poll (stall watchdog + retry pump
            # may free a lane) and wait for a worker to make progress
            pool.poll()
            time.sleep(1e-3)

    # ---- egress: results -> publish --------------------------------------

    def _land_results(self, ctx: MuxCtx) -> None:
        pool = self._pool
        pool.check_fatal()
        pool.poll()
        while pool.ready:
            meta, ok = pool.ready.popleft()
            lanes = meta["lanes"]
            ok = ok[:lanes]
            if self._tracer is not None:
                # dispatch/land timestamps were stamped into the meta by
                # the worker thread; emitted here so the span ring keeps
                # its single writer (this mux thread)
                dev = int(meta.get("t_dev", 0)) & 0xFF
                seq = meta.get("pool_seq", 0)
                if "t_disp" in meta:
                    self._tracer.point(
                        SPAN.DISPATCH, ts=meta["t_disp"], seq=seq,
                        aux16=dev,
                    )
                self._tracer.point(
                    SPAN.LAND, ts=meta.get("t_land"), seq=seq, aux16=dev,
                    aux64=lanes,
                )
            ctx.metrics.inc("verified_sigs", lanes)
            ctx.metrics.inc("device_batches")
            ctx.metrics.hist_sample("lane_batch", lanes)
            cnt = meta["sig_cnt"]
            starts = np.concatenate(([0], np.cumsum(cnt)[:-1]))
            txn_ok = (
                np.logical_and.reduceat(ok, starts)
                if lanes
                else np.zeros(0, bool)
            )
            n_fail = int((~txn_ok).sum())
            if n_fail:
                ctx.metrics.inc("verify_fail_txns", n_fail)
            if not txn_ok.any():
                continue
            # dedup tag: first 8 bytes of the first signature, LE u64
            # (reference: fd_dedup keys the tango sig field, fd_dedup.c:125)
            # — computed by fdt_verify_expand at staging time
            self._outq.append(
                dict(
                    tags=meta["tags"][txn_ok],
                    rows=meta["rows"][txn_ok],
                    szs=meta["szs"][txn_ok].astype(np.uint16),
                    tsorigs=meta["tsorigs"][txn_ok],
                    seqs=meta["seqs"][txn_ok],
                )
            )
            self._outq_txns += int(txn_ok.sum())

    def _publish_ready(self, ctx: MuxCtx) -> None:
        while self._outq and ctx.credits > 0:
            b = self._outq[0]
            n = len(b["tags"])
            if n <= ctx.credits:
                self._outq.popleft()
                ctx.publish(b["tags"], b["rows"], b["szs"], tsorigs=b["tsorigs"])
                ctx.credits -= n
                self._outq_txns -= n
            else:
                m = ctx.credits
                ctx.publish(
                    b["tags"][:m], b["rows"][:m], b["szs"][:m],
                    tsorigs=b["tsorigs"][:m],
                )
                for k in ("tags", "rows", "szs", "tsorigs", "seqs"):
                    b[k] = b[k][m:]
                ctx.credits = 0
                self._outq_txns -= m

    def after_credit(self, ctx: MuxCtx) -> None:
        self._land_results(ctx)
        self._publish_ready(ctx)
        if self._pending_devices is not None:
            self._maybe_repartition()
        # keep the devices fed: push a partial batch when the pool has
        # room and nothing fuller is coming (trickle traffic)
        if self._staged_lanes and self._pool.can_accept():
            self._submit_front(self.max_lanes)
        self._mirror_policy_metrics(ctx)

    def _mirror_policy_metrics(self, ctx: MuxCtx) -> None:
        """Expose the pool's degradation state in the shared metrics
        region (monitors read it live).  Aggregates every iteration;
        per-device rows every 16th (they are O(devices) set calls)."""
        pool = self._pool
        ps = self._policies
        m = ctx.metrics
        fb = sum(p.fallback_batches for p in ps)
        if self._tracer is not None and fb > self._prev_fallback:
            self._tracer.point(
                SPAN.FALLBACK, aux64=fb - self._prev_fallback
            )
        self._prev_fallback = fb
        m.set("fallback_batches", fb)
        m.set("device_errors", sum(p.device_errors for p in ps))
        m.set("device_trips", sum(p.device_trips for p in ps))
        m.set("host_reprobes", sum(p.host_reprobes for p in ps))
        m.set("pool_resubmits", pool.resubmits)
        m.set("pool_late_results", pool.late_results)
        self._mirror_tick += 1
        if (self._mirror_tick & 0xF) != 1:
            return
        now = time.monotonic()
        for i, w in enumerate(pool.workers):
            p = ps[i]
            m.set(f"dev{i}_depth", w.reqq.qsize())
            m.set(f"dev{i}_inflight", max(w.inflight(), 0))
            m.set(f"dev{i}_landed", w.landed_n)
            m.set(f"dev{i}_failed", p.device_errors + getattr(
                p, "device_stalls", 0))
            degraded = (
                # a cleanly stopped worker (halt) is not a fault; a
                # dead/errored one mid-run is
                (not w.alive() and not pool._stopping)
                or w.error is not None
                or p.stalled
                or (p.tripped and not p.healthy(now))
            )
            if (
                self._tracer is not None
                and degraded
                and not self._prev_degraded.get(i)
            ):
                self._tracer.point(SPAN.QUARANTINE, aux16=i)
            self._prev_degraded[i] = int(degraded)
            m.set(f"dev{i}_degraded", int(degraded))

    def on_crash(self, ctx: MuxCtx) -> None:
        # drop in-flight host state: the supervisor's ring replay
        # re-delivers anything the dead incarnation consumed but never
        # forwarded, and the downstream dedup collapses re-delivery of
        # what it DID forward.  The policy objects (device fns + trip
        # state) survive into the next incarnation.
        if self._pool is not None:
            zombies, _dropped = self._pool.abort()
            for i in zombies:
                # the zombie worker (stuck mid device/host call; threads
                # are unkillable) still holds its old policy — detach a
                # fresh copy so its late dispatch/land calls can't
                # corrupt the live incarnation's degradation state
                self._policies[i] = _clone_policy(
                    self._policies[i],
                    trip_after=self.fallback_trip,
                    reprobe_every=self.fallback_reprobe,
                )
            self._pool = None
        self._staged.clear()
        self._staged_lanes = 0
        self._outq.clear()
        self._outq_txns = 0

    def on_halt(self, ctx: MuxCtx) -> None:
        # drain everything: staged -> devices -> results -> downstream.
        # consumers are still running (topology halts upstream-first,
        # disco/topo.py halt order), so credits keep freeing.
        while self._staged_lanes:
            self._submit_front(self.max_lanes)
        pool = self._pool
        deadline = time.monotonic() + 60.0
        while not pool.idle() and time.monotonic() < deadline:
            self._land_results(ctx)
            if pool.outstanding:
                time.sleep(1e-3)
        pool.stop()
        self._land_results(ctx)
        deadline = time.monotonic() + 30.0
        while self._outq and time.monotonic() < deadline:
            cr = min(o.cr_avail() for o in ctx.outs) if ctx.outs else 0
            if cr <= 0:
                time.sleep(100e-6)
                continue
            ctx.credits = cr
            self._publish_ready(ctx)
        self._mirror_tick = 0  # force the per-device rows one last time
        self._mirror_policy_metrics(ctx)


def _resolve_devices(devices, device: str, device_fn) -> list[int]:
    """`devices` spec -> local device ordinals (pool domains).

    "auto" probes jax ONLY for a real device="auto" kernel (a host-only
    or stubbed tile must never pull the backend in); int N = ordinals
    0..N-1 (logical domains when stubbed); an explicit list is taken
    verbatim (disjoint ordinal sets across seq-sharded replicas — see
    disco.topo.device_assignments)."""
    if devices in (None, 1, "off"):
        return [0]  # "off" mirrors disco.topo.device_assignments
    if devices == "auto":
        if device == "auto" and device_fn is None:
            from firedancer_tpu.utils.hostdev import local_device_count

            return list(range(local_device_count()))
        return [0]
    if isinstance(devices, int):
        return list(range(max(devices, 1)))
    out = [int(d) for d in devices]
    return out or [0]


def _clone_policy(
    old: FallbackPolicy, *, trip_after: int, reprobe_every: int
) -> FallbackPolicy:
    """Fresh policy object carrying over the old one's degradation
    state (a wedged zombie thread keeps a dead reference instead)."""
    if isinstance(old, DevicePolicy):
        p: FallbackPolicy = DevicePolicy(
            old.device_fn, old.host_fn,
            index=old.index,
            trip_after=old.trip_after,
            backoff_base_s=old.backoff_base_s,
            backoff_max_s=old.backoff_max_s,
            stall_patience_s=old.stall_patience_s,
            fault_hook=old.fault_hook,
        )
        for attr in ("backoff_s", "quarantined_until", "device_stalls"):
            setattr(p, attr, getattr(old, attr))
        # NOT `stalled`: only the wedged call's return clears that flag,
        # and the zombie holds the OLD object — a copied flag would
        # quarantine the clone forever.  The carried-over backoff still
        # delays the re-probe, and a still-wedged device just re-trips
        # the patience watchdog.
    else:
        p = FallbackPolicy(
            old.device_fn, old.host_fn,
            trip_after=trip_after,
            reprobe_every=reprobe_every,
            fault_hook=old.fault_hook,
        )
    for attr in (
        "consec_failures", "tripped", "fallback_batches",
        "device_errors", "device_trips", "host_reprobes",
    ):
        setattr(p, attr, getattr(old, attr))
    return p


def _split_chunk(chunk: dict, k_txns: int, k_lanes: int) -> tuple[dict, dict]:
    """Split a staged chunk after k_txns txns / k_lanes lanes."""
    head, tail = {}, {}
    for key in ("rows", "szs", "tsorigs", "sig_cnt", "tags", "seqs"):
        head[key], tail[key] = chunk[key][:k_txns], chunk[key][k_txns:]
    for key in ("digests", "sigs", "pubs"):
        head[key], tail[key] = chunk[key][:k_lanes], chunk[key][k_lanes:]
    return head, tail


def _pad2(a: np.ndarray, n: int) -> np.ndarray:
    if len(a) == n:
        return a
    out = np.zeros((n,) + a.shape[1:], dtype=a.dtype)
    out[: len(a)] = a
    return out


def _pad1(a: np.ndarray, n: int) -> np.ndarray:
    if len(a) == n:
        return a
    out = np.zeros(n, dtype=a.dtype)
    out[: len(a)] = a
    return out
