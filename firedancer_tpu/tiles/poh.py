"""PoH tile: the proof-of-history clock, slot state machine, and mixins.

Reference model: src/app/fdctl/run/tiles/fd_poh.c (design essay at
:10-250) — the validator's one sequential component: iterate
state = SHA-256(state) continuously, track the slot boundary every
ticks_per_slot ticks, follow the leader schedule (become leader when our
identity holds the slot, hand off when it passes), and mix executed
microblocks into the chain ONLY while leader.

The chain itself runs on the HOST: it is a sequential sha256 ladder
with no batch parallelism for an accelerator to exploit (the reference
burns a dedicated CPU core on it, fd_poh.c).  The DEVICE'S job is what
parallelizes — ops/poh.verify_entries batch-checks entries, which is
why entries out carry (prev_state, hashcnt, mixin, state).  Slot
boundaries emit a tick entry with the slot number in the sig field.

ISSUE 12 (native block egress): the ladder no longer pays a Python
hashlib call per row.  The chain state, pacing clock and slot machine
live in a SHARED words block (the tile's workspace arena in the process
runtime) mutated identically by this file's Python loop and by
tango/native/fdt_poh.c — the stem frag handler (mixins) plus an
after-credit hook (the paced tick batch), so steady state is zero
Python per frag AND per tick batch.  Every emission arms a small
journal (pre-state, mix, in/out seqs) before mutating the chain:
PohTile._recover re-derives an interrupted emission deterministically
and skips the publishes the out mcache already carries, making each
microblock mix-in EXACTLY-ONCE across SIGKILL + supervisor replay and
the entry stream gapless (prev/state chain continuity holds across a
crash)."""

from __future__ import annotations

import numpy as np

from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile, drain_straggler_ins
from firedancer_tpu.tango import rings as R
from firedancer_tpu.tango import tempo
import hashlib as _hashlib

ENTRY_SZ = 32 + 8 + 32 + 32  # prev_state | hashcnt u64 | mixin | state

#: mainnet: 64 ticks per slot (the reference derives it from genesis)
TICKS_PER_SLOT = 64

#: slot-boundary entries publish tag = SLOT_BOUNDARY_TAG | slot, keeping
#: them disjoint from mixin/tick entry tags (small hashcnt values)
SLOT_BOUNDARY_TAG = 1 << 63

#: shared words (i64) — layout pinned to tango/native/fdt_poh.h
_W_HASHCNT, _W_SLOT, _W_TICKS, _W_NEXT_NS = 0, 1, 2, 3
_W_INTERVAL, _W_TICK_BATCH, _W_TPS, _W_LEADER = 4, 5, 6, 7
_W_HW0 = 8  # per-in consumed high-water marks, words 8..15
_W_MAGIC = 16  # host-side init flag (never read by C)
_W_CNT = 24

#: journal words (u64; prev/mix bytes from word 8) — fdt_poh.h layout
_J_PHASE, _J_INIDX, _J_INSEQ, _J_OUTSEQ0 = 0, 1, 2, 3
_J_HASHCNT, _J_TICKS, _J_SLOT = 4, 5, 6
_J_PREV, _J_MIX = 8, 12
#: tick_batch / ticks_per_slot AT ARM TIME: recovery must re-derive
#: the emission with the DEAD incarnation's config (a restart may
#: carry a config change)
_J_TB, _J_TPS = 16, 17
_J_WORDS = 24


class PohTile(Tile):
    """ins = bank_poh microblock rings; outs[0] = entries ring."""

    schema = MetricsSchema(
        counters=(
            "hashcnt",
            "mixins",
            "entries",
            "slots",
            "leader_slots",
            "dropped_mixins",
            # supervisor replay of a microblock a previous incarnation
            # already mixed (skipped below the consumed high-water mark
            # — the exactly-once discipline, not an anomaly)
            "replayed_mixins",
        ),
    )

    def __init__(
        self,
        *,
        tick_batch: int = 64,
        ticks_per_slot: int = TICKS_PER_SLOT,
        slot_ms: float = 400.0,
        leaders=None,
        identity: bytes | None = None,
        slot0: int = 0,
        name: str = "poh",
    ):
        """leaders/identity: an EpochLeaders schedule (flamenco.leaders)
        plus our pubkey drive the leader-slot state machine; with
        leaders=None the tile is always leader (single-node tests).

        slot_ms paces the clock to wall time (mainnet: 400 ms slots,
        hashcnt rate derived from it — fd_poh.c's hashcnt_duration_ns).
        Unpaced ticking would burn a full core spinning sha256 (the
        reference DEDICATES a core; shared-core hosts cannot) and starve
        every other tile.  slot_ms=0 disables pacing (tests)."""
        self.name = name
        self.tick_batch = tick_batch
        self.ticks_per_slot = ticks_per_slot
        self.leaders = leaders
        self.identity = identity
        #: ns between tick batches (0 = free-run)
        self._interval_ns = int(
            slot_ms * 1e6 * tick_batch / ticks_per_slot
        ) if slot_ms else 0
        # host-local backing until on_boot rebinds to the shared block
        # (tests construct the tile and poke .slot before any boot)
        self._chain = np.zeros(32, dtype=np.uint8)
        self._w = np.zeros(_W_CNT, dtype=np.int64)
        self._jnl = np.zeros(_J_WORDS, dtype=np.uint64)
        self._w[_W_SLOT] = slot0
        self._w[_W_INTERVAL] = self._interval_ns
        self._w[_W_TICK_BATCH] = tick_batch
        self._w[_W_TPS] = ticks_per_slot
        self._scratch = np.zeros(ENTRY_SZ, dtype=np.uint8)
        #: test hook: called between the journal arm and the publish to
        #: exercise the crash window deterministically (Python path)
        self._crash_probe = None

    # ---- shared-word views (both loop modes mutate the SAME words) -------

    @property
    def state(self) -> np.ndarray:
        return self._chain

    @state.setter
    def state(self, v) -> None:
        self._chain[:] = v

    @property
    def hashcnt(self) -> int:
        return int(self._w[_W_HASHCNT])

    @hashcnt.setter
    def hashcnt(self, v: int) -> None:
        self._w[_W_HASHCNT] = v

    @property
    def slot(self) -> int:
        return int(self._w[_W_SLOT])

    @slot.setter
    def slot(self, v: int) -> None:
        self._w[_W_SLOT] = v

    @property
    def ticks_in_slot(self) -> int:
        return int(self._w[_W_TICKS])

    @ticks_in_slot.setter
    def ticks_in_slot(self, v: int) -> None:
        self._w[_W_TICKS] = v

    # ---- leader state ----------------------------------------------------

    def is_leader(self, slot: int | None = None) -> bool:
        if self.leaders is None:
            return True
        s = self.slot if slot is None else slot
        if not self.leaders.contains(s):
            return False  # outside the schedule's epoch window
        return self.leaders.leader_for_slot(s) == self.identity

    # ---- boot / recovery -------------------------------------------------

    def wksp_footprint(self) -> int:
        return 1024

    def on_boot(self, ctx: MuxCtx) -> None:
        # the chain block lives in the workspace (shm in the process
        # runtime): state survives a SIGKILL, so the restarted
        # incarnation CONTINUES the chain instead of restarting it
        blk = ctx.alloc("poh_chain", 32 + (_W_CNT + _J_WORDS) * 8)
        chain = blk[:32]
        words = blk[32 : 32 + _W_CNT * 8].view(np.int64)
        jnl = blk[32 + _W_CNT * 8 :][: _J_WORDS * 8].view(np.uint64)
        if int(words[_W_MAGIC]) == 0:
            # first boot: seed the shared block from the ctor state
            chain[:] = self._chain
            words[:] = self._w
            words[_W_MAGIC] = 1
        else:
            # config words are always the ctor's (a restart may carry a
            # config change); chain/clock/slot words are the survivors'
            words[_W_INTERVAL] = self._interval_ns
            words[_W_TICK_BATCH] = self.tick_batch
            words[_W_TPS] = self.ticks_per_slot
        self._chain = chain
        self._w = words
        self._jnl = jnl
        words[_W_LEADER] = 1 if self.leaders is None else 0
        self._recover(ctx)
        if self.is_leader():
            ctx.metrics.inc("leader_slots")

    def _recover(self, ctx: MuxCtx) -> None:
        """Complete an emission a dead incarnation left mid-window: the
        journal carries everything needed to re-derive it
        deterministically; the out mcache's (producer_rejoin-repaired)
        seq names how many of its publishes already landed."""
        jw = self._jnl
        phase = int(jw[_J_PHASE])
        if phase == 0:
            return
        prev = jw[_J_PREV : _J_PREV + 4].tobytes()
        out = ctx.outs[0] if ctx.outs else None
        already = 0
        if out is not None:
            already = max(
                R.seq_diff(out.mcache.seq_query(), int(jw[_J_OUTSEQ0])), 0
            )
        if phase == 1:  # mixin
            mix = jw[_J_MIX : _J_MIX + 4].tobytes()
            self._chain[:] = np.frombuffer(
                _hashlib.sha256(prev + mix).digest(), np.uint8
            )
            self._w[_W_HASHCNT] = int(jw[_J_HASHCNT]) + 1
            ii = int(jw[_J_INIDX])
            hw = int(jw[_J_INSEQ]) + 1
            if ii < 8 and R.seq_diff(hw, int(self._w[_W_HW0 + ii])) > 0:
                self._w[_W_HW0 + ii] = hw
            if out is not None and already < 1:
                self._emit(
                    ctx, np.frombuffer(prev, np.uint8), 1,
                    np.frombuffer(mix, np.uint8), self._chain,
                )
        elif phase == 2:  # tick batch (+ any slot boundaries)
            tb = int(jw[_J_TB]) or self.tick_batch
            tps = int(jw[_J_TPS]) or self.ticks_per_slot
            st = prev
            for _ in range(tb):
                st = _hashlib.sha256(st).digest()
            self._chain[:] = np.frombuffer(st, np.uint8)
            self._w[_W_HASHCNT] = int(jw[_J_HASHCNT]) + tb
            ticks = int(jw[_J_TICKS]) + tb
            slot = int(jw[_J_SLOT])
            entries = [
                (np.frombuffer(prev, np.uint8), tb,
                 np.zeros(32, np.uint8), self._chain, None)
            ]
            while ticks >= tps:
                ticks -= tps
                slot += 1
                entries.append(
                    (self._chain, 0, np.zeros(32, np.uint8), self._chain,
                     SLOT_BOUNDARY_TAG | slot)
                )
            self._w[_W_TICKS] = ticks
            self._w[_W_SLOT] = slot
            if out is not None:
                for prev_a, n, mix_a, st_a, tag in entries[already:]:
                    self._emit(ctx, prev_a, n, mix_a, st_a, tag=tag)
        jw[_J_PHASE] = 0

    # ---- native stem (ISSUE 12) -----------------------------------------

    def native_handler(self, ctx: MuxCtx):
        """Native fast path: fdt_poh_mixins drains microblock frags
        (mix → append → emit, journal-armed) and fdt_poh_tick runs the
        paced tick batch + slot machine as the stem's after-credit hook
        — the fdt_pack_sched shape.  Requires always-leader (a leader
        schedule is host-side Python state) and a dcache-backed single
        entries out."""
        if (
            self.leaders is not None
            or len(ctx.outs) != 1
            or ctx.outs[0].dcache is None
            or any(il.dcache is None for il in ctx.ins)
            or len(ctx.ins) > 8
        ):
            return None
        args = np.zeros(8, np.uint64)
        args[0] = self._chain.ctypes.data
        args[1] = self._w.ctypes.data
        args[2] = self._jnl.ctypes.data
        args[3] = self._scratch.ctypes.data
        return R.StemSpec(
            R.STEM_H_POH, args,
            counters=("hashcnt", "mixins", "entries", "slots",
                      "leader_slots", "replayed_mixins"),
            keepalive=(args, self._scratch),
            ready=lambda: self._crash_probe is None,
            ac_handler=R.STEM_AC_POH,
            ac_args=args,
        )

    # ---- emission (Python reference path) --------------------------------

    def _emit(self, ctx: MuxCtx, prev, hashcnt, mix, state, tag=None) -> None:
        buf = np.zeros(ENTRY_SZ, dtype=np.uint8)
        buf[0:32] = prev
        buf[32:40].view("<u8")[0] = hashcnt
        buf[40:72] = mix
        buf[72:104] = state
        ctx.publish(
            np.array([tag if tag is not None else (hashcnt or 1)],
                     dtype=np.uint64),
            buf[None, :],
            np.array([ENTRY_SZ], dtype=np.uint16),
        )
        ctx.metrics.inc("entries")

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        il = ctx.ins[in_idx]
        rows = il.gather(frags)
        leader = self.is_leader()  # constant within one callback
        jw = self._jnl
        w = self._w
        for i in range(len(rows)):
            seq = int(frags["seq"][i])
            hw = int(w[_W_HW0 + in_idx]) if in_idx < 8 else 0
            if hw and R.seq_diff(R.seq_u64(seq + 1), hw) <= 0:
                # supervisor replay of an already-mixed microblock:
                # exactly-once means skip (the entry is already out)
                ctx.metrics.inc("replayed_mixins")
                continue
            if not leader:
                # a bank handed us a microblock outside our leader slot:
                # fail-safe drop (the reference cannot reach this state
                # because pack only schedules while leader; we count it)
                ctx.metrics.inc("dropped_mixins")
                if in_idx < 8:
                    w[_W_HW0 + in_idx] = R.seq_u64(seq + 1)
                continue
            mb = rows[i, : frags["sz"][i]]
            # microblock hash = SHA-256 of its bytes (stand-in for the
            # entry merkle root the reference mixes in)
            mix = np.frombuffer(
                _hashlib.sha256(mb.tobytes()).digest(), np.uint8
            )
            # arm the journal BEFORE mutating the chain (fdt_poh.h crash
            # discipline — byte-identical to the native handler's)
            jw[_J_PREV : _J_PREV + 4] = np.frombuffer(
                self._chain.tobytes(), np.uint64
            )
            jw[_J_MIX : _J_MIX + 4] = np.frombuffer(mix.tobytes(), np.uint64)
            jw[_J_INIDX] = in_idx
            jw[_J_INSEQ] = seq
            jw[_J_OUTSEQ0] = R.seq_u64(ctx.outs[0].seq) if ctx.outs else 0
            jw[_J_HASHCNT] = int(w[_W_HASHCNT])
            jw[_J_PHASE] = 1
            prev = self._chain.copy()
            self._chain[:] = np.frombuffer(
                _hashlib.sha256(
                    prev.tobytes() + mix.tobytes()
                ).digest(), np.uint8,
            )
            w[_W_HASHCNT] += 1
            ctx.metrics.inc("hashcnt")
            ctx.metrics.inc("mixins")
            if self._crash_probe is not None:
                self._crash_probe()
            self._emit(ctx, prev, 1, mix, self._chain)
            if in_idx < 8:
                w[_W_HW0 + in_idx] = R.seq_u64(seq + 1)
            jw[_J_PHASE] = 0

    def on_halt(self, ctx: MuxCtx) -> None:
        # drain straggler bank mixins so the last microblocks of a run
        # still enter the chain (banks may publish right up to HALT)
        drain_straggler_ins(self, ctx, deadline_s=2.0)

    def after_credit(self, ctx: MuxCtx) -> None:
        w = self._w
        now = 0
        if int(w[_W_INTERVAL]):
            now = tempo.tickcount()
            if now < int(w[_W_NEXT_NS]):
                return
        # one firing emits the tick entry PLUS every slot-boundary entry
        # the batch crosses: gate the WHOLE emission on a live credit
        # read (a boundary firing at cr==1 would overrun a reliable
        # consumer — the poh-emit-over-credit mutant class); the pacing
        # deadline is only re-armed once the firing is admitted, so a
        # credit-starved tick retries instead of skipping
        needed = 1 + (
            int(w[_W_TICKS]) + self.tick_batch
        ) // self.ticks_per_slot
        if ctx.outs and ctx.outs[0].cr_avail() < needed:
            return
        if int(w[_W_INTERVAL]):
            nxt = int(w[_W_NEXT_NS])
            w[_W_NEXT_NS] = (
                now + int(w[_W_INTERVAL])
                if now - nxt > 1_000_000_000
                else nxt + int(w[_W_INTERVAL])
            )
        # batch-advance the clock.  The PoH chain is a SEQUENTIAL sha256
        # ladder — there is no batch parallelism for the device to
        # exploit, and on the axon tunnel every dispatch costs ~110 ms
        # serialized against the verify tile's executions (measured: PoH
        # device calls throttled the whole landed-TPS pipeline to
        # ~270 TPS).  The reference burns a dedicated CPU core on this
        # chain (fd_poh.c); ops/poh.verify_entries keeps the DEVICE for
        # what parallelizes — verifying many entries at once.
        jw = self._jnl
        jw[_J_PREV : _J_PREV + 4] = np.frombuffer(
            self._chain.tobytes(), np.uint64
        )
        jw[_J_OUTSEQ0] = R.seq_u64(ctx.outs[0].seq) if ctx.outs else 0
        jw[_J_HASHCNT] = int(w[_W_HASHCNT])
        jw[_J_TICKS] = int(w[_W_TICKS])
        jw[_J_SLOT] = int(w[_W_SLOT])
        jw[_J_TB] = self.tick_batch
        jw[_J_TPS] = self.ticks_per_slot
        jw[_J_PHASE] = 2
        prev = self._chain.copy()
        st = self._chain.tobytes()
        for _ in range(self.tick_batch):
            st = _hashlib.sha256(st).digest()
        self._chain[:] = np.frombuffer(st, np.uint8)
        w[_W_HASHCNT] += self.tick_batch
        ctx.metrics.inc("hashcnt", self.tick_batch)
        self._emit(ctx, prev, self.tick_batch, np.zeros(32, np.uint8),
                   self._chain)
        # slot state machine: tick_batch counts as tick_batch ticks
        w[_W_TICKS] += self.tick_batch
        while int(w[_W_TICKS]) >= self.ticks_per_slot:
            w[_W_TICKS] -= self.ticks_per_slot
            w[_W_SLOT] += 1
            ctx.metrics.inc("slots")
            if self.is_leader():
                ctx.metrics.inc("leader_slots")
            # slot-boundary entry: tag = high bit | slot number — a tag
            # space disjoint from mixin (sig=1) and tick (sig=hashcnt)
            # entries so downstream consumers can detect boundaries
            self._emit(
                ctx, self._chain, 0, np.zeros(32, np.uint8), self._chain,
                tag=SLOT_BOUNDARY_TAG | int(w[_W_SLOT]),
            )
        jw[_J_PHASE] = 0
