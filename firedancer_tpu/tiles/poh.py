"""PoH tile: the proof-of-history clock, mixing executed microblocks into
the hash chain.

Reference model: src/app/fdctl/run/tiles/fd_poh.c — the validator's one
sequential component: iterate state = SHA-256(state) continuously (500ns
per hashcnt on mainnet), and on each executed microblock from a bank,
mix its hash into the chain (one mixin consumes one hashcnt), emitting
entries downstream (to shred in the reference).

TPU-native shape: ticks are batched — after_credit runs `tick_batch`
appends as ONE device dispatch (lax.fori_loop of the fixed-32B SHA-256
compression, ops/poh.append_n) instead of one hash per loop iteration.
Entries out carry (prev_state, hashcnt, mixin, state) so a downstream
verifier can batch-check them (ops/poh.verify_entries).
"""

from __future__ import annotations

import numpy as np

from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile
from firedancer_tpu.ops import poh as POH
from firedancer_tpu.ops import sha256 as SHA

ENTRY_SZ = 32 + 8 + 32 + 32  # prev_state | hashcnt u64 | mixin | state


class PohTile(Tile):
    """ins = bank_poh microblock rings; outs[0] = entries ring."""

    schema = MetricsSchema(
        counters=("hashcnt", "mixins", "entries"),
    )

    def __init__(self, *, tick_batch: int = 64, name: str = "poh"):
        self.name = name
        self.tick_batch = tick_batch
        self.state = np.zeros(32, dtype=np.uint8)
        self.hashcnt = 0
        self._append = None
        self._mixin = None

    def on_boot(self, ctx: MuxCtx) -> None:
        import functools

        import jax

        self._append = jax.jit(
            functools.partial(POH.append_n, n=self.tick_batch)
        )
        self._mixin = jax.jit(POH.mixin)
        # warm compiles
        s = self.state[None, :]
        np.asarray(self._append(s))
        np.asarray(self._mixin(s, s))

    def _emit(self, ctx: MuxCtx, prev, hashcnt, mix, state) -> None:
        buf = np.zeros(ENTRY_SZ, dtype=np.uint8)
        buf[0:32] = prev
        buf[32:40].view("<u8")[0] = hashcnt
        buf[40:72] = mix
        buf[72:104] = state
        ctx.publish(
            np.array([hashcnt or 1], dtype=np.uint64),
            buf[None, :],
            np.array([ENTRY_SZ], dtype=np.uint16),
        )
        ctx.metrics.inc("entries")

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        il = ctx.ins[in_idx]
        rows = il.gather(frags)
        for i in range(len(rows)):
            mb = rows[i, : frags["sz"][i]]
            # microblock hash = SHA-256 of its bytes (stand-in for the
            # entry merkle root the reference mixes in)
            mix = np.asarray(
                SHA.sha256(mb[None, :], np.array([len(mb)], np.int32))
            )[0]
            prev = self.state.copy()
            self.state = np.asarray(
                self._mixin(self.state[None, :], mix[None, :])
            )[0]
            self.hashcnt += 1
            ctx.metrics.inc("hashcnt")
            ctx.metrics.inc("mixins")
            self._emit(ctx, prev, 1, mix, self.state)

    def after_credit(self, ctx: MuxCtx) -> None:
        # batch-advance the clock: one device dispatch per tick_batch
        prev = self.state.copy()
        self.state = np.asarray(self._append(self.state[None, :]))[0]
        self.hashcnt += self.tick_batch
        ctx.metrics.inc("hashcnt", self.tick_batch)
        self._emit(ctx, prev, self.tick_batch, np.zeros(32, np.uint8), self.state)
