"""PoH tile: the proof-of-history clock, slot state machine, and mixins.

Reference model: src/app/fdctl/run/tiles/fd_poh.c (design essay at
:10-250) — the validator's one sequential component: iterate
state = SHA-256(state) continuously, track the slot boundary every
ticks_per_slot ticks, follow the leader schedule (become leader when our
identity holds the slot, hand off when it passes), and mix executed
microblocks into the chain ONLY while leader.

The chain itself runs on the HOST: it is a sequential sha256 ladder
with no batch parallelism for an accelerator to exploit (the reference
burns a dedicated CPU core on it, fd_poh.c).  The DEVICE'S job is what
parallelizes — ops/poh.verify_entries batch-checks entries, which is
why entries out carry (prev_state, hashcnt, mixin, state).  Slot
boundaries emit a tick entry with the slot number in the sig field.
"""

from __future__ import annotations

import numpy as np

from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile
import hashlib as _hashlib

ENTRY_SZ = 32 + 8 + 32 + 32  # prev_state | hashcnt u64 | mixin | state

#: mainnet: 64 ticks per slot (the reference derives it from genesis)
TICKS_PER_SLOT = 64

#: slot-boundary entries publish tag = SLOT_BOUNDARY_TAG | slot, keeping
#: them disjoint from mixin/tick entry tags (small hashcnt values)
SLOT_BOUNDARY_TAG = 1 << 63


class PohTile(Tile):
    """ins = bank_poh microblock rings; outs[0] = entries ring."""

    schema = MetricsSchema(
        counters=(
            "hashcnt",
            "mixins",
            "entries",
            "slots",
            "leader_slots",
            "dropped_mixins",
        ),
    )

    def __init__(
        self,
        *,
        tick_batch: int = 64,
        ticks_per_slot: int = TICKS_PER_SLOT,
        slot_ms: float = 400.0,
        leaders=None,
        identity: bytes | None = None,
        slot0: int = 0,
        name: str = "poh",
    ):
        """leaders/identity: an EpochLeaders schedule (flamenco.leaders)
        plus our pubkey drive the leader-slot state machine; with
        leaders=None the tile is always leader (single-node tests).

        slot_ms paces the clock to wall time (mainnet: 400 ms slots,
        hashcnt rate derived from it — fd_poh.c's hashcnt_duration_ns).
        Unpaced ticking would burn a full core spinning sha256 (the
        reference DEDICATES a core; shared-core hosts cannot) and starve
        every other tile.  slot_ms=0 disables pacing (tests)."""
        self.name = name
        self.tick_batch = tick_batch
        self.ticks_per_slot = ticks_per_slot
        self.leaders = leaders
        self.identity = identity
        self.slot = slot0
        self.ticks_in_slot = 0
        self.state = np.zeros(32, dtype=np.uint8)
        self.hashcnt = 0
        #: seconds between tick batches (0 = free-run)
        self._batch_interval = (
            (slot_ms / 1000.0) * tick_batch / ticks_per_slot
            if slot_ms else 0.0
        )
        self._next_batch = 0.0

    # ---- leader state ----------------------------------------------------

    def is_leader(self, slot: int | None = None) -> bool:
        if self.leaders is None:
            return True
        s = self.slot if slot is None else slot
        if not self.leaders.contains(s):
            return False  # outside the schedule's epoch window
        return self.leaders.leader_for_slot(s) == self.identity

    def on_boot(self, ctx: MuxCtx) -> None:
        if self.is_leader():
            ctx.metrics.inc("leader_slots")

    def _emit(self, ctx: MuxCtx, prev, hashcnt, mix, state, tag=None) -> None:
        buf = np.zeros(ENTRY_SZ, dtype=np.uint8)
        buf[0:32] = prev
        buf[32:40].view("<u8")[0] = hashcnt
        buf[40:72] = mix
        buf[72:104] = state
        ctx.publish(
            np.array([tag if tag is not None else (hashcnt or 1)],
                     dtype=np.uint64),
            buf[None, :],
            np.array([ENTRY_SZ], dtype=np.uint16),
        )
        ctx.metrics.inc("entries")

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        il = ctx.ins[in_idx]
        rows = il.gather(frags)
        leader = self.is_leader()  # constant within one callback
        for i in range(len(rows)):
            if not leader:
                # a bank handed us a microblock outside our leader slot:
                # fail-safe drop (the reference cannot reach this state
                # because pack only schedules while leader; we count it)
                ctx.metrics.inc("dropped_mixins")
                continue
            mb = rows[i, : frags["sz"][i]]
            # microblock hash = SHA-256 of its bytes (stand-in for the
            # entry merkle root the reference mixes in)
            mix = np.frombuffer(
                _hashlib.sha256(mb.tobytes()).digest(), np.uint8
            )
            prev = self.state.copy()
            self.state = np.frombuffer(
                _hashlib.sha256(
                    prev.tobytes() + mix.tobytes()
                ).digest(), np.uint8,
            )
            self.hashcnt += 1
            ctx.metrics.inc("hashcnt")
            ctx.metrics.inc("mixins")
            self._emit(ctx, prev, 1, mix, self.state)

    def on_halt(self, ctx: MuxCtx) -> None:
        # drain straggler bank mixins so the last microblocks of a run
        # still enter the chain (banks may publish right up to HALT)
        import time as _t

        deadline = _t.monotonic() + 2.0
        while _t.monotonic() < deadline:
            got = 0
            for i, il in enumerate(ctx.ins):
                budget = min(
                    o.cr_avail() for o in ctx.outs
                ) if ctx.outs else 4096
                if budget <= 0:
                    break
                frags, il.seq, ovr = il.mcache.drain(il.seq, budget)
                if ovr:
                    ctx.metrics.inc("overrun_frags", ovr)
                    il.fseq.diag_add(0, ovr)
                if len(frags):
                    got += len(frags)
                    self.on_frags(ctx, i, frags)
            if got == 0:
                break

    def after_credit(self, ctx: MuxCtx) -> None:
        if self._batch_interval:
            import time as _t

            now = _t.monotonic()
            if now < self._next_batch:
                return
            self._next_batch = (
                now + self._batch_interval
                if now - self._next_batch > 1.0
                else self._next_batch + self._batch_interval
            )
        # batch-advance the clock.  The PoH chain is a SEQUENTIAL sha256
        # ladder — there is no batch parallelism for the device to
        # exploit, and on the axon tunnel every dispatch costs ~110 ms
        # serialized against the verify tile's executions (measured: PoH
        # device calls throttled the whole landed-TPS pipeline to
        # ~270 TPS).  The reference burns a dedicated CPU core on this
        # chain (fd_poh.c); ops/poh.verify_entries keeps the DEVICE for
        # what parallelizes — verifying many entries at once.
        prev = self.state.copy()
        st = self.state.tobytes()
        for _ in range(self.tick_batch):
            st = _hashlib.sha256(st).digest()
        self.state = np.frombuffer(st, np.uint8)
        self.hashcnt += self.tick_batch
        ctx.metrics.inc("hashcnt", self.tick_batch)
        self._emit(ctx, prev, self.tick_batch, np.zeros(32, np.uint8),
                   self.state)
        # slot state machine: tick_batch counts as tick_batch ticks
        self.ticks_in_slot += self.tick_batch
        while self.ticks_in_slot >= self.ticks_per_slot:
            self.ticks_in_slot -= self.ticks_per_slot
            self.slot += 1
            ctx.metrics.inc("slots")
            if self.is_leader():
                ctx.metrics.inc("leader_slots")
            # slot-boundary entry: tag = high bit | slot number — a tag
            # space disjoint from mixin (sig=1) and tick (sig=hashcnt)
            # entries so downstream consumers can detect boundaries
            self._emit(
                ctx, self.state, 0, np.zeros(32, np.uint8), self.state,
                tag=SLOT_BOUNDARY_TAG | self.slot,
            )