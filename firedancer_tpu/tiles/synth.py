"""Synthetic ingress load-generator tile.

Reference model: src/disco/verify/verify_synth_load.c (synthetic
sig-verify load with modeled failure rates) and the fddev bench txn
generator tiles (src/app/fddev/tiles/fd_benchg.c).  Pre-generates a pool
of genuinely-signed transactions at boot, then streams them through the
out link at full ring rate, optionally re-publishing duplicates and
corrupting a fraction of signatures so downstream verify/dedup tiles have
real work to reject.
"""

from __future__ import annotations

import numpy as np

from firedancer_tpu.ballet import txn as T
from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile
# hostpath sign/public are bit-identical to golden's (parity-tested) and
# ~50x faster — pool generation used to dominate test wall time
from firedancer_tpu.ops.ed25519 import hostpath

from . import wire


def make_txn_pool(
    n_txns: int,
    *,
    n_signers: int = 4,
    n_accounts: int = 16,
    corrupt_frac: float = 0.0,
    seed: int = 0,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Build a pool of signed txns (+trailers) as dense rows.

    Returns (rows (n, LINK_MTU) u8, szs (n,) u16, good (n,) bool) where
    good[i] is False for txns whose signature was deliberately corrupted.
    """
    rng = np.random.default_rng(seed)
    signers = []
    for i in range(n_signers):
        sk = rng.integers(0, 256, 32, dtype=np.uint8).tobytes()
        signers.append((sk, hostpath.public_from_secret(sk)))
    accounts = [
        rng.integers(0, 256, 32, dtype=np.uint8).tobytes()
        for _ in range(n_accounts)
    ]
    program = rng.integers(0, 256, 32, dtype=np.uint8).tobytes()

    rows = np.zeros((n_txns, wire.LINK_MTU), dtype=np.uint8)
    szs = np.zeros(n_txns, dtype=np.uint16)
    good = np.ones(n_txns, dtype=bool)
    blockhash = rng.integers(0, 256, 32, dtype=np.uint8).tobytes()
    for i in range(n_txns):
        sk, pk = signers[i % n_signers]
        extra = [accounts[j] for j in rng.choice(n_accounts, 2, replace=False)]
        addrs = [pk] + extra + [program]
        data = rng.integers(0, 256, rng.integers(8, 64), dtype=np.uint8).tobytes()
        body = T.build(
            [bytes(64)],
            addrs,
            blockhash,
            [(len(addrs) - 1, [0, 1, 2], data)],
            readonly_unsigned_cnt=1,
        )
        desc = T.parse(body)
        assert desc is not None
        msg = desc.message(body)
        sig = hostpath.sign(sk, msg)
        payload = body[:1] + sig + body[1 + 64 :]
        if corrupt_frac > 0 and rng.random() < corrupt_frac:
            b = bytearray(payload)
            b[1 + rng.integers(0, 64)] ^= 0xFF
            payload = bytes(b)
            good[i] = False
        full = wire.append_trailer(payload, desc)
        rows[i, : len(full)] = np.frombuffer(full, dtype=np.uint8)
        szs[i] = len(full)
    return rows, szs, good


class SynthTile(Tile):
    """Streams a pre-signed txn pool; sig field = pool index tag."""

    schema = MetricsSchema(
        counters=("published_txns", "flood_dup_txns"),
    )

    def __init__(
        self,
        rows: np.ndarray,
        szs: np.ndarray,
        *,
        total: int | None = None,
        repeat: int = 1,
        name: str = "synth",
    ):
        """Publish each pool entry `repeat` times (back to back batches),
        up to `total` frags overall (None = until halted)."""
        self.name = name
        self.rows = rows
        self.szs = szs
        self.repeat = repeat
        self.total = total
        self.sent = 0
        # injected duplicate-storm queue (faultinj flood faults, ISSUE
        # 13): pool indices re-published verbatim — dedup must collapse
        # them, exactly-once at the sink is the invariant under storm
        import collections

        self._dups: collections.deque = collections.deque()
        # the dedup tag downstream tiles key on: first 8B of the ed25519
        # signature (reference: fd_verify.c publishes with this sig field)
        tr = wire.parse_trailers(rows, szs.astype(np.int64))
        n = len(rows)
        sig0 = rows[
            np.arange(n)[:, None], tr["sig_off"][:, None] + np.arange(8)
        ]
        self.tags = sig0.astype(np.uint64) @ (
            np.uint64(1) << (np.uint64(8) * np.arange(8, dtype=np.uint64))
        )

    def after_credit(self, ctx: MuxCtx) -> None:
        if ctx.faults is not None:
            for fi, kind, count, _prof in ctx.faults.take_injected():
                if kind != "flood":
                    continue  # conn_churn is wire-edge-only; ignore
                # deterministic duplicate storm: pool indices from the
                # injector's seeded hash — a replayed seed re-publishes
                # the SAME duplicates (disco/faultinj.py contract)
                from firedancer_tpu.disco.faultinj import _hash_u64

                pool = len(self.rows)
                h = _hash_u64(
                    ctx.faults.inj.seed, fi,
                    np.arange(count, dtype=np.uint64),
                )
                self._dups.extend(int(x) for x in h % np.uint64(pool))
        budget = ctx.credits
        if budget <= 0:
            return
        if self._dups:
            take = min(len(self._dups), budget)
            idx = np.array(
                [self._dups.popleft() for _ in range(take)], dtype=np.int64
            )
            ctx.publish(self.tags[idx], self.rows[idx], self.szs[idx])
            ctx.metrics.inc("flood_dup_txns", take)
            budget -= take
            if budget <= 0:
                return
        if self.total is not None:
            budget = min(budget, self.total - self.sent)
            if budget <= 0:
                return
        pool = len(self.rows)
        idx = (np.arange(self.sent, self.sent + budget) // self.repeat) % pool
        ctx.publish(self.tags[idx], self.rows[idx], self.szs[idx])
        self.sent += budget
        ctx.metrics.inc("published_txns", budget)
