"""Inter-tile wire format for the ingress pipeline.

Reference model: the quic tile publishes payload + parsed fd_txn_t + sz as
one dcache entry (FD_TPU_DCACHE_MTU = 2086, src/disco/fd_disco_base.h:31-35)
so downstream tiles never re-parse.  Our equivalent is leaner: the parse
descriptor fields the verify/dedup/pack tiles actually need are packed into
a fixed 16-byte trailer appended to the raw txn bytes:

    [ txn bytes (txn_sz)
      | u16 sig_off | u16 pub_off | u16 msg_off | u16 msg_len | u16 txn_sz
      | u8 sig_cnt | u8 acct_cnt | u8 ro_signed_cnt | u8 ro_unsigned_cnt
      | u16 blockhash_off ]

frag.sz = txn_sz + 16.  All trailer fields are little-endian, and every
consumer extracts them with vectorized numpy gathers — no per-frag Python
on the hot path.
"""

from __future__ import annotations

import numpy as np

from firedancer_tpu.ballet import txn as T
from firedancer_tpu.tango import rings as R

TRAILER_SZ = 16
#: dcache MTU for pipeline links carrying txn+trailer payloads
LINK_MTU = T.MTU + TRAILER_SZ


def expand_native(
    dcache: R.DCache,
    frags: np.ndarray,
    msg_width: int,
    with_digests: bool = False,
    with_msgs: bool = True,
) -> dict:
    """One native call: dcache gather + trailer parse + per-sig lane
    expansion + dedup tags + (optionally) the SHA512(R||A||M) k-digests
    (fdt_verify_expand) — the verify tile's whole host prep, GIL-released.

    with_msgs=False skips the per-lane message copy entirely (the digest
    path never ships messages to the device).

    Returns dict(rows, szs, sig_cnt, tags, sigs, pubs, txn_idx
    [, msgs, lens][, digests]) with lane arrays truncated to the lane
    count."""
    chunks = np.ascontiguousarray(frags["chunk"], np.uint32)
    szs = np.ascontiguousarray(frags["sz"], np.uint16)
    n = len(chunks)
    width = dcache.mtu
    # worst-case lanes/txn: the C bounds check admits sig_cnt only while
    # 64*cnt fits inside the payload, i.e. cnt <= (width - TRAILER_SZ)/64
    max_lanes = n * max((width - TRAILER_SZ) // 64, 1)
    rows = np.empty((n, width), np.uint8)
    msgs = np.empty((max_lanes, msg_width), np.uint8) if with_msgs else None
    lens = np.empty(max_lanes, np.int32) if with_msgs else None
    sigs = np.empty((max_lanes, 64), np.uint8)
    pubs = np.empty((max_lanes, 32), np.uint8)
    txn_idx = np.empty(max_lanes, np.int32)
    sig_cnt = np.empty(n, np.int32)
    tags = np.empty(n, np.uint64)
    digests = np.empty((max_lanes, 64), np.uint8) if with_digests else None
    lanes = R._lib.fdt_verify_expand(
        R._ptr(dcache.mem), chunks.ctypes.data, szs.ctypes.data, n, width,
        rows.ctypes.data, msg_width,
        msgs.ctypes.data if msgs is not None else None,
        lens.ctypes.data if lens is not None else None,
        sigs.ctypes.data, pubs.ctypes.data, txn_idx.ctypes.data,
        sig_cnt.ctypes.data, tags.ctypes.data,
        digests.ctypes.data if digests is not None else None,
    )
    out = dict(
        rows=rows, szs=szs, sig_cnt=sig_cnt.astype(np.int64), tags=tags,
        sigs=sigs[:lanes], pubs=pubs[:lanes], txn_idx=txn_idx[:lanes],
    )
    if msgs is not None:
        out["msgs"] = msgs[:lanes]
        out["lens"] = lens[:lanes]
    if digests is not None:
        out["digests"] = digests[:lanes]
    return out


def append_trailer(payload: bytes, desc: T.TxnDesc) -> bytes:
    """Producer side (synth/quic tile): serialize the trailer."""
    n = len(payload)
    tr = np.zeros(TRAILER_SZ, dtype=np.uint8)
    u16 = tr[:10].view("<u2")
    u16[0] = desc.signature_off
    u16[1] = desc.acct_addr_off
    u16[2] = desc.message_off
    u16[3] = n - desc.message_off
    u16[4] = n
    tr[10] = desc.signature_cnt
    tr[11] = desc.acct_addr_cnt
    tr[12] = desc.readonly_signed_cnt
    tr[13] = desc.readonly_unsigned_cnt
    tr[14:16].view("<u2")[0] = desc.recent_blockhash_off
    return payload + tr.tobytes()


def parse_trailers(rows: np.ndarray, szs: np.ndarray) -> dict[str, np.ndarray]:
    """Vectorized trailer extraction from gathered (n, width) payload rows.

    Returns int32 arrays per trailer field.
    """
    n = len(rows)
    base = (szs.astype(np.int64) - TRAILER_SZ)[:, None]
    idx = base + np.arange(TRAILER_SZ, dtype=np.int64)[None, :]
    tb = rows[np.arange(n)[:, None], idx].astype(np.int32)
    u16 = tb[:, 0:10:2] | (tb[:, 1:10:2] << 8)
    return {
        "sig_off": u16[:, 0],
        "pub_off": u16[:, 1],
        "msg_off": u16[:, 2],
        "msg_len": u16[:, 3],
        "txn_sz": u16[:, 4],
        "sig_cnt": tb[:, 10],
        "acct_cnt": tb[:, 11],
        "ro_signed_cnt": tb[:, 12],
        "ro_unsigned_cnt": tb[:, 13],
        "bh_off": tb[:, 14] | (tb[:, 15] << 8),
    }


def expand_sig_lanes(rows: np.ndarray, tr: dict[str, np.ndarray], msg_width: int):
    """Expand n txns into one verify lane per signature, fully vectorized.

    Signer pubkey j signs the message with signature j (fd_txn_verify
    behavior, /root/reference/src/app/fdctl/run/tiles/fd_verify.h:43-88).

    Returns (msgs (L, msg_width) u8 zero-padded, lens (L,) i32,
    sigs (L, 64) u8, pubs (L, 32) u8, txn_idx (L,) i32).
    """
    n = len(rows)
    cnt = tr["sig_cnt"].astype(np.int64)
    txn_idx = np.repeat(np.arange(n, dtype=np.int64), cnt)
    lanes = len(txn_idx)
    # per-lane signature index within its txn: 0..cnt[t]-1
    starts = np.concatenate(([0], np.cumsum(cnt)[:-1]))
    sig_j = np.arange(lanes, dtype=np.int64) - np.repeat(starts, cnt)

    sig_base = tr["sig_off"].astype(np.int64)[txn_idx] + 64 * sig_j
    sigs = rows[txn_idx[:, None], sig_base[:, None] + np.arange(64)]
    pub_base = tr["pub_off"].astype(np.int64)[txn_idx] + 32 * sig_j
    pubs = rows[txn_idx[:, None], pub_base[:, None] + np.arange(32)]

    msg_off = tr["msg_off"].astype(np.int64)[txn_idx]
    msg_len = tr["msg_len"].astype(np.int64)[txn_idx]
    col = np.arange(msg_width, dtype=np.int64)[None, :]
    src = np.minimum(msg_off[:, None] + col, rows.shape[1] - 1)
    msgs = rows[txn_idx[:, None], src]
    msgs = np.where(col < msg_len[:, None], msgs, 0).astype(np.uint8)
    return msgs, msg_len.astype(np.int32), sigs, pubs, txn_idx.astype(np.int32)
