"""Metric tile: Prometheus scrape endpoint over every tile's metrics.

Reference model: src/app/fdctl/run/tiles/fd_metric.c — an HTTP server
reading all tiles' metrics shared memory and rendering the Prometheus
text exposition format.  This build reads the SAME schema the monitor
consumes (the topology's published manifest / in-process registry) and
serves it via ballet.http.

Naming: fdt_<tile>_<metric>[_total] for counters;
fdt_<tile>_<metric>_bucket{le="2^k"} / _sum / _count for the 16-bucket
power-of-two histograms (disco/metrics.py layout).
"""

from __future__ import annotations

from firedancer_tpu.ballet.http import HttpServer
from firedancer_tpu.disco.metrics import Metrics, MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile


def render_prometheus(tiles: dict[str, Metrics]) -> bytes:
    """Prometheus text format over {tile name: Metrics}."""
    out = []
    for tile, m in sorted(tiles.items()):
        for c in m.schema.counters:
            out.append(f"# TYPE fdt_{tile}_{c} counter")
            out.append(f"fdt_{tile}_{c} {m.counter(c)}")
        for hname in m.schema.hists:
            h = m.hist(hname)
            out.append(f"# TYPE fdt_{tile}_{hname} histogram")
            cum = 0
            # width-agnostic: wide hists (sched_lag_us-class) carry
            # more than HIST_BUCKETS buckets
            for b in range(len(h["buckets"])):
                cum += h["buckets"][b]
                le = (1 << (b + 1)) - 1
                out.append(
                    f'fdt_{tile}_{hname}_bucket{{le="{le}"}} {cum}'
                )
            out.append(
                f'fdt_{tile}_{hname}_bucket{{le="+Inf"}} {h["count"]}'
            )
            out.append(f"fdt_{tile}_{hname}_sum {h['sum']}")
            out.append(f"fdt_{tile}_{hname}_count {h['count']}")
    return ("\n".join(out) + "\n").encode()


class MetricTile(Tile):
    """Serves /metrics over HTTP.  Reads either the in-process topology
    registry (registry=dict of name->Metrics) or a named workspace
    manifest (wksp_name=..., the cross-process monitor path)."""

    name = "metric"
    schema = MetricsSchema(counters=("scrapes", "bad_requests"))
    #: observer tile: closes over the topology's registry callable, so
    #: it stays a parent THREAD under the process runtime (it only
    #: reads shared memory — no isolation is lost)
    proc_safe = False

    def __init__(
        self,
        *,
        registry: dict[str, Metrics] | None = None,
        wksp_name: str | None = None,
        addr=("127.0.0.1", 0),
    ):
        assert (registry is None) != (wksp_name is None), (
            "exactly one of registry / wksp_name"
        )
        self._registry = registry
        self._wksp_name = wksp_name
        self._addr_req = addr
        self.server: HttpServer | None = None
        self._ctx: MuxCtx | None = None

    @property
    def addr(self):
        return self.server.addr

    def _tiles(self) -> dict[str, Metrics]:
        if self._registry is not None:
            # in-process: a dict or a callable returning one (a Topology
            # binds its registry only after build(), so tiles constructed
            # earlier pass `topo.metrics_registry`)
            r = self._registry
            return r() if callable(r) else r
        from firedancer_tpu.app.monitor import Monitor

        mon = Monitor(self._wksp_name)
        return {name: tv.metrics for name, tv in mon.tiles.items()}

    def _handle(self, req):
        if req.path not in ("/metrics", "/"):
            return 404, b"not found\n", "text/plain"
        self._ctx.metrics.inc("scrapes")
        body = render_prometheus(self._tiles())
        return 200, body, "text/plain; version=0.0.4; charset=utf-8"

    def on_boot(self, ctx: MuxCtx) -> None:
        self._ctx = ctx
        self.server = HttpServer(self._handle, self._addr_req)

    def on_halt(self, ctx: MuxCtx) -> None:
        if self.server is not None:
            self.server.close()
