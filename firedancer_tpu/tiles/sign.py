"""Sign tile (keyguard): the only tile holding the identity private key.

Reference model: src/disco/keyguard/ + src/app/fdctl/run/tiles/fd_sign.c —
other tiles (quic/TLS certs, shred merkle roots, gossip) request
signatures over dedicated request/response rings; the keyguard refuses
payloads whose type cannot be unambiguously determined, so a compromised
peer tile can never trick it into signing a transaction or a message of
another protocol (fd_keyguard.h:26-50 payload-type matchers).

One request ring per role (like the reference's per-peer rings): the role
is a property of the ring, not of the frag, so a compromised peer cannot
claim a different role than its ring grants.  Request frag payload = the
raw bytes to sign; response = the 64-byte signature with the request's
sig field echoed for correlation.
"""

from __future__ import annotations

import numpy as np

from firedancer_tpu.ballet import txn as T
from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile

# roles (who may sign what; one role per in-ring, like the reference's
# per-peer rings)
ROLE_SHRED = 1  # 32-byte merkle roots
ROLE_TLS_CV = 2  # TLS 1.3 CertificateVerify transcripts
ROLE_GOSSIP = 3  # gossip CRDS payloads

_CV_PREFIX = b" " * 64 + b"TLS 1.3, server CertificateVerify" + b"\0"


def payload_allowed(role: int, payload: bytes) -> bool:
    """Type matcher: refuse anything ambiguous (fd_keyguard behavior:
    a payload that PARSES AS A TRANSACTION is never signed by any role —
    the identity key must not be usable to forge txns)."""
    if T.parse(payload) is not None or T.parse(payload[1:]) is not None:
        return False
    if role == ROLE_SHRED:
        # merkle roots: 20-byte bmtree shred nodes or 32-byte wide nodes
        return len(payload) in (20, 32)
    if role == ROLE_TLS_CV:
        return payload.startswith(_CV_PREFIX) and len(payload) == len(
            _CV_PREFIX
        ) + 32
    if role == ROLE_GOSSIP:
        return 0 < len(payload) <= 1232
    return False


class SignTile(Tile):
    """ins[i] = request ring for role roles[i]; outs[i] = its responses."""

    name = "sign"
    schema = MetricsSchema(counters=("signed", "refused"))

    def __init__(self, identity_secret: bytes, roles: list[int]):
        self.identity_secret = identity_secret
        self.roles = roles
        self.pubkey: bytes | None = None

    def on_boot(self, ctx: MuxCtx) -> None:
        from firedancer_tpu.ops.ed25519 import golden

        self.pubkey = golden.public_from_secret(self.identity_secret)

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        from firedancer_tpu.ops.ed25519 import golden

        role = self.roles[in_idx]
        il = ctx.ins[in_idx]
        rows = il.gather(frags)
        for i in range(len(rows)):
            payload = rows[i, : frags["sz"][i]].tobytes()
            if not payload_allowed(role, payload):
                ctx.metrics.inc("refused")
                continue
            sig = golden.sign(self.identity_secret, payload)
            out = np.frombuffer(sig, np.uint8)
            ctx.outs[in_idx].publish(
                frags["sig"][i : i + 1],
                out[None, :],
                np.array([64], np.uint16),
            )
            ctx.metrics.inc("signed")
