"""Pack tile: buffers verified txns and schedules microblocks to banks.

Reference model: src/app/fdctl/run/tiles/fd_pack.c — during_frag inserts
incoming txns into the pack engine; after_credit, when a bank is free and
the microblock cadence (<= 2ms, MICROBLOCK_DURATION_NS fd_pack.c:26) has
elapsed, emits fd_pack_schedule_next_microblock's output to that bank's
ring and tracks completion via the bank-busy backchannel.

Here the engine is ballet/pack.Pack (dense-array scheduler backed by the
native fdt_pack.c hot paths + optional TPU prefilter) and the completion
backchannel is a reliable bank→pack ring carrying (bank, handle) frags.
Ingress inserts are BATCHED: one fdt_txn_scan over the drained frag batch
then a vectorized slot scatter — no per-txn Python on the hot path.

Divergence from the reference, by design: `mb_inflight` microblocks may
be outstanding per bank (the reference keeps one per bank tile and relies
on dedicated cores; on a shared-core host the pack→bank→pack round-trip
latency is scheduling-bound, so pipelining depth — not parallel cores —
is what keeps the banks saturated).  Account locks are held per
microblock exactly as in the reference, so conflict safety is unchanged.

Microblock wire format (one frag per microblock on the pack_bank link):
    [ u32 handle | u16 bank | u16 txn_cnt | txn_cnt * ( u16 sz | sz bytes ) ]
"""

from __future__ import annotations

import numpy as np

from firedancer_tpu.ballet import pack as P
from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile, drain_straggler_ins
from firedancer_tpu.tango import rings as R
from firedancer_tpu.tango import tempo

from . import wire

MICROBLOCK_DURATION_NS = 2_000_000  # reference cadence: fd_pack.c:26
MB_HDR = 8


def mb_encode(
    handle: int, bank: int, rows: np.ndarray, szs: np.ndarray,
    idx: np.ndarray | None = None,
) -> np.ndarray:
    """Native microblock encode.  idx selects rows (e.g. pool slots);
    None encodes every row in order."""
    szs16 = np.ascontiguousarray(szs, np.uint16)
    if idx is None:
        idx = np.arange(len(szs16), dtype=np.int64)
    idx = np.ascontiguousarray(idx, np.int64)
    n = len(idx)
    total = MB_HDR + int(szs16[idx].sum()) + 2 * n
    out = np.zeros(total, dtype=np.uint8)
    got = R._lib.fdt_mb_encode(
        np.ascontiguousarray(rows).ctypes.data, rows.shape[1],
        szs16.ctypes.data, idx.ctypes.data, n, handle, bank,
        out.ctypes.data, total,
    )
    assert got == total
    return out


def mb_decode(buf: np.ndarray):
    handle = int(buf[0:4].view("<u4")[0])
    bank = int(buf[4:6].view("<u2")[0])
    n = int(buf[6:8].view("<u2")[0])
    txns = []
    off = MB_HDR
    for _ in range(n):
        sz = int(buf[off : off + 2].view("<u2")[0])
        txns.append(buf[off + 2 : off + 2 + sz])
        off += 2 + sz
    return handle, bank, txns


class PackTile(Tile):
    """ins[0] = dedup_pack txns; ins[1..] = bank completion rings;
    outs[i] = pack_bank ring for bank i."""

    schema = MetricsSchema(
        counters=(
            "inserted_txns",
            "insert_rejected",
            "microblocks",
            "microblock_txns",
            "completions",
            "blocks",
            # completion whose (bank, handle) is no longer outstanding:
            # a restarted bank replays its ring window and re-publishes
            # completions this tile already released — a metered drop,
            # never a crash (exactly-once lives in the bank journal)
            "stale_completions",
        ),
    )

    def __init__(
        self,
        n_banks: int,
        *,
        depth: int = 4096,
        cu_limit: int = 1_500_000,
        txn_limit: int = 31,
        mb_inflight: int = 1,
        microblock_ns: int = MICROBLOCK_DURATION_NS,
        slot_ns: int = 400_000_000,
        use_device_select: bool = False,
        name: str = "pack",
    ):
        """slot_ns: block-budget rollover period.  The reference resets
        pack's block/vote/writer budgets at leader-slot boundaries
        (fd_pack_end_block); this tile approximates the slot clock with
        wall time at the mainnet slot duration — without the rollover the
        48M-CU block budget is consumed exactly once and scheduling
        stops forever.

        mb_inflight: outstanding microblocks per bank (pipelining depth;
        see the module docstring)."""
        self.name = name
        self.n_banks = n_banks
        self.cu_limit = cu_limit
        self.txn_limit = txn_limit
        self.mb_inflight = mb_inflight
        self.microblock_ns = microblock_ns
        self.slot_ns = slot_ns
        self.engine = P.Pack(depth, max_banks=n_banks)
        #: scheduling policy knobs shared verbatim with the native
        #: after-credit hook (schedule_microblock defaults)
        self.vote_fraction = 0.25
        self.scan_limit = 1024
        # per-bank busy counts and cadence gates live in native-visible
        # i64 arrays: the fdt_pack_sched hook and the Python after_credit
        # mutate the SAME words, so the two loops are interchangeable
        # mid-run.  Per-BANK cadence, as in the reference (fd_pack.c:193
        # sets bank_ready_at[i] = now + MICROBLOCK_DURATION_NS per
        # bank) — a global gate would cap the whole tile at 1/cadence
        # regardless of bank count.
        self.bank_busy = np.zeros(n_banks, np.int64)
        self._bank_ready_at = np.zeros(n_banks, np.int64)
        #: block-budget rollover deadline (0 = unarmed); armed on first
        #: use by whichever loop runs first
        self._block_deadline = np.zeros(1, np.int64)
        self._byte_limit = 0  # derived from the out-ring MTU at boot
        self._dev_select = None
        if use_device_select:
            from firedancer_tpu.ops import pack_select

            self._dev_select = pack_select.select_noconflict

    def on_boot(self, ctx: MuxCtx) -> None:
        if ctx.outs and ctx.outs[0].dcache is not None:
            # the encoded microblock must fit one frag on the bank ring
            # (frag sz is u16): headroom below both the dcache MTU and
            # the meta field's ceiling
            self._byte_limit = min(ctx.outs[0].dcache.mtu, 0xFFFF) - MB_HDR

    #: native stem scan scratch rows (frags per inner round; bigger
    #:  drains chunk through it)
    STEM_SCAN_CAP = 1024

    def native_handler(self, ctx: MuxCtx):
        """Native stem fast path: the full data-plane tile (ISSUE 11).

        * INSERT (ins[0], ISSUE 10): gather + fdt_txn_scan(+bitsets) +
          free-slot scatter into the engine's dense pool arrays in one
          GIL-released call.  The priority-eviction path (pool full)
          bails to Python before mutating anything.
        * COMPLETIONS (ins[1..]): decode (bank << 32) | handle sigs,
          microblock_complete slot release + exact lock release via
          fdt_pack_release_x — a pending completion no longer ejects
          the stem.
        * SCHEDULING (after-credit hook, fdt_pack_sched): per-bank
          cadence gating, per-bank cr_avail re-read, votes-first
          priority ordering + the fdt_pack_select_x greedy conflict
          walk, CU/byte/txn budgeting, fdt_mb_encode straight into the
          out dcache, publish, busy/ready bookkeeping — all inside the
          GIL-released burst.

        Block-boundary end_block, the eviction path, and device_select
        remain Python slow paths handed back unconsumed (device_select
        keeps the PR 9 insert-only shape entirely)."""
        if not ctx.ins or ctx.ins[0].dcache is None:
            return None
        eng = self.engine
        cap = self.STEM_SCAN_CAP
        sw = ctx.ins[0].dcache.mtu
        W = eng.W
        s = (
            np.zeros((cap, sw), np.uint8),  # 0 scan rows
            np.zeros(cap, np.uint32),  # 1 scan szs
            np.zeros(cap, np.uint8),  # 2 ok
            np.zeros(cap, np.uint8),  # 3 is_vote
            np.zeros(cap, np.uint8),  # 4 fast
            np.zeros(cap, np.uint32),  # 5 cost
            np.zeros(cap, np.uint64),  # 6 rewards
            np.zeros(cap, np.uint32),  # 7 cu_limit
            np.zeros(cap, np.uint64),  # 8 tags
            np.zeros(cap, np.uint64),  # 9 lamports
            np.zeros(cap, np.uint32),  # 10 payer_off
            np.zeros(cap, np.uint32),  # 11 src_off
            np.zeros(cap, np.uint32),  # 12 dst_off
            np.zeros(cap, np.uint32),  # 13 fee
            np.zeros((cap, W), np.uint64),  # 14 bs_rw
            np.zeros((cap, W), np.uint64),  # 15 bs_w
            np.zeros((cap, P.MAX_WRITERS), np.uint64),  # 16 whash
            np.zeros(cap, np.uint8),  # 17 w_cnt
            np.zeros((cap, P.MAX_READERS), np.uint64),  # 18 rhash
            np.zeros(cap, np.uint8),  # 19 r_cnt
        )
        args = np.zeros(43, np.uint64)
        args[0] = eng.state.ctypes.data
        args[1] = len(eng.state)
        args[2] = eng.rows.ctypes.data
        args[3] = eng.rows.shape[1]
        args[4] = eng.szs.ctypes.data
        args[5] = eng.rewards.ctypes.data
        args[6] = eng.cost.ctypes.data
        args[7] = eng.expires_at.ctypes.data
        args[8] = eng.sig_tag.ctypes.data
        args[9] = eng.is_vote.ctypes.data
        args[10] = eng.bs_rw.ctypes.data
        args[11] = eng.bs_w.ctypes.data
        args[12] = W
        args[13] = eng.whash.ctypes.data
        args[14] = eng.w_cnt.ctypes.data
        args[15] = P.MAX_WRITERS
        args[16] = eng.rhash.ctypes.data
        args[17] = eng.r_cnt.ctypes.data
        args[18] = P.MAX_READERS
        args[19] = eng.nbits
        args[20] = wire.TRAILER_SZ
        args[21] = s[0].ctypes.data
        args[22] = sw
        args[23] = cap
        for k in range(1, 20):  # PH_SSZS .. PH_SRCNT are contiguous
            args[23 + k] = s[k].ctypes.data

        # native scheduler + completion handling: only when every bank
        # has its own dcache-backed out ring and the policy has no
        # Python-only piece on the hot path (device_select keeps the
        # insert-only shape; a zero byte_limit would let an encoded
        # microblock outgrow the out MTU inside C)
        sched_ok = (
            self._dev_select is None
            and self._byte_limit > 0
            and len(ctx.outs) == self.n_banks
            and all(o.dcache is not None for o in ctx.outs)
        )
        if not sched_ok:
            return R.StemSpec(
                R.STEM_H_PACK, args,
                counters=("inserted_txns", "insert_rejected"),
                keepalive=(s, args),
                native_ins=(0,),
                cap=cap,
            )

        eng_p = len(eng.state)
        sscr = (
            np.zeros(eng_p, np.int64),  # candidate order
            np.zeros(eng_p, np.int64),  # merge scratch
            np.zeros(eng_p, np.float64),  # priorities
            np.zeros(eng_p, np.int64),  # picks / chain walk
        )
        sa = np.zeros(R.PACK_SCHED_WORDS, np.uint64)
        sa[0] = eng.state.ctypes.data
        sa[1] = eng_p
        sa[2] = eng.rows.ctypes.data
        sa[3] = eng.rows.shape[1]
        sa[4] = eng.szs.ctypes.data
        sa[5] = eng.rewards.ctypes.data
        sa[6] = eng.cost.ctypes.data
        sa[7] = eng.is_vote.ctypes.data
        sa[8] = eng.whash.ctypes.data
        sa[9] = eng.w_cnt.ctypes.data
        sa[10] = P.MAX_WRITERS
        sa[11] = eng.rhash.ctypes.data
        sa[12] = eng.r_cnt.ctypes.data
        sa[13] = P.MAX_READERS
        sa[14] = eng.lw_keys.ctypes.data
        sa[15] = eng.lw_vals.ctypes.data
        sa[16] = eng._lock_mask
        sa[17] = eng.lr_keys.ctypes.data
        sa[18] = eng.lr_vals.ctypes.data
        sa[19] = eng.wc_keys.ctypes.data
        sa[20] = eng.wc_vals.ctypes.data
        sa[21] = eng._wc_mask
        sa[22] = eng.writer_cost_cap
        sa[23] = eng._sched_words.ctypes.data
        sa[24] = eng.block_cost_limit
        sa[25] = eng.vote_cost_limit
        sa[26] = eng.mb_used.ctypes.data
        sa[27] = eng.mb_bank.ctypes.data
        sa[28] = eng.mb_handle.ctypes.data
        sa[29] = eng.mb_head.ctypes.data
        sa[30] = eng.mb_cnt.ctypes.data
        sa[31] = eng.mb_cost.ctypes.data
        sa[32] = eng.mb_next.ctypes.data
        sa[33] = len(eng.mb_used)
        sa[34] = self.n_banks
        sa[35] = self.bank_busy.ctypes.data
        sa[36] = self._bank_ready_at.ctypes.data
        sa[37] = self.mb_inflight
        sa[38] = self.microblock_ns
        sa[39] = self.cu_limit
        sa[40] = self.txn_limit
        sa[41] = self._byte_limit
        sa[42] = np.float64(self.vote_fraction).view(np.uint64)
        sa[43] = self.scan_limit
        sa[44] = self._block_deadline.ctypes.data
        sa[45] = self.slot_ns
        sa[46] = sscr[0].ctypes.data
        sa[47] = sscr[1].ctypes.data
        sa[48] = sscr[2].ctypes.data
        sa[49] = sscr[3].ctypes.data
        return R.StemSpec(
            R.STEM_H_PACK, args,
            counters=("inserted_txns", "insert_rejected", "microblocks",
                      "microblock_txns", "completions",
                      "stale_completions"),
            keepalive=(s, args, sa, sscr),
            cap=cap,
            ac_handler=R.STEM_AC_PACK,
            ac_args=sa,
        )

    def on_epoch(self, ctx: MuxCtx) -> None:
        """Elastic bank membership (disco/elastic.py): pack is the
        bank kind's PRODUCER — assignment is explicit (it picks the out
        ring), so the mask gates the scheduler rather than a seq
        journal.  A deactivated bank's cadence word is parked in the
        far future: BOTH loops (the Python after_credit and the native
        fdt_pack_sched hook) already skip a bank whose bank_ready_at
        is beyond `now`, so one shared-word store retires the bank from
        scheduling without touching the native ABI.  The stem's epoch
        watch guarantees this runs at a burst boundary before any
        post-flip scheduling round."""
        super().on_epoch(ctx)
        eb = self.elastic
        if eb is None:
            return
        from firedancer_tpu.disco.elastic import (
            BANK_PARKED_AT, BANK_PARKED_THRESH,
        )

        mask = eb.bind(ctx).mask(eb.slot)
        for i in range(self.n_banks):
            if (mask >> i) & 1:
                if self._bank_ready_at[i] >= BANK_PARKED_THRESH:
                    self._bank_ready_at[i] = 0  # re-activated: ready now
            else:
                self._bank_ready_at[i] = BANK_PARKED_AT

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        if in_idx == 0:
            il = ctx.ins[0]
            rows = il.gather(frags)
            # payload sizes: frag sz minus the 16-byte wire trailer
            szs = frags["sz"].astype(np.int64) - wire.TRAILER_SZ
            scan = P.txn_scan(
                rows, np.maximum(szs, 0).astype(np.uint32),
                nbits=self.engine.nbits, with_bitsets=True,
            )
            # dedup tags ride the frag sig field; keep them as sig_tag
            scan.tags[:] = frags["sig"]
            n_ok = self.engine.insert_batch(
                rows, np.maximum(szs, 0).astype(np.uint32), scan=scan
            )
            ctx.metrics.inc("inserted_txns", n_ok)
            if n_ok != len(rows):
                ctx.metrics.inc("insert_rejected", len(rows) - n_ok)
        else:
            # completion ring: sig field carries (bank << 32) | handle
            for sig in frags["sig"]:
                bank = int(sig) >> 32
                handle = int(sig) & 0xFFFFFFFF
                try:
                    self.engine.microblock_complete(bank, handle)
                except KeyError:
                    ctx.metrics.inc("stale_completions")
                    continue
                self.bank_busy[bank] -= 1
                ctx.metrics.inc("completions")

    def on_halt(self, ctx: MuxCtx) -> None:
        # drain straggler bank completions so a run's final microblocks
        # release their locks and the completion counters settle (banks
        # publish their last completions right up to HALT — the
        # completions == microblocks invariant raced the halt before)
        import time as _t

        if len(ctx.ins) <= 1:
            return
        comp_ins = tuple(range(1, len(ctx.ins)))
        deadline = _t.monotonic() + 1.0
        while True:
            got = drain_straggler_ins(self, ctx, only=comp_ins,
                                      budget=4096)
            if self.engine.outstanding_cnt == 0:
                break
            if got == 0:
                if _t.monotonic() >= deadline:
                    break
                _t.sleep(1e-3)

    def after_credit(self, ctx: MuxCtx) -> None:
        # hot-path-clock discipline: loop-body clock reads go through
        # the sanctioned tempo tick source, never bare time.* calls
        now = tempo.tickcount()
        if self._block_deadline[0] == 0:
            self._block_deadline[0] = now + self.slot_ns
        elif now >= self._block_deadline[0]:
            # block boundary: stop scheduling and let in-flight
            # microblocks complete, then reset the block budgets
            # (end_block requires no outstanding microblocks — the O(1)
            # counter, maintained by schedule/complete, replaces the
            # old per-call dict scan)
            if self.engine.outstanding_cnt:
                return
            self.engine.end_block()
            self._block_deadline[0] = now + self.slot_ns
            ctx.metrics.inc("blocks")
        for bank in range(self.n_banks):
            if now < self._bank_ready_at[bank]:
                continue
            if self.bank_busy[bank] >= self.mb_inflight:
                continue
            out = ctx.outs[bank]
            if out.cr_avail() < 1:
                continue
            mb = self.engine.schedule_microblock(
                bank,
                cu_limit=self.cu_limit,
                txn_limit=self.txn_limit,
                vote_fraction=self.vote_fraction,
                scan_limit=self.scan_limit,
                byte_limit=self._byte_limit,
                device_select=self._dev_select,
            )
            if mb is None:
                continue
            # encode straight from the pool (no row gather copy)
            idx = mb.txn_idx
            payload = mb_encode(
                mb.handle, bank, self.engine.rows, self.engine.szs, idx=idx
            )
            out.publish(
                np.array([(bank << 32) | mb.handle], dtype=np.uint64),
                payload[None, :],
                np.array([len(payload)], dtype=np.uint16),
            )
            self.bank_busy[bank] += 1
            self._bank_ready_at[bank] = now + self.microblock_ns
            ctx.metrics.inc("microblocks")
            ctx.metrics.inc("microblock_txns", len(idx))
