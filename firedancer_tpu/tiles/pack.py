"""Pack tile: buffers verified txns and schedules microblocks to banks.

Reference model: src/app/fdctl/run/tiles/fd_pack.c — during_frag inserts
incoming txns into the pack engine; after_credit, when a bank is free and
the microblock cadence (<= 2ms, MICROBLOCK_DURATION_NS fd_pack.c:26) has
elapsed, emits fd_pack_schedule_next_microblock's output to that bank's
ring and tracks completion via the bank-busy backchannel.

Here the engine is ballet/pack.Pack (dense-array scheduler + optional TPU
prefilter) and the completion backchannel is a reliable bank→pack ring
carrying (bank, handle) frags.

Microblock wire format (one frag per microblock on the pack_bank link):
    [ u32 handle | u16 bank | u16 txn_cnt | txn_cnt * ( u16 sz | sz bytes ) ]
"""

from __future__ import annotations

import time

import numpy as np

from firedancer_tpu.ballet import pack as P
from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile

from . import wire

MICROBLOCK_DURATION_NS = 2_000_000  # reference cadence: fd_pack.c:26
MB_HDR = 8


def mb_encode(handle: int, bank: int, rows: np.ndarray, szs: np.ndarray) -> np.ndarray:
    n = len(szs)
    total = MB_HDR + int(szs.sum()) + 2 * n
    out = np.zeros(total, dtype=np.uint8)
    out[0:4].view("<u4")[0] = handle
    out[4:6].view("<u2")[0] = bank
    out[6:8].view("<u2")[0] = n
    off = MB_HDR
    for i in range(n):
        sz = int(szs[i])
        out[off : off + 2].view("<u2")[0] = sz
        out[off + 2 : off + 2 + sz] = rows[i, :sz]
        off += 2 + sz
    return out


def mb_decode(buf: np.ndarray):
    handle = int(buf[0:4].view("<u4")[0])
    bank = int(buf[4:6].view("<u2")[0])
    n = int(buf[6:8].view("<u2")[0])
    txns = []
    off = MB_HDR
    for _ in range(n):
        sz = int(buf[off : off + 2].view("<u2")[0])
        txns.append(buf[off + 2 : off + 2 + sz])
        off += 2 + sz
    return handle, bank, txns


class PackTile(Tile):
    """ins[0] = dedup_pack txns; ins[1..] = bank completion rings;
    outs[i] = pack_bank ring for bank i."""

    schema = MetricsSchema(
        counters=(
            "inserted_txns",
            "insert_rejected",
            "microblocks",
            "microblock_txns",
            "completions",
            "blocks",
        ),
    )

    def __init__(
        self,
        n_banks: int,
        *,
        depth: int = 4096,
        cu_limit: int = 1_500_000,
        txn_limit: int = 31,
        microblock_ns: int = MICROBLOCK_DURATION_NS,
        slot_ns: int = 400_000_000,
        use_device_select: bool = False,
        name: str = "pack",
    ):
        """slot_ns: block-budget rollover period.  The reference resets
        pack's block/vote/writer budgets at leader-slot boundaries
        (fd_pack_end_block); this tile approximates the slot clock with
        wall time at the mainnet slot duration — without the rollover the
        48M-CU block budget is consumed exactly once and scheduling
        stops forever."""
        self.name = name
        self.n_banks = n_banks
        self.cu_limit = cu_limit
        self.txn_limit = txn_limit
        self.microblock_ns = microblock_ns
        self.slot_ns = slot_ns
        self.engine = P.Pack(depth, max_banks=n_banks)
        self.bank_free = [True] * n_banks
        self._last_mb_ns = 0
        self._block_started_ns = 0
        self._dev_select = None
        if use_device_select:
            from firedancer_tpu.ops import pack_select

            self._dev_select = pack_select.select_noconflict

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        if in_idx == 0:
            il = ctx.ins[0]
            rows = il.gather(frags)
            tr = wire.parse_trailers(rows, frags["sz"].astype(np.int64))
            n_ok = 0
            for i in range(len(rows)):
                payload = bytes(rows[i, : tr["txn_sz"][i]])
                if self.engine.insert(payload, sig_tag=int(frags["sig"][i])) == "ok":
                    n_ok += 1
            ctx.metrics.inc("inserted_txns", n_ok)
            if n_ok != len(rows):
                ctx.metrics.inc("insert_rejected", len(rows) - n_ok)
        else:
            # completion ring: sig field carries (bank << 32) | handle
            for sig in frags["sig"]:
                bank = int(sig) >> 32
                handle = int(sig) & 0xFFFFFFFF
                self.engine.microblock_complete(bank, handle)
                self.bank_free[bank] = True
                ctx.metrics.inc("completions")

    def after_credit(self, ctx: MuxCtx) -> None:
        now = time.monotonic_ns()
        if self._block_started_ns == 0:
            self._block_started_ns = now
        elif now - self._block_started_ns >= self.slot_ns:
            # block boundary: stop scheduling and let in-flight
            # microblocks complete, then reset the block budgets
            # (end_block requires no outstanding microblocks)
            if any(v for v in self.engine.outstanding.values()):
                return
            self.engine.end_block()
            self._block_started_ns = now
            ctx.metrics.inc("blocks")
        if now - self._last_mb_ns < self.microblock_ns:
            return
        for bank in range(self.n_banks):
            if not self.bank_free[bank]:
                continue
            mb = self.engine.schedule_microblock(
                bank,
                cu_limit=self.cu_limit,
                txn_limit=self.txn_limit,
                device_select=self._dev_select,
            )
            if mb is None:
                continue
            idx = mb.txn_idx
            payload = mb_encode(
                mb.handle, bank, self.engine.rows[idx], self.engine.szs[idx]
            )
            out = ctx.outs[bank]
            out.publish(
                np.array([(bank << 32) | mb.handle], dtype=np.uint64),
                payload[None, :],
                np.array([len(payload)], dtype=np.uint16),
            )
            self.bank_free[bank] = False
            self._last_mb_ns = now
            ctx.metrics.inc("microblocks")
            ctx.metrics.inc("microblock_txns", len(idx))
