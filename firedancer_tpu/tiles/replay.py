"""Replay tile: deterministic pcap-driven ingress.

Reference model: src/disco/replay/fd_replay_tile.c — feed a captured
packet stream into a topology for reproducible testing and benchmarking.
Loads the pcap at boot (each UDP payload = one raw txn), parses txns once
into dense trailer rows, then streams them at full ring rate exactly like
the synth tile; `repeat` loops the corpus for sustained-load benches.
Replay of the same corpus is bit-identical run to run (the payload stream
carries no timestamps; tsorig is stamped at publish for latency
measurement, not part of the payload)."""

from __future__ import annotations

import numpy as np

from firedancer_tpu.ballet import txn as T
from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile, now_ts
from firedancer_tpu.waltz import pcap

from . import wire


def corpus_to_pool(path: str) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """pcap -> (rows (n, LINK_MTU) u8, szs (n,) u16, tags (n,) u64).
    Unparseable payloads are dropped (counted by the tile)."""
    rows_l, szs_l, tags_l = [], [], []
    for _ts, payload in pcap.read_udp_payloads(path):
        desc = T.parse(payload)
        if desc is None:
            continue
        full = wire.append_trailer(payload, desc)
        row = np.zeros(wire.LINK_MTU, np.uint8)
        row[: len(full)] = np.frombuffer(full, np.uint8)
        rows_l.append(row)
        szs_l.append(len(full))
        tags_l.append(
            int.from_bytes(
                payload[desc.signature_off : desc.signature_off + 8], "little"
            )
        )
    rows = np.stack(rows_l) if rows_l else np.zeros((0, wire.LINK_MTU), np.uint8)
    return rows, np.asarray(szs_l, np.uint16), np.asarray(tags_l, np.uint64)


class ReplayTile(Tile):
    """Streams a pcap corpus; sig field = first 8 sig bytes (dedup tag)."""

    schema = MetricsSchema(counters=("published_txns", "corpus_txns"))

    def __init__(
        self,
        path: str,
        *,
        total: int | None = None,
        name: str = "replay",
    ):
        """Publish corpus entries in order, looping, up to `total` frags
        (None = one full pass)."""
        self.name = name
        self.path = path
        self.total = total
        self.sent = 0
        self.rows = self.szs = self.tags = None

    def on_boot(self, ctx: MuxCtx) -> None:
        self.rows, self.szs, self.tags = corpus_to_pool(self.path)
        ctx.metrics.inc("corpus_txns", len(self.rows))
        if self.total is None:
            self.total = len(self.rows)

    def after_credit(self, ctx: MuxCtx) -> None:
        budget = min(ctx.credits, self.total - self.sent)
        if budget <= 0 or not len(self.rows):
            return
        idx = np.arange(self.sent, self.sent + budget) % len(self.rows)
        ctx.publish(
            self.tags[idx], self.rows[idx], self.szs[idx],
            tsorigs=np.full(budget, now_ts(), np.uint32),
        )
        self.sent += budget
        ctx.metrics.inc("published_txns", budget)
