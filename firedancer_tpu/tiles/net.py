"""Net tile: the socket edge, decoupled from protocol tiles.

Reference model: src/app/fdctl/run/tiles/fd_net.c — the only tile
touching the NIC (AF_XDP there, UDP sockets here): rx datagrams route by
destination port onto per-protocol rings, and protocol tiles send by
publishing to the net tile's tx ring.  Datagram frags carry the peer
address as a 6-byte prefix (ip4 + port), so protocol tiles stay sans-IO.

Ring layout: outs[0] = rx ring (to the quic tile, QUIC port + legacy
port datagrams alike; the ctl field distinguishes: CTL_QUIC/CTL_LEGACY);
ins[0] = tx ring (addr-prefixed datagrams to put on the wire).

ISSUE 12 (native block egress): both directions run as native stem
bodies (tango/native/fdt_net.c) — tx drains the ring with sendmmsg
iovecs pointing straight into the in dcache, rx recvmmsg-writes
addr-prefixed rows DIRECTLY into the out dcache as the after-credit
hook — one syscall per burst, zero Python per datagram at steady
state.  The egress route-classification metrics (the fd_ip mirror) ride
a native route cache: a destination not yet classified hands the frag
back to this file's Python loop, which does the IpStack lookup and
seeds the native cache (the bank-tile MISS -> resolve -> retry
pattern)."""

from __future__ import annotations

import socket
import struct

import numpy as np

from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile
from firedancer_tpu.tango import rings as R
from firedancer_tpu.waltz.udpsock import UdpSock

ADDR_SZ = 6
#: ctl tags for rx frags (which port the datagram arrived on)
CTL_QUIC = 8
CTL_LEGACY = 16

#: dcache MTU for net rings: addr prefix + a full UDP payload
NET_MTU = ADDR_SZ + 1500

#: native route-cache geometry: twice the Python dict's 4096-entry
#: bound so open addressing stays sparse
_RC_CAP = 8192


def addr_pack(addr: tuple[str, int]) -> bytes:
    return socket.inet_aton(addr[0]) + struct.pack("<H", addr[1])


def addr_unpack(b: bytes) -> tuple[str, int]:
    return socket.inet_ntoa(bytes(b[:4])), struct.unpack("<H", bytes(b[4:6]))[0]


class NetTile(Tile):
    """Owns the sockets; routes rx by port, drains the tx ring."""

    name = "net"
    schema = MetricsSchema(
        counters=("rx_dgrams", "tx_dgrams", "rx_bytes", "tx_bytes",
                  "oversize_drops", "tx_routed", "tx_unrouted"),
    )

    def __init__(
        self,
        *,
        quic_addr=("127.0.0.1", 0),
        udp_addr=("127.0.0.1", 0),
        burst: int = 256,
    ):
        self._quic_addr_req = quic_addr
        self._udp_addr_req = udp_addr
        self.burst = burst
        self.quic_sock: UdpSock | None = None
        self.udp_sock: UdpSock | None = None

    @property
    def quic_addr(self):
        return self.quic_sock.addr

    @property
    def udp_addr(self):
        return self.udp_sock.addr

    def on_boot(self, ctx: MuxCtx) -> None:
        self.quic_sock = UdpSock(self._quic_addr_req)
        self.udp_sock = UdpSock(self._udp_addr_req)
        # egress routing observability: mirror the host tables (the
        # reference's net tile consults fd_ip to pick the egress
        # interface/next hop for every tx, src/waltz/ip/fd_ip.c; with
        # kernel UDP sockets the kernel routes for real, so the mirror's
        # job is surfacing that decision in metrics)
        from firedancer_tpu.waltz.ip import IpStack

        try:
            self._ip = IpStack.from_proc()
        except OSError:
            self._ip = IpStack()
        self._route_cache: dict[str, bool] = {}
        # native route cache + args block (host memory; the cache is a
        # metrics mirror, rebuilt from scratch on restart)
        self._nwords = np.zeros(8, np.int64)
        self._rc_keys = np.zeros(_RC_CAP, np.uint32)
        self._rc_vals = np.zeros(_RC_CAP, np.uint8)
        self._rx_szs = np.zeros(max(self.burst, 16), np.uint32)
        self._nargs = np.zeros(4, np.uint64)
        self._nargs[0] = self._nwords.ctypes.data
        self._nargs[1] = self._rc_keys.ctypes.data
        self._nargs[2] = self._rc_vals.ctypes.data
        self._nargs[3] = self._rx_szs.ctypes.data
        self._nwords[0] = self.quic_sock.sock.fileno()  # tx rides quic
        self._nwords[1] = self.quic_sock.sock.fileno()
        self._nwords[2] = self.udp_sock.sock.fileno()
        self._nwords[3] = self.burst
        self._nwords[4] = NET_MTU
        self._nwords[5] = _RC_CAP - 1

    def _route_classify(self, ip_str: str) -> bool:
        """IpStack lookup with the Python-side cache; seeds the native
        cache so the stem's next burst stays native (MISS -> resolve ->
        retry)."""
        hit = self._route_cache.get(ip_str)
        if hit is None:
            hit = self._ip.lookup_route(ip_str) is not None
            if len(self._route_cache) < 4096:
                self._route_cache[ip_str] = hit
                ip_u32 = struct.unpack(
                    "<I", socket.inet_aton(ip_str)
                )[0]
                R._lib.fdt_net_route_put(
                    self._nargs.ctypes.data, ip_u32, int(hit)
                )
        return hit

    def native_handler(self, ctx: MuxCtx):
        """Native fast path: fdt_net_tx (sendmmsg straight from the in
        dcache, route metrics off the native cache) plus fdt_net_rx as
        the after-credit hook (recvmmsg straight into the out dcache,
        credit-gated)."""
        if (
            len(ctx.outs) != 1
            or ctx.outs[0].dcache is None
            or any(il.dcache is None for il in ctx.ins)
        ):
            return None
        return R.StemSpec(
            R.STEM_H_NET, self._nargs,
            counters=("rx_dgrams", "tx_dgrams", "rx_bytes", "tx_bytes",
                      "oversize_drops", "tx_routed", "tx_unrouted"),
            keepalive=(self._nargs, self._nwords, self._rc_keys,
                       self._rc_vals, self._rx_szs),
            ac_handler=R.STEM_AC_NET,
            ac_args=self._nargs,
        )

    def on_halt(self, ctx: MuxCtx) -> None:
        for s in (self.quic_sock, self.udp_sock):
            if s is not None:
                s.close()

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        """tx ring: addr-prefixed datagrams out the QUIC socket."""
        il = ctx.ins[in_idx]
        rows = il.gather(frags)
        pkts = []
        for i in range(len(rows)):
            row = rows[i, : frags["sz"][i]]
            pkts.append((row[ADDR_SZ:].tobytes(), addr_unpack(row[:ADDR_SZ])))
        n = self.quic_sock.send_burst(pkts)
        # route classification covers only packets actually SENT, so
        # tx_routed + tx_unrouted == tx_dgrams holds across partial
        # bursts (EAGAIN drops)
        routed = unrouted = 0
        for _, addr in pkts[:n]:
            hit = self._route_classify(addr[0])
            routed += hit
            unrouted += not hit
        if routed:
            ctx.metrics.inc("tx_routed", routed)
        if unrouted:
            ctx.metrics.inc("tx_unrouted", unrouted)
        ctx.metrics.inc("tx_dgrams", n)
        ctx.metrics.inc("tx_bytes", int(frags["sz"].sum()) - ADDR_SZ * len(rows))

    def after_credit(self, ctx: MuxCtx) -> None:
        budget = ctx.credits
        if budget <= 0:
            return
        rows_l, szs_l, ctls_l = [], [], []
        for sock, ctl in ((self.quic_sock, CTL_QUIC), (self.udp_sock, CTL_LEGACY)):
            # the budget is shared across both sockets: the combined
            # publish must stay within the iteration's credits
            take = min(self.burst, budget - len(rows_l))
            if take <= 0:
                break
            for data, addr in sock.recv_burst(take):
                if len(data) > NET_MTU - ADDR_SZ:
                    ctx.metrics.inc("oversize_drops")
                    continue
                payload = addr_pack(addr) + data
                row = np.zeros(NET_MTU, np.uint8)
                row[: len(payload)] = np.frombuffer(payload, np.uint8)
                rows_l.append(row)
                szs_l.append(len(payload))
                ctls_l.append(ctl | 3)  # SOM|EOM
        if not rows_l:
            return
        n = len(rows_l)
        ctx.metrics.inc("rx_dgrams", n)
        ctx.metrics.inc("rx_bytes", int(sum(szs_l)) - ADDR_SZ * n)
        ctx.publish(
            np.arange(n, dtype=np.uint64),
            np.stack(rows_l),
            np.asarray(szs_l, np.uint16),
            ctls=np.asarray(ctls_l, np.uint16),
        )
        ctx.credits -= n
