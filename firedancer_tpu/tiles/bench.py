"""Load-generation for the full-validator bench (benchg/benchs analog).

Reference model: src/app/fddev/bench.c:62-90 — benchg tiles sign a
stream of distinct transfer transactions, benchs blasts them over UDP at
the QUIC tile's regular (legacy, non-QUIC) transaction port, and bencho
observes landed transactions via RPC getTransactionCount.  This build's
analog: `make_transfer_pool` mass-signs a distinct-txn corpus with the
TPU batch signer (ops/ed25519/sign.py) and `UdpBlaster` is the benchs
sender thread; the observer is the existing RPC tile.

Distinctness matters: every txn has a unique (dest, amount) so dedup
cannot collapse the load and every landed count is a real execution.
"""

from __future__ import annotations

import socket
import threading
import time

import numpy as np

from firedancer_tpu.ballet import txn as T
from firedancer_tpu.flamenco.accounts import SYSTEM_PROGRAM_ID
from firedancer_tpu.ops.ed25519 import sign as dsign


def make_transfer_pool(
    n_txns: int,
    *,
    n_signers: int = 1024,
    seed: int = 0,
    amount_base: int = 1,
) -> tuple[np.ndarray, list[bytes]]:
    """n distinct signed system transfers -> ((n, sz) u8 payload rows,
    payer pubkeys to pre-fund).

    One template txn is built/parsed once; per-txn dest+amount are
    patched into the template body and the signatures come from the
    device batch signer in ONE execution across all keys.

    n_signers matters: pack's conflict-aware scheduler serializes txns
    sharing a writable payer account, so payer diversity IS the
    schedulable parallelism (the reference's benchg funds a whole
    account set for the same reason).
    """
    rng = np.random.default_rng(seed)
    secrets = [
        rng.integers(0, 256, 32, np.uint8).tobytes() for _ in range(n_signers)
    ]
    # one device batch instead of n_signers host scalar muls
    pubs = dsign.public_keys(secrets)
    blockhash = rng.integers(0, 256, 32, np.uint8).tobytes()

    # template: transfer(payer -> dest, amount); offsets recovered once
    dest0 = bytes(range(32))
    data0 = (2).to_bytes(4, "little") + (0).to_bytes(8, "little")
    body0 = T.build(
        [bytes(64)], [pubs[0], dest0, SYSTEM_PROGRAM_ID], blockhash,
        [(2, [0, 1], data0)], readonly_unsigned_cnt=1,
    )
    desc0 = T.parse(body0)
    assert desc0 is not None
    payer_off = desc0.acct_addr_off
    dest_off = payer_off + 32
    amt_off = desc0.instr[0].data_off + 4
    sz = len(body0)

    rows = np.zeros((n_txns, sz), np.uint8)
    rows[:] = np.frombuffer(body0, np.uint8)
    # unique dest per txn; amount = index (both inside the signed message)
    dests = rng.integers(0, 256, (n_txns, 32), np.uint8)
    rows[:, dest_off:dest_off + 32] = dests
    amts = (np.arange(n_txns, dtype=np.uint64) + amount_base)
    rows[:, amt_off:amt_off + 8] = (
        amts[:, None] >> (8 * np.arange(8, dtype=np.uint64))
    ).astype(np.uint8)

    msg_off = 1 + 64 * desc0.signature_cnt
    pub_rows = np.stack([np.frombuffer(p, np.uint8) for p in pubs])
    rows[:, payer_off:payer_off + 32] = pub_rows[
        np.arange(n_txns) % n_signers
    ]
    pairs = [
        (secrets[i % n_signers], rows[i, msg_off:].tobytes())
        for i in range(n_txns)
    ]
    sigs = dsign.sign_many(pairs, pubs=dict(zip(secrets, pubs)))
    for i, sig in enumerate(sigs):
        rows[i, 1:65] = np.frombuffer(sig, np.uint8)
    return rows, pubs


class UdpBlaster:
    """benchs analog: a sender thread blasting pool rows at a UDP addr.

    UDP severs the ring-credit backpressure the reference's benchs
    tiles inherit, and pack DROPS inserts when its buffer is full — an
    unpaced blast of a finite pool burns most of it as rejects within
    seconds.  Feedback pacing restores the backpressure: the owner
    updates `landed` (RPC-observed count) and the sender keeps
    sent - landed <= window."""

    def __init__(self, rows: np.ndarray, addr: tuple[str, int],
                 burst: int = 64, pace_s: float = 0.0,
                 window: int | None = None):
        self.rows = rows
        self.addr = addr
        self.burst = burst
        self.pace_s = pace_s
        self.window = window
        self.sent = 0
        #: RPC-observed landed count, updated by the measuring loop
        self.landed = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def _run(self):
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            n = len(self.rows)
            last_landed, last_progress = -1, time.monotonic()
            while not self._stop.is_set() and self.sent < n:
                if (
                    self.window is not None
                    and self.sent - self.landed > self.window
                ):
                    # permanently lost txns (UDP drops, rejects) never
                    # leave the window; a long landing stall (device
                    # tunnel hiccups block the verify tile for tens of
                    # seconds) must NOT trigger unpaced sending — that
                    # burns the finite pool as full-buffer rejects in
                    # seconds (measured round 5: a 20 s stall torched
                    # 300K of a 512K pool).  Hold position unless the
                    # stall outlives any observed tunnel hiccup.
                    now = time.monotonic()
                    if self.landed != last_landed:
                        last_landed, last_progress = self.landed, now
                    if now - last_progress < 120.0:
                        time.sleep(0.005)
                        continue
                end = min(self.sent + self.burst, n)
                for i in range(self.sent, end):
                    try:
                        sock.sendto(self.rows[i].tobytes(), self.addr)
                    except OSError:
                        pass
                self.sent = end
                if self.pace_s:
                    time.sleep(self.pace_s)
        finally:
            sock.close()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5.0)

    @property
    def done(self) -> bool:
        return self.sent >= len(self.rows)
