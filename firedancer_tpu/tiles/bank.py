"""Bank tile: executes scheduled microblocks and reports completion.

Reference model: src/app/fdctl/run/tiles/fd_bank.c — receives microblocks
from pack, executes them (in the reference via Rust FFI into Agave:
fd_ext_bank_load_and_execute_txns, fd_bank.c:100-104), flags itself free
through the busy fseq, and forwards the executed microblock to the poh
tile for mixin.

Execution runs the flamenco runtime (flamenco/runtime.py: fee collection,
system program, sBPF programs via the VM) against a funk account store
when one is provided; without a funk the tile falls back to fee-only
accounting (the round-1 stub, kept for plumbing-only tests).  Completion
travels as a frag on the bank→pack ring (sig = bank<<32 | handle); the
executed microblock is forwarded on the bank→poh ring.
"""

from __future__ import annotations

import numpy as np

from firedancer_tpu.ballet import compute_budget as CB
from firedancer_tpu.ballet import txn as T
from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile

from . import pack as packtile


def execute_txns(txns: list[np.ndarray]) -> int:
    """Fee-only fallback executor.  Returns lamports collected."""
    fees = 0
    for t in txns:
        d = T.parse(bytes(t))
        if d is None:
            continue
        fees += CB.FEE_PER_SIGNATURE * d.signature_cnt
    return fees


class BankTile(Tile):
    """ins[0] = pack_bank microblocks; outs[0] = bank_pack completions,
    outs[1] = bank_poh executed microblocks."""

    schema = MetricsSchema(
        counters=(
            "executed_microblocks",
            "executed_txns",
            "failed_txns",
            "fees_lamports",
        ),
    )

    def __init__(self, bank_id: int, name: str | None = None, *, funk=None):
        self.bank_id = bank_id
        self.name = name or f"bank{bank_id}"
        self.funk = funk
        self._executor = None

    def on_boot(self, ctx: MuxCtx) -> None:
        if self.funk is not None:
            from firedancer_tpu.flamenco.runtime import Executor

            self._executor = Executor(self.funk)
            # sysvar accounts (clock/rent/epoch schedule) materialize at
            # slot start so programs can read them like any account
            self._executor.begin_slot(0)

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        il = ctx.ins[in_idx]
        rows = il.gather(frags)
        for i in range(len(rows)):
            buf = rows[i, : frags["sz"][i]]
            handle, bank, txns = packtile.mb_decode(buf)
            assert bank == self.bank_id
            if self._executor is not None:
                fees = 0
                for t in txns:
                    # one malformed txn must not take the bank down: record
                    # it as failed and keep executing the microblock
                    try:
                        res = self._executor.execute_txn(bytes(t))
                    except Exception:
                        ctx.metrics.inc("failed_txns")
                        continue
                    fees += res.fee
                    if not res.ok:
                        ctx.metrics.inc("failed_txns")
            else:
                fees = execute_txns(txns)
            ctx.metrics.inc("executed_microblocks")
            ctx.metrics.inc("executed_txns", len(txns))
            ctx.metrics.inc("fees_lamports", fees)
            tag = np.array([(bank << 32) | handle], dtype=np.uint64)
            # forward to poh first, then free the bank at pack
            ctx.outs[1].publish(
                tag, buf[None, :], np.array([len(buf)], dtype=np.uint16)
            )
            ctx.outs[0].publish(tag)
