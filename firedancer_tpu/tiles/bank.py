"""Bank tile: executes scheduled microblocks and reports completion.

Reference model: src/app/fdctl/run/tiles/fd_bank.c — receives microblocks
from pack, executes them (in the reference via Rust FFI into Agave:
fd_ext_bank_load_and_execute_txns, fd_bank.c:100-104), flags itself free
through the busy fseq, and forwards the executed microblock to the poh
tile for mixin.

Execution is BATCHED: one native call (fdt_mb_decode + fdt_txn_scan)
parses and classifies the whole microblock, the dominant txn class
(simple system transfers) executes through the runtime's allocation-free
fast path over the funk lamports cache
(flamenco/runtime.py execute_fast_transfers), and only the remainder
walks the general per-txn executor.  That is this build's analog of the
reference never executing in the tile's own interpreter loop.

Completion travels as a frag on the bank→pack ring (sig = bank<<32 |
handle); the executed microblock is forwarded on the bank→poh ring.
"""

from __future__ import annotations

import numpy as np

from firedancer_tpu.ballet import compute_budget as CB
from firedancer_tpu.ballet import pack as P
from firedancer_tpu.ballet import txn as T
from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile
from firedancer_tpu.tango import rings as R


def execute_txns(txns: list[np.ndarray]) -> int:
    """Fee-only fallback executor.  Returns lamports collected."""
    fees = 0
    for t in txns:
        d = T.parse(bytes(t))
        if d is None:
            continue
        fees += CB.FEE_PER_SIGNATURE * d.signature_cnt
    return fees


class BankTile(Tile):
    """ins[0] = pack_bank microblocks; outs[0] = bank_pack completions,
    outs[1] = bank_poh executed microblocks."""

    schema = MetricsSchema(
        counters=(
            "executed_microblocks",
            "executed_txns",
            "failed_txns",
            "fast_txns",
            "fees_lamports",
        ),
    )

    def __init__(self, bank_id: int, name: str | None = None, *, funk=None):
        self.bank_id = bank_id
        self.name = name or f"bank{bank_id}"
        self.funk = funk
        self._executor = None
        # native-decode scratch (grown on demand)
        self._srows = np.zeros((256, T.MTU), np.uint8)
        self._sszs = np.zeros(256, np.uint32)

    def on_boot(self, ctx: MuxCtx) -> None:
        if self.funk is not None:
            from firedancer_tpu.flamenco.runtime import Executor

            self._executor = Executor(self.funk)
            # sysvar accounts (clock/rent/epoch schedule) materialize at
            # slot start so programs can read them like any account
            self._executor.begin_slot(0)

    def _decode(self, buf: np.ndarray):
        """Native microblock decode -> (rows view, szs view) scratch."""
        n = int(buf[6:8].view("<u2")[0])
        if n > len(self._sszs):
            cap = 1 << (n - 1).bit_length()
            self._srows = np.zeros((cap, T.MTU), np.uint8)
            self._sszs = np.zeros(cap, np.uint32)
        got = R._lib.fdt_mb_decode(
            np.ascontiguousarray(buf).ctypes.data, len(buf),
            self._srows.ctypes.data, self._srows.shape[1],
            self._sszs.ctypes.data, len(self._sszs),
        )
        assert got == n, "malformed microblock from pack"
        return self._srows[:n], self._sszs[:n]

    def _execute(self, ctx: MuxCtx, rows: np.ndarray, szs: np.ndarray) -> int:
        """Execute one decoded microblock; returns fees collected."""
        ex = self._executor
        n = len(rows)
        if ex is None:
            return execute_txns([rows[i, : szs[i]] for i in range(n)])
        scan = P.txn_scan(rows, szs)
        fast_idx = np.flatnonzero(scan.fast)
        fees = 0
        if len(fast_idx):
            payloads = [rows[i, : szs[i]].tobytes() for i in fast_idx]
            f, executed, failed = ex.execute_fast_transfers(
                payloads,
                scan.fee[fast_idx].tolist(),
                scan.lamports[fast_idx].tolist(),
                scan.payer_off[fast_idx].tolist(),
                scan.src_off[fast_idx].tolist(),
                scan.dst_off[fast_idx].tolist(),
            )
            fees += f
            ctx.metrics.inc("fast_txns", len(fast_idx))
            if failed:
                ctx.metrics.inc("failed_txns", failed)
        slow_idx = np.flatnonzero(~scan.fast.astype(bool))
        for i in slow_idx:
            # one malformed txn must not take the bank down: record it as
            # failed and keep executing the microblock
            try:
                res = ex.execute_txn(rows[i, : szs[i]].tobytes())
            except Exception:
                ctx.metrics.inc("failed_txns")
                continue
            fees += res.fee
            if not res.ok:
                ctx.metrics.inc("failed_txns")
        return fees

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        il = ctx.ins[in_idx]
        rows = il.gather(frags)
        for i in range(len(rows)):
            buf = rows[i, : frags["sz"][i]]
            handle = int(buf[0:4].view("<u4")[0])
            bank = int(buf[4:6].view("<u2")[0])
            assert bank == self.bank_id
            trows, tszs = self._decode(buf)
            fees = self._execute(ctx, trows, tszs)
            ctx.metrics.inc("executed_microblocks")
            ctx.metrics.inc("executed_txns", len(trows))
            ctx.metrics.inc("fees_lamports", fees)
            tag = np.array([(bank << 32) | handle], dtype=np.uint64)
            # forward to poh first, then free the bank at pack
            ctx.outs[1].publish(
                tag, buf[None, :], np.array([len(buf)], dtype=np.uint16)
            )
            ctx.outs[0].publish(tag)
