"""Bank tile: executes scheduled microblocks and reports completion.

Reference model: src/app/fdctl/run/tiles/fd_bank.c — receives microblocks
from pack, executes them (in the reference via Rust FFI into Agave:
fd_ext_bank_load_and_execute_txns, fd_bank.c:100-104), flags itself free
through the busy fseq, and forwards the executed microblock to the poh
tile for mixin.

Execution is BATCHED end to end: one native call (fdt_mb_decode +
fdt_txn_scan) parses and classifies the whole microblock, and the
dominant txn class (simple system transfers) executes through ONE
GIL-released native call per microblock (fdt_bank_exec) against a
shared-memory account table that every bank shard maps — the analog of
the reference handing the whole microblock to an external engine rather
than executing in the tile's interpreter.  Only NONTRIVIAL accounts
(data, non-system owner) fall back to the general per-txn executor, in
sequence, and the table<->funk coherence protocol in
flamenco/runtime.py keeps both views identical.

The table lives in the topology workspace (ctx.shared), so bank tiles
sharded as PROCESSES (PR 7 runtime) execute against one table without
touching the GIL or each other — pack's exact account-lock tables
already guarantee no two in-flight microblocks share a writable
account.  The per-bank undo journal + per-slot version words make a
SIGKILL mid-microblock lossless: on_boot rolls back a half-applied txn,
drains pending funk write-backs, and a redelivered microblock resumes
at the exact txn the dead incarnation reached.

Completion travels as a frag on the bank→pack ring (sig = bank<<32 |
handle); the executed microblock is forwarded on the bank→poh ring.
A malformed microblock is a metered drop (`malformed_microblocks`) that
still frees the bank at pack — one bad frag must not take the bank
down, matching the slow path's one-bad-txn rule.
"""

from __future__ import annotations

import numpy as np

from firedancer_tpu.ballet import compute_budget as CB
from firedancer_tpu.ballet import pack as P
from firedancer_tpu.ballet import txn as T
from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile
from firedancer_tpu.tango import rings as R


def execute_txns(txns: list[np.ndarray]) -> int:
    """Fee-only fallback executor.  Returns lamports collected."""
    fees = 0
    for t in txns:
        d = T.parse(bytes(t))
        if d is None:
            continue
        fees += CB.FEE_PER_SIGNATURE * d.signature_cnt
    return fees


class BankTile(Tile):
    """ins[0] = pack_bank microblocks; outs[0] = bank_pack completions,
    outs[1] = bank_poh executed microblocks."""

    schema = MetricsSchema(
        counters=(
            "executed_microblocks",
            "executed_txns",
            "failed_txns",
            "fast_txns",
            "fees_lamports",
            "malformed_microblocks",
            "native_txns",
            "committed_accounts",
        ),
    )

    #: default shared account-table slots (64 B each; all bank shards
    #: must agree — the topology asserts it)
    TABLE_SLOTS = 1 << 14

    #: funk write-back cadence: the table is authoritative (fallback
    #: txns flush per-key, restarts drain via recover), so the batched
    #: commit amortizes over microblocks — hot payers are written once
    #: per window instead of once per microblock.  Housekeeping ticks
    #: bound the staleness funk observers (RPC) can see.
    COMMIT_EVERY = 16

    def __init__(self, bank_id: int, name: str | None = None, *, funk=None,
                 native: bool = True, table_slots: int | None = None,
                 commit_every: int | None = None):
        self.bank_id = bank_id
        self.name = name or f"bank{bank_id}"
        self.funk = funk
        self.native = native
        self.table_slots = table_slots or self.TABLE_SLOTS
        self.commit_every = commit_every or self.COMMIT_EVERY
        self._executor = None
        self._table = None
        self._mb_uncommitted = 0
        # native-decode scratch (grown on demand)
        self._srows = np.zeros((256, T.MTU), np.uint8)
        self._sszs = np.zeros(256, np.uint32)

    def _use_native(self) -> bool:
        return self.native and self.funk is not None

    def shared_wksp_footprints(self) -> dict[str, int]:
        if not self._use_native():
            return {}
        from firedancer_tpu.flamenco.runtime import BankTable

        return {"banktab": BankTable.footprint(self.table_slots)}

    def wksp_footprint(self) -> int:
        # per-bank undo journal (shm arena in the process runtime, so a
        # restarted incarnation resumes a half-applied microblock)
        return 512

    def on_boot(self, ctx: MuxCtx) -> None:
        if self.funk is not None:
            from firedancer_tpu.flamenco.runtime import BankTable, Executor

            self._executor = Executor(self.funk)
            # sysvar accounts (clock/rent/epoch schedule) materialize at
            # slot start so programs can read them like any account
            self._executor.begin_slot(0)
            if self.native:
                mem = ctx.shared(
                    "banktab", BankTable.footprint(self.table_slots)
                )
                jnl = ctx.alloc("bankjnl", BankTable.JOURNAL_BYTES)
                self._table = BankTable(
                    mem, self.table_slots, journal=jnl
                )
                # restart protocol: roll back a half-applied txn and
                # drain pending write-backs BEFORE any new microblock;
                # the journal keeps (tag, txns done) so a redelivered
                # microblock resumes exactly once (see _execute)
                self._table.recover(self.funk, self._executor.xid)

    #: native stem decode/scan scratch rows (fixed; a microblock with
    #: more txns hands back to the Python path's growable scratch)
    STEM_TXN_CAP = 1024

    def native_handler(self, ctx: MuxCtx):
        """Native stem fast path (ISSUE 10): fdt_bank_pipeline fuses
        fdt_mb_decode + fdt_txn_scan + fdt_bank_exec into one call per
        microblock — the last per-microblock Python is gone.  Anything
        the shared table cannot express (a non-fast txn, a cold key, a
        NONTRIVIAL account) hands the frag back UNCONSUMED to the
        Python on_frags path, whose journal-keyed resume keeps the
        already-executed fast prefix exactly-once.  The deferred funk
        commit keeps its cadence via the after-burst hook."""
        if (
            self._table is None
            or len(ctx.outs) != 2
            or ctx.outs[1].dcache is None
            or any(il.dcache is None for il in ctx.ins)
        ):
            return None
        cap = self.STEM_TXN_CAP
        tbl = self._table
        ex = self._executor
        s = (
            np.zeros((cap, T.MTU), np.uint8),  # 0 decode rows
            np.zeros(cap, np.uint32),  # 1 szs
            np.zeros(cap, np.uint8),  # 2 ok
            np.zeros(cap, np.uint8),  # 3 is_vote
            np.zeros(cap, np.uint8),  # 4 fast
            np.zeros(cap, np.uint32),  # 5 cost
            np.zeros(cap, np.uint64),  # 6 rewards
            np.zeros(cap, np.uint32),  # 7 cu_limit
            np.zeros(cap, np.uint64),  # 8 tags
            np.zeros(cap, np.uint64),  # 9 lamports
            np.zeros(cap, np.uint32),  # 10 payer_off
            np.zeros(cap, np.uint32),  # 11 src_off
            np.zeros(cap, np.uint32),  # 12 dst_off
            np.zeros(cap, np.uint32),  # 13 fee
            np.zeros(cap, np.int64),  # 14 idx
            np.zeros(cap, np.uint8),  # 15 status
            np.zeros(cap, np.uint64),  # 16 ofees
        )
        args = np.zeros(24, np.uint64)
        args[0] = s[0].ctypes.data
        args[1] = T.MTU
        args[2] = s[1].ctypes.data
        args[3] = cap
        for k in range(2, 17):  # BH_OK .. BH_OFEES are contiguous
            args[2 + k] = s[k].ctypes.data
        args[19] = tbl.mem.ctypes.data
        args[20] = tbl.journal.ctypes.data
        args[22] = self.bank_id

        def _refresh_features() -> bool:
            # the Python fallback re-evaluates the feature flag per
            # execution (flamenco/runtime.py); refresh the baked word
            # every iteration so a slot advance / activation epoch can
            # never diverge the native path from the fallback path
            args[21] = int(
                ex.features.active("system_transfer_zero_check", ex.slot)
            )
            return True

        _refresh_features()
        return R.StemSpec(
            R.STEM_H_BANK, args,
            ready=_refresh_features,
            counters=(
                "executed_microblocks", "executed_txns", "failed_txns",
                "fast_txns", "fees_lamports", "malformed_microblocks",
                "native_txns",
            ),
            keepalive=(s, args),
            after_burst=self._stem_after_burst,
        )

    def _stem_after_burst(self, ctx: MuxCtx, ctrs) -> None:
        # the deferred-commit cadence, fed by the burst's
        # executed_microblocks delta (counter scratch slot 0)
        n_mb = int(ctrs[0])
        if n_mb:
            self._mb_uncommitted += n_mb
            if self._mb_uncommitted >= self.commit_every:
                self._commit(ctx)

    def _decode(self, buf: np.ndarray):
        """Native microblock decode -> (rows view, szs view) scratch, or
        None on a malformed microblock (metered drop at the caller)."""
        n = int(buf[6:8].view("<u2")[0])
        if n > len(self._sszs):
            cap = 1 << (n - 1).bit_length()
            self._srows = np.zeros((cap, T.MTU), np.uint8)
            self._sszs = np.zeros(cap, np.uint32)
        got = R._lib.fdt_mb_decode(
            np.ascontiguousarray(buf).ctypes.data, len(buf),
            self._srows.ctypes.data, self._srows.shape[1],
            self._sszs.ctypes.data, len(self._sszs),
        )
        if got != n:
            return None
        return self._srows[:n], self._sszs[:n]

    def _execute(self, ctx: MuxCtx, rows: np.ndarray, szs: np.ndarray,
                 tag: int) -> int | None:
        """Execute one decoded microblock; returns fees collected, or
        None when a previous incarnation already applied it in full (a
        replayed frag must re-publish but never re-execute).  `tag` is
        the carrying frag's seq — the crash-resume journal key."""
        ex = self._executor
        n = len(rows)
        if ex is None:
            return execute_txns([rows[i, : szs[i]] for i in range(n)])
        tbl = self._table
        if tbl is not None and tbl.already_complete(tag):
            # the supervisor's replay window spans many microblocks;
            # ones below the completed-seq mark were fully applied (and
            # counted) by a dead incarnation — re-executing them against
            # the surviving shm table would double-apply every transfer.
            # Known process-runtime limitation: slow-path (NONTRIVIAL)
            # writes of the dead incarnation lived only in its pickled
            # funk COPY and are NOT re-materialized here — re-executing
            # them would double-apply any trivial table-held account the
            # txn also touches, corrupting the shared table to patch a
            # funk copy that is divergent across bank processes anyway
            # (PR 7's documented funk model; shared-memory funk is
            # ROADMAP work).  The shm table — the authoritative state
            # this PR adds — stays exactly-once.
            return None
        scan = P.txn_scan(rows, szs)
        fast_idx = np.flatnonzero(scan.fast)
        slow_idx = np.flatnonzero(~scan.fast.astype(bool))
        nf = len(fast_idx)
        # txns a dead incarnation already applied under this tag (fast
        # subset positions [0, nf), then slow positions [nf, n)) — their
        # metrics were counted by that incarnation (shm), so skip silently
        resume = tbl.begin(tag) if tbl is not None else 0
        fees = 0
        if nf:
            if tbl is not None:
                # one GIL-released native call for the whole fast run;
                # scratch rows feed C directly (no per-txn .tobytes());
                # metrics count what THIS incarnation executed, so a
                # mid-microblock resume never double-counts
                f, executed, failed = ex.execute_fast_transfers_native(
                    tbl, rows, szs, fast_idx, scan,
                    tag=tag, start=min(resume, nf),
                )
                ctx.metrics.inc(
                    "native_txns", executed - ex.last_fallbacks
                )
            else:
                payloads = [rows[i, : szs[i]].tobytes() for i in fast_idx]
                f, executed, failed = ex.execute_fast_transfers(
                    payloads,
                    scan.fee[fast_idx].tolist(),
                    scan.lamports[fast_idx].tolist(),
                    scan.payer_off[fast_idx].tolist(),
                    scan.src_off[fast_idx].tolist(),
                    scan.dst_off[fast_idx].tolist(),
                )
            fees += f
            ctx.metrics.inc("fast_txns", executed)
            if failed:
                ctx.metrics.inc("failed_txns", failed)
        for k in range(len(slow_idx)):
            pos = nf + k
            if pos < resume:
                continue
            i = slow_idx[k]
            # one malformed txn must not take the bank down: record it as
            # failed and keep executing the microblock
            try:
                payload = rows[i, : szs[i]].tobytes()
                res = (
                    ex.execute_txn_with_table(tbl, payload)
                    if tbl is not None
                    else ex.execute_txn(payload)
                )
            except Exception:
                ctx.metrics.inc("failed_txns")
                if tbl is not None:
                    tbl.mark_done(tag, pos + 1)
                continue
            fees += res.fee
            if not res.ok:
                ctx.metrics.inc("failed_txns")
            if tbl is not None:
                tbl.mark_done(tag, pos + 1)
        if tbl is not None:
            tbl.mark_complete(tag)
            self._mb_uncommitted += 1
            if self._mb_uncommitted >= self.commit_every:
                self._commit(ctx)
        return fees

    def _commit(self, ctx: MuxCtx) -> None:
        """Batched funk write-back of everything the window dirtied (and
        anything a crashed sibling left pending)."""
        self._mb_uncommitted = 0
        ncom = self._table.commit(self._executor.funk, self._executor.xid)
        if ncom:
            ctx.metrics.inc("committed_accounts", ncom)

    def elastic_drained(self, ctx: MuxCtx) -> bool:
        """Retirement drain contract (disco/elastic.py): the binding
        has already established that pack acked the retiring epoch (no
        new microblocks will be scheduled here) and that the in ring is
        consumed to its head; what remains is THIS shard's deferred
        state — flush the funk commit so every balance the shard
        dirtied is durable and its shared-table slots are released
        (clean, committed slots are claimable by the surviving
        shards).  Execution itself is synchronous per frag, so a
        caught-up ring implies no half-applied microblock."""
        if self._table is not None and self._mb_uncommitted:
            self._commit(ctx)
        return True

    def during_housekeeping(self, ctx: MuxCtx) -> None:
        # bound funk staleness for observers (RPC txn counts read
        # metrics, but balances read funk): a clean table makes this a
        # single native scan
        if self._table is not None and self._mb_uncommitted:
            self._commit(ctx)

    def on_halt(self, ctx: MuxCtx) -> None:
        if self._table is not None:
            self._commit(ctx)

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        il = ctx.ins[in_idx]
        rows = il.gather(frags)
        for i in range(len(rows)):
            buf = rows[i, : frags["sz"][i]]
            handle = int(buf[0:4].view("<u4")[0])
            bank = int(buf[4:6].view("<u2")[0])
            assert bank == self.bank_id
            tag = np.array([(bank << 32) | handle], dtype=np.uint64)
            dec = self._decode(buf)
            if dec is None:
                # malformed microblock: metered drop — but the bank MUST
                # still complete at pack or its handle and account locks
                # leak; nothing is forwarded to poh
                ctx.metrics.inc("malformed_microblocks")
                ctx.outs[0].publish(tag)
                continue
            trows, tszs = dec
            fees = self._execute(ctx, trows, tszs, int(frags["seq"][i]))
            if fees is not None:
                ctx.metrics.inc("executed_microblocks")
                ctx.metrics.inc("executed_txns", len(trows))
                ctx.metrics.inc("fees_lamports", fees)
            # forward to poh first, then free the bank at pack
            ctx.outs[1].publish(
                tag, buf[None, :], np.array([len(buf)], dtype=np.uint16)
            )
            ctx.outs[0].publish(tag)
