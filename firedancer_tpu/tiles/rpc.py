"""RPC tile: JSON-RPC 2.0 over HTTP for observers and tooling.

Reference model: the fddev `bencho` tile observes landed TPS through the
validator's JSON-RPC endpoint, and src/ballet/json vendors a parser for
that client path.  This build serves the observer surface natively:
getTransactionCount / getSlot / getHealth / getVersion / getBalance /
getIdentity over ballet.http (the JSON codec is the host stdlib — the
analog of the reference vendoring a C parser).

Data sources are callables so the tile composes with any topology:
txn_count (e.g. a bank tile's executed_txns counter via the metrics
registry), slot (the poh tile), and an optional funk for balances.
"""

from __future__ import annotations

import json

from firedancer_tpu.ballet import base58
from firedancer_tpu.ballet.http import HttpServer
from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile

VERSION = "firedancer-tpu/0.3"


class RpcTile(Tile):
    name = "rpc"
    schema = MetricsSchema(counters=("requests", "bad_requests"))
    #: observer tile: its counter/slot callables close over parent-side
    #: topology state — stays a parent THREAD under the process runtime
    proc_safe = False

    def __init__(
        self,
        *,
        txn_count=None,
        slot=None,
        funk=None,
        identity: bytes | None = None,
        addr=("127.0.0.1", 0),
    ):
        self._txn_count = txn_count or (lambda: 0)
        self._slot = slot or (lambda: 0)
        self._funk = funk
        self._identity = identity
        self._addr_req = addr
        self.server: HttpServer | None = None
        self._ctx: MuxCtx | None = None

    @property
    def addr(self):
        return self.server.addr

    def _dispatch(self, method: str, params: list):
        if method == "getTransactionCount":
            return int(self._txn_count())
        if method == "getSlot":
            return int(self._slot())
        if method == "getHealth":
            return "ok"
        if method == "getVersion":
            return {"solana-core": VERSION}
        if method == "getIdentity":
            if self._identity is None:
                raise ValueError("no identity configured")
            return {"identity": base58.encode_32(self._identity)}
        if method == "getBalance":
            if self._funk is None:
                raise ValueError("no account store attached")
            from firedancer_tpu.flamenco.accounts import AccountMgr

            key = base58.decode_32(params[0])
            if key is None:
                raise ValueError("bad pubkey")
            return {
                "context": {"slot": int(self._slot())},
                "value": AccountMgr(self._funk).lamports(key),
            }
        raise LookupError(method)

    def _handle(self, req):
        if req.method != "POST":
            return 404, b"POST json-rpc only\n", "text/plain"
        self._ctx.metrics.inc("requests")
        try:
            body = json.loads(req.body)
            method = body["method"]
            params = body.get("params", [])
            rid = body.get("id")
        except (ValueError, KeyError, TypeError):
            self._ctx.metrics.inc("bad_requests")
            return 200, json.dumps(
                {"jsonrpc": "2.0", "id": None,
                 "error": {"code": -32700, "message": "parse error"}}
            ).encode(), "application/json"
        try:
            result = self._dispatch(method, params)
            resp = {"jsonrpc": "2.0", "id": rid, "result": result}
        except LookupError:
            resp = {"jsonrpc": "2.0", "id": rid,
                    "error": {"code": -32601, "message": "method not found"}}
        except Exception as e:  # noqa: BLE001 — rpc boundary
            resp = {"jsonrpc": "2.0", "id": rid,
                    "error": {"code": -32602, "message": str(e)}}
        return 200, json.dumps(resp).encode(), "application/json"

    def on_boot(self, ctx: MuxCtx) -> None:
        self._ctx = ctx
        self.server = HttpServer(self._handle, self._addr_req)

    def on_halt(self, ctx: MuxCtx) -> None:
        if self.server is not None:
            self.server.close()


def rpc_call(addr: tuple[str, int], method: str, params=None, rid=1):
    """Tiny JSON-RPC client (the bencho observer's request shape)."""
    import socket

    from firedancer_tpu.ballet.http import build_response  # noqa: F401
    from firedancer_tpu.ballet.http import parse_response

    body = json.dumps(
        {"jsonrpc": "2.0", "id": rid, "method": method,
         "params": params or []}
    ).encode()
    req = (
        f"POST / HTTP/1.1\r\nHost: {addr[0]}\r\n"
        f"Content-Type: application/json\r\n"
        f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
    ).encode() + body
    with socket.create_connection(addr, timeout=5.0) as s:
        s.sendall(req)
        data = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
    _status, _h, resp = parse_response(data)
    return json.loads(resp)
