"""Store tile: persist shreds and reassembled blocks.

Reference model: src/app/fdctl/run/tiles/fd_store.c:149 — the reference
hands completed shred sets to the Agave blockstore over FFI
(fd_ext_blockstore_insert_shreds); this build persists NATIVELY: a
Blockstore directory holds per-slot shred logs (length-prefixed raw wire
bytes, append-only) and, once the slot's FEC sets all complete through a
fec_resolver, the reassembled entry-batch payload as the block file.

The store is also the read side for replay/repair: `shreds(slot)` and
`block(slot)` recover everything written.
"""

from __future__ import annotations

import os
import struct

import numpy as np

from firedancer_tpu.ballet import shred as SH
from firedancer_tpu.disco.fec_resolver import FecResolver
from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile


class Blockstore:
    """Directory-backed shred + block persistence.

    Layout: <dir>/slot_<n>.shreds — concatenated (u16 len | raw bytes)
    records; <dir>/slot_<n>.block — the reassembled entry-batch payload,
    written once the slot completes."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._logs: dict[int, object] = {}

    def _open(self, name: str, mode: str):
        # scratch reapers in some environments delete long-lived dirs
        # out from under the process; a blockstore must outlive them
        try:
            return open(os.path.join(self.path, name), mode)
        except FileNotFoundError:
            os.makedirs(self.path, exist_ok=True)
            return open(os.path.join(self.path, name), mode)

    def append_shred(self, slot: int, raw: bytes) -> None:
        f = self._logs.get(slot)
        if f is None:
            f = self._logs[slot] = self._open(f"slot_{slot}.shreds", "ab")
        f.write(struct.pack("<H", len(raw)) + raw)

    def write_block(self, slot: int, payload: bytes) -> None:
        with self._open(f"slot_{slot}.block", "wb") as f:
            f.write(payload)

    def shreds(self, slot: int) -> list[bytes]:
        p = os.path.join(self.path, f"slot_{slot}.shreds")
        if not os.path.exists(p):
            return []
        self.flush()
        out = []
        with open(p, "rb") as f:
            data = f.read()
        off = 0
        while off + 2 <= len(data):
            (n,) = struct.unpack_from("<H", data, off)
            off += 2
            out.append(data[off : off + n])
            off += n
        return out

    def block(self, slot: int) -> bytes | None:
        p = os.path.join(self.path, f"slot_{slot}.block")
        if not os.path.exists(p):
            return None
        with open(p, "rb") as f:
            return f.read()

    def slots(self) -> list[int]:
        out = set()
        for name in os.listdir(self.path):
            if name.startswith("slot_"):
                out.add(int(name.split("_")[1].split(".")[0]))
        return sorted(out)

    def flush(self) -> None:
        for f in self._logs.values():
            f.flush()

    def close(self) -> None:
        for f in self._logs.values():
            f.close()
        self._logs.clear()


class StoreTile(Tile):
    """ins[0] = shred ring (from the shred tile or net ingress)."""

    schema = MetricsSchema(
        counters=(
            "stored_shreds",
            "completed_sets",
            "completed_slots",
            "recovered_shreds",
            "rejected_shreds",
        ),
    )

    def __init__(self, path: str, *, verify_sig=None, name: str = "store"):
        self.name = name
        self.store = Blockstore(path)
        self._resolver = FecResolver(verify_sig=verify_sig)
        #: per-slot completed set payloads: slot -> {fec_set_idx: payload}
        self._sets: dict[int, dict[int, bytes]] = {}
        #: slots whose SLOT_COMPLETE set has landed: slot -> last set idx
        self._complete_at: dict[int, int] = {}

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        il = ctx.ins[in_idx]
        rows = il.gather(frags)
        for i in range(len(rows)):
            raw = rows[i, : frags["sz"][i]].tobytes()
            s = SH.parse(raw)
            if s is None:
                ctx.metrics.inc("rejected_shreds")
                continue
            self.store.append_shred(s.slot, raw)
            ctx.metrics.inc("stored_shreds")
            res = self._resolver.add_shred(raw)
            rej = self._resolver.rejected
            if rej:
                ctx.metrics.inc("rejected_shreds", rej)
                self._resolver.rejected = 0
            if res is None:
                continue
            ctx.metrics.inc("completed_sets")
            if res.recovered_cnt:
                ctx.metrics.inc("recovered_shreds", res.recovered_cnt)
            # record (payload, span): fec_set_idx is the set's first data
            # shred index and the span is its data shred count, so slot
            # completion is a contiguity walk over [idx, idx+span) ranges
            self._sets.setdefault(res.slot, {})[res.fec_set_idx] = (
                res.payload, len(res.data_shreds),
            )
            last = SH.parse(res.data_shreds[-1])
            if last is not None and last.flags is not None and (
                last.flags & SH.FLAG_SLOT_COMPLETE
            ):
                self._complete_at[res.slot] = res.fec_set_idx
            self._try_finish_slot(ctx, res.slot)

    def _try_finish_slot(self, ctx: MuxCtx, slot: int) -> None:
        """A slot is done when its SLOT_COMPLETE set and every set below
        it have completed: walk the contiguous set chain from index 0."""
        end = self._complete_at.get(slot)
        if end is None:
            return
        sets = self._sets.get(slot, {})
        payload = bytearray()
        cur = 0
        while cur in sets:
            chunk, span = sets[cur]
            payload += chunk
            if cur == end:
                self.store.write_block(slot, bytes(payload))
                self.store.flush()
                ctx.metrics.inc("completed_slots")
                del self._sets[slot]
                del self._complete_at[slot]
                return
            cur += span

    def on_halt(self, ctx: MuxCtx) -> None:
        self.store.flush()
        self.store.close()
