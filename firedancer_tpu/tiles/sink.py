"""Sink tile: terminal consumer that counts (and optionally records) frags.

Test/bench helper — the analog of the rx tiles the reference's multi-tile
concurrency tests spawn (src/disco/dedup/test_dedup.c:654-660)."""

from __future__ import annotations

import threading

import numpy as np

from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile


class SinkTile(Tile):
    schema = MetricsSchema(counters=("sunk_frags",), hists=("latency_us",))

    def __init__(self, *, record: bool = False, name: str = "sink"):
        self.name = name
        self.record = record
        self.sigs: list[np.ndarray] = []
        self.payloads: list[np.ndarray] = []
        self.sizes: list[np.ndarray] = []
        self.lock = threading.Lock()

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        ctx.metrics.inc("sunk_frags", len(frags))
        # end-to-end latency: origin tsorig (stamped at ingress, carried
        # through every relay) to arrival here; sign-extended wrap-safe
        # delta (ts_diff) so a 2^32 µs wrap mid-run cannot turn a small
        # latency into a ~71-minute garbage sample
        from firedancer_tpu.disco.mux import now_ts, ts_diff_arr

        lat = np.maximum(ts_diff_arr(now_ts(), frags["tsorig"]), 0)
        ctx.metrics.hist_sample_many("latency_us", lat)
        if self.record:
            rows = ctx.ins[in_idx].gather(frags)
            with self.lock:
                self.sigs.append(frags["sig"].copy())
                self.payloads.append(rows)
                self.sizes.append(frags["sz"].copy())

    def all_sigs(self) -> np.ndarray:
        with self.lock:
            return (
                np.concatenate(self.sigs)
                if self.sigs
                else np.zeros(0, dtype=np.uint64)
            )
