"""Sink tile: terminal consumer that counts (and optionally records) frags.

Test/bench helper — the analog of the rx tiles the reference's multi-tile
concurrency tests spawn (src/disco/dedup/test_dedup.c:654-660).

Two recording surfaces:
  * record=True — host-side lists (sigs/payloads/sizes), readable via
    all_sigs() from the same process.  Thread runtime only: in the
    process runtime the lists fill in the CHILD and the parent's copy
    stays empty.
  * shm_log=N — a sig log IN THE WORKSPACE (ctx.alloc region: cursor
    word + N u64 slots), written by the sink and readable from ANY
    process via Topology.tile_alloc_view(name, "siglog") +
    read_siglog().  This is what the process-runtime parity/chaos
    checks diff across runtimes.
"""

from __future__ import annotations

import threading

import numpy as np

from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile

SIGLOG_ALLOC = "siglog"

#: guards SinkTile's lazy per-instance Lock creation: two threads (the
#: mux loop in on_frags, a test in all_sigs) racing the first access
#: must end up sharing ONE lock, or mutual exclusion is silently lost
_LOCK_INIT = threading.Lock()


def siglog_footprint(cap: int) -> int:
    return 8 * (1 + cap)


def read_siglog(mem: np.ndarray) -> np.ndarray:
    """Decode a sink shm sig log region: the first min(cursor, cap)
    recorded sigs (the log is a truncating append, not a ring — parity
    checks need exact prefixes, so overflow drops the tail and the
    cursor keeps counting for the caller to notice)."""
    words = mem[: (len(mem) // 8) * 8].view(np.uint64)
    cap = len(words) - 1
    n = min(int(words[0]), cap)
    return words[1 : 1 + n].copy()


class SinkTile(Tile):
    schema = MetricsSchema(counters=("sunk_frags",), hists=("latency_us",))

    def __init__(
        self,
        *,
        record: bool = False,
        shm_log: int = 0,
        name: str = "sink",
    ):
        self.name = name
        self.record = record
        self.shm_log = int(shm_log)
        self.sigs: list[np.ndarray] = []
        self.payloads: list[np.ndarray] = []
        self.sizes: list[np.ndarray] = []
        # NOT created here: a Lock captured by the ctor would not
        # survive the process runtime's spawn pickle (the fdtlint
        # proc-safe-tile rule); created on first use instead
        self._lock: threading.Lock | None = None
        self._slog: np.ndarray | None = None

    @property
    def lock(self) -> threading.Lock:
        if self._lock is None:
            with _LOCK_INIT:
                if self._lock is None:
                    self._lock = threading.Lock()
        return self._lock

    def wksp_footprint(self) -> int:
        return siglog_footprint(self.shm_log) if self.shm_log else 0

    def on_boot(self, ctx: MuxCtx) -> None:
        if self.shm_log:
            mem = ctx.alloc(SIGLOG_ALLOC, siglog_footprint(self.shm_log))
            self._slog = mem[: (len(mem) // 8) * 8].view(np.uint64)

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        ctx.metrics.inc("sunk_frags", len(frags))
        # end-to-end latency: origin tsorig (stamped at ingress, carried
        # through every relay) to arrival here; sign-extended wrap-safe
        # delta (ts_diff) so a 2^32 µs wrap mid-run cannot turn a small
        # latency into a ~71-minute garbage sample
        from firedancer_tpu.disco.mux import now_ts, ts_diff_arr

        lat = np.maximum(ts_diff_arr(now_ts(), frags["tsorig"]), 0)
        ctx.metrics.hist_sample_many("latency_us", lat)
        if self._slog is not None:
            w = self._slog
            cap = len(w) - 1
            cur = int(w[0])
            keep = frags["sig"][: max(cap - cur, 0)]
            if len(keep):
                w[1 + cur : 1 + cur + len(keep)] = keep
            # cursor counts EVERY sig (overflow visible to readers);
            # bumped after the stores so a concurrent reader never sees
            # slots it could misread as live
            w[0] = np.uint64(cur + len(frags))
        if self.record:
            rows = ctx.ins[in_idx].gather(frags)
            with self.lock:
                self.sigs.append(frags["sig"].copy())
                self.payloads.append(rows)
                self.sizes.append(frags["sz"].copy())

    def all_sigs(self) -> np.ndarray:
        with self.lock:
            return (
                np.concatenate(self.sigs)
                if self.sigs
                else np.zeros(0, dtype=np.uint64)
            )
