"""QUIC + legacy-UDP ingress tile: the wire edge of the TPU pipeline.

Reference model: src/app/fdctl/run/tiles/fd_quic.c — a QUIC server whose
completed TPU streams are reassembled (src/disco/quic/fd_tpu.h) and
published as parsed txn + trailer frags to the verify tiles, plus the
legacy non-QUIC UDP path (fd_quic.c:148-170) where one datagram = one raw
txn.  This build listens on two UDP ports (QUIC and legacy) through the
waltz.udpsock burst interface; stream reassembly lives inside
waltz.quic.Connection and the txn parse/trailer format is shared with the
synth tile (tiles/wire.py), so downstream tiles cannot tell wire ingress
from synthetic ingress.
"""

from __future__ import annotations

import numpy as np

from firedancer_tpu.ballet import pack as P
from firedancer_tpu.ballet import txn as T
from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile
from firedancer_tpu.waltz import quic as Q
from firedancer_tpu.waltz.udpsock import UdpSock

from . import wire


class QuicIngressTile(Tile):
    """Terminates QUIC (and legacy UDP) and publishes txn+trailer frags."""

    name = "quic"
    schema = MetricsSchema(
        counters=(
            "rx_dgrams",
            "tx_dgrams",
            "rx_txns_quic",
            "rx_txns_udp",
            "parse_fail_txns",
            "conns_opened",
        ),
    )

    def __init__(
        self,
        identity_secret: bytes,
        *,
        quic_addr=("127.0.0.1", 0),
        udp_addr=("127.0.0.1", 0),
        burst: int = 256,
        via_net: bool = False,
    ):
        """via_net=True: sans-IO mode behind a NetTile — ins[0] carries
        addr-prefixed datagram frags, outs[-1] is the tx ring back to the
        net tile (reference topology: net -> quic -> net)."""
        self.identity_secret = identity_secret
        self._quic_addr_req = quic_addr
        self._udp_addr_req = udp_addr
        self.burst = burst
        self.via_net = via_net
        self.quic_sock: UdpSock | None = None
        self.udp_sock: UdpSock | None = None
        self.server: Q.QuicServer | None = None
        import collections

        # parsed txn+trailer payloads: a deque + preallocated publish
        # buffer — the old list sliced `self._backlog[credits:]` every
        # burst, an O(backlog) copy per iteration under backpressure
        self._backlog: collections.deque = collections.deque()
        self._tx_backlog: collections.deque = collections.deque()
        self._pub_rows: np.ndarray | None = None
        self._tx_rows: np.ndarray | None = None
        self._tx_szs: np.ndarray | None = None

    # bound addresses, available after on_boot (ports may be ephemeral)
    @property
    def quic_addr(self):
        return self.quic_sock.addr

    @property
    def udp_addr(self):
        return self.udp_sock.addr

    def on_boot(self, ctx: MuxCtx) -> None:
        if not self.via_net and self.quic_sock is None:
            # restart-safe: a supervised re-incarnation keeps the bound
            # sockets (senders hold the addresses) — only a first boot
            # opens them
            self.quic_sock = UdpSock(self._quic_addr_req)
            self.udp_sock = UdpSock(self._udp_addr_req)
        if self.server is None:
            self.server = Q.QuicServer(self.identity_secret)

    def on_halt(self, ctx: MuxCtx) -> None:
        if self.quic_sock:
            self.quic_sock.close()
        if self.udp_sock:
            self.udp_sock.close()

    #: preallocated egress row capacity (chunked above this)
    _TX_ROWS = 512

    def _tx(self, ctx: MuxCtx, out_pkts: list[tuple[bytes, tuple]]) -> None:
        """Send datagrams: straight out the socket via ONE sendmmsg
        burst (fdt_udp_send_burst, ISSUE 12), or queue them for the tx
        ring toward the net tile (one rx datagram can produce several
        tx datagrams, so ring publishes are credit-gated in _flush_tx)."""
        if not out_pkts:
            return
        if not self.via_net:
            ctx.metrics.inc("tx_dgrams", self._send_burst_native(out_pkts))
            return
        self._tx_backlog.extend(out_pkts)
        self._flush_tx(ctx)

    def _send_burst_native(self, pkts) -> int:
        """One batched-datagram syscall per burst instead of a Python
        sendto per packet; oversize payloads (never produced by our
        QUIC encoder) fall back to the per-packet path."""
        from firedancer_tpu.tiles.net import NET_MTU, addr_pack
        from firedancer_tpu.tango import rings as R

        if self._tx_rows is None:
            self._tx_rows = np.zeros((self._TX_ROWS, NET_MTU), np.uint8)
            self._tx_szs = np.zeros(self._TX_ROWS, np.uint32)
        if any(len(d) + 6 > NET_MTU for d, _ in pkts):
            return self.quic_sock.send_burst(pkts)
        sent = 0
        for lo in range(0, len(pkts), self._TX_ROWS):
            chunk = pkts[lo : lo + self._TX_ROWS]
            for i, (d, addr) in enumerate(chunk):
                pre = addr_pack(addr)
                self._tx_rows[i, :6] = np.frombuffer(pre, np.uint8)
                self._tx_rows[i, 6 : 6 + len(d)] = np.frombuffer(
                    d, np.uint8
                )
                self._tx_szs[i] = 6 + len(d)
            got = R._lib.fdt_udp_send_burst(
                self.quic_sock.sock.fileno(),
                self._tx_rows.ctypes.data, self._tx_rows.shape[1],
                self._tx_szs.ctypes.data, len(chunk), None,
            )
            sent += max(int(got), 0)
            if got < len(chunk):
                break  # EAGAIN: drop the tail (send_burst semantics)
        return sent

    def _flush_tx(self, ctx: MuxCtx) -> None:
        """Publish queued tx datagrams within the net ring's own credit
        headroom (independent of the txn ring's budget)."""
        if not self._tx_backlog:
            return
        from firedancer_tpu.tiles.net import NET_MTU, addr_pack

        out = ctx.outs[-1]
        n = min(len(self._tx_backlog), out.cr_avail(), self._TX_ROWS)
        if n <= 0:
            return
        if self._tx_rows is None:
            self._tx_rows = np.zeros((self._TX_ROWS, NET_MTU), np.uint8)
            self._tx_szs = np.zeros(self._TX_ROWS, np.uint32)
        rows = self._tx_rows
        szs = np.zeros(n, np.uint16)
        for i in range(n):
            d, addr = self._tx_backlog.popleft()
            payload = addr_pack(addr) + d
            rows[i, : len(payload)] = np.frombuffer(payload, np.uint8)
            szs[i] = len(payload)
        out.publish(np.arange(n, dtype=np.uint64), rows[:n], szs)
        ctx.metrics.inc("tx_dgrams", n)

    def during_housekeeping(self, ctx: MuxCtx) -> None:
        # loss-recovery probe timers: retransmit when acks stall
        out_pkts = []
        for addr, conn in list(self.server.by_addr.items()):
            conn.on_timer()
            for d in conn.datagrams_out():
                out_pkts.append((d, addr))
        self._tx(ctx, out_pkts)

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        """via_net mode: datagram frags from the net tile."""
        from firedancer_tpu.tiles.net import ADDR_SZ, CTL_LEGACY, addr_unpack

        il = ctx.ins[in_idx]
        rows = il.gather(frags)
        out_pkts = []
        udp_raws: list[bytes] = []
        quic_raws: list[bytes] = []
        n_conns = len(self.server.conns)
        for i in range(len(rows)):
            row = rows[i, : frags["sz"][i]]
            addr = addr_unpack(row[:ADDR_SZ])
            data = row[ADDR_SZ:].tobytes()
            ctx.metrics.inc("rx_dgrams")
            if frags["ctl"][i] & CTL_LEGACY:
                udp_raws.append(data)
                continue
            conn = self.server.on_datagram(data, addr)
            if conn is not None:
                for d in conn.datagrams_out():
                    out_pkts.append((d, addr))
                if conn.txns:
                    quic_raws.extend(conn.txns)
                    conn.txns.clear()
        # one native parse+trailer call per drained batch, not per txn
        self._ingest_batch(ctx, udp_raws, "rx_txns_udp")
        self._ingest_batch(ctx, quic_raws, "rx_txns_quic")
        for pkt, addr in self.server.stateless_out:
            out_pkts.append((pkt, addr))
        self.server.stateless_out.clear()
        if len(self.server.conns) > n_conns:
            ctx.metrics.inc("conns_opened", len(self.server.conns) - n_conns)
        self._tx(ctx, out_pkts)

    def _ingest_batch(
        self, ctx: MuxCtx, raws: list[bytes], counter: str
    ) -> None:
        """Parse + trailer a whole ingest batch in ONE native call
        (fdt_txn_scan's wire-trailer output) instead of a per-txn
        Python T.parse + append_trailer loop.

        Behavior is bit-identical to the per-txn path per batch: scan
        ok covers parse AND compute-budget estimate, but the old path
        only dropped parse failures — so rejects take a per-txn Python
        fallback that keeps estimate-fail txns flowing (pack drops them
        later under its own reject metric).  Rejects are rare on real
        traffic, so the fallback stays off the hot path.  (Within one
        drained datagram batch, legacy-UDP and QUIC txns now ingest as
        two class-ordered batches instead of interleaved by arrival —
        pipeline order across txns carries no semantics; dedup and pack
        are order-insensitive.)"""
        if not raws:
            return
        n = len(raws)
        rows = np.zeros((n, wire.LINK_MTU), np.uint8)
        szs = np.zeros(n, np.uint32)
        for i, raw in enumerate(raws):
            if len(raw) <= T.MTU:
                rows[i, : len(raw)] = np.frombuffer(raw, np.uint8)
                szs[i] = len(raw)
            # oversize datagrams keep sz 0: the scan rejects them and
            # the fallback's T.parse delivers the old verdict
        scan = P.txn_scan(rows, szs, with_trailer=True)
        n_ok = 0
        n_fail = 0
        for i in range(n):
            if scan.ok[i]:
                self._backlog.append(bytes(rows[i, : scan.tszs[i]]))
                n_ok += 1
                continue
            desc = T.parse(raws[i])
            if desc is None:
                n_fail += 1
            else:
                self._backlog.append(wire.append_trailer(raws[i], desc))
                n_ok += 1
        if n_ok:
            ctx.metrics.inc(counter, n_ok)
        if n_fail:
            ctx.metrics.inc("parse_fail_txns", n_fail)

    def after_credit(self, ctx: MuxCtx) -> None:
        n_conns = len(self.server.conns)
        if not self.via_net:
            # legacy UDP: one datagram = one txn (fd_quic.c legacy path);
            # the whole burst goes through ONE native parse+trailer call
            udp_raws = [
                data for data, _addr in self.udp_sock.recv_burst(self.burst)
            ]
            if udp_raws:
                ctx.metrics.inc("rx_dgrams", len(udp_raws))
                self._ingest_batch(ctx, udp_raws, "rx_txns_udp")

            # QUIC datagrams
            out_pkts = []
            touched = []
            quic_raws: list[bytes] = []
            for data, addr in self.quic_sock.recv_burst(self.burst):
                ctx.metrics.inc("rx_dgrams")
                conn = self.server.on_datagram(data, addr)
                if conn is not None:
                    touched.append((conn, addr))
            for conn, addr in touched:
                for d in conn.datagrams_out():
                    out_pkts.append((d, addr))
                if conn.txns:
                    quic_raws.extend(conn.txns)
                    conn.txns.clear()
            self._ingest_batch(ctx, quic_raws, "rx_txns_quic")
            # stateless Retry responses (server retry mode)
            for pkt, addr in self.server.stateless_out:
                out_pkts.append((pkt, addr))
            self.server.stateless_out.clear()
            self._tx(ctx, out_pkts)
        if len(self.server.conns) > n_conns:
            ctx.metrics.inc("conns_opened", len(self.server.conns) - n_conns)

        if self.via_net:
            self._flush_tx(ctx)  # drain tx held back by net-ring credits
        # publish backlog within credit budget (txn ring = outs[0] only;
        # in via_net mode outs[-1] is the net tx ring).  The backlog is
        # a deque drained into a preallocated row buffer: the old list
        # slice (`self._backlog[credits:]`) copied the WHOLE remaining
        # backlog every iteration under backpressure — O(n) per burst.
        if not self._backlog or ctx.credits <= 0:
            return
        if self._pub_rows is None:
            self._pub_rows = np.zeros(
                (self._TX_ROWS, wire.LINK_MTU), np.uint8
            )
        credits = ctx.credits
        while self._backlog and credits > 0:
            # chunked through the preallocated buffer: the WHOLE credit
            # budget drains per firing (matching the old slice path's
            # throughput), just _TX_ROWS rows at a time
            n = min(len(self._backlog), credits, self._TX_ROWS)
            rows = self._pub_rows
            szs = np.zeros(n, np.uint16)
            for i in range(n):
                payload = self._backlog.popleft()
                rows[i, : len(payload)] = np.frombuffer(payload, np.uint8)
                szs[i] = len(payload)
            tr = wire.parse_trailers(rows[:n], szs.astype(np.int64))
            sig0 = rows[
                np.arange(n)[:, None], tr["sig_off"][:, None] + np.arange(8)
            ]
            tags = sig0.astype(np.uint64) @ (
                np.uint64(1)
                << (np.uint64(8) * np.arange(8, dtype=np.uint64))
            )
            ctx.outs[0].publish(tags, rows[:n], szs)
            ctx.metrics.inc("out_frags", n)
            credits -= n
