"""QUIC + legacy-UDP ingress tile: the wire edge of the TPU pipeline.

Reference model: src/app/fdctl/run/tiles/fd_quic.c — a QUIC server whose
completed TPU streams are reassembled (src/disco/quic/fd_tpu.h) and
published as parsed txn + trailer frags to the verify tiles, plus the
legacy non-QUIC UDP path (fd_quic.c:148-170) where one datagram = one raw
txn.  This build listens on two UDP ports (QUIC and legacy) through the
waltz.udpsock burst interface; stream reassembly lives inside
waltz.quic.Connection and the txn parse/trailer format is shared with the
synth tile (tiles/wire.py), so downstream tiles cannot tell wire ingress
from synthetic ingress.

Hostile-ingress hardening (ISSUE 13): this tile is the front line
against the open internet, so every admission decision is explicit and
every rejection is a METERED DROP with a reason code — never an
exception out of the tile loop:

  * connection admission (waltz/admission.py ConnAdmission wired into
    the QuicServer): global + per-source caps, handshake-rate limiting
    with stateless-Retry backoff signaling, idle-churn and
    slow-loris (handshake-deadline) eviction;
  * a per-connection txn-rate token bucket at drain time;
  * a stake-weighted QoS gate at quic->verify: a StakeTable classes
    each source (TLS identity when the handshake completed, address
    identity otherwise) into unstaked / low-stake / high-stake; the
    txn backlog is one bounded priority queue PER CLASS, drained
    high-first, with preemption — at capacity an arriving staked txn
    evicts the oldest queued lower-class txn instead of being refused;
  * SLO-driven load shedding (LoadShedder): explicit degradation
    levels (admit-all -> shed-unstaked -> shed-lowstake ->
    emergency-staked-only) driven by live backlog occupancy AND the
    burn-rate engine's commanded level from the shared `shed` region
    (disco/slo.py recommended_shed_level, written by the flight
    recorder); transitions are metered (`shed_level` gauge,
    `shed_transitions`) and each escalation freezes an fdtflight
    incident bundle.

The txn ledger closes by construction: gate_txns (txns presented to
the QoS gate) == admit_staked + admit_unstaked + drop_txn_rate +
shed_unstaked + shed_lowstake; the adversarial harness
(scripts/adversary.py) asserts it.  `shed_backlog` meters BOTH
refused enqueues and preemption victims — a preempted txn was already
admitted and counted toward rx_txns_* when first enqueued — so it is
a drop counter, not a term of the admit identity.

All admission/shed decisions run in the tango.tempo.tickcount clock
domain — the fdtlint `hot-path-clock` rule bans bare time.* reads from
this hot path and from every Admission/Shed class.
"""

from __future__ import annotations

import collections

import numpy as np

from firedancer_tpu.ballet import pack as P
from firedancer_tpu.ballet import txn as T
from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile
from firedancer_tpu.tango import tempo
from firedancer_tpu.waltz import admission as ADM
from firedancer_tpu.waltz import quic as Q
from firedancer_tpu.waltz.admission import (
    AdmissionConfig,
    ConnAdmission,
    LoadShedder,
    StakeTable,
)
from firedancer_tpu.waltz.udpsock import UdpSock

from . import wire

#: stake classes drained high-priority-first by the publish path
_N_CLASSES = 3
#: gate-shed counter per class (CLASS_HI is never level-shed)
_SHED_COUNTER = ("shed_unstaked", "shed_lowstake", None)


class QuicIngressTile(Tile):
    """Terminates QUIC (and legacy UDP) and publishes txn+trailer frags."""

    name = "quic"
    schema = MetricsSchema(
        counters=(
            "rx_dgrams",
            "tx_dgrams",
            "rx_txns_quic",
            "rx_txns_udp",
            "parse_fail_txns",
            "conns_opened",
            # ---- hostile-ingress hardening (ISSUE 13) ----
            # txn-level QoS gate ledger: gate_txns == admit_staked +
            # admit_unstaked + drop_txn_rate + shed_unstaked +
            # shed_lowstake (checked by scripts/adversary.py)
            "gate_txns",
            "admit_staked",
            "admit_unstaked",
            "drop_txn_rate",
            "shed_unstaked",
            "shed_lowstake",
            "shed_backlog",
            # connection admission refusals (waltz/admission.py REASONS)
            "drop_conn_cap",
            "drop_source_cap",
            "drop_handshake_rate",
            "drop_emergency",
            "retry_sent",
            # eviction sweeps: idle churn / never-completed handshakes
            "conns_evicted_idle",
            "conns_evicted_handshake",
            # load-shed controller state (gauge + cumulative edges)
            "shed_level",
            "shed_transitions",
            # hostile traffic synthesized by injected flood/conn_churn
            # faults (disco/faultinj.py take_injected)
            "adv_injected",
            # egress-burst tail dropped on EAGAIN (was a silent drop)
            "tx_eagain_drops",
            # elastic admission autosizing (disco/elastic.py): caps
            # re-derived on every verify-shard-count change, with the
            # live values exported as gauges so "did admission track
            # the scale event" reads straight off a monitor snapshot
            "adm_autosize",
            "adm_max_conns",
            "adm_backlog_cap",
            "elastic_verify_shards",
        ),
    )

    def __init__(
        self,
        identity_secret: bytes,
        *,
        quic_addr=("127.0.0.1", 0),
        udp_addr=("127.0.0.1", 0),
        burst: int = 256,
        via_net: bool = False,
        admission: AdmissionConfig | None = None,
        stakes: StakeTable | None = None,
    ):
        """via_net=True: sans-IO mode behind a NetTile — ins[0] carries
        addr-prefixed datagram frags, outs[-1] is the tx ring back to the
        net tile (reference topology: net -> quic -> net).

        admission / stakes: the ingress-defense policy (plain dataclass /
        dict state, so the tile stays spawn-picklable for the process
        runtime).  None = permissive defaults, bit-compatible with the
        pre-hardening build at shed level 0."""
        self.identity_secret = identity_secret
        self._quic_addr_req = quic_addr
        self._udp_addr_req = udp_addr
        self.burst = burst
        self.via_net = via_net
        self.admission_cfg = admission or AdmissionConfig()
        self.stakes = stakes or StakeTable(
            low_stake=self.admission_cfg.low_stake
        )
        self.quic_sock: UdpSock | None = None
        self.udp_sock: UdpSock | None = None
        self.server: Q.QuicServer | None = None
        self.admission_ctl: ConnAdmission | None = None
        self.shedder: LoadShedder | None = None
        self._shed_words: np.ndarray | None = None
        #: elastic autosizing baseline (the UNSCALED config, captured
        #: once): a supervised thread-restart re-runs on_boot with
        #: admission_cfg already autosized — re-capturing it would
        #: compound the scaling factor on every restart
        self._adm_base: AdmissionConfig | None = None

        # parsed txn+trailer payloads: one bounded deque per stake
        # class, drained high-class-first by the publish path (staked
        # traffic preempts unstaked when verify credits are scarce).
        # Deques + a preallocated publish buffer — the old list sliced
        # `self._backlog[credits:]` every burst, an O(backlog) copy per
        # iteration under backpressure
        self._backlogs: list[collections.deque] = [
            collections.deque() for _ in range(_N_CLASSES)
        ]
        self._backlog_total = 0
        self._tx_backlog: collections.deque = collections.deque()
        self._pub_rows: np.ndarray | None = None
        self._tx_rows: np.ndarray | None = None
        self._tx_szs: np.ndarray | None = None
        # recently gate-admitted raw txns: the duplicate-storm pool the
        # injected `flood` fault replays copies from
        self._recent_raws: collections.deque = collections.deque(maxlen=64)
        # fired-but-unsynthesized injected-attack chunks: (fault_idx,
        # kind, profile, next_offset, end) — drained a bounded slice per
        # iteration so a huge wave can never starve the heartbeat
        self._inj_pending: collections.deque = collections.deque()
        self._churn_n = 0
        self._smallorder_tmpl: bytes | None = None

    @property
    def _backlog(self) -> collections.deque:
        """Compat view: the unstaked-class queue (everything, when no
        stake table is configured).  NOTE: direct appends bypass the
        `_backlog_total` accounting the shed controller reads — tests
        only; production paths go through `_enqueue`."""
        return self._backlogs[ADM.CLASS_UNSTAKED]

    # bound addresses, available after on_boot (ports may be ephemeral)
    @property
    def quic_addr(self):
        return self.quic_sock.addr

    @property
    def udp_addr(self):
        return self.udp_sock.addr

    def shared_wksp_footprints(self) -> dict[str, int]:
        # the SLO-engine -> shed-controller backchannel: the flight
        # recorder writes the commanded minimum level, this tile writes
        # the live level (disjoint words; waltz/admission.py layout)
        return {"shed": ADM.SHED_FOOTPRINT}

    def on_boot(self, ctx: MuxCtx) -> None:
        if not self.via_net and self.quic_sock is None:
            # restart-safe: a supervised re-incarnation keeps the bound
            # sockets (senders hold the addresses) — only a first boot
            # opens them
            self.quic_sock = UdpSock(self._quic_addr_req)
            self.udp_sock = UdpSock(self._udp_addr_req)
        if self.admission_ctl is None:
            self.admission_ctl = ConnAdmission(
                self.admission_cfg, self.stakes
            )
            self.shedder = LoadShedder(self.admission_cfg)
        if self.server is None:
            self.server = Q.QuicServer(
                self.identity_secret,
                max_conns=self.admission_cfg.max_conns,
                admission=self.admission_ctl,
            )
        if ctx is not None and self._shed_words is None:
            mem = ctx.shared("shed", ADM.SHED_FOOTPRINT)
            self._shed_words = mem[: (len(mem) // 8) * 8].view(np.uint64)
        if self._adm_base is None:
            # the unscaled admission baseline the elastic autosizer
            # scales from (calibrated for base_active verify shards)
            self._adm_base = self.admission_cfg

    def on_epoch(self, ctx: MuxCtx) -> None:
        """Elastic epoch flip (disco/elastic.py): quic is the verify
        kind's PRODUCER — the base hook appends the flip-journal entry
        that makes the new assignment take effect at the next publish
        seq, then this override AUTOSIZES the admission caps to the
        live verify shard count (ROADMAP item 3 leftover): connection
        and backlog capacity scale with what the verify stage can
        absorb, so a scale-in tightens the front door instead of
        queueing txns the pipeline can no longer serve."""
        super().on_epoch(ctx)
        eb = self.elastic
        if eb is None or self.admission_ctl is None:
            return
        n = eb.bind(ctx).n_active(eb.slot)
        base = getattr(self, "_adm_base", None) or self.admission_cfg
        cfg = base.autosized(n, eb.base_active)
        if cfg is not self.admission_cfg:
            self.admission_cfg = cfg
            self.admission_ctl.cfg = cfg
            if self.shedder is not None:
                self.shedder.cfg = cfg
            if self.server is not None:
                self.server.max_conns = cfg.max_conns
            ctx.metrics.inc("adm_autosize")
        ctx.metrics.set("adm_max_conns", self.admission_cfg.max_conns)
        ctx.metrics.set(
            "adm_backlog_cap", self.admission_cfg.backlog_cap
        )
        ctx.metrics.set("elastic_verify_shards", n)

    def on_halt(self, ctx: MuxCtx) -> None:
        if self.quic_sock:
            self.quic_sock.close()
        if self.udp_sock:
            self.udp_sock.close()

    #: preallocated egress row capacity (chunked above this)
    _TX_ROWS = 512

    def _tx(self, ctx: MuxCtx, out_pkts: list[tuple[bytes, tuple]]) -> None:
        """Send datagrams: straight out the socket via ONE sendmmsg
        burst (fdt_udp_send_burst, ISSUE 12), or queue them for the tx
        ring toward the net tile (one rx datagram can produce several
        tx datagrams, so ring publishes are credit-gated in _flush_tx)."""
        if not out_pkts:
            return
        if not self.via_net:
            sent = self._send_burst_native(out_pkts)
            ctx.metrics.inc("tx_dgrams", sent)
            if sent < len(out_pkts):
                # EAGAIN dropped the burst tail — a declared, metered
                # drop (monitor NOTE row), not a silent one (ISSUE 13
                # satellite; the tail is unrecoverable either way:
                # QUIC loss recovery retransmits what mattered)
                ctx.metrics.inc("tx_eagain_drops", len(out_pkts) - sent)
            return
        self._tx_backlog.extend(out_pkts)
        self._flush_tx(ctx)

    def _send_burst_native(self, pkts) -> int:
        """One batched-datagram syscall per burst instead of a Python
        sendto per packet; oversize payloads (never produced by our
        QUIC encoder) fall back to the per-packet path.  Returns the
        count actually sent; the caller meters any EAGAIN tail."""
        from firedancer_tpu.tiles.net import NET_MTU, addr_pack
        from firedancer_tpu.tango import rings as R

        if self._tx_rows is None:
            self._tx_rows = np.zeros((self._TX_ROWS, NET_MTU), np.uint8)
            self._tx_szs = np.zeros(self._TX_ROWS, np.uint32)
        if any(len(d) + 6 > NET_MTU for d, _ in pkts):
            return self.quic_sock.send_burst(pkts)
        sent = 0
        for lo in range(0, len(pkts), self._TX_ROWS):
            chunk = pkts[lo : lo + self._TX_ROWS]
            for i, (d, addr) in enumerate(chunk):
                pre = addr_pack(addr)
                self._tx_rows[i, :6] = np.frombuffer(pre, np.uint8)
                self._tx_rows[i, 6 : 6 + len(d)] = np.frombuffer(
                    d, np.uint8
                )
                self._tx_szs[i] = 6 + len(d)
            got = R._lib.fdt_udp_send_burst(
                self.quic_sock.sock.fileno(),
                self._tx_rows.ctypes.data, self._tx_rows.shape[1],
                self._tx_szs.ctypes.data, len(chunk), None,
            )
            sent += max(int(got), 0)
            if got < len(chunk):
                break  # EAGAIN: the caller meters the dropped tail
        return sent

    def _flush_tx(self, ctx: MuxCtx) -> None:
        """Publish queued tx datagrams within the net ring's own credit
        headroom (independent of the txn ring's budget)."""
        if not self._tx_backlog:
            return
        from firedancer_tpu.tiles.net import NET_MTU, addr_pack

        out = ctx.outs[-1]
        n = min(len(self._tx_backlog), out.cr_avail(), self._TX_ROWS)
        if n <= 0:
            return
        if self._tx_rows is None:
            self._tx_rows = np.zeros((self._TX_ROWS, NET_MTU), np.uint8)
            self._tx_szs = np.zeros(self._TX_ROWS, np.uint32)
        rows = self._tx_rows
        szs = np.zeros(n, np.uint16)
        for i in range(n):
            d, addr = self._tx_backlog.popleft()
            payload = addr_pack(addr) + d
            rows[i, : len(payload)] = np.frombuffer(payload, np.uint8)
            szs[i] = len(payload)
        out.publish(np.arange(n, dtype=np.uint64), rows[:n], szs)
        ctx.metrics.inc("tx_dgrams", n)

    def during_housekeeping(self, ctx: MuxCtx) -> None:
        now = tempo.tickcount()
        # idle-churn + slow-loris eviction sweep: connections silent
        # past idle_timeout, or still handshaking past the handshake
        # deadline (trickled garbage keeps a loris conn "active", so
        # activity alone must not grant residency)
        if self.admission_ctl is not None and self.server is not None:
            idle, loris = self.admission_ctl.sweep(self.server, now)
            for addr in idle:
                if self.server.evict(addr):
                    ctx.metrics.inc("conns_evicted_idle")
            for addr in loris:
                if self.server.evict(addr):
                    ctx.metrics.inc("conns_evicted_handshake")
        # loss-recovery probe timers: retransmit when acks stall
        out_pkts = []
        for addr, conn in list(self.server.by_addr.items()):
            conn.on_timer()
            for d in conn.datagrams_out():
                out_pkts.append((d, addr))
        self._tx(ctx, out_pkts)

    # ---- stake-weighted txn gate ----------------------------------------

    def _conn_identity(self, conn, addr) -> bytes:
        """Stake identity: the TLS peer identity once the handshake
        completed (a staked validator proves its key), else the address
        identity (legacy UDP / pre-handshake sources are at best
        address-staked)."""
        pid = getattr(conn, "peer_identity", None) if conn else None
        return bytes(pid) if pid else ADM.addr_identity(addr)

    def _gate_raws(
        self, ctx: MuxCtx, raws: list[bytes], identity: bytes,
        key: bytes, now: int, admitted: list[list[bytes]],
    ) -> None:
        """Txn-level admission for one source's drained burst: rate
        bucket -> shed-level gate -> class queue.  Every outcome is a
        counter; the ledger gate_txns == admit_* + drop_txn_rate +
        shed_{unstaked,lowstake} closes per call."""
        if not raws:
            return
        m = ctx.metrics
        m.inc("gate_txns", len(raws))
        ok = self.admission_ctl.admit_txns(key, identity, now, len(raws))
        if ok < len(raws):
            m.inc("drop_txn_rate", len(raws) - ok)
            raws = raws[:ok]
        if not raws:
            return
        cls_ = self.stakes.cls_of(identity)
        if not LoadShedder.admits(cls_, self.shedder.level):
            m.inc(_SHED_COUNTER[cls_], len(raws))
            return
        m.inc("admit_staked" if cls_ else "admit_unstaked", len(raws))
        admitted[cls_].extend(raws)
        if cls_ == ADM.CLASS_UNSTAKED:
            # duplicate-storm pool for the injected flood fault: replay
            # fodder must itself have passed the gate once
            self._recent_raws.extend(raws[:4])

    def _enqueue(self, ctx: MuxCtx, payload: bytes, cls_: int) -> bool:
        """Bounded-backlog append with stake preemption: at capacity an
        arriving higher-class txn evicts the OLDEST queued lower-class
        txn (metered shed_backlog) instead of being refused; same-or-
        higher-class incoming at capacity is the refused side."""
        qs = self._backlogs
        if self._backlog_total < self.admission_cfg.backlog_cap:
            qs[cls_].append(payload)
            self._backlog_total += 1
            return True
        for victim in range(cls_):
            if qs[victim]:
                qs[victim].popleft()
                ctx.metrics.inc("shed_backlog")
                qs[cls_].append(payload)
                return True
        ctx.metrics.inc("shed_backlog")
        return False

    def _shed_update(self, ctx: MuxCtx, now: int) -> None:
        """One load-shed controller step: live backlog occupancy, with
        the SLO engine's commanded level (shared `shed` region, written
        by the flight recorder) as a floor.  Level transitions are
        metered and mirrored to shared memory; the flight recorder
        freezes an incident bundle on every escalation edge."""
        frac = self._backlog_total / max(self.admission_cfg.backlog_cap, 1)
        commanded = 0
        if self._shed_words is not None:
            commanded = int(self._shed_words[ADM.SHED_W_COMMANDED])
        before = self.shedder.level
        level = self.shedder.update(now, frac, commanded)
        if level != before:
            ctx.metrics.set("shed_level", level)
            ctx.metrics.inc("shed_transitions")
            if self._shed_words is not None:
                self._shed_words[ADM.SHED_W_LEVEL] = np.uint64(level)
                self._shed_words[ADM.SHED_W_TRANSITIONS] = np.uint64(
                    self.shedder.transitions
                )
            self.admission_ctl.level = level

    def _drain_admit_drops(self, ctx: MuxCtx) -> None:
        """Mirror the server's refusal tally into the shared metrics."""
        drops = self.server.admit_drops
        if not drops:
            return
        for reason, n in drops.items():
            ctx.metrics.inc(reason, n)
        drops.clear()

    # ---- ingress ---------------------------------------------------------

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        """via_net mode: datagram frags from the net tile."""
        from firedancer_tpu.tiles.net import ADDR_SZ, CTL_LEGACY, addr_unpack

        now = tempo.tickcount()
        self.server.now_tick = now
        il = ctx.ins[in_idx]
        rows = il.gather(frags)
        out_pkts = []
        udp_by_src: dict = {}
        touched: list = []
        n_conns = len(self.server.conns)
        for i in range(len(rows)):
            row = rows[i, : frags["sz"][i]]
            addr = addr_unpack(row[:ADDR_SZ])
            data = row[ADDR_SZ:].tobytes()
            ctx.metrics.inc("rx_dgrams")
            if frags["ctl"][i] & CTL_LEGACY:
                udp_by_src.setdefault(addr, []).append(data)
                continue
            conn = self.server.on_datagram(data, addr)
            if conn is not None:
                for d in conn.datagrams_out():
                    out_pkts.append((d, addr))
                if conn.txns:
                    touched.append((conn, addr))
        self._ingest_sources(ctx, udp_by_src, touched, now)
        for pkt, addr in self.server.stateless_out:
            out_pkts.append((pkt, addr))
        self.server.stateless_out.clear()
        self._drain_admit_drops(ctx)
        if len(self.server.conns) > n_conns:
            ctx.metrics.inc("conns_opened", len(self.server.conns) - n_conns)
        self._tx(ctx, out_pkts)

    def _ingest_sources(
        self, ctx: MuxCtx, udp_by_src: dict, touched: list, now: int
    ) -> None:
        """Run every source's drained txns through the QoS gate, then
        parse each stake class as ONE batched scan (class-ordered within
        the burst; pipeline order across txns carries no semantics)."""
        if udp_by_src:
            admitted: list[list[bytes]] = [[] for _ in range(_N_CLASSES)]
            for addr, raws in udp_by_src.items():
                ident = ADM.addr_identity(addr)
                self._gate_raws(ctx, raws, ident, ident, now, admitted)
            for cls_ in range(_N_CLASSES - 1, -1, -1):
                self._ingest_batch(
                    ctx, admitted[cls_], "rx_txns_udp", cls_
                )
        if touched:
            admitted = [[] for _ in range(_N_CLASSES)]
            seen = set()
            for conn, addr in touched:
                if id(conn) in seen or not conn.txns:
                    continue
                seen.add(id(conn))
                raws, conn.txns = conn.txns, []
                self._gate_raws(
                    ctx, raws, self._conn_identity(conn, addr),
                    bytes(conn.scid), now, admitted,
                )
            for cls_ in range(_N_CLASSES - 1, -1, -1):
                self._ingest_batch(
                    ctx, admitted[cls_], "rx_txns_quic", cls_
                )

    def _ingest_batch(
        self, ctx: MuxCtx, raws: list[bytes], counter: str, cls_: int = 0
    ) -> None:
        """Parse + trailer a whole ingest batch in ONE native call
        (fdt_txn_scan's wire-trailer output) instead of a per-txn
        Python T.parse + append_trailer loop.

        Behavior is bit-identical to the per-txn path per batch: scan
        ok covers parse AND compute-budget estimate, but the old path
        only dropped parse failures — so rejects take a per-txn Python
        fallback that keeps estimate-fail txns flowing (pack drops them
        later under its own reject metric).  Rejects are rare on real
        traffic, so the fallback stays off the hot path."""
        if not raws:
            return
        n = len(raws)
        rows = np.zeros((n, wire.LINK_MTU), np.uint8)
        szs = np.zeros(n, np.uint32)
        for i, raw in enumerate(raws):
            if len(raw) <= T.MTU:
                rows[i, : len(raw)] = np.frombuffer(raw, np.uint8)
                szs[i] = len(raw)
            # oversize datagrams keep sz 0: the scan rejects them and
            # the fallback's T.parse delivers the old verdict
        scan = P.txn_scan(rows, szs, with_trailer=True)
        n_ok = 0
        n_fail = 0
        for i in range(n):
            if scan.ok[i]:
                if self._enqueue(ctx, bytes(rows[i, : scan.tszs[i]]), cls_):
                    n_ok += 1
                continue
            desc = T.parse(raws[i])
            if desc is None:
                n_fail += 1
            elif self._enqueue(
                ctx, wire.append_trailer(raws[i], desc), cls_
            ):
                n_ok += 1
        if n_ok:
            ctx.metrics.inc(counter, n_ok)
        if n_fail:
            ctx.metrics.inc("parse_fail_txns", n_fail)

    # ---- injected hostile traffic (disco/faultinj.py flood/conn_churn) --

    #: injected items synthesized per loop iteration — bounds the
    #: per-iteration attack work so a wave can never starve the
    #: heartbeat (the traffic spreads over consecutive bursts, which is
    #: also what a real flood looks like from a polled socket)
    _INJECT_BUDGET = 48

    def _pump_injected(self, ctx: MuxCtx, now: int) -> None:
        """Synthesize the scheduled hostile traffic IN-PROCESS (works
        identically under the thread and process runtimes, since the
        fault schedule rides the injector into the child): connection
        floods, churn storms, slow-loris handshakes, and txn spam
        (garbage, malformed, small-order, duplicate storms) — all
        derived from the injector's seed via the same splitmix hash the
        drop/corrupt faults use, so a replayed seed offers byte-
        identical attack traffic."""
        for fi, kind, count, profile in ctx.faults.take_injected():
            prof = profile or (
                "churn" if kind == "conn_churn" else "garbage"
            )
            self._inj_pending.append((fi, kind, prof, 0, max(count, 0)))
        budget = self._INJECT_BUDGET
        while self._inj_pending and budget > 0:
            fi, kind, prof, lo, end = self._inj_pending[0]
            hi = min(lo + budget, end)
            self._do_inject(ctx, fi, prof, lo, hi, now)
            budget -= hi - lo
            if hi >= end:
                self._inj_pending.popleft()
            else:
                self._inj_pending[0] = (fi, kind, prof, hi, end)

    def _do_inject(
        self, ctx: MuxCtx, fi: int, prof: str, lo: int, hi: int, now: int
    ) -> None:
        from firedancer_tpu.disco.faultinj import _hash_u64

        if hi <= lo:
            return
        seed = ctx.faults.inj.seed
        h = _hash_u64(seed, fi, np.arange(lo, hi, dtype=np.uint64))
        if prof in ("churn", "handshake", "loris"):
            self._inject_conns(ctx, h, prof, now)
        elif prof in ("malformed", "smallorder", "dup"):
            self._inject_txns(ctx, seed, fi, h, prof, now)
        else:  # garbage datagrams: parser/robustness pressure
            for i in range(hi - lo):
                n = 24 + int(h[i] % 200)
                data = (
                    _hash_u64(
                        seed, fi ^ 0x77,
                        np.arange((n + 7) // 8, dtype=np.uint64)
                        + np.uint64(lo + i),
                    ).tobytes()[:n]
                )
                self.server.on_datagram(data, self._adv_addr(h[i]))
            ctx.metrics.inc("adv_injected", hi - lo)

    @staticmethod
    def _adv_addr(h) -> tuple[str, int]:
        """Deterministic loopback-net source address from a hash word
        (127/8 is all local, so even real-socket Retry responses to a
        synthetic attacker stay on-host)."""
        v = int(h)
        return (
            f"127.{1 + (v >> 8) % 200}.{(v >> 16) % 256}.{1 + (v >> 24) % 200}",
            1024 + v % 60000,
        )

    def _inject_conns(
        self, ctx: MuxCtx, h: np.ndarray, prof: str, now: int
    ) -> None:
        """Connection-opening Initial floods.  churn: every Initial from
        a globally-fresh source (table churn; LRU + idle eviction must
        absorb it).  handshake: a 4-address pool hammers the per-source
        cap + handshake-rate bucket.  loris: fresh conns that never
        complete their handshake but keep trickling bytes — only the
        handshake-deadline eviction clears them."""
        self.server.now_tick = now
        count = len(h)
        for i in range(count):
            v = int(h[i])
            if prof == "handshake":
                addr = (f"127.250.0.{1 + v % 4}", 4000 + (v >> 8) % 2000)
            else:
                self._churn_n += 1
                addr = self._adv_addr(
                    np.uint64(v) ^ np.uint64(self._churn_n << 32)
                )
            dcid = v.to_bytes(8, "little")
            scid = (v ^ 0xA5A5A5A5).to_bytes(8, "little")
            pkt = (
                bytes([0xC0])
                + (1).to_bytes(4, "big")
                + bytes([8]) + dcid
                + bytes([8]) + scid
                + b"\x00"  # empty token
                + Q.vi_enc(40) + bytes(40)
            )
            self.server.on_datagram(pkt, addr)
            if prof == "loris":
                # keep previously-opened loris conns "active" with
                # trickled garbage so idle eviction alone cannot clear
                # them (the handshake deadline must)
                for prev in list(self.server.by_addr)[-4:]:
                    self.server.on_datagram(b"\x40" + bytes(24), prev)
        ctx.metrics.inc("adv_injected", count)

    def _inject_txns(
        self, ctx: MuxCtx, seed: int, fi: int, h: np.ndarray,
        prof: str, now: int,
    ) -> None:
        """Txn spam through the SAME gate real traffic takes, from a
        deterministic unstaked attacker identity."""
        from firedancer_tpu.disco.faultinj import _hash_u64

        count = len(h)
        raws: list[bytes] = []
        if prof == "dup":
            pool = list(self._recent_raws)
            if pool:
                raws = [pool[int(h[i]) % len(pool)] for i in range(count)]
            else:
                # nothing gate-admitted yet to replay (e.g. shedding
                # already stopped unstaked admits): degrade to
                # malformed spam so the canonical record's scheduled
                # count still equals traffic actually injected — a
                # fired flood that injected nothing would make the
                # replay artifact lie
                prof = "malformed"
        if prof == "smallorder":
            tmpl = self._smallorder_txn()
            for i in range(count):
                sig = _hash_u64(
                    seed, fi ^ 0x50, np.arange(8, dtype=np.uint64) + h[i]
                ).tobytes()
                raws.append(tmpl[:1] + sig + tmpl[65:])
        elif prof == "malformed":  # random bytes that fail T.parse
            for i in range(count):
                n = 40 + int(h[i] % 120)
                raws.append(
                    _hash_u64(
                        seed, fi ^ 0x33,
                        np.arange((n + 7) // 8, dtype=np.uint64) + h[i],
                    ).tobytes()[:n]
                )
        if not raws:
            return
        ident = ADM.addr_identity(("127.66.0.1", 6666 + fi))
        admitted: list[list[bytes]] = [[] for _ in range(_N_CLASSES)]
        self._gate_raws(ctx, raws, ident, ident, now, admitted)
        for cls_ in range(_N_CLASSES - 1, -1, -1):
            self._ingest_batch(ctx, admitted[cls_], "rx_txns_udp", cls_)
        ctx.metrics.inc("adv_injected", len(raws))

    def _smallorder_txn(self) -> bytes:
        """A parseable txn whose payer pubkey is the identity point
        (order 1, the canonical small-order encoding): structurally
        valid, cryptographically poison — verify must reject it without
        disturbing the surrounding batch."""
        if self._smallorder_tmpl is None:
            small_pk = b"\x01" + bytes(31)  # identity point, y = 1
            self._smallorder_tmpl = T.build(
                [bytes(64)],
                [small_pk, bytes(32), b"\x02" * 32],
                bytes(32),
                [(2, [0, 1], b"\x00" * 12)],
                readonly_unsigned_cnt=1,
            )
        return self._smallorder_tmpl

    # ---- publish ---------------------------------------------------------

    def after_credit(self, ctx: MuxCtx) -> None:
        now = tempo.tickcount()
        n_conns = len(self.server.conns)
        self.server.now_tick = now
        self._shed_update(ctx, now)
        if ctx.faults is not None:
            self._pump_injected(ctx, now)
        if not self.via_net:
            # legacy UDP: one datagram = one txn (fd_quic.c legacy path);
            # gated per source, then ONE native parse+trailer call per
            # stake class
            udp_by_src: dict = {}
            for data, addr in self.udp_sock.recv_burst(self.burst):
                ctx.metrics.inc("rx_dgrams")
                udp_by_src.setdefault(addr, []).append(data)

            # QUIC datagrams
            out_pkts = []
            touched = []
            for data, addr in self.quic_sock.recv_burst(self.burst):
                ctx.metrics.inc("rx_dgrams")
                conn = self.server.on_datagram(data, addr)
                if conn is not None:
                    touched.append((conn, addr))
            for conn, addr in touched:
                for d in conn.datagrams_out():
                    out_pkts.append((d, addr))
            self._ingest_sources(ctx, udp_by_src, touched, now)
            # stateless Retry responses (server retry mode + the
            # handshake-rate backoff signal)
            for pkt, addr in self.server.stateless_out:
                out_pkts.append((pkt, addr))
            self.server.stateless_out.clear()
            self._tx(ctx, out_pkts)
        self._drain_admit_drops(ctx)
        if len(self.server.conns) > n_conns:
            ctx.metrics.inc("conns_opened", len(self.server.conns) - n_conns)

        if self.via_net:
            self._flush_tx(ctx)  # drain tx held back by net-ring credits
        # publish backlog within credit budget (txn ring = outs[0] only;
        # in via_net mode outs[-1] is the net tx ring).  The backlogs
        # are per-stake-class deques drained HIGH CLASS FIRST into a
        # preallocated row buffer — staked traffic preempts unstaked
        # when verify credits are scarce, and the old list slice
        # (`self._backlog[credits:]`) that copied the WHOLE remaining
        # backlog every iteration under backpressure is gone.
        if ctx.credits <= 0 or not any(self._backlogs):
            return
        if self._pub_rows is None:
            self._pub_rows = np.zeros(
                (self._TX_ROWS, wire.LINK_MTU), np.uint8
            )
        credits = ctx.credits
        for cls_ in range(_N_CLASSES - 1, -1, -1):
            q = self._backlogs[cls_]
            while q and credits > 0:
                # chunked through the preallocated buffer: the WHOLE
                # credit budget drains per firing, _TX_ROWS rows at a
                # time
                n = min(len(q), credits, self._TX_ROWS)
                rows = self._pub_rows
                szs = np.zeros(n, np.uint16)
                for i in range(n):
                    payload = q.popleft()
                    rows[i, : len(payload)] = np.frombuffer(
                        payload, np.uint8
                    )
                    szs[i] = len(payload)
                self._backlog_total = max(self._backlog_total - n, 0)
                tr = wire.parse_trailers(rows[:n], szs.astype(np.int64))
                sig0 = rows[
                    np.arange(n)[:, None],
                    tr["sig_off"][:, None] + np.arange(8),
                ]
                tags = sig0.astype(np.uint64) @ (
                    np.uint64(1)
                    << (np.uint64(8) * np.arange(8, dtype=np.uint64))
                )
                ctx.outs[0].publish(tags, rows[:n], szs)
                ctx.metrics.inc("out_frags", n)
                credits -= n
