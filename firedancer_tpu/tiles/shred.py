"""Shred tile: PoH entries → entry batches → FEC sets → signed shreds.

Reference model: src/app/fdctl/run/tiles/fd_shred.c (the 847-LoC tile
whose header essay describes its flow control) — while leader, it turns
the PoH tile's entry stream into entry batches, shreds each batch
(disco/shredder), has the keyguard sign every FEC set's merkle root, and
emits the signed shreds toward the network (turbine) and the store tile.

Differences from the reference, by design:
  * signing is ASYNCHRONOUS over the keyguard rings: a FEC set parks in
    a pending map keyed by a request tag while its root is at the sign
    tile; the shred tile keeps draining entries meanwhile (the reference
    spins in fd_keyguard_client_sign).
  * turbine destinations are computed per shred (disco/shred_dest
    stake-weighted shuffle) and the chosen root is recorded in metrics;
    the UDP egress rides the net tile when one is attached.

Ring layout: ins[0] = poh entries; ins[1] (optional) = sign responses.
outs[0] = shreds (one frag per shred, payload = raw wire bytes,
sig = slot<<32 | code_bit<<31 | shred idx); outs[1] (optional) = sign
requests (32-byte merkle roots, sig = request tag).

ISSUE 12 (native block egress): the per-frag paths — entry append,
sign-response signature patch — and the credit-gated `_outq`/`_signq`
drains run as native stem handlers (tango/native/fdt_shred.c).  The
batch buffer, both queues and the FEC pending store are DENSE SHARED
ARRAYS (the tile's workspace arena in the process runtime) that this
file's Python loop pushes/pops identically, so the two loop modes are
interchangeable mid-run, a killed child's queues survive into the
restarted incarnation, and the supervisor's entry replay is collapsed
back to exactly-once by a consumed high-water mark + append journal.
The actual Reed-Solomon/merkle shredding stays a Python slow path at
slot boundaries (the PR 9 handback contract — once per slot, not per
frag).  Capacity overflows spill to Python-side state, which gates the
stem off until drained (the dedup-amnesty pattern)."""

from __future__ import annotations

import collections

import numpy as np

from firedancer_tpu.ballet import shred as SH
from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile, drain_straggler_ins
from firedancer_tpu.disco.shredder import EntryBatchMeta, Shredder
from firedancer_tpu.tango import rings as R
from firedancer_tpu.tiles.poh import SLOT_BOUNDARY_TAG

#: shared words (i64) — layout pinned to tango/native/fdt_shred.h
_W_BATCH_LEN, _W_SLOT, _W_OQ_HEAD, _W_OQ_TAIL = 0, 1, 2, 3
_W_SQ_HEAD, _W_SQ_TAIL, _W_HW_ENT = 4, 5, 6
_W_J_PHASE, _W_J_SEQ, _W_J_LEN = 7, 8, 9
#: next sign-request tag (never read by C; crash-surviving so a
#: restarted incarnation can never reuse a tag that still names a live
#: pre-crash set in the surviving pending store)
_W_NEXT_TAG = 10
_W_MAGIC = 15  # host-side init flag (never read by C)
_W_CNT = 16


def _null_signer(root) -> bytes:
    """Placeholder Shredder signer (real signatures arrive via the
    keyguard rings); module-level so the tile stays spawn-picklable."""
    return b"\0" * 64


def shred_tag(slot: int, idx: int, is_code: bool) -> int:
    """Frag sig for a shred: slot<<32 | code_bit<<31 | idx."""
    return (slot << 32) | (int(is_code) << 31) | idx


class ShredTile(Tile):
    #: shred <-> keyguard form a request/response ring cycle; the loop's
    #: global credit gate would deadlock when the sign-request ring
    #: fills (we must keep draining sign RESPONSES to unblock the
    #: keyguard), so every publish is gated per-ring here instead
    manual_credits = True

    schema = MetricsSchema(
        counters=(
            "batches",
            "fec_sets",
            "data_shreds",
            "parity_shreds",
            "sign_requests",
            "sign_responses",
            "turbine_dests",
            # supervisor replay of entries a previous incarnation
            # already appended (skipped below the consumed high-water
            # mark — the exactly-once discipline, not an anomaly)
            "replayed_entries",
        ),
    )

    #: shared-structure capacities (native dense arrays; overflows past
    #: them spill to Python state and gate the stem off)
    ROW_W = SH.MAX_SZ
    OQ_CAP = 1 << 14
    SQ_CAP = 1 << 10
    PD_CAP = 64  #: FEC sets awaiting signature in the native store
    PD_MAX = 64  #: max shreds per stored set (32 data + 32 parity)
    BATCH_CAP = 1 << 20

    def __init__(
        self,
        *,
        shred_version: int = 1,
        signer=None,
        shred_dest=None,
        identity: bytes | None = None,
        name: str = "shred",
    ):
        """signer(root)->sig for local signing; None uses the keyguard
        rings (ins[1]/outs[1] must exist).  shred_dest: a
        disco.shred_dest.ShredDest for turbine fanout queries; identity:
        our pubkey (the turbine tree is leader-rooted, and while leader we
        transmit to the shuffle root)."""
        self.name = name
        self.shred_version = shred_version
        self.signer = signer
        self.shred_dest = shred_dest
        self.identity = identity
        # _null_signer (module-level, picklable) instead of a ctor
        # lambda: the tile object must survive the process runtime's
        # spawn pickle (fdtlint proc-safe-tile)
        self._shredder = Shredder(shred_version, signer=_null_signer)
        #: FEC sets the NATIVE store could not hold (store full or an
        #: oversized set): tag -> (slot, FecSet), Python-released
        self._pending: dict[int, tuple[int, object]] = {}
        #: Python-side spill for the shared rings (normally empty; the
        #: stem stays off while any spill is pending)
        self._oq_overflow: collections.deque = collections.deque()
        self._sq_overflow: collections.deque = collections.deque()
        self._batch_overflow = bytearray()
        #: shared-structure views, bound in on_boot (ctx.alloc: the
        #: workspace arena in the process runtime, local memory in
        #: standalone tests) — NOT allocated here so the spawn pickle
        #: stays small
        self._w = None

    # ---- shared-structure layout -----------------------------------------

    def _seg_sizes(self) -> list[tuple[str, int]]:
        return [
            ("words", _W_CNT * 8),
            ("batch", self.BATCH_CAP),
            ("oq_tag", self.OQ_CAP * 8),
            ("oq_sz", self.OQ_CAP * 8),
            ("oq_rows", self.OQ_CAP * self.ROW_W),
            ("sq_tag", self.SQ_CAP * 8),
            ("sq_root", self.SQ_CAP * 32),
            ("sq_sz", self.SQ_CAP * 8),
            ("pd_tag", self.PD_CAP * 8),
            ("pd_cnt", self.PD_CAP * 8),
            ("pd_tags", self.PD_CAP * self.PD_MAX * 8),
            ("pd_szs", self.PD_CAP * self.PD_MAX * 8),
            ("pd_rows", self.PD_CAP * self.PD_MAX * self.ROW_W),
        ]

    def wksp_footprint(self) -> int:
        return sum(sz for _, sz in self._seg_sizes()) + 4096

    def _alloc_views(self, mem: np.ndarray | None) -> None:
        segs = self._seg_sizes()
        total = sum(sz for _, sz in segs)
        if mem is None:
            mem = np.zeros(total, np.uint8)
        off = 0
        v = {}
        for name, sz in segs:
            v[name] = mem[off : off + sz]
            off += sz
        self._w = v["words"].view(np.int64)
        self._batch_buf = v["batch"]
        self._oq_tag = v["oq_tag"].view(np.uint64)
        self._oq_sz = v["oq_sz"].view(np.uint64)
        self._oq_rows = v["oq_rows"].reshape(self.OQ_CAP, self.ROW_W)
        self._sq_tag = v["sq_tag"].view(np.uint64)
        self._sq_root = v["sq_root"].reshape(self.SQ_CAP, 32)
        self._sq_sz = v["sq_sz"].view(np.uint64)
        self._pd_tag = v["pd_tag"].view(np.uint64)
        self._pd_cnt = v["pd_cnt"].view(np.int64)
        self._pd_tags = v["pd_tags"].view(np.uint64).reshape(
            self.PD_CAP, self.PD_MAX
        )
        self._pd_szs = v["pd_szs"].view(np.uint64).reshape(
            self.PD_CAP, self.PD_MAX
        )
        self._pd_rows = v["pd_rows"].reshape(
            self.PD_CAP, self.PD_MAX, self.ROW_W
        )

    def on_boot(self, ctx: MuxCtx) -> None:
        segs = self._seg_sizes()
        mem = ctx.alloc("shred_egress", sum(sz for _, sz in segs))
        self._alloc_views(mem)
        if int(self._w[_W_MAGIC]) == 0:
            self._w[_W_SLOT] = -1
            self._w[_W_NEXT_TAG] = 1
            self._w[_W_MAGIC] = 1
        self._recover(ctx)

    def _recover(self, ctx: MuxCtx) -> None:
        """Resolve an append a dead incarnation left mid-window: the
        journaled pre-append length tells whether the byte copy landed
        before the high-water store did."""
        w = self._w
        if int(w[_W_J_PHASE]):
            if int(w[_W_BATCH_LEN]) > int(w[_W_J_LEN]):
                hw = R.seq_u64(int(w[_W_J_SEQ]) + 1)
                if R.seq_diff(hw, int(w[_W_HW_ENT])) > 0:
                    w[_W_HW_ENT] = hw
            w[_W_J_PHASE] = 0

    # ---- slot / queue views ----------------------------------------------

    @property
    def _slot(self) -> int | None:
        s = int(self._w[_W_SLOT])
        return None if s < 0 else s

    @_slot.setter
    def _slot(self, v: int | None) -> None:
        self._w[_W_SLOT] = -1 if v is None else v

    @property
    def outq_len(self) -> int:
        return (
            int(self._w[_W_OQ_TAIL]) - int(self._w[_W_OQ_HEAD])
            + len(self._oq_overflow)
        )

    @property
    def signq_len(self) -> int:
        return (
            int(self._w[_W_SQ_TAIL]) - int(self._w[_W_SQ_HEAD])
            + len(self._sq_overflow)
        )

    @property
    def pending_cnt(self) -> int:
        """FEC sets awaiting their root signature (native store +
        Python-held)."""
        return int((self._pd_cnt > 0).sum()) + len(self._pending)

    def _batch_len(self) -> int:
        return int(self._w[_W_BATCH_LEN]) + len(self._batch_overflow)

    def _oq_put(self, tag: int, raw: bytes) -> None:
        """Store one entry at the out-ring tail (caller checked room)."""
        slot = int(self._w[_W_OQ_TAIL]) & (self.OQ_CAP - 1)
        self._oq_rows[slot, : len(raw)] = np.frombuffer(raw, np.uint8)
        self._oq_tag[slot] = tag
        self._oq_sz[slot] = len(raw)
        self._w[_W_OQ_TAIL] += 1

    def _sq_put(self, tag: int, root: bytes) -> None:
        slot = int(self._w[_W_SQ_TAIL]) & (self.SQ_CAP - 1)
        self._sq_root[slot, : len(root)] = np.frombuffer(root, np.uint8)
        self._sq_tag[slot] = tag
        self._sq_sz[slot] = len(root)
        self._w[_W_SQ_TAIL] += 1

    def _outq_push(self, tag: int, raw: bytes) -> None:
        w = self._w
        used = int(w[_W_OQ_TAIL]) - int(w[_W_OQ_HEAD])
        if self._oq_overflow or used >= self.OQ_CAP:
            self._oq_overflow.append((tag, raw))
            return
        self._oq_put(tag, raw)

    def _signq_push(self, tag: int, root: bytes) -> None:
        w = self._w
        used = int(w[_W_SQ_TAIL]) - int(w[_W_SQ_HEAD])
        if self._sq_overflow or used >= self.SQ_CAP:
            self._sq_overflow.append((tag, root))
            return
        self._sq_put(tag, root)

    def _refill_rings(self) -> None:
        """Move Python spill back into the shared rings as space frees
        (FIFO preserved: spill only drains from the front)."""
        w = self._w
        while self._oq_overflow and (
            int(w[_W_OQ_TAIL]) - int(w[_W_OQ_HEAD]) < self.OQ_CAP
        ):
            self._oq_put(*self._oq_overflow.popleft())
        while self._sq_overflow and (
            int(w[_W_SQ_TAIL]) - int(w[_W_SQ_HEAD]) < self.SQ_CAP
        ):
            self._sq_put(*self._sq_overflow.popleft())

    # ---- native stem (ISSUE 12) ------------------------------------------

    def native_handler(self, ctx: MuxCtx):
        """Native fast path: fdt_shred_entries (batch append, slot
        boundaries handed back), fdt_shred_sign (signature patch over
        the pending store into the out queue), and fdt_shred_drain (the
        after-credit hook: per-ring credit-gated `_signq`/`_outq`
        publish — the manual-credit discipline).  Python spill state
        (ring overflow, Python-held pending sets in `_pending` are fine
        — an unknown tag hands back) gates the stem off until drained.
        Turbine fan-out metrics are per-shred Python work, so a
        shred_dest keeps the Python loop."""
        if (
            self.shred_dest is not None
            or not ctx.ins
            or any(il.dcache is None for il in ctx.ins)
            or not ctx.outs
            or ctx.outs[0].dcache is None
            or (self.signer is None and len(ctx.outs) < 2)
        ):
            return None
        args = np.zeros(19, np.uint64)
        args[0] = self._w.ctypes.data
        args[1] = self._batch_buf.ctypes.data
        args[2] = self.BATCH_CAP
        args[3] = self._oq_tag.ctypes.data
        args[4] = self._oq_sz.ctypes.data
        args[5] = self._oq_rows.ctypes.data
        args[6] = self.OQ_CAP
        args[7] = self._sq_tag.ctypes.data
        args[8] = self._sq_root.ctypes.data
        args[9] = self.SQ_CAP
        args[10] = self._pd_tag.ctypes.data
        args[11] = self._pd_cnt.ctypes.data
        args[12] = self._pd_tags.ctypes.data
        args[13] = self._pd_szs.ctypes.data
        args[14] = self._pd_rows.ctypes.data
        args[15] = self.PD_CAP
        args[16] = self.PD_MAX
        args[17] = self.ROW_W
        args[18] = self._sq_sz.ctypes.data
        return R.StemSpec(
            R.STEM_H_SHRED, args,
            counters=("sign_requests", "sign_responses",
                      "replayed_entries"),
            keepalive=(args,),
            ready=lambda: (
                not self._oq_overflow
                and not self._sq_overflow
                and not self._batch_overflow
            ),
            ac_handler=R.STEM_AC_SHRED,
            ac_args=args,
            manual=True,
        )

    # ---- ingress ---------------------------------------------------------

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        if in_idx == 1:
            self._on_sign_responses(ctx, frags)
            return
        il = ctx.ins[in_idx]
        rows = il.gather(frags)
        w = self._w
        for i in range(len(rows)):
            seq = int(frags["seq"][i])
            hw = int(w[_W_HW_ENT])
            if hw and R.seq_diff(R.seq_u64(seq + 1), hw) <= 0:
                ctx.metrics.inc("replayed_entries")
                continue
            tag = int(frags["sig"][i])
            if tag & SLOT_BOUNDARY_TAG:
                new_slot = tag & 0xFFFFFFFF
                self._finish_slot(ctx, block_complete=True)
                self._slot = new_slot
                w[_W_HW_ENT] = R.seq_u64(seq + 1)
                continue
            if self._slot is None:
                self._slot = 0
            payload = rows[i, : frags["sz"][i]].tobytes()
            length = int(w[_W_BATCH_LEN])
            if length + len(payload) <= self.BATCH_CAP and (
                not self._batch_overflow
            ):
                # append journal: the crash window between the byte
                # copy and the hw store (fdt_shred.h discipline)
                w[_W_J_SEQ] = seq
                w[_W_J_LEN] = length
                w[_W_J_PHASE] = 1
                self._batch_buf[length : length + len(payload)] = (
                    np.frombuffer(payload, np.uint8)
                )
                w[_W_BATCH_LEN] = length + len(payload)
                w[_W_HW_ENT] = R.seq_u64(seq + 1)
                w[_W_J_PHASE] = 0
            else:
                # shared-buffer overflow: Python spill (gates the stem
                # off; drains at the next slot boundary)
                self._batch_overflow += payload
                w[_W_HW_ENT] = R.seq_u64(seq + 1)

    def _finish_slot(self, ctx: MuxCtx, *, block_complete: bool) -> None:
        if self._slot is None or self._batch_len() == 0:
            return
        batch = (
            bytes(self._batch_buf[: int(self._w[_W_BATCH_LEN])])
            + bytes(self._batch_overflow)
        )
        self._shredder.start_slot(self._slot)
        meta = EntryBatchMeta(block_complete=block_complete)
        sets = self._shredder.shred_batch(batch, meta)
        # clear the (crash-surviving) batch length only AFTER the long
        # shredder call: a SIGKILL mid-shred leaves the length word and
        # the boundary frag's high-water mark intact, so the supervisor
        # replay re-runs this slot identically instead of dropping the
        # whole batch.  (The remaining window — a kill between this
        # store and the last queue push below — is the microseconds of
        # parking, not the milliseconds of Reed-Solomon/merkle work.)
        self._w[_W_BATCH_LEN] = 0
        self._batch_overflow = bytearray()
        ctx.metrics.inc("batches")
        for fec in sets:
            ctx.metrics.inc("fec_sets")
            ctx.metrics.inc("data_shreds", len(fec.data_shreds))
            ctx.metrics.inc("parity_shreds", len(fec.parity_shreds))
            if self.signer is not None:
                self._release(ctx, self._slot, fec,
                              self.signer(fec.merkle_root))
                continue
            tag = int(self._w[_W_NEXT_TAG])
            self._w[_W_NEXT_TAG] = tag + 1
            if not self._pd_store(tag, self._slot, fec):
                # native store full or oversized set: Python-held (the
                # sign response for it hands the stem back)
                self._pending[tag] = (self._slot, fec)
            self._signq_push(tag, fec.merkle_root)

    def _pd_store(self, tag: int, slot: int, fec) -> bool:
        """Park one FEC set in the native pending store (unsigned
        shreds + precomputed publish sigs); False = does not fit."""
        raws = fec.data_shreds + fec.parity_shreds
        if len(raws) > self.PD_MAX:
            return False
        free = np.flatnonzero(self._pd_cnt == 0)
        if not len(free):
            return False
        p = int(free[0])
        for s, raw in enumerate(raws):
            sh = SH.parse(raw)
            assert sh is not None
            self._pd_rows[p, s, : len(raw)] = np.frombuffer(raw, np.uint8)
            self._pd_tags[p, s] = shred_tag(slot, sh.idx, not sh.is_data)
            self._pd_szs[p, s] = len(raw)
        self._pd_tag[p] = tag
        self._pd_cnt[p] = len(raws)
        return True

    # ---- keyguard responses ----------------------------------------------

    def _pd_release(self, ctx: MuxCtx, tag: int, sig: bytes) -> bool:
        """Release a native-store set through the shared out queue (the
        Python twin of fdt_shred_sign's patch loop)."""
        hit = np.flatnonzero((self._pd_tag == tag) & (self._pd_cnt > 0))
        if not len(hit):
            return False
        p = int(hit[0])
        cnt = int(self._pd_cnt[p])
        for s in range(cnt):
            sz = int(self._pd_szs[p, s])
            raw = sig + self._pd_rows[p, s, 64:sz].tobytes()
            self._outq_push(int(self._pd_tags[p, s]), raw)
        self._pd_cnt[p] = 0
        ctx.metrics.inc("sign_responses")
        return True

    def _on_sign_responses(self, ctx: MuxCtx, frags: np.ndarray) -> None:
        il = ctx.ins[1]
        rows = il.gather(frags)
        for i in range(len(rows)):
            tag = int(frags["sig"][i])
            sig = rows[i, :64].tobytes()
            if self._pd_release(ctx, tag, sig):
                continue
            entry = self._pending.pop(tag, None)
            if entry is None:
                continue
            slot, fec = entry
            ctx.metrics.inc("sign_responses")
            self._release(ctx, slot, fec, sig)

    def _release(self, ctx: MuxCtx, slot: int, fec, sig: bytes) -> None:
        """Patch the signature into every shred of the set and queue the
        shreds for publication (the proof region never covers the
        signature, so late patching is sound)."""
        fec.signature = sig
        for raw in fec.data_shreds + fec.parity_shreds:
            patched = sig + raw[64:]
            s = SH.parse(patched)
            assert s is not None
            self._outq_push(shred_tag(slot, s.idx, not s.is_data), patched)
            if self.shred_dest is not None and self.identity is not None:
                order = self.shred_dest.shuffle(
                    slot, s.idx, 0 if s.is_data else 1, self.identity
                )
                if order:
                    ctx.metrics.inc("turbine_dests")

    # ---- egress ----------------------------------------------------------

    def _drain_signq(self, ctx: MuxCtx) -> None:
        self._refill_rings()
        w = self._w
        pending = int(w[_W_SQ_TAIL]) - int(w[_W_SQ_HEAD])
        if not pending:
            return
        if len(ctx.outs) < 2:
            raise RuntimeError(
                "shred tile: keyguard signing requires outs[1] (sign ring)"
            )
        n = min(pending, ctx.outs[1].cr_avail())
        if n <= 0:
            return
        idxs = (
            np.arange(int(w[_W_SQ_HEAD]), int(w[_W_SQ_HEAD]) + n)
            & (self.SQ_CAP - 1)
        )
        # fancy indexing already materializes fresh contiguous copies
        tags = self._sq_tag[idxs]
        rows = self._sq_root[idxs]
        szs = self._sq_sz[idxs].astype(np.uint16)
        w[_W_SQ_HEAD] += n
        ctx.outs[1].publish(tags, rows, szs)
        ctx.metrics.inc("sign_requests", n)

    def in_budget(self, ctx: MuxCtx) -> int | None:
        """Bound the internal queues (manual-credit contract): stop
        absorbing entries while the signed-shred backlog is deep."""
        return 0 if self.outq_len > 8192 else None

    def after_credit(self, ctx: MuxCtx) -> None:
        self._drain_signq(ctx)
        w = self._w
        while True:
            self._refill_rings()
            pending = int(w[_W_OQ_TAIL]) - int(w[_W_OQ_HEAD])
            if not pending:
                break
            budget = ctx.outs[0].cr_avail()
            if budget <= 0:
                break
            n = min(pending, budget)
            idxs = (
                np.arange(int(w[_W_OQ_HEAD]), int(w[_W_OQ_HEAD]) + n)
                & (self.OQ_CAP - 1)
            )
            # fancy indexing already materializes fresh copies
            tags = self._oq_tag[idxs]
            szs = self._oq_sz[idxs].astype(np.uint16)
            rows = self._oq_rows[idxs]
            w[_W_OQ_HEAD] += n
            ctx.outs[0].publish(tags, rows, szs)

    def on_halt(self, ctx: MuxCtx) -> None:
        # flush the final partial slot so short-lived test topologies
        # don't lose the tail batch, then drain straggler sign responses
        # and queued shreds while downstream credits free up
        self._finish_slot(ctx, block_complete=False)
        import time as _t

        deadline = _t.monotonic() + 10.0
        while (
            self.outq_len or self.pending_cnt or self.signq_len
        ) and _t.monotonic() < deadline:
            if len(ctx.ins) > 1 and self.pending_cnt:
                drain_straggler_ins(self, ctx, only=(1,), budget=256)
            ctx.credits = ctx.outs[0].cr_avail()
            self.after_credit(ctx)
            _t.sleep(100e-6)
