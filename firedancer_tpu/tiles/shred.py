"""Shred tile: PoH entries → entry batches → FEC sets → signed shreds.

Reference model: src/app/fdctl/run/tiles/fd_shred.c (the 847-LoC tile
whose header essay describes its flow control) — while leader, it turns
the PoH tile's entry stream into entry batches, shreds each batch
(disco/shredder), has the keyguard sign every FEC set's merkle root, and
emits the signed shreds toward the network (turbine) and the store tile.

Differences from the reference, by design:
  * signing is ASYNCHRONOUS over the keyguard rings: a FEC set parks in
    a pending map keyed by a request tag while its root is at the sign
    tile; the shred tile keeps draining entries meanwhile (the reference
    spins in fd_keyguard_client_sign).
  * turbine destinations are computed per shred (disco/shred_dest
    stake-weighted shuffle) and the chosen root is recorded in metrics;
    the UDP egress rides the net tile when one is attached.

Ring layout: ins[0] = poh entries; ins[1] (optional) = sign responses.
outs[0] = shreds (one frag per shred, payload = raw wire bytes,
sig = slot<<32 | code_bit<<31 | shred idx); outs[1] (optional) = sign
requests (32-byte merkle roots, sig = request tag).
"""

from __future__ import annotations

import collections

import numpy as np

from firedancer_tpu.ballet import shred as SH
from firedancer_tpu.disco.metrics import MetricsSchema
from firedancer_tpu.disco.mux import MuxCtx, Tile
from firedancer_tpu.disco.shredder import EntryBatchMeta, Shredder
from firedancer_tpu.tiles.poh import SLOT_BOUNDARY_TAG


def _null_signer(root) -> bytes:
    """Placeholder Shredder signer (real signatures arrive via the
    keyguard rings); module-level so the tile stays spawn-picklable."""
    return b"\0" * 64


def shred_tag(slot: int, idx: int, is_code: bool) -> int:
    """Frag sig for a shred: slot<<32 | code_bit<<31 | idx."""
    return (slot << 32) | (int(is_code) << 31) | idx


class ShredTile(Tile):
    #: shred <-> keyguard form a request/response ring cycle; the loop's
    #: global credit gate would deadlock when the sign-request ring
    #: fills (we must keep draining sign RESPONSES to unblock the
    #: keyguard), so every publish is gated per-ring here instead
    manual_credits = True

    schema = MetricsSchema(
        counters=(
            "batches",
            "fec_sets",
            "data_shreds",
            "parity_shreds",
            "sign_requests",
            "sign_responses",
            "turbine_dests",
        ),
    )

    def __init__(
        self,
        *,
        shred_version: int = 1,
        signer=None,
        shred_dest=None,
        identity: bytes | None = None,
        name: str = "shred",
    ):
        """signer(root)->sig for local signing; None uses the keyguard
        rings (ins[1]/outs[1] must exist).  shred_dest: a
        disco.shred_dest.ShredDest for turbine fanout queries; identity:
        our pubkey (the turbine tree is leader-rooted, and while leader we
        transmit to the shuffle root)."""
        self.name = name
        self.shred_version = shred_version
        self.signer = signer
        self.shred_dest = shred_dest
        self.identity = identity
        # _null_signer (module-level, picklable) instead of a ctor
        # lambda: the tile object must survive the process runtime's
        # spawn pickle (fdtlint proc-safe-tile)
        self._shredder = Shredder(shred_version, signer=_null_signer)
        self._slot: int | None = None
        self._batch = bytearray()
        #: FEC sets waiting for their root signature: tag -> (slot, FecSet)
        self._pending: dict[int, tuple[int, object]] = {}
        self._next_tag = 1
        #: signed shreds waiting for downstream credits
        self._outq: collections.deque = collections.deque()
        #: sign requests waiting for keyguard-ring credits (a slot boundary
        #: can shred into more FEC sets than one frag's worth of credits)
        self._signq: collections.deque = collections.deque()

    # ---- ingress ---------------------------------------------------------

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        if in_idx == 1:
            self._on_sign_responses(ctx, frags)
            return
        il = ctx.ins[in_idx]
        rows = il.gather(frags)
        for i in range(len(rows)):
            tag = int(frags["sig"][i])
            if tag & SLOT_BOUNDARY_TAG:
                new_slot = tag & 0xFFFFFFFF
                self._finish_slot(ctx, block_complete=True)
                self._slot = new_slot
                continue
            if self._slot is None:
                self._slot = 0
            self._batch += rows[i, : frags["sz"][i]].tobytes()

    def _finish_slot(self, ctx: MuxCtx, *, block_complete: bool) -> None:
        if self._slot is None or not self._batch:
            return
        self._shredder.start_slot(self._slot)
        meta = EntryBatchMeta(block_complete=block_complete)
        sets = self._shredder.shred_batch(bytes(self._batch), meta)
        self._batch.clear()
        ctx.metrics.inc("batches")
        for fec in sets:
            ctx.metrics.inc("fec_sets")
            ctx.metrics.inc("data_shreds", len(fec.data_shreds))
            ctx.metrics.inc("parity_shreds", len(fec.parity_shreds))
            if self.signer is not None:
                self._release(ctx, self._slot, fec,
                              self.signer(fec.merkle_root))
            else:
                tag = self._next_tag
                self._next_tag += 1
                self._pending[tag] = (self._slot, fec)
                self._signq.append((tag, fec.merkle_root))

    # ---- keyguard responses ----------------------------------------------

    def _on_sign_responses(self, ctx: MuxCtx, frags: np.ndarray) -> None:
        il = ctx.ins[1]
        rows = il.gather(frags)
        for i in range(len(rows)):
            tag = int(frags["sig"][i])
            entry = self._pending.pop(tag, None)
            if entry is None:
                continue
            slot, fec = entry
            sig = rows[i, :64].tobytes()
            ctx.metrics.inc("sign_responses")
            self._release(ctx, slot, fec, sig)

    def _release(self, ctx: MuxCtx, slot: int, fec, sig: bytes) -> None:
        """Patch the signature into every shred of the set and queue the
        shreds for publication (the proof region never covers the
        signature, so late patching is sound)."""
        fec.signature = sig
        for raw in fec.data_shreds + fec.parity_shreds:
            patched = sig + raw[64:]
            s = SH.parse(patched)
            assert s is not None
            self._outq.append((slot, s.idx, not s.is_data, patched))
            if self.shred_dest is not None and self.identity is not None:
                order = self.shred_dest.shuffle(
                    slot, s.idx, 0 if s.is_data else 1, self.identity
                )
                if order:
                    ctx.metrics.inc("turbine_dests")

    # ---- egress ----------------------------------------------------------

    def _drain_signq(self, ctx: MuxCtx) -> None:
        if not self._signq:
            return
        if len(ctx.outs) < 2:
            raise RuntimeError(
                "shred tile: keyguard signing requires outs[1] (sign ring)"
            )
        n = min(len(self._signq), ctx.outs[1].cr_avail())
        if n <= 0:
            return
        items = [self._signq.popleft() for _ in range(n)]
        tags = np.array([t for t, _ in items], np.uint64)
        rows = np.stack(
            [np.frombuffer(r, np.uint8) for _, r in items]
        )
        ctx.outs[1].publish(
            tags, rows, np.full(n, rows.shape[1], np.uint16)
        )
        ctx.metrics.inc("sign_requests", n)

    def in_budget(self, ctx: MuxCtx) -> int | None:
        """Bound the internal queues (manual-credit contract): stop
        absorbing entries while the signed-shred backlog is deep."""
        return 0 if len(self._outq) > 8192 else None

    def after_credit(self, ctx: MuxCtx) -> None:
        self._drain_signq(ctx)
        while self._outq:
            budget = ctx.outs[0].cr_avail()
            if budget <= 0:
                break
            n = min(len(self._outq), budget)
            items = [self._outq.popleft() for _ in range(n)]
            w = max(len(it[3]) for it in items)
            rows = np.zeros((n, w), np.uint8)
            szs = np.zeros(n, np.uint16)
            tags = np.zeros(n, np.uint64)
            for i, (slot, idx, is_code, raw) in enumerate(items):
                rows[i, : len(raw)] = np.frombuffer(raw, np.uint8)
                szs[i] = len(raw)
                tags[i] = shred_tag(slot, idx, is_code)
            ctx.outs[0].publish(tags, rows, szs)

    def on_halt(self, ctx: MuxCtx) -> None:
        # flush the final partial slot so short-lived test topologies
        # don't lose the tail batch, then drain straggler sign responses
        # and queued shreds while downstream credits free up
        self._finish_slot(ctx, block_complete=False)
        import time as _t

        deadline = _t.monotonic() + 10.0
        while (self._outq or self._pending or self._signq) and _t.monotonic() < deadline:
            if len(ctx.ins) > 1 and self._pending:
                il = ctx.ins[1]
                frags, il.seq, ovr = il.mcache.drain(il.seq, 256)
                if ovr:
                    ctx.metrics.inc("overrun_frags", ovr)
                    il.fseq.diag_add(0, ovr)
                if len(frags):
                    self._on_sign_responses(ctx, frags)
            ctx.credits = ctx.outs[0].cr_avail()
            self.after_credit(ctx)
            _t.sleep(100e-6)
