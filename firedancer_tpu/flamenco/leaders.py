"""Epoch leader schedule (stake-weighted rotation assignment).

Behavior contract: src/flamenco/leaders/fd_leaders.c — seed a
ChaCha20Rng (MODE_MOD) from the epoch, build a weighted sampler over the
stake weights (stake-descending order), and draw one leader index per
rotation of FD_EPOCH_SLOTS_PER_ROTATION (4) slots.
"""

from __future__ import annotations

from dataclasses import dataclass

from firedancer_tpu.ballet.chacha20 import MODE_MOD, ChaCha20Rng
from firedancer_tpu.ballet.wsample import WSample

SLOTS_PER_ROTATION = 4


def epoch_seed(epoch: int) -> bytes:
    """The rng key: epoch as little-endian u64 zero-padded to 32 bytes
    (Solana's leader_schedule seed convention)."""
    return epoch.to_bytes(8, "little") + bytes(24)


def sorted_stake_weights(stakes: dict[bytes, int]) -> list[tuple[bytes, int]]:
    """(pubkey -> stake) -> list ordered stake-desc, pubkey-desc — the
    deterministic order the schedule is sampled against."""
    return sorted(stakes.items(), key=lambda kv: (kv[1], kv[0]), reverse=True)


@dataclass
class EpochLeaders:
    epoch: int
    slot0: int
    slot_cnt: int
    pubkeys: list[bytes]  # deduped identity table
    sched: list[int]  # one pubkey index per rotation

    def contains(self, slot: int) -> bool:
        return self.slot0 <= slot < self.slot0 + self.slot_cnt

    def leader_for_slot(self, slot: int) -> bytes:
        if not self.contains(slot):
            raise ValueError(f"slot {slot} outside epoch {self.epoch}")
        rot = (slot - self.slot0) // SLOTS_PER_ROTATION
        return self.pubkeys[self.sched[rot]]


def derive(
    epoch: int,
    slot0: int,
    slot_cnt: int,
    stakes: dict[bytes, int],
) -> EpochLeaders:
    ordered = sorted_stake_weights(stakes)
    pubkeys = [pk for pk, _ in ordered]
    weights = [w for _, w in ordered]
    rng = ChaCha20Rng(epoch_seed(epoch), MODE_MOD)
    ws = WSample(rng, weights, restore_enabled=False)
    sched_cnt = (slot_cnt + SLOTS_PER_ROTATION - 1) // SLOTS_PER_ROTATION
    sched = [ws.sample() for _ in range(sched_cnt)]
    return EpochLeaders(epoch, slot0, slot_cnt, pubkeys, sched)
