"""Feature-gate registry: named runtime behavior switches activated by
on-chain feature accounts.

Reference model: src/flamenco/features/ (fd_features.h + 1,437 generated
LoC from feature_map.json) — each feature is a pubkey-addressed account
owned by the feature program; its state is `Feature { activated_at:
Option<Slot> }` (bincode).  The runtime derives a flat activation-slot
table from the account database; FD_FEATURE_DISABLED (u64 max) marks
inactive.  This build keeps the same shape declaratively: a name->pubkey
map, a Features table with enable_all/disable_all (the reference's dev
harness defaults), and from_accounts() deriving activations from funk.

Feature pubkeys are consensus constants (reference feature_map.json).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from firedancer_tpu.ballet.base58 import decode_32

#: sentinel activation slot: not activated (FD_FEATURE_DISABLED)
DISABLED = (1 << 64) - 1

#: the feature program that owns activation accounts
FEATURE_OWNER_ID = decode_32("Feature111111111111111111111111111111111111")

#: name -> feature account pubkey (subset of the reference's 180-entry
#: feature_map.json: the gates this runtime's surface can meaningfully
#: switch, plus well-known ids kept for wire parity)
FEATURE_IDS: dict[str, bytes] = {
    name: decode_32(b58)
    for name, b58 in {
        "versioned_tx_message_enabled":
            "3KZZ6Ks1885aGBQ45fwRcPXVBCtzUvxhUTkwKMR41Tca",
        "blake3_syscall_enabled":
            "HTW2pSyErTj4BV6KBM9NZ9VBUJVxt7sacNWcf76wtzb3",
        "curve25519_syscall_enabled":
            "7rcw5UtqgDTBBv2EcynNfYckgdAaH1MAsCjKgXMkN7Ri",
        "ed25519_program_enabled":
            "6ppMXNYLhVd7GcsZ5uV11wQEW7spppiMVfqQv5SXhDpX",
        "secp256k1_program_enabled":
            "E3PHP7w8kB7np3CTQ1qQ2tW3KCtjRSXBQgW9vM2mWv2Y",
        "system_transfer_zero_check":
            "BrTR9hzw4WBGFP65AJMbpAo64DcA3U6jdPSga9fMV5cS",
        "require_rent_exempt_accounts":
            "BkFDxiJQWZXGTZaJQxH7wVEHkAmwCgSEVkrvswFfRJPD",
        "return_data_syscall_enabled":
            "DwScAzPUjuv65TMbDnFY7AgwmotzWy3xpEJMXM3hZFaB",
        "sol_log_data_syscall_enabled":
            "6uaHcKPGUy4J7emLBgUTeufhJdiwhngW6a1R9B7c2ob9",
        "secp256k1_recover_syscall_enabled":
            "6RvdSWHh8oh72Dp7wMTS2DBkf3fRPtChfNrAo3cZZoXJ",
        "tx_wide_compute_cap":
            "5ekBxc8itEnPv4NzGJtr8BVVQLNMQuLMNQQj7pHoLNZ9",
    }.items()
}


def encode_feature_account(activated_at: int | None) -> bytes:
    """Feature account data: bincode Option<u64> activation slot."""
    if activated_at is None:
        return b"\x00"
    return b"\x01" + activated_at.to_bytes(8, "little")


def decode_feature_account(data: bytes) -> int | None:
    """-> activation slot, or None when pending/malformed."""
    if not data or data[0] == 0:
        return None
    if len(data) < 9:
        return None
    return int.from_bytes(data[1:9], "little")


@dataclass
class Features:
    """Flat activation table: name -> activation slot (DISABLED if not
    activated)."""

    slots: dict[str, int] = field(default_factory=dict)

    @classmethod
    def all_enabled(cls) -> "Features":
        """Every known feature active from slot 0 (the reference's
        fd_features_enable_all dev default)."""
        return cls({name: 0 for name in FEATURE_IDS})

    @classmethod
    def all_disabled(cls) -> "Features":
        return cls({name: DISABLED for name in FEATURE_IDS})

    def active(self, name: str, slot: int) -> bool:
        a = self.slots.get(name, DISABLED)
        return a != DISABLED and slot >= a

    def activate(self, name: str, slot: int) -> None:
        self.slots[name] = slot

    @classmethod
    def from_accounts(cls, load, default: "Features | None" = None):
        """Derive activations from feature accounts (`load(pubkey) ->
        Account | None`).  An existing feature account OVERRIDES the
        default table: pending (activated_at None) means disabled; a
        missing account keeps the default entry (dev harnesses run
        all-enabled, like the reference's)."""
        out = dict((default or cls.all_enabled()).slots)
        for name, pk in FEATURE_IDS.items():
            acct = load(pk)
            if acct is None or acct.owner != FEATURE_OWNER_ID:
                continue
            at = decode_feature_account(acct.data)
            out[name] = DISABLED if at is None else at
        return cls(out)
