"""Sysvars: cluster-state accounts programs read at well-known addresses.

Reference model: src/flamenco/runtime/sysvar/ (fd_sysvar_clock.c,
fd_sysvar_rent.c, fd_sysvar_epoch_schedule.c) — the runtime materializes
cluster state (clock, rent parameters, epoch schedule) into accounts
owned by the sysvar program so on-chain programs can read them like any
other account.  Layouts are the bincode wire shapes of the corresponding
Solana types (fixed-width little-endian fields).

The bank installs/refreshes them per slot via `install(mgr, slot, ...)`.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from firedancer_tpu.ballet.base58 import decode_32
from firedancer_tpu.flamenco.accounts import Account, AccountMgr

#: the sysvar owner program id ("Sysvar1111...")
SYSVAR_OWNER_ID = decode_32("Sysvar1111111111111111111111111111111111111")
CLOCK_ID = decode_32("SysvarC1ock11111111111111111111111111111111")
RENT_ID = decode_32("SysvarRent111111111111111111111111111111111")
EPOCH_SCHEDULE_ID = decode_32("SysvarEpochSchedu1e111111111111111111111111")


@dataclass
class Clock:
    slot: int = 0
    epoch_start_timestamp: int = 0
    epoch: int = 0
    leader_schedule_epoch: int = 0
    unix_timestamp: int = 0

    _S = struct.Struct("<QqQQq")

    def encode(self) -> bytes:
        return self._S.pack(
            self.slot, self.epoch_start_timestamp, self.epoch,
            self.leader_schedule_epoch, self.unix_timestamp,
        )

    @classmethod
    def decode(cls, raw: bytes) -> "Clock":
        return cls(*cls._S.unpack_from(raw))


@dataclass
class Rent:
    lamports_per_byte_year: int = 3480
    exemption_threshold: float = 2.0
    burn_percent: int = 50

    _S = struct.Struct("<QdB")

    def encode(self) -> bytes:
        return self._S.pack(
            self.lamports_per_byte_year, self.exemption_threshold,
            self.burn_percent,
        )

    @classmethod
    def decode(cls, raw: bytes) -> "Rent":
        return cls(*cls._S.unpack_from(raw))

    def minimum_balance(self, data_len: int) -> int:
        return int(
            (128 + data_len)
            * self.lamports_per_byte_year
            * self.exemption_threshold
        )


@dataclass
class EpochSchedule:
    slots_per_epoch: int = 432_000
    leader_schedule_slot_offset: int = 432_000
    warmup: bool = False
    first_normal_epoch: int = 0
    first_normal_slot: int = 0

    _S = struct.Struct("<QQBQQ")

    def encode(self) -> bytes:
        return self._S.pack(
            self.slots_per_epoch, self.leader_schedule_slot_offset,
            int(self.warmup), self.first_normal_epoch, self.first_normal_slot,
        )

    @classmethod
    def decode(cls, raw: bytes) -> "EpochSchedule":
        s = cls(*cls._S.unpack_from(raw))
        s.warmup = bool(s.warmup)
        return s

    def epoch_of(self, slot: int) -> int:
        return slot // self.slots_per_epoch  # post-warmup schedule


def install(
    mgr: AccountMgr,
    slot: int,
    *,
    unix_timestamp: int = 0,
    rent: Rent | None = None,
    schedule: EpochSchedule | None = None,
) -> None:
    """Materialize/refresh the sysvar accounts for `slot` (the bank calls
    this at slot start; reference: fd_sysvar_clock_update)."""
    rent = rent or Rent()
    schedule = schedule or EpochSchedule()
    epoch = schedule.epoch_of(slot)
    clock = Clock(
        slot=slot,
        epoch=epoch,
        leader_schedule_epoch=epoch + 1,
        unix_timestamp=unix_timestamp,
    )
    for key, body in (
        (CLOCK_ID, clock.encode()),
        (RENT_ID, rent.encode()),
        (EPOCH_SCHEDULE_ID, schedule.encode()),
    ):
        mgr.store(
            key,
            Account(
                lamports=1_000_000_000,
                owner=SYSVAR_OWNER_ID,
                data=body,
            ),
        )
