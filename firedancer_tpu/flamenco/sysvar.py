"""Sysvars: cluster-state accounts programs read at well-known addresses.

Reference model: src/flamenco/runtime/sysvar/ (fd_sysvar_clock.c,
fd_sysvar_rent.c, fd_sysvar_epoch_schedule.c) — the runtime materializes
cluster state (clock, rent parameters, epoch schedule) into accounts
owned by the sysvar program so on-chain programs can read them like any
other account.  Layouts are the bincode wire shapes of the corresponding
Solana types (fixed-width little-endian fields).

The bank installs/refreshes them per slot via `install(mgr, slot, ...)`.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from firedancer_tpu.ballet.base58 import decode_32
from firedancer_tpu.flamenco.accounts import Account, AccountMgr

#: the sysvar owner program id ("Sysvar1111...")
SYSVAR_OWNER_ID = decode_32("Sysvar1111111111111111111111111111111111111")
CLOCK_ID = decode_32("SysvarC1ock11111111111111111111111111111111")
RENT_ID = decode_32("SysvarRent111111111111111111111111111111111")
EPOCH_SCHEDULE_ID = decode_32("SysvarEpochSchedu1e111111111111111111111111")
SLOT_HASHES_ID = decode_32("SysvarS1otHashes111111111111111111111111111")
RECENT_BLOCKHASHES_ID = decode_32(
    "SysvarRecentB1ockHashes11111111111111111111"
)

#: SlotHashes capacity (reference: fd_sysvar_slot_hashes.c slot_hashes_max)
SLOT_HASHES_MAX = 512


@dataclass
class Clock:
    slot: int = 0
    epoch_start_timestamp: int = 0
    epoch: int = 0
    leader_schedule_epoch: int = 0
    unix_timestamp: int = 0

    _S = struct.Struct("<QqQQq")

    def encode(self) -> bytes:
        return self._S.pack(
            self.slot, self.epoch_start_timestamp, self.epoch,
            self.leader_schedule_epoch, self.unix_timestamp,
        )

    @classmethod
    def decode(cls, raw: bytes) -> "Clock":
        return cls(*cls._S.unpack_from(raw))


@dataclass
class Rent:
    lamports_per_byte_year: int = 3480
    exemption_threshold: float = 2.0
    burn_percent: int = 50

    _S = struct.Struct("<QdB")

    def encode(self) -> bytes:
        return self._S.pack(
            self.lamports_per_byte_year, self.exemption_threshold,
            self.burn_percent,
        )

    @classmethod
    def decode(cls, raw: bytes) -> "Rent":
        return cls(*cls._S.unpack_from(raw))

    def minimum_balance(self, data_len: int) -> int:
        return int(
            (128 + data_len)
            * self.lamports_per_byte_year
            * self.exemption_threshold
        )


@dataclass
class EpochSchedule:
    slots_per_epoch: int = 432_000
    leader_schedule_slot_offset: int = 432_000
    warmup: bool = False
    first_normal_epoch: int = 0
    first_normal_slot: int = 0

    _S = struct.Struct("<QQBQQ")

    def encode(self) -> bytes:
        return self._S.pack(
            self.slots_per_epoch, self.leader_schedule_slot_offset,
            int(self.warmup), self.first_normal_epoch, self.first_normal_slot,
        )

    @classmethod
    def decode(cls, raw: bytes) -> "EpochSchedule":
        s = cls(*cls._S.unpack_from(raw))
        s.warmup = bool(s.warmup)
        return s

    def epoch_of(self, slot: int) -> int:
        return slot // self.slots_per_epoch  # post-warmup schedule


@dataclass
class SlotHashes:
    """Most-recent-first (slot, hash) pairs, capped at SLOT_HASHES_MAX.

    Layout is the Solana bincode Vec<(u64, [u8;32])> the reference
    serializes in fd_sysvar_slot_hashes.c (u64 count + packed entries).
    Consumers: ALT deactivation cooldown (a deactivating table serves
    lookups while its deactivation slot is still present here).
    """

    entries: list = None  # list[(slot, hash32)]

    def __post_init__(self):
        if self.entries is None:
            self.entries = []

    def add(self, slot: int, h: bytes) -> None:
        self.entries.insert(0, (slot, h))
        del self.entries[SLOT_HASHES_MAX:]

    def contains_slot(self, slot: int) -> bool:
        return any(s == slot for s, _ in self.entries)

    def encode(self) -> bytes:
        out = bytearray(len(self.entries).to_bytes(8, "little"))
        for s, h in self.entries:
            out += s.to_bytes(8, "little") + h
        return bytes(out)

    @classmethod
    def decode(cls, raw: bytes) -> "SlotHashes":
        n = int.from_bytes(raw[:8], "little")
        entries = []
        off = 8
        for _ in range(min(n, SLOT_HASHES_MAX)):
            s = int.from_bytes(raw[off : off + 8], "little")
            entries.append((s, bytes(raw[off + 8 : off + 40])))
            off += 40
        return cls(entries)


@dataclass
class RecentBlockhashes:
    """Vec<(hash, fee_calculator)> newest first (deprecated sysvar the
    nonce instructions still account-check; reference
    fd_sysvar_recent_hashes.c)."""

    entries: list = None  # list[(hash32, lamports_per_signature)]

    def __post_init__(self):
        if self.entries is None:
            self.entries = []

    def encode(self) -> bytes:
        out = bytearray(len(self.entries).to_bytes(8, "little"))
        for h, lps in self.entries:
            out += h + lps.to_bytes(8, "little")
        return bytes(out)

    @classmethod
    def decode(cls, raw: bytes) -> "RecentBlockhashes":
        n = int.from_bytes(raw[:8], "little")
        entries = []
        off = 8
        for _ in range(n):
            entries.append(
                (
                    bytes(raw[off : off + 32]),
                    int.from_bytes(raw[off + 32 : off + 40], "little"),
                )
            )
            off += 40
        return cls(entries)


def install(
    mgr: AccountMgr,
    slot: int,
    *,
    unix_timestamp: int = 0,
    rent: Rent | None = None,
    schedule: EpochSchedule | None = None,
    slot_hashes: SlotHashes | None = None,
    recent_blockhashes: RecentBlockhashes | None = None,
) -> None:
    """Materialize/refresh the sysvar accounts for `slot` (the bank calls
    this at slot start; reference: fd_sysvar_clock_update)."""
    rent = rent or Rent()
    schedule = schedule or EpochSchedule()
    epoch = schedule.epoch_of(slot)
    clock = Clock(
        slot=slot,
        epoch=epoch,
        leader_schedule_epoch=epoch + 1,
        unix_timestamp=unix_timestamp,
    )
    bodies = [
        (CLOCK_ID, clock.encode()),
        (RENT_ID, rent.encode()),
        (EPOCH_SCHEDULE_ID, schedule.encode()),
    ]
    if slot_hashes is not None:
        bodies.append((SLOT_HASHES_ID, slot_hashes.encode()))
    if recent_blockhashes is not None:
        bodies.append(
            (RECENT_BLOCKHASHES_ID, recent_blockhashes.encode())
        )
    for key, body in bodies:
        mgr.store(
            key,
            Account(
                lamports=1_000_000_000,
                owner=SYSVAR_OWNER_ID,
                data=body,
            ),
        )
