"""Transaction execution runtime — the bank tile's executor.

Reference model: src/flamenco/runtime/fd_executor.c (dispatch txn ->
program), runtime/program/fd_system_program.c (transfer / create_account /
assign / allocate with the bincode u32-discriminant instruction encoding),
and the BPF loader path into the VM (fd_vm_interp).  Accounts live in funk
via flamenco.accounts; each executed batch runs inside a funk transaction
so a failed block can be cancelled wholesale (the fork model the reference
gets from funk too).

Execution semantics implemented:
  * fee collection: FEE_PER_SIGNATURE lamports per signature, debited
    from the fee payer (first signer) BEFORE execution; txn rejected
    outright if the payer cannot cover fees
  * per-instruction dispatch by owner/program id: system program native
    impl; programs owned by the BPF loader execute in the sBPF VM
  * failed txns roll back their own writes but still pay fees (matching
    the reference's fee-then-execute ordering)
  * rent: create_account requires the rent-exempt minimum for the
    requested space (simplified linear model; reference sysvar rent)
"""

from __future__ import annotations

from dataclasses import dataclass, field

from firedancer_tpu.ballet import txn as T
from firedancer_tpu.flamenco.accounts import (
    Account, AccountMgr, SYSTEM_PROGRAM_ID,
)
from firedancer_tpu.funk.funk import Funk, ROOT_XID

FEE_PER_SIGNATURE = 5000

#: simplified rent-exempt minimum: lamports per byte-year * 2 years
RENT_PER_BYTE = 3480 * 2
RENT_BASE = 890_880

#: consensus cap on account data size (10 MiB, MAX_PERMITTED_DATA_LENGTH)
MAX_DATA_LEN = 10 * 1024 * 1024

BPF_LOADER_ID = b"BPFLoader" + bytes(23)

# system instruction discriminants (bincode u32le)
_SYS_CREATE = 0
_SYS_ASSIGN = 1
_SYS_TRANSFER = 2
_SYS_ALLOCATE = 8


def rent_exempt_minimum(space: int) -> int:
    return RENT_BASE + RENT_PER_BYTE * space


@dataclass
class TxnResult:
    ok: bool
    err: str = ""
    fee: int = 0
    logs: list = field(default_factory=list)
    cu_used: int = 0


class Executor:
    """Executes parsed transactions against a funk fork."""

    def __init__(self, funk: Funk, xid: bytes = ROOT_XID):
        self.funk = funk
        self.xid = xid
        self.mgr = AccountMgr(funk, xid)

    # ---- entry points ---------------------------------------------------

    def execute_txn(self, payload: bytes, desc: T.TxnDesc | None = None) -> TxnResult:
        desc = desc or T.parse(payload)
        if desc is None:
            return TxnResult(False, "parse")
        keys = [
            bytes(desc.acct_addr(payload, j))
            for j in range(desc.acct_addr_cnt)
        ]
        fee = FEE_PER_SIGNATURE * desc.signature_cnt

        payer = self.mgr.load(keys[0])
        if payer is None or payer.lamports < fee:
            return TxnResult(False, "insufficient fee payer", fee=0)
        payer.lamports -= fee
        self.mgr.store(keys[0], payer)

        # execute instructions against a scratch overlay so a failed txn
        # rolls back its writes but keeps the fee debit
        overlay: dict[bytes, Account | None] = {}

        def load(k: bytes) -> Account | None:
            if k in overlay:
                a = overlay[k]
                return None if a is None else Account(**vars(a))
            return self.mgr.load(k)

        def store(k: bytes, a: Account) -> None:
            overlay[k] = a

        logs: list = []
        for ins in desc.instr:
            prog_key = keys[ins.program_id]
            data = payload[ins.data_off : ins.data_off + ins.data_sz]
            ins_keys = [
                keys[payload[ins.acct_off + j]]
                for j in range(ins.acct_cnt)
            ]
            err = self._dispatch(
                prog_key, data, ins_keys, desc, keys, load, store, logs
            )
            if err:
                return TxnResult(False, err, fee=fee, logs=logs)
        for k, a in overlay.items():
            if a is not None:
                self.mgr.store(k, a)
        return TxnResult(True, fee=fee, logs=logs)

    # ---- dispatch -------------------------------------------------------

    def _dispatch(self, prog_key, data, ins_keys, desc, keys, load, store,
                  logs) -> str:
        if prog_key == SYSTEM_PROGRAM_ID:
            return self._system(data, ins_keys, desc, keys, load, store)
        prog = load(prog_key)
        if prog is not None and prog.owner == BPF_LOADER_ID and prog.executable:
            return self._bpf(prog, data, ins_keys, load, store, logs)
        return "unknown program"

    def _system(self, data, ins_keys, desc, keys, load, store) -> str:
        if len(data) < 4:
            return "bad system instruction"
        disc = int.from_bytes(data[:4], "little")
        if disc == _SYS_TRANSFER:
            if len(ins_keys) < 2 or len(data) < 12:
                return "bad transfer"
            lamports = int.from_bytes(data[4:12], "little")
            src_k, dst_k = ins_keys[0], ins_keys[1]
            if not self._is_signer(src_k, desc, keys):
                return "missing signature"
            src = load(src_k)
            if src is None or src.lamports < lamports:
                return "insufficient funds"
            if src_k == dst_k:
                return ""  # self-transfer is a no-op (never mints)
            dst = load(dst_k) or Account(0)
            src.lamports -= lamports
            dst.lamports += lamports
            store(src_k, src)
            store(dst_k, dst)
            return ""
        if disc == _SYS_CREATE:
            if len(ins_keys) < 2 or len(data) < 52:
                return "bad create_account"
            lamports = int.from_bytes(data[4:12], "little")
            space = int.from_bytes(data[12:20], "little")
            if space > MAX_DATA_LEN:
                return "data length exceeds maximum"
            owner = data[20:52]
            src_k, new_k = ins_keys[0], ins_keys[1]
            if not self._is_signer(src_k, desc, keys) or not self._is_signer(
                new_k, desc, keys
            ):
                return "missing signature"
            if lamports < rent_exempt_minimum(space):
                return "rent: not exempt"
            src = load(src_k)
            if src is None or src.lamports < lamports:
                return "insufficient funds"
            if load(new_k) is not None:
                return "account exists"
            src.lamports -= lamports
            store(src_k, src)
            store(new_k, Account(lamports, owner, False, 0, bytes(space)))
            return ""
        if disc == _SYS_ASSIGN:
            if len(ins_keys) < 1 or len(data) < 36:
                return "bad assign"
            k = ins_keys[0]
            if not self._is_signer(k, desc, keys):
                return "missing signature"
            a = load(k)
            if a is None:
                return "no account"
            a.owner = data[4:36]
            store(k, a)
            return ""
        if disc == _SYS_ALLOCATE:
            if len(ins_keys) < 1 or len(data) < 12:
                return "bad allocate"
            space = int.from_bytes(data[4:12], "little")
            if space > MAX_DATA_LEN:
                return "data length exceeds maximum"
            k = ins_keys[0]
            if not self._is_signer(k, desc, keys):
                return "missing signature"
            a = load(k)
            if a is None:
                return "no account"
            if a.lamports < rent_exempt_minimum(space):
                return "rent: not exempt"
            a.data = bytes(space)
            store(k, a)
            return ""
        return "unsupported system instruction"

    @staticmethod
    def _is_signer(key: bytes, desc: T.TxnDesc, keys: list) -> bool:
        return key in keys[: desc.signature_cnt]

    def _bpf(self, prog: Account, data, ins_keys, load, store, logs) -> str:
        from firedancer_tpu.ballet import sbpf
        from firedancer_tpu.flamenco.vm import Vm, VmError

        try:
            program = sbpf.load(prog.data)
        except sbpf.SbpfError as e:
            return f"elf: {e}"
        vm = Vm(program)
        vm.input_mem = bytearray(data)  # instruction data as input region
        try:
            r0 = vm.run()
        except VmError as e:
            logs.extend(vm.logs)
            return f"vm: {e}"
        logs.extend(vm.logs)
        return "" if r0 == 0 else f"program error {r0}"
