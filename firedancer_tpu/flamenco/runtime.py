"""Transaction execution runtime — the bank tile's executor.

Reference model: src/flamenco/runtime/fd_executor.c (dispatch txn ->
program), runtime/program/fd_system_program.c (transfer / create_account /
assign / allocate with the bincode u32-discriminant instruction encoding),
and the BPF loader path into the VM (fd_vm_interp).  Accounts live in funk
via flamenco.accounts; each executed batch runs inside a funk transaction
so a failed block can be cancelled wholesale (the fork model the reference
gets from funk too).

Execution semantics implemented:
  * fee collection: FEE_PER_SIGNATURE lamports per signature, debited
    from the fee payer (first signer) BEFORE execution; txn rejected
    outright if the payer cannot cover fees
  * per-instruction dispatch by owner/program id: system program native
    impl; programs owned by the BPF loader execute in the sBPF VM
  * failed txns roll back their own writes but still pay fees (matching
    the reference's fee-then-execute ordering)
  * rent: create_account requires the rent-exempt minimum for the
    requested space (simplified linear model; reference sysvar rent)
"""

from __future__ import annotations

from dataclasses import dataclass, field

import struct

from firedancer_tpu.ballet import txn as T
from firedancer_tpu.ballet.base58 import decode_32
from firedancer_tpu.flamenco.accounts import (
    _HDR, Account, AccountMgr, SYSTEM_PROGRAM_ID,
)
from firedancer_tpu.funk.funk import Funk, ROOT_XID

FEE_PER_SIGNATURE = 5000

#: address-lookup-table native program
#: (reference: runtime/program/fd_address_lookup_table_program.c)
ALT_PROGRAM_ID = decode_32("AddressLookupTab1e1111111111111111111111111")
#: config native program (reference: fd_config_program.c)
CONFIG_PROGRAM_ID = decode_32("Config1111111111111111111111111111111111111")
#: ed25519 signature-verification precompile (fd_ed25519_program.c)
ED25519_PROGRAM_ID = decode_32("Ed25519SigVerify111111111111111111111111111")
#: keccak-secp256k1 precompile (eth-style ecrecover verification; the
#: sibling of the ed25519 precompile)
SECP256K1_PROGRAM_ID = decode_32(
    "KeccakSecp256k11111111111111111111111111111"
)

#: ALT account layout: 56-byte header then packed 32-byte addresses
_ALT_HDR = struct.Struct("<IQQBB32sH")
ALT_HEADER_SZ = 56
_ALT_DISC_TABLE = 1
ALT_DEACT_NONE = (1 << 64) - 1
#: slots a deactivating table keeps serving lookups (reference: table is
#: usable until its deactivation slot ages out of slot-hashes, ~512 slots)
ALT_DEACT_COOLDOWN = 513

# ALT instruction discriminants (bincode u32le)
_ALT_CREATE = 0
_ALT_FREEZE = 1
_ALT_EXTEND = 2
_ALT_DEACTIVATE = 3

#: simplified rent-exempt minimum: lamports per byte-year * 2 years
RENT_PER_BYTE = 3480 * 2
RENT_BASE = 890_880

#: consensus cap on account data size (10 MiB, MAX_PERMITTED_DATA_LENGTH)
MAX_DATA_LEN = 10 * 1024 * 1024
#: spare bytes after each account's data in the VM input region — the
#: realloc headroom of Solana's aligned serializer (10 KiB)
MAX_PERMITTED_DATA_INCREASE = 10 * 1024

BPF_LOADER_ID = b"BPFLoader" + bytes(23)

#: BPF loader v4 (reference: runtime/program/fd_bpf_loader_v4_program.c)
LOADER_V4_ID = decode_32("LoaderV411111111111111111111111111111111111")
#: loader-v4 account state header: u64 slot | authority[32] | u64 status
LOADER_V4_STATE_SZ = 48
_V4_RETRACTED, _V4_DEPLOYED, _V4_FINALIZED = 0, 1, 2
#: slots between deploy/retract status flips (fd_bpf_loader_v4_program.c
#: DEPLOYMENT_COOLDOWN_IN_SLOTS)
V4_DEPLOYMENT_COOLDOWN = 750
# loader-v4 instruction discriminants (bincode u32le)
_V4_WRITE, _V4_TRUNCATE, _V4_DEPLOY, _V4_RETRACT, _V4_XFER_AUTH = range(5)

# system instruction discriminants (bincode u32le)
_SYS_CREATE = 0
_SYS_ASSIGN = 1
_SYS_TRANSFER = 2
_SYS_ADVANCE_NONCE = 4
_SYS_WITHDRAW_NONCE = 5
_SYS_INIT_NONCE = 6
_SYS_AUTHORIZE_NONCE = 7
_SYS_ALLOCATE = 8

#: nonce account data: Versions(u32) + State(u32) + authority(32) +
#: durable_nonce(32) + fee_calculator.lamports_per_signature(u64)
NONCE_STATE_SZ = 80
_NONCE_VERSION_LEGACY = 0
_NONCE_VERSION_CURRENT = 1
_NONCE_UNINITIALIZED = 0
_NONCE_INITIALIZED = 1


def durable_nonce_from_blockhash(blockhash: bytes) -> bytes:
    """sha256("DURABLE_NONCE" || blockhash) — the domain-separated nonce
    value (reference: fd_durable_nonce_from_blockhash,
    fd_system_program_nonce.c:67-72)."""
    import hashlib

    return hashlib.sha256(b"DURABLE_NONCE" + blockhash).digest()


def _nonce_decode(data: bytes):
    """-> (state, authority, durable, fee) with state in
    {_NONCE_UNINITIALIZED, _NONCE_INITIALIZED}, or None on malformed
    data.  Accepts both Legacy and Current versions (reference decode
    switch, fd_system_program_nonce.c:155-168)."""
    if len(data) < 8:
        return None
    version = int.from_bytes(data[:4], "little")
    if version not in (_NONCE_VERSION_LEGACY, _NONCE_VERSION_CURRENT):
        return None
    state = int.from_bytes(data[4:8], "little")
    if state == _NONCE_UNINITIALIZED:
        return (_NONCE_UNINITIALIZED, None, None, 0)
    if state != _NONCE_INITIALIZED or len(data) < NONCE_STATE_SZ:
        return None
    return (
        _NONCE_INITIALIZED,
        bytes(data[8:40]),
        bytes(data[40:72]),
        int.from_bytes(data[72:80], "little"),
    )


def _nonce_encode(state: int, authority: bytes = bytes(32),
                  durable: bytes = bytes(32), fee: int = 0) -> bytes:
    return (
        _NONCE_VERSION_CURRENT.to_bytes(4, "little")
        + state.to_bytes(4, "little")
        + authority
        + durable
        + fee.to_bytes(8, "little")
    )


def rent_exempt_minimum(space: int) -> int:
    return RENT_BASE + RENT_PER_BYTE * space


def _v4_state(data: bytes):
    """Loader-v4 state header -> (slot, authority, status) or None."""
    if len(data) < LOADER_V4_STATE_SZ:
        return None
    return (
        int.from_bytes(data[0:8], "little"),
        bytes(data[8:40]),
        int.from_bytes(data[40:48], "little"),
    )


def _v4_state_encode(slot: int, authority: bytes, status: int) -> bytes:
    return (
        slot.to_bytes(8, "little") + authority
        + status.to_bytes(8, "little")
    )


def alt_addresses(table_data: bytes) -> list[bytes] | None:
    """Addresses held by an ALT account (None on malformation)."""
    if len(table_data) < ALT_HEADER_SZ:
        return None
    disc = int.from_bytes(table_data[:4], "little")
    if disc != _ALT_DISC_TABLE:
        return None
    body = table_data[ALT_HEADER_SZ:]
    if len(body) % 32:
        return None
    return [body[i : i + 32] for i in range(0, len(body), 32)]


@dataclass
class TxnResult:
    ok: bool
    err: str = ""
    fee: int = 0
    logs: list = field(default_factory=list)
    cu_used: int = 0


#: reference: max instruction stack height 5 (top-level is height 1), so
#: CPI may nest 4 deep (fd_vm_syscall_cpi max invoke depth behavior)
MAX_INVOKE_STACK = 5
#: per-txn compute budget shared across every instruction + CPI callee
TXN_CU_BUDGET = 1_400_000
#: CPI flat cost (reference: vm syscall cost model for sol_invoke_*)
CPI_BASE_CU = 1_000
#: PDA seed constraints (reference: fd_pubkey_create_program_address)
MAX_SEEDS = 16
MAX_SEED_LEN = 32


@dataclass(frozen=True)
class InstrCtx:
    """Per-instruction execution context: the privilege sets the reference
    carries in fd_instr_info (signer/writable flags per account), the
    invoke stack for re-entrancy rules, and the shared CU meter.

    `signers` includes txn signers and, under CPI, caller-granted signer
    privileges + PDA signers; `writables` is the writable-privilege set
    granted by the caller (top level: the txn message header flags).
    `stack` holds the program ids of active invocations, outermost first.
    `meter` is a 1-element mutable list: remaining CUs for the whole txn.
    """

    signers: frozenset
    writables: frozenset
    stack: tuple = ()
    meter: list = field(default_factory=lambda: [TXN_CU_BUDGET])
    #: (payload, desc) of the enclosing transaction — precompiles read
    #: sibling instructions' data through it (fd_ed25519_program.c
    #: _get_instr_data)
    txn: tuple | None = None

    @property
    def depth(self) -> int:
        return len(self.stack)

    def child(self, signers, writables) -> "InstrCtx":
        """Privilege-restricted context for a CPI callee.  The invoke
        stack is NOT pushed here — _dispatch pushes the callee program id
        when it runs the instruction."""
        return InstrCtx(
            frozenset(signers), frozenset(writables), self.stack,
            self.meter, self.txn,
        )


def create_program_address(seeds, program_id: bytes):
    """PDA derivation: sha256(seeds.. || program_id || marker), rejected
    when the digest decodes to a curve point (reference:
    fd_pubkey_create_program_address)."""
    import hashlib

    if len(seeds) > MAX_SEEDS:
        return None
    h = hashlib.sha256()
    for s in seeds:
        if len(s) > MAX_SEED_LEN:
            return None
        h.update(s)
    h.update(program_id)
    h.update(b"ProgramDerivedAddress")
    out = h.digest()
    from firedancer_tpu.ops.ed25519 import golden

    if golden.point_decompress(out) is not None:
        return None  # on-curve: not a valid PDA
    return out


def find_program_address(seeds, program_id: bytes):
    """(address, bump) with the canonical descending bump search."""
    for bump in range(255, -1, -1):
        pda = create_program_address(list(seeds) + [bytes([bump])], program_id)
        if pda is not None:
            return pda, bump
    return None


def classify_record(raw: bytes | None) -> tuple[int, int]:
    """THE table-executability rule, shared by every fast path:
    -> (BankTable.ST_*, lamports).  TRIVIAL means a header-only
    system-owned account with no executable/rent bits — exactly what
    the native table (and the python fast path's lamports cache) may
    hold; anything else is NONTRIVIAL and must execute generally."""
    if raw is None:
        return BankTable.ST_ABSENT, 0
    if len(raw) != _HDR.size:
        return BankTable.ST_NONTRIVIAL, 0
    lam, owner, execu, rent = _HDR.unpack(raw)
    if owner != SYSTEM_PROGRAM_ID or execu or rent:
        return BankTable.ST_NONTRIVIAL, 0
    return BankTable.ST_TRIVIAL, lam


class BankTable:
    """Shared-memory native account table + per-bank undo journal — the
    host-side handle on tango/native/fdt_bank.c.

    The table region lives in the topology workspace so every bank tile
    (thread or process) shards over ONE table and it survives SIGKILL
    restarts; the 256-byte journal region is per-bank (tile arena) and
    makes each txn's slot writes atomic across a crash.  Funk remains
    the system of record: `commit()` drains entries funk has not seen
    yet (per-slot version words) with the existing lam_cache
    invalidation discipline, and `recover()` is the restart protocol
    (roll back a half-applied txn, then drain everything pending).

    Only TRIVIAL system accounts (header-only, system-owned, no
    executable/rent bits) are table-executable; other accounts are
    cached as NONTRIVIAL markers so the executor can stop and fall back
    per txn.  See Executor.execute_fast_transfers_native."""

    ST_EMPTY, ST_BUSY, ST_TRIVIAL, ST_NONTRIVIAL, ST_ABSENT = range(5)
    #: per-txn exec statuses (fdt_bank.h FDT_BANK_*)
    OK, FAIL, REJECT, MISS, NONTRIV = range(5)
    JOURNAL_BYTES = 256
    _DRAIN_MAX = 4096

    def __init__(self, mem, slot_cnt: int, journal=None):
        import numpy as np

        from firedancer_tpu.tango import rings as R

        self.lib = R._lib
        self.mem = mem
        assert mem.flags["C_CONTIGUOUS"]
        rc = self.lib.fdt_bank_tab_new(mem.ctypes.data, slot_cnt)
        if rc < 0:
            raise ValueError(
                f"fdt_bank table init failed (slot_cnt={slot_cnt}; power "
                f"of two, geometry must match an existing table)"
            )
        self.rejoined = bool(rc)
        self.slot_cnt = slot_cnt
        if journal is None:
            journal = np.zeros(self.JOURNAL_BYTES, np.uint8)
        self.journal = journal
        self._jw = journal[: self.JOURNAL_BYTES].view(np.uint64)
        # commit drain scratch (reused across calls)
        self._dk = np.zeros((self._DRAIN_MAX, 32), np.uint8)
        self._dl = np.zeros(self._DRAIN_MAX, np.uint64)
        self._ds = np.zeros(self._DRAIN_MAX, np.uint8)
        self._dslot = np.zeros(self._DRAIN_MAX, np.uint64)
        self._dver = np.zeros(self._DRAIN_MAX, np.uint64)
        self._g1 = np.zeros(1, np.uint64)  # get() out scratch

    @classmethod
    def footprint(cls, slot_cnt: int) -> int:
        from firedancer_tpu.tango import rings as R

        fp = int(R._lib.fdt_bank_tab_footprint(slot_cnt))
        if not fp:
            raise ValueError(f"bad bank table slot_cnt {slot_cnt}")
        return fp

    # -- key ops ----------------------------------------------------------
    # key bytes pass straight through the c_void_p args (no per-call
    # numpy marshalling — the batch cold-resolve path calls these for
    # every key of every remaining txn)

    def get(self, key: bytes) -> tuple[int, int]:
        """-> (state, lamports); lamports meaningful for ST_TRIVIAL."""
        st = self.lib.fdt_bank_tab_get(
            self.mem.ctypes.data, key, self._g1.ctypes.data
        )
        return int(st), int(self._g1[0])

    def put(self, key: bytes, state: int, lamports: int = 0,
            dirty: bool = False) -> bool:
        """Upsert; False when the table is full (caller falls back)."""
        return (
            self.lib.fdt_bank_tab_put(
                self.mem.ctypes.data, key, state, lamports, int(dirty)
            )
            == 0
        )

    def resolve(self, funk, xid: bytes, key: bytes) -> int:
        """Classify the funk record for `key` into the table (marked
        funk-synced).  Returns the state cached, or ST_EMPTY when the
        table is full."""
        st, lam = classify_record(funk.rec_read(xid, key))
        if not self.put(key, st, lam):
            return self.ST_EMPTY
        return st

    # -- microblock journal ----------------------------------------------

    #: python-owned journal word (past the C undo area): seq AFTER the
    #: last fully-completed microblock tag, 0 = none yet.  Frag seqs are
    #: monotonic per link, so a redelivered microblock below this mark
    #: was applied in full by a previous incarnation and must be SKIPPED
    #: — the supervisor replay window spans many microblocks, and the
    #: (tag, done) pair above only protects the last one.
    _JW_COMPLETED = 31

    def begin(self, tag: int) -> int:
        """Adopt a microblock: returns the txns a previous incarnation
        already applied under this tag (0 for a fresh microblock)."""
        if int(self._jw[0]) == tag:
            return int(self._jw[1])
        # done first, tag last: a kill between the stores must never
        # leave (new tag, stale done) — that resume would skip txns
        self._jw[1] = 0
        self._jw[0] = tag
        return 0

    def mark_done(self, tag: int, done: int) -> None:
        """Record python-side (fallback/slow) txn completion so a
        restart resumes after it."""
        if int(self._jw[0]) == tag:
            self._jw[1] = done

    def mark_complete(self, tag: int) -> None:
        """Record a fully-executed microblock: replay below this seq
        re-publishes (completion frees pack) but never re-executes."""
        from firedancer_tpu.tango.rings import seq_u64

        self._jw[self._JW_COMPLETED] = seq_u64(tag + 1)

    def already_complete(self, tag: int) -> bool:
        from firedancer_tpu.tango.rings import seq_lt

        v = int(self._jw[self._JW_COMPLETED])
        return v != 0 and seq_lt(tag, v)

    # -- funk write-back --------------------------------------------------

    def commit(self, funk, xid: bytes = ROOT_XID) -> int:
        """Drain every entry funk has not seen into funk records, with
        the lam_cache discipline the python fast path keeps (rec_write
        invalidates; the fresh decode is re-cached).  Returns entries
        written back."""
        pack = _HDR.pack
        absent = self.ST_ABSENT
        total = 0
        while True:
            got = int(
                self.lib.fdt_bank_commit(
                    self.mem.ctypes.data, self._dk.ctypes.data,
                    self._dl.ctypes.data, self._ds.ctypes.data,
                    self._dslot.ctypes.data, self._dver.ctypes.data,
                    self._DRAIN_MAX,
                )
            )
            if got:
                keys = [self._dk[m].tobytes() for m in range(got)]
                lams = self._dl[:got].tolist()
                sts = self._ds[:got].tolist()
                funk.rec_write_many(
                    xid,
                    (
                        (
                            keys[m],
                            None if sts[m] == absent
                            else pack(lams[m], SYSTEM_PROGRAM_ID, 0, 0),
                        )
                        for m in range(got)
                    ),
                )
                if xid == ROOT_XID:
                    # re-warm the cache the write-back just invalidated
                    funk.lam_cache.update(
                        (keys[m], lams[m])
                        for m in range(got)
                        if sts[m] != absent
                    )
                # funk has the records: NOW retire the drained versions
                # (a kill before this ack re-drains them — funk write-
                # back is idempotent, so at-least-once is lossless)
                self.lib.fdt_bank_commit_ack(
                    self.mem.ctypes.data, self._dslot.ctypes.data,
                    self._dver.ctypes.data, got,
                )
            total += got
            if got < self._DRAIN_MAX:
                return total

    def recover(self, funk, xid: bytes = ROOT_XID) -> tuple[int, int, bool]:
        """Restart protocol: roll back a half-applied txn (undo journal)
        and drain everything pending into funk.  Returns (microblock
        tag, txns done under it, rolled_back) so the tile can resume a
        redelivered microblock exactly once."""
        import numpy as np

        out = np.zeros(2, np.uint64)
        rolled = bool(
            self.lib.fdt_bank_recover(
                self.mem.ctypes.data, self.journal.ctypes.data,
                out.ctypes.data,
            )
        )
        self.commit(funk, xid)
        return int(out[0]), int(out[1]), rolled


class Executor:
    """Executes parsed transactions against a funk fork."""

    def __init__(self, funk: Funk, xid: bytes = ROOT_XID):
        from firedancer_tpu.flamenco.features import Features

        self.funk = funk
        self.xid = xid
        self.mgr = AccountMgr(funk, xid)
        self.slot = 0  # bank slot (ALT create derivation, deactivation)
        #: runtime behavior switches (reference: fd_features_t); dev
        #: default is all-enabled, overridden by on-chain feature
        #: accounts at each slot boundary
        self.features = Features.all_enabled()
        #: most recent blockhash (durable-nonce derivation; the bank
        #: feeds the PoH state in via begin_slot)
        self.recent_blockhash = bytes(32)
        #: lamports/sig recorded into initialized nonce accounts
        self.lamports_per_signature = FEE_PER_SIGNATURE
        self._slot_hashes = None  # sysvar.SlotHashes, built lazily
        #: static + ALT-resolved keys of the last execute_txn call — the
        #: bank's table<->funk resync reads it (execute_txn_with_table)
        self.last_touched: list[bytes] = []
        #: txns of the last execute_fast_transfers_native call that ran
        #: through the per-txn general-executor fallback (the bank tile
        #: subtracts these from its native_txns metric)
        self.last_fallbacks = 0

    def begin_slot(self, slot: int, unix_timestamp: int = 0,
                   blockhash: bytes | None = None) -> None:
        """Advance the bank slot: refresh the sysvar accounts
        (reference: fd_sysvar_clock_update at slot start).  blockhash
        is the previous slot's bank/PoH hash; it extends the slot-hashes
        history (fd_sysvar_slot_hashes.c slot_hashes_update) and drives
        durable-nonce derivation."""
        import hashlib

        from firedancer_tpu.flamenco import sysvar
        from firedancer_tpu.flamenco.features import Features

        if self._slot_hashes is None:
            self._slot_hashes = sysvar.SlotHashes()
        prev = self.slot
        self.slot = slot
        if blockhash is None:
            # deterministic stand-in chain when no PoH state is wired
            blockhash = hashlib.sha256(
                b"fdt-blockhash" + slot.to_bytes(8, "little")
            ).digest()
        if slot > prev:
            # one entry per slot in (prev, slot), newest last-added: the
            # reference's sysvar covers every slot (consecutive on
            # mainnet) — a sparse bank clock must not leave holes, or a
            # table deactivated in a skipped slot would read as expired
            # immediately (fd_sysvar_slot_hashes.c slot_hashes_update)
            lo = max(prev, slot - sysvar.SLOT_HASHES_MAX)
            for s in range(lo, slot):
                h = (
                    self.recent_blockhash if s == prev
                    else hashlib.sha256(
                        b"fdt-slot" + s.to_bytes(8, "little")
                    ).digest()
                )
                self._slot_hashes.add(s, h)
        self.recent_blockhash = blockhash
        sysvar.install(
            self.mgr, slot, unix_timestamp=unix_timestamp,
            slot_hashes=self._slot_hashes,
            recent_blockhashes=sysvar.RecentBlockhashes(
                [(blockhash, self.lamports_per_signature)]
            ),
        )
        # refresh the feature table from the account database
        # (reference: fd_features derive from feature accounts)
        self.features = Features.from_accounts(
            self.mgr.load, default=self.features
        )

    # ---- address lookup tables ------------------------------------------

    def _resolve_alts(self, payload: bytes, desc: T.TxnDesc):
        """-> list of resolved keys (writables then readonlys), or an
        error string.  Reference behavior: fd_runtime load of v0 message
        addresses via the ALT program's on-chain tables."""
        writable: list[bytes] = []
        readonly: list[bytes] = []
        for lut in desc.address_tables:
            table_key = payload[lut.addr_off : lut.addr_off + 32]
            acct = self.mgr.load(table_key)
            if acct is None or acct.owner != ALT_PROGRAM_ID:
                return "alt: table account missing"
            if len(acct.data) >= ALT_HEADER_SZ:
                deact = int.from_bytes(acct.data[4:12], "little")
                if deact != ALT_DEACT_NONE and self._alt_fully_deactivated(
                    deact
                ):
                    return "alt: table deactivated"
            addrs = alt_addresses(acct.data)
            if addrs is None:
                return "alt: malformed table"
            for off, cnt, out in (
                (lut.writable_off, lut.writable_cnt, writable),
                (lut.readonly_off, lut.readonly_cnt, readonly),
            ):
                for j in range(cnt):
                    idx = payload[off + j]
                    if idx >= len(addrs):
                        return "alt: index out of range"
                    out.append(addrs[idx])
        return writable + readonly

    def _alt_fully_deactivated(self, deact_slot: int) -> bool:
        """A deactivating table serves lookups while its deactivation
        slot is still in the slot-hashes history (reference: the table
        status is derived from the SlotHashes sysvar,
        fd_address_lookup_table_program.c); the fixed cooldown is the
        fallback when no history exists yet (early tests, forked
        executors that never ran begin_slot)."""
        from firedancer_tpu.flamenco import sysvar

        if deact_slot == self.slot:
            return False  # deactivated this slot: still usable
        acct = self.mgr.load(sysvar.SLOT_HASHES_ID)
        if acct is not None and acct.data:
            return not sysvar.SlotHashes.decode(acct.data).contains_slot(
                deact_slot
            )
        return self.slot >= deact_slot + ALT_DEACT_COOLDOWN

    # ---- entry points ---------------------------------------------------

    def execute_txn(self, payload: bytes, desc: T.TxnDesc | None = None) -> TxnResult:
        desc = desc or T.parse(payload)
        if desc is None:
            return TxnResult(False, "parse")
        if desc.transaction_version != T.VLEGACY and not self.features.active(
            "versioned_tx_message_enabled", self.slot
        ):
            return TxnResult(False, "versioned transactions not enabled")
        keys = [
            bytes(desc.acct_addr(payload, j))
            for j in range(desc.acct_addr_cnt)
        ]
        if desc.addr_table_adtl_cnt > 0:
            # v0: resolve address-table lookups against on-chain ALT
            # accounts (message ordering: static keys, then all writable
            # lookups, then all readonly lookups)
            resolved = self._resolve_alts(payload, desc)
            if isinstance(resolved, str):
                return TxnResult(False, resolved)
            keys += resolved
        self.last_touched = keys
        fee = FEE_PER_SIGNATURE * desc.signature_cnt

        payer = self.mgr.load(keys[0])
        if payer is None or payer.lamports < fee:
            return TxnResult(False, "insufficient fee payer", fee=0)
        payer.lamports -= fee
        self.mgr.store(keys[0], payer)

        # execute instructions against a scratch overlay so a failed txn
        # rolls back its writes but keeps the fee debit
        overlay: dict[bytes, Account | None] = {}

        def load(k: bytes) -> Account | None:
            if k in overlay:
                a = overlay[k]
                return None if a is None else Account(**vars(a))
            return self.mgr.load(k)

        def store(k: bytes, a: Account) -> None:
            overlay[k] = a

        logs: list = []
        meter = [TXN_CU_BUDGET]
        txn_signers = frozenset(keys[: desc.signature_cnt])
        for ins in desc.instr:
            prog_key = keys[ins.program_id]
            data = payload[ins.data_off : ins.data_off + ins.data_sz]
            ins_idx = [payload[ins.acct_off + j] for j in range(ins.acct_cnt)]
            ins_keys = [keys[j] for j in ins_idx]
            ctx = InstrCtx(
                frozenset(k for k in ins_keys if k in txn_signers),
                frozenset(
                    k for j, k in zip(ins_idx, ins_keys)
                    if desc.is_writable(j)
                ),
                meter=meter,
                txn=(payload, desc),
            )
            err = self._dispatch(prog_key, data, ins_keys, ctx, load, store, logs)
            if err:
                return TxnResult(
                    False, err, fee=fee, logs=logs,
                    cu_used=TXN_CU_BUDGET - meter[0],
                )
        for k, a in overlay.items():
            if a is not None:
                self.mgr.store(k, a)
        return TxnResult(
            True, fee=fee, logs=logs, cu_used=TXN_CU_BUDGET - meter[0]
        )

    # ---- batched fast path ----------------------------------------------

    def execute_fast_transfers(
        self, payloads, fees, amounts, payer_offs, src_offs, dst_offs
    ) -> tuple[int, int, int]:
        """Execute a batch of scan-classified simple transfers (legacy
        txns whose only non-compute-budget instruction is one system
        transfer with a writable-signer source — fdt_txn_scan `fast`)
        against the funk lamports cache, skipping the per-txn overlay
        machinery.  Semantics are EXACTLY execute_txn's for this txn
        class (fee-then-execute, failed transfer keeps the fee,
        self-transfer no-op, dst account creation); any account that is
        not a trivial system account falls back to execute_txn.

        This is the reference's answer to bank throughput, re-shaped: it
        executes via a batched external engine rather than the tile's own
        interpreter loop (fd_bank.c:100-104 fd_ext_bank_load_and_execute
        _txns); here the "external engine" is the native scan + this
        allocation-free loop over the shared lamports cache.

        Returns (fees_collected, executed_cnt, failed_cnt)."""
        funk = self.funk
        # the lamports cache is coherent ONLY over the published root fork
        # (funk invalidates it on every root mutation; writes into in-prep
        # txns bypass that) — a forked executor runs uncached
        cache = funk.lam_cache if self.xid == ROOT_XID else {}
        rec_read = funk.rec_read
        rec_write = funk.rec_write
        xid = self.xid
        hdr_pack = _HDR.pack
        zero_check = self.features.active(
            "system_transfer_zero_check", self.slot
        )
        ABSENT, NONTRIVIAL = -1, -2

        def lam_of(key: bytes) -> int:
            v = cache.get(key)
            if v is not None:
                return v
            # one classification rule for every fast path (the native
            # table's resolve uses the same helper)
            st, lam = classify_record(rec_read(xid, key))
            if st == BankTable.ST_ABSENT:
                return ABSENT
            if st == BankTable.ST_NONTRIVIAL:
                return NONTRIVIAL
            cache[key] = lam
            return lam

        def put(key: bytes, lam: int) -> None:
            rec_write(xid, key, hdr_pack(lam, SYSTEM_PROGRAM_ID, 0, 0))
            cache[key] = lam

        fees_total = 0
        executed = 0
        failed = 0
        for t in range(len(payloads)):
            p = payloads[t]
            po, so, do = payer_offs[t], src_offs[t], dst_offs[t]
            payer = p[po : po + 32]
            fee = fees[t]
            amt = amounts[t]
            pl = lam_of(payer)
            if pl == NONTRIVIAL:
                r = self.execute_txn(p)
                if xid != ROOT_XID:
                    # funk only invalidates its root lam_cache on
                    # writes; the fork-local dict must drop whatever
                    # the general executor just rewrote
                    cache.clear()
                fees_total += r.fee
                executed += 1
                failed += not r.ok
                continue
            if pl < fee:  # ABSENT or underfunded: txn rejected, no fee
                failed += 1
                executed += 1
                continue
            executed += 1
            fees_total += fee
            # per-txn mini-overlay: duplicate keys (dst aliasing the
            # payer, etc.) must observe earlier writes exactly like the
            # slow path's sequential load/store sequence
            vals: dict = {payer: pl - fee}
            src = payer if so == po else p[so : so + 32]
            sl = vals.get(src)
            if sl is None:
                sl = lam_of(src)
            if sl == NONTRIVIAL:
                # fall back BEFORE committing (execute_txn redoes the fee)
                fees_total -= fee
                r = self.execute_txn(p)
                if xid != ROOT_XID:
                    cache.clear()  # see the payer-fallback note above
                fees_total += r.fee
                failed += not r.ok
                continue
            if sl == ABSENT:
                # missing source: pre-feature a 0-lamport transfer is a
                # silent no-op; post-feature it is "insufficient funds"
                if not (amt == 0 and not zero_check):
                    failed += 1
                put(payer, pl - fee)  # fee kept, transfer rolled back
                continue
            if sl < amt:
                failed += 1
                put(payer, pl - fee)
                continue
            dst = p[do : do + 32]
            if src == dst:
                put(payer, pl - fee)  # self-transfer no-op; fee applies
                continue
            vals[src] = sl - amt
            dl = vals.get(dst)
            if dl is None:
                dl = lam_of(dst)
            if dl == NONTRIVIAL:
                # dst holds data/another owner: credit the full record
                # via the account manager, commit the rest as trivials
                a = self.mgr.load(dst)
                a.lamports += amt
                for k, v in vals.items():
                    put(k, v)
                self.mgr.store(dst, a)
                continue
            if dl == ABSENT:
                dl = 0
            vals[dst] = dl + amt
            for k, v in vals.items():
                put(k, v)
        return fees_total, executed, failed

    # ---- native batched fast path (fdt_bank) ----------------------------

    def execute_fast_transfers_native(
        self, table, rows, szs, idx, scan, tag: int = 0, start: int = 0
    ) -> tuple[int, int, int]:
        """Execute the scan-classified fast-transfer subset `idx` of
        `rows` through the native shared-memory executor
        (tango/native/fdt_bank.c fdt_bank_exec): one GIL-released C call
        applies the whole run, stopping only at a txn the table cannot
        represent.  Stops are handled here IN ORDER — a cache MISS
        batch-resolves every remaining key from funk and retries; a
        NONTRIVIAL account runs that one txn through the general
        executor (with table<->funk coherence, execute_txn_with_table)
        and the batch resumes after it — so the observable semantics
        stay exactly execute_fast_transfers', which is pinned to
        execute_txn by tests/test_bank_fast.py + test_bank_native.py.

        `tag` names the microblock (the carrying frag's seq) for the
        crash-resume journal; `start` skips txns a previous incarnation
        already applied.  Returns (fees_collected, executed, failed);
        table mutations stay pending for BankTable.commit()."""
        import numpy as np

        lib = table.lib
        n = len(idx)
        if start >= n:
            return 0, 0, 0
        idx64 = np.ascontiguousarray(idx, np.int64)
        status = np.zeros(n, np.uint8)
        ofees = np.zeros(n, np.uint64)
        zero_check = int(
            self.features.active("system_transfer_zero_check", self.slot)
        )
        fees = executed = failed = 0
        t = int(start)
        resolved = False
        self.last_fallbacks = 0
        while t < n:
            done = lib.fdt_bank_exec(
                rows.ctypes.data, rows.shape[1], idx64.ctypes.data, t, n,
                scan.payer_off.ctypes.data, scan.src_off.ctypes.data,
                scan.dst_off.ctypes.data, scan.fee.ctypes.data,
                scan.lamports.ctypes.data, table.mem.ctypes.data,
                table.journal.ctypes.data, tag, zero_check,
                status.ctypes.data, ofees.ctypes.data,
            )
            if done > t:
                executed += done - t
                failed += int(np.count_nonzero(status[t:done]))
                fees += int(ofees[t:done].sum())
                t = done
            if t >= n:
                break
            st = int(status[t])
            if st == BankTable.MISS and not resolved:
                # cold keys: resolve the whole remaining subset from
                # funk in ONE pass — a later MISS can then only mean the
                # table is full, which falls back below (re-resolving
                # per stop would make a full table O(n^2))
                resolved = True
                self._bank_resolve(table, rows, idx64[t:], scan)
                continue
            # NONTRIVIAL account (or a miss the table could not absorb,
            # e.g. full): the general executor runs this one txn in
            # sequence, then the native batch resumes after it
            i = int(idx64[t])
            r = self.execute_txn_with_table(
                table, rows[i, : szs[i]].tobytes()
            )
            fees += r.fee
            executed += 1
            failed += not r.ok
            self.last_fallbacks += 1
            t += 1
            table.mark_done(tag, t)
        return fees, executed, failed

    def _bank_resolve(self, table, rows, sub_idx, scan) -> None:
        """Classify every uncached payer/src/dst key of the remaining
        subset txns from funk into the table (TRIVIAL lamports,
        NONTRIVIAL marker, or known-ABSENT).  A full table is tolerated:
        the executor stops again and the txn falls back."""
        for t in sub_idx:
            t = int(t)
            for off in (
                int(scan.payer_off[t]), int(scan.src_off[t]),
                int(scan.dst_off[t]),
            ):
                key = rows[t, off : off + 32].tobytes()
                if table.get(key)[0] == BankTable.ST_EMPTY:
                    table.resolve(self.funk, self.xid, key)

    def execute_txn_with_table(self, table, payload: bytes) -> TxnResult:
        """General-executor escape hatch for a txn scheduled into the
        native path: flush the txn's table-held accounts into funk first
        (the table is authoritative for TRIVIAL entries and funk may lag
        a commit), run execute_txn, then resync every touched key back
        into the table (update-only: keys the table never cached stay
        uncached).  Pack's account locks are still held by this
        microblock, so no other bank can race the flush/resync."""
        desc = T.parse(payload)
        if desc is not None:
            keys = [
                bytes(desc.acct_addr(payload, j))
                for j in range(desc.acct_addr_cnt)
            ]
            if desc.addr_table_adtl_cnt > 0:
                # ALT-resolved keys can be trivial table-held accounts
                # too: flushing only the static keys would let the
                # general executor read a stale funk balance (and the
                # resync below would then clobber the table with it)
                resolved = self._resolve_alts(payload, desc)
                if not isinstance(resolved, str):
                    keys += resolved
            for k in keys:
                st, lam = table.get(k)
                if st == BankTable.ST_TRIVIAL:
                    self.funk.rec_write(
                        self.xid, k, _HDR.pack(lam, SYSTEM_PROGRAM_ID, 0, 0)
                    )
                    if self.xid == ROOT_XID:
                        self.funk.lam_cache[k] = lam
                elif st == BankTable.ST_ABSENT:
                    self.funk.rec_remove(self.xid, k)
        self.last_touched = []
        r = self.execute_txn(payload, desc)
        for k in self.last_touched:
            st, _ = table.get(k)
            if st not in (BankTable.ST_EMPTY, BankTable.ST_BUSY):
                table.resolve(self.funk, self.xid, k)
        return r

    # ---- dispatch -------------------------------------------------------

    def _dispatch(self, prog_key, data, ins_keys, ctx: InstrCtx, load, store,
                  logs) -> str:
        if ctx.depth >= MAX_INVOKE_STACK:
            return "max invoke stack depth"
        ctx = InstrCtx(
            ctx.signers, ctx.writables, ctx.stack + (prog_key,),
            ctx.meter, ctx.txn,
        )
        if prog_key == SYSTEM_PROGRAM_ID:
            return self._system(data, ins_keys, ctx, load, store)
        if prog_key == ALT_PROGRAM_ID:
            return self._alt_program(data, ins_keys, ctx, load, store)
        if prog_key == CONFIG_PROGRAM_ID:
            return self._config_program(data, ins_keys, ctx, load, store)
        if prog_key == ED25519_PROGRAM_ID:
            if not self.features.active("ed25519_program_enabled", self.slot):
                return "unknown program"
            return self._ed25519_program(data, ctx)
        if prog_key == SECP256K1_PROGRAM_ID:
            if not self.features.active(
                "secp256k1_program_enabled", self.slot
            ):
                return "unknown program"
            return self._secp256k1_program(data, ctx)
        if prog_key == LOADER_V4_ID:
            return self._loader_v4(data, ins_keys, ctx, load, store)
        prog = load(prog_key)
        if prog is not None and prog.owner == BPF_LOADER_ID and prog.executable:
            return self._bpf(
                prog, prog_key, data, ins_keys, ctx, load, store, logs
            )
        if prog is not None and prog.owner == LOADER_V4_ID:
            # a loader-v4 program account: ELF bytes follow the 48-byte
            # state header; only DEPLOYED programs execute
            st = _v4_state(prog.data)
            if st is None or st[2] == _V4_RETRACTED:
                return "program not deployed"
            return self._bpf(
                prog, prog_key, data, ins_keys, ctx, load, store, logs,
                elf=bytes(prog.data[LOADER_V4_STATE_SZ:]),
            )
        return "unknown program"


    def _alt_program(self, data, ins_keys, ctx: InstrCtx, load, store) -> str:
        """Address-lookup-table native program: create / freeze / extend /
        deactivate (fd_address_lookup_table_program.c behavior, simplified:
        no PDA derivation check — the table address is the account given)."""
        if len(data) < 4:
            return "alt: bad instruction"
        disc = int.from_bytes(data[:4], "little")
        if disc == _ALT_CREATE:
            if len(ins_keys) < 2:
                return "alt: bad create"
            table_k, auth_k = ins_keys[0], ins_keys[1]
            if auth_k not in ctx.signers:
                return "alt: missing authority signature"
            if table_k not in ctx.writables:
                return "alt: table not writable"
            if load(table_k) is not None:
                return "alt: account exists"
            hdr = _ALT_HDR.pack(
                _ALT_DISC_TABLE, ALT_DEACT_NONE, 0, 0, 1, auth_k, 0
            )
            # lamport conservation: the table starts unfunded; rent is the
            # caller's business (system-transfer to it), never minted here
            store(table_k, Account(0, ALT_PROGRAM_ID, False, 0, hdr))
            return ""
        # remaining instructions operate on an existing live table with
        # the authority as the second account
        if len(ins_keys) < 2:
            return "alt: bad instruction accounts"
        table_k, auth_k = ins_keys[0], ins_keys[1]
        if table_k not in ctx.writables:
            return "alt: table not writable"
        acct = load(table_k)
        if acct is None or acct.owner != ALT_PROGRAM_ID:
            return "alt: no table"
        disc0, deact, last_slot, last_idx, has_auth, auth, _pad = (
            _ALT_HDR.unpack_from(acct.data)
        )
        if disc0 != _ALT_DISC_TABLE:
            return "alt: malformed table"
        if not has_auth:
            return "alt: frozen"
        if auth != auth_k or auth_k not in ctx.signers:
            return "alt: bad authority"
        if disc == _ALT_FREEZE:
            if deact != ALT_DEACT_NONE:
                return "alt: deactivated tables cannot be frozen"
            acct.data = (
                _ALT_HDR.pack(
                    _ALT_DISC_TABLE, deact, last_slot, last_idx, 0,
                    bytes(32), 0,
                )
                + acct.data[ALT_HEADER_SZ:]
            )
            store(table_k, acct)
            return ""
        if disc == _ALT_EXTEND:
            if deact != ALT_DEACT_NONE:
                return "alt: deactivated"
            if len(data) < 12:
                return "alt: bad extend"
            n = int.from_bytes(data[4:12], "little")
            if n == 0:
                return "alt: empty extend"
            if len(data) < 12 + 32 * n:
                return "alt: bad extend"
            existing = (len(acct.data) - ALT_HEADER_SZ) // 32
            if existing + n > 256:
                return "alt: table full"
            new_addrs = data[12 : 12 + 32 * n]
            acct.data = (
                _ALT_HDR.pack(
                    _ALT_DISC_TABLE, deact, self.slot, existing, 1, auth, 0
                )
                + acct.data[ALT_HEADER_SZ:]
                + new_addrs
            )
            store(table_k, acct)
            return ""
        if disc == _ALT_DEACTIVATE:
            if deact != ALT_DEACT_NONE:
                return "alt: already deactivated"
            acct.data = (
                _ALT_HDR.pack(
                    _ALT_DISC_TABLE, self.slot, last_slot, last_idx, 1,
                    auth, 0,
                )
                + acct.data[ALT_HEADER_SZ:]
            )
            store(table_k, acct)
            return ""
        return "alt: unsupported instruction"

    def _config_program(self, data, ins_keys, ctx: InstrCtx, load,
                        store) -> str:
        """Config native program (reference fd_config_program.c /
        config_processor.rs): instruction data = short_vec ConfigKeys
        (pubkey, is_signer u8) followed by opaque config payload, stored
        into the config account (no realloc).  Every listed signer key
        must have signed; previously stored signer keys must re-sign
        every update (simplified: the deserialize-and-compare core,
        without the account-data-as-current-signers edge cases)."""
        if len(ins_keys) < 1:
            return "config: missing account"
        cfg_k = ins_keys[0]
        acct = load(cfg_k)
        if acct is None or acct.owner != CONFIG_PROGRAM_ID:
            return "config: bad account owner"
        if cfg_k not in ctx.writables:
            return "config: account not writable"

        def parse_keys(buf):
            if not buf:
                return None
            n, off = buf[0], 1  # short_vec length (single-byte for <128)
            if n & 0x80:
                return None  # >127 keys unsupported (reference caps too)
            out = []
            for _ in range(n):
                if off + 33 > len(buf):
                    return None
                out.append((buf[off:off + 32], buf[off + 32] != 0))
                off += 33
            return out

        new_keys = parse_keys(data)
        if new_keys is None:
            return "config: bad instruction data"
        stored_keys = parse_keys(acct.data) or []
        cfg_signed = cfg_k in ctx.signers
        for pk, is_signer in new_keys:
            if not is_signer:
                continue
            if pk == cfg_k:
                if not cfg_signed:
                    return "config: config account must sign"
            elif pk not in ctx.signers:
                return "config: missing signer " + pk.hex()[:8]
        # stored signers must approve every update (the config account
        # satisfies its own entry only by actually signing)
        for pk, was_signer in stored_keys:
            if not was_signer:
                continue
            if pk == cfg_k:
                if not cfg_signed:
                    return "config: config account must sign"
            elif pk not in ctx.signers:
                return "config: stored signer did not sign"
        if not stored_keys and not cfg_signed:
            return "config: config account must sign"
        if len(data) > len(acct.data):
            return "config: instruction data too large"
        acct.data = bytes(data) + acct.data[len(data):]
        store(cfg_k, acct)
        return ""

    def _ed25519_program(self, data, ctx: InstrCtx) -> str:
        """Ed25519 precompile (reference fd_ed25519_program.c): the
        instruction data carries u8 count + 14-byte offset records
        pointing at sig/pubkey/msg bytes inside this or any other
        instruction's data (0xFFFF = this instruction); every referenced
        signature must verify or the whole txn fails."""
        from firedancer_tpu.ops.ed25519 import golden

        if len(data) < 2:
            return "ed25519: bad instruction data"
        count = data[0]

        def instr_data(idx: int):
            if idx == 0xFFFF:
                return data
            if ctx.txn is None:
                return None
            payload, desc = ctx.txn
            if idx >= desc.instr_cnt:
                return None
            ins = desc.instr[idx]
            return payload[ins.data_off : ins.data_off + ins.data_sz]

        off = 2
        for _ in range(count):
            if off + 14 > len(data):
                return "ed25519: bad offsets"
            (sig_off, sig_ix, pk_off, pk_ix, msg_off, msg_sz, msg_ix
             ) = struct.unpack_from("<7H", data, off)
            off += 14
            parts = []
            for d_ix, d_off, d_sz in (
                (sig_ix, sig_off, 64), (pk_ix, pk_off, 32),
                (msg_ix, msg_off, msg_sz),
            ):
                src = instr_data(d_ix)
                if src is None or d_off + d_sz > len(src):
                    return "ed25519: data offsets out of range"
                parts.append(bytes(src[d_off : d_off + d_sz]))
            sig, pk, msg = parts
            if golden.verify(msg, sig, pk) != 0:
                return "ed25519: invalid signature"
        return ""

    def _v4_check_program(self, ins_keys, ctx: InstrCtx, load):
        """check_program_account (fd_bpf_loader_v4_program.c:43-104):
        -> (account, state, authority_key) or an error string."""
        if len(ins_keys) < 2:
            return "v4: not enough accounts"
        prog_k, auth_k = ins_keys[0], ins_keys[1]
        acct = load(prog_k)
        if acct is None or acct.owner != LOADER_V4_ID:
            return "v4: program not owned by loader"
        if len(acct.data) == 0:
            return "v4: program is uninitialized"
        st = _v4_state(acct.data)
        if st is None:
            return "v4: account data too small"
        if prog_k not in ctx.writables:
            return "v4: program account not writable"
        if auth_k not in ctx.signers:
            return "v4: authority did not sign"
        if st[1] != auth_k:
            return "v4: incorrect authority"
        if st[2] == _V4_FINALIZED:
            return "v4: program is finalized"
        return acct, st, auth_k

    def _loader_v4(self, data, ins_keys, ctx: InstrCtx, load, store) -> str:
        """BPF loader v4 meta-instructions: write / truncate / deploy /
        retract / transfer_authority (behavior contract:
        fd_bpf_loader_v4_program.c — write :166-232, truncate :234-264,
        deploy :366-560, retract :560-620, transfer_authority :623-680).
        Program bytes live after the 48-byte state header; deployment
        cooldown and status machine match the reference."""
        if len(data) < 4:
            return "v4: bad instruction"
        disc = int.from_bytes(data[:4], "little")

        if disc == _V4_WRITE:
            if len(data) < 16:
                return "v4: bad write"
            offset = int.from_bytes(data[4:8], "little")
            n = int.from_bytes(data[8:16], "little")
            if len(data) < 16 + n:
                return "v4: bad write"
            chk = self._v4_check_program(ins_keys, ctx, load)
            if isinstance(chk, str):
                return chk
            acct, st, _ = chk
            if st[2] != _V4_RETRACTED:
                return "v4: program is not retracted"
            body_sz = len(acct.data) - LOADER_V4_STATE_SZ
            if offset + n > body_sz:
                return "v4: write out of bounds"
            off = LOADER_V4_STATE_SZ + offset
            acct.data = (
                acct.data[:off] + bytes(data[16 : 16 + n])
                + acct.data[off + n :]
            )
            store(ins_keys[0], acct)
            return ""

        if disc == _V4_TRUNCATE:
            if len(data) < 8 or len(ins_keys) < 2:
                return "v4: bad truncate"
            new_sz = int.from_bytes(data[4:8], "little")
            prog_k, auth_k = ins_keys[0], ins_keys[1]
            acct = load(prog_k)
            if acct is None:
                return "v4: no program account"
            is_init = new_sz > 0 and len(acct.data) < LOADER_V4_STATE_SZ
            if is_init:
                if acct.owner != LOADER_V4_ID:
                    return "v4: program not owned by loader"
                if prog_k not in ctx.writables:
                    return "v4: program account not writable"
                if prog_k not in ctx.signers:
                    return "v4: program did not sign"
                if auth_k not in ctx.signers:
                    return "v4: authority did not sign"
            else:
                chk = self._v4_check_program(ins_keys, ctx, load)
                if isinstance(chk, str):
                    return chk
                acct, st, _ = chk
                if st[2] != _V4_RETRACTED:
                    return "v4: program is not retracted"
            required = (
                0 if new_sz == 0
                else rent_exempt_minimum(LOADER_V4_STATE_SZ + new_sz)
            )
            if acct.lamports < required:
                return "v4: insufficient lamports"
            if acct.lamports > required:
                # excess goes to the recipient account (index 2)
                if len(ins_keys) < 3:
                    return "v4: recipient missing"
                rcpt_k = ins_keys[2]
                if rcpt_k not in ctx.writables:
                    return "v4: recipient not writable"
                excess = acct.lamports - required
                rcpt = load(rcpt_k) or Account(0)
                acct.lamports -= excess
                rcpt.lamports += excess
                store(rcpt_k, rcpt)
            raw_new = (
                0 if new_sz == 0 else LOADER_V4_STATE_SZ + new_sz
            )
            if raw_new > MAX_DATA_LEN:
                return "v4: program too large"
            if raw_new > len(acct.data):
                acct.data = acct.data + bytes(raw_new - len(acct.data))
            else:
                acct.data = acct.data[:raw_new]
            if new_sz and is_init:
                acct.data = (
                    _v4_state_encode(0, auth_k, _V4_RETRACTED)
                    + acct.data[LOADER_V4_STATE_SZ:]
                )
            store(prog_k, acct)
            return ""

        if disc == _V4_DEPLOY:
            chk = self._v4_check_program(ins_keys, ctx, load)
            if isinstance(chk, str):
                return chk
            acct, st, auth_k = chk
            if st[0] + V4_DEPLOYMENT_COOLDOWN > self.slot:
                return "v4: deployment cooldown in effect"
            if st[2] != _V4_RETRACTED:
                return "v4: program is not retracted"
            source_k = ins_keys[2] if len(ins_keys) >= 3 else None
            if source_k is not None:
                src_chk = self._v4_check_program(
                    [source_k, auth_k], ctx, load
                )
                if isinstance(src_chk, str):
                    return src_chk
                src, src_st, _ = src_chk
                if src_st[2] != _V4_RETRACTED:
                    return "v4: source program is not retracted"
                # move the source's data region + top up rent
                transfer = max(
                    0, rent_exempt_minimum(len(src.data)) - acct.lamports
                )
                acct.data = bytes(src.data)
                src.data = b""
                src.lamports -= transfer
                acct.lamports += transfer
                store(source_k, src)
            if len(acct.data) < LOADER_V4_STATE_SZ:
                return "v4: account data too small"
            acct.data = (
                _v4_state_encode(self.slot, st[1], _V4_DEPLOYED)
                + acct.data[LOADER_V4_STATE_SZ:]
            )
            acct.executable = True
            store(ins_keys[0], acct)
            return ""

        if disc == _V4_RETRACT:
            chk = self._v4_check_program(ins_keys, ctx, load)
            if isinstance(chk, str):
                return chk
            acct, st, _ = chk
            if st[0] + V4_DEPLOYMENT_COOLDOWN > self.slot:
                return "v4: deployment cooldown in effect"
            if st[2] == _V4_RETRACTED:
                return "v4: program is not deployed"
            acct.data = (
                _v4_state_encode(st[0], st[1], _V4_RETRACTED)
                + acct.data[LOADER_V4_STATE_SZ:]
            )
            store(ins_keys[0], acct)
            return ""

        if disc == _V4_XFER_AUTH:
            chk = self._v4_check_program(ins_keys, ctx, load)
            if isinstance(chk, str):
                return chk
            acct, st, _ = chk
            new_auth = ins_keys[2] if len(ins_keys) >= 3 else None
            if new_auth is not None:
                if new_auth not in ctx.signers:
                    return "v4: new authority did not sign"
                acct.data = (
                    _v4_state_encode(st[0], new_auth, st[2])
                    + acct.data[LOADER_V4_STATE_SZ:]
                )
            elif st[2] == _V4_DEPLOYED:
                acct.data = (
                    _v4_state_encode(st[0], st[1], _V4_FINALIZED)
                    + acct.data[LOADER_V4_STATE_SZ:]
                )
            else:
                return "v4: program must be deployed to be finalized"
            store(ins_keys[0], acct)
            return ""
        return "v4: unsupported instruction"

    def _secp256k1_program(self, data, ctx: InstrCtx) -> str:
        """Keccak-secp256k1 precompile (the ed25519 precompile's sibling;
        behavior contract: Solana's secp256k1_program, account-less):
        data = u8 count, then count 11-byte offset records
        {sig_off u16, sig_ix u8, eth_addr_off u16, eth_addr_ix u8,
        msg_off u16, msg_sz u16, msg_ix u8}.  The signature field is 65
        bytes (r||s||recovery_id); verification recovers the pubkey from
        keccak256(msg) and compares keccak256(pubkey)[12:] against the
        20-byte eth address."""
        from firedancer_tpu.ballet import secp256k1 as K1
        from firedancer_tpu.ops.keccak256 import digest_host

        if len(data) < 1:
            return "secp256k1: bad instruction data"
        count = data[0]

        def instr_data(idx: int):
            if idx == 0xFF:
                return data
            if ctx.txn is None:
                return None
            payload, desc = ctx.txn
            if idx >= desc.instr_cnt:
                return None
            ins = desc.instr[idx]
            return payload[ins.data_off : ins.data_off + ins.data_sz]

        off = 1
        for _ in range(count):
            if off + 11 > len(data):
                return "secp256k1: bad offsets"
            sig_off, sig_ix = struct.unpack_from("<HB", data, off)
            ea_off, ea_ix = struct.unpack_from("<HB", data, off + 3)
            msg_off, msg_sz, msg_ix = struct.unpack_from(
                "<HHB", data, off + 6
            )
            off += 11
            parts = []
            for d_ix, d_off, d_sz in (
                (sig_ix, sig_off, 65), (ea_ix, ea_off, 20),
                (msg_ix, msg_off, msg_sz),
            ):
                src = instr_data(d_ix)
                if src is None or d_off + d_sz > len(src):
                    return "secp256k1: data offsets out of range"
                parts.append(bytes(src[d_off : d_off + d_sz]))
            sig65, eth_addr, msg = parts
            pub = K1.recover(digest_host(msg), sig65[:64], sig65[64])
            if pub is None or K1.eth_address(pub) != eth_addr:
                return "secp256k1: invalid signature"
        return ""

    def _system(self, data, ins_keys, ctx: InstrCtx, load, store) -> str:
        if len(data) < 4:
            return "bad system instruction"
        disc = int.from_bytes(data[:4], "little")
        if disc == _SYS_TRANSFER:
            if len(ins_keys) < 2 or len(data) < 12:
                return "bad transfer"
            lamports = int.from_bytes(data[4:12], "little")
            src_k, dst_k = ins_keys[0], ins_keys[1]
            if src_k not in ctx.signers:
                return "missing signature"
            if src_k not in ctx.writables or dst_k not in ctx.writables:
                return "account not writable"
            src = load(src_k)
            if src is None and lamports == 0 and not self.features.active(
                "system_transfer_zero_check", self.slot
            ):
                return ""  # pre-feature: 0-lamport from missing src is ok
            if src is None or src.lamports < lamports:
                return "insufficient funds"
            if src_k == dst_k:
                return ""  # self-transfer is a no-op (never mints)
            dst = load(dst_k) or Account(0)
            src.lamports -= lamports
            dst.lamports += lamports
            store(src_k, src)
            store(dst_k, dst)
            return ""
        if disc == _SYS_CREATE:
            if len(ins_keys) < 2 or len(data) < 52:
                return "bad create_account"
            lamports = int.from_bytes(data[4:12], "little")
            space = int.from_bytes(data[12:20], "little")
            if space > MAX_DATA_LEN:
                return "data length exceeds maximum"
            owner = data[20:52]
            src_k, new_k = ins_keys[0], ins_keys[1]
            if src_k not in ctx.signers or new_k not in ctx.signers:
                return "missing signature"
            if src_k not in ctx.writables or new_k not in ctx.writables:
                return "account not writable"
            if lamports < rent_exempt_minimum(space):
                return "rent: not exempt"
            src = load(src_k)
            if src is None or src.lamports < lamports:
                return "insufficient funds"
            if load(new_k) is not None:
                return "account exists"
            src.lamports -= lamports
            store(src_k, src)
            store(new_k, Account(lamports, owner, False, 0, bytes(space)))
            return ""
        if disc == _SYS_ASSIGN:
            if len(ins_keys) < 1 or len(data) < 36:
                return "bad assign"
            k = ins_keys[0]
            if k not in ctx.signers:
                return "missing signature"
            if k not in ctx.writables:
                return "account not writable"
            a = load(k)
            if a is None:
                return "no account"
            a.owner = data[4:36]
            store(k, a)
            return ""
        if disc in (
            _SYS_ADVANCE_NONCE, _SYS_WITHDRAW_NONCE, _SYS_INIT_NONCE,
            _SYS_AUTHORIZE_NONCE,
        ):
            return self._system_nonce(disc, data, ins_keys, ctx, load, store)
        if disc == _SYS_ALLOCATE:
            if len(ins_keys) < 1 or len(data) < 12:
                return "bad allocate"
            space = int.from_bytes(data[4:12], "little")
            if space > MAX_DATA_LEN:
                return "data length exceeds maximum"
            k = ins_keys[0]
            if k not in ctx.signers:
                return "missing signature"
            if k not in ctx.writables:
                return "account not writable"
            a = load(k)
            if a is None:
                return "no account"
            if a.lamports < rent_exempt_minimum(space):
                return "rent: not exempt"
            a.data = bytes(space)
            store(k, a)
            return ""
        return "unsupported system instruction"

    def _system_nonce(self, disc, data, ins_keys, ctx: InstrCtx, load,
                      store) -> str:
        """Durable-nonce system instructions (behavior contract:
        fd_system_program_nonce.c — advance :121-230, withdraw :277-470,
        initialize :495-600, authorize :700-790; account orders match
        system_processor.rs).

        The "recent blockhashes" the reference reads through the sysvar
        is this executor's recent_blockhash (set by begin_slot from the
        bank's PoH state)."""
        next_durable = durable_nonce_from_blockhash(self.recent_blockhash)
        nonce_k = ins_keys[0] if ins_keys else None
        if nonce_k is None:
            return "nonce: missing account"
        if nonce_k not in ctx.writables:
            return "nonce: account not writable"
        acct = load(nonce_k)
        if acct is None or acct.owner != SYSTEM_PROGRAM_ID:
            return "nonce: bad account"
        st = _nonce_decode(acct.data)
        if st is None:
            return "nonce: invalid account data"
        state, authority, durable, _fee = st

        if disc == _SYS_ADVANCE_NONCE:
            # accounts: [nonce, recent_blockhashes sysvar, authority]
            if len(ins_keys) < 3:
                return "nonce: not enough accounts"
            if state != _NONCE_INITIALIZED:
                return "nonce: uninitialized"
            if authority not in ctx.signers:
                return "nonce: missing authority signature"
            if durable == next_durable:
                return "nonce: can only advance once per slot"
            acct.data = _nonce_encode(
                _NONCE_INITIALIZED, authority, next_durable,
                self.lamports_per_signature,
            )
            store(nonce_k, acct)
            return ""

        if disc == _SYS_WITHDRAW_NONCE:
            # accounts: [nonce, to, recent_blockhashes, rent, authority]
            if len(ins_keys) < 5 or len(data) < 12:
                return "nonce: bad withdraw"
            lamports = int.from_bytes(data[4:12], "little")
            to_k = ins_keys[1]
            if to_k not in ctx.writables:
                return "nonce: destination not writable"
            if state == _NONCE_UNINITIALIZED:
                if lamports > acct.lamports:
                    return "insufficient funds"
                signer = nonce_k
            else:
                if lamports == acct.lamports:
                    # full withdrawal is allowed only once the stored
                    # durable nonce EXPIRED (differs from the current
                    # slot's value): closing a nonce whose stored value
                    # still equals the live durable nonce would let the
                    # protected transaction be replayed (Agave
                    # NonceBlockhashNotExpired; the reference snapshot's
                    # inverted 0 != memcmp at
                    # fd_system_program_nonce.c:366 contradicts the
                    # Agave lines it cites and is not followed here)
                    if durable == next_durable:
                        return "nonce: blockhash not expired"
                    acct.data = _nonce_encode(_NONCE_UNINITIALIZED)
                else:
                    if lamports + rent_exempt_minimum(
                        len(acct.data)
                    ) > acct.lamports:
                        return "insufficient funds"
                signer = authority
            if signer not in ctx.signers:
                return "nonce: missing authority signature"
            if nonce_k == to_k:
                # Agave fails this with an account-borrow error (source
                # and destination cannot be borrowed simultaneously); a
                # silent no-op success would diverge on txn status
                return "nonce: source and destination are the same account"
            acct.lamports -= lamports
            store(nonce_k, acct)
            dst = load(to_k) or Account(0)
            dst.lamports += lamports
            store(to_k, dst)
            return ""

        if disc == _SYS_INIT_NONCE:
            # accounts: [nonce, recent_blockhashes, rent]; data: authority
            if len(ins_keys) < 3 or len(data) < 36:
                return "nonce: bad initialize"
            if state != _NONCE_UNINITIALIZED:
                return "nonce: already initialized"
            if acct.lamports < rent_exempt_minimum(len(acct.data)):
                return "insufficient funds"
            acct.data = _nonce_encode(
                _NONCE_INITIALIZED, bytes(data[4:36]), next_durable,
                self.lamports_per_signature,
            )
            store(nonce_k, acct)
            return ""

        # _SYS_AUTHORIZE_NONCE: accounts [nonce, authority]; data: new auth
        if len(data) < 36:
            return "nonce: bad authorize"
        if state != _NONCE_INITIALIZED:
            return "nonce: uninitialized"
        if authority not in ctx.signers:
            return "nonce: missing authority signature"
        acct.data = _nonce_encode(
            _NONCE_INITIALIZED, bytes(data[4:36]), durable,
            self.lamports_per_signature,
        )
        store(nonce_k, acct)
        return ""

    def _bpf(self, prog: Account, prog_key: bytes, data, ins_keys,
             ctx: InstrCtx, load, store, logs, elf: bytes | None = None
             ) -> str:
        """Execute an sBPF program with the instruction's accounts
        serialized into the VM input region in SOLANA'S aligned input
        layout (the reference implements the same region in
        fd_vm_context.c; layout from the Solana SDK's aligned
        serializer):

          u64 acct_cnt
          per account, first occurrence:
            u8  dup marker = 0xFF
            u8  is_signer | u8 is_writable | u8 executable
            u32 original_data_len
            pubkey[32] | owner[32] | u64 lamports | u64 data_len
            data | 10240 spare bytes (MAX_PERMITTED_DATA_INCREASE)
            pad to 8 | u64 rent_epoch
          per duplicate: u8 index-of-original + 7 pad bytes
          u64 ins_data_len | ins_data | program_id[32]

        Writable accounts commit back lamports, owner, and data — with
        REALLOC honored: the program may rewrite data_len up to
        original + 10240 (and under MAX_DATA_LEN); the spare region is
        what makes in-place growth addressable.

        CPI: sol_invoke_signed_c re-enters _dispatch with caller-granted
        privileges + PDA signers (reference: fd_vm_syscalls.c
        fd_vm_syscall_cpi_c); see _register_cpi for the marshalling."""
        from firedancer_tpu.ballet import sbpf
        from firedancer_tpu.flamenco.vm import Vm, VmError

        try:
            program = sbpf.load(elf if elf is not None else prog.data)
        except sbpf.SbpfError as e:
            return f"elf: {e}"
        vm = Vm(program, cu_limit=ctx.meter[0])

        buf = bytearray()
        buf += len(ins_keys).to_bytes(8, "little")
        offsets = []  # (key, writable, lam_off, len_off, data_off,
        #               orig_len, owner_off)
        seen: dict[bytes, int] = {}
        for idx, k in enumerate(ins_keys):
            if k in seen:
                buf += bytes([seen[k]]) + bytes(7)
                continue
            seen[k] = idx
            a = load(k) or Account(0)
            writable = k in ctx.writables
            buf += bytes([
                0xFF,
                1 if k in ctx.signers else 0,
                1 if writable else 0,
                1 if a.executable else 0,
            ])
            buf += len(a.data).to_bytes(4, "little")
            buf += k
            owner_off = len(buf)
            buf += a.owner
            lam_off = len(buf)
            buf += a.lamports.to_bytes(8, "little")
            len_off = len(buf)
            buf += len(a.data).to_bytes(8, "little")
            data_off = len(buf)
            buf += a.data
            buf += bytes(MAX_PERMITTED_DATA_INCREASE)
            buf += bytes((-len(a.data)) % 8)
            buf += int(a.rent_epoch).to_bytes(8, "little")
            offsets.append(
                (k, writable, lam_off, len_off, data_off, len(a.data),
                 owner_off)
            )
        buf += len(data).to_bytes(8, "little") + data
        buf += prog_key
        vm.input_mem = bytearray(buf)

        # lamport conservation baseline BEFORE execution: CPI commits into
        # the overlay mid-run, so the post-run overlay is not the baseline
        pre_sum = 0
        for k, *_ in offsets:
            pre_sum += (load(k) or Account(0)).lamports

        self._register_cpi(
            vm, prog_key, ins_keys, offsets, ctx, load, store, logs
        )

        try:
            r0 = vm.run()
        except VmError as e:
            logs.extend(vm.logs)
            ctx.meter[0] = max(vm.cu, 0)
            return f"vm: {e}"
        logs.extend(vm.logs)
        ctx.meter[0] = max(vm.cu, 0)
        if r0 != 0:
            return f"program error {r0}"
        # Lamport conservation (ref fd_instr_info sum check): the sum of
        # lamports across the instruction's unique accounts must not
        # change.  `offsets` holds one entry per unique account (dups
        # serialize as index references).
        post = {}  # key -> (lamports, data | None, owner | None)
        for k, writable, lam_off, len_off, data_off, orig_len, owner_off \
                in offsets:
            if writable:
                new_len = int.from_bytes(
                    vm.input_mem[len_off : len_off + 8], "little"
                )
                if (
                    new_len > orig_len + MAX_PERMITTED_DATA_INCREASE
                    or new_len > MAX_DATA_LEN
                ):
                    return "invalid account data realloc"
                new_owner = bytes(
                    vm.input_mem[owner_off : owner_off + 32]
                )
                cur = load(k) or Account(0)
                new_data = bytes(
                    vm.input_mem[data_off : data_off + new_len]
                )
                if new_owner != cur.owner:
                    # owner reassignment through the input region is
                    # legal only for the account's CURRENT owning
                    # program on a non-executable account (reference:
                    # fd_account_set_owner / Agave ModifiedProgramId)
                    if cur.owner != prog_key or cur.executable:
                        return "instruction illegally modified " \
                               "account owner"
                    # ... and only with all-zero account data
                    # (fd_account_is_zeroed): handing an account with
                    # live crafted bytes to a new owner would let that
                    # owner mistake attacker data for self-initialized
                    # state
                    if any(new_data):
                        return "instruction illegally modified " \
                               "account owner"
                post[k] = (
                    int.from_bytes(
                        vm.input_mem[lam_off : lam_off + 8], "little"
                    ),
                    new_data,
                    new_owner,
                )
            else:
                a = load(k) or Account(0)
                post[k] = (a.lamports, None, None)
        if sum(lam for lam, _, _ in post.values()) != pre_sum:
            return "instruction changed total lamports"
        for k, (lam, new_data, new_owner) in post.items():
            if new_data is None:
                continue
            a = load(k) or Account(0)
            a.lamports = lam
            a.data = new_data
            a.owner = new_owner
            store(k, a)
        return ""

    # ---- cross-program invocation ---------------------------------------

    def _register_cpi(self, vm, prog_key: bytes, ins_keys, offsets,
                      ctx: InstrCtx, load, store, logs) -> None:
        """Install the CPI + PDA syscalls on a VM instance.

        Marshalling follows the reference's C ABI (fd_vm_syscall_cpi_c):
          SolInstruction  { program_id *u64, accounts *u64, accounts_len,
                            data *u64, data_len }          (40 B)
          SolAccountMeta  { pubkey *u64, is_writable u8, is_signer u8 }
                                                           (16 B stride)
          SolSignerSeedsC { addr *u64, len u64 } of SolSignerSeedC pairs
        Account state flows through the runtime's own serialization table
        (`offsets`), which is this build's analog of the reference's
        account-info translation + copy-back."""
        from firedancer_tpu.flamenco.vm import VmError

        def _sync_down():
            """Caller's input-region writes -> overlay (callee must see
            the caller's in-flight state, including in-place reallocs)."""
            for k, writable, lam_off, len_off, data_off, orig_len, \
                    owner_off in offsets:
                if not writable:
                    continue
                cur_len = int.from_bytes(
                    vm.input_mem[len_off : len_off + 8], "little"
                )
                if cur_len > orig_len + MAX_PERMITTED_DATA_INCREASE:
                    raise VmError("cpi: invalid account data realloc")
                a = load(k) or Account(0)
                new_owner = bytes(
                    vm.input_mem[owner_off : owner_off + 32]
                )
                new_data = bytes(
                    vm.input_mem[data_off : data_off + cur_len]
                )
                if new_owner != a.owner:
                    # same owner-reassignment rule as the commit path:
                    # current owner only, non-executable, and all-zero
                    # data (fd_account_is_zeroed)
                    if (
                        a.owner != prog_key
                        or a.executable
                        or any(new_data)
                    ):
                        raise VmError(
                            "cpi: instruction illegally modified "
                            "account owner"
                        )
                    a.owner = new_owner
                a.lamports = int.from_bytes(
                    vm.input_mem[lam_off : lam_off + 8], "little"
                )
                a.data = new_data
                store(k, a)

        def _sync_up():
            """Overlay -> caller's input region after the callee ran.
            A callee-side realloc copies back into the caller's spare
            region (reference: CPI copy-back honors resized accounts up
            to the serialized headroom)."""
            for k, writable, lam_off, len_off, data_off, orig_len, \
                    owner_off in offsets:
                if not writable:
                    continue
                a = load(k) or Account(0)
                if len(a.data) > orig_len + MAX_PERMITTED_DATA_INCREASE:
                    raise VmError(
                        "cpi: account grown beyond realloc headroom"
                    )
                vm.input_mem[lam_off : lam_off + 8] = a.lamports.to_bytes(
                    8, "little"
                )
                vm.input_mem[len_off : len_off + 8] = len(a.data).to_bytes(
                    8, "little"
                )
                vm.input_mem[data_off : data_off + len(a.data)] = a.data
                vm.input_mem[owner_off : owner_off + 32] = a.owner

        def _seed_array(addr, count):
            """Read a SolSignerSeedC[count] array -> list of seed bytes,
            or None on constraint violation."""
            if count > MAX_SEEDS:
                return None
            seeds = []
            for j in range(count):
                sa = vm.mem_read(addr + 16 * j, 8)
                sl = vm.mem_read(addr + 16 * j + 8, 8)
                if sl > MAX_SEED_LEN:
                    return None
                seeds.append(vm.mem_read_bytes(sa, sl))
            return seeds

        def _read_seeds(r4, r5):
            if r5 > MAX_SEEDS:
                raise VmError("cpi: too many signer seed sets")
            pdas = []
            for i in range(r5):
                seeds_addr = vm.mem_read(r4 + 16 * i, 8)
                n = vm.mem_read(r4 + 16 * i + 8, 8)
                seeds = _seed_array(seeds_addr, n)
                if seeds is None:
                    raise VmError("cpi: bad signer seeds")
                pda = create_program_address(seeds, prog_key)
                if pda is None:
                    raise VmError("cpi: invalid seeds (no PDA)")
                pdas.append(pda)
            return pdas

        caller_keys = set(ins_keys)

        def sol_invoke_signed_c(vm_, r1, r2, r3, r4, r5):
            vm.consume(CPI_BASE_CU)
            target = vm.mem_read_bytes(vm.mem_read(r1, 8), 32)
            metas_addr = vm.mem_read(r1 + 8, 8)
            metas_len = vm.mem_read(r1 + 16, 8)
            data_addr = vm.mem_read(r1 + 24, 8)
            data_len = vm.mem_read(r1 + 32, 8)
            if metas_len > 64:
                raise VmError("cpi: too many account metas")
            if data_len > 10 * 1024:
                raise VmError("cpi: instruction data too large")
            inner_data = vm.mem_read_bytes(data_addr, data_len)

            # the callee program account must be provided by the caller's
            # instruction context (reference: callee must appear in the
            # caller's account infos)
            if target not in caller_keys:
                raise VmError("cpi: program not in caller context")
            # re-entrancy: a program already on the stack may only be
            # re-entered by direct self-recursion, i.e. when it IS the
            # currently executing program (reference rule)
            if target != ctx.stack[-1] and target in ctx.stack:
                raise VmError("cpi: reentrancy violation")

            pdas = set(_read_seeds(r4, r5))
            inner_keys, inner_signers, inner_writables = [], set(), set()
            for i in range(metas_len):
                base = metas_addr + 16 * i
                k = vm.mem_read_bytes(vm.mem_read(base, 8), 32)
                w = vm.mem_read(base + 8, 1)
                s = vm.mem_read(base + 9, 1)
                if k not in caller_keys:
                    raise VmError("cpi: account not in caller context")
                inner_keys.append(k)
                if w:
                    if k not in ctx.writables:
                        raise VmError("cpi: writable privilege escalation")
                    inner_writables.add(k)
                if s:
                    if k not in ctx.signers and k not in pdas:
                        raise VmError("cpi: signer privilege escalation")
                    inner_signers.add(k)

            _sync_down()
            ctx.meter[0] = max(vm.cu, 0)
            err = self._dispatch(
                target, inner_data, inner_keys,
                ctx.child(inner_signers, inner_writables),
                load, store, logs,
            )
            vm.cu = ctx.meter[0]
            if err:
                raise VmError(f"cpi: {err}")
            _sync_up()
            return 0

        def sol_create_program_address(vm_, r1, r2, r3, r4, r5):
            # r1 = seeds (SolSignerSeedC array), r2 = count,
            # r3 = program id addr, r4 = result addr
            vm.consume(1500)
            seeds = _seed_array(r1, r2)
            if seeds is None:
                return 1
            pid = vm.mem_read_bytes(r3, 32)
            pda = create_program_address(seeds, pid)
            if pda is None:
                return 1
            vm.mem_write_bytes(r4, pda)
            return 0

        def sol_try_find_program_address(vm_, r1, r2, r3, r4, r5):
            # as above + r5 = bump seed out address.  CUs are charged per
            # derivation attempt (reference: create_program_address units
            # per bump iteration), which also bounds the host-side work.
            seeds = _seed_array(r1, r2)
            if seeds is None:
                vm.consume(1500)
                return 1
            pid = vm.mem_read_bytes(r3, 32)
            for bump in range(255, -1, -1):
                vm.consume(1500)
                pda = create_program_address(seeds + [bytes([bump])], pid)
                if pda is not None:
                    vm.mem_write_bytes(r4, pda)
                    vm.mem_write(r5, 1, bump)
                    return 0
            return 1

        vm.register_syscall(b"sol_invoke_signed_c", sol_invoke_signed_c)
        vm.register_syscall(
            b"sol_create_program_address", sol_create_program_address
        )
        vm.register_syscall(
            b"sol_try_find_program_address", sol_try_find_program_address
        )
