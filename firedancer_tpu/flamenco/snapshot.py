"""Snapshots: boot-from-state — create, restore, and HTTP transfer.

Reference model: src/flamenco/snapshot/ — fd_snapshot_create.h (pack the
account store into a tar.zst archive), fd_snapshot_restore.c (stream the
tar, materialize accounts into funk), and fd_snapshot_http.c (the
streaming HTTP download state machine).  This build's archive is a tar
of the funk root records plus a manifest carrying slot + the accounts
root hash, zstd-framed by ballet.zstd; restore verifies the hash so a
corrupt or truncated snapshot can never silently boot.

Round 4: every path is STREAMING with O(block) buffers — create pipes
the tar through zstd.StreamCompressor to disk, restore pulls the file
through zstd.StreamDecompressor into python's sequential tar reader
("r|"), serve chunks the file, and download streams the body to disk —
real snapshots are tens of GB and must never be held whole in RAM
(reference: fd_snapshot_http.c:1-30).

Layout inside the tar:
    manifest.bin               bincode MANIFEST (version/slot/hash/count)
    accounts/<hex key>         raw record bytes (accounts.Account codec)

The manifest is a typed bincode struct (flamenco/bincode.py schema) like
the reference's AccountsDb manifest (src/flamenco/types/fd_types.json
SnapshotManifest types), with an explicit version field so a format
change (e.g. round 4's flat-sha256 -> sharded accounts hash) fails
restore with "unsupported snapshot format", never with a misleading
hash-mismatch error.
"""

from __future__ import annotations

import hashlib
import io
import os
import struct
import tarfile

from firedancer_tpu.ballet import zstd as Z
from firedancer_tpu.flamenco import bincode as BC
from firedancer_tpu.funk.funk import Funk

#: read/write granularity for the streaming paths
CHUNK = 256 * 1024

#: bumped whenever the archive layout or accounts-hash tree changes
MANIFEST_VERSION = 3

MANIFEST = BC.struct_of(
    ("version", "u32"),
    ("slot", "u64"),
    ("accounts_hash", ("bytes", 32)),
    ("account_cnt", "u64"),
)


#: shards of the accounts-hash tree (fixed so the hash value is stable
#: regardless of pool size)
_HASH_SHARDS = 16


def accounts_hash(records: dict[bytes, bytes], tpool=None) -> bytes:
    """Root hash: sha256 over per-shard sha256es of the sorted (key,
    value) stream, shards computed fork-join across a tpool (reference:
    the accounts hash is tpool-parallel, fd_accounts_hash; the two-level
    tree here serves the same integrity role as its merkle).

    The shard split is a pure function of the sorted key order, so the
    value is independent of whether (or how wide) a pool computed it."""
    keys = sorted(records)
    shard_digests = [b""] * _HASH_SHARDS
    bounds = [
        (len(keys) * s // _HASH_SHARDS, len(keys) * (s + 1) // _HASH_SHARDS)
        for s in range(_HASH_SHARDS)
    ]

    def shard(lo: int, hi: int) -> None:
        for s, (a, b) in enumerate(bounds):
            if not lo <= s < hi:
                continue
            h = hashlib.sha256()
            for k in keys[a:b]:
                v = records[k]
                h.update(len(k).to_bytes(4, "little"))
                h.update(k)
                h.update(len(v).to_bytes(4, "little"))
                h.update(v)
            shard_digests[s] = h.digest()

    if tpool is not None:
        tpool.run_all(shard, 0, _HASH_SHARDS)
    else:
        shard(0, _HASH_SHARDS)
    root = hashlib.sha256()
    for d in shard_digests:
        root.update(d)
    return root.digest()


class _CompressingWriter:
    """File-like sink: tarfile writes -> zstd stream -> disk."""

    def __init__(self, f):
        self.f = f
        self.z = Z.StreamCompressor()

    def write(self, data: bytes) -> int:
        self.f.write(self.z.write(bytes(data)))
        return len(data)

    def finish(self) -> None:
        self.f.write(self.z.finish())


class _DecompressingReader:
    """File-like source: disk -> zstd stream -> tarfile reads."""

    def __init__(self, f):
        self.f = f
        self.z = Z.StreamDecompressor()
        self.buf = bytearray()

    def read(self, n: int = -1) -> bytes:
        while (n < 0 or len(self.buf) < n) and not self.z.eof:
            raw = self.f.read(CHUNK)
            self.buf += self.z.feed(raw)
            if not raw:
                break
        if n < 0:
            out, self.buf = bytes(self.buf), bytearray()
        else:
            out, self.buf = bytes(self.buf[:n]), self.buf[n:]
        return out


def create(funk: Funk, path: str, *, slot: int = 0) -> bytes:
    """Stream the published (root) state to a tar.zst snapshot file.
    Returns the accounts hash.  Peak memory is O(largest record), not
    O(archive)."""
    root_hash = _pooled_accounts_hash(funk.root)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        sink = _CompressingWriter(f)
        with tarfile.open(fileobj=sink, mode="w|") as tar:
            manifest = BC.encode(
                MANIFEST,
                {
                    "version": MANIFEST_VERSION,
                    "slot": slot,
                    "accounts_hash": root_hash,
                    "account_cnt": len(funk.root),
                },
            )
            mi = tarfile.TarInfo("manifest.bin")
            mi.size = len(manifest)
            tar.addfile(mi, io.BytesIO(manifest))
            for k in sorted(funk.root):
                ti = tarfile.TarInfo(f"accounts/{k.hex()}")
                ti.size = len(funk.root[k])
                tar.addfile(ti, io.BytesIO(funk.root[k]))
        sink.finish()
    os.replace(tmp, path)
    return root_hash


def _pooled_accounts_hash(records: dict[bytes, bytes]) -> bytes:
    """accounts_hash with a transient fork-join pool for big stores
    (hashlib releases the GIL, so shards genuinely overlap)."""
    if len(records) < 1024:
        return accounts_hash(records)
    from firedancer_tpu.utils.tpool import TPool

    pool = TPool(4)
    try:
        return accounts_hash(records, tpool=pool)
    finally:
        pool.close()


class SnapshotError(ValueError):
    pass


def restore(path: str) -> tuple[Funk, int, bytes]:
    """Load a snapshot file -> (funk, slot, accounts_hash).  Raises
    SnapshotError when the recomputed hash disagrees with the manifest.

    The archive streams through the zstd decoder into a sequential tar
    reader: no whole-file or whole-archive buffer exists at any point
    (restore peak RSS is O(largest record) + the account store itself).
    """
    funk = Funk()
    manifest = None
    try:
        with open(path, "rb") as f:
            src = _DecompressingReader(f)
            with tarfile.open(fileobj=src, mode="r|") as tar:
                for m in tar:
                    if not m.isfile():
                        continue
                    body = tar.extractfile(m).read()
                    if m.name == "manifest.bin":
                        manifest, _ = BC.decode(MANIFEST, body)
                    elif m.name == "manifest.json":
                        # pre-v3 archives (json manifest, flat accounts
                        # hash): a format mismatch, not corruption
                        raise SnapshotError(
                            "unsupported snapshot format (pre-v3 "
                            "manifest)"
                        )
                    elif m.name.startswith("accounts/"):
                        funk.root[
                            bytes.fromhex(m.name.split("/", 1)[1])
                        ] = body
    except SnapshotError:
        raise
    except (Z.ZstdError, tarfile.TarError, ValueError, struct.error) as e:
        # struct.error: a truncated manifest.bin fails inside BC.decode
        raise SnapshotError(f"corrupt snapshot: {e}") from None
    if manifest is None:
        raise SnapshotError("missing manifest")
    if manifest["version"] != MANIFEST_VERSION:
        raise SnapshotError(
            f"unsupported snapshot format (manifest v{manifest['version']},"
            f" want v{MANIFEST_VERSION})"
        )
    got = _pooled_accounts_hash(funk.root)
    if got != manifest["accounts_hash"]:
        raise SnapshotError("accounts hash mismatch")
    if manifest["account_cnt"] != len(funk.root):
        raise SnapshotError("account count mismatch")
    return funk, int(manifest["slot"]), got


# ---------------------------------------------------------------------------
# HTTP transfer (fd_snapshot_http analog, over ballet.http)
# ---------------------------------------------------------------------------


def serve(path: str, addr=("127.0.0.1", 0)):
    """Serve a snapshot file at /snapshot.tar.zst; returns the server
    (close() when done).  The body is chunked from disk, never loaded
    whole."""
    from firedancer_tpu.ballet.http import HttpServer

    def handler(req):
        if req.path != "/snapshot.tar.zst":
            return 404, b"not found\n", "text/plain"

        def chunks():
            with open(path, "rb") as f:
                while True:
                    blk = f.read(CHUNK)
                    if not blk:
                        return
                    yield blk

        return 200, chunks(), "application/octet-stream"

    return HttpServer(handler, addr)


def download(addr: tuple[str, int], out_path: str) -> None:
    """Fetch /snapshot.tar.zst from a peer into out_path, streaming the
    body to disk chunk by chunk."""
    from firedancer_tpu.ballet.http import get_stream

    tmp = out_path + ".tmp"
    try:
        with open(tmp, "wb") as f:
            status, _n = get_stream(addr, "/snapshot.tar.zst", f.write)
        if status != 200:
            raise SnapshotError(f"http {status}")
    except SnapshotError:
        os.unlink(tmp)
        raise
    except (OSError, ValueError) as e:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise SnapshotError(f"download failed: {e}") from None
    os.replace(tmp, out_path)
