"""Snapshots: boot-from-state — create, restore, and HTTP transfer.

Reference model: src/flamenco/snapshot/ — fd_snapshot_create.h (pack the
account store into a tar.zst archive), fd_snapshot_restore.c (stream the
tar, materialize accounts into funk), and fd_snapshot_http.c (the
streaming HTTP download state machine).  This build's archive is a tar
of the funk root records plus a manifest carrying slot + the accounts
root hash, zstd-framed by ballet.zstd; restore verifies the hash so a
corrupt or truncated snapshot can never silently boot.

Layout inside the tar:
    manifest.json              {"slot": N, "accounts_hash": hex, "n": N}
    accounts/<hex key>         raw record bytes (accounts.Account codec)
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import tarfile

from firedancer_tpu.ballet import zstd as Z
from firedancer_tpu.funk.funk import Funk


def accounts_hash(records: dict[bytes, bytes]) -> bytes:
    """Order-independent-by-construction root hash: sha256 over the
    sorted (key, value) stream (the reference hashes the account delta
    merkle; a flat sorted hash serves the same integrity role here)."""
    h = hashlib.sha256()
    for k in sorted(records):
        v = records[k]
        h.update(len(k).to_bytes(4, "little"))
        h.update(k)
        h.update(len(v).to_bytes(4, "little"))
        h.update(v)
    return h.digest()


def create(funk: Funk, path: str, *, slot: int = 0) -> bytes:
    """Write the published (root) state as a tar.zst snapshot file.
    Returns the accounts hash."""
    root_hash = accounts_hash(funk.root)
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tar:
        manifest = json.dumps(
            {
                "slot": slot,
                "accounts_hash": root_hash.hex(),
                "n": len(funk.root),
            }
        ).encode()
        mi = tarfile.TarInfo("manifest.json")
        mi.size = len(manifest)
        tar.addfile(mi, io.BytesIO(manifest))
        for k in sorted(funk.root):
            ti = tarfile.TarInfo(f"accounts/{k.hex()}")
            ti.size = len(funk.root[k])
            tar.addfile(ti, io.BytesIO(funk.root[k]))
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(Z.compress(buf.getvalue()))
    os.replace(tmp, path)
    return root_hash


class SnapshotError(ValueError):
    pass


def restore(path: str) -> tuple[Funk, int, bytes]:
    """Load a snapshot file -> (funk, slot, accounts_hash).  Raises
    SnapshotError when the recomputed hash disagrees with the manifest."""
    with open(path, "rb") as f:
        raw = Z.decompress(f.read())
    funk = Funk()
    manifest = None
    with tarfile.open(fileobj=io.BytesIO(raw), mode="r") as tar:
        for m in tar.getmembers():
            body = tar.extractfile(m).read() if m.isfile() else b""
            if m.name == "manifest.json":
                manifest = json.loads(body)
            elif m.name.startswith("accounts/"):
                funk.root[bytes.fromhex(m.name.split("/", 1)[1])] = body
    if manifest is None:
        raise SnapshotError("missing manifest")
    got = accounts_hash(funk.root)
    if got.hex() != manifest["accounts_hash"]:
        raise SnapshotError("accounts hash mismatch")
    if manifest["n"] != len(funk.root):
        raise SnapshotError("account count mismatch")
    return funk, int(manifest["slot"]), got


# ---------------------------------------------------------------------------
# HTTP transfer (fd_snapshot_http analog, over ballet.http)
# ---------------------------------------------------------------------------


def serve(path: str, addr=("127.0.0.1", 0)):
    """Serve a snapshot file at /snapshot.tar.zst; returns the server
    (close() when done)."""
    from firedancer_tpu.ballet.http import HttpServer

    def handler(req):
        if req.path != "/snapshot.tar.zst":
            return 404, b"not found\n", "text/plain"
        with open(path, "rb") as f:
            return 200, f.read(), "application/octet-stream"

    return HttpServer(handler, addr)


def download(addr: tuple[str, int], out_path: str) -> None:
    """Fetch /snapshot.tar.zst from a peer into out_path."""
    from firedancer_tpu.ballet.http import get

    status, body = get(addr, "/snapshot.tar.zst", timeout=30.0)
    if status != 200:
        raise SnapshotError(f"http {status}")
    tmp = out_path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(body)
    os.replace(tmp, out_path)
