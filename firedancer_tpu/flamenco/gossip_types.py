"""Mainnet gossip wire types: CrdsValue / CrdsData / gossip messages.

Declarative bincode schemas for the Solana gossip protocol's UDP payloads,
matching the reference's generated types (layout source:
/root/reference/src/flamenco/types/fd_types.json `gossip_*`/`crds_*`
entries, encode/decode paths /root/reference/src/flamenco/gossip/
fd_gossip.c).  Wire convention: bincode fixint LE; enums u32-tagged;
Vec = u64 count; `compact` vectors = LEB128 short_vec; `varint` fields =
serde_varint — all provided by flamenco.bincode.

The CRDS signable payload is the bincode encoding of the CrdsData alone;
the signature covers exactly those bytes (fd_gossip.c
fd_gossip_sign_crds_value behavior).
"""

from __future__ import annotations

import hashlib
import struct as _struct

from firedancer_tpu.flamenco.bincode import (
    PUBKEY, SIGNATURE, decode, encode, enum_of, opt, shortvec, struct_of,
    varint, vec,
)

# ---------------------------------------------------------------------------
# address / socket primitives
# ---------------------------------------------------------------------------

IP_ADDR = enum_of(
    ("ip4", ("bytes", 4)),
    ("ip6", ("bytes", 16)),
)

SOCKET_ADDR = struct_of(("addr", IP_ADDR), ("port", "u16"))

#: placeholder unspecified socket (0.0.0.0:0)
UNSPEC_SOCKET = {"addr": ("ip4", bytes(4)), "port": 0}


def sock(ip: str, port: int) -> dict:
    return {"addr": ("ip4", bytes(int(x) for x in ip.split("."))), "port": port}


def sock_to_tuple(s: dict):
    kind, raw = s["addr"]
    if kind != "ip4":
        return None
    return (".".join(str(b) for b in raw), s["port"])


# ---------------------------------------------------------------------------
# CRDS data variants (fd_types.json order — discriminants are consensus!)
# ---------------------------------------------------------------------------

CONTACT_INFO_V1 = struct_of(
    ("id", PUBKEY),
    ("gossip", SOCKET_ADDR),
    ("tvu", SOCKET_ADDR),
    ("tvu_fwd", SOCKET_ADDR),
    ("repair", SOCKET_ADDR),
    ("tpu", SOCKET_ADDR),
    ("tpu_fwd", SOCKET_ADDR),
    ("tpu_vote", SOCKET_ADDR),
    ("rpc", SOCKET_ADDR),
    ("rpc_pubsub", SOCKET_ADDR),
    ("serve_repair", SOCKET_ADDR),
    ("wallclock", "u64"),
    ("shred_version", "u16"),
)

#: flamenco_txn in the reference is a raw embedded txn; the vote's txn
#: travels as its serialized bytes (u64-counted in the reference's vector
#: framing of the raw payload is NOT used — the txn is parsed in place;
#: we carry the raw bytes and parse with ballet.txn)
VOTE = struct_of(
    ("index", "u8"),
    ("from", PUBKEY),
    ("txn", ("txnbytes",)),
    ("wallclock", "u64"),
)

LOWEST_SLOT = struct_of(
    ("u8", "u8"),
    ("from", PUBKEY),
    ("root", "u64"),
    ("lowest", "u64"),
    ("slots", vec("u64")),
    ("i_dont_know", "u64"),
    ("wallclock", "u64"),
)

SLOT_HASH = struct_of(("slot", "u64"), ("hash", ("bytes", 32)))

SLOT_HASHES = struct_of(
    ("from", PUBKEY),
    ("hashes", vec(SLOT_HASH)),
    ("wallclock", "u64"),
)

BITVEC_U8 = struct_of(
    ("bits", opt(struct_of(("vec", vec("u8"))))),
    ("len", "u64"),
)

BITVEC_U64 = struct_of(
    ("bits", opt(struct_of(("vec", vec("u64"))))),
    ("len", "u64"),
)

SLOTS = struct_of(
    ("first_slot", "u64"), ("num", "u64"), ("slots", BITVEC_U8),
)

FLATE2_SLOTS = struct_of(
    ("first_slot", "u64"), ("num", "u64"), ("compressed", vec("u8")),
)

SLOTS_ENUM = enum_of(("flate2", FLATE2_SLOTS), ("uncompressed", SLOTS))

EPOCH_SLOTS = struct_of(
    ("u8", "u8"),
    ("from", PUBKEY),
    ("slots", vec(SLOTS_ENUM)),
    ("wallclock", "u64"),
)

VERSION_V1 = struct_of(
    ("from", PUBKEY),
    ("wallclock", "u64"),
    ("major", "u16"), ("minor", "u16"), ("patch", "u16"),
    ("commit", opt("u32")),
)

VERSION_V2 = struct_of(
    ("from", PUBKEY),
    ("wallclock", "u64"),
    ("major", "u16"), ("minor", "u16"), ("patch", "u16"),
    ("commit", opt("u32")),
    ("feature_set", "u32"),
)

VERSION_V3 = struct_of(
    ("major", varint("u16")), ("minor", varint("u16")),
    ("patch", varint("u16")),
    ("commit", "u32"), ("feature_set", "u32"),
    ("client", varint("u16")),
)

NODE_INSTANCE = struct_of(
    ("from", PUBKEY),
    ("wallclock", "u64"),
    ("timestamp", "u64"),
    ("token", "u64"),
)

DUPLICATE_SHRED = struct_of(
    ("version", "u16"),
    ("from", PUBKEY),
    ("wallclock", "u64"),
    ("slot", "u64"),
    ("shred_index", "u32"),
    ("shred_variant", "u8"),
    ("chunk_cnt", "u8"),
    ("chunk_idx", "u8"),
    ("chunk", vec("u8")),
)

INC_SNAPSHOT_HASHES = struct_of(
    ("from", PUBKEY),
    ("base_hash", SLOT_HASH),
    ("hashes", vec(SLOT_HASH)),
    ("wallclock", "u64"),
)

SOCKET_ENTRY = struct_of(
    ("key", "u8"), ("index", "u8"), ("offset", varint("u16")),
)

CONTACT_INFO_V2 = struct_of(
    ("from", PUBKEY),
    ("wallclock", varint("u64")),
    ("outset", "u64"),
    ("shred_version", "u16"),
    ("version", VERSION_V3),
    ("addrs", shortvec(IP_ADDR)),
    ("sockets", shortvec(SOCKET_ENTRY)),
    ("extensions", shortvec("u32")),
)

CRDS_DATA = enum_of(
    ("contact_info_v1", CONTACT_INFO_V1),
    ("vote", VOTE),
    ("lowest_slot", LOWEST_SLOT),
    ("snapshot_hashes", SLOT_HASHES),
    ("accounts_hashes", SLOT_HASHES),
    ("epoch_slots", EPOCH_SLOTS),
    ("version_v1", VERSION_V1),
    ("version_v2", VERSION_V2),
    ("node_instance", NODE_INSTANCE),
    ("duplicate_shred", DUPLICATE_SHRED),
    ("incremental_snapshot_hashes", INC_SNAPSHOT_HASHES),
    ("contact_info_v2", CONTACT_INFO_V2),
)

CRDS_VALUE = struct_of(("signature", SIGNATURE), ("data", CRDS_DATA))

# ---------------------------------------------------------------------------
# gossip protocol messages
# ---------------------------------------------------------------------------

CRDS_BLOOM = struct_of(
    ("keys", vec("u64")),
    ("bits", BITVEC_U64),
    ("num_bits_set", "u64"),
)

CRDS_FILTER = struct_of(
    ("filter", CRDS_BLOOM),
    ("mask", "u64"),
    ("mask_bits", "u32"),
)

PING = struct_of(
    ("from", PUBKEY), ("token", ("bytes", 32)), ("signature", SIGNATURE),
)

PRUNE_DATA = struct_of(
    ("pubkey", PUBKEY),
    ("prunes", vec(PUBKEY)),
    ("signature", SIGNATURE),
    ("destination", PUBKEY),
    ("wallclock", "u64"),
)

PRUNE_SIGN_DATA = struct_of(
    ("pubkey", PUBKEY),
    ("prunes", vec(PUBKEY)),
    ("destination", PUBKEY),
    ("wallclock", "u64"),
)

GOSSIP_MSG = enum_of(
    ("pull_req", struct_of(("filter", CRDS_FILTER), ("value", CRDS_VALUE))),
    ("pull_resp", struct_of(("pubkey", PUBKEY), ("crds", vec(CRDS_VALUE)))),
    ("push_msg", struct_of(("pubkey", PUBKEY), ("crds", vec(CRDS_VALUE)))),
    ("prune_msg", struct_of(("pubkey", PUBKEY), ("data", PRUNE_DATA))),
    ("ping", PING),
    ("pong", PING),
)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def encode_msg(msg) -> bytes:
    return encode(GOSSIP_MSG, msg)


def decode_msg(buf: bytes):
    v, off = decode(GOSSIP_MSG, buf, 0)
    if off != len(buf):
        raise ValueError("trailing bytes")
    return v


def crds_signable(data) -> bytes:
    """The byte range a CrdsValue signature covers: bincode(data)."""
    return encode(CRDS_DATA, data)


def sign_crds(secret: bytes, data) -> dict:
    from firedancer_tpu.ops.ed25519 import golden

    sig = golden.sign(secret, crds_signable(data))
    return {"signature": sig, "data": data}


def verify_crds(value: dict) -> bool:
    from firedancer_tpu.ops.ed25519 import golden

    origin = crds_origin(value["data"])
    if origin is None:
        return False
    return golden.verify(
        crds_signable(value["data"]), value["signature"], origin
    ) == 0


def crds_origin(data):
    """The origin pubkey of a CRDS datum (the key the signature is
    checked against and the CRDS table is keyed by)."""
    name, payload = data
    if name == "contact_info_v1":
        return payload["id"]
    return payload.get("from")


def crds_label(data) -> tuple:
    """CRDS table key: (variant, origin [, index/slot discriminator])."""
    name, payload = data
    origin = crds_origin(data)
    if name == "vote":
        return (name, origin, payload["index"])
    if name == "duplicate_shred":
        return (name, origin, payload["slot"])
    return (name, origin)


def crds_wallclock(data) -> int:
    name, payload = data
    return int(payload.get("wallclock", 0))


def value_hash(value: dict) -> bytes:
    """sha256 of the full encoded CrdsValue (pull-filter identity)."""
    return hashlib.sha256(encode(CRDS_VALUE, value)).digest()
