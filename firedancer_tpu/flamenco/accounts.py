"""Account store on funk — the runtime's account manager.

Reference model: src/flamenco/runtime/fd_acc_mgr.c (+ fd_borrowed_account):
accounts are funk records keyed by pubkey, holding the canonical account
shape (lamports, owner, executable, rent epoch, data).  The wire codec is
a fixed little-endian header + data tail; values are opaque to funk.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from firedancer_tpu.funk.funk import Funk, ROOT_XID

_HDR = struct.Struct("<Q32sBQ")  # lamports, owner, executable, rent_epoch

SYSTEM_PROGRAM_ID = bytes(32)


@dataclass
class Account:
    lamports: int
    owner: bytes = SYSTEM_PROGRAM_ID
    executable: bool = False
    rent_epoch: int = 0
    data: bytes = b""

    def encode(self) -> bytes:
        return (
            _HDR.pack(
                self.lamports, self.owner, int(self.executable),
                self.rent_epoch,
            )
            + self.data
        )

    @classmethod
    def decode(cls, raw: bytes) -> "Account":
        lam, owner, execu, rent = _HDR.unpack_from(raw)
        return cls(lam, owner, bool(execu), rent, raw[_HDR.size :])


class AccountMgr:
    """Reads/writes accounts inside one funk transaction (fork)."""

    def __init__(self, funk: Funk, xid: bytes = ROOT_XID):
        self.funk = funk
        self.xid = xid

    def load(self, pubkey: bytes) -> Account | None:
        raw = self.funk.rec_read(self.xid, pubkey)
        return None if raw is None else Account.decode(raw)

    def store(self, pubkey: bytes, acct: Account) -> None:
        self.funk.rec_write(self.xid, pubkey, acct.encode())

    def lamports(self, pubkey: bytes) -> int:
        a = self.load(pubkey)
        return 0 if a is None else a.lamports
