"""Bincode codec combinators + Solana on-chain type schemas.

Reference model: src/flamenco/types/ — 26K LoC of GENERATED bincode
serializers (fd_types.json -> fd_types.h/.c).  The TPU-native substrate
replaces code generation with declarative schemas interpreted by a small
combinator set: a schema IS the Python data structure, and encode/decode
walk it.  The wire format is bincode's fixed-width little-endian
convention (the one Solana uses for account/state types): integers
little-endian, bool = 1 byte, Option = u8 tag + payload, Vec = u64 count
+ elements, enum = u32 discriminant + variant payload.

Schemas below cover the state types the runtime touches (clock, rent,
epoch schedule, stake/vote essentials); new types are one declaration
each, not generated code.
"""

from __future__ import annotations

import struct
from typing import Any

# ---------------------------------------------------------------------------
# combinators: a schema is (kind, ...) tuples or primitive name strings
# ---------------------------------------------------------------------------

_PRIM = {
    "u8": ("<B", 1), "u16": ("<H", 2), "u32": ("<I", 4), "u64": ("<Q", 8),
    "i8": ("<b", 1), "i16": ("<h", 2), "i32": ("<i", 4), "i64": ("<q", 8),
    "f64": ("<d", 8),
}


def opt(inner) -> tuple:
    return ("option", inner)


def vec(inner) -> tuple:
    return ("vec", inner)


def shortvec(inner) -> tuple:
    """Solana short_vec: LEB128 u16 length + elements (the "compact"
    modifier in fd_types.json)."""
    return ("shortvec", inner)


def varint(prim: str) -> tuple:
    """serde_varint integer: 7-bit LEB128 groups, low first."""
    return ("varint", prim)


def _varint_encode(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _varint_decode(buf: bytes, off: int, max_bytes: int) -> tuple[int, int]:
    v = 0
    for i in range(max_bytes):
        if off + i >= len(buf):
            raise ValueError("short varint")
        b = buf[off + i]
        v |= (b & 0x7F) << (7 * i)
        if not b & 0x80:
            return v, off + i + 1
    raise ValueError("varint too long")


def arr(inner, n: int) -> tuple:
    return ("array", inner, n)


def struct_of(*fields: tuple[str, Any]) -> tuple:
    return ("struct", fields)


def enum_of(*variants: tuple[str, Any]) -> tuple:
    """variants: (name, schema-or-None) in discriminant order (u32)."""
    return ("enum", variants)


PUBKEY = ("bytes", 32)
SIGNATURE = ("bytes", 64)


def encode(schema, val) -> bytes:
    if isinstance(schema, str):
        fmt, _ = _PRIM[schema]
        return struct.pack(fmt, val)
    kind = schema[0]
    if kind == "bool":
        return bytes([1 if val else 0])
    if kind == "bytes":
        assert len(val) == schema[1], (len(val), schema[1])
        return bytes(val)
    if kind == "option":
        if val is None:
            return b"\x00"
        return b"\x01" + encode(schema[1], val)
    if kind == "vec":
        out = struct.pack("<Q", len(val))
        for v in val:
            out += encode(schema[1], v)
        return out
    if kind == "shortvec":
        out = _varint_encode(len(val))
        for v in val:
            out += encode(schema[1], v)
        return out
    if kind == "varint":
        return _varint_encode(val)
    if kind == "txnbytes":
        # embedded transaction: raw serialized bytes, no length prefix
        # (fd_types "flamenco_txn"; the decoder parses it in place)
        return bytes(val)
    if kind == "array":
        assert len(val) == schema[2]
        return b"".join(encode(schema[1], v) for v in val)
    if kind == "struct":
        return b"".join(encode(s, val[name]) for name, s in schema[1])
    if kind == "enum":
        name, payload = val
        for i, (vname, vschema) in enumerate(schema[1]):
            if vname == name:
                out = struct.pack("<I", i)
                if vschema is not None:
                    out += encode(vschema, payload)
                return out
        raise ValueError(f"unknown variant {name!r}")
    raise ValueError(f"bad schema {schema!r}")


def decode(schema, buf: bytes, off: int = 0) -> tuple[Any, int]:
    if isinstance(schema, str):
        fmt, n = _PRIM[schema]
        return struct.unpack_from(fmt, buf, off)[0], off + n
    kind = schema[0]
    if kind == "bool":
        if buf[off] > 1:
            raise ValueError("bad bool")
        return bool(buf[off]), off + 1
    if kind == "bytes":
        n = schema[1]
        if off + n > len(buf):
            raise ValueError("short bytes")
        return buf[off : off + n], off + n
    if kind == "option":
        tag = buf[off]
        if tag > 1:
            raise ValueError("bad option tag")
        if tag == 0:
            return None, off + 1
        return decode(schema[1], buf, off + 1)
    if kind == "vec":
        (n,) = struct.unpack_from("<Q", buf, off)
        if n > 1 << 24:
            raise ValueError("vec too long")
        off += 8
        out = []
        for _ in range(n):
            v, off = decode(schema[1], buf, off)
            out.append(v)
        return out, off
    if kind == "shortvec":
        n, off = _varint_decode(buf, off, 3)
        if n > 0xFFFF:
            raise ValueError("shortvec too long")
        out = []
        for _ in range(n):
            v, off = decode(schema[1], buf, off)
            out.append(v)
        return out, off
    if kind == "varint":
        limit = {"u16": 3, "u32": 5, "u64": 10}[schema[1]]
        v, off = _varint_decode(buf, off, limit)
        return v, off
    if kind == "txnbytes":
        from firedancer_tpu.ballet import txn as _T

        # window the parse to one MTU: the embedded txn is at most MTU
        # bytes, while the enclosing datagram may be far larger
        desc = _T.parse(bytes(buf[off : off + _T.MTU]), allow_trailing=True)
        if desc is None:
            raise ValueError("bad embedded txn")
        return bytes(buf[off : off + desc.sz]), off + desc.sz
    if kind == "array":
        out = []
        for _ in range(schema[2]):
            v, off = decode(schema[1], buf, off)
            out.append(v)
        return out, off
    if kind == "struct":
        out = {}
        for name, s in schema[1]:
            out[name], off = decode(s, buf, off)
        return out, off
    if kind == "enum":
        (disc,) = struct.unpack_from("<I", buf, off)
        off += 4
        if disc >= len(schema[1]):
            raise ValueError(f"bad discriminant {disc}")
        vname, vschema = schema[1][disc]
        if vschema is None:
            return (vname, None), off
        v, off = decode(vschema, buf, off)
        return (vname, v), off
    raise ValueError(f"bad schema {schema!r}")


# ---------------------------------------------------------------------------
# Solana state-type schemas (fd_types analogs, declared not generated)
# ---------------------------------------------------------------------------

CLOCK = struct_of(
    ("slot", "u64"),
    ("epoch_start_timestamp", "i64"),
    ("epoch", "u64"),
    ("leader_schedule_epoch", "u64"),
    ("unix_timestamp", "i64"),
)

RENT = struct_of(
    ("lamports_per_byte_year", "u64"),
    ("exemption_threshold", "f64"),
    ("burn_percent", "u8"),
)

EPOCH_SCHEDULE = struct_of(
    ("slots_per_epoch", "u64"),
    ("leader_schedule_slot_offset", "u64"),
    ("warmup", ("bool",)),
    ("first_normal_epoch", "u64"),
    ("first_normal_slot", "u64"),
)

STAKE_HISTORY_ENTRY = struct_of(
    ("effective", "u64"), ("activating", "u64"), ("deactivating", "u64"),
)

STAKE_HISTORY = vec(struct_of(
    ("epoch", "u64"), ("entry", STAKE_HISTORY_ENTRY),
))

DELEGATION = struct_of(
    ("voter_pubkey", PUBKEY),
    ("stake", "u64"),
    ("activation_epoch", "u64"),
    ("deactivation_epoch", "u64"),
    ("warmup_cooldown_rate", "f64"),
)

STAKE = struct_of(
    ("delegation", DELEGATION), ("credits_observed", "u64"),
)

LOCKUP = struct_of(
    ("unix_timestamp", "i64"), ("epoch", "u64"), ("custodian", PUBKEY),
)

AUTHORIZED = struct_of(("staker", PUBKEY), ("withdrawer", PUBKEY))

STAKE_META = struct_of(
    ("rent_exempt_reserve", "u64"),
    ("authorized", AUTHORIZED),
    ("lockup", LOCKUP),
)

#: StakeStateV2: the account state of the stake program
STAKE_STATE = enum_of(
    ("uninitialized", None),
    ("initialized", STAKE_META),
    ("stake", struct_of(
        ("meta", STAKE_META), ("stake", STAKE), ("flags", "u8"),
    )),
    ("rewards_pool", None),
)

VOTE_LOCKOUT = struct_of(("slot", "u64"), ("confirmation_count", "u32"))

#: the vote-state essentials gossip/consensus tooling reads
VOTE_STATE_CORE = struct_of(
    ("node_pubkey", PUBKEY),
    ("authorized_withdrawer", PUBKEY),
    ("commission", "u8"),
    ("votes", vec(VOTE_LOCKOUT)),
    ("root_slot", opt("u64")),
)
