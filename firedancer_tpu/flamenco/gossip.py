"""Gossip: CRDS replication over UDP — how a validator learns the
cluster.

Reference model: src/flamenco/gossip/fd_gossip.c (1,957 LoC) — the
Solana gossip protocol: a conflict-free replicated data store (CRDS) of
signed values (contact info, votes, ...) keyed by (origin, kind), newest
wallclock wins; spread by push (eager fanout to live peers) and pull
(anti-entropy: ask a random peer for values you lack), with ping/pong
tokens proving peer liveness before they enter the active set.

This build implements that architecture with its own compact wire format
(this is NOT the mainnet-compatible encoding; the reference's bincode
layouts live in its generated types layer which has no analog here yet):

    msg   = u8 kind | body
    PING  = token[32]
    PONG  = sha256(token)[32]
    PUSH  = u16 n | n * value
    PULLQ = u16 n | n * u64 (xxh-mixed hashes of values held) | value(self)
    PULLR = u16 n | n * value
    value = sig[64] | origin[32] | u8 vkind | u64 wallclock
            | u16 len | body       (sig covers everything after it)

Values are Ed25519-signed by their origin and verified on receipt; an
invalid signature drops the value (the reference does the same via its
sigverify path).  Contact-info bodies carry the shred version plus
gossip/TPU socket addresses, which is exactly what stake_ci/shred_dest
(disco/shred_dest.py) need to run turbine without hand-fed contacts.
"""

from __future__ import annotations

import hashlib
import os
import socket
import struct
import time
from dataclasses import dataclass, field

from firedancer_tpu.ops.ed25519 import golden

MSG_PING, MSG_PONG, MSG_PUSH, MSG_PULLQ, MSG_PULLR = range(5)

V_CONTACT = 0
V_VOTE = 1

#: push fanout (reference default push fanout class)
PUSH_FANOUT = 6
#: peer considered live if a pong arrived within this window
LIVENESS_S = 20.0
#: drop values older than this (reference CRDS timeouts)
VALUE_TTL_S = 60.0


def _addr_pack(addr: tuple[str, int]) -> bytes:
    return socket.inet_aton(addr[0]) + struct.pack("<H", addr[1])


def _addr_unpack(b: bytes) -> tuple[str, int]:
    return socket.inet_ntoa(b[:4]), struct.unpack("<H", b[4:6])[0]


@dataclass(frozen=True)
class ContactInfo:
    pubkey: bytes
    shred_version: int
    gossip_addr: tuple[str, int]
    tpu_addr: tuple[str, int]
    wallclock: int = 0

    def body(self) -> bytes:
        return (
            struct.pack("<H", self.shred_version)
            + _addr_pack(self.gossip_addr)
            + _addr_pack(self.tpu_addr)
        )

    @classmethod
    def from_value(cls, v: "CrdsValue") -> "ContactInfo":
        sv = struct.unpack("<H", v.body[:2])[0]
        return cls(
            v.origin, sv, _addr_unpack(v.body[2:8]),
            _addr_unpack(v.body[8:14]), v.wallclock,
        )


@dataclass(frozen=True)
class CrdsValue:
    origin: bytes
    vkind: int
    wallclock: int
    body: bytes
    signature: bytes

    def signable(self) -> bytes:
        return (
            self.origin
            + bytes([self.vkind])
            + struct.pack("<Q", self.wallclock)
            + struct.pack("<H", len(self.body))
            + self.body
        )

    def encode(self) -> bytes:
        return self.signature + self.signable()

    @classmethod
    def decode(cls, b: bytes, off: int) -> tuple["CrdsValue", int] | None:
        if len(b) - off < 64 + 32 + 1 + 8 + 2:
            return None
        sig = b[off : off + 64]
        o = off + 64
        origin = b[o : o + 32]
        vkind = b[o + 32]
        (wallclock,) = struct.unpack_from("<Q", b, o + 33)
        (ln,) = struct.unpack_from("<H", b, o + 41)
        body_off = o + 43
        if body_off + ln > len(b):
            return None
        body = b[body_off : body_off + ln]
        return cls(origin, vkind, wallclock, body, sig), body_off + ln

    def verify(self) -> bool:
        return golden.verify(self.signable(), self.signature, self.origin) == 0

    def key(self) -> tuple[bytes, int]:
        return (self.origin, self.vkind)

    def digest64(self) -> int:
        h = hashlib.sha256(self.signature).digest()
        return int.from_bytes(h[:8], "little")


def make_value(secret: bytes, vkind: int, body: bytes,
               wallclock: int | None = None) -> CrdsValue:
    origin = golden.public_from_secret(secret)
    wc = int(time.time() * 1000) if wallclock is None else wallclock
    unsigned = CrdsValue(origin, vkind, wc, body, b"\0" * 64)
    sig = golden.sign(secret, unsigned.signable())
    return CrdsValue(origin, vkind, wc, body, sig)


@dataclass
class _Peer:
    contact: ContactInfo
    last_pong: float = 0.0
    ping_token: bytes = b""


class GossipNode:
    """One gossip endpoint over a real UDP socket (non-blocking)."""

    def __init__(
        self,
        identity_secret: bytes,
        *,
        shred_version: int = 1,
        bind=("127.0.0.1", 0),
        tpu_addr=("127.0.0.1", 0),
        entrypoints: list[tuple[str, int]] | None = None,
        now=None,
    ):
        self.secret = identity_secret
        self.pubkey = golden.public_from_secret(identity_secret)
        self.shred_version = shred_version
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(bind)
        self.sock.setblocking(False)
        self.addr = self.sock.getsockname()
        self.tpu_addr = tpu_addr
        self.entrypoints = list(entrypoints or [])
        self.crds: dict[tuple[bytes, int], CrdsValue] = {}
        self.peers: dict[bytes, _Peer] = {}
        #: outstanding bootstrap ping tokens, one per entrypoint addr
        self._pending_pings: dict[tuple[str, int], bytes] = {}
        self._now = now or time.monotonic
        self._rng = os.urandom
        self.stats = {
            "rx": 0, "tx": 0, "push_rx": 0, "pull_rx": 0,
            "bad_sig": 0, "stale": 0,
        }
        self._refresh_self()

    # ---- CRDS ------------------------------------------------------------

    def _refresh_self(self) -> None:
        me = ContactInfo(
            self.pubkey, self.shred_version, self.addr, self.tpu_addr
        )
        self._self_value = make_value(self.secret, V_CONTACT, me.body())
        self._upsert(self._self_value, verified=True)

    def _upsert(self, v: CrdsValue, verified: bool = False) -> bool:
        """Insert if newer than what we hold; returns True when adopted."""
        cur = self.crds.get(v.key())
        if cur is not None and cur.wallclock >= v.wallclock:
            self.stats["stale"] += 1
            return False
        if not verified and not v.verify():
            self.stats["bad_sig"] += 1
            return False
        self.crds[v.key()] = v
        if v.vkind == V_CONTACT and v.origin != self.pubkey:
            ci = ContactInfo.from_value(v)
            p = self.peers.get(v.origin)
            if p is None:
                self.peers[v.origin] = _Peer(ci)
            else:
                p.contact = ci
        return True

    def contacts(self) -> list[ContactInfo]:
        return [
            ContactInfo.from_value(v)
            for v in self.crds.values()
            if v.vkind == V_CONTACT
        ]

    # ---- wire ------------------------------------------------------------

    def _send(self, payload: bytes, addr) -> None:
        try:
            self.sock.sendto(payload, addr)
            self.stats["tx"] += 1
        except OSError:
            pass

    def _encode_values(self, kind: int, values: list[CrdsValue]) -> bytes:
        out = bytes([kind]) + struct.pack("<H", len(values))
        for v in values:
            out += v.encode()
        return out

    def _decode_values(self, data: bytes, off: int) -> list[CrdsValue]:
        if len(data) < off + 2:
            return []
        (n,) = struct.unpack_from("<H", data, off)
        off += 2
        out = []
        for _ in range(min(n, 64)):
            hit = CrdsValue.decode(data, off)
            if hit is None:
                break
            v, off = hit
            out.append(v)
        return out

    # ---- protocol drivers ------------------------------------------------

    def tick(self) -> None:
        """One round: drain rx, ping entrypoints/peers, push, pull."""
        self._drain_rx()
        now = self._now()
        # bootstrap: ping entrypoints we know nothing about yet (one
        # outstanding token per entrypoint so concurrent bootstraps work)
        for ep in self.entrypoints:
            if any(
                p.contact.gossip_addr == ep for p in self.peers.values()
            ):
                self._pending_pings.pop(ep, None)
                continue
            token = self._pending_pings.get(ep)
            if token is None:
                token = self._rng(32)
                self._pending_pings[ep] = token
            self._send(bytes([MSG_PING]) + token, ep)
        live = [
            p for p in self.peers.values()
            if now - p.last_pong <= LIVENESS_S
        ]
        stale = [
            p for p in self.peers.values()
            if now - p.last_pong > LIVENESS_S
        ]
        for p in stale:
            token = self._rng(32)
            p.ping_token = token
            self._send(bytes([MSG_PING]) + token, p.contact.gossip_addr)
        # push: my newest values to up to PUSH_FANOUT live peers
        if live:
            values = list(self.crds.values())[:32]
            msg = self._encode_values(MSG_PUSH, values)
            for p in live[:PUSH_FANOUT]:
                self._send(msg, p.contact.gossip_addr)
            # pull: anti-entropy with one live peer
            target = live[int.from_bytes(self._rng(2), "little") % len(live)]
            have = struct.pack(
                "<H", min(len(self.crds), 1024)
            ) + b"".join(
                struct.pack("<Q", v.digest64())
                for v in list(self.crds.values())[:1024]
            )
            self._send(
                bytes([MSG_PULLQ]) + have + self._self_value.encode(),
                target.contact.gossip_addr,
            )

    def _drain_rx(self) -> None:
        while True:
            try:
                data, addr = self.sock.recvfrom(65536)
            except BlockingIOError:
                return
            except OSError:
                return
            self.stats["rx"] += 1
            try:
                self._on_msg(data, addr)
            except (struct.error, IndexError, ValueError):
                continue  # malformed datagram: drop

    def _on_msg(self, data: bytes, addr) -> None:
        if not data:
            return
        kind = data[0]
        if kind == MSG_PING and len(data) >= 33:
            self._send(
                bytes([MSG_PONG]) + hashlib.sha256(data[1:33]).digest(), addr
            )
            # answer with our contact so bootstrap converges fast
            self._send(
                self._encode_values(MSG_PUSH, [self._self_value]), addr
            )
        elif kind == MSG_PONG and len(data) >= 33:
            for p in self.peers.values():
                if p.ping_token and hashlib.sha256(
                    p.ping_token
                ).digest() == data[1:33]:
                    p.last_pong = self._now()
                    p.ping_token = b""
            # entrypoint pong (no peer entry yet): match against every
            # outstanding entrypoint token
            for ep, tok in list(self._pending_pings.items()):
                if hashlib.sha256(tok).digest() == data[1:33]:
                    del self._pending_pings[ep]
                    break
        elif kind == MSG_PUSH:
            self.stats["push_rx"] += 1
            for v in self._decode_values(data, 1):
                self._upsert(v)
            # learning a contact from a ping-answer counts as liveness
            for p in self.peers.values():
                if p.contact.gossip_addr == addr and p.last_pong == 0.0:
                    p.last_pong = self._now()
        elif kind == MSG_PULLQ:
            (n,) = struct.unpack_from("<H", data, 1)
            o = 3
            have = set()
            for _ in range(min(n, 1024)):
                have.add(struct.unpack_from("<Q", data, o)[0])
                o += 8
            hit = CrdsValue.decode(data, o)
            if hit is not None:
                self._upsert(hit[0])
            missing = [
                v for v in self.crds.values() if v.digest64() not in have
            ][:32]
            if missing:
                self._send(self._encode_values(MSG_PULLR, missing), addr)
        elif kind == MSG_PULLR:
            self.stats["pull_rx"] += 1
            for v in self._decode_values(data, 1):
                self._upsert(v)

    def close(self) -> None:
        self.sock.close()
