"""Gossip: CRDS replication over UDP — how a validator learns the
cluster.

Reference model: src/flamenco/gossip/fd_gossip.c (1,957 LoC) — the
Solana gossip protocol: a conflict-free replicated data store (CRDS) of
signed values (contact info, votes, ...) keyed by (origin, kind), newest
wallclock wins; spread by push (eager fanout to live peers) and pull
(anti-entropy: bloom-filtered requests answered with missing values),
with ping/pong tokens proving peer liveness, and prune messages cutting
redundant push routes.

Round 4: the wire format IS the mainnet bincode layout
(flamenco/gossip_types.py declares the schemas from the reference's
fd_types.json): gossip_msg = u32-tagged enum {pull_req, pull_resp,
push_msg, prune_msg, ping, pong}; values are CrdsValue {signature,
crds_data}; pull filters are CrdsFilter blooms whose bit positions use
the reference's FNV-mix (fd_gossip.c fd_gossip_bloom_pos).  Signatures
cover bincode(crds_data) and are verified on receipt.
"""

from __future__ import annotations

import hashlib
import os
import socket
import struct
import time
from dataclasses import dataclass, field

from firedancer_tpu.flamenco import gossip_types as GT
from firedancer_tpu.flamenco.bincode import encode
from firedancer_tpu.ops.ed25519 import golden

#: push fanout (reference default push fanout class)
PUSH_FANOUT = 6
#: peer considered live if a pong arrived within this window
LIVENESS_S = 20.0
#: drop values older than this (reference CRDS timeouts)
VALUE_TTL_S = 60.0
#: bloom geometry for outgoing pull requests (reference sizes its filter
#: to the packet budget; these are scaled-down equivalents)
BLOOM_BITS = 4096
BLOOM_KEYS = 3
#: stale duplicate pushes from one relayer before we prune it for the
#: duplicated origins (reference prune behavior)
PRUNE_DUP_THRESHOLD = 3
#: prune routes expire after this long (reference: prunes time out)
PRUNE_TTL_S = 500.0
#: stake-weighted push active set resample period (reference rotates its
#: active set on a similar cadence)
ACTIVE_SET_REFRESH_S = 7.5


def _pong_token(ping_token: bytes) -> bytes:
    """Pong token = sha256("SOLANA_PING_PONG" || ping token) — the
    reference's response-hash domain separation (fd_gossip.c:496,745)."""
    return hashlib.sha256(b"SOLANA_PING_PONG" + ping_token).digest()


def bloom_pos(value_hash: bytes, key: int, nbits: int) -> int:
    """The reference's hash->bit-position FNV mix (fd_gossip.c
    fd_gossip_bloom_pos): key ^= byte; key *= FNV prime; pos = key %
    nbits."""
    for i in range(32):
        key ^= value_hash[i]
        key = (key * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    return key % nbits


@dataclass(frozen=True)
class ContactInfo:
    """App-facing view of a contact_info_v1 CRDS datum."""

    pubkey: bytes
    shred_version: int
    gossip_addr: tuple[str, int]
    tpu_addr: tuple[str, int]
    wallclock: int = 0

    def to_data(self) -> tuple:
        payload = {
            "id": self.pubkey,
            "gossip": GT.sock(*self.gossip_addr),
            "tvu": dict(GT.UNSPEC_SOCKET),
            "tvu_fwd": dict(GT.UNSPEC_SOCKET),
            "repair": dict(GT.UNSPEC_SOCKET),
            "tpu": GT.sock(*self.tpu_addr),
            "tpu_fwd": dict(GT.UNSPEC_SOCKET),
            "tpu_vote": dict(GT.UNSPEC_SOCKET),
            "rpc": dict(GT.UNSPEC_SOCKET),
            "rpc_pubsub": dict(GT.UNSPEC_SOCKET),
            "serve_repair": dict(GT.UNSPEC_SOCKET),
            "wallclock": self.wallclock,
            "shred_version": self.shred_version,
        }
        return ("contact_info_v1", payload)

    @classmethod
    def from_data(cls, data: tuple) -> "ContactInfo | None":
        name, p = data
        if name != "contact_info_v1":
            return None
        g = GT.sock_to_tuple(p["gossip"])
        t = GT.sock_to_tuple(p["tpu"])
        if g is None or t is None:
            return None
        return cls(p["id"], p["shred_version"], g, t, p["wallclock"])


def make_contact_value(secret: bytes, ci: ContactInfo) -> dict:
    return GT.sign_crds(secret, ci.to_data())


@dataclass
class _Peer:
    contact: ContactInfo
    last_pong: float = 0.0
    ping_token: bytes = b""
    #: origins this peer asked us not to push to it (prune protocol):
    #: origin -> monotonic expiry time
    pruned: dict = field(default_factory=dict)
    #: per-origin stale-duplicate counts feeding our outgoing prunes
    dup_counts: dict = field(default_factory=dict)
    #: push cursor: values with adopt-seq > this still need pushing
    push_seq: int = 0


class GossipNode:
    """One gossip endpoint over a real UDP socket (non-blocking)."""

    def __init__(
        self,
        identity_secret: bytes,
        *,
        shred_version: int = 1,
        bind=("127.0.0.1", 0),
        tpu_addr=("127.0.0.1", 0),
        entrypoints: list[tuple[str, int]] | None = None,
        now=None,
        stakes: dict | None = None,
    ):
        """stakes: pubkey -> stake lamports; drives stake-weighted push
        active-set selection (reference: fd_gossip.c maintains a
        stake-ordered active push set and refreshes it periodically)."""
        self.secret = identity_secret
        self.pubkey = golden.public_from_secret(identity_secret)
        self.shred_version = shred_version
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(bind)
        self.sock.setblocking(False)
        self.addr = self.sock.getsockname()
        self.tpu_addr = tpu_addr
        self.entrypoints = list(entrypoints or [])
        #: CRDS table: crds_label -> {"signature", "data"}
        self.crds: dict[tuple, dict] = {}
        #: cached sha256(bincode(value)) per label (pull-filter identity)
        self._hashes: dict[tuple, bytes] = {}
        #: monotonically increasing adopt sequence per label (push-once)
        self._adopt_seq: dict[tuple, int] = {}
        self._seq = 0
        self.peers: dict[bytes, _Peer] = {}
        #: outstanding bootstrap ping tokens, one per entrypoint addr
        self._pending_pings: dict[tuple[str, int], bytes] = {}
        self._now = now or time.monotonic
        self._rng = os.urandom
        self.stakes: dict[bytes, int] = dict(stakes or {})
        #: current push active set (origin pubkeys), stake-weight sampled
        self._active_set: list[bytes] = []
        self._active_refresh_at = 0.0
        self.stats = {
            "rx": 0, "tx": 0, "push_rx": 0, "pull_rx": 0,
            "bad_sig": 0, "stale": 0, "prune_rx": 0, "prune_tx": 0,
        }
        self._refresh_self()

    # ---- CRDS ------------------------------------------------------------

    def _refresh_self(self) -> None:
        me = ContactInfo(
            self.pubkey, self.shred_version, self.addr, self.tpu_addr,
            wallclock=int(time.time() * 1000),
        )
        self._self_value = make_contact_value(self.secret, me)
        self._upsert(self._self_value, verified=True)

    def _upsert(self, v: dict, verified: bool = False,
                relayer: bytes | None = None) -> bool:
        """Insert if newer than what we hold; returns True when adopted."""
        label = GT.crds_label(v["data"])
        cur = self.crds.get(label)
        if cur is not None and (
            GT.crds_wallclock(cur["data"]) >= GT.crds_wallclock(v["data"])
        ):
            self.stats["stale"] += 1
            if relayer is not None:
                # byte-identical to the stored (already verified) value ->
                # known-good duplicate; different bytes must re-verify
                # before they can feed prune counters (forgeries must not
                # sever honest push routes)
                if GT.value_hash(v) == self._hashes.get(label) or (
                    GT.verify_crds(v)
                ):
                    p = self.peers.get(relayer)
                    if p is not None:
                        origin = GT.crds_origin(v["data"])
                        p.dup_counts[origin] = p.dup_counts.get(origin, 0) + 1
            return False
        if not verified and not GT.verify_crds(v):
            self.stats["bad_sig"] += 1
            return False
        self.crds[label] = v
        self._hashes[label] = GT.value_hash(v)
        self._seq += 1
        self._adopt_seq[label] = self._seq
        origin = GT.crds_origin(v["data"])
        ci = ContactInfo.from_data(v["data"])
        if ci is not None and origin != self.pubkey:
            p = self.peers.get(origin)
            if p is None:
                self.peers[origin] = _Peer(ci)
            else:
                p.contact = ci
        return True

    def contacts(self) -> list[ContactInfo]:
        out = []
        for v in self.crds.values():
            ci = ContactInfo.from_data(v["data"])
            if ci is not None:
                out.append(ci)
        return out

    # ---- wire ------------------------------------------------------------

    def _send(self, msg, addr) -> None:
        try:
            self.sock.sendto(GT.encode_msg(msg), addr)
            self.stats["tx"] += 1
        except OSError:
            pass

    def _make_ping(self, token: bytes) -> tuple:
        return ("ping", {
            "from": self.pubkey,
            "token": token,
            "signature": golden.sign(self.secret, token),
        })

    def _make_pull_filter(self) -> dict:
        """CrdsFilter bloom over every value hash we hold (single-shard:
        mask_bits 0 means every hash falls in this filter's partition)."""
        keys = [
            int.from_bytes(self._rng(8), "little") for _ in range(BLOOM_KEYS)
        ]
        words = [0] * (BLOOM_BITS // 64)
        nset = 0
        for h in self._hashes.values():
            for k in keys:
                pos = bloom_pos(h, k, BLOOM_BITS)
                w, b = divmod(pos, 64)
                if not words[w] >> b & 1:
                    nset += 1
                words[w] |= 1 << b
        return {
            "filter": {
                "keys": keys,
                "bits": {"bits": {"vec": words}, "len": BLOOM_BITS},
                "num_bits_set": nset,
            },
            "mask": (1 << 64) - 1,
            "mask_bits": 0,
        }

    def _filter_misses(self, flt: dict) -> list[dict]:
        """Values we hold that the requester's bloom does NOT contain
        (reference: fd_gossip.c pull-request handling)."""
        keys = flt["filter"]["keys"]
        bv = flt["filter"]["bits"]
        words = bv["bits"]["vec"] if bv["bits"] else []
        nbits = bv["len"] or 1
        mask = flt["mask"]
        mask_bits = flt["mask_bits"]
        out = []
        for label, v in self.crds.items():
            h = self._hashes[label]
            if mask_bits:
                m = (1 << 64) - 1 >> mask_bits
                if (int.from_bytes(h[:8], "little") | m) != mask:
                    continue  # not this filter's hash-space shard
            hit = True
            for k in keys:
                pos = bloom_pos(h, k, nbits)
                w, b = divmod(pos, 64)
                if w >= len(words) or not words[w] >> b & 1:
                    hit = False
                    break
            if not hit or not keys:
                out.append(v)
        return out

    # ---- protocol drivers ------------------------------------------------

    def tick(self) -> None:
        """One round: drain rx, ping entrypoints/peers, push, pull,
        prune redundant relayers."""
        self._drain_rx()
        now = self._now()
        # bootstrap: ping entrypoints we know nothing about yet (one
        # outstanding token per entrypoint so concurrent bootstraps work)
        for ep in self.entrypoints:
            if any(
                p.contact.gossip_addr == ep for p in self.peers.values()
            ):
                self._pending_pings.pop(ep, None)
                continue
            token = self._pending_pings.get(ep)
            if token is None:
                token = self._rng(32)
                self._pending_pings[ep] = token
            self._send(self._make_ping(token), ep)
        live = [
            p for p in self.peers.values()
            if now - p.last_pong <= LIVENESS_S
        ]
        stale = [
            p for p in self.peers.values()
            if now - p.last_pong > LIVENESS_S
        ]
        for p in stale:
            token = self._rng(32)
            p.ping_token = token
            self._send(self._make_ping(token), p.contact.gossip_addr)
        if live:
            # push: values adopted since each peer's cursor (push-once,
            # like the reference's push queue), honoring prune routes
            # (expired prunes reopen).  Targets come from the
            # stake-weighted active set, refreshed periodically.
            for p in self._push_targets(live, now):
                for origin, exp in list(p.pruned.items()):
                    if now >= exp:
                        del p.pruned[origin]
                        # the push cursor advanced past values skipped
                        # under this prune; rewind below the earliest
                        # adopt-seq of the origin's values so they are
                        # pushed after all (re-pushing a few other
                        # values is harmless: upserts are idempotent)
                        seqs = [
                            seq
                            for label, seq in self._adopt_seq.items()
                            if GT.crds_origin(self.crds[label]["data"])
                            == origin
                        ]
                        if seqs:
                            p.push_seq = min(p.push_seq, min(seqs) - 1)
                pending = sorted(
                    (seq, label)
                    for label, seq in self._adopt_seq.items()
                    if seq > p.push_seq
                )
                send = []
                for seq, label in pending:
                    origin = GT.crds_origin(self.crds[label]["data"])
                    if origin not in p.pruned:
                        send.append(self.crds[label])
                        if len(send) >= 32:
                            p.push_seq = seq
                            break
                else:
                    p.push_seq = self._seq
                if send:
                    self._send(("push_msg", {
                        "pubkey": self.pubkey, "crds": send,
                    }), p.contact.gossip_addr)
            # pull: anti-entropy with one live peer
            target = live[int.from_bytes(self._rng(2), "little") % len(live)]
            self._send(("pull_req", {
                "filter": self._make_pull_filter(),
                "value": self._self_value,
            }), target.contact.gossip_addr)
            # prune relayers that keep pushing duplicates
            self._send_prunes()

    def set_stakes(self, stakes: dict) -> None:
        """Replace the stake map and force an active-set refresh."""
        self.stakes = dict(stakes)
        self._active_refresh_at = 0.0

    def _push_targets(self, live: list, now: float) -> list:
        """PUSH_FANOUT live peers sampled ∝ (stake + 1) without
        replacement — the reference's stake-weighted active set
        (fd_gossip.c active-set maintenance; +1 keeps zero-stake nodes
        reachable).  Resampled every ACTIVE_SET_REFRESH_S so route
        diversity rotates like the reference's periodic refresh."""
        by_origin = {
            origin: p for origin, p in self.peers.items() if p in live
        }
        if now >= self._active_refresh_at or not all(
            o in by_origin for o in self._active_set
        ):
            self._active_refresh_at = now + ACTIVE_SET_REFRESH_S
            pool = list(by_origin)
            weights = [self.stakes.get(o, 0) + 1 for o in pool]
            chosen: list[bytes] = []
            while pool and len(chosen) < PUSH_FANOUT:
                total = sum(weights)
                r = int.from_bytes(self._rng(8), "little") % total
                for i, w in enumerate(weights):
                    r -= w
                    if r < 0:
                        break
                chosen.append(pool.pop(i))
                weights.pop(i)
            self._active_set = chosen
        return [by_origin[o] for o in self._active_set if o in by_origin]

    def _send_prunes(self) -> None:
        for origin, p in self.peers.items():
            dups = [
                o for o, c in p.dup_counts.items()
                if c >= PRUNE_DUP_THRESHOLD
            ]
            if not dups or origin in (None, self.pubkey):
                continue
            wallclock = int(time.time() * 1000)
            sign_payload = encode(GT.PRUNE_SIGN_DATA, {
                "pubkey": self.pubkey, "prunes": dups,
                "destination": origin, "wallclock": wallclock,
            })
            self._send(("prune_msg", {
                "pubkey": self.pubkey,
                "data": {
                    "pubkey": self.pubkey,
                    "prunes": dups,
                    "signature": golden.sign(self.secret, sign_payload),
                    "destination": origin,
                    "wallclock": wallclock,
                },
            }), p.contact.gossip_addr)
            self.stats["prune_tx"] += 1
            p.dup_counts.clear()

    def _drain_rx(self) -> None:
        while True:
            try:
                data, addr = self.sock.recvfrom(65536)
            except BlockingIOError:
                return
            except OSError:
                return
            self.stats["rx"] += 1
            try:
                self._on_msg(GT.decode_msg(data), addr)
            except (struct.error, IndexError, ValueError, KeyError):
                continue  # malformed datagram: drop

    def _on_msg(self, msg, addr) -> None:
        kind, body = msg
        if kind == "ping":
            # the reference verifies the ping signature before answering
            # (fd_gossip.c:475-485) and hashes the pong token as
            # sha256("SOLANA_PING_PONG" || token) (fd_gossip.c:496)
            if golden.verify(
                body["token"], body["signature"], body["from"]
            ):
                return
            pong_token = _pong_token(body["token"])
            self._send(("pong", {
                "from": self.pubkey,
                "token": pong_token,
                "signature": golden.sign(self.secret, pong_token),
            }), addr)
            # answer with our contact so bootstrap converges fast
            self._send(("push_msg", {
                "pubkey": self.pubkey, "crds": [self._self_value],
            }), addr)
        elif kind == "pong":
            # verify the pong signature before trusting it for liveness
            # (the reference verifies at fd_gossip.c:754-760)
            got = body["token"]
            if golden.verify(got, body["signature"], body["from"]):
                return
            # the signature must bind to the IDENTITY we pinged, not just
            # any key: an on-path observer of the ping token could
            # otherwise keep a dead peer marked alive with its own
            # signature (the reference verifies against the pinged
            # peer's key, fd_gossip.c:754-760)
            for origin, p in self.peers.items():
                if (
                    p.ping_token
                    and origin == body["from"]
                    and _pong_token(p.ping_token) == got
                ):
                    p.last_pong = self._now()
                    p.ping_token = b""
            # entrypoint pong (no peer entry yet): match against every
            # outstanding entrypoint token
            for ep, tok in list(self._pending_pings.items()):
                if _pong_token(tok) == got:
                    del self._pending_pings[ep]
                    break
        elif kind == "push_msg":
            self.stats["push_rx"] += 1
            for v in body["crds"][:64]:
                self._upsert(v, relayer=body["pubkey"])
            # learning a contact from a ping-answer counts as liveness
            for p in self.peers.values():
                if p.contact.gossip_addr == addr and p.last_pong == 0.0:
                    p.last_pong = self._now()
        elif kind == "pull_req":
            self._upsert(body["value"])
            missing = self._filter_misses(body["filter"])[:32]
            if missing:
                self._send(("pull_resp", {
                    "pubkey": self.pubkey, "crds": missing,
                }), addr)
        elif kind == "pull_resp":
            self.stats["pull_rx"] += 1
            for v in body["crds"][:64]:
                self._upsert(v)
        elif kind == "prune_msg":
            self.stats["prune_rx"] += 1
            d = body["data"]
            if d["destination"] != self.pubkey:
                return
            sign_payload = encode(GT.PRUNE_SIGN_DATA, {
                "pubkey": d["pubkey"], "prunes": d["prunes"],
                "destination": d["destination"], "wallclock": d["wallclock"],
            })
            if golden.verify(sign_payload, d["signature"], d["pubkey"]) != 0:
                self.stats["bad_sig"] += 1
                return
            p = self.peers.get(d["pubkey"])
            if p is not None:
                exp = self._now() + PRUNE_TTL_S
                for o in d["prunes"]:
                    p.pruned[o] = exp

    def close(self) -> None:
        self.sock.close()
