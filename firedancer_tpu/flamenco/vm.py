"""sBPF virtual machine interpreter.

Reference model: src/flamenco/vm/fd_vm_interp.c (computed-goto dispatch
over the sBPF instruction set), fd_vm_context.c (memory map), and
fd_vm_syscalls.c.  This is a host-side Python interpreter covering the
base integer ISA the loader emits — execution is control-plane work here
(the TPU data plane is verify/dedup); the per-instruction dict dispatch is
the honest Python analog of the reference's jump table, with the same
register file shape, memory regions, and compute-unit metering.

ISA covered: ALU64/ALU32 (add sub mul div or and lsh rsh neg mod xor mov
arsh), LD_IMM64, LDX/ST/STX {b,h,w,dw}, all JMP/JMP32 conditions, CALL
(registered syscalls by murmur3 id), CALLX, EXIT.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from firedancer_tpu.ballet.sbpf import (
    MM_HEAP, MM_INPUT, MM_PROGRAM, MM_STACK, Program, syscall_hash,
)

U64 = (1 << 64) - 1
U32 = (1 << 32) - 1

STACK_FRAME_SZ = 4096
MAX_CALL_DEPTH = 64


class VmError(Exception):
    pass


def _s64(x: int) -> int:
    return x - (1 << 64) if x & (1 << 63) else x


def _s32(x: int) -> int:
    x &= U32
    return x - (1 << 32) if x & (1 << 31) else x


@dataclass
class Vm:
    prog: Program
    heap_sz: int = 32 * 1024
    cu_limit: int = 200_000
    input_mem: bytearray = field(default_factory=bytearray)

    def __post_init__(self):
        self.reg = [0] * 11
        self.stack = bytearray(STACK_FRAME_SZ * MAX_CALL_DEPTH)
        self.heap = bytearray(self.heap_sz)
        self.cu = self.cu_limit
        self.logs: list[bytes] = []
        self.call_depth = 0
        self._ret_stack: list[int] = []
        self.syscalls: dict[int, callable] = {}
        self._register_default_syscalls()
        self.reg[1] = MM_INPUT
        self.reg[10] = MM_STACK + STACK_FRAME_SZ  # frame pointer

    # ---- memory map -----------------------------------------------------

    def _region(self, addr: int, sz: int):
        """Map a VM address to (buffer, offset, writable)."""
        for base, buf, writable in (
            (MM_PROGRAM, self.prog.rodata, False),
            (MM_STACK, self.stack, True),
            (MM_HEAP, self.heap, True),
            (MM_INPUT, self.input_mem, True),
        ):
            off = addr - base
            if 0 <= off and off + sz <= len(buf):
                return buf, off, writable
        raise VmError(f"memory access violation at {addr:#x} sz {sz}")

    def mem_read(self, addr: int, sz: int) -> int:
        buf, off, _ = self._region(addr, sz)
        return int.from_bytes(buf[off : off + sz], "little")

    def mem_read_bytes(self, addr: int, sz: int) -> bytes:
        buf, off, _ = self._region(addr, sz)
        return bytes(buf[off : off + sz])

    def mem_write(self, addr: int, sz: int, val: int) -> None:
        buf, off, writable = self._region(addr, sz)
        if not writable:
            raise VmError(f"write to read-only memory at {addr:#x}")
        buf[off : off + sz] = (val & ((1 << (8 * sz)) - 1)).to_bytes(
            sz, "little"
        )

    # ---- syscalls -------------------------------------------------------

    def register_syscall(self, name: bytes, fn) -> None:
        self.syscalls[syscall_hash(name)] = fn

    def _register_default_syscalls(self) -> None:
        def sol_log(vm, r1, r2, r3, r4, r5):
            vm.logs.append(vm.mem_read_bytes(r1, r2))
            return 0

        def sol_log_64(vm, r1, r2, r3, r4, r5):
            vm.logs.append(
                b"%x %x %x %x %x" % (r1, r2, r3, r4, r5)
            )
            return 0

        def sol_memcpy(vm, r1, r2, r3, r4, r5):
            data = vm.mem_read_bytes(r2, r3)
            for i, b in enumerate(data):
                vm.mem_write(r1 + i, 1, b)
            return 0

        def abort(vm, r1, r2, r3, r4, r5):
            raise VmError("abort() called")

        self.register_syscall(b"sol_log_", sol_log)
        self.register_syscall(b"sol_log_64_", sol_log_64)
        self.register_syscall(b"sol_memcpy_", sol_memcpy)
        self.register_syscall(b"abort", abort)

    # ---- interpreter ----------------------------------------------------

    def run(self) -> int:
        """Execute from the entrypoint; returns r0.  Raises VmError on
        fault or CU exhaustion."""
        text = self.prog.text
        n_ins = len(text) // 8
        pc = self.prog.entry_pc
        reg = self.reg
        while True:
            if not 0 <= pc < n_ins:
                raise VmError(f"pc out of bounds: {pc}")
            self.cu -= 1
            if self.cu < 0:
                raise VmError("compute budget exceeded")
            ins = text[8 * pc : 8 * pc + 8]
            op = ins[0]
            dst = ins[1] & 0xF
            src = ins[1] >> 4
            off = int.from_bytes(ins[2:4], "little", signed=True)
            imm = int.from_bytes(ins[4:8], "little", signed=True)
            cls = op & 7
            pc += 1

            if op == 0x18:  # lddw
                if pc >= n_ins:
                    raise VmError("truncated lddw")
                hi = int.from_bytes(text[8 * pc + 4 : 8 * pc + 8], "little")
                reg[dst] = ((imm & U32) | (hi << 32)) & U64
                pc += 1
            elif cls in (0x07, 0x04):  # ALU64 / ALU32
                is64 = cls == 0x07
                b = reg[src] if op & 0x08 else imm & (U64 if is64 else U32)
                a = reg[dst] if is64 else reg[dst] & U32
                if not is64:
                    b &= U32
                code = op & 0xF0
                if code == 0x00:
                    r = a + b
                elif code == 0x10:
                    r = a - b
                elif code == 0x20:
                    r = a * b
                elif code == 0x30:
                    if b == 0:
                        raise VmError("division by zero")
                    r = a // b
                elif code == 0x40:
                    r = a | b
                elif code == 0x50:
                    r = a & b
                elif code == 0x60:
                    r = a << (b & (63 if is64 else 31))
                elif code == 0x70:
                    r = a >> (b & (63 if is64 else 31))
                elif code == 0x80:  # neg
                    r = -a
                elif code == 0x90:
                    if b == 0:
                        raise VmError("division by zero")
                    r = a % b
                elif code == 0xA0:
                    r = a ^ b
                elif code == 0xB0:
                    r = b
                elif code == 0xC0:  # arsh
                    sa = _s64(a) if is64 else _s32(a)
                    r = sa >> (b & (63 if is64 else 31))
                else:
                    raise VmError(f"bad ALU opcode {op:#x}")
                reg[dst] = r & (U64 if is64 else U32)
            elif cls == 0x05 or cls == 0x06:  # JMP / JMP32
                is64 = cls == 0x05
                if op == 0x05:  # ja
                    pc += off
                    continue
                if op == 0x85:  # call: registered syscall, else bpf-to-bpf
                    fnid = imm & U32
                    if fnid in self.syscalls:
                        self._call(imm)
                    else:
                        self.call_depth += 1
                        if self.call_depth >= MAX_CALL_DEPTH:
                            raise VmError("call depth exceeded")
                        self._ret_stack.append(pc)
                        reg[10] += STACK_FRAME_SZ
                        pc += imm  # relative target (signed imm)
                    continue
                if op == 0x8D:  # callx
                    raise VmError("callx unsupported")
                if op == 0x95:  # exit
                    if self._ret_stack:
                        pc = self._ret_stack.pop()
                        self.call_depth -= 1
                        reg[10] -= STACK_FRAME_SZ
                        continue
                    return reg[0]
                a = reg[dst] if is64 else reg[dst] & U32
                b = reg[src] if op & 0x08 else imm & (U64 if is64 else U32)
                if not is64:
                    b &= U32
                sa = _s64(a) if is64 else _s32(a)
                sb = (_s64(b) if is64 else _s32(b)) if op & 0x08 else imm
                code = op & 0xF0
                taken = {
                    0x10: a == b,
                    0x20: a > b,
                    0x30: a >= b,
                    0xA0: a < b,
                    0xB0: a <= b,
                    0x40: bool(a & b),
                    0x50: a != b,
                    0x60: sa > sb,
                    0x70: sa >= sb,
                    0xC0: sa < sb,
                    0xD0: sa <= sb,
                }.get(code)
                if taken is None:
                    raise VmError(f"bad JMP opcode {op:#x}")
                if taken:
                    pc += off
            elif cls in (0x01, 0x02, 0x03):  # LDX / ST / STX
                sz = {0x10: 1, 0x08: 2, 0x00: 4, 0x18: 8}[op & 0x18]
                if cls == 0x01:  # ldx
                    reg[dst] = self.mem_read((reg[src] + off) & U64, sz)
                elif cls == 0x02:  # st imm
                    self.mem_write((reg[dst] + off) & U64, sz, imm & U64)
                else:  # stx
                    self.mem_write((reg[dst] + off) & U64, sz, reg[src])
            else:
                raise VmError(f"unknown opcode {op:#x}")
        raise AssertionError("unreachable")

    def _call(self, imm: int) -> None:
        fn = self.syscalls.get(imm & U32)
        if fn is None:
            raise VmError(f"unknown syscall {imm & U32:#x}")
        self.cu -= 100
        if self.cu < 0:
            raise VmError("compute budget exceeded")
        self.reg[0] = (
            fn(self, *(self.reg[1:6])) or 0
        ) & U64
