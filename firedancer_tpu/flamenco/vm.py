"""sBPF virtual machine interpreter.

Reference model: src/flamenco/vm/fd_vm_interp.c (computed-goto dispatch
over the sBPF instruction set), fd_vm_context.c (memory map), and
fd_vm_syscalls.c.  This is a host-side Python interpreter covering the
base integer ISA the loader emits — execution is control-plane work here
(the TPU data plane is verify/dedup); the per-instruction dict dispatch is
the honest Python analog of the reference's jump table, with the same
register file shape, memory regions, and compute-unit metering.

ISA covered: ALU64/ALU32 (add sub mul div or and lsh rsh neg mod xor mov
arsh), LD_IMM64, LDX/ST/STX {b,h,w,dw}, all JMP/JMP32 conditions, CALL
(registered syscalls by murmur3 id), CALLX, EXIT.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from firedancer_tpu.ballet.sbpf import (
    MM_HEAP, MM_INPUT, MM_PROGRAM, MM_STACK, Program, syscall_hash,
)

U64 = (1 << 64) - 1
U32 = (1 << 32) - 1

STACK_FRAME_SZ = 4096
MAX_CALL_DEPTH = 64


class VmError(Exception):
    pass


def _s64(x: int) -> int:
    return x - (1 << 64) if x & (1 << 63) else x


def _s32(x: int) -> int:
    x &= U32
    return x - (1 << 32) if x & (1 << 31) else x


@dataclass
class Vm:
    prog: Program
    heap_sz: int = 32 * 1024
    cu_limit: int = 200_000
    input_mem: bytearray = field(default_factory=bytearray)
    #: per-instruction tracer (fd_vm_trace.c analog): records
    #: (pc, opcode, regs snapshot) up to trace_limit entries
    trace: bool = False
    trace_limit: int = 65536

    def __post_init__(self):
        self.reg = [0] * 11
        self.stack = bytearray(STACK_FRAME_SZ * MAX_CALL_DEPTH)
        self.heap = bytearray(self.heap_sz)
        self.cu = self.cu_limit
        self.logs: list[bytes] = []
        self.trace_log: list[tuple[int, int, tuple[int, ...]]] = []
        self.call_depth = 0
        self._ret_stack: list[int] = []
        self.syscalls: dict[int, callable] = {}
        self._register_default_syscalls()
        self.reg[1] = MM_INPUT
        self.reg[10] = MM_STACK + STACK_FRAME_SZ  # frame pointer

    # ---- memory map -----------------------------------------------------

    def _region(self, addr: int, sz: int):
        """Map a VM address to (buffer, offset, writable)."""
        for base, buf, writable in (
            (MM_PROGRAM, self.prog.rodata, False),
            (MM_STACK, self.stack, True),
            (MM_HEAP, self.heap, True),
            (MM_INPUT, self.input_mem, True),
        ):
            off = addr - base
            if 0 <= off and off + sz <= len(buf):
                return buf, off, writable
        raise VmError(f"memory access violation at {addr:#x} sz {sz}")

    def mem_read(self, addr: int, sz: int) -> int:
        buf, off, _ = self._region(addr, sz)
        return int.from_bytes(buf[off : off + sz], "little")

    def mem_read_bytes(self, addr: int, sz: int) -> bytes:
        if sz == 0:
            return b""
        buf, off, _ = self._region(addr, sz)
        return bytes(buf[off : off + sz])

    def mem_write(self, addr: int, sz: int, val: int) -> None:
        buf, off, writable = self._region(addr, sz)
        if not writable:
            raise VmError(f"write to read-only memory at {addr:#x}")
        buf[off : off + sz] = (val & ((1 << (8 * sz)) - 1)).to_bytes(
            sz, "little"
        )

    # ---- syscalls -------------------------------------------------------

    def register_syscall(self, name: bytes, fn) -> None:
        self.syscalls[syscall_hash(name)] = fn

    def _register_default_syscalls(self) -> None:
        # per-syscall CU costs beyond the flat call cost (reference:
        # fd_vm_syscalls.c cost model — hashes charge base + per-byte)
        def sol_log(vm, r1, r2, r3, r4, r5):
            vm.consume(max(r2, 100))
            vm.logs.append(vm.mem_read_bytes(r1, r2))
            return 0

        def sol_log_64(vm, r1, r2, r3, r4, r5):
            vm.consume(100)
            vm.logs.append(
                b"%x %x %x %x %x" % (r1, r2, r3, r4, r5)
            )
            return 0

        def sol_log_pubkey(vm, r1, r2, r3, r4, r5):
            vm.consume(100)
            from firedancer_tpu.ballet import base58

            vm.logs.append(base58.encode_32(vm.mem_read_bytes(r1, 32)).encode())
            return 0

        def sol_memcpy(vm, r1, r2, r3, r4, r5):
            vm.consume(r3 // 250 + 1)
            data = vm.mem_read_bytes(r2, r3)
            vm.mem_write_bytes(r1, data)
            return 0

        def sol_memset(vm, r1, r2, r3, r4, r5):
            vm.consume(r3 // 250 + 1)
            vm.mem_write_bytes(r1, bytes([r2 & 0xFF]) * r3)
            return 0

        def sol_memcmp(vm, r1, r2, r3, r4, r5):
            vm.consume(r3 // 250 + 1)
            a = vm.mem_read_bytes(r1, r3)
            b = vm.mem_read_bytes(r2, r3)
            diff = 0
            for x, y in zip(a, b):
                if x != y:
                    diff = (x - y) & 0xFFFFFFFF
                    break
            vm.mem_write(r4, 4, diff)
            return 0

        def _hash_syscall(hasher, base_cost, byte_cost):
            def syscall(vm, r1, r2, r3, r4, r5):
                # r1 = &[(addr, len)] slice vector, r2 = count,
                # r3 = result address (32 bytes out)
                vm.consume(base_cost)
                h = hasher()
                for i in range(r2):
                    addr = vm.mem_read(r1 + 16 * i, 8)
                    ln = vm.mem_read(r1 + 16 * i + 8, 8)
                    vm.consume(ln * byte_cost // 100)
                    h.update(vm.mem_read_bytes(addr, ln))
                vm.mem_write_bytes(r3, h.digest())
                return 0

            return syscall

        import hashlib

        from firedancer_tpu.ops.keccak256 import digest_host

        class _Keccak:
            def __init__(self):
                self._buf = b""

            def update(self, b):
                self._buf += b

            def digest(self):
                return digest_host(self._buf)

        def abort(vm, r1, r2, r3, r4, r5):
            raise VmError("abort() called")

        self.register_syscall(b"sol_log_", sol_log)
        self.register_syscall(b"sol_log_64_", sol_log_64)
        self.register_syscall(b"sol_log_pubkey", sol_log_pubkey)
        self.register_syscall(b"sol_memcpy_", sol_memcpy)
        self.register_syscall(b"sol_memset_", sol_memset)
        self.register_syscall(b"sol_memcmp_", sol_memcmp)
        self.register_syscall(
            b"sol_sha256", _hash_syscall(hashlib.sha256, 85, 1)
        )
        self.register_syscall(
            b"sol_keccak256", _hash_syscall(_Keccak, 85, 1)
        )
        self.register_syscall(b"abort", abort)

    def consume(self, cus: int) -> None:
        """Charge compute units (syscall cost model)."""
        self.cu -= int(cus)
        if self.cu < 0:
            raise VmError("compute budget exceeded")

    def mem_write_bytes(self, addr: int, data: bytes) -> None:
        if not data:
            return
        buf, off, writable = self._region(addr, len(data))
        if not writable:
            raise VmError(f"write to read-only memory at {addr:#x}")
        buf[off : off + len(data)] = data

    # ---- interpreter ----------------------------------------------------

    def run(self) -> int:
        """Execute from the entrypoint; returns r0.  Raises VmError on
        fault or CU exhaustion."""
        text = self.prog.text
        n_ins = len(text) // 8
        pc = self.prog.entry_pc
        reg = self.reg
        while True:
            if not 0 <= pc < n_ins:
                raise VmError(f"pc out of bounds: {pc}")
            self.cu -= 1
            if self.cu < 0:
                raise VmError("compute budget exceeded")
            ins = text[8 * pc : 8 * pc + 8]
            op = ins[0]
            dst = ins[1] & 0xF
            src = ins[1] >> 4
            off = int.from_bytes(ins[2:4], "little", signed=True)
            imm = int.from_bytes(ins[4:8], "little", signed=True)
            cls = op & 7
            if self.trace and len(self.trace_log) < self.trace_limit:
                self.trace_log.append((pc, op, tuple(reg)))
            pc += 1

            if op == 0x18:  # lddw
                if pc >= n_ins:
                    raise VmError("truncated lddw")
                hi = int.from_bytes(text[8 * pc + 4 : 8 * pc + 8], "little")
                reg[dst] = ((imm & U32) | (hi << 32)) & U64
                pc += 1
            elif cls in (0x07, 0x04):  # ALU64 / ALU32
                is64 = cls == 0x07
                b = reg[src] if op & 0x08 else imm & (U64 if is64 else U32)
                a = reg[dst] if is64 else reg[dst] & U32
                if not is64:
                    b &= U32
                code = op & 0xF0
                if code == 0x00:
                    r = a + b
                elif code == 0x10:
                    r = a - b
                elif code == 0x20:
                    r = a * b
                elif code == 0x30:
                    if b == 0:
                        raise VmError("division by zero")
                    r = a // b
                elif code == 0x40:
                    r = a | b
                elif code == 0x50:
                    r = a & b
                elif code == 0x60:
                    r = a << (b & (63 if is64 else 31))
                elif code == 0x70:
                    r = a >> (b & (63 if is64 else 31))
                elif code == 0x80:  # neg
                    r = -a
                elif code == 0x90:
                    if b == 0:
                        raise VmError("division by zero")
                    r = a % b
                elif code == 0xA0:
                    r = a ^ b
                elif code == 0xB0:
                    r = b
                elif code == 0xC0:  # arsh
                    sa = _s64(a) if is64 else _s32(a)
                    r = sa >> (b & (63 if is64 else 31))
                else:
                    raise VmError(f"bad ALU opcode {op:#x}")
                reg[dst] = r & (U64 if is64 else U32)
            elif cls == 0x05 or cls == 0x06:  # JMP / JMP32
                is64 = cls == 0x05
                if op == 0x05:  # ja
                    pc += off
                    continue
                if op == 0x85:  # call: registered syscall, else bpf-to-bpf
                    fnid = imm & U32
                    if fnid in self.syscalls:
                        self._call(imm)
                    else:
                        self.call_depth += 1
                        if self.call_depth >= MAX_CALL_DEPTH:
                            raise VmError("call depth exceeded")
                        self._ret_stack.append(pc)
                        reg[10] += STACK_FRAME_SZ
                        pc += imm  # relative target (signed imm)
                    continue
                if op == 0x8D:  # callx: indirect bpf-to-bpf via reg[imm]
                    if not 0 <= imm < 11:
                        raise VmError(f"callx bad register r{imm}")
                    tgt = reg[imm]
                    rel = tgt - MM_PROGRAM - self.prog.text_addr
                    if rel < 0 or rel % 8 or rel // 8 >= n_ins:
                        raise VmError(f"callx target oob {tgt:#x}")
                    self.call_depth += 1
                    if self.call_depth >= MAX_CALL_DEPTH:
                        raise VmError("call depth exceeded")
                    self._ret_stack.append(pc)
                    reg[10] += STACK_FRAME_SZ
                    pc = rel // 8
                    continue
                if op == 0x95:  # exit
                    if self._ret_stack:
                        pc = self._ret_stack.pop()
                        self.call_depth -= 1
                        reg[10] -= STACK_FRAME_SZ
                        continue
                    return reg[0]
                a = reg[dst] if is64 else reg[dst] & U32
                b = reg[src] if op & 0x08 else imm & (U64 if is64 else U32)
                if not is64:
                    b &= U32
                sa = _s64(a) if is64 else _s32(a)
                sb = (_s64(b) if is64 else _s32(b)) if op & 0x08 else imm
                code = op & 0xF0
                taken = {
                    0x10: a == b,
                    0x20: a > b,
                    0x30: a >= b,
                    0xA0: a < b,
                    0xB0: a <= b,
                    0x40: bool(a & b),
                    0x50: a != b,
                    0x60: sa > sb,
                    0x70: sa >= sb,
                    0xC0: sa < sb,
                    0xD0: sa <= sb,
                }.get(code)
                if taken is None:
                    raise VmError(f"bad JMP opcode {op:#x}")
                if taken:
                    pc += off
            elif cls in (0x01, 0x02, 0x03):  # LDX / ST / STX
                sz = {0x10: 1, 0x08: 2, 0x00: 4, 0x18: 8}[op & 0x18]
                if cls == 0x01:  # ldx
                    reg[dst] = self.mem_read((reg[src] + off) & U64, sz)
                elif cls == 0x02:  # st imm
                    self.mem_write((reg[dst] + off) & U64, sz, imm & U64)
                else:  # stx
                    self.mem_write((reg[dst] + off) & U64, sz, reg[src])
            else:
                raise VmError(f"unknown opcode {op:#x}")
        raise AssertionError("unreachable")

    def _call(self, imm: int) -> None:
        fn = self.syscalls.get(imm & U32)
        if fn is None:
            raise VmError(f"unknown syscall {imm & U32:#x}")
        self.cu -= 100
        if self.cu < 0:
            raise VmError("compute budget exceeded")
        self.reg[0] = (
            fn(self, *(self.reg[1:6])) or 0
        ) & U64


# ---------------------------------------------------------------------------
# disassembler + trace formatting (fd_vm_disasm.c / fd_vm_trace.c analogs)
# ---------------------------------------------------------------------------

_ALU_NAMES = {0x00: "add", 0x10: "sub", 0x20: "mul", 0x30: "div",
              0x40: "or", 0x50: "and", 0x60: "lsh", 0x70: "rsh",
              0x80: "neg", 0x90: "mod", 0xA0: "xor", 0xB0: "mov",
              0xC0: "arsh"}
_JMP_NAMES = {0x00: "ja", 0x10: "jeq", 0x20: "jgt", 0x30: "jge",
              0x40: "jset", 0x50: "jne", 0x60: "jsgt", 0x70: "jsge",
              0xA0: "jlt", 0xB0: "jle", 0xC0: "jslt", 0xD0: "jsle"}
_SIZES = {0x10: "b", 0x08: "h", 0x00: "w", 0x18: "dw"}


def disasm(ins: bytes) -> str:
    """One 8-byte instruction -> assembly-ish text."""
    op = ins[0]
    dst = ins[1] & 0xF
    src = ins[1] >> 4
    off = int.from_bytes(ins[2:4], "little", signed=True)
    imm = int.from_bytes(ins[4:8], "little", signed=True)
    cls = op & 7
    if op == 0x18:
        return f"lddw r{dst}, {imm:#x}(lo)"
    if op == 0x85:
        return f"call {imm:#x}"
    if op == 0x8D:
        return f"callx r{imm & 0xF}"
    if op == 0x95:
        return "exit"
    if cls in (0x07, 0x04):
        name = _ALU_NAMES.get(op & 0xF0, f"alu{op:#x}")
        w = "64" if cls == 0x07 else "32"
        rhs = f"r{src}" if op & 0x08 else f"{imm}"
        return f"{name}{w} r{dst}, {rhs}"
    if cls in (0x05, 0x06):
        name = _JMP_NAMES.get(op & 0xF0, f"jmp{op:#x}")
        if name == "ja":
            return f"ja {off:+d}"
        rhs = f"r{src}" if op & 0x08 else f"{imm}"
        return f"{name} r{dst}, {rhs}, {off:+d}"
    if cls == 0x01:
        return f"ldx{_SIZES[op & 0x18]} r{dst}, [r{src}{off:+d}]"
    if cls == 0x02:
        return f"st{_SIZES[op & 0x18]} [r{dst}{off:+d}], {imm}"
    if cls == 0x03:
        return f"stx{_SIZES[op & 0x18]} [r{dst}{off:+d}], r{src}"
    return f".quad {int.from_bytes(ins, 'little'):#x}"


def format_trace(vm: "Vm", limit: int | None = None) -> str:
    """Rendered instruction trace of a traced run (fd_vm_trace output
    shape: pc, disassembly, registers)."""
    out = []
    text = vm.prog.text
    for pc, _op, regs in vm.trace_log[: limit or len(vm.trace_log)]:
        ins = text[8 * pc : 8 * pc + 8]
        rs = " ".join(f"r{i}={regs[i]:#x}" for i in range(11))
        out.append(f"{pc:6d}: {disasm(ins):<28} {rs}")
    return "\n".join(out)
