"""Shredder: entry batches → merkle data+parity shreds (FEC sets).

Behavior contract: src/disco/shred/fd_shredder.{h,c} —
  * an entry batch splits into FEC sets of up to 31200 payload bytes
    (the tail set absorbs the remainder; a set is never smaller than
    half-normal unless the batch is)
  * data shred count ceil-divides the chunk by the per-shred payload;
    parity count comes from the data_to_parity table (32:32 for full
    sets); payload size is 1115 - 20*tree_depth bytes
  * Reed-Solomon runs over each data shred's bytes [0x40, 0x58+payload)
    (everything after the signature), producing the parity payloads
  * every shred's merkle leaf hashes prefix || bytes [0x40, end of its
    RS-covered region); the 20-byte-node tree's root is signed by the
    leader and the per-leaf proof is appended to each shred
  * data shred flags: reference tick, DATA_COMPLETE on the batch's last
    shred, SLOT_COMPLETE when the block ends

TPU-native notes: parity generation is the MXU bit-matmul
(ops/reedsol.encode) over the whole set at once, and leaf hashing is one
batched SHA-256 dispatch (ballet/bmtree) per layer.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from firedancer_tpu.ballet import bmtree as BM
from firedancer_tpu.ballet import shred as SH
from firedancer_tpu.ops import reedsol as RS

NORMAL_FEC_SET_PAYLOAD_SZ = 31200

DATA_TO_PARITY = [
    0, 17, 18, 19, 19, 20, 21, 21,
    22, 23, 23, 24, 24, 25, 25, 26,
    26, 26, 27, 27, 28, 28, 29, 29,
    29, 30, 30, 31, 31, 31, 32, 32, 32,
]


def tree_depth_for(leaf_cnt: int) -> int:
    """Non-root layer count (fd_bmtree_depth(leaves) - 1)."""
    if leaf_cnt <= 1:
        return max(leaf_cnt - 1, 0)
    return (leaf_cnt - 1).bit_length()


def count_data_shreds(chunk: int) -> int:
    if chunk <= 9135:
        return max(1, (chunk + 1014) // 1015)
    return (chunk + 994) // 995


def count_parity_shreds(chunk: int) -> int:
    return DATA_TO_PARITY[count_data_shreds(chunk)]


@dataclass(frozen=True)
class EntryBatchMeta:
    parent_offset: int = 1
    reference_tick: int = 0
    block_complete: bool = False


@dataclass
class FecSet:
    data_shreds: list[bytes]
    parity_shreds: list[bytes]
    merkle_root: bytes
    signature: bytes


def _default_signer(root: bytes) -> bytes:
    return b"\0" * 64


class Shredder:
    """Stateful across batches of a slot (shred index offsets)."""

    def __init__(self, shred_version: int, signer=None):
        self.shred_version = shred_version
        self.signer = signer or _default_signer
        self.slot = None
        self.data_idx = 0
        self.parity_idx = 0

    def start_slot(self, slot: int) -> None:
        self.slot = slot
        self.data_idx = 0
        self.parity_idx = 0

    def shred_batch(self, entry_batch: bytes, meta: EntryBatchMeta) -> list[FecSet]:
        assert self.slot is not None, "start_slot first"
        assert entry_batch
        out = []
        offset = 0
        total = len(entry_batch)
        while offset < total:
            remaining = total - offset
            chunk = (
                NORMAL_FEC_SET_PAYLOAD_SZ
                if remaining >= 2 * NORMAL_FEC_SET_PAYLOAD_SZ
                else remaining
            )
            fec, consumed = self._build_fec_set(
                entry_batch, offset, chunk, total, meta
            )
            out.append(fec)
            offset += consumed
        return out

    def _build_fec_set(
        self, batch: bytes, offset: int, chunk: int, total: int,
        meta: EntryBatchMeta,
    ) -> tuple[FecSet, int]:
        d_cnt = count_data_shreds(chunk)
        p_cnt = count_parity_shreds(chunk)
        depth = tree_depth_for(d_cnt + p_cnt)
        data_payload_sz = 1115 - 20 * depth
        parity_payload_sz = data_payload_sz + SH.DATA_HEADER_SZ - 0x40
        proof_sz = depth * SH.MERKLE_NODE_SZ

        last_in_batch = offset + chunk == total
        flags_last = (
            (SH.FLAG_SLOT_COMPLETE if (last_in_batch and meta.block_complete) else 0)
            | (SH.FLAG_DATA_COMPLETE if last_in_batch else 0)
        )

        # ---- data shreds (unsigned, no proof yet) ----
        data_bufs = []
        consumed = 0
        for i in range(d_cnt):
            payload_sz = min(chunk - consumed, data_payload_sz)
            payload = batch[offset + consumed : offset + consumed + payload_sz]
            consumed += payload_sz
            flags = (
                (flags_last if i == d_cnt - 1 else 0)
                | (meta.reference_tick & SH.REF_TICK_MASK)
            )
            buf = bytearray(SH.MIN_SZ)
            buf[0x40] = SH.TYPE_MERKLE_DATA | depth
            import struct

            struct.pack_into(
                "<QIHI", buf, 0x41,
                self.slot, self.data_idx + i, self.shred_version, self.data_idx,
            )
            struct.pack_into(
                "<HBH", buf, 0x53,
                meta.parent_offset, flags, SH.DATA_HEADER_SZ + payload_sz,
            )
            buf[SH.DATA_HEADER_SZ : SH.DATA_HEADER_SZ + payload_sz] = payload
            data_bufs.append(buf)

        # ---- parity payloads: RS over data bytes [0x40, 0x40+cov) ----
        cov = parity_payload_sz
        data_mat = np.zeros((d_cnt, cov), np.uint8)
        for i, buf in enumerate(data_bufs):
            data_mat[i] = np.frombuffer(bytes(buf[0x40 : 0x40 + cov]), np.uint8)
        parity_mat = RS.encode(data_mat, p_cnt)

        parity_bufs = []
        for j in range(p_cnt):
            buf = bytearray(SH.MAX_SZ)
            buf[0x40] = SH.TYPE_MERKLE_CODE | depth
            import struct

            struct.pack_into(
                "<QIHI", buf, 0x41,
                self.slot, self.parity_idx + j, self.shred_version,
                self.parity_idx,
            )
            struct.pack_into("<HHH", buf, 0x53, d_cnt, p_cnt, j)
            buf[SH.CODE_HEADER_SZ : SH.CODE_HEADER_SZ + cov] = parity_mat[j].tobytes()
            parity_bufs.append(buf)

        # ---- merkle tree over all shreds' covered regions ----
        # data leaves cover [0x40, 0x58+payload) = cov bytes; parity
        # leaves additionally cover their own code header:
        # [0x40, 0x59+cov) (fd_shredder.c data/parity_merkle_sz)
        leaves = [bytes(b[0x40 : 0x40 + cov]) for b in data_bufs] + [
            bytes(b[0x40 : SH.CODE_HEADER_SZ + cov]) for b in parity_bufs
        ]
        layers = BM.layers_of(leaves, 20)
        root = bytes(layers[-1][0])
        sig = self.signer(root)

        # ---- write signature + proofs ----
        def proof_for(idx: int) -> bytes:
            nodes = []
            k = idx
            for layer in layers[:-1]:
                sib = k ^ 1
                nodes.append(
                    bytes(layer[sib]) if sib < len(layer) else bytes(layer[k])
                )
                k >>= 1
            return b"".join(nodes)

        for i, buf in enumerate(data_bufs):
            buf[0:0x40] = sig
            buf[SH.MIN_SZ - proof_sz : SH.MIN_SZ] = proof_for(i)
        for j, buf in enumerate(parity_bufs):
            buf[0:0x40] = sig
            buf[SH.MAX_SZ - proof_sz : SH.MAX_SZ] = proof_for(d_cnt + j)

        self.data_idx += d_cnt
        self.parity_idx += p_cnt
        return (
            FecSet(
                [bytes(b) for b in data_bufs],
                [bytes(b) for b in parity_bufs],
                root,
                sig,
            ),
            consumed,
        )
