"""Tile supervisor: heartbeat watchdog + crash-restart + circuit breaker.

Reference model: the reference splits this between `fdctl monitor`
(monitor.c:233 — snapshot every tile's cnc heartbeat/signal and render
the diffs) and `fdctl run`'s process supervisor (run/run.c — a failed
tile kills the topology).  This build goes one step further than the
reference's fail-stop: because ALL state crossing tile boundaries lives
in single-writer tango rings, a dead tile can be restarted IN PLACE —
its peers keep running, the new incarnation resyncs its consumer seqs
from the published fseqs (tango.rings.consumer_rejoin), its producer
cursor from the mcache (producer_rejoin), re-attaches its workspace
allocations (MuxCtx.alloc is idempotent by name) and re-runs on_boot.

Policy knobs mirror classic supervision trees: a heartbeat deadline
turns a wedged tile into a detected failure (the supervisor abandons the
stuck incarnation via ctx.interrupt and re-incarnates the tile), capped
exponential backoff stops a crash-looping tile from burning the host,
and a circuit breaker (N failures inside a sliding window) marks the
tile degraded — surfaced through the shared metrics region so
`app/monitor.py` alarms on it from another process.

Restart loss accounting: reliable in-links can be rewound `replay` frags
on rejoin (at-least-once redelivery).  A downstream dedup stage whose
tag cache survives restarts (tiles/dedup.py joins, never re-inits, on
incarnation > 0) collapses the replay back to exactly-once, so the only
survivor loss a crash can cause is (a) frags a dead incarnation consumed
beyond the replay window and never forwarded, and (b) jump-to-head skips
on unreliable links — which are declared in `overrun_frags`.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass

from firedancer_tpu.tango import rings as R

from .topo import Topology


@dataclass
class RestartPolicy:
    """Supervision knobs (per supervisor; replay may vary per tile)."""

    #: heartbeat older than this (while RUN) is a miss -> stall restart
    hb_timeout_s: float = 1.0
    #: watchdog sampling period
    poll_s: float = 0.02
    #: how long to wait for a dead/abandoned incarnation's thread to exit
    #: before declaring the tile wedged-degraded (threads cannot be
    #: killed; a truly wedged tile needs the process-per-tile runner)
    join_timeout_s: float = 10.0
    #: capped exponential restart backoff
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    #: uptime after which the backoff resets to base
    healthy_after_s: float = 5.0
    #: circuit breaker: this many failures inside the window -> degraded
    breaker_n: int = 5
    breaker_window_s: float = 30.0
    #: a (re-)incarnation still in BOOT after this long is treated as a
    #: failure (on_boot hang: device re-init, stuck bind, wedged native
    #: call) — generous because first boots compile device kernels
    boot_timeout_s: float = 600.0
    #: reliable-link replay window on rejoin, in frags (int = all tiles,
    #: dict = per tile name); see tango.rings.consumer_rejoin
    replay: int | dict = 0


def rejoin_links(ins, outs, replay: int = 0, on_skip=None) -> None:
    """Resync a restarted tile's ring endpoints: consumer seqs from the
    published fseqs (rewound up to `replay` frags on reliable links),
    producer cursors from the mcaches.  Shared by the supervisor's
    crash-restart path and fdtmc's restart scenarios (analysis/
    mcmodels.py), so the model checker exercises the exact code the
    supervisor runs.

    `ins` items need .mcache/.fseq/.reliable/.seq, `outs` items
    .mcache/.seq (disco.mux.InLink/OutLink shaped).  `on_skip(link,
    skipped)` observes unreliable-link jump gaps for loss accounting."""
    for il in ins:
        il.seq, skipped = R.consumer_rejoin(
            il.mcache, il.fseq, reliable=il.reliable, replay=replay
        )
        if skipped and on_skip is not None:
            on_skip(il, skipped)
        il.fseq.update(il.seq)
    for o in outs:
        o.seq = R.producer_rejoin(o.mcache)


class _TileState:
    def __init__(self) -> None:
        self.fail_times: collections.deque = collections.deque()
        self.backoff_s = 0.0
        self.boot_mono_ns = 0
        self.degraded: str | None = None
        self.respawn_at = 0.0  # monotonic; 0 = running
        self.restarts = 0


class Supervisor:
    """Run a Topology's tiles under heartbeat/crash supervision.

    Usage:
        topo = Topology(); ...declare links/tiles...
        sup = Supervisor(topo, policy=RestartPolicy(...), faults=inj)
        sup.start(batch_max=...)
        ...
        sup.halt(); topo.close()
    """

    def __init__(
        self,
        topo: Topology,
        policy: RestartPolicy | None = None,
        faults=None,
    ):
        self.topo = topo
        self.policy = policy or RestartPolicy()
        self.faults = faults
        #: failure observers: cb(tile_name, kind, detail_dict) invoked
        #: on the watchdog thread for kind in {"restart", "breaker",
        #: "wedged"} AFTER the supervisor has recorded the event in the
        #: shared metrics region.  The flight recorder (disco/flight.py)
        #: hooks here so every supervision action can freeze an incident
        #: bundle; callbacks must be fast and must not raise (exceptions
        #: are swallowed so a broken observer cannot wedge supervision).
        self._listeners: list = []
        self._state: dict[str, _TileState] = {}
        self._loop_kw: dict = {}
        #: True when the topology runs the process-per-tile runtime —
        #: failure handling then kills/reaps CHILD PROCESSES (SIGKILL
        #: works on a wedged child, unlike a wedged thread) and ring
        #: rejoin happens in the respawned child at boot
        self._process = False
        #: deliberate reconfiguration in progress (disco/elastic.py):
        #: tile name -> operation label.  While a tile is COMMANDED the
        #: watchdog stands back entirely — the operation owns its
        #: lifecycle (including crash-mid-drain recovery), so a
        #: deliberate drain/halt/respawn never counts toward the
        #: circuit breaker, never escalates backoff, and never races a
        #: watchdog respawn.  Events emitted for commanded work carry
        #: kind "reconfig" (flight bundles classify as reconfig:<op>).
        self._commanded: dict[str, str] = {}
        self._halting = False
        self._watchdog: threading.Thread | None = None
        self._stop = threading.Event()

    def add_listener(self, cb) -> None:
        """Register a failure observer: cb(tile, kind, detail)."""
        self._listeners.append(cb)

    # ---- commanded reconfiguration (disco/elastic.py) -------------------

    def command(self, name: str, op: str):
        """Context manager bracketing a DELIBERATE operation on `name`
        (elastic scale-out/in, rolling restart, config reload): the
        watchdog ignores the tile for the duration, so the halt/reap/
        respawn sequence the operation performs is never misread as a
        crash — no breaker count, no backoff escalation, no racing
        respawn.  The operation reports itself via note_commanded."""
        import contextlib

        @contextlib.contextmanager
        def _bracket():
            self._commanded[name] = op
            try:
                yield
            finally:
                self._commanded.pop(name, None)

        return _bracket()

    def note_spawn(self, name: str) -> None:
        """A commanded operation is about to (re)spawn `name`: refresh
        the watchdog's boot clock so the tile is not instantly declared
        boot-timed-out when the command bracket releases."""
        st = self._state.get(name)
        if st is not None:
            st.boot_mono_ns = time.monotonic_ns()
            st.respawn_at = 0.0

    def note_commanded(self, name: str | None, op: str, detail: dict) -> None:
        """Emit a deliberate-reconfiguration event to the listeners
        (the flight recorder freezes a bundle fdtincident classifies
        as `reconfig:<op>` — distinct from crash incidents)."""
        self._emit(name or "", "reconfig", {"op": op, **detail})

    def note_upgrade(self, name: str | None, op: str, detail: dict) -> None:
        """Emit a hot-upgrade lifecycle event (commanded, refused, or
        rolled back — disco/topo.py hot_upgrade).  Flight bundles
        classify as `upgrade:<op>`; refusal/rollback details carry both
        version digests so the incident names the ABI drift.  Like
        note_commanded, never a crash: a failed upgrade rolls back the
        old recipe under the command bracket and burns no breaker."""
        self._emit(name or "", "upgrade", {"op": op, **detail})

    def _emit(self, tile: str, kind: str, detail: dict) -> None:
        for cb in self._listeners:
            try:
                cb(tile, kind, detail)
            except Exception:  # noqa: BLE001 — observers cannot wedge us
                from firedancer_tpu.utils import log

                log.err("supervisor listener failed on %s/%s", tile, kind)

    # ---- lifecycle ------------------------------------------------------

    def start(self, boot_timeout_s: float = 600.0, **loop_kw) -> None:
        topo = self.topo
        if topo.wksp is None:
            topo.build()
        self._loop_kw = loop_kw
        topo._loop_kw = dict(loop_kw)
        # same stem resolution as Topology.start: supervised tiles (and
        # every restarted incarnation) run the same inner loop the
        # config/env selected
        topo._loop_kw["stem"] = topo._resolve_stem(loop_kw.get("stem"))
        self._process = topo._runtime == "process"
        if self._process and self.faults is not None:
            # process runtime: the schedule rides the spawn args so
            # each child reconstructs an IDENTICAL injector (seed +
            # fault list) — deterministic effects, child-local event
            # logs (parent-side accounting reads the shm metrics)
            topo.faults_spec = (self.faults.seed, list(self.faults.faults))
        for name, ts in topo.tiles.items():
            self._state[name] = _TileState()
            if self.faults is not None and not (
                self._process and ts.tile.proc_safe
            ):
                ts.ctx.faults = self.faults.view(name)
        if self._process:
            # publish ONCE, before any child spawns: children attach
            # via the directory, and re-publishing per spawn would
            # truncate-rewrite the file under a concurrent attach
            topo.export_manifest()
        for name, ts in topo.tiles.items():
            if ts.active:
                self._spawn(name)
        # boot-wait: every tile leaves BOOT (RUN, or FAIL -> the watchdog
        # will treat the boot crash like any other failure)
        deadline = time.monotonic() + boot_timeout_s
        for name, ts in topo.tiles.items():
            if not ts.active:
                continue
            while topo._cncs[name].signal_query() == R.CNC_BOOT:
                p = ts.proc
                if p is not None and not p.is_alive():
                    # died before signaling (spawn/import crash): mark
                    # FAIL so the watchdog runs the normal restart path
                    topo._cncs[name].signal(R.CNC_FAIL)
                    break
                if time.monotonic() > deadline:
                    self.halt()
                    raise TimeoutError(f"tile {name!r} stuck in BOOT")
                time.sleep(1e-3)
        topo.export_manifest()
        self._watchdog = threading.Thread(
            target=self._watch, name="supervisor", daemon=True
        )
        self._watchdog.start()

    def _spawn(self, name: str) -> None:
        """(Re)spawn one tile via the topology's runtime-aware spawner
        (child process, or a thread for the thread runtime and
        proc_safe=False observers)."""
        topo, st = self.topo, self._state[name]
        st.boot_mono_ns = time.monotonic_ns()
        st.respawn_at = 0.0
        replay = self.policy.replay
        if isinstance(replay, dict):
            replay = replay.get(name, 0)
        topo._spawn_tile(name, replay=replay)

    def halt(self, timeout_s: float = 30.0) -> None:
        self._halting = True
        self._stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=timeout_s)
            self._watchdog = None
        # wedged incarnations only ever exit via the interrupt flag
        for name, st in self._state.items():
            if st.degraded is not None:
                self.topo.tiles[name].ctx.interrupt.set()
        self.topo.halt(timeout_s=timeout_s)

    # ---- watchdog -------------------------------------------------------

    def _watch(self) -> None:
        p = self.policy
        while not self._stop.wait(p.poll_s):
            now_ns = time.monotonic_ns()
            now = time.monotonic()
            for name, ts in self.topo.tiles.items():
                st = self._state[name]
                if st.degraded is not None or self._halting:
                    continue
                # elastic: inactive (provisioned/retired) members are
                # not running by design; commanded tiles are mid-
                # deliberate-op and the operation owns their lifecycle
                if not ts.active or name in self._commanded:
                    continue
                if st.respawn_at:  # waiting out the backoff
                    if now >= st.respawn_at:
                        self._spawn(name)
                    continue
                cnc = self.topo._cncs[name]
                sig = cnc.signal_query()
                proc = ts.proc
                died = (
                    not proc.is_alive()
                    if proc is not None
                    else ts.thread is not None and not ts.thread.is_alive()
                )
                if sig == R.CNC_FAIL or (died and sig == R.CNC_RUN):
                    self._handle_failure(name, "crash")
                    continue
                if sig == R.CNC_RUN:
                    hb = cnc.heartbeat_query()
                    ref = max(hb, st.boot_mono_ns)
                    if now_ns - ref > int(p.hb_timeout_s * 1e9):
                        self.topo._metrics[name].inc("hb_misses")
                        self._handle_failure(name, "heartbeat")
                elif sig == R.CNC_BOOT:
                    # a re-incarnation hung in on_boot never reaches RUN
                    # or FAIL on its own — without this deadline it
                    # would be invisible to every other clause forever;
                    # a child that DIED in boot (import crash) is
                    # detectable immediately by its exit
                    if proc is not None and died:
                        self._handle_failure(name, "boot crash")
                    elif now_ns - st.boot_mono_ns > int(
                        p.boot_timeout_s * 1e9
                    ):
                        self._handle_failure(name, "boot timeout")

    def _handle_failure(self, name: str, reason: str) -> None:
        from firedancer_tpu.utils import log

        p = self.policy
        topo, ts, st = self.topo, self.topo.tiles[name], self._state[name]
        ctx = ts.ctx
        metrics = topo._metrics[name]
        if ts.proc is not None:
            # a child PROCESS can actually be killed — the wedged-thread
            # escape hatch the threaded runtime lacks.  SIGKILL, reap,
            # and the single-writer discipline is guaranteed by the
            # process exit (no Python cooperation needed).
            proc = ts.proc
            if proc.is_alive():
                proc.kill()
            proc.join(timeout=p.join_timeout_s)
            if proc.is_alive():
                # unkillable (uninterruptible D-state): restarting over
                # a live writer would break the rings — degrade
                st.degraded = "wedged"
                metrics.set("degraded", 1)
                log.err("tile %s child unkillable; degraded", name)
                self._emit(name, "wedged", {"reason": reason})
                return
            try:
                proc.close()
            except ValueError:
                pass
            ts.proc = None
        else:
            # abandon the incarnation: a stalled loop exits at its next
            # interrupt check; a crashed one is already on its way out
            ctx.interrupt.set()
            ts.thread.join(timeout=p.join_timeout_s)
            if ts.thread.is_alive():
                # the thread ignored the interrupt: restarting over a
                # live writer would break the single-writer discipline
                st.degraded = "wedged"
                metrics.set("degraded", 1)
                log.err("tile %s wedged (interrupt ignored); degraded", name)
                self._emit(name, "wedged", {"reason": reason})
                return
        now = time.monotonic()
        # circuit breaker over a sliding failure window
        st.fail_times.append(now)
        while st.fail_times and now - st.fail_times[0] > p.breaker_window_s:
            st.fail_times.popleft()
        if len(st.fail_times) >= p.breaker_n:
            st.degraded = "breaker"
            metrics.set("degraded", 1)
            log.err(
                "tile %s: %d failures in %.0fs; circuit breaker open",
                name, len(st.fail_times), p.breaker_window_s,
            )
            self._emit(
                name, "breaker",
                {"reason": reason, "failures": len(st.fail_times),
                 "window_s": p.breaker_window_s},
            )
            return
        # capped exponential backoff, reset after a healthy uptime
        uptime_s = (time.monotonic_ns() - st.boot_mono_ns) / 1e9
        if st.backoff_s and uptime_s > p.healthy_after_s:
            st.backoff_s = 0.0
        st.backoff_s = (
            p.backoff_base_s
            if not st.backoff_s
            else min(st.backoff_s * 2.0, p.backoff_max_s)
        )
        # ring rejoin: consumer seqs from the published fseqs (with the
        # configured replay window), producer cursors from the mcaches.
        # Thread runtime: repaired here, parent-side.  Process runtime:
        # the NEW CHILD runs the same rejoin_links at boot (its endpoint
        # objects live in the child; the repair inputs — fseqs, mcache
        # cursors — are all shm), so the parent only does bookkeeping.
        is_proc = self._process and ts.tile.proc_safe
        if not is_proc:
            replay = p.replay
            if isinstance(replay, dict):
                replay = replay.get(name, 0)

            def _account_skip(il, skipped):
                metrics.inc("overrun_frags", skipped)
                il.fseq.diag_add(0, skipped)

            rejoin_links(
                ctx.ins, ctx.outs, replay=replay, on_skip=_account_skip
            )
        if ctx.tracer is not None:
            # the dead incarnation (thread joined / process reaped) is
            # gone and the new one has not spawned, so this is the
            # ring's only writer — the restart annotation makes the
            # kill -> rejoin gap visible (and assertable) in the trace
            ctx.tracer.fault(
                "restart", seq=ctx.incarnation + 1,
                aux64=st.restarts + 1,
            )
        if not is_proc:
            # process children take their resources (sockets, worker
            # threads, device handles) down with them — on_crash is a
            # thread-runtime cleanup hook
            ts.tile.on_crash(ctx)
        ctx.interrupt.clear()
        ctx.booted = False
        ctx.incarnation += 1
        st.restarts += 1
        metrics.inc("restarts")
        topo._cncs[name].signal(R.CNC_BOOT)
        st.respawn_at = time.monotonic() + st.backoff_s
        log.info(
            "tile %s restarting (%s, incarnation %d, backoff %.0fms)",
            name, reason, ctx.incarnation, st.backoff_s * 1e3,
        )
        self._emit(
            name, "restart",
            {"reason": reason, "incarnation": ctx.incarnation,
             "restarts": st.restarts, "backoff_s": st.backoff_s},
        )

    # ---- introspection --------------------------------------------------

    def restarts(self, name: str) -> int:
        return self._state[name].restarts

    def degraded(self, name: str) -> str | None:
        return self._state[name].degraded

    def status(self) -> dict:
        out = {}
        for name, st in self._state.items():
            out[name] = {
                "restarts": st.restarts,
                "degraded": st.degraded,
                "backoff_s": st.backoff_s,
            }
        return out
