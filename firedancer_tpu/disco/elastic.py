"""fdt_elastic: SLO-driven runtime scaling and live topology
reconfiguration with zero-loss shard handover.

The reference validator's topology — like every build here before this
module — is fixed at boot.  ROADMAP item 4 names the frontier past the
paper: millions of users means diurnal and adversarial load swings, and
a static 17-tile shape is wrong at both ends.  This module composes the
machinery previous PRs built (process runtime + boot-manifest rejoin,
crash-restart with zero-loss ring rejoin, the shared bank table, burn-
rate SLOs) into an elasticity subsystem with three parts:

  * THE SHARD MAP — a versioned shared-memory region holding, per shard
    KIND (seq-sharded verify replicas, pack-assigned bank shards), an
    epoch word and a per-member active mask.  Ring layout never changes
    at runtime: the topology provisions `max_shards` members (links,
    mcaches, fseqs, metrics) at build and membership changes by
    flipping mask bits under a bumped epoch.  Producers and members
    re-read the map ONLY at burst boundaries — the Python run loop
    checks the epoch word each iteration before draining, and the
    native stem carries the same word in its config block
    (fdt_stem.c C_EPOCH_PTR/C_EPOCH_SEEN) and hands the burst back to
    Python unconsumed when it moved.  The `elastic-stale-epoch` fdtmc
    corpus mutant pins exactly the bug this discipline prevents: a
    producer trusting a pre-flip map for post-flip frags.

  * HANDOVER PROTOCOL — seq-sharded links (quic_verify) need every seq
    owned by exactly ONE member across a flip, even though members
    observe the flip at different times.  The link's single PRODUCER
    resolves the race: on observing a new epoch at a burst boundary it
    appends a FLIP ENTRY (start_seq = its next publish seq, the new
    mask) to a small journal in the shard-map region, then publishes.
    Because the journal store is sequenced before the mcache publish
    (and consumers read frags through the line-seq acquire), any
    consumer that can see a frag with seq >= start_seq can also see
    the entry that governs it — assignment is a pure function of
    (seq, journal), never of when a consumer happened to re-read.
    Bank shards need no journal: assignment is explicit (pack chooses
    the out ring), so the mask just gates the scheduler.

  * DRAIN / RETIRE — retirement is drain -> handover -> reap: the
    retiring member stops being assigned new seqs at the flip, drains
    its in-flight window (verify lands its device pool + reorder
    buffer; banks flush their funk commit), then publishes a DRAINED
    marker (the epoch it drained at) in the shard-map region (mirrored
    into its pstat words by the parent), and only then is reaped.  A
    SIGKILL mid-drain is recovered by the retire loop itself: the dead
    member is respawned (ring rejoin + replay, the PR 1/7 machinery)
    until the drain completes — the same zero-loss/zero-dup bar as
    crash chaos, asserted by tests/test_elastic.py.

Inactive members' reliable fseqs are PARKED in the far seq future
(producer head + 2^62): fdt_fctl_cr_avail treats a consumer ahead of
the producer as fresh credit, so a provisioned-but-idle member (or a
reaped corpse) never backpressures the producer, and activation lands
it at the live head via the ordinary consumer_rejoin path.

`ElasticController` runs in the parent next to the supervisor: it
consumes the SLO burn-rate engine (scale-out on queue-wait / e2e p99
burn, scale-in on sustained idle), paces operations with dwell
hysteresis like the ingress LoadShedder, brackets every operation as a
COMMANDED op with the supervisor (so deliberate drains never count
toward the circuit breaker and classify as `reconfig:<op>` incident
bundles), exposes rolling restart / config reload as first-class
operations, and feeds admission-cap autosizing (the quic tile scales
its ConnAdmission caps with the live verify shard count).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

import numpy as np

from firedancer_tpu.tango import rings as R

# ---------------------------------------------------------------------------
# shard-map region layout (u64 words)
#
# One region per topology ("shared_shardmap"), allocated by the topology
# at build time so process-runtime children join it by name.  Writers
# are disjoint by word (the single-writer-per-word discipline every
# other shm control plane here follows):
#   * the CONTROLLER (parent) owns epoch / n_members / active mask;
#   * the kind's PRODUCER tile owns producer_ack / jlen / journal;
#   * member i owns ack[i] and drained[i].

SHARDMAP_MAGIC = 0x46445445_4C415331  # "FDTELAS1"
MAX_KINDS = 4
MAX_MEMBERS = 16
JOURNAL_ENTRIES = 8

_H_MAGIC, _H_NKINDS = 0, 1
_KIND0 = 8
_KIND_WORDS = 64
_K_EPOCH, _K_NMEMB, _K_MASK, _K_PACK, _K_JLEN = 0, 1, 2, 3, 4
_K_DRAINED0 = 8   # 16 words: drained epoch per member (0 = never)
_K_ACK0 = 24      # 16 words: last epoch member i observed
_K_J0 = 40        # 8 entries x (start_seq, mask, index tag) = 24 words
_J_ENT_WORDS = 3

SHARDMAP_FOOTPRINT = 8 * (_KIND0 + MAX_KINDS * _KIND_WORDS)

#: inactive/reaped members' fseqs are parked this far AHEAD of the
#: producer: cr_avail treats a consumer ahead as fresh credit, and
#: consumer_rejoin's wrap-safe min lands activation at the live head
PARK_OFFSET = 1 << 62

#: bank_ready_at parking value for deactivated banks (pack's scheduler
#: — both loops — skips a bank whose ready_at is in the far future);
#: threshold distinguishes parking from ordinary cadence gating
BANK_PARKED_AT = 1 << 62
BANK_PARKED_THRESH = 1 << 61


def _popcount(mask: int) -> int:
    return bin(mask & ((1 << MAX_MEMBERS) - 1)).count("1")


def active_members(mask: int) -> list[int]:
    """Sorted member indices of an active mask — the seq-shard
    assignment order (seq s belongs to members[s % len(members)])."""
    return [i for i in range(MAX_MEMBERS) if mask & (1 << i)]


def device_partition(universe: list[int], mask: int, index: int) -> list[int]:
    """Member `index`'s device-ordinal slice of `universe` under the
    LIVE active mask — the runtime restatement of topo.py's boot-time
    device_assignments (same strided partition, same round-robin
    sharing when devices are scarcer than members), keyed by rank among
    the CURRENTLY active members.  Scale-out recruits the spare
    ordinals the smaller active set left unused; scale-in returns them
    to the survivors.  Empty for an inactive member."""
    act = active_members(mask)
    if index not in act:
        return []
    rank, n = act.index(index), len(act)
    if len(universe) < n:
        return [universe[rank % len(universe)]]
    return list(universe[rank::n])


class ShardMap:
    """View of the shared shard-map region (owner or joiner)."""

    def __init__(self, mem_u8: np.ndarray, join: bool = True):
        self.words = mem_u8[: (len(mem_u8) // 8) * 8].view(np.uint64)
        if not join and int(self.words[_H_MAGIC]) != SHARDMAP_MAGIC:
            self.words[_H_NKINDS] = 0
            # magic last: a joiner that sees it sees a full header
            self.words[_H_MAGIC] = np.uint64(SHARDMAP_MAGIC)

    def _k(self, slot: int) -> int:
        assert 0 <= slot < MAX_KINDS
        return _KIND0 + slot * _KIND_WORDS

    # -- controller-owned words -------------------------------------------

    def init_kind(self, slot: int, n_members: int, mask: int) -> None:
        k = self._k(slot)
        w = self.words
        w[k + _K_NMEMB] = n_members
        w[k + _K_MASK] = mask
        w[k + _K_EPOCH] = 1
        # journal entry 0 covers the whole seq space from boot
        w[k + _K_J0] = 0
        w[k + _K_J0 + 1] = mask
        w[k + _K_J0 + 2] = 0  # index tag
        w[k + _K_JLEN] = 1
        self.words[_H_NKINDS] = max(int(self.words[_H_NKINDS]), slot + 1)

    def flip(self, slot: int, mask: int) -> int:
        """Set the active mask and bump the epoch (mask store first, so
        an epoch observer always reads the new mask).  Returns the new
        epoch."""
        k = self._k(slot)
        self.words[k + _K_MASK] = mask
        ep = int(self.words[k + _K_EPOCH]) + 1
        self.words[k + _K_EPOCH] = np.uint64(ep)
        return ep

    # -- reads -------------------------------------------------------------

    def epoch_word(self, slot: int) -> np.ndarray:
        k = self._k(slot)
        return self.words[k + _K_EPOCH : k + _K_EPOCH + 1]

    def epoch(self, slot: int) -> int:
        return int(self.words[self._k(slot) + _K_EPOCH])

    def n_members(self, slot: int) -> int:
        return int(self.words[self._k(slot) + _K_NMEMB])

    def mask(self, slot: int) -> int:
        return int(self.words[self._k(slot) + _K_MASK])

    def n_active(self, slot: int) -> int:
        return _popcount(self.mask(slot))

    def producer_ack(self, slot: int) -> int:
        return int(self.words[self._k(slot) + _K_PACK])

    def member_ack(self, slot: int, i: int) -> int:
        return int(self.words[self._k(slot) + _K_ACK0 + i])

    def drained(self, slot: int, i: int) -> int:
        return int(self.words[self._k(slot) + _K_DRAINED0 + i])

    # -- producer-owned words ---------------------------------------------

    def append_flip(self, slot: int, start_seq: int, mask: int) -> None:
        """Producer-side: record that frags from start_seq onward are
        assigned per `mask`.  Entry body (start, mask, then its INDEX
        TAG) first, length last — and the caller publishes frags only
        AFTER this returns, so a consumer that can see a governed frag
        can see its entry.  The journal is a ring of JOURNAL_ENTRIES;
        once it wraps, a reader racing the overwrite of its oldest slot
        detects the mismatch via the tag and retries (journal()).  The
        controller's dwell pacing keeps live frags governed by retained
        entries (every entry older than one ring depth of the newest is
        dead by the reliable-consumer bound); the drain gate in
        ElasticBinding.tick is conservative when an excluding entry may
        have been evicted."""
        k = self._k(slot)
        w = self.words
        n = int(w[k + _K_JLEN])
        e = k + _K_J0 + _J_ENT_WORDS * (n % JOURNAL_ENTRIES)
        w[e] = np.uint64(R.seq_u64(start_seq))
        w[e + 1] = np.uint64(mask)
        w[e + 2] = np.uint64(n)
        w[k + _K_JLEN] = np.uint64(n + 1)

    def set_producer_ack(self, slot: int, epoch: int) -> None:
        self.words[self._k(slot) + _K_PACK] = np.uint64(epoch)

    # -- member-owned words -----------------------------------------------

    def set_member_ack(self, slot: int, i: int, epoch: int) -> None:
        self.words[self._k(slot) + _K_ACK0 + i] = np.uint64(epoch)

    def set_drained(self, slot: int, i: int, epoch: int) -> None:
        self.words[self._k(slot) + _K_DRAINED0 + i] = np.uint64(epoch)

    # -- journal reads -----------------------------------------------------

    def journal(self, slot: int) -> tuple[np.ndarray, np.ndarray]:
        """(start_seqs, masks) of the live journal entries, oldest
        first.  Reader-safe across the ring wrap: each entry carries
        its journal INDEX tag, so a reader racing the producer's
        overwrite of its oldest slot sees a tag from the future,
        re-reads jlen and retries — the producer is the single writer,
        so the retry converges immediately."""
        k = self._k(slot)
        w = self.words
        while True:
            n = int(w[k + _K_JLEN])
            take = min(n, JOURNAL_ENTRIES)
            lo = n - take
            idx = [(lo + j) % JOURNAL_ENTRIES for j in range(take)]
            starts = np.empty(take, np.uint64)
            masks = np.empty(take, np.uint64)
            ok = True
            for j, sl in enumerate(idx):
                e = k + _K_J0 + _J_ENT_WORDS * sl
                starts[j] = w[e]
                masks[j] = w[e + 1]
                if int(w[e + 2]) != lo + j:
                    ok = False
                    break
            if ok and int(w[k + _K_JLEN]) == n:
                return starts, masks

    def assign_mask(
        self, slot: int, seqs: np.ndarray, member: int
    ) -> np.ndarray:
        """Bool mask over a frag-seq batch: which seqs belong to
        `member` under the journal's epoch-resolved assignment.  Wrap-
        safe: per-entry comparisons go through signed mod-2^64
        distances, never raw u64 order."""
        starts, masks = self.journal(slot)
        seqs = np.asarray(seqs, np.uint64)
        out = np.zeros(len(seqs), bool)
        if not len(starts):
            return out
        # entry index per seq = (# entries with start <= seq) - 1;
        # entries are append-ordered so later entries shadow earlier
        with np.errstate(over="ignore"):
            ge = np.stack(
                [
                    (seqs - np.uint64(s)).astype(np.int64) >= 0
                    for s in starts
                ]
            )
        eidx = np.maximum(ge.sum(axis=0) - 1, 0)
        for j in range(len(starts)):
            mem = active_members(int(masks[j]))
            if not mem:
                continue
            sel = eidx == j
            if not sel.any():
                continue
            # fully vectorized ownership test: one modulo + one gather
            # per entry (a batch spans 1-2 entries in practice) — no
            # per-frag Python, the elastic analog of the static
            # filter's single `seq % cnt == idx`
            mem_arr = np.asarray(mem, np.int64)
            pos = (seqs[sel] % np.uint64(len(mem))).astype(np.int64)
            out[sel] = mem_arr[pos] == member
        return out

    def jlen(self, slot: int) -> int:
        return int(self.words[self._k(slot) + _K_JLEN])

    def member_past_flip(self, slot: int, member: int, seq: int) -> bool:
        """Has `seq` passed the newest flip entry that EXCLUDES member?
        (the retiring member's drain boundary; True when no such entry
        exists — nothing to drain past)."""
        starts, masks = self.journal(slot)
        bound = None
        for j in range(len(starts)):
            if not (int(masks[j]) >> member) & 1:
                bound = int(starts[j])
        if bound is None:
            return True
        return R.seq_diff(R.seq_u64(seq), bound) >= 0


# ---------------------------------------------------------------------------
# per-tile binding


@dataclass
class ElasticBinding:
    """Injected onto member/producer tiles by Topology.declare_shards;
    rides the spawn pickle so process children reconstruct it.  The
    generic role behavior (flip-journal appends, acks, drain markers)
    lives here; tiles override Tile.on_epoch / Tile.elastic_drained to
    add their own reconfiguration on top."""

    kind: str
    slot: int
    role: str  # "member" | "producer"
    index: int | None = None  # member index (members only)
    link: str | None = None   # producer: sharded out link (None = bank
    #                           style); member: its sharded in link
    #: initial active count — the autosizing base (quic admission caps
    #: scale by n_active / base_active)
    base_active: int = 1

    def __post_init__(self):
        self._smv: ShardMap | None = None

    # dataclass + pickle: drop the cached view (child re-binds)
    def __getstate__(self):
        st = dict(self.__dict__)
        st["_smv"] = None
        return st

    def bind(self, ctx) -> ShardMap:
        if self._smv is None:
            self._smv = ShardMap(
                ctx.shared("shardmap", SHARDMAP_FOOTPRINT)
            )
        return self._smv

    def epoch_word(self, ctx) -> np.ndarray:
        return self.bind(ctx).epoch_word(self.slot)

    def is_active(self, ctx) -> bool:
        assert self.index is not None
        return bool((self.bind(ctx).mask(self.slot) >> self.index) & 1)

    def _member_link(self, ctx):
        if self.link is None:
            return None
        for il in ctx.ins:
            if il.name == self.link:
                return il
        return None

    def on_epoch(self, tile, ctx) -> None:
        """Burst-boundary epoch observation (generic role half)."""
        smv = self.bind(ctx)
        ep = smv.epoch(self.slot)
        if self.role == "producer":
            # the shm ACK word is the append guard: run_loop calls
            # on_epoch at EVERY (re)boot, and a producer that re-
            # appended per incarnation would churn the 8-entry journal
            # ring past live flip entries under crash-restart storms —
            # an already-acked epoch appends nothing.  Append-then-ack
            # order bounds the failure the other way: a crash between
            # the two re-appends ONE duplicate entry (same mask, later
            # start) on the next boot, which assignment resolves
            # identically.
            if smv.producer_ack(self.slot) < ep:
                if self.link is not None:
                    # flip entry BEFORE any frag it governs publishes:
                    # the next publish seq is the entry's start
                    try:
                        out = ctx.out(self.link)
                    except KeyError:
                        out = None
                    if out is not None:
                        smv.append_flip(
                            self.slot, out.seq, smv.mask(self.slot)
                        )
                smv.set_producer_ack(self.slot, ep)
        else:
            smv.set_member_ack(self.slot, self.index, ep)

    def assign(self, ctx, seqs: np.ndarray) -> np.ndarray:
        """Member-side frag filter for a drained batch."""
        return self.bind(ctx).assign_mask(self.slot, seqs, self.index)

    def tick(self, tile, ctx) -> None:
        """Housekeeping-cadence member bookkeeping: refresh the ack and
        evaluate the drain contract when retired.  Drained requires,
        in order: (1) this member observed the retiring epoch, (2) the
        producer acked it (no more frags will be assigned here), (3)
        the in cursor passed the flip boundary (journal kinds) or
        caught the quiet ring head (bank kinds), (4) the tile's own
        in-flight window is empty (tile.elastic_drained)."""
        if self.role != "member":
            return
        smv = self.bind(ctx)
        ep = smv.epoch(self.slot)
        smv.set_member_ack(self.slot, self.index, ep)
        if self.is_active(ctx):
            return
        if smv.drained(self.slot, self.index) >= ep:
            return
        if smv.producer_ack(self.slot) < ep:
            return
        il = self._member_link(ctx)
        if il is not None:
            starts, masks = smv.journal(self.slot)
            bound = None
            for j in range(len(starts)):
                if not (int(masks[j]) >> self.index) & 1:
                    bound = int(starts[j])
            caught_up = R.seq_diff(
                R.seq_u64(il.seq), il.mcache.seq_query()
            ) >= 0
            if bound is not None:
                # journal kind: drain past the excluding flip boundary
                if R.seq_diff(R.seq_u64(il.seq), bound) < 0:
                    return
            elif len(starts) > 1 and (
                smv.jlen(self.slot) <= JOURNAL_ENTRIES
            ):
                # journal kind, but no entry excludes us and nothing
                # was evicted: the producer has not yet appended the
                # retiring flip — too early to judge
                return
            elif not caught_up:
                # bank-style kind (no flips recorded) or the excluding
                # entry may have been EVICTED by ring wrap: be
                # conservative and require the quiet ring head
                return
        if not tile.elastic_drained(ctx):
            return
        smv.set_drained(self.slot, self.index, ep)


# ---------------------------------------------------------------------------
# config + controller


@dataclass(frozen=True)
class ElasticKindConfig:
    """Per-kind controller policy (the `[elastic.<kind>]` config)."""

    min_shards: int = 1
    max_shards: int = 1
    #: scale OUT when the watched SLOs' fast burn reaches this and holds
    #: for a dwell (1.0 = budget-exhausting rate)
    scale_out_burn: float = 1.0
    #: scale IN when the per-active-shard landed rate stays under this
    #: for idle_for_s
    scale_in_idle_tps: float = 1.0
    idle_for_s: float = 3.0


@dataclass(frozen=True)
class ElasticConfig:
    """The `[elastic]` config section (app/config.py)."""

    kinds: dict = field(default_factory=dict)  # kind -> ElasticKindConfig
    #: minimum time between reconfig operations (dwell pacing, the
    #: LoadShedder discipline: a transient burst costs one op, and the
    #: flip-journal ring can never outrun live frags)
    dwell_s: float = 2.0
    poll_s: float = 0.05
    #: SLO names whose fast burn drives scale-out
    watch_slos: tuple = ("queue_wait_p99_us", "e2e_p99_us")

    @classmethod
    def from_dict(cls, doc: dict) -> "ElasticConfig":
        kinds = {}
        top = {
            k: v
            for k, v in doc.items()
            if k in ("dwell_s", "poll_s")
        }
        if "watch_slos" in doc:
            top["watch_slos"] = tuple(doc["watch_slos"])
        for k, v in doc.items():
            if isinstance(v, dict):
                import dataclasses as _dc

                known = {f.name for f in _dc.fields(ElasticKindConfig)}
                kinds[k] = ElasticKindConfig(
                    **{kk: vv for kk, vv in v.items() if kk in known}
                )
        return cls(kinds=kinds, **top)


#: elastic gauge-region op codes (last_op_code gauge)
OP_CODES = {
    "scale-out": 1,
    "scale-in": 2,
    "rolling-restart": 3,
    "config-reload": 4,
    # hot code upgrade lifecycle (fdt_upgrade): commanded, refused at
    # the version handshake, or rolled back to the old recipe
    "hot-upgrade": 5,
    "refused": 6,
    "rollback": 7,
}


def elastic_metrics_schema(kinds: list[str]):
    """Schema for the shared `elastic` gauge region (fdt_elastic_* via
    the metric tile): per-kind shard count / epoch / drain-pending,
    plus the op history gauges the monitor renders."""
    from .metrics import MetricsSchema

    counters: list[str] = []
    for kind in kinds:
        counters += [
            f"{kind}_shards",
            f"{kind}_epoch",
            f"{kind}_drain_pending",
        ]
    counters += ["reconfigs", "last_op_code", "last_op_ts_us"]
    return MetricsSchema(counters=tuple(counters))


class ElasticController:
    """SLO-driven scaling policy over a Topology's shard groups.

    Deliberate-operation plumbing: every op runs inside the
    supervisor's COMMANDED bracket (the watchdog stands back; a crash
    mid-op is the op's to repair) and emits a `reconfig` event through
    the supervisor listeners (so the flight recorder freezes a bundle
    fdtincident classifies as `reconfig:<op>`), or directly through an
    attached FlightRecorder when unsupervised.

    Policy: scale-out fires when any watched SLO's fast burn holds at
    or above scale_out_burn; scale-in fires when the per-active-shard
    landed rate stays under scale_in_idle_tps for idle_for_s.  Both are
    dwell-paced (one op per dwell_s) with the same hysteresis shape as
    the ingress LoadShedder.
    """

    def __init__(
        self,
        topo,
        cfg: ElasticConfig,
        sup=None,
        slo=None,
        flight=None,
        clock=time.monotonic,
    ):
        self.topo = topo
        self.cfg = cfg
        self.sup = sup
        self.slo = slo
        self.flight = flight
        self.clock = clock
        self.ops: list[dict] = []  # history, newest last
        self._last_op_t = 0.0
        self._idle_since: dict[str, float] = {}
        self._rate_base: dict[str, tuple[float, int]] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="elastic", daemon=True
        )
        self._thread.start()

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.poll_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — scaling must not kill the host
                from firedancer_tpu.utils import log
                import traceback

                log.err(
                    "elastic controller error:\n%s", traceback.format_exc()
                )

    # -- policy -----------------------------------------------------------

    def _burn(self) -> float:
        """Max fast burn across the watched SLOs' last evaluation."""
        if self.slo is None:
            return 0.0
        return max(
            (
                s.burn_fast
                for s in self.slo._last
                if s.name in self.cfg.watch_slos
            ),
            default=0.0,
        )

    def _member_rate(self, kind: str, now: float) -> float | None:
        """Per-active-shard landed (in_frags) rate since the last tick."""
        grp = self.topo._shard_groups.get(kind)
        if grp is None:
            return None
        total = 0
        n_act = 0
        smv = self.topo.shardmap()
        mask = smv.mask(grp["slot"])
        for i, name in enumerate(grp["members"]):
            if not (mask >> i) & 1:
                continue
            n_act += 1
            total += self.topo.metrics(name).counter("in_frags")
        base = self._rate_base.get(kind)
        self._rate_base[kind] = (now, total)
        if base is None or now <= base[0]:
            return None
        return (total - base[1]) / (now - base[0]) / max(n_act, 1)

    def tick(self) -> None:
        """One controller pass (exposed for deterministic tests)."""
        now = self.clock()
        if self.slo is not None:
            from .flight import snapshot_topology

            self.slo.observe(snapshot_topology(self.topo), now=now)
            self.slo.evaluate(now=now)
        burn = self._burn()
        smv = self.topo.shardmap()
        for kind, kcfg in self.cfg.kinds.items():
            grp = self.topo._shard_groups.get(kind)
            if grp is None:
                continue
            n_act = smv.n_active(grp["slot"])
            rate = self._member_rate(kind, now)
            if burn >= kcfg.scale_out_burn and n_act < kcfg.max_shards:
                self._idle_since.pop(kind, None)
                if now - self._last_op_t >= self.cfg.dwell_s:
                    self.scale_out(kind)
                continue
            if rate is not None and rate < kcfg.scale_in_idle_tps:
                t0 = self._idle_since.setdefault(kind, now)
                if (
                    n_act > kcfg.min_shards
                    and now - t0 >= kcfg.idle_for_s
                    and now - self._last_op_t >= self.cfg.dwell_s
                ):
                    # retire the highest active member (LIFO, so the
                    # boot members are the stable core)
                    mask = smv.mask(grp["slot"])
                    i = max(active_members(mask))
                    self.scale_in(kind, i)
            else:
                self._idle_since.pop(kind, None)
        self.export_gauges()

    # -- deliberate operations --------------------------------------------

    def _commanded(self, name: str, op: str):
        if self.sup is not None:
            return self.sup.command(name, op)
        import contextlib

        return contextlib.nullcontext()

    def _note(
        self, op: str, tile: str | None, detail: dict,
        kind: str = "reconfig",
    ) -> None:
        rec = {"op": op, "tile": tile, "t": self.clock(), **detail}
        self.ops.append(rec)
        self._last_op_t = self.clock()
        m = self.topo._metrics.get("elastic")
        if m is not None:
            m.inc("reconfigs")
            m.set("last_op_code", OP_CODES.get(op.split(":")[0], 0))
            m.set("last_op_ts_us", time.monotonic_ns() // 1000)
        if self.sup is not None:
            if kind == "upgrade":
                self.sup.note_upgrade(tile, op, detail)
            else:
                self.sup.note_commanded(tile, op, detail)
        elif self.flight is not None:
            self.flight.trigger(kind, tile, {"op": op, **detail})

    def scale_out(self, kind: str) -> int:
        grp = self.topo._shard_groups[kind]
        smv = self.topo.shardmap()
        mask = smv.mask(grp["slot"])
        # same dual check as add_shard's own selection (mask bit clear
        # AND tile inactive), so a half-retired member is never picked
        # and an at-capacity kind raises descriptively, not IndexError
        free = [
            i
            for i in range(len(grp["members"]))
            if not (mask >> i) & 1
            and not self.topo.tiles[grp["members"][i]].active
        ]
        if not free:
            raise RuntimeError(
                f"shard kind {kind!r}: no free member to scale out"
            )
        i = free[0]
        name = grp["members"][i]
        with self._commanded(name, f"scale-out:{kind}"):
            if self.sup is not None:
                self.sup.note_spawn(name)
            self.topo.add_shard(kind, i)
        self._note(
            f"scale-out:{kind}", name,
            {"member": i, "shards": smv.n_active(grp["slot"])},
        )
        return i

    def scale_in(self, kind: str, i: int | None = None) -> int:
        grp = self.topo._shard_groups[kind]
        smv = self.topo.shardmap()
        if i is None:
            i = max(active_members(smv.mask(grp["slot"])))
        name = grp["members"][i]
        with self._commanded(name, f"scale-in:{kind}"):
            self.topo.retire_shard(kind, i)
        self._note(
            f"scale-in:{kind}", name,
            {"member": i, "shards": smv.n_active(grp["slot"])},
        )
        return i

    def rolling_restart(self, name: str, mutate=None, replay: int = 0) -> None:
        """Restart one tile under traffic (drain -> respawn -> rejoin);
        `mutate(tile)` applies a config change to the respawned
        incarnation (config reload / code hot-swap both ride it)."""
        op = "config-reload" if mutate is not None else "rolling-restart"
        with self._commanded(name, op):
            if self.sup is not None:
                self.sup.note_spawn(name)
            self.topo.rolling_restart(name, mutate=mutate, replay=replay)
        self._note(op, name, {})

    def hot_upgrade(self, name: str, **kw) -> None:
        """Commanded hot code upgrade of one tile (topo.hot_upgrade
        kwargs pass through: version_root/so_path/digest/mutate/replay/
        timeout_s).  Every outcome is an `upgrade`-kind event the
        flight recorder bundles and fdtincident classifies as
        `upgrade:<op>`: success (`hot-upgrade`), handshake refusal
        (`refused`, carrying BOTH version digests — the running tile
        was never touched), or boot-failure rollback (`rollback` — the
        old recipe is back at RUN before this re-raises).  The whole
        sequence runs under the supervisor's command bracket, so a
        refused/failed new-version spawn never burns the circuit
        breaker."""
        from .topo import UpgradeRefused, UpgradeRolledBack

        with self._commanded(name, "hot-upgrade"):
            if self.sup is not None:
                self.sup.note_spawn(name)
            try:
                self.topo.hot_upgrade(name, **kw)
            except UpgradeRefused as e:
                self._note(
                    "refused", name,
                    {
                        "shm_digest": f"{e.shm_digest:#018x}",
                        "new_digest": f"{e.new_digest:#018x}",
                    },
                    kind="upgrade",
                )
                raise
            except UpgradeRolledBack as e:
                self._note(
                    "rollback", name, {"cause": repr(e.cause)},
                    kind="upgrade",
                )
                raise
        self._note("hot-upgrade", name, {}, kind="upgrade")

    # -- gauges -----------------------------------------------------------

    def export_gauges(self) -> None:
        m = self.topo._metrics.get("elastic")
        if m is None:
            return
        smv = self.topo.shardmap()
        known = set(m.schema.counters)
        for kind, grp in self.topo._shard_groups.items():
            slot = grp["slot"]
            vals = {
                f"{kind}_shards": smv.n_active(slot),
                f"{kind}_epoch": smv.epoch(slot),
                f"{kind}_drain_pending": sum(
                    1
                    for i in range(len(grp["members"]))
                    if not (smv.mask(slot) >> i) & 1
                    and self.topo.tiles[grp["members"][i]].active
                ),
            }
            for k, v in vals.items():
                if k in known:
                    m.set(k, v)
