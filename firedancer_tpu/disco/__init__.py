"""disco — the tile framework: metrics, the mux run loop, topologies.

TPU-native re-design of the reference's disco layer
(src/disco/mux/fd_mux.c run loop, src/disco/topo/fd_topo.h declarative
topology, src/disco/metrics/ shared-memory metrics).  The key deliberate
difference: callbacks are BATCH-first (a tile sees an array of frags per
loop iteration, not one frag per callback), because our hot tiles amortize
work over device-sized batches and the per-frag work happens in native
code or on the TPU, never in the Python loop body.
"""

from .elastic import (  # noqa: F401
    ElasticConfig,
    ElasticController,
    ElasticKindConfig,
    ShardMap,
)
from .faultinj import Fault, FaultInjector  # noqa: F401
from .flight import FlightConfig, FlightRecorder  # noqa: F401
from .handshake import (  # noqa: F401
    Handshake,
    HandshakeRefused,
    probe_digest,
)
from .metrics import (  # noqa: F401
    Metrics,
    MetricsSchema,
    hist_frac_above,
    hist_percentile,
)
from .slo import SloConfig, SloEngine  # noqa: F401
from .mux import (  # noqa: F401
    InLink,
    MuxCtx,
    OutLink,
    Tile,
    run_loop,
    ts_diff,
    ts_diff_arr,
)
from .supervisor import RestartPolicy, Supervisor  # noqa: F401
from .topo import (  # noqa: F401
    Topology,
    UpgradeRefused,
    UpgradeRolledBack,
)
from .trace import SpanRing, TraceConfig, Tracer  # noqa: F401
