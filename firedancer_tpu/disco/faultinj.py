"""Deterministic, schedule-driven fault injection for tile topologies.

The chaos-test analog of the reference's test harnesses that wedge and
kill tiles by hand (e.g. src/tango/test_frag_tx/rx killing producers
mid-stream): a seeded `FaultInjector` holds a schedule of `Fault`s and
the mux loop (disco/mux.py) consults a per-tile `TileFaults` view at
three well-defined points —

  1. top of every iteration, BEFORE the heartbeat: `tick()` fires
     scripted kills (raise), stalls (heartbeat starvation: sleep without
     beating, abandonable via ctx.interrupt) and arms credit squeezes;
  2. after the credit computation: `squeeze_credits()` forces zero
     credits (scripted backpressure);
  3. between the ring drain and the tile callback: `mangle_frags()`
     drops frags or corrupts their payload bytes in the dcache.

`FallbackPolicy` (tiles/verify.py) additionally calls `device_error()`
once per device batch to fire scripted TPU/Pallas dispatch failures.

Determinism contract: every stochastic choice (which frag is dropped,
which byte is flipped) is a pure hash of (seed, fault index, per-link
frag index), NOT of batch boundaries or wall time — two runs over the
same input stream inject byte-identical fault effects regardless of how
the loop happened to batch the frags.  The injector records every fired
event in `events` (append order follows wall-clock firing and is NOT
deterministic across trigger domains); `fired()` returns the canonical
merged record, which IS equal across replays of the same seed —
chaos tests diff that.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

import numpy as np


class FaultKill(RuntimeError):
    """A scripted tile crash (the injected analog of an unhandled tile
    exception): propagates out of the run loop through the normal
    CNC_FAIL path."""


class DeviceFault(RuntimeError):
    """A scripted device-dispatch failure (the injected analog of a
    TPU/Pallas runtime error): raised into FallbackPolicy's dispatch."""


@dataclass
class Fault:
    """One scheduled fault.

    tile:  target tile name.
    kind:  kill | stall | backpressure | drop | corrupt | device_error
           | flood | conn_churn.
    at:    trigger index — loop-iteration tick (kill/stall/backpressure/
           flood/conn_churn with on="tick"), cumulative in-frag count
           (on="frag", and always for drop/corrupt), or device-batch
           index (device_error).  All indices are cumulative across
           restarts.
    on:    "tick" or "frag" trigger domain for kill/stall/backpressure/
           flood/conn_churn.
    count: frags affected (drop/corrupt), iterations squeezed
           (backpressure), device batches failed (device_error), or
           hostile items synthesized (flood/conn_churn).
    frac:  per-frag probability within the [at, at+count) window for
           drop/corrupt (seeded hash, batch-boundary independent).
    duration_s: stall length (heartbeat starvation time).
    link:  restrict drop/corrupt to one in-link name (None = all).
           For flood faults the field doubles as the ATTACK PROFILE the
           consuming tile synthesizes ("garbage" | "handshake" |
           "loris" | "malformed" | "smallorder" | "dup"; None = the
           tile default).

    flood / conn_churn are INJECTED-TRAFFIC faults (ISSUE 13): when one
    fires (point 1, same trigger domains as kill/stall) it is
    canonical-record'd like every other kind, then parked on the view's
    pending-injection list; a tile that understands hostile ingress
    (tiles/quic.py synthesizes connection floods / churn storms / txn
    spam, tiles/synth.py synthesizes duplicate storms) drains it via
    `take_injected()` and generates the traffic IN-PROCESS from the
    injector's seed — one injection path shared by chaos_soak.py and
    scripts/adversary.py, identical under the thread and process
    runtimes.  A kill between fire and consumption loses that pending
    injection for the dead incarnation (the fired flag is durable, so
    it never re-fires — the canonical record stays exact).
    device: restrict device_error to one device-pool domain (None = the
           tile's merged batch stream).  A targeted fault's `at` indexes
           THAT device's own batch sequence, which stays deterministic
           under the pool's timing-dependent scheduler; an untargeted
           fault on a multi-device tile indexes the merged stream, whose
           order depends on scheduling — use at=0 windows there.
    """

    tile: str
    kind: str
    at: int = 0
    on: str = "tick"
    count: int = 1
    frac: float = 1.0
    duration_s: float = 0.0
    link: str | None = None
    device: int | None = None
    fired: bool = field(default=False, compare=False)


def _hash_u64(seed: int, fault_idx: int, idx: np.ndarray) -> np.ndarray:
    """splitmix64-style mix of (seed, fault, frag index) -> u64, the
    batch-independent randomness source for drop/corrupt decisions."""
    x = (
        np.asarray(idx, np.uint64)
        + np.uint64((seed * 0x9E3779B97F4A7C15 + fault_idx) & (2**64 - 1))
    )
    with np.errstate(over="ignore"):
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


class FaultInjector:
    """Seeded schedule of faults + shared event log."""

    def __init__(self, seed: int = 0, faults: list[Fault] | None = None):
        self.seed = int(seed)
        self.faults: list[Fault] = list(faults or [])
        self.events: list[tuple] = []
        self._lock = threading.Lock()

    def add(self, tile: str, kind: str, **kw) -> "FaultInjector":
        self.faults.append(Fault(tile, kind, **kw))
        return self

    def log(self, tile: str, kind: str, where: int, detail=None) -> None:
        with self._lock:
            self.events.append((tile, kind, int(where), detail))

    def view(self, tile_name: str) -> "TileFaults":
        """The per-tile hook object the mux loop consults.  Each tile
        only ever touches its own view (no cross-tile locking on the
        hot path)."""
        mine = [
            (i, f) for i, f in enumerate(self.faults) if f.tile == tile_name
        ]
        return TileFaults(self, tile_name, mine)

    def fold_shm_fired(self, tile: str, mem_u8) -> None:
        """Parent-side restore of a CHILD's durable fired flags
        (TileFaults.bind_shm layout) into this injector's event log.

        Under process isolation the child's `log()` calls land in the
        child's reconstructed injector, so the parent's canonical
        record — what incident bundles embed and fdtincident classifies
        against — would read empty.  The fired FLAGS survive in the
        tile's fstat workspace region, and every tick-domain fault's
        log detail is schedule-derivable, so the parent can synthesize
        the exact event (kill/stall/backpressure/flood/conn_churn).
        Frag-domain kinds (drop/corrupt) and device_error fire with
        per-frag / per-batch detail only the child saw; they synthesize
        with EMPTY detail — kind and window are canonical, the frag
        list is not (classification keys off kinds, never the list)."""
        mine = [(i, f) for i, f in enumerate(self.faults) if f.tile == tile]
        if not mine:
            return
        w = mem_u8[: (len(mem_u8) // 8) * 8].view(np.uint64)
        if len(w) < 2 + len(mine):
            return
        with self._lock:
            have = {(e[0], e[1], e[2]) for e in self.events}
        for k, (_, f) in enumerate(mine):
            if not w[2 + k]:
                continue
            f.fired = True
            if f.kind in ("flood", "conn_churn"):
                ev = (tile, f.kind, f.at, (f.count, f.link))
            elif f.kind == "kill":
                ev = (tile, "kill", f.at, None)
            elif f.kind == "stall":
                ev = (tile, "stall", f.at, f.duration_s)
            elif f.kind == "backpressure":
                ev = (tile, "backpressure", f.at, f.count)
            else:  # drop / corrupt / device_error: detail is child-only
                ev = (tile, f.kind, f.at, [])
            if (tile, f.kind, f.at) not in have:
                self.log(*ev)

    def fold_topology(self, topo) -> None:
        """fold_shm_fired over every tile with an fstat region (the
        process runtime); a no-op for thread topologies, where the
        shared injector already holds the events."""
        wksp = getattr(topo, "wksp", None)
        if wksp is None:
            return
        for name in topo.tiles:
            key = f"fstat_{name}"
            if key in getattr(wksp, "_allocs", {}):
                self.fold_shm_fired(name, wksp.view(key))

    def fired(self) -> list[tuple]:
        """Canonical record of everything that fired: drop/corrupt
        windows merged per fault (their per-batch log entries depend on
        batch boundaries; their union does not), then sorted.  Two runs
        with the same seed, schedule, and input stream produce EQUAL
        lists — this is the replay-diffable artifact."""
        with self._lock:
            frag: dict[tuple, list] = {}
            rest = []
            for t, k, w, d in self.events:
                if k in ("drop", "corrupt"):
                    frag.setdefault((t, k, w), []).extend(d)
                else:
                    rest.append((t, k, w, d))
        merged = [
            (t, k, w, tuple(sorted(d))) for (t, k, w), d in frag.items()
        ]
        return sorted(merged + rest, key=repr)

    def count(self, kind: str, tile: str | None = None) -> int:
        with self._lock:
            return sum(
                1
                for e in self.events
                if e[1] == kind and (tile is None or e[0] == tile)
            )

    def dropped_frags(self, tile: str | None = None) -> int:
        with self._lock:
            return sum(
                len(e[3])
                for e in self.events
                if e[1] == "drop" and (tile is None or e[0] == tile)
            )

    def corrupted_frags(self, tile: str | None = None) -> int:
        with self._lock:
            return sum(
                len(e[3])
                for e in self.events
                if e[1] == "corrupt" and (tile is None or e[0] == tile)
            )


class TileFaults:
    """One tile's fault hooks (held on MuxCtx.faults)."""

    def __init__(self, inj: FaultInjector, tile: str, faults: list):
        self.inj = inj
        self.tile = tile
        #: ordered (global index, fault) pairs for shm state mapping
        self._mine = list(faults)
        #: process runtime: shm backing for the cumulative trigger
        #: state (ticks, frags_seen, per-fault fired flags) — see
        #: bind_shm.  None in the threaded runtime (the shared injector
        #: object itself carries the state across restarts).
        self._shm = None
        #: span tracer (disco/trace.py), bound by the run loop at boot
        #: so injected faults annotate themselves into the tile's trace
        #: (only ever written from the tile's own loop thread)
        self.tracer = None
        self.ticks = 0
        self.frags_seen = 0  # across all in-links (on="frag" triggers)
        self._link_idx: dict[str, int] = {}  # per-link cumulative index
        self.dev_batches = 0
        #: per-device batch indices (device-pool workers each call
        #: device_error with their domain index)
        self.dev_batches_by: dict[int, int] = {}
        #: device_error is called from every pool worker thread; the
        #: merged dev_batches read-modify-write must not lose updates
        #: (a lost increment shifts an untargeted fault window and
        #: breaks the injector's determinism contract)
        self._dev_lock = threading.Lock()
        self._squeeze = 0
        #: fired-but-unconsumed injected-traffic faults, drained by the
        #: owning tile via take_injected(): (fault_idx, kind, count,
        #: profile) tuples
        self._injected: list[tuple[int, str, int, str | None]] = []
        self._tick_faults = [
            (i, f)
            for i, f in faults
            if f.kind in ("kill", "stall", "backpressure")
        ]
        self._inj_faults = [
            (i, f) for i, f in faults if f.kind in ("flood", "conn_churn")
        ]
        self._frag_faults = [
            (i, f) for i, f in faults if f.kind in ("drop", "corrupt")
        ]
        self._dev_faults = [
            (i, f) for i, f in faults if f.kind == "device_error"
        ]

    def bind_shm(self, mem_u8) -> None:
        """Back the cumulative trigger state with a workspace region so
        it survives a CHILD PROCESS restart.  The documented contract —
        "all indices are cumulative across restarts" and a fired fault
        stays fired — holds in the threaded runtime because every
        incarnation shares one injector object; a re-spawned child
        reconstructs the injector from the manifest, so without this a
        scripted kill would re-fire in EVERY incarnation (a kill loop).
        Layout: w0 = ticks, w1 = frags_seen, w2+k = fired flag of this
        tile's k-th fault.  Single writer (the owning tile's loop)."""
        need = 2 + len(self._mine)
        w = mem_u8[: (len(mem_u8) // 8) * 8].view(np.uint64)
        if len(w) < need:
            raise ValueError(
                f"fault-state region too small: {len(w)} words for "
                f"{len(self._mine)} faults"
            )
        self._shm = w
        self.ticks = int(w[0])
        self.frags_seen = int(w[1])
        for k, (_, f) in enumerate(self._mine):
            f.fired = bool(w[2 + k])

    def _persist_fired(self, f: Fault) -> None:
        if self._shm is not None:
            for k, (_, mf) in enumerate(self._mine):
                if mf is f:
                    self._shm[2 + k] = 1
                    return

    # -- point 1: loop top ------------------------------------------------

    def tick(self, ctx) -> None:
        self.ticks += 1
        if self._shm is not None:
            self._shm[0] = np.uint64(self.ticks)
        # injected-traffic faults fire first (a same-tick kill must not
        # swallow a scheduled flood's canonical record)
        for i, f in self._inj_faults:
            if f.fired:
                continue
            ref = self.ticks if f.on == "tick" else self.frags_seen
            if ref < f.at:
                continue
            f.fired = True
            self._persist_fired(f)
            self.inj.log(self.tile, f.kind, f.at, (f.count, f.link))
            if self.tracer is not None:
                self.tracer.fault(f.kind, seq=f.at, aux64=f.count)
            self._injected.append((i, f.kind, f.count, f.link))
        for _, f in self._tick_faults:
            if f.fired:
                continue
            ref = self.ticks if f.on == "tick" else self.frags_seen
            if ref < f.at:
                continue
            f.fired = True
            # persist BEFORE the effect: a kill raises out of this
            # frame, and the flag must already be durable when the
            # supervisor respawns the child
            self._persist_fired(f)
            if f.kind == "kill":
                self.inj.log(self.tile, "kill", f.at)
                if self.tracer is not None:
                    self.tracer.fault("kill", seq=f.at)
                raise FaultKill(f"{self.tile}: scripted kill at {f.at}")
            if f.kind == "stall":
                self.inj.log(self.tile, "stall", f.at, f.duration_s)
                if self.tracer is not None:
                    self.tracer.fault(
                        "stall", seq=f.at, aux64=int(f.duration_s * 1e6)
                    )
                self._stall(ctx, f.duration_s)
            elif f.kind == "backpressure":
                self.inj.log(self.tile, "backpressure", f.at, f.count)
                self._squeeze += f.count

    def _stall(self, ctx, duration_s: float) -> None:
        """Heartbeat starvation: hold the loop without beating.  The
        supervisor's only handle on a wedged tile is ctx.interrupt —
        honoring it here is what the interrupt protocol guarantees for
        any stall that sleeps cooperatively."""
        from .mux import TileInterrupted

        end = time.monotonic() + duration_s
        while time.monotonic() < end:
            if ctx.interrupt.is_set():
                raise TileInterrupted(
                    f"{self.tile}: stall abandoned by supervisor"
                )
            time.sleep(2e-3)

    def take_injected(self) -> list[tuple[int, str, int, str | None]]:
        """Drain fired-but-unconsumed flood/conn_churn injections (the
        owning tile synthesizes the hostile traffic; see Fault docs)."""
        if not self._injected:
            return []
        out, self._injected = self._injected, []
        return out

    # -- point 2: credit gate ---------------------------------------------

    def squeeze_credits(self) -> bool:
        if self._squeeze > 0:
            self._squeeze -= 1
            return True
        return False

    # -- point 3: drained frags -------------------------------------------

    @property
    def has_frag_faults(self) -> bool:
        """True when any drop/corrupt fault targets this tile.  The
        native stem cannot route frags through mangle_frags (the bytes
        never surface to Python), so the run loop keeps the tile on the
        Python path whenever this is set — the injection windows stay
        deterministic and the documented point-3 semantics exact."""
        return bool(self._frag_faults)

    def note_frags(self, il, n: int) -> None:
        """Burst-boundary frag accounting for the native stem: n frags
        were consumed on `il` without passing through mangle_frags (no
        drop/corrupt faults exist for this tile — see has_frag_faults),
        so the cumulative counters that drive on="frag" triggers keep
        advancing and a scripted kill/stall still fires at the next
        burst boundary (point 1 reads frags_seen)."""
        self.frags_seen += n
        if self._shm is not None:
            self._shm[1] = np.uint64(self.frags_seen)
        self._link_idx[il.name] = self._link_idx.get(il.name, 0) + n

    def mangle_frags(self, il, frags: np.ndarray) -> np.ndarray:
        n = len(frags)
        self.frags_seen += n
        if self._shm is not None:
            self._shm[1] = np.uint64(self.frags_seen)
        # drop/corrupt windows index the PER-LINK frag stream: each link
        # is a FIFO, so these indices are deterministic even when a tile
        # drains several in-links in timing-dependent interleavings
        base = self._link_idx.get(il.name, 0)
        self._link_idx[il.name] = base + n
        if not self._frag_faults:
            return frags
        gidx = np.arange(base, base + n, dtype=np.uint64)
        keep = np.ones(n, dtype=bool)
        for fi, f in self._frag_faults:
            if f.link is not None and f.link != il.name:
                continue
            sel = (gidx >= f.at) & (gidx < f.at + f.count)
            if f.frac < 1.0:
                h = _hash_u64(self.inj.seed, fi, gidx)
                sel &= (h >> np.uint64(11)).astype(np.float64) / float(
                    1 << 53
                ) < f.frac
            if not sel.any():
                continue
            hit = np.flatnonzero(sel)
            if self.tracer is not None:
                self.tracer.fault(f.kind, seq=f.at, aux64=len(hit))
            if f.kind == "drop":
                keep[hit] = False
                self.inj.log(
                    self.tile, "drop", fi, [int(g) for g in gidx[hit]]
                )
            else:  # corrupt: flip a deterministic signature byte in place
                pos = _hash_u64(self.inj.seed, fi ^ 0x5A5A, gidx[hit])
                for t, j in enumerate(hit):
                    sz = int(frags["sz"][j])
                    # byte 1..64 lies inside the (first) signature for
                    # the wire txn format: structurally harmless, but
                    # cryptographically fatal — verify must reject it
                    span = np.uint64(min(64, max(sz - 1, 1)))
                    off = int(frags["chunk"][j]) * 64 + 1 + int(
                        pos[t] % span
                    )
                    il.dcache.mem[off] ^= 0xFF
                self.inj.log(
                    self.tile, "corrupt", fi, [int(g) for g in gidx[hit]]
                )
        if keep.all():
            return frags
        return frags[keep]

    # -- device batches (FallbackPolicy hook) -----------------------------

    def device_error(self, device: int | None = None) -> None:
        """Fired once per device-batch attempt.  Single-device policies
        call it bare (the merged stream); pool domains pass their index
        so a fault can target ONE device — the quarantine/redistribute
        chaos tests key on that."""
        with self._dev_lock:
            b = self.dev_batches
            self.dev_batches = b + 1
            bd = None
            if device is not None:
                bd = self.dev_batches_by.get(device, 0)
                self.dev_batches_by[device] = bd + 1
        for _, f in self._dev_faults:
            if f.device is not None:
                if device is None or f.device != device:
                    continue
                ref = bd
            else:
                ref = b
            if f.at <= ref < f.at + f.count:
                self.inj.log(self.tile, "device_error", ref, device)
                raise DeviceFault(
                    f"{self.tile}: scripted device failure at batch {ref}"
                    + (f" on dev{device}" if device is not None else "")
                )
