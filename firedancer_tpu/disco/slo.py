"""SLO engine: asserted service-level objectives with multi-window
burn-rate evaluation over the fdttrace measurement substrate.

The framing is "The Tail at Scale" (Dean & Barroso, CACM 2013) applied
to the quic→verify→dedup→pack path: the SLOs are TAIL objectives
(e2e p99, per-hop p99) plus a throughput floor and a drop ceiling, and
the alerting is the multi-window burn-rate scheme (a breach must burn
the error budget fast over a short window AND be sustained over a long
window before it alarms — a single slow batch is noise, a sustained
regression is an incident).

Inputs are monitor-shaped snapshots ({tile: {"counters": ..,
"lat_hists": ..}}, app/monitor.py Monitor.snapshot or
flight.snapshot_topology) — the engine is a pure library over them, so
the in-process flight recorder and an attached cross-process monitor
evaluate the SAME objectives from the same shared-memory histograms.

SLO semantics (all optional; None = not asserted):
  e2e_p99_us        end-to-end p99 ceiling, measured on the merged
                    e2e_us_* hists of the path's EXIT tiles (tiles with
                    no out links: sink/store).  Budget: at most
                    `budget` (default 1%) of samples may exceed it.
                    NOTE: a latency ceiling must sit inside its hist's
                    log2 domain or a violation can never be observed —
                    the bound is derived from the storage format
                    (hist_domain_end_us), NOT hardcoded: the per-link
                    latency hists are WIDE (2^WIDE_HIST_BUCKETS µs
                    domain with an explicit overflow bucket — ISSUE 15
                    widened them from 16-bucket, retiring the old
                    2^16 µs SLO ceiling bound).  SloConfig validation
                    rejects unobservable ceilings loudly instead of
                    asserting an SLO that can never fire.
  verify_hop_p99_us verify service-time p99 ceiling (svc_us_* hists of
                    verify* tiles), same budget semantics.
  landed_tps_min    throughput floor: windowed in_frags rate at the
                    exit tiles must stay >= this.
  drop_rate_max     ceiling on the per-window drop fraction,
                    dropped / (landed + dropped), where dropped sums
                    the declared-loss counters (overruns + verify
                    rejects) across every tile and landed is the
                    exit-tile frag count.  (Landed, not a sum of every
                    hop's in_frags — that would count each frag once
                    per hop and understate the fraction by the
                    pipeline depth.)

Burn rate for the latency SLOs = bad_fraction / budget; for the floor,
shortfall = floor / measured_rate; for the drop ceiling, observed_rate /
ceiling.  A breach fires when BOTH windows exceed their thresholds
(burn_fast over the fast window and burn_slow over the slow window),
following the SRE-workbook multiwindow scheme.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from .metrics import (
    HIST_BUCKETS,
    WIDE_HIST_BUCKETS,
    hist_delta,
    hist_frac_above,
    hist_percentile,
    merge_hists,
)


def hist_domain_end_us(wide: bool = False) -> float:
    """Largest value a log2 latency hist can distinguish from the
    overflow clamp — the observability bound for latency ceilings.
    Derived from the storage format so widening a hist (the sched-lag
    fix) automatically lifts the matching ceiling-bound check here."""
    return float(1 << (WIDE_HIST_BUCKETS if wide else HIST_BUCKETS))

#: counters summed into the window's "dropped" numerator — declared
#: frag loss only (injected drops are declared by faultinj, not here)
DROP_COUNTERS = ("overrun_frags", "verify_fail_txns", "dup_txns")
#: dup_txns is exactly-once collapse, not loss — excluded by default
DEFAULT_DROP_COUNTERS = ("overrun_frags", "verify_fail_txns")


@dataclass(frozen=True)
class SloConfig:
    """The `[slo]` config section (app/config.py).  Window/threshold
    defaults suit a live deployment; tests shrink the windows."""

    e2e_p99_us: float | None = None
    verify_hop_p99_us: float | None = None
    #: queue-wait tail ceiling across every hop (qwait_us_* hists,
    #: merged over all tiles): time frags sit in rings behind a busy
    #: consumer — the CAPACITY signal, and what the elastic controller
    #: (disco/elastic.py) watches for scale-out (a saturated shard
    #: shows up as queue-wait long before e2e breaches)
    queue_wait_p99_us: float | None = None
    landed_tps_min: float | None = None
    drop_rate_max: float | None = None
    #: error budget for the latency SLOs: tolerated fraction of samples
    #: above the ceiling (p99 objective = 1% budget)
    budget: float = 0.01
    fast_window_s: float = 5.0
    slow_window_s: float = 60.0
    #: burn-rate thresholds per window (SRE-workbook style: fast burn
    #: must be large, slow burn sustained)
    burn_fast: float = 10.0
    burn_slow: float = 2.0

    def validate(self) -> None:
        """Reject latency ceilings the storage format can never observe
        as violated (they would assert an SLO that cannot fire).  The
        bound comes from the hist width the objective is evaluated
        over: the per-link qwait/svc/e2e hists are WIDE
        (WIDE_HIST_BUCKETS with an explicit overflow bucket, ISSUE 15 —
        previously 16-bucket, which capped every latency SLO at
        2^16 µs), so ceilings must sit under the wide domain end
        (2^WIDE_HIST_BUCKETS µs ~ 16.8 s)."""
        for name in (
            "e2e_p99_us", "verify_hop_p99_us", "queue_wait_p99_us"
        ):
            v = getattr(self, name)
            if v is not None and v >= hist_domain_end_us(wide=True):
                raise ValueError(
                    f"slo {name}={v:,.0f}us is unobservable: the "
                    f"{WIDE_HIST_BUCKETS}-bucket latency hist domain "
                    f"ends at {hist_domain_end_us(wide=True):,.0f}us — "
                    f"a violation could never be recorded (lower the "
                    f"ceiling)"
                )

    def asserted(self) -> list[str]:
        return [
            k
            for k in (
                "e2e_p99_us",
                "verify_hop_p99_us",
                "queue_wait_p99_us",
                "landed_tps_min",
                "drop_rate_max",
            )
            if getattr(self, k) is not None
        ]

    def to_dict(self) -> dict:
        from dataclasses import asdict

        return asdict(self)

    @classmethod
    def from_dict(cls, doc: dict) -> "SloConfig":
        import dataclasses

        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in doc.items() if k in known})


@dataclass
class SloStatus:
    """One SLO's evaluation at a point in time."""

    name: str
    threshold: float
    #: the fast/slow-window burn rates (>= 1.0 means the window is
    #: violating the objective at budget-exhausting rate)
    burn_fast: float = 0.0
    burn_slow: float = 0.0
    breached: bool = False
    #: measured headline value over the fast window (p99 / rate / frac)
    measured: float = 0.0
    detail: str = ""

    def to_dict(self) -> dict:
        from dataclasses import asdict

        return asdict(self)


@dataclass
class _Digest:
    """One observation: the cumulative state the windows difference."""

    ts: float
    e2e: dict = field(default_factory=dict)
    verify_hop: dict = field(default_factory=dict)
    qwait: dict = field(default_factory=dict)
    landed_frags: int = 0
    dropped_frags: int = 0


class SloEngine:
    """Windowed burn-rate evaluation.  Feed monitor-shaped snapshots
    via observe(); read evaluate()/alarm_rows()/gauges().

    `tile_links` ({tile: {"ins": [...], "outs": [...]}}) tells the
    engine which tiles are path exits (no outs) — the topology manifest
    and flight.snapshot_topology both carry it."""

    def __init__(
        self,
        cfg: SloConfig,
        tile_links: dict[str, dict] | None = None,
        clock=time.monotonic,
    ):
        cfg.validate()
        self.cfg = cfg
        self.tile_links = tile_links or {}
        self.clock = clock
        self._digests: list[_Digest] = []
        self._last: list[SloStatus] = []
        #: breach edges: name -> currently-breached (for incident
        #: debounce — the flight recorder fires one bundle per edge)
        self.breached_now: dict[str, bool] = {}

    # -- snapshot digestion ----------------------------------------------

    def _exit_tiles(self, snap: dict) -> list[str]:
        names = [n for n in snap if n != "_links"]
        if self.tile_links:
            exits = [
                n
                for n in names
                if not self.tile_links.get(n, {}).get("outs")
                # observer tiles (metric/rpc) have no ins either
                and self.tile_links.get(n, {}).get("ins")
            ]
            if exits:
                return exits
        return names

    def observe(self, snap: dict, now: float | None = None) -> None:
        """Digest one snapshot.  Keeps ~2x slow_window of history."""
        now = self.clock() if now is None else now
        d = _Digest(ts=now)
        exits = set(self._exit_tiles(snap))
        e2e, vhop, qwait = [], [], []
        for name, row in snap.items():
            if name == "_links":
                continue
            c = row.get("counters", {})
            hists = row.get("lat_hists", {})
            if name in exits:
                d.landed_frags += c.get("in_frags", 0)
                e2e.extend(
                    h for k, h in hists.items() if k.startswith("e2e_us_")
                )
            if name.startswith("verify"):
                vhop.extend(
                    h for k, h in hists.items() if k.startswith("svc_us_")
                )
            # queue-wait merges EVERY hop: the signal is "frags waiting
            # behind a busy consumer", wherever the bottleneck sits
            qwait.extend(
                h for k, h in hists.items() if k.startswith("qwait_us_")
            )
            d.dropped_frags += sum(
                c.get(k, 0) for k in DEFAULT_DROP_COUNTERS
            )
        d.e2e = merge_hists(e2e)
        d.verify_hop = merge_hists(vhop)
        d.qwait = merge_hists(qwait)
        self._digests.append(d)
        horizon = now - 2.0 * self.cfg.slow_window_s - 1.0
        while len(self._digests) > 2 and self._digests[1].ts <= horizon:
            self._digests.pop(0)

    def _window(self, now: float, span_s: float) -> tuple[_Digest, _Digest] | None:
        """(oldest digest inside [now-span, now], newest digest), or
        None when the window has no baseline yet.  When the sampling
        interval exceeds the span (a monitor polling slower than the
        fast window), fall back to the NEWEST prior digest — a window
        one sampling interval wide, the closest available approximation
        — never to the oldest history, which would silently dilute a
        fast burn into the slow-window average."""
        if len(self._digests) < 2:
            return None
        cur = self._digests[-1]
        base = None
        for d in self._digests[:-1]:
            if d.ts >= now - span_s:
                base = d
                break
        if base is None:
            base = self._digests[-2]
        if cur.ts <= base.ts:
            return None
        return base, cur

    # -- evaluation -------------------------------------------------------

    def _latency_burn(
        self, now: float, span_s: float, which: str, ceiling: float
    ) -> tuple[float, float]:
        """(burn, measured p99) for a latency SLO over one window."""
        w = self._window(now, span_s)
        if w is None:
            return 0.0, 0.0
        base, cur = w
        dh = hist_delta(getattr(cur, which), getattr(base, which))
        if dh.get("count", 0) <= 0:
            return 0.0, 0.0
        bad = hist_frac_above(dh, ceiling)
        return bad / max(self.cfg.budget, 1e-9), hist_percentile(dh, 99.0)

    def _rate(self, now: float, span_s: float, attr: str) -> float | None:
        w = self._window(now, span_s)
        if w is None:
            return None
        base, cur = w
        return (getattr(cur, attr) - getattr(base, attr)) / (
            cur.ts - base.ts
        )

    def evaluate(self, now: float | None = None) -> list[SloStatus]:
        now = self.clock() if now is None else now
        cfg = self.cfg
        out: list[SloStatus] = []

        for name, which in (
            ("e2e_p99_us", "e2e"),
            ("verify_hop_p99_us", "verify_hop"),
            ("queue_wait_p99_us", "qwait"),
        ):
            ceiling = getattr(cfg, name)
            if ceiling is None:
                continue
            bf, p99f = self._latency_burn(
                now, cfg.fast_window_s, which, ceiling
            )
            bs, _ = self._latency_burn(now, cfg.slow_window_s, which, ceiling)
            st = SloStatus(
                name, ceiling, round(bf, 3), round(bs, 3),
                breached=bf >= cfg.burn_fast and bs >= cfg.burn_slow,
                measured=round(p99f, 1),
                detail=f"p99={p99f:,.0f}us ceiling={ceiling:,.0f}us",
            )
            out.append(st)

        if cfg.landed_tps_min is not None:
            rf = self._rate(now, cfg.fast_window_s, "landed_frags")
            rs = self._rate(now, cfg.slow_window_s, "landed_frags")
            bf = 0.0 if rf is None else cfg.landed_tps_min / max(rf, 1e-9)
            bs = 0.0 if rs is None else cfg.landed_tps_min / max(rs, 1e-9)
            out.append(
                SloStatus(
                    "landed_tps_min", cfg.landed_tps_min,
                    round(min(bf, 1e6), 3), round(min(bs, 1e6), 3),
                    # the floor's "burn" is shortfall; both windows must
                    # be under the floor (shortfall > 1) to breach
                    breached=bf > 1.0 and bs > 1.0,
                    measured=0.0 if rf is None else round(rf, 1),
                    detail=(
                        f"rate={0.0 if rf is None else rf:,.0f}/s "
                        f"floor={cfg.landed_tps_min:,.0f}/s"
                    ),
                )
            )

        if cfg.drop_rate_max is not None:
            st = self._drop_status(now)
            out.append(st)

        self._last = out
        self.breached_now = {s.name: s.breached for s in out}
        return out

    def _drop_status(self, now: float) -> SloStatus:
        cfg = self.cfg

        def frac(span_s: float) -> float | None:
            w = self._window(now, span_s)
            if w is None:
                return None
            base, cur = w
            ddrop = max(cur.dropped_frags - base.dropped_frags, 0)
            dland = max(cur.landed_frags - base.landed_frags, 0)
            if ddrop + dland <= 0:
                return None
            return ddrop / (ddrop + dland)

        ff, fs = frac(cfg.fast_window_s), frac(cfg.slow_window_s)
        bf = 0.0 if ff is None else ff / max(cfg.drop_rate_max, 1e-9)
        bs = 0.0 if fs is None else fs / max(cfg.drop_rate_max, 1e-9)
        return SloStatus(
            "drop_rate_max", cfg.drop_rate_max,
            round(bf, 3), round(bs, 3),
            breached=bf > 1.0 and bs > 1.0,
            measured=0.0 if ff is None else round(ff, 6),
            detail=(
                f"drop_frac={0.0 if ff is None else ff:.4f} "
                f"ceiling={cfg.drop_rate_max:.4f}"
            ),
        )

    def recommended_shed_level(self) -> int:
        """Map the last evaluation's burn rates to a commanded ingress
        load-shed level (waltz/admission.py LoadShedder semantics; the
        flight recorder writes it into the shared `shed` region and the
        quic tile treats it as a FLOOR under its local backpressure
        view):

            0  no latency/throughput SLO burning
            1  budget burning (fast burn >= 1): shed unstaked
            2  fast burn at alert threshold: shed low-stake too
            3  confirmed breach: emergency staked-only

        Only the tail-LATENCY SLOs drive shedding.  drop_rate_max is
        excluded (shedding RAISES the drop rate by design) and so is
        landed_tps_min (shedding LOWERS landed throughput): feeding
        either back would be positive feedback — a benign traffic lull
        burns the throughput floor, commands a shed, which lowers
        landed TPS further and latches the shedder at max forever.
        Shedding is judged right only if it protects the latency tail,
        so only the latency tail may command it.  queue_wait_p99_us is
        also excluded: a burning queue-wait means the topology is
        UNDERSIZED, and the right actuator is the elastic controller
        (scale-out, disco/elastic.py) — shedding paying traffic to
        mask a capacity shortfall would hide exactly the signal
        scaling needs."""
        lvl = 0
        for s in self._last:
            if s.name not in ("e2e_p99_us", "verify_hop_p99_us"):
                continue
            if s.breached:
                lvl = max(lvl, 3)
            elif s.burn_fast >= self.cfg.burn_fast:
                lvl = max(lvl, 2)
            elif s.burn_fast >= 1.0:
                lvl = max(lvl, 1)
        return lvl

    # -- surfacing --------------------------------------------------------

    def alarm_rows(self) -> list[str]:
        """Monitor alarm lines for the last evaluation (breaches as
        ALARM, elevated-but-unconfirmed fast burns as NOTE)."""
        out = []
        for s in self._last:
            if s.breached:
                out.append(
                    f"ALARM slo {s.name}: breached ({s.detail}; burn "
                    f"fast={s.burn_fast} slow={s.burn_slow})"
                )
            elif s.burn_fast >= 1.0:
                out.append(
                    f"NOTE slo {s.name}: burning budget ({s.detail}; "
                    f"burn fast={s.burn_fast} slow={s.burn_slow})"
                )
        return out

    def gauges(self) -> dict[str, int]:
        """Fixed-point (x1000) gauges for the shared `slo` metrics
        region / Prometheus export: per-SLO fast/slow burn and breach."""
        out: dict[str, int] = {}
        for s in self._last:
            key = s.name
            out[f"{key}_burn_fast_x1000"] = int(
                min(max(s.burn_fast, 0.0), 1e6) * 1000
            )
            out[f"{key}_burn_slow_x1000"] = int(
                min(max(s.burn_slow, 0.0), 1e6) * 1000
            )
            out[f"{key}_breached"] = int(s.breached)
        return out

    def to_dict(self) -> dict:
        return {
            "config": self.cfg.to_dict(),
            "status": [s.to_dict() for s in self._last],
        }


def slo_metrics_schema(cfg: SloConfig):
    """Schema for the shared `slo` gauge region (one counter slot per
    gauge the engine exports), so monitors/Prometheus scrape burn rates
    from shared memory like any tile's metrics."""
    from .metrics import MetricsSchema

    counters: list[str] = []
    for name in cfg.asserted():
        counters += [
            f"{name}_burn_fast_x1000",
            f"{name}_burn_slow_x1000",
            f"{name}_breached",
        ]
    counters.append("slo_evaluations")
    counters.append("slo_breaches")
    return MetricsSchema(counters=tuple(counters))
