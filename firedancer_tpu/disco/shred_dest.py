"""Turbine destination computation: stake_ci + shred_dest.

Reference model: src/disco/shred/fd_stake_ci.c (stake-weighted contact
info, double-buffered across epoch boundaries) and fd_shred_dest.c
(per-shred stake-weighted shuffle of the cluster, tree fanout, and "who
are MY children / am I the root" queries).  Behavior re-derived from the
turbine design: the leader sends each shred to the shuffle's root; every
node forwards to up to `fanout` children in the shuffled order.

TPU-batch angle: destinations for a whole FEC set are computed in one
call — the per-shred weighted shuffles share the stake table and differ
only in their ChaCha20 seeds (seeded from the shred's merkle root / sig,
like the reference), so the host loop is over shreds with vectorized
numpy inside WSample.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from firedancer_tpu.ballet.chacha20 import MODE_SHIFT, ChaCha20Rng
from firedancer_tpu.ballet.wsample import WSample

#: turbine data-plane fanout (Solana DATA_PLANE_FANOUT)
FANOUT = 200


@dataclass
class ContactInfo:
    pubkey: bytes
    stake: int
    addr: tuple[str, int] = ("0.0.0.0", 0)


class StakeCI:
    """Stake-weighted contact info, double-buffered per epoch.

    The reference keeps two epochs live (fd_stake_ci.h) because shreds
    near an epoch boundary may belong to either; `for_slot` picks the
    epoch's table."""

    def __init__(self):
        self.epochs: dict[int, list[ContactInfo]] = {}

    def set_epoch(self, epoch: int, infos: list[ContactInfo]) -> None:
        # deterministic order: stake desc, pubkey desc (leaders.py rule)
        self.epochs[epoch] = sorted(
            infos, key=lambda c: (c.stake, c.pubkey), reverse=True
        )
        # keep at most the two most recent epochs
        for e in sorted(self.epochs)[:-2]:
            del self.epochs[e]

    def for_epoch(self, epoch: int) -> list[ContactInfo]:
        return self.epochs[epoch]


def _shred_seed(slot: int, shred_idx: int, shred_type: int,
                leader: bytes) -> bytes:
    """Per-shred shuffle seed (derived from slot/index/type/leader, the
    reference's seed inputs for the turbine shuffle)."""
    import hashlib

    return hashlib.sha256(
        slot.to_bytes(8, "little")
        + shred_idx.to_bytes(4, "little")
        + bytes([shred_type])
        + leader
    ).digest()


@dataclass
class ShredDest:
    """Turbine tree queries for one cluster snapshot."""

    infos: list[ContactInfo]
    fanout: int = FANOUT
    _excl_cache: dict = field(init=False, default_factory=dict)

    def _excluding(self, leader: bytes) -> tuple[list[int], list[int]]:
        """(weights, idx_map) with the leader removed — computed once per
        (cluster, leader) and shared by every shred's shuffle."""
        hit = self._excl_cache.get(leader)
        if hit is not None:
            return hit
        weights = []
        idx_map = []
        for i, c in enumerate(self.infos):
            if c.pubkey == leader:
                continue
            weights.append(max(c.stake, 1))
            idx_map.append(i)
        self._excl_cache[leader] = (weights, idx_map)
        return weights, idx_map

    def shuffle(self, slot: int, shred_idx: int, shred_type: int,
                leader: bytes) -> list[int]:
        """Stake-weighted shuffle of contact indices for one shred.
        The leader is EXCLUDED (it transmits, it never receives)."""
        rng = ChaCha20Rng(_shred_seed(slot, shred_idx, shred_type, leader),
                          MODE_SHIFT)
        weights, idx_map = self._excluding(leader)
        if not weights:
            return []
        ws = WSample(rng, weights, restore_enabled=False)
        return [idx_map[j] for j in ws.sample_and_remove_many(len(weights))]

    def children(self, order: list[int], me: bytes) -> tuple[list[int], bool]:
        """(my child indices in the tree, am-I-root).  Tree layout over
        the shuffled order: node at position p forwards to positions
        fanout*p+1 .. fanout*p+fanout (the standard turbine broadcast
        tree)."""
        pos = None
        for p, idx in enumerate(order):
            if self.infos[idx].pubkey == me:
                pos = p
                break
        if pos is None:
            return [], False
        lo = self.fanout * pos + 1
        hi = min(lo + self.fanout, len(order))
        return [order[p] for p in range(lo, hi)], pos == 0


def fec_set_destinations(
    sd: ShredDest, slot: int, leader: bytes, me: bytes,
    shred_idxs: list[int], shred_type: int = 0,
) -> list[tuple[list[int], bool]]:
    """Destinations for every shred of a FEC set in one call."""
    out = []
    for si in shred_idxs:
        order = sd.shuffle(slot, si, shred_type, leader)
        out.append(sd.children(order, me))
    return out
