"""Shared-memory metrics regions, one per tile.

Reference model: src/disco/metrics/ — an XML schema compiled to typed
per-tile offset tables, written lock-free by the owning tile via
FD_MCNT_INC / FD_MGAUGE_SET / FD_MHIST_COPY macros and scraped by a
monitor/metric tile reading the same shared memory.

Here the schema is a plain Python object (no codegen step needed — Python
IS the config language), but the storage contract is the same: a flat u64
array in a workspace, single-writer, torn-read-tolerant, readable by any
process mapping the workspace.  Histograms use the reference's shape: 16
power-of-two buckets (src/util/hist/fd_histf.h) plus sum and count words.

NATIVE MIRROR (ISSUE 15): tango/native/fdt_trace.c's
fdt_trace_hist_sample re-states hist_sample's exact bucketing (bucket
floor(log2(max(v,1))) clamped to nb-1; sum += max(v,0); count += 1) so
the in-burst stem writes qwait/svc/e2e samples into the SAME hist words
this module lays out (see hist_ref) — shared format, pinned
word-identical by tests/test_fdttrace_native.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

HIST_BUCKETS = 16
#: wide log2 hists: 24 buckets + the same sum/count tail.  Bucket 23
#: covers [2^23, 2^24) so a µs-domain wide hist represents ~16.8 s
#: before clamping; the TOP bucket is the explicit overflow bucket
#: (values beyond the domain land there and percentiles interpolate
#: inside it with the documented 2x-span bias).  Introduced for
#: `sched_lag_us` (disco/profile.py): the 16-bucket domain ends at
#: 2^16 µs = 65.5 ms, and the threaded-runtime baseline (PROFILE.md
#: round 8) PINS its p99 at that ceiling — both the pre-refactor
#: 100 ms-class lags and the post-refactor sub-ms lags must be
#: representable for the process-runtime A/B to mean anything.
WIDE_HIST_BUCKETS = 24
_HIST_WORDS = HIST_BUCKETS + 2  # buckets + sum + count

#: the per-device health/throughput row exported by device-pool tiles
#: (tiles/verify.py): queue depth, batches in flight, batches landed,
#: batches failed (errors + stalls), and a 0/1 degraded gauge
#: (quarantined / stalled / dead worker)
DEVICE_METRICS = ("depth", "inflight", "landed", "failed", "degraded")


def device_counters(
    n_devices: int, names: tuple[str, ...] = DEVICE_METRICS
) -> tuple[str, ...]:
    """Schema counters for an n-device pool: dev0_depth, dev0_inflight,
    ... dev{n-1}_degraded.  Kept here (not in the tile) so readers —
    app/monitor.py's health rows, tests — parse the same naming."""
    return tuple(
        f"dev{i}_{m}" for i in range(n_devices) for m in names
    )


def parse_device_counter(name: str) -> tuple[int, str] | None:
    """"dev3_landed" -> (3, "landed"); None for non-device counters."""
    if not name.startswith("dev"):
        return None
    head, _, metric = name.partition("_")
    if not metric or metric not in DEVICE_METRICS:
        return None
    try:
        return int(head[3:]), metric
    except ValueError:
        return None


def device_rows(counters: dict) -> dict[int, dict]:
    """Group a tile's counter snapshot into per-device health rows:
    {dev_idx: {metric: value}} for every dev{i}_* counter present."""
    out: dict[int, dict] = {}
    for name, v in counters.items():
        parsed = parse_device_counter(name)
        if parsed is not None:
            idx, metric = parsed
            out.setdefault(idx, {})[metric] = v
    return out


@dataclass(frozen=True)
class MetricsSchema:
    """Ordered metric names for one tile kind.

    counters: monotone u64 counts (also used for gauges via set()).
    hists: 16-bucket log2 histograms with sum/count.
    wide_hists: names (a subset of hists) stored with WIDE_HIST_BUCKETS
    buckets instead — a wider domain plus an explicit overflow bucket,
    for distributions (scheduler lag) whose tail outruns 2^16.  Layout-
    affecting: every reader of a region must use the SAME schema
    including this field (it rides the topology manifest).
    """

    counters: tuple[str, ...] = ()
    hists: tuple[str, ...] = ()
    wide_hists: tuple[str, ...] = ()

    def hist_buckets(self, name: str) -> int:
        return WIDE_HIST_BUCKETS if name in self.wide_hists else HIST_BUCKETS

    # every tile gets these on top of its own schema
    BASE_COUNTERS = (
        "in_frags",
        "in_bytes",
        "out_frags",
        "out_bytes",
        "overrun_frags",
        "backpressure_iters",
        "housekeep_iters",
        "loop_iters",
        # frags consumed through the native stem's GIL-released burst
        # loop (tango/native/fdt_stem.c) — always a subset of in_frags,
        # so stem_frags/in_frags is the native-coverage ratio a monitor
        # or bench can read straight off the tile
        "stem_frags",
        # 1 when this incarnation's run loop engaged a native stem (the
        # tile registered a handler under stem="native"), written at
        # boot by the tile itself.  Monitors key stem-coverage rows and
        # the pinned-to-Python alarm off it: a stem-CONFIGURED tile
        # whose py_frags advance while stem_frags sit flat has silently
        # lost native coverage (amnesty/fault pins), which was
        # previously invisible from outside.
        "stem_engaged",
        # the Python-side complements (ISSUE 11 zero-Python steady-state
        # contract): frags the Python on_frags callback handled, and
        # Python after_credit invocations.  A fully native data-plane
        # tile shows both FLAT across a measured window while
        # stem_frags/microblocks advance.
        "py_frags",
        "py_credit",
        # supervision counters, written by disco/supervisor.py (distinct
        # slots from the tile's own, so the single-writer-per-word
        # discipline holds): crash/stall restarts, heartbeat deadline
        # misses, and the circuit-breaker latch (1 = tile degraded,
        # supervisor gave up restarting)
        "restarts",
        "hb_misses",
        "degraded",
    )
    #: loop phase durations are sampled every 16th iteration (reference:
    #: fd_mux.c histograms every loop phase via tickcount)
    BASE_HISTS = ("batch_sz", "loop_ns", "hk_ns", "frag_ns", "credit_ns")

    def with_base(self) -> "MetricsSchema":
        return MetricsSchema(
            counters=MetricsSchema.BASE_COUNTERS + tuple(self.counters),
            hists=MetricsSchema.BASE_HISTS + tuple(self.hists),
            wide_hists=tuple(self.wide_hists),
        )

    def footprint_words(self) -> int:
        return len(self.counters) + sum(
            self.hist_buckets(h) + 2 for h in self.hists
        )


@dataclass
class _Hist:
    base: int
    nb: int = HIST_BUCKETS


class Metrics:
    """A tile's metrics region: a u64 view into a workspace allocation."""

    def __init__(self, mem_u8: np.ndarray, schema: MetricsSchema):
        self.schema = schema
        n = schema.footprint_words()
        self.words = mem_u8[: n * 8].view(np.uint64)
        self._slot: dict[str, int] = {}
        off = 0
        for c in schema.counters:
            self._slot[c] = off
            off += 1
        self._hist: dict[str, _Hist] = {}
        for h in schema.hists:
            nb = schema.hist_buckets(h)
            self._hist[h] = _Hist(off, nb)
            off += nb + 2

    @staticmethod
    def footprint(schema: MetricsSchema) -> int:
        return schema.footprint_words() * 8

    # -- writer side (owning tile only) ----------------------------------

    def inc(self, name: str, v: int = 1) -> None:
        self.words[self._slot[name]] += np.uint64(v)

    def set(self, name: str, v: int) -> None:
        self.words[self._slot[name]] = np.uint64(v)

    def hist_sample(self, name: str, value: int) -> None:
        h = self._hist[name]
        b = min(max(int(value), 1).bit_length() - 1, h.nb - 1)
        w = self.words
        w[h.base + b] += np.uint64(1)
        w[h.base + h.nb] += np.uint64(max(int(value), 0))
        w[h.base + h.nb + 1] += np.uint64(1)

    def hist_sample_many(self, name: str, values: np.ndarray) -> None:
        h = self._hist[name]
        raw = np.asarray(values, dtype=np.int64)
        # bucketing floors at 1; the sum clamps negatives to 0, matching
        # hist_sample's max(value, 0) — NOT the raw values
        v = np.maximum(raw, 1)
        buckets = np.minimum(
            np.floor(np.log2(v)).astype(np.int64), h.nb - 1
        )
        counts = np.bincount(buckets, minlength=h.nb).astype(np.uint64)
        w = self.words
        w[h.base : h.base + h.nb] += counts
        w[h.base + h.nb] += np.uint64(int(np.maximum(raw, 0).sum()))
        w[h.base + h.nb + 1] += np.uint64(len(raw))

    def hist_ref(self, name: str) -> tuple[int, int]:
        """(address of the hist's first bucket word, bucket count) — the
        native in-burst trace emitter (tango/native/fdt_trace.c) updates
        the hist in place with hist_sample's exact bucketing, so native
        and Python samples land in ONE storage with one estimator."""
        h = self._hist[name]
        return int(self.words.ctypes.data) + h.base * 8, h.nb

    # -- reader side (any process) ---------------------------------------

    def counter(self, name: str) -> int:
        return int(self.words[self._slot[name]])

    def hist(self, name: str) -> dict:
        h = self._hist[name]
        w = self.words
        return {
            "buckets": w[h.base : h.base + h.nb].tolist(),
            "sum": int(w[h.base + h.nb]),
            "count": int(w[h.base + h.nb + 1]),
        }

    def read(self) -> dict:
        out = {c: self.counter(c) for c in self.schema.counters}
        out.update({h: self.hist(h) for h in self.schema.hists})
        return out


# ---------------------------------------------------------------------------
# percentile estimation over the 16-bucket log2 histograms
#
# Bucket b holds samples v with floor(log2(max(v, 1))) == b, i.e. bucket 0
# covers [0, 2) and bucket b covers [2^b, 2^(b+1)), with the top bucket
# clamped open-ended.  A percentile is estimated by walking the cumulative
# counts to the containing bucket and interpolating linearly inside it —
# the error is bounded by the bucket's 2x span, which is the resolution
# the storage format buys (the reference converts the same fd_histf
# buckets to approximate percentiles in fd_top).


def hist_percentile(h: dict, q: float) -> float:
    """Estimate the q-th percentile (q in [0, 100]) of a Metrics.hist()
    snapshot by log-bucket linear interpolation.  0.0 on an empty hist.

    Boundary contract (pinned in tests/test_fdttrace.py):
      * empty hist / count <= 0 / no occupied bucket -> 0.0;
      * q is clamped into [0, 100]; q=0 returns the lower edge of the
        first occupied bucket (the min estimate), q=100 the upper edge
        of the last occupied one (the max estimate);
      * all mass in the overflow bucket interpolates inside
        [2^(nb-1), 2^nb] for the hist's own bucket count nb (16, or
        WIDE_HIST_BUCKETS for wide hists — the estimator works off
        len(buckets), so both widths share this code) — a finite
        estimate with the documented 2x-span bias for values beyond
        the top bucket;
      * torn snapshots (the regions are read lock-free, and windowed
        deltas of torn reads can even go negative per bucket) never
        push the walk past the occupied mass: negative bucket counts
        are treated as empty and the rank is clamped to the occupied
        total, so the estimate stays inside the last occupied bucket
        instead of jumping to the 2^HIST_BUCKETS sentinel."""
    buckets = h.get("buckets") or []
    count = h.get("count", 0)
    if count <= 0:
        return 0.0
    occupied = [(b, n) for b, n in enumerate(buckets) if n > 0]
    if not occupied:
        # count incremented before its bucket landed (torn read)
        return 0.0
    mass = sum(n for _, n in occupied)
    rank = (min(max(q, 0.0), 100.0) / 100.0) * min(count, mass)
    cum = 0
    for b, n in occupied:
        if cum + n >= rank:
            lo = 0.0 if b == 0 else float(1 << b)
            # the top bucket is open-ended; assume the same 2x
            # geometric span as the others (documented estimator bias
            # for distributions with mass beyond 2^HIST_BUCKETS)
            hi = float(1 << (b + 1))
            return lo + (hi - lo) * (max(rank - cum, 0.0) / n)
        cum += n
    # unreachable while rank <= mass; keep the clamp for safety
    b, n = occupied[-1]
    return float(1 << (b + 1))


def merge_hists(hs: list[dict]) -> dict:
    """Sum Metrics.hist() snapshots bucket-wise (counts, sums, and a
    buckets vector as long as the longest input) — the primitive behind
    cross-tile SLO windows (disco/slo.py) and profile aggregation
    (disco/profile.py)."""
    out = {"count": 0, "sum": 0, "buckets": []}
    for h in hs:
        out["count"] += h.get("count", 0)
        out["sum"] += h.get("sum", 0)
        bk = h.get("buckets", [])
        if len(bk) > len(out["buckets"]):
            out["buckets"] += [0] * (len(bk) - len(out["buckets"]))
        for i, n in enumerate(bk):
            out["buckets"][i] += n
    return out


def hist_delta(cur: dict, prev: dict | None) -> dict:
    """Windowed hist: cur - prev per bucket (both cumulative monotone
    snapshots of the same region).  No/empty prev -> cur unchanged
    (cumulative view).  Buckets are padded to the longer vector so a
    schema-extended snapshot diffs cleanly against an older one."""
    if not prev or not prev.get("count"):
        return cur
    cb, pb = cur.get("buckets", []), prev.get("buckets", [])
    n = max(len(cb), len(pb))
    return {
        "count": cur.get("count", 0) - prev.get("count", 0),
        "sum": cur.get("sum", 0) - prev.get("sum", 0),
        "buckets": [
            (cb[i] if i < len(cb) else 0) - (pb[i] if i < len(pb) else 0)
            for i in range(n)
        ],
    }


def hist_frac_above(h: dict, x: float) -> float:
    """Estimated fraction of a Metrics.hist() snapshot's samples that
    exceed `x`, by the same log-bucket linear interpolation as
    hist_percentile (and the same torn-read tolerance).  This is the
    SLO engine's primitive: for a latency SLO "p99 <= X", the bad
    fraction of a window is hist_frac_above(window_delta, X)."""
    buckets = h.get("buckets") or []
    mass = sum(n for n in buckets if n > 0)
    if mass <= 0:
        return 0.0
    above = 0.0
    for b, n in enumerate(buckets):
        if n <= 0:
            continue
        lo = 0.0 if b == 0 else float(1 << b)
        hi = float(1 << (b + 1))
        if x < lo:
            above += n
        elif x < hi:
            above += n * ((hi - x) / (hi - lo))
    return min(above / mass, 1.0)
