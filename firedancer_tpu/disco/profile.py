"""Per-tile run-loop profiler: sampled wall/CPU attribution + GIL-wait.

ROADMAP item 1 (the multi-process tile runtime) needs a QUANTIFIED
baseline for what the 17-threads-one-GIL runtime actually costs each
tile — the continuous-profiling posture of Google-Wide Profiling (Ren
et al., IEEE Micro 2010) applied to the mux loop.  The run loop
(disco/mux.py) already histogram-samples phase WALL durations 1-in-16;
this layer adds, on the same sampled iterations, the thread-CPU clock
(time.thread_time_ns) so wall - cpu decomposes into

    gil_wait = wall - cpu - voluntary_sleep

per phase: the time this tile's thread spent runnable but not running —
GIL contention plus OS scheduling — which is exactly the quantity the
process-per-tile refactor should drive to ~zero.  A scheduler-lag
histogram (actual minus intended housekeeping firing time) captures the
same contention from the other side: how late the loop's time-based
cadence fires under interpreter load.

Storage: one Metrics region per tile (PROFILE_SCHEMA) in the topology
workspace ("profile_{tile}" alloc) — u64 accumulators + one log2 hist,
single-writer (the tile's loop thread), torn-read tolerant, mappable by
monitors and by the bench.  Because the state lives in workspace native
buffers, the whole layer survives the item-1 process-runtime refactor
unchanged.

Cost when off: ctx.profiler is None and every hook in the loop is one
attribute check.  Cost when on: two thread_time_ns reads + a few u64
adds per SAMPLED iteration (1-in-16) plus two clock reads around idle
sleeps (which are idle by definition).
"""

from __future__ import annotations

from .metrics import Metrics, MetricsSchema, hist_percentile, merge_hists

#: loop phases the profiler attributes (wall + cpu per phase)
PHASES = ("frag", "hk", "credit")

PROFILE_SCHEMA = MetricsSchema(
    counters=(
        # whole sampled iterations
        "iter_wall_ns",
        "iter_cpu_ns",
        "iter_sleep_ns",
        "iter_samples",
        # per-phase attribution (sampled iterations only)
        "frag_wall_ns",
        "frag_cpu_ns",
        "hk_wall_ns",
        "hk_cpu_ns",
        "credit_wall_ns",
        "credit_cpu_ns",
        # backpressured sampled iterations (zero-credit stalls)
        "bp_wall_ns",
        "bp_samples",
        # every voluntary sleep (not just sampled): actual time slept
        "sleep_ns",
        "sleep_req_ns",
        "sleeps",
    ),
    hists=(
        #: actual - intended housekeeping firing time, µs: the loop's
        #: time-based cadence lag under GIL/scheduler contention
        "sched_lag_us",
    ),
    # sched-lag is a WIDE hist (metrics.WIDE_HIST_BUCKETS): the
    # 16-bucket domain ends at 2^16 µs and the threaded baseline pins
    # its p99 exactly there (PROFILE.md round 8 caveat) — the
    # process-runtime A/B needs the 100 ms-class "before" AND the
    # sub-ms "after" to be representable in the same storage format,
    # with the top bucket as the explicit overflow bucket.
    wide_hists=("sched_lag_us",),
)


class TileProfiler:
    """Writer facade held on MuxCtx.profiler (tile loop thread only)."""

    __slots__ = ("m",)

    def __init__(self, metrics: Metrics):
        self.m = metrics

    # -- writer side (loop thread) ---------------------------------------

    def add_iter(self, wall_ns: int, cpu_ns: int, sleep_ns: int = 0) -> None:
        m = self.m
        m.inc("iter_wall_ns", max(wall_ns, 0))
        m.inc("iter_cpu_ns", max(cpu_ns, 0))
        if sleep_ns:
            m.inc("iter_sleep_ns", max(sleep_ns, 0))
        m.inc("iter_samples")

    def add_phase(self, phase: str, wall_ns: int, cpu_ns: int) -> None:
        m = self.m
        m.inc(f"{phase}_wall_ns", max(wall_ns, 0))
        m.inc(f"{phase}_cpu_ns", max(cpu_ns, 0))

    def add_bp(self, wall_ns: int) -> None:
        m = self.m
        m.inc("bp_wall_ns", max(wall_ns, 0))
        m.inc("bp_samples")

    def add_sleep(self, actual_ns: int, requested_ns: int) -> None:
        m = self.m
        m.inc("sleep_ns", max(actual_ns, 0))
        m.inc("sleep_req_ns", max(requested_ns, 0))
        m.inc("sleeps")

    def sched_lag(self, lag_ns: int) -> None:
        self.m.hist_sample("sched_lag_us", max(lag_ns, 0) // 1000)


# ---------------------------------------------------------------------------
# readers


def profile_row(m: Metrics) -> dict:
    """One tile's profile summary from its (possibly live) region.

    gil_wait_frac = (wall - cpu - sleep) / (wall - sleep) over the
    sampled iterations: the fraction of the tile's NON-SLEEPING loop
    time spent waiting for the interpreter/core rather than executing.
    Phase fractions are of sampled non-sleep wall time."""
    c = {k: m.counter(k) for k in PROFILE_SCHEMA.counters}
    busy = max(c["iter_wall_ns"] - c["iter_sleep_ns"], 0)
    wait = max(busy - c["iter_cpu_ns"], 0)
    lag = m.hist(
        "sched_lag_us"
    ) if "sched_lag_us" in m.schema.hists else {"count": 0}
    row = {
        "samples": c["iter_samples"],
        "gil_wait_frac": round(wait / busy, 4) if busy else 0.0,
        "busy_wall_ns": busy,
        "cpu_ns": c["iter_cpu_ns"],
        "sleep_ns": c["sleep_ns"],
        #: oversleep: how much longer voluntary sleeps ran than asked —
        #: the scheduler's contribution seen from the sleep side
        "oversleep_ns": max(c["sleep_ns"] - c["sleep_req_ns"], 0),
        "sched_lag_p50_us": round(hist_percentile(lag, 50), 1),
        "sched_lag_p99_us": round(hist_percentile(lag, 99), 1),
        "sched_lag_n": lag.get("count", 0),
        #: share of sampled non-sleep time spent in zero-credit
        #: (backpressured) iterations — stalled behind a slow consumer
        "bp_frac": (
            round(min(c["bp_wall_ns"] / busy, 1.0), 4) if busy else 0.0
        ),
    }
    for ph in PHASES:
        row[f"{ph}_frac"] = (
            round(c[f"{ph}_wall_ns"] / busy, 4) if busy else 0.0
        )
        pw = c[f"{ph}_wall_ns"]
        row[f"{ph}_gil_wait_frac"] = (
            round(max(pw - c[f"{ph}_cpu_ns"], 0) / pw, 4) if pw else 0.0
        )
    return row


def aggregate(profiles: dict[str, Metrics]) -> dict:
    """Topology-level summary for bench JSON: gil_wait_frac weighted by
    each tile's busy wall time, and the merged sched-lag p99."""
    busy_total = 0
    wait_total = 0
    lags = []
    rows = {}
    for name, m in profiles.items():
        row = profile_row(m)
        rows[name] = row
        busy_total += row["busy_wall_ns"]
        wait_total += int(row["gil_wait_frac"] * row["busy_wall_ns"])
        if "sched_lag_us" in m.schema.hists:
            lags.append(m.hist("sched_lag_us"))
    merged = merge_hists(lags)
    return {
        "gil_wait_frac": (
            round(wait_total / busy_total, 4) if busy_total else 0.0
        ),
        "sched_lag_p99_us": round(hist_percentile(merged, 99), 1),
        "sched_lag_n": merged.get("count", 0),
        "tiles": rows,
    }


def render_rows(profiles: dict[str, Metrics]) -> str:
    """Human table (PROFILE.md / monitor footer)."""
    lines = [
        f"{'tile':>10} {'gil_wait':>9} {'frag':>6} {'hk':>6} "
        f"{'credit':>7} {'bp':>6} {'lag p50/p99 us':>16} {'samples':>8}"
    ]
    for name in sorted(profiles):
        r = profile_row(profiles[name])
        lines.append(
            f"{name:>10} {r['gil_wait_frac'] * 100:8.1f}% "
            f"{r['frag_frac'] * 100:5.1f}% {r['hk_frac'] * 100:5.1f}% "
            f"{r['credit_frac'] * 100:6.1f}% {r['bp_frac'] * 100:5.1f}% "
            f"{r['sched_lag_p50_us']:,.0f}/{r['sched_lag_p99_us']:,.0f}"
            f"{'':>4} {r['samples']:8,}"
        )
    return "\n".join(lines)
