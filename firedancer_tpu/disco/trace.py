"""fdttrace: per-tile span-event rings for end-to-end frag tracing.

Reference model: the reference carries compressed publish/origin
timestamps in every frag (fd_frag_meta_ts_comp, fd_tango_base.h) and
histogram-samples every mux phase (fd_mux.c:435-444), but never keeps a
per-frag record.  This build adds one: each tile owns a SPAN RING — a
flat u64 region in the workspace with the same storage contract as the
metrics regions (disco/metrics.py): single writer (the tile's mux
thread), lock-free, torn-read-tolerant, readable by any process that
maps the workspace.  The run loop (disco/mux.py) writes span events at
its fixed points (frag ingest, publish, housekeeping, backpressure) and
the verify device pool adds its own (enqueue, dispatch, land, fallback,
quarantine); `scripts/fdttrace.py` drains the rings and assembles
per-frag timelines keyed by (link, seq, sig).

Sampling: 1-in-N by the frag's sig field.  The sig is the dedup tag and
is CARRIED across hops (quic stamps it, verify/dedup forward it), so
`sig % N == 0` selects the SAME frags at every hop — which is what makes
cross-tile timelines assemblable.  N=1 traces everything (tests); large
N keeps the hot path allocation-light; tracing off (no Tracer installed)
costs one `is not None` check per loop phase.

Event layout (4 u64 words, little-endian):
    w0 = kind(u8) << 56 | link(u8) << 48 | aux16(u16) << 32 | ts(u32)
    w1 = seq   (ring seq for frag events; pool seq for device events)
    w2 = sig   (the frag's dedup tag; 0 for tile-scoped events)
    w3 = aux64 (INGEST: tsorig << 32 | tspub; PUBLISH: tsorig;
                others: event-specific payload, e.g. a duration)

ts is the same compressed µs-mod-2^32 domain as the frag meta's
tsorig/tspub (disco.mux.now_ts) — all arithmetic on it must go through
the wrap-safe ts_diff helpers in disco/mux.py.

NATIVE MIRROR (ISSUE 15): tango/native/fdt_trace.c re-states this
module's storage format in C — the event word packing, the ring
header's reserve-before-store / commit-after-store cursor discipline,
and the 1-in-N sig sampling — so the native stem emits span records a
Python reader drains indistinguishably from Tracer's.  The layout
constants below (_HDR_WORDS, EVENT_WORDS, header word meanings, INGEST/
PUBLISH kinds) are therefore SHARED FORMAT: changing any of them means
changing fdt_trace.c in the same commit, and the differential tests in
tests/test_fdttrace_native.py pin the two byte-identical.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

# -- span kinds -------------------------------------------------------------

INGEST = 1      # frags consumed from an in-link (one event per sampled frag)
PUBLISH = 2     # frags published to an out-link (one event per sampled frag)
HK = 3          # housekeeping fired (aux64 = duration ns)
BP = 4          # backpressure streak began (zero credits across outs)
ENQUEUE = 5     # verify pool: batch accepted (seq = pool_seq, aux16 = lanes)
DISPATCH = 6    # verify pool: device dispatch began (aux16 = device idx)
LAND = 7        # verify pool: batch landed (aux16 = device idx)
FALLBACK = 8    # verify pool: batches served by the strict host path
QUARANTINE = 9  # verify pool: a device domain degraded (aux16 = device idx)
FAULT = 10      # faultinj / supervisor annotation (aux16 = FAULT_CODES)

KIND_NAMES = {
    INGEST: "ingest", PUBLISH: "publish", HK: "hk", BP: "bp",
    ENQUEUE: "enqueue", DISPATCH: "dispatch", LAND: "land",
    FALLBACK: "fallback", QUARANTINE: "quarantine", FAULT: "fault",
}

#: aux16 codes for FAULT events — injected faults (disco/faultinj.py)
#: and supervisor restarts annotate the trace so kill -> restart gaps
#: are visible (and assertable) in the assembled timeline
FAULT_CODES = {
    "kill": 1, "stall": 2, "backpressure": 3, "drop": 4, "corrupt": 5,
    "device_error": 6, "restart": 7, "flood": 8, "conn_churn": 9,
}
FAULT_NAMES = {v: k for k, v in FAULT_CODES.items()}


@dataclass(frozen=True)
class TraceConfig:
    """Topology-level tracing knobs (disco.topo.Topology.enable_trace).

    sample: 1-in-N frag sampling by sig (1 = every frag; 0 disables —
    no tracer is installed and the hot path pays nothing).
    depth: span events retained per tile before the writer laps the
    reader (the reader detects and reports the dropped count)."""

    sample: int = 64
    depth: int = 1 << 14


_HDR_WORDS = 8
EVENT_WORDS = 4


class SpanRing:
    """Lock-free single-writer span-event ring in a u64 workspace region.

    Header: word0 = committed cursor (total events ever written,
    monotone), word1 = depth, word2 = sample (reader metadata),
    word3 = reserve cursor.  Events live at slot (i % depth).  The
    writer bumps the RESERVE cursor first, stores the event words,
    then advances the committed cursor — so a reader can bound every
    slot the writer may currently be storing into (ordering is
    best-effort from Python/numpy, exactly the metrics regions'
    torn-read tolerance): `read` copies [since, committed), then
    re-checks the reserve cursor and discards anything a concurrent
    write_block could have been overwriting during the copy, so no
    torn entry is returned as data (it is counted dropped instead)."""

    def __init__(self, mem_u8: np.ndarray, depth: int = 0, sample: int = 0,
                 join: bool = False):
        self.words = mem_u8[: (len(mem_u8) // 8) * 8].view(np.uint64)
        if join:
            self.depth = int(self.words[1])
            self.sample = int(self.words[2])
        else:
            assert depth > 0 and depth & (depth - 1) == 0, (
                f"span ring depth {depth} must be a power of two"
            )
            self.depth = depth
            self.sample = sample
            self.words[0] = 0
            self.words[1] = depth
            self.words[2] = sample
            self.words[3] = 0
        self.ev = self.words[
            _HDR_WORDS : _HDR_WORDS + self.depth * EVENT_WORDS
        ].reshape(self.depth, EVENT_WORDS)

    @staticmethod
    def footprint(depth: int) -> int:
        return (_HDR_WORDS + depth * EVENT_WORDS) * 8

    # -- writer side (owning tile's mux thread only) ----------------------

    def write_block(self, rows: np.ndarray) -> None:
        """Append a (k, 4) u64 block of events.  A block larger than the
        ring keeps its tail, but the cursor still advances by the full
        block so the reader's lap accounting stays truthful."""
        k = len(rows)
        if k == 0:
            return
        w = int(self.words[0])
        # reserve before storing: a concurrent reader bounds the slots
        # this store may be scribbling over by re-checking word3
        self.words[3] = np.uint64(w + k)
        kept = rows[-self.depth :]
        idx = (w + (k - len(kept)) + np.arange(len(kept))) % self.depth
        self.ev[idx] = kept
        self.words[0] = np.uint64(w + k)

    # -- reader side (any process) ----------------------------------------

    def cursor(self) -> int:
        return int(self.words[0])

    def read(self, since: int = 0) -> tuple[np.ndarray, int, int]:
        """Events [since, cursor) that are still live.  Returns
        (events (k,4) u64 copy, new_since, dropped) where dropped counts
        entries lost to writer laps — including any a write_block COULD
        have been overwriting while we copied (the reserve cursor is
        bumped before the stores, so re-checking it after the copy
        bounds the in-progress write too), so no torn entry is ever
        returned as data."""
        c = int(self.words[0])
        lo = max(since, c - self.depth)
        if lo >= c:
            return np.zeros((0, EVENT_WORDS), np.uint64), c, lo - since
        idx = (lo + np.arange(c - lo)) % self.depth
        out = self.ev[idx].copy()
        r2 = int(self.words[3])  # writer reservations during the copy
        # clamp to c: a writer that laps the WHOLE window mid-copy can
        # push r2 - depth beyond the committed cursor we are reporting —
        # without the clamp the dropped count would cover events beyond
        # [since, c), and the next read (starting at c) would count
        # those same losses AGAIN, double-reporting drops
        safe_lo = min(max(lo, r2 - self.depth), c)
        if safe_lo > lo:
            out = out[safe_lo - lo :]
        return out, c, safe_lo - since


def decode(events: np.ndarray) -> list[dict]:
    """(k, 4) u64 event block -> list of field dicts."""
    out = []
    for w0, w1, w2, w3 in events.tolist():
        out.append(
            {
                "kind": (w0 >> 56) & 0xFF,
                "link": (w0 >> 48) & 0xFF,
                "aux16": (w0 >> 32) & 0xFFFF,
                "ts": w0 & 0xFFFFFFFF,
                "seq": w1,
                "sig": w2,
                "aux64": w3,
            }
        )
    return out


def _pack_w0(kind: int, link: int, aux16, ts) -> np.ndarray:
    return (
        (np.uint64(kind & 0xFF) << np.uint64(56))
        | (np.uint64(link & 0xFF) << np.uint64(48))
        | (np.asarray(aux16, np.uint64) << np.uint64(32))
        | np.asarray(ts, np.uint64)
    )


class Tracer:
    """A tile's span-event writer facade.

    Installed on MuxCtx.tracer by the topology when tracing is enabled;
    every write runs on the tile's mux thread (or, for the supervisor's
    restart annotation, strictly after that thread has been joined), so
    the ring's single-writer contract holds."""

    def __init__(self, ring: SpanRing, sample: int, name: str = ""):
        self.ring = ring
        self.sample = max(int(sample), 1)
        self.name = name

    def _mask(self, sigs: np.ndarray) -> np.ndarray:
        if self.sample == 1:
            return slice(None)
        return sigs % np.uint64(self.sample) == 0

    def ingest(self, link: int, frags: np.ndarray, ts: int) -> None:
        """One INGEST per sampled frag of a drained batch.  aux64 packs
        the frag's own tsorig/tspub so the assembler can attribute
        queue-wait (ts - tspub) and end-to-end (ts - tsorig) offline."""
        sel = frags[self._mask(frags["sig"])]
        n = len(sel)
        if n == 0:
            return
        rows = np.empty((n, EVENT_WORDS), np.uint64)
        rows[:, 0] = _pack_w0(INGEST, link, 0, ts)
        rows[:, 1] = sel["seq"]
        rows[:, 2] = sel["sig"]
        rows[:, 3] = (sel["tsorig"].astype(np.uint64) << np.uint64(32)) | (
            sel["tspub"].astype(np.uint64)
        )
        self.ring.write_block(rows)

    def publish(
        self,
        link: int,
        seq0: int,
        sigs: np.ndarray,
        tspub: int,
        tsorigs: np.ndarray | None,
    ) -> None:
        """One PUBLISH per sampled frag of a published batch."""
        sigs = np.asarray(sigs, np.uint64)
        mask = self._mask(sigs)
        seqs = (np.uint64(seq0) + np.arange(len(sigs), dtype=np.uint64))[mask]
        sel = sigs[mask]
        n = len(sel)
        if n == 0:
            return
        rows = np.empty((n, EVENT_WORDS), np.uint64)
        rows[:, 0] = _pack_w0(PUBLISH, link, 0, tspub & 0xFFFFFFFF)
        rows[:, 1] = seqs
        rows[:, 2] = sel
        if tsorigs is None:
            rows[:, 3] = np.uint64(tspub & 0xFFFFFFFF)
        else:
            rows[:, 3] = np.asarray(tsorigs, np.uint64)[mask]
        self.ring.write_block(rows)

    def point(
        self,
        kind: int,
        *,
        link: int = 0,
        ts: int | None = None,
        seq: int = 0,
        sig: int = 0,
        aux16: int = 0,
        aux64: int = 0,
    ) -> None:
        """One tile-scoped event (HK/BP/pool/fault annotations)."""
        if ts is None:
            from .mux import now_ts

            ts = now_ts()
        row = np.empty((1, EVENT_WORDS), np.uint64)
        row[0, 0] = _pack_w0(kind, link, aux16 & 0xFFFF, ts & 0xFFFFFFFF)
        row[0, 1] = seq & (2**64 - 1)
        row[0, 2] = sig & (2**64 - 1)
        row[0, 3] = aux64 & (2**64 - 1)
        self.ring.write_block(row)

    def fault(self, code: str, *, seq: int = 0, aux64: int = 0) -> None:
        """Annotate an injected fault / supervisor restart into the
        trace (FAULT_CODES[code] rides aux16)."""
        self.point(FAULT, seq=seq, aux16=FAULT_CODES.get(code, 0),
                   aux64=aux64)
