"""The generic tile run loop — the TPU-native analog of fd_mux_tile.

Reference model: src/disco/mux/fd_mux.c:90-707 — a loop interleaving
housekeeping events (heartbeat, flow-control publish/receive, metrics
flush, command-and-control), credit checks against the slowest reliable
consumer, and frag polling with overrun detection, invoking a tile's
callback vtable (fd_mux.h:115-260).

Deliberate re-design for this build: callbacks are batch-first.  One loop
iteration drains up to `credits` frags per in-link in ONE native call and
hands the whole array to the tile, which processes it with numpy/native
code or ships it to the TPU.  The Python interpreter executes O(1) work
per batch, not per frag — that is what makes a Python-hosted control loop
viable at millions of frags/s.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

import numpy as np

from firedancer_tpu.tango import rings as R

from .metrics import Metrics, MetricsSchema
from .trace import BP as _SPAN_BP
from .trace import HK as _SPAN_HK


class TileInterrupted(RuntimeError):
    """Raised inside a tile loop when the supervisor abandons this
    incarnation (stall recovery): the thread unwinds through the normal
    failure path (CNC_FAIL + fseq finalize) so a fresh incarnation can
    rejoin the rings safely."""


def now_ts() -> int:
    """Frag timestamp: microseconds, truncated to the meta's u32 field
    (wraps every ~71 min; latency deltas use modular arithmetic like the
    reference's compressed tspub, fd_frag_meta_ts_comp)."""
    return (time.monotonic_ns() // 1000) & 0xFFFFFFFF


# -- wrap-safe compressed-timestamp arithmetic ------------------------------
#
# now_ts() values live on a u32 ring (2^32 µs ~ 71 min); a plain Python
# subtraction goes negative-garbage the first time the ring wraps mid-run.
# Every latency delta on frag timestamps must go through these helpers —
# the u32 analog of tango.rings.seq_diff (the PR 3 discipline), matching
# the reference's compressed-timestamp decompression (fd_frag_meta_ts_comp
# sign-extends the low bits against a reference clock, fd_tango_base.h).

_TS_MASK = 0xFFFFFFFF
_TS_HALF = 1 << 31


def ts_diff(a: int, b: int) -> int:
    """Signed µs distance a - b mod 2^32 (positive: a is after b).
    Valid while |true distance| < ~35.8 min (2^31 µs)."""
    d = (int(a) - int(b)) & _TS_MASK
    return d - (1 << 32) if d >= _TS_HALF else d


def ts_diff_arr(a, b) -> np.ndarray:
    """Vector ts_diff: i64 signed distances for u32 timestamp arrays."""
    with np.errstate(over="ignore"):
        d = np.asarray(a, np.uint32) - np.asarray(b, np.uint32)
    return d.astype(np.int64) - (
        (d >= np.uint32(_TS_HALF)).astype(np.int64) << 32
    )


#: per-in-link latency attribution hists, appended to every tile's
#: schema by the topology at build time (disco/topo.py): queue-wait =
#: consume-ts - upstream tspub, service = post-callback ts - consume-ts,
#: end-to-end = consume-ts - origin tsorig.  All in the compressed-µs
#: domain, all wrap-safe via ts_diff.
LINK_HIST_KINDS = ("qwait_us", "svc_us", "e2e_us")


def link_hist_names(link: str) -> tuple[str, ...]:
    return tuple(f"{k}_{link}" for k in LINK_HIST_KINDS)


@dataclass
class InLink:
    """This tile's consumer endpoint of one link."""

    name: str
    mcache: R.MCache
    dcache: R.DCache | None
    fseq: R.FSeq  # this consumer's progress backchannel
    reliable: bool = True
    seq: int = 0
    #: observability wiring (set by the topology at build time): the
    #: link's small-int id for span events, and this endpoint's per-link
    #: latency hist names — None when the ctx's metrics schema lacks
    #: them (hand-built tiles in unit tests), which disables recording
    link_id: int = 0
    h_qwait: str | None = None
    h_svc: str | None = None
    h_e2e: str | None = None

    def gather(self, frags: np.ndarray, width: int | None = None) -> np.ndarray:
        """Dense (n, width) u8 payload matrix for a drained frag batch."""
        assert self.dcache is not None
        w = width if width is not None else self.dcache.mtu
        return self.dcache.read_batch(frags["chunk"], frags["sz"], w)


@dataclass
class OutLink:
    """This tile's producer endpoint of one link (single producer)."""

    name: str
    mcache: R.MCache
    dcache: R.DCache | None
    consumer_fseqs: list[R.FSeq] = field(default_factory=list)  # reliable only
    seq: int = 0
    #: span-event wiring (topology build time); tracer None = tracing off
    link_id: int = 0
    tracer: object | None = None

    @property
    def depth(self) -> int:
        return self.mcache.depth

    def cr_avail(self) -> int:
        """Publishes safe without overrunning any reliable consumer
        (reference credit model: src/tango/fctl/fd_fctl.h)."""
        if not self.consumer_fseqs:
            return self.depth
        lo = min(f.query() for f in self.consumer_fseqs)
        return R.cr_avail(self.seq, lo, self.depth)

    def publish(
        self,
        sigs: np.ndarray,
        rows: np.ndarray | None = None,
        szs: np.ndarray | None = None,
        ctls: np.ndarray | None = None,
        tspub: int = 0,
        tsorigs: np.ndarray | None = None,
    ) -> int:
        """Batch-publish len(sigs) frags; payload rows are scattered into
        the dcache first when given.  Returns frags published.

        tspub defaults to now; pass tsorigs = in-frags' tsorig to carry
        origin timestamps through a relay tile (latency observability)."""
        n = len(sigs)
        if n == 0:
            return 0
        chunks = None
        if rows is not None:
            assert self.dcache is not None and szs is not None
            chunks = self.dcache.write_batch(rows, szs)
        if tspub == 0:
            tspub = now_ts()
        seq0 = self.seq
        # run_loop gates every callback round on cr_avail() across outs;
        # OutLink.publish is the one sanctioned wrapper under that gate
        # (manual-credit tiles re-check per ring).  fdtlint: allow[ring-credit]
        self.seq = self.mcache.publish_batch(
            seq0, sigs, chunks, szs, ctls, tspub, tsorigs
        )
        if self.tracer is not None:
            self.tracer.publish(self.link_id, seq0, sigs, tspub, tsorigs)
        return n


class MuxCtx:
    """Per-tile run context handed to every callback."""

    def __init__(
        self,
        name: str,
        cnc: R.CNC,
        ins: list[InLink],
        outs: list[OutLink],
        metrics: Metrics,
        wksp: R.Workspace | None = None,
    ):
        self.name = name
        self.cnc = cnc
        self.ins = ins
        self.outs = outs
        self.metrics = metrics
        #: the topology's shared workspace — tiles allocate observable
        #: state (tcaches etc.) here so a monitor process can map it
        self.wksp = wksp
        #: process runtime: a tile-private shm sub-allocator
        #: (tango.rings.WkspArena) that replaces direct workspace
        #: allocation — an ATTACHED workspace cannot allocate (the bump
        #: cursor is host-side state two children would race), so each
        #: child carves its own pre-sized arena instead.  None in the
        #: threaded runtime.
        self.arena = None
        self.credits = 0  # refreshed by the loop before each callback round
        self.halted = False
        #: supervision hooks: the supervisor sets `interrupt` to abandon a
        #: stalled incarnation; `faults` is a faultinj.TileFaults view the
        #: loop consults at its well-defined injection points; incarnation
        #: counts restarts so on_boot can distinguish join-vs-init of
        #: workspace state that must survive a crash (dedup's tcache)
        self.interrupt = threading.Event()
        self.faults = None
        #: span-event writer (disco/trace.py Tracer), installed by the
        #: topology when tracing is enabled; None keeps every trace
        #: point a single attribute check
        self.tracer = None
        #: run-loop profiler (disco/profile.py TileProfiler), installed
        #: by the topology when profiling is enabled; None keeps every
        #: profile point a single attribute check
        self.profiler = None
        #: the live native stem handle (tango.rings.Stem) when the run
        #: loop is driving this tile's registered native handler; None
        #: on the Python loop (tests/monitors read it, never write)
        self.stem = None
        #: deterministic-clock injection for the trace parity harness
        #: (tests only): a u64[2] (value, step) array the native
        #: in-burst trace reads instead of CLOCK_MONOTONIC.  Harnesses
        #: monkeypatch disco.mux.now_ts to read the SAME array so the
        #: Python loop and the native stem stamp identical timestamps
        #: on identical frag streams.  None in production.
        self.trace_clock = None
        self.incarnation = 0
        #: True once the current incarnation's on_boot completed — lets
        #: the topology distinguish "died during boot" (raise at start)
        #: from "crashed after RUN" (fail-stop via poll_failure)
        self.booted = False
        self._local_allocs: dict[str, np.ndarray] = {}

    def out(self, name: str) -> OutLink:
        for o in self.outs:
            if o.name == name:
                return o
        raise KeyError(name)

    def alloc(self, name: str, footprint: int) -> np.ndarray:
        """Observable tile state: allocated in the shared workspace when
        the topology provides one (so a monitor process can map it), else
        process-local memory (standalone tile tests).

        Idempotent by name (Workspace.alloc's contract): a restarted
        incarnation re-running on_boot gets the SAME region back, so
        state that must survive a crash (dedup's tag cache) persists
        across restarts — the tile decides whether to re-init it or
        rejoin it via `ctx.incarnation`.  In the process runtime the
        allocation comes from the tile's own shm arena (same idempotent
        contract; WkspArena keeps the name table in shared memory so
        the parent/monitors resolve the region by name)."""
        key = f"{self.name}_{name}"
        if self.arena is not None:
            return self.arena.alloc(key, footprint)
        if self.wksp is not None:
            return self.wksp.alloc(key, footprint)
        return self._local_alloc(key, footprint)

    def _local_alloc(self, key: str, footprint: int) -> np.ndarray:
        """Process-local fallback buffer for workspace-less ctx
        (standalone tile tests): idempotent by key, footprint-checked."""
        buf = self._local_allocs.get(key)
        if buf is None:
            buf = self._local_allocs[key] = np.zeros(
                footprint, dtype=np.uint8
            )
        elif len(buf) != footprint:
            raise ValueError(
                f"realloc of {key!r} with footprint {footprint} != "
                f"existing {len(buf)}"
            )
        return buf

    def shared(self, name: str, footprint: int) -> np.ndarray:
        """A topology-WIDE shared region: every tile asking for `name`
        gets the SAME memory (the bank tiles' shared account table),
        unlike alloc(), which is namespaced per tile.

        The region must be declared via Tile.shared_wksp_footprints()
        so the topology budgets and allocates it at build time — that
        is what lets a process-runtime child JOIN it here (an attached
        workspace cannot allocate new regions, but Workspace.alloc is
        idempotent by name so this call resolves the parent's
        allocation).  Standalone ctx (no workspace): a process-local
        buffer, so direct tile tests still run."""
        key = f"shared_{name}"
        if self.wksp is not None:
            return self.wksp.alloc(key, footprint)
        return self._local_alloc(key, footprint)

    def publish(self, sigs, rows=None, szs=None, ctls=None, tsorigs=None) -> int:
        """Publish to every out link (the common single-out case)."""
        n = 0
        for o in self.outs:
            n = o.publish(sigs, rows, szs, ctls, tsorigs=tsorigs)
        if n:
            self.metrics.inc("out_frags", n)
            if szs is not None:
                self.metrics.inc("out_bytes", int(np.asarray(szs).sum()))
        return n


class Tile:
    """Callback vtable, batch-first (reference: fd_mux_callbacks_t,
    src/disco/mux/fd_mux.h:115-260 — before/during/after_frag collapse
    into one on_frags batch callback here)."""

    name = "tile"
    schema = MetricsSchema()

    def wksp_footprint(self) -> int:
        """Bytes of shared-workspace state this tile allocates in on_boot
        (beyond links/metrics, which the topology accounts for itself)."""
        return 0

    def shared_wksp_footprints(self) -> dict[str, int]:
        """Topology-WIDE shared regions this tile joins via
        ctx.shared(name, footprint): {name: footprint}.  The topology
        allocates each named region ONCE at build (tiles naming the
        same region must agree on its footprint), which is what makes
        it reachable from process-runtime children — the bank tiles'
        shared account table is the motivating case."""
        return {}

    def on_boot(self, ctx: MuxCtx) -> None: ...

    def on_frags(self, ctx: MuxCtx, in_idx: int, frags: np.ndarray) -> None:
        """A batch of frags arrived on ins[in_idx]."""

    def in_budget(self, ctx: MuxCtx) -> int | None:
        """Max in-frags this tile can absorb this iteration (None =
        unlimited).  Tiles with internal queues (async device dispatch)
        return 0 when full so upstream backpressure propagates through
        the rings instead of an unbounded host buffer."""
        return None

    def ack_floor(self, ctx: MuxCtx, in_idx: int) -> int | None:
        """Oldest ins[in_idx] frag seq this tile might still need, or
        None when everything consumed is flushed.  The loop publishes
        min(cursor, floor) as the fseq — so a tile holding consumed
        frags in an internal pipeline (async device dispatch) keeps the
        producer's credit gate protecting them in the ring until their
        results are published downstream.  Without the holdback, a
        crash between consume and publish can lose frags PERMANENTLY:
        the advanced fseq lets the producer overwrite them, putting
        them beyond any rejoin replay window (consumer_rejoin clamps to
        the oldest frag the ring still holds).  The floor must be
        monotone between calls (it only advances as the pipeline
        flushes in frag order)."""
        return None

    #: False = this tile stays a THREAD in the parent even under the
    #: process runtime (Topology.start(mode="process")).  Observer
    #: tiles that close over parent-side state (the metric tile's
    #: registry callable, the rpc tile's counter lambdas) are the
    #: intended users: they only READ shared memory, so keeping them
    #: in-parent loses no isolation, while their closures could never
    #: ride a spawn pickle.  Pipeline tiles must be proc-safe (the
    #: fdtlint `proc-safe-tile` rule guards their ctors).
    proc_safe = True

    def native_handler(self, ctx: MuxCtx) -> "R.StemSpec | None":
        """Opt into the native stem (tango/native/fdt_stem.c): return a
        tango.rings.StemSpec describing this tile's native frag handler
        and the run loop will drain/handle/publish whole bursts in ONE
        GIL-released call, returning to Python only at burst boundaries.
        Called once, after on_boot (handler state pointers must exist).

        None (the default) keeps the Python on_frags loop — which
        remains the bit-identical reference semantics, the only loop
        fdtmc schedules, and the path every frag the native handler
        cannot express is handed back to.  Tiles registering a handler
        must not mutate Python-side state from the fast path (the
        fdtlint `stem-native-handler` rule): everything the handler
        touches lives in the args block's shared/native memory."""
        return None

    #: a manual-credit tile gates each publish on that ring's own
    #: cr_avail() instead of the loop's min-over-all-outs gate.  Needed
    #: when two tiles form a request/response ring CYCLE (shred <->
    #: keyguard): the global gate would stop the tile entirely when one
    #: out ring fills, so it could never drain the response ring that
    #: unblocks the peer — a deadlock.  Manual tiles must bound their
    #: internal queues via in_budget.
    manual_credits = False

    #: elastic topology (disco/elastic.py): an ElasticBinding injected
    #: by Topology.declare_shards onto shard members and producers (it
    #: rides the spawn pickle).  None = not elastic; every hook below
    #: stays a single attribute check.
    elastic = None

    def epoch_word(self, ctx: MuxCtx):
        """The shard-map epoch word this tile watches (u64[1] shm view)
        or None.  The run loop re-reads it at every burst boundary and
        calls on_epoch when it moved — the ONLY sanctioned point for a
        tile to act on a membership flip (the burst-boundary re-read
        discipline the elastic-stale-epoch fdtmc mutant pins)."""
        eb = self.elastic
        return None if eb is None else eb.epoch_word(ctx)

    def on_epoch(self, ctx: MuxCtx) -> None:
        """A shard-map epoch flip was observed at a burst boundary.
        The base behavior is the binding's role half (producers append
        the flip-journal entry + ack; members ack); tiles override AND
        call super() to layer their own reconfiguration (pack parks
        retired banks' cadence words, quic autosizes admission caps)."""
        eb = self.elastic
        if eb is not None:
            eb.on_epoch(self, ctx)

    def shard_tick(self, ctx: MuxCtx) -> None:
        """Housekeeping-cadence elastic bookkeeping (ack refresh + the
        retirement drain contract — see ElasticBinding.tick)."""
        eb = self.elastic
        if eb is not None:
            eb.tick(self, ctx)

    def elastic_drained(self, ctx: MuxCtx) -> bool:
        """Member-side drain predicate: True when this tile holds no
        in-flight work beyond its ring cursors (those are checked by
        the binding).  Tiles with internal pipelines override: verify
        waits for its device pool + reorder buffer to land, banks flush
        their funk commit first."""
        return True

    def after_credit(self, ctx: MuxCtx) -> None:
        """Called every iteration after frag processing while credits
        remain — where producer tiles generate work (reference:
        after_credit, fd_mux.h)."""

    def during_housekeeping(self, ctx: MuxCtx) -> None: ...

    def on_halt(self, ctx: MuxCtx) -> None: ...

    def on_crash(self, ctx: MuxCtx) -> None:
        """Called by the supervisor (on the supervisor thread, after the
        dead incarnation's thread has been joined) before on_boot re-runs:
        release resources the dead incarnation held (worker threads,
        sockets) and drop in-flight host-side state — ring state is
        resynced separately via the rejoin helpers."""


def drain_straggler_ins(
    tile: "Tile",
    ctx: "MuxCtx",
    *,
    only: tuple | None = None,
    budget: int | None = None,
    deadline_s: float | None = None,
    default_budget: int = 4096,
) -> int:
    """Post-HALT straggler drain shared by egress tiles (poh, shred):
    sweep the in-links through tile.on_frags with the standard overrun
    accounting (metered + fseq-diag'd, the fdtlint ring-overrun
    discipline), bounded per sweep by the outs' credit headroom.

    `only` restricts the sweep to those in-link indices (shred's halt
    loop drains just the sign-response ring); `budget` overrides the
    credit-derived bound.  With `deadline_s` the sweep repeats until a
    full pass drains nothing or the deadline passes; without it one
    sweep runs.  Returns frags drained by the final sweep."""
    deadline = (
        time.monotonic() + deadline_s if deadline_s is not None else None
    )
    got = 0
    while True:
        got = 0
        idxs = range(len(ctx.ins)) if only is None else only
        for i in idxs:
            il = ctx.ins[i]
            b = budget
            if b is None:
                b = min(
                    (o.cr_avail() for o in ctx.outs),
                    default=default_budget,
                )
            if b <= 0:
                break
            frags, il.seq, ovr = il.mcache.drain(il.seq, b)
            if ovr:
                ctx.metrics.inc("overrun_frags", ovr)
                il.fseq.diag_add(0, ovr)
            if len(frags):
                got += len(frags)
                tile.on_frags(ctx, i, frags)
        if deadline is None or got == 0 or time.monotonic() >= deadline:
            return got


def _arm_stem_trace(stem, ctx, m, tracer) -> bool:
    """Arm the native in-burst trace (tango/native/fdt_trace.c) on a
    freshly built stem: wire the tile's per-in-link latency hists, its
    span ring and the (test-harness) injected clock into the stem's
    trace block so per-frag drain/publish timestamps, qwait/svc/e2e
    hist updates and span emission all happen INSIDE the GIL-released
    burst — the measurement substrate living with the data plane
    instead of being applied at the burst boundary with one post-burst
    clock read (the PROFILE.md round-11d skew).  Returns False when the
    ctx has neither link hists nor a tracer; the stem then runs
    untraced (zero overhead) and _stem_apply keeps the legacy
    burst-boundary bookkeeping for whatever hists exist."""
    in_rows = []
    any_h = False
    for il in ctx.ins:
        if il.h_qwait is not None:
            any_h = True
            in_rows.append(
                (
                    il.link_id,
                    m.hist_ref(il.h_qwait),
                    m.hist_ref(il.h_e2e),
                    m.hist_ref(il.h_svc),
                )
            )
        else:
            in_rows.append((il.link_id, None, None, None))
    ring_addr = 0
    sample = 1
    if tracer is not None:
        ring_addr = tracer.ring.words.ctypes.data
        sample = tracer.sample
    if not any_h and not ring_addr:
        return False
    batch = (
        m.hist_ref("batch_sz") if "batch_sz" in m.schema.hists else None
    )
    stem.arm_trace(
        ring_addr=ring_addr,
        sample=sample,
        in_rows=in_rows,
        out_links=[ol.link_id for ol in ctx.outs],
        batch_hist=batch,
        clock=ctx.trace_clock,
        keepalive=(
            m.words,
            None if tracer is None else tracer.ring.words,
        ),
    )
    return True


def _stem_apply(
    ctx, m, stem, spec, tracer, faults, out_seq0, tspub,
    trace_native=False,
) -> int:
    """Burst-boundary bookkeeping for one native stem call: the stem
    accumulated counter deltas, drained-frag metas and published-sig
    scratch in native memory; apply them to metrics/faultinj ONCE per
    burst (the batched per-frag-update contract).

    With the in-burst trace armed (trace_native, ISSUE 15) this slims
    to COUNTERS + FAULTINJ: hists and span events were already written
    per frag inside the burst by fdt_trace with per-frag clock reads.
    Unarmed (no link hists, no tracer — or a pre-trace harness), the
    legacy path applies latency hists with the post-burst clock, where
    qwait/e2e carry up to one burst of skew.
    Returns total frags consumed by the burst."""
    total = 0
    for i, il in enumerate(ctx.ins):
        ovr = stem.overruns(i)
        if ovr:
            m.inc("overrun_frags", ovr)
            il.fseq.diag_add(0, ovr)
        n = stem.consumed(i)
        if not n:
            continue
        total += n
        m.inc("in_frags", n)
        m.inc("in_bytes", stem.in_bytes(i))
        if faults is not None:
            faults.note_frags(il, n)
        if trace_native:
            continue
        m.hist_sample("batch_sz", n)
        frags = stem.frags(i)
        t_cons = 0
        if il.h_qwait is not None:
            t_cons = now_ts()
            m.hist_sample_many(
                il.h_qwait,
                np.maximum(ts_diff_arr(t_cons, frags["tspub"]), 0),
            )
            m.hist_sample_many(
                il.h_e2e,
                np.maximum(ts_diff_arr(t_cons, frags["tsorig"]), 0),
            )
            m.hist_sample(il.h_svc, max(ts_diff(t_cons, tspub), 0))
        if tracer is not None:
            tracer.ingest(il.link_id, frags, t_cons or now_ts())
    for o, ol in enumerate(ctx.outs):
        p = stem.published(o)
        if not p:
            continue
        m.inc("out_frags", p)
        m.inc("out_bytes", stem.out_bytes(o))
        if ol.tracer is not None and not trace_native:
            ol.tracer.publish(
                ol.link_id, out_seq0[o], stem.out_sigs(o), tspub,
                stem.out_tsorigs(o),
            )
    ctrs = stem.counters
    for idx, name in enumerate(spec.counters):
        v = int(ctrs[idx])
        if v:
            m.inc(name, v)
    if total and spec.after_burst is not None:
        spec.after_burst(ctx, ctrs)
    return total


def run_loop(
    tile: Tile,
    ctx: MuxCtx,
    *,
    batch_max: int = 4096,
    lazy_ns: int | None = None,
    idle_sleep_s: float = 50e-6,
    idle_before_sleep: int = 32,
    stem: str | None = None,
) -> None:
    """Drive one tile until its cnc receives HALT (or on_boot/callbacks
    raise).  Mirrors the fd_mux_tile phase structure: housekeeping →
    credit receive → frag drain → callbacks → idle backoff.

    Housekeeping cadence is time-based via tango.tempo: the interval
    derives from the smallest ring depth (lazy_default) and each firing
    re-arms at a jittered point (async_reload) so tiles decorrelate."""
    from firedancer_tpu.tango import tempo

    m = ctx.metrics
    cnc = ctx.cnc
    faults = ctx.faults
    tracer = ctx.tracer
    # run-loop profiler (disco/profile.py): wall/CPU phase attribution
    # and scheduler-lag on the SAME 1-in-16 sampled iterations as the
    # phase hists; None costs one attribute check per hook point
    prof = ctx.profiler
    idle_sleep_ns = int(idle_sleep_s * 1e9)
    if faults is not None:
        # injected faults annotate themselves into the trace (the
        # kill -> restart gap must be visible in the timeline)
        faults.tracer = tracer
    try:
        tile.on_boot(ctx)
    except Exception:
        # boot failures must still be visible on the cnc (the supervisor
        # and topology boot-wait key off FAIL, not thread liveness)
        cnc.signal(R.CNC_FAIL)
        raise
    ctx.booted = True
    # elastic shard map (disco/elastic.py): bind the watched epoch word
    # and apply the CURRENT membership before any frag flows — the loop
    # re-reads the word at every burst boundary below
    ep_word = tile.epoch_word(ctx)
    ep_seen = -1
    if ep_word is not None:
        ep_seen = int(ep_word[0])
        tile.on_epoch(ctx)
    # native stem (ISSUE 10): the tile may register a native frag
    # handler; the loop then drains/handles/publishes whole bursts in
    # one GIL-released call, falling back to the Python path per
    # iteration whenever the handler cannot express the work (pending
    # amnesty, fallback txns, frag-fault injection, in_budget tiles)
    stem_obj = None
    stem_spec = None
    if stem == "native":
        stem_spec = tile.native_handler(ctx)
        # a manual-credit tile (shred <-> keyguard ring cycle) may run
        # the stem ONLY when its spec declares the manual discipline:
        # handlers never publish from the frag path, and the after-
        # credit hook gates each ring on its own cr_avail
        if (
            stem_spec is not None
            and tile.manual_credits
            and not stem_spec.manual
        ):
            stem_spec = None
        if stem_spec is not None:
            try:
                stem_obj = R.Stem(
                    ctx.ins, ctx.outs, stem_spec, cap=batch_max
                )
            except ValueError:
                # unsupported shape (> 8 ins / 8 outs / 4 reliable
                # consumers per out): the Python loop is always correct
                stem_obj = None
                stem_spec = None
    ctx.stem = stem_obj
    # in-burst tracing (ISSUE 15): move the measurement substrate into
    # the burst — per-frag drain/publish timestamps, native hist
    # updates and native span emission.  stem_engaged is the monitor's
    # stem-coverage anchor (set every boot so a restarted incarnation
    # under a different stem mode reports truthfully).
    stem_trace = False
    if stem_obj is not None:
        stem_trace = _arm_stem_trace(stem_obj, ctx, m, tracer)
    m.set("stem_engaged", 1 if stem_obj is not None else 0)
    if stem_obj is not None and ep_word is not None:
        # the stem carries the same epoch word in its config block and
        # hands a burst back UNCONSUMED when it moved, so the native
        # loop keeps the burst-boundary re-read discipline even though
        # Python only regains control between bursts
        stem_obj.watch_epoch(ep_word, ep_seen)
    cnc.signal(R.CNC_RUN)
    if lazy_ns is None:
        depths = [il.mcache.depth for il in ctx.ins] + [
            o.depth for o in ctx.outs
        ]
        lazy_ns = tempo.lazy_default(min(depths) if depths else batch_max)
    next_hk = 0  # fire immediately on the first iteration
    idle = 0
    iters = 0
    try:
        while True:
            # fault-injection point 1: scripted kill / stall / credit
            # squeeze fire at the top of the iteration, BEFORE the
            # heartbeat — a stall here starves the heartbeat exactly like
            # a wedged tile would
            if faults is not None:
                faults.tick(ctx)
            if ctx.interrupt.is_set():
                raise TileInterrupted(f"{ctx.name}: abandoned by supervisor")
            # burst-boundary shard-map re-read: one shm load per
            # iteration; a moved epoch reconfigures the tile BEFORE any
            # frag of the new membership window is drained
            if ep_word is not None:
                _e = int(ep_word[0])
                if _e != ep_seen:
                    ep_seen = _e
                    tile.on_epoch(ctx)
                    if stem_obj is not None:
                        stem_obj.set_epoch_seen(_e)
            now = time.monotonic_ns()
            # phase durations are histogram-sampled every 16th iteration
            # (the reference histograms every phase, fd_mux.c:435-444; a
            # 1/16 sample keeps the Python-side cost negligible while
            # preserving the distribution)
            sample = (iters & 0xF) == 0
            p_cpu0 = (
                time.thread_time_ns()
                if prof is not None and sample
                else 0
            )
            p_sleep = 0  # voluntary sleep inside this iteration (ns)
            iters += 1
            if now >= next_hk:
                # scheduler lag: how far past the INTENDED firing point
                # the loop actually got here (GIL/scheduler contention
                # seen from the time-based cadence's side)
                hk_lag_ns = now - next_hk if next_hk else 0
                next_hk = now + tempo.async_reload(lazy_ns)
                cnc.heartbeat(now)
                for i_hk, il in enumerate(ctx.ins):
                    floor = tile.ack_floor(ctx, i_hk)
                    il.fseq.update(
                        il.seq if floor is None
                        else R.seq_min(floor, il.seq)
                    )
                m.inc("housekeep_iters")
                if cnc.signal_query() == R.CNC_HALT:
                    break
                if ep_word is not None:
                    tile.shard_tick(ctx)
                tile.during_housekeeping(ctx)
                if prof is not None:
                    if hk_lag_ns:
                        prof.sched_lag(hk_lag_ns)
                    if sample:
                        prof.add_phase(
                            "hk",
                            time.monotonic_ns() - now,
                            time.thread_time_ns() - p_cpu0,
                        )
                if sample:
                    hk_ns = time.monotonic_ns() - now
                    m.hist_sample("hk_ns", hk_ns)
                    if tracer is not None:
                        tracer.point(_SPAN_HK, aux64=hk_ns)
            m.inc("loop_iters")

            if tile.manual_credits:
                cr = batch_max
            else:
                cr = batch_max
                for o in ctx.outs:
                    cr = min(cr, o.cr_avail())
                # fault-injection point 2: forced zero-credit backpressure
                if faults is not None and faults.squeeze_credits():
                    cr = 0
                if ctx.outs and cr == 0:
                    m.inc("backpressure_iters")
                    if tracer is not None and idle == 0:
                        # one BP span per streak start (per-iteration
                        # events would flood the ring with no new info)
                        tracer.point(_SPAN_BP)
                    idle += 1
                    if idle >= idle_before_sleep:
                        if prof is None:
                            time.sleep(idle_sleep_s)
                        else:
                            t0s = time.monotonic_ns()
                            time.sleep(idle_sleep_s)
                            p_sleep = time.monotonic_ns() - t0s
                            prof.add_sleep(p_sleep, idle_sleep_ns)
                    if prof is not None and sample:
                        end = time.monotonic_ns()
                        prof.add_bp(max(end - now - p_sleep, 0))
                        prof.add_iter(
                            end - now,
                            time.thread_time_ns() - p_cpu0,
                            p_sleep,
                        )
                    continue
            ctx.credits = cr

            out_seq0 = [o.seq for o in ctx.outs]
            got = 0
            t_frag0 = time.monotonic_ns() if sample else 0
            p_cpu_frag0 = (
                time.thread_time_ns()
                if prof is not None and sample
                else 0
            )
            absorb = tile.in_budget(ctx)
            run_py = True
            # run_ac: whether THIS iteration calls the Python
            # after_credit.  A spec with a native after-credit hook
            # (pack's fdt_pack_sched) schedules inside the burst, so
            # the Python slot is skipped except on PYTHON handbacks
            # (end_block, eviction, unknown completion) — that skip is
            # what makes the tile zero-Python per microblock at steady
            # state (asserted via the py_credit counter).
            run_ac = True
            if (
                stem_obj is not None
                and absorb is None
                and (faults is None or not faults.has_frag_faults)
                and (stem_spec.ready is None or stem_spec.ready())
            ):
                # one GIL-released burst: drain + handle + publish +
                # fseq/credit updates all native; Python resumes here
                # at the burst boundary with the accumulated deltas
                ts_b0 = now_ts()
                s_got, s_stat, s_in = stem_obj.run(cr, ts_b0)
                got += _stem_apply(
                    ctx, m, stem_obj, stem_spec, tracer, faults,
                    out_seq0, ts_b0, stem_trace,
                )
                if s_got:
                    m.inc("stem_frags", s_got)
                # STEM_PYTHON: a pending frag needs the slow path (or a
                # python-only in-link has traffic) — fall through to the
                # Python drain with the remaining credit budget.  Any
                # other status (IDLE/BUDGET/BP) already consumed
                # everything this iteration may.  An EPOCH handback
                # (the shard map moved under the stem) skips the Python
                # drain outright: the next iteration's top-of-loop
                # check reconfigures the tile FIRST, so no frag is ever
                # handled under a stale membership view.
                run_py = (
                    s_stat == R.STEM_PYTHON and s_in != R.STEM_IN_EPOCH
                )
                if stem_spec.ac_handler or s_in == R.STEM_IN_EPOCH:
                    run_ac = run_py
            # rotate the drain order so a saturated in-link cannot starve
            # the others of the shared credit budget (e.g. pack's txn
            # firehose starving its bank-completion rings would idle
            # every bank)
            n_ins = len(ctx.ins)
            order = range(n_ins) if n_ins <= 1 else [
                (iters + j) % n_ins for j in range(n_ins)
            ]
            for i in order if run_py else ():
                il = ctx.ins[i]
                # credits are consumed across in-links: a tile republishes
                # at most 1 out-frag per in-frag, so bounding the remaining
                # drain budget by frags already taken this iteration keeps
                # total publishes <= cr even with many in-links
                budget = cr - got
                if absorb is not None:
                    budget = min(budget, absorb - got)
                if budget <= 0:
                    break
                frags, il.seq, ovr = il.mcache.drain(il.seq, budget)
                if ovr:
                    m.inc("overrun_frags", ovr)
                    il.fseq.diag_add(0, ovr)
                # fault-injection point 3: drop / corrupt frag payloads
                # between the ring and the tile callback (injected drops
                # are declared in the injector's event log, not metrics)
                if faults is not None and len(frags):
                    frags = faults.mangle_frags(il, frags)
                if len(frags):
                    got += len(frags)
                    m.inc("in_frags", len(frags))
                    m.inc("in_bytes", int(frags["sz"].sum()))
                    m.hist_sample("batch_sz", len(frags))
                    # per-hop latency attribution on the compressed-µs
                    # clock, per drained batch (two vector subtracts on
                    # arrays already in hand — negligible next to the
                    # batch's gather/publish work): queue-wait behind
                    # the upstream publish, end-to-end from the origin
                    # stamp, and batch service time after the callback
                    t_cons = 0
                    if il.h_qwait is not None:
                        t_cons = now_ts()
                        m.hist_sample_many(
                            il.h_qwait,
                            np.maximum(
                                ts_diff_arr(t_cons, frags["tspub"]), 0
                            ),
                        )
                        m.hist_sample_many(
                            il.h_e2e,
                            np.maximum(
                                ts_diff_arr(t_cons, frags["tsorig"]), 0
                            ),
                        )
                    if tracer is not None:
                        tracer.ingest(
                            il.link_id, frags, t_cons or now_ts()
                        )
                    # py_frags counts frags the PYTHON callback handled
                    # (vs stem_frags): stem coverage and the zero-
                    # Python-per-frag steady-state assert both read it
                    m.inc("py_frags", len(frags))
                    tile.on_frags(ctx, i, frags)
                    if il.h_svc is not None:
                        m.hist_sample(
                            il.h_svc, max(ts_diff(now_ts(), t_cons), 0)
                        )
            ctx.credits = cr - got
            if sample:
                t_credit0 = time.monotonic_ns()
                p_cpu_credit0 = (
                    time.thread_time_ns() if prof is not None else 0
                )
                if got:
                    m.hist_sample("frag_ns", t_credit0 - t_frag0)
                    if prof is not None:
                        prof.add_phase(
                            "frag",
                            t_credit0 - t_frag0,
                            p_cpu_credit0 - p_cpu_frag0,
                        )
                if run_ac:
                    m.inc("py_credit")
                    tile.after_credit(ctx)
                t_end = time.monotonic_ns()
                m.hist_sample("credit_ns", t_end - t_credit0)
                m.hist_sample("loop_ns", t_end - now)
                if prof is not None:
                    prof.add_phase(
                        "credit",
                        t_end - t_credit0,
                        time.thread_time_ns() - p_cpu_credit0,
                    )
            else:
                if run_ac:
                    m.inc("py_credit")
                    tile.after_credit(ctx)

            produced = any(o.seq != s0 for o, s0 in zip(ctx.outs, out_seq0))
            if got == 0 and not produced:
                idle += 1
                if idle >= idle_before_sleep:
                    if prof is None:
                        time.sleep(idle_sleep_s)
                    else:
                        t0s = time.monotonic_ns()
                        time.sleep(idle_sleep_s)
                        p_sleep += time.monotonic_ns() - t0s
                        prof.add_sleep(
                            time.monotonic_ns() - t0s, idle_sleep_ns
                        )
            else:
                idle = 0
            if prof is not None and sample:
                prof.add_iter(
                    time.monotonic_ns() - now,
                    time.thread_time_ns() - p_cpu0,
                    p_sleep,
                )
    except Exception:
        cnc.signal(R.CNC_FAIL)
        raise
    finally:
        # crash finalize honors the ack floor: frags still in the
        # tile's internal pipeline stay producer-protected in the ring,
        # so the next incarnation's rejoin replay recovers them
        for i_f, il in enumerate(ctx.ins):
            floor = tile.ack_floor(ctx, i_f)
            il.fseq.update(
                il.seq if floor is None else R.seq_min(floor, il.seq)
            )
        if cnc.signal_query() != R.CNC_FAIL:
            tile.on_halt(ctx)
            # on_halt flushed the pipeline (or timed out with a
            # residue): republish so a completed drain finalizes at the
            # consumed cursor — commanded-halt boundaries compare this
            # fseq against the producer cursor
            for i_f, il in enumerate(ctx.ins):
                floor = tile.ack_floor(ctx, i_f)
                il.fseq.update(
                    il.seq if floor is None else R.seq_min(floor, il.seq)
                )
            cnc.signal(R.CNC_BOOT)  # halt acknowledged (reference protocol)
