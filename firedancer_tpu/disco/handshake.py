"""Runtime ring-ABI version handshake (fdt_upgrade).

fdtlint proves at lint time that ONE tree's ctypes table, C prototypes,
and cfg-word constants agree with each other.  Hot code upgrade breaks
the single-tree assumption: after `Topology.hot_upgrade` a respawned
incarnation may run a DIFFERENT module tree (and a different .so)
against rings the old tree built.  This module promotes the static
check into a runtime contract:

- `tango/rings.py abi_digest()` folds the incarnation's entire ring
  contract — native symbol set (the .so's .hsk sidecar from
  utils/cbuild.py), ctypes sigs table, ring/stem layout constants,
  cfg-word map, emit-body signatures — into one nonzero u64.
- `Topology.build()` allocates the `shared_handshake` region and writes
  the building tree's digest into it (single writer: the parent; a
  joiner only reads).
- EVERY process-runtime child compares its own digest against the shm
  word right after `Workspace.attach`, BEFORE binding a single ring
  (`check_join`).  Mismatch → `HandshakeRefused` carrying both digests;
  the child exits without touching ring memory and the supervisor/
  flight path classifies an `upgrade` incident.
- An operator who has proven two versions ring-compatible out of band
  can `approve()` the foreign digest into the compat table (8 slots);
  `compatible()` accepts either the primary word or any table entry.

The `ring-handshake-rebind` fdtlint rule pins that every rebind path
(attach + link construction) performs this check.

Word layout (16 u64 words, 128 bytes):

    0  MAGIC
    1  DIGEST       the building tree's abi_digest()
    2  NCOMPAT      live entries in the compat table
    3..10           compat table slots
    11..15          spare
"""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np

HANDSHAKE_MAGIC = 0xF17EDA2CE57E0003
HANDSHAKE_FOOTPRINT = 128  # 16 u64 words

_W_MAGIC, _W_DIGEST, _W_NCOMPAT, _W_COMPAT0 = 0, 1, 2, 3
MAX_COMPAT = 8


class HandshakeRefused(RuntimeError):
    """A joining incarnation's ABI digest matched neither the workspace
    word nor any compat-table entry — refused before any ring bind."""

    def __init__(self, shm_digest: int, my_digest: int, tile: str = ""):
        self.shm_digest = shm_digest
        self.my_digest = my_digest
        self.tile = tile
        super().__init__(
            f"version handshake refused{f' for tile {tile!r}' if tile else ''}: "
            f"workspace ABI digest {shm_digest:#018x} vs joining "
            f"incarnation {my_digest:#018x} — mixed-version topology is "
            f"not proven ring-compatible (rebuild from the same tree, or "
            f"approve the digest via Topology.approve_version after an "
            f"out-of-band compatibility proof)"
        )


class Handshake:
    """View of the shared_handshake region (owner or joiner)."""

    def __init__(self, mem_u8: np.ndarray, join: bool = True):
        self.words = mem_u8[: (len(mem_u8) // 8) * 8].view(np.uint64)
        if not join:
            self.words[_W_DIGEST] = 0
            self.words[_W_NCOMPAT] = 0
            # magic last: a joiner that sees it sees a full header
            self.words[_W_MAGIC] = np.uint64(HANDSHAKE_MAGIC)

    # -- owner (parent) side ------------------------------------------------

    def init(self, digest: int) -> None:
        assert digest != 0, "0 is the uninitialized-word sentinel"
        self.words[_W_DIGEST] = np.uint64(digest)

    def approve(self, digest: int) -> None:
        """Admit a foreign digest into the compat table (operator has
        proven the two versions ring-compatible out of band)."""
        if self.compatible(digest):
            return
        n = int(self.words[_W_NCOMPAT])
        assert n < MAX_COMPAT, "compat table full"
        # slot store first, count after: a concurrent reader never sees
        # a live count covering an unwritten slot
        self.words[_W_COMPAT0 + n] = np.uint64(digest)
        self.words[_W_NCOMPAT] = np.uint64(n + 1)

    # -- joiner side ---------------------------------------------------------

    def digest(self) -> int:
        return int(self.words[_W_DIGEST])

    def compatible(self, digest: int) -> bool:
        if int(self.words[_W_MAGIC]) != HANDSHAKE_MAGIC:
            return False
        if digest == self.digest():
            return True
        n = min(int(self.words[_W_NCOMPAT]), MAX_COMPAT)
        return any(
            int(self.words[_W_COMPAT0 + i]) == digest for i in range(n)
        )


def check_join(mem_u8: np.ndarray, my_digest: int, tile: str = "") -> None:
    """The joiner-side gate: raise HandshakeRefused unless `my_digest`
    is proven compatible with the workspace's handshake word.  Called
    by every rebind path after Workspace.attach and before any
    InLink/OutLink/ring construction."""
    hs = Handshake(mem_u8, join=True)
    if not hs.compatible(my_digest):
        raise HandshakeRefused(hs.digest(), my_digest, tile=tile)


# ---------------------------------------------------------------------------
# version probing (parent side, pre-upgrade)

_PROBE_CACHE: dict[tuple[str | None, str | None], int] = {}


def probe_digest(version_root: str | None = None,
                 so_path: str | None = None) -> int:
    """The abi_digest a child spawned with (version_root, so_path)
    would compute — the parent's pre-flight check before committing a
    hot upgrade.  Identity (no overrides) answers in-process; a foreign
    tree is probed in a throwaway interpreter with the same sys.path /
    FDT_SO_PATH surgery `Topology._spawn_tile` performs, cached per
    (root, so)."""
    if version_root is None and so_path is None:
        from firedancer_tpu.tango import rings as R

        return R.abi_digest()
    key = (version_root, so_path)
    if key in _PROBE_CACHE:
        return _PROBE_CACHE[key]
    env = dict(os.environ)
    if so_path is not None:
        env["FDT_SO_PATH"] = so_path
    code = (
        "import firedancer_tpu.tango.rings as r; print(r.abi_digest())"
    )
    if version_root is not None:
        code = f"import sys; sys.path.insert(0, {version_root!r}); " + code
    out = subprocess.run(
        [sys.executable, "-c", code],
        env=env, capture_output=True, text=True, timeout=300,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"version probe failed for root={version_root!r} "
            f"so={so_path!r}:\n{out.stderr}"
        )
    d = int(out.stdout.strip().splitlines()[-1])
    _PROBE_CACHE[key] = d
    return d
