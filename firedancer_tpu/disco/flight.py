"""fdtflight: black-box flight recorder and incident bundles.

PR 5's fdttrace answers "where does a frag spend its time"; this layer
answers "what exactly was the system doing when it broke".  Three parts:

  * A per-tile BLACK BOX: a small ring of periodic state records in
    workspace shared memory (BlackBox) — metric deltas, ring cursors
    (mcache seq / fseq / credit view) and supervision state, sampled by
    the recorder's watcher thread.  Like the span rings it is a
    single-writer, torn-read-tolerant u64 region: the data survives the
    death of any tile — including a SIGKILLed tile CHILD PROCESS under
    the ISSUE 7 process runtime — because it lives in the workspace,
    not in the tile.

  * A trigger engine: supervisor crash/stall restarts, circuit-breaker
    trips and wedges (via Supervisor.add_listener), device quarantines
    (dev{i}_degraded gauge edges), SLO breaches (disco/slo.py burn-rate
    edges) and explicit signals (FlightRecorder.trigger / SIGUSR1) each
    freeze the black boxes and dump an INCIDENT BUNDLE.

  * The bundle itself: one self-contained JSON document — trigger,
    topology manifest, faultinj seed + canonical fired record, SLO
    state, per-tile state (cnc signal, counters, ring cursors, recent
    black-box records) and the last-N span events per tile — enough to
    classify, render, and diff the incident offline with NO access to
    the live system (`scripts/fdtincident.py`).

Determinism note: two runs of the same seeded fault schedule produce
bundles whose canonical fields (trigger kind/tile, classification,
faultinj seed + fired record) are equal; wall-clock fields and counter
values are declared noisy and compared only informationally by
`fdtincident diff`.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass

import numpy as np

from . import trace as T
from .metrics import device_rows

_SIGNAMES = {0: "BOOT", 1: "RUN", 2: "HALT", 3: "FAIL"}

#: counters every black-box record carries (beyond ts + ring cursors)
BOX_COUNTERS = (
    "in_frags",
    "out_frags",
    "overrun_frags",
    "backpressure_iters",
    "loop_iters",
    "restarts",
    "degraded",
)

_BOX_HDR_WORDS = 8


class BlackBox:
    """Per-tile snapshot ring in a u64 workspace region.

    Header: word0 = record cursor (total records ever written),
    word1 = depth, word2 = rec_words.  Records live at slot
    (i % depth); same single-writer torn-read-tolerant contract as the
    metrics regions.  The single writer is the flight recorder's
    watcher thread (for every box — one writer thread, many boxes)."""

    def __init__(
        self, mem_u8: np.ndarray, depth: int = 0, rec_words: int = 0,
        join: bool = False,
    ):
        self.words = mem_u8[: (len(mem_u8) // 8) * 8].view(np.uint64)
        if join:
            self.depth = int(self.words[1])
            self.rec_words = int(self.words[2])
        else:
            assert depth > 0 and rec_words > 0
            self.depth = depth
            self.rec_words = rec_words
            self.words[0] = 0
            self.words[1] = depth
            self.words[2] = rec_words
        self.recs = self.words[
            _BOX_HDR_WORDS : _BOX_HDR_WORDS + self.depth * self.rec_words
        ].reshape(self.depth, self.rec_words)

    @staticmethod
    def footprint(depth: int, rec_words: int) -> int:
        return (_BOX_HDR_WORDS + depth * rec_words) * 8

    def write(self, rec) -> None:
        c = int(self.words[0])
        row = np.zeros(self.rec_words, np.uint64)
        n = min(len(rec), self.rec_words)
        row[:n] = np.asarray(rec[:n], np.uint64)
        self.recs[c % self.depth] = row
        self.words[0] = np.uint64(c + 1)

    def read_all(self) -> list[list[int]]:
        """Last min(cursor, depth) records, oldest first."""
        c = int(self.words[0])
        lo = max(c - self.depth, 0)
        idx = (lo + np.arange(c - lo)) % self.depth
        return self.recs[idx].tolist()


@dataclass(frozen=True)
class FlightConfig:
    """Topology-level flight-recorder knobs (Topology.enable_flight)."""

    #: black-box records retained per tile
    depth: int = 64
    #: span events included per tile in a bundle's timeline
    timeline_n: int = 256


def box_rec_words(n_ins: int, n_outs: int) -> int:
    """Record layout: ts_us, BOX_COUNTERS, then (produced, consumed)
    per in-link and (produced, min_consumer_seq) per out-link."""
    return 1 + len(BOX_COUNTERS) + 2 * n_ins + 2 * n_outs


def decode_box_record(rec: list[int], ins: list[str], outs: list[str]) -> dict:
    out = {"ts_us": rec[0]}
    base = 1
    for i, c in enumerate(BOX_COUNTERS):
        out[c] = rec[base + i]
    base += len(BOX_COUNTERS)
    out["ins"] = {}
    for i, ln in enumerate(ins):
        out["ins"][ln] = {
            "produced": rec[base + 2 * i],
            "consumed": rec[base + 2 * i + 1],
        }
    base += 2 * len(ins)
    out["outs"] = {}
    for i, ln in enumerate(outs):
        out["outs"][ln] = {
            "produced": rec[base + 2 * i],
            "slowest_consumer": rec[base + 2 * i + 1],
        }
    return out


# ---------------------------------------------------------------------------
# in-process topology snapshots (monitor-shaped, shared with the SLO
# engine and the bundles; app/monitor.py produces the same shape from an
# attached workspace)


_LAT_PREFIXES = ("qwait_us_", "svc_us_", "e2e_us_")


def snapshot_topology(topo) -> dict:
    """One monitor-shaped snapshot of a built in-process Topology."""
    out: dict = {}
    for name in topo.tiles:
        m = topo._metrics[name]
        cnc = topo._cncs[name]
        sig = cnc.signal_query()
        out[name] = {
            "signal": _SIGNAMES.get(sig, str(sig)),
            "heartbeat": cnc.heartbeat_query(),
            "counters": {
                c: m.counter(c) for c in m.schema.counters
            },
            "lat_hists": {
                h: m.hist(h)
                for h in m.schema.hists
                if h.startswith(_LAT_PREFIXES)
            },
        }
    links: dict = {}
    for lname, ls in topo.links.items():
        mc = topo._mcaches.get(lname)
        prod = mc.seq_query() if mc is not None else None
        seqs = {}
        for cons, _rel in ls.consumers:
            fs = topo._fseqs.get((lname, cons))
            if fs is None:
                continue
            cseq = fs.query()
            seqs[cons] = {
                "seq": cseq,
                "lag": None if prod is None else max(prod - cseq, 0),
            }
        links[lname] = {"produced": prod, "consumers": seqs}
    out["_links"] = links
    return out


def tile_links(topo) -> dict[str, dict]:
    return {
        name: {"ins": [ln for ln, _ in ts.ins], "outs": list(ts.outs)}
        for name, ts in topo.tiles.items()
    }


# ---------------------------------------------------------------------------
# the recorder


class FlightRecorder:
    """Watch a built (and usually supervised) in-process Topology;
    record black boxes; dump incident bundles on triggers.

    Usage:
        topo.enable_flight(); topo.enable_trace(...)   # before build
        sup = Supervisor(topo, ..., faults=inj)
        rec = FlightRecorder(topo, out_dir, slo=SloEngine(...),
                             faults=inj)
        rec.attach_supervisor(sup)
        sup.start(); rec.start()
        ...
        rec.stop(); sup.halt()
    """

    def __init__(
        self,
        topo,
        out_dir: str,
        slo=None,
        faults=None,
        poll_s: float = 0.05,
        name: str | None = None,
    ):
        assert topo.wksp is not None, "FlightRecorder needs a built topology"
        self.topo = topo
        self.out_dir = out_dir
        self.slo = slo
        self.faults = faults
        self.poll_s = poll_s
        self.name = name or topo.name or "fdt"
        self.incidents: list[str] = []
        self._seq = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._sup = None
        #: supervisor events are queued here and dumped by the WATCHER
        #: thread (next poll, <= poll_s later): bundle construction is
        #: snapshot + span decode + JSON I/O, far too slow for the
        #: supervisor watchdog's "callbacks must be fast" contract — a
        #: restart storm must not serialize restarts behind file writes
        self._pending: list[tuple[str, str | None, dict]] = []
        #: edge detectors
        self._dev_degraded: dict[tuple[str, int], int] = {}
        self._tile_degraded: dict[str, int] = {}
        self._slo_breached: dict[str, bool] = {}
        #: ingress load-shed level per tile (escalation-edge detector)
        self._shed_level: dict[str, int] = {}
        #: shared `shed` region (waltz/admission.py layout) — resolved
        #: lazily; the SLO engine's recommended level is written there
        #: as the quic tile's commanded floor
        self._shed_words = None
        os.makedirs(out_dir, exist_ok=True)

    # -- trigger wiring ---------------------------------------------------

    def attach_supervisor(self, sup) -> None:
        """Subscribe to the supervisor's failure events (restart /
        breaker / wedged become incident triggers)."""
        self._sup = sup
        sup.add_listener(self._on_supervisor_event)

    def _on_supervisor_event(self, tile: str, kind: str, detail: dict) -> None:
        # enqueue only — the watcher thread builds the bundle.  The
        # black boxes and span rings hold the state leading up to the
        # failure, so a <= poll_s dump delay loses nothing.
        with self._lock:
            self._pending.append((kind, tile, dict(detail)))

    def install_signal(self, signum=None) -> None:
        """Explicit-signal trigger: SIGUSR1 (or `signum`) dumps a
        bundle.  Must be called from the main thread."""
        import signal as _signal

        signum = _signal.SIGUSR1 if signum is None else signum
        _signal.signal(
            signum,
            lambda sn, frame: self.trigger("signal", detail={"signum": sn}),
        )

    def trigger(self, kind: str = "manual", tile: str | None = None,
                detail: dict | None = None) -> str:
        """Explicit incident dump; returns the bundle path."""
        return self._incident(kind, tile, detail or {})

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._watch, name="flight", daemon=True
        )
        self._thread.start()

    def stop(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None
        self._drain_pending()  # events that raced the shutdown

    # -- watcher ----------------------------------------------------------

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — diagnosis must not kill the host
                from firedancer_tpu.utils import log

                import traceback

                log.err("flight watcher error:\n%s", traceback.format_exc())

    def poll_once(self) -> None:
        """One watcher pass: queued supervisor events, box records,
        trigger edge detection.  Exposed for deterministic tests (no
        thread needed)."""
        self._drain_pending()
        snap = snapshot_topology(self.topo)
        self._write_boxes(snap)
        self._detect_quarantine(snap)
        self._detect_shed(snap)
        if self._sup is None:
            self._detect_degraded(snap)
        if self.slo is not None:
            self.slo.observe(snap)
            self.slo.evaluate()
            self._export_slo_gauges()
            self._command_shed(self.slo.recommended_shed_level())
            for name, breached in self.slo.breached_now.items():
                was = self._slo_breached.get(name, False)
                if breached and not was:
                    st = next(
                        s for s in self.slo._last if s.name == name
                    )
                    # cumulative breach count, incremented on the EDGE
                    # (the live per-SLO gauges clear when the windows
                    # quieten; this records that it happened)
                    m = self.topo._metrics.get("slo")
                    if m is not None:
                        m.inc("slo_breaches")
                    self._incident(
                        "slo", None,
                        {"slo": name, **st.to_dict()},
                    )
                self._slo_breached[name] = breached

    def _drain_pending(self) -> None:
        while True:
            with self._lock:
                if not self._pending:
                    return
                kind, tile, detail = self._pending.pop(0)
            self._incident(kind, tile, detail)

    def _write_boxes(self, snap: dict) -> None:
        boxes = getattr(self.topo, "_flightboxes", {})
        if not boxes:
            return
        ts_us = time.monotonic_ns() // 1000
        links = snap.get("_links", {})
        for name, box in boxes.items():
            row = snap.get(name)
            if row is None:
                continue
            c = row["counters"]
            rec = [ts_us] + [c.get(k, 0) for k in BOX_COUNTERS]
            ts = self.topo.tiles[name]
            for ln, _rel in ts.ins:
                li = links.get(ln, {})
                prod = li.get("produced") or 0
                cons = li.get("consumers", {}).get(name, {}).get("seq", 0)
                rec += [prod, cons]
            for ln in ts.outs:
                li = links.get(ln, {})
                prod = li.get("produced") or 0
                consumers = li.get("consumers", {})
                slowest = min(
                    (v["seq"] for v in consumers.values()), default=prod
                )
                rec += [prod, slowest]
            box.write(rec)

    def _detect_quarantine(self, snap: dict) -> None:
        for name, row in snap.items():
            if name == "_links":
                continue
            for i, dev in device_rows(row["counters"]).items():
                cur = int(bool(dev.get("degraded")))
                was = self._dev_degraded.get((name, i), 0)
                if cur and not was:
                    self._incident(
                        "quarantine", name,
                        {"device": i, "landed": dev.get("landed", 0),
                         "failed": dev.get("failed", 0)},
                    )
                self._dev_degraded[(name, i)] = cur

    def _detect_shed(self, snap: dict) -> None:
        """Ingress load-shed escalation edges (ISSUE 13): every UPWARD
        `shed_level` transition of a hardened ingress tile freezes an
        incident bundle — a flood that forced degradation is an
        incident with evidence attached, not just a counter blip.
        De-escalations are silent (recovery is the desired path)."""
        for name, row in snap.items():
            if name == "_links":
                continue
            c = row["counters"]
            if "shed_level" not in c:
                continue
            cur = int(c["shed_level"])
            was = self._shed_level.get(name, 0)
            if cur > was:
                self._incident(
                    "shed", name,
                    {"level": cur, "from": was,
                     "transitions": c.get("shed_transitions", 0)},
                )
            self._shed_level[name] = cur

    def _command_shed(self, level: int) -> None:
        """Write the SLO engine's recommended shed level into the shared
        `shed` region (the quic tile's commanded floor).  Words 0/1 are
        the recorder's; the tile owns words 2/3 (waltz/admission.py
        layout)."""
        from firedancer_tpu.waltz.admission import (
            SHED_FOOTPRINT, SHED_W_BURN, SHED_W_COMMANDED,
        )

        if self._shed_words is False:
            return  # no tile budgeted the region: latched off
        if self._shed_words is None:
            try:
                mem = self.topo.wksp.alloc("shared_shed", SHED_FOOTPRINT)
            except Exception:  # noqa: BLE001 — attached wksp cannot alloc
                # latch: the region will never appear mid-run, and
                # raising+catching once per poll is an exception storm
                self._shed_words = False
                return
            self._shed_words = mem[: (len(mem) // 8) * 8].view(np.uint64)
        self._shed_words[SHED_W_COMMANDED] = np.uint64(max(level, 0))
        burn = max(
            (s.burn_fast for s in self.slo._last), default=0.0
        )
        self._shed_words[SHED_W_BURN] = np.uint64(
            int(min(max(burn, 0.0), 1e6) * 1000)
        )

    def _detect_degraded(self, snap: dict) -> None:
        """Fallback breaker detection via the shared degraded gauge,
        for unsupervised/attached runs with no listener hook."""
        for name, row in snap.items():
            if name == "_links":
                continue
            cur = int(bool(row["counters"].get("degraded")))
            was = self._tile_degraded.get(name, 0)
            if cur and not was:
                self._incident(
                    "breaker", name,
                    {"restarts": row["counters"].get("restarts", 0)},
                )
            self._tile_degraded[name] = cur

    def _export_slo_gauges(self) -> None:
        m = self.topo._metrics.get("slo")
        if m is None:
            return
        gauges = self.slo.gauges()
        known = set(m.schema.counters)
        for k, v in gauges.items():
            if k in known:
                m.set(k, v)
        m.inc("slo_evaluations")

    # -- bundles ----------------------------------------------------------

    def _incident(self, kind: str, tile: str | None, detail: dict) -> str:
        with self._lock:
            self._seq += 1
            seq = self._seq
            bundle = self._build_bundle(kind, tile, detail, seq)
            path = os.path.join(
                self.out_dir, f"incident_{seq:04d}_{kind}.json"
            )
            # write-then-rename: bundle files appear atomically, so a
            # concurrent `fdtincident` scan never reads a partial doc
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(bundle, f, indent=1, sort_keys=True, default=int)
            os.replace(tmp, path)
            self.incidents.append(path)
        from firedancer_tpu.utils import log

        log.info(
            "flight: incident %s (%s%s) -> %s",
            seq, kind, f" tile={tile}" if tile else "", path,
        )
        return path

    def _build_bundle(
        self, kind: str, tile: str | None, detail: dict, seq: int
    ) -> dict:
        topo = self.topo
        snap = snapshot_topology(topo)
        tlinks = tile_links(topo)
        bundle: dict = {
            "version": 1,
            "id": f"{self.name}-{seq:04d}-{kind}",
            "seq": seq,
            "trigger": {
                "kind": kind,
                "tile": tile,
                "detail": detail,
                "ts_mono_us": time.monotonic_ns() // 1000,
                "wall_time": time.time(),
            },
            "topology": {
                "name": topo.name,
                "tiles": tlinks,
                "links": {
                    ln: {"depth": ls.depth, "mtu": ls.mtu,
                         "producer": ls.producer}
                    for ln, ls in topo.links.items()
                },
            },
        }
        if self.faults is not None:
            # process runtime: the children's durable fired flags fold
            # into the parent record first, so a bundle frozen by the
            # parent classifies identically under both runtimes
            self.faults.fold_topology(self.topo)
            bundle["faultinj"] = {
                "seed": self.faults.seed,
                "fired": [list(e) for e in self.faults.fired()],
            }
        if self.slo is not None:
            bundle["slo"] = self.slo.to_dict()
        # live reconfig/shed context (ISSUE 15): the elastic shard-map
        # epochs and the commanded shed level at freeze time, snapshot
        # into the bundle AND into per-tile state below — a postmortem
        # of a frag-loss or latency incident must answer "was a
        # membership flip or a shed escalation in flight?" without
        # correlating external logs
        shardmap = getattr(topo, "_shardmap", None)
        shard_groups = getattr(topo, "_shard_groups", {}) or {}
        elastic_kinds: dict = {}
        tile_elastic: dict[str, dict] = {}
        if shardmap is not None:
            for kind, grp in shard_groups.items():
                epoch = shardmap.epoch(grp["slot"])
                mask = shardmap.mask(grp["slot"])
                elastic_kinds[kind] = {
                    "epoch": epoch,
                    "active_mask": mask,
                    "producer": grp["producer"],
                }
                for j, member in enumerate(grp["members"]):
                    tile_elastic[member] = {
                        "kind": kind,
                        "epoch": epoch,
                        "active": bool((mask >> j) & 1),
                        "member_idx": j,
                    }
                if grp["producer"]:
                    tile_elastic.setdefault(
                        grp["producer"],
                        {"kind": kind, "epoch": epoch, "role": "producer"},
                    )
        if elastic_kinds:
            bundle["elastic"] = elastic_kinds
        shed_commanded = None
        if self._shed_words is None and topo.wksp is not None:
            # resolve the shared region READ-ONLY (it may exist even if
            # this recorder never commanded a shed — the quic tile
            # allocates it via ctx.shared): view(), never alloc() —
            # alloc is create-or-attach and would fabricate a zeroed
            # shed block in every bundle of a topology that has no shed
            # subsystem at all.  Leave None on a missing region so
            # _command_shed's False latch semantics stay its own.
            try:
                mem = topo.wksp.view("shared_shed")
                self._shed_words = (
                    mem[: (len(mem) // 8) * 8].view(np.uint64)
                )
            except KeyError:
                pass
        if self._shed_words is not None and self._shed_words is not False:
            from firedancer_tpu.waltz.admission import (
                SHED_W_COMMANDED, SHED_W_LEVEL, SHED_W_TRANSITIONS,
            )

            shed_commanded = int(self._shed_words[SHED_W_COMMANDED])
            bundle["shed"] = {
                "commanded": shed_commanded,
                "live_level": int(self._shed_words[SHED_W_LEVEL]),
                "transitions": int(self._shed_words[SHED_W_TRANSITIONS]),
            }
        tiles: dict = {}
        boxes = getattr(topo, "_flightboxes", {})
        for name, row in snap.items():
            if name == "_links":
                continue
            entry: dict = {
                "signal": row["signal"],
                "counters": row["counters"],
            }
            el = tile_elastic.get(name)
            if el is not None:
                entry["elastic"] = el
            # per-tile shed state: the tile's LOCAL level (its counters)
            # alongside the SLO engine's commanded floor — divergence
            # (local > commanded) means local backpressure escalated
            if "shed_level" in row["counters"] or shed_commanded:
                entry["shed"] = {
                    "level": row["counters"].get("shed_level", 0),
                    "commanded": shed_commanded or 0,
                }
            box = boxes.get(name)
            if box is not None:
                ins = tlinks[name]["ins"]
                outs = tlinks[name]["outs"]
                entry["flight"] = [
                    decode_box_record(r, ins, outs)
                    for r in box.read_all()
                ]
            tiles[name] = entry
        bundle["tiles"] = tiles
        bundle["rings"] = snap.get("_links", {})
        bundle["timeline"] = self._timeline()
        return bundle

    def _timeline(self) -> dict:
        """Last-N decoded span events per tile (needs enable_trace)."""
        cfg = getattr(self.topo, "flight", None) or FlightConfig()
        out: dict = {}
        for name, tracer in getattr(self.topo, "_tracers", {}).items():
            ring = tracer.ring
            c = ring.cursor()
            evs, _, _ = ring.read(max(c - cfg.timeline_n, 0))
            decoded = []
            for e in T.decode(evs):
                d = {
                    "kind": T.KIND_NAMES.get(e["kind"], str(e["kind"])),
                    "link": e["link"],
                    "ts": e["ts"],
                    "seq": e["seq"],
                    "sig": e["sig"],
                    "aux16": e["aux16"],
                    "aux64": e["aux64"],
                }
                if e["kind"] == T.FAULT:
                    d["fault"] = T.FAULT_NAMES.get(e["aux16"], "?")
                decoded.append(d)
            out[name] = decoded
        return out
