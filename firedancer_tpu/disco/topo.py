"""Declarative topology: links + tiles + workspace layout + a runner.

Reference model: src/disco/topo/fd_topo.h:28-230 (fd_topo_t = wksps,
links, tiles, objs; built by fd_topob_*) and fd_topo_run.c (join
workspaces → init → run loop).  The reference runs each tile as a
sandboxed process over hugetlbfs shared memory; this build's default
runner is one thread per tile over one process-local workspace (the
reference's own tests use exactly this shape, e.g.
src/disco/dedup/test_dedup.c:654-660), with the same objects working
cross-process when the workspace is named (/dev/shm-backed, see
tango.rings.Workspace).

Fail-stop supervision mirrors run/run.c:264-270: any tile failure halts
the whole topology.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from firedancer_tpu.tango import rings as R

from .metrics import Metrics, MetricsSchema
from .mux import InLink, MuxCtx, OutLink, Tile, link_hist_names, run_loop
from .trace import SpanRing, TraceConfig, Tracer


def device_assignments(spec, n_tiles: int) -> list[list[int]]:
    """Partition a `verify_devices` spec (auto | N | [ordinals]) across
    n_tiles seq-sharded verify replicas.

    Each replica gets a DISJOINT device-ordinal list so two workers
    never contend for one accelerator (the reference pins each
    wiredancer lane to one FPGA slot for the same reason).  With fewer
    devices than replicas the devices are shared round-robin — valid,
    just contended.  "auto" probes the jax local-device inventory AT
    BUILD TIME (the partition needs the count), which initializes and
    freezes the backend — a caller that must control the platform
    (the forced virtual CPU mesh) calls ensure_cpu_devices() first;
    host-only topologies should pass an explicit spec, not "auto".
    """
    assert n_tiles >= 1
    if spec in (None, 1, "off"):
        return [[0] for _ in range(n_tiles)]
    if spec == "auto":
        from firedancer_tpu.utils.hostdev import local_device_count

        indices = list(range(local_device_count()))
    elif isinstance(spec, int):
        indices = list(range(max(spec, 1)))
    else:
        indices = [int(d) for d in spec] or [0]
    if len(indices) < n_tiles:
        return [[indices[i % len(indices)]] for i in range(n_tiles)]
    return [indices[i::n_tiles] for i in range(n_tiles)]


@dataclass
class LinkSpec:
    name: str
    depth: int
    mtu: int = 0  # 0 = metadata-only link (no dcache)
    producer: str | None = None
    consumers: list[tuple[str, bool]] = field(default_factory=list)


@dataclass
class TileSpec:
    tile: Tile
    ins: list[tuple[str, bool]]  # (link name, reliable)
    outs: list[str]
    ctx: MuxCtx | None = None
    thread: threading.Thread | None = None
    error: BaseException | None = None


class Topology:
    """Build links and tiles, then run them on threads.

    Usage:
        topo = Topology()
        topo.link("synth_verify", depth=1024, mtu=1280)
        topo.tile(SynthTile(...), outs=["synth_verify"])
        topo.tile(VerifyTile(...), ins=[("synth_verify", True)], outs=[...])
        topo.start(); ...; topo.halt()
    """

    def __init__(
        self, name: str | None = None, trace: TraceConfig | None = None
    ):
        self.name = name
        self.links: dict[str, LinkSpec] = {}
        self.tiles: dict[str, TileSpec] = {}
        self.wksp: R.Workspace | None = None
        # sample <= 0 means OFF (TraceConfig contract) — normalize here
        # so build() installs no tracer regardless of which entry point
        # (constructor arg or enable_trace) carried the config in
        self.trace = trace if trace is not None and trace.sample > 0 else None
        #: run-loop profiling (disco/profile.py): None = off; set via
        #: enable_profile() before build()
        self.profile = None
        #: flight recorder black boxes (disco/flight.py): None = off;
        #: set via enable_flight() before build()
        self.flight = None
        #: asserted SLOs (disco/slo.py SloConfig): None = none asserted.
        #: When set before build(), a shared `slo` gauge region is
        #: allocated (metrics_registry()["slo"]) and the config rides
        #: the manifest so attached monitors evaluate the same SLOs.
        self.slo = None
        self._mcaches: dict[str, R.MCache] = {}
        self._dcaches: dict[str, R.DCache] = {}
        self._fseqs: dict[tuple[str, str], R.FSeq] = {}
        self._cncs: dict[str, R.CNC] = {}
        self._metrics: dict[str, Metrics] = {}
        self._schemas: dict[str, MetricsSchema] = {}
        self._tracers: dict[str, Tracer] = {}
        self._profilers: dict = {}
        self._flightboxes: dict = {}

    def enable_trace(self, sample: int = 64, depth: int = 1 << 14) -> None:
        """Turn on fdttrace span rings for every tile (must run before
        build()).  sample <= 0 disables — no tracer is installed and
        the hot path pays only the per-phase None checks."""
        assert self.wksp is None, "enable_trace before build()"
        self.trace = (
            TraceConfig(sample=sample, depth=depth) if sample > 0 else None
        )

    def enable_profile(self, on: bool = True) -> None:
        """Turn on the per-tile run-loop profiler (disco/profile.py):
        sampled wall/CPU phase attribution, GIL-wait fraction, and the
        scheduler-lag histogram, in per-tile workspace regions.  Must
        run before build(); off = one None check per loop hook."""
        assert self.wksp is None, "enable_profile before build()"
        self.profile = True if on else None

    def enable_flight(self, depth: int = 64, timeline_n: int = 256) -> None:
        """Allocate per-tile flight-recorder black boxes
        (disco/flight.py BlackBox) in the workspace.  Must run before
        build().  The boxes are written by a FlightRecorder's watcher
        thread, not by the tiles — enabling this costs the hot path
        nothing."""
        assert self.wksp is None, "enable_flight before build()"
        from .flight import FlightConfig

        self.flight = FlightConfig(depth=depth, timeline_n=timeline_n)

    # ---- declaration ----------------------------------------------------

    def link(self, name: str, depth: int, mtu: int = 0) -> None:
        assert name not in self.links, f"duplicate link {name!r}"
        self.links[name] = LinkSpec(name, depth, mtu)

    def tile(
        self,
        tile: Tile,
        ins: list[tuple[str, bool]] | None = None,
        outs: list[str] | None = None,
    ) -> None:
        name = tile.name
        assert name not in self.tiles, f"duplicate tile {name!r}"
        ins = list(ins or [])
        outs = list(outs or [])
        for ln, reliable in ins:
            self.links[ln].consumers.append((name, reliable))
        for ln in outs:
            spec = self.links[ln]
            assert spec.producer is None, f"link {ln!r} has two producers"
            spec.producer = name
        self.tiles[name] = TileSpec(tile, ins, outs)

    # ---- build ----------------------------------------------------------

    def _tile_schema(self, ts: TileSpec) -> MetricsSchema:
        """The tile's own schema + base + the per-in-link latency
        attribution hists (qwait/svc/e2e per consumed link) the run
        loop records.  Everything that reads a tile's metrics region —
        build, manifest export, monitor, metric tile — must agree on
        this one layout."""
        base = ts.tile.schema.with_base()
        link_hists = tuple(
            h for ln, _rel in ts.ins for h in link_hist_names(ln)
        )
        return MetricsSchema(base.counters, base.hists + link_hists)

    def _footprint(self) -> int:
        total = 4096
        for ls in self.links.values():
            total += R.MCache.footprint(ls.depth) + 256
            if ls.mtu:
                total += R.DCache.footprint(ls.mtu, ls.depth) + 256
            total += (R.FSeq.footprint() + 128) * max(len(ls.consumers), 1)
        for ts in self.tiles.values():
            total += R.CNC.footprint() + 128
            total += Metrics.footprint(self._tile_schema(ts)) + 256
            total += ts.tile.wksp_footprint() + 256
            if self.trace is not None:
                total += SpanRing.footprint(self.trace.depth) + 256
            if self.profile is not None:
                from .profile import PROFILE_SCHEMA

                total += Metrics.footprint(PROFILE_SCHEMA) + 256
            if self.flight is not None:
                from .flight import BlackBox, box_rec_words

                total += BlackBox.footprint(
                    self.flight.depth,
                    box_rec_words(len(ts.ins), len(ts.outs)),
                ) + 256
        if self.slo is not None:
            from .slo import slo_metrics_schema

            total += Metrics.footprint(slo_metrics_schema(self.slo)) + 256
        return total

    def build(self) -> None:
        assert self.wksp is None, "already built"
        self.wksp = R.Workspace(self._footprint(), name=self.name)
        for ls in self.links.values():
            self._mcaches[ls.name] = R.MCache.create(
                self.wksp, f"mc_{ls.name}", ls.depth
            )
            if ls.mtu:
                self._dcaches[ls.name] = R.DCache.create(
                    self.wksp, f"dc_{ls.name}", ls.mtu, ls.depth
                )
            for cons, _rel in ls.consumers:
                self._fseqs[(ls.name, cons)] = R.FSeq.create(
                    self.wksp, f"fs_{ls.name}_{cons}"
                )
        # link ids: declaration-order small ints, shared with the span
        # events (u8 field) and the manifest's id -> name table
        link_ids = {ln: i for i, ln in enumerate(self.links)}
        assert len(link_ids) <= 256, "span events carry a u8 link id"
        for name, ts in self.tiles.items():
            self._cncs[name] = R.CNC.create(self.wksp, f"cnc_{name}")
            schema = self._tile_schema(ts)
            self._schemas[name] = schema
            mem = self.wksp.alloc(f"metrics_{name}", Metrics.footprint(schema))
            self._metrics[name] = Metrics(mem, schema)
            if self.trace is not None:
                ring = SpanRing(
                    self.wksp.alloc(
                        f"trace_{name}", SpanRing.footprint(self.trace.depth)
                    ),
                    self.trace.depth,
                    self.trace.sample,
                )
                self._tracers[name] = Tracer(
                    ring, self.trace.sample, name=name
                )
            if self.profile is not None:
                from .profile import PROFILE_SCHEMA, TileProfiler

                pmem = self.wksp.alloc(
                    f"profile_{name}", Metrics.footprint(PROFILE_SCHEMA)
                )
                self._profilers[name] = TileProfiler(
                    Metrics(pmem, PROFILE_SCHEMA)
                )
            if self.flight is not None:
                from .flight import BlackBox, box_rec_words

                rw = box_rec_words(len(ts.ins), len(ts.outs))
                bmem = self.wksp.alloc(
                    f"flight_{name}",
                    BlackBox.footprint(self.flight.depth, rw),
                )
                self._flightboxes[name] = BlackBox(
                    bmem, self.flight.depth, rw
                )
        if self.slo is not None:
            from .slo import slo_metrics_schema

            sschema = slo_metrics_schema(self.slo)
            smem = self.wksp.alloc(
                "metrics_slo", Metrics.footprint(sschema)
            )
            # a pseudo-tile entry: the Prometheus metric tile renders it
            # as fdt_slo_* gauges; the flight recorder's watcher is the
            # single writer
            self._metrics["slo"] = Metrics(smem, sschema)
        for name, ts in self.tiles.items():
            tracer = self._tracers.get(name)
            ins = [
                InLink(
                    ln,
                    self._mcaches[ln],
                    self._dcaches.get(ln),
                    self._fseqs[(ln, name)],
                    reliable,
                    link_id=link_ids[ln],
                    h_qwait=f"qwait_us_{ln}",
                    h_svc=f"svc_us_{ln}",
                    h_e2e=f"e2e_us_{ln}",
                )
                for ln, reliable in ts.ins
            ]
            outs = [
                OutLink(
                    ln,
                    self._mcaches[ln],
                    self._dcaches.get(ln),
                    [
                        self._fseqs[(ln, cons)]
                        for cons, rel in self.links[ln].consumers
                        if rel
                    ],
                    link_id=link_ids[ln],
                    tracer=tracer,
                )
                for ln in ts.outs
            ]
            ts.ctx = MuxCtx(
                name, self._cncs[name], ins, outs, self._metrics[name],
                wksp=self.wksp,
            )
            ts.ctx.tracer = tracer
            ts.ctx.profiler = self._profilers.get(name)

    def export_manifest(self) -> None:
        """Publish the workspace directory + a monitor manifest (tile
        schemas, metrics/cnc alloc names, link fseq names) so a separate
        process can attach and observe (app/monitor.py).  No-op for
        anonymous (in-process) workspaces."""
        if self.wksp is None or self.wksp.name is None:
            return
        tiles = {}
        for name, ts in self.tiles.items():
            schema = self._schemas.get(name) or self._tile_schema(ts)
            tiles[name] = {
                "metrics": f"metrics_{name}",
                "cnc": f"cnc_{name}",
                "counters": list(schema.counters),
                "hists": list(schema.hists),
                "ins": [ln for ln, _rel in ts.ins],
                "outs": list(ts.outs),
            }
        links = {
            ls.name: {
                "depth": ls.depth,
                "mcache": f"mc_{ls.name}",
                "consumers": [
                    {"tile": cons, "fseq": f"fs_{ls.name}_{cons}"}
                    for cons, _rel in ls.consumers
                ],
            }
            for ls in self.links.values()
        }
        extra = {"tiles": tiles, "links": links}
        if self.trace is not None:
            # fdttrace attach surface: per-tile span ring alloc names +
            # the link id -> name table the u8 link field indexes
            extra["trace"] = {
                "sample": self.trace.sample,
                "depth": self.trace.depth,
                "links": list(self.links),
                "tiles": {name: f"trace_{name}" for name in self.tiles},
            }
        if self.profile is not None:
            # fdtflight attach surface: per-tile profiler regions
            extra["profile"] = {
                "tiles": {name: f"profile_{name}" for name in self.tiles},
            }
        if self.flight is not None:
            extra["flight"] = {
                "depth": self.flight.depth,
                "tiles": {name: f"flight_{name}" for name in self.tiles},
            }
        if self.slo is not None:
            # attached monitors evaluate the SAME objectives from the
            # same shared histograms (disco/slo.py SloEngine)
            extra["slo"] = {
                "config": self.slo.to_dict(),
                "metrics": "metrics_slo",
            }
        self.wksp.publish_directory(extra)

    # ---- run ------------------------------------------------------------

    def _tile_main(self, ts: TileSpec, loop_kw: dict) -> None:
        from firedancer_tpu.utils import log

        log.set_tile(ts.ctx.name)
        log.info("tile booting")
        try:
            run_loop(ts.tile, ts.ctx, **loop_kw)
            log.info("tile halted")
        except BaseException as e:  # noqa: BLE001 — fail-stop supervision
            import traceback

            log.err("tile failed: %r\n%s", e, traceback.format_exc())
            ts.error = e

    def start(self, boot_timeout_s: float = 600.0, **loop_kw) -> None:
        # default boot budget is generous: tile on_boot warms device
        # compile caches, and first compiles are slow (tens of seconds)
        if self.wksp is None:
            self.build()
        for name, ts in self.tiles.items():
            t = threading.Thread(
                target=self._tile_main, args=(ts, loop_kw), name=f"tile:{name}"
            )
            t.daemon = True
            ts.thread = t
            t.start()
        # wait for every tile to reach RUN (or fail during boot)
        deadline = time.monotonic() + boot_timeout_s
        for name, ts in self.tiles.items():
            while self._cncs[name].signal_query() == R.CNC_BOOT:
                if ts.error is not None:
                    self.halt()
                    raise ts.error
                if time.monotonic() > deadline:
                    self.halt()
                    raise TimeoutError(f"tile {name!r} stuck in BOOT")
                time.sleep(1e-3)
            if self._cncs[name].signal_query() == R.CNC_FAIL:
                # run_loop signals FAIL before the exception reaches
                # _tile_main, so give the error a moment to land
                if ts.thread is not None:
                    ts.thread.join(timeout=10.0)
                if not ts.ctx.booted:
                    # died DURING on_boot (bad config, missing device):
                    # that is a construction error — raise now.  A tile
                    # that reached RUN and then crashed (a race with
                    # fast-failing workloads) stays fail-stop via
                    # poll_failure, as before this supervision work.
                    self.halt()
                    if ts.error is not None:
                        raise ts.error
                    raise RuntimeError(
                        f"tile {name!r} failed during boot"
                    )
        # publish AFTER boot: tile on_boot workspace allocations (tcaches
        # etc.) must appear in the directory the monitor attaches to
        self.export_manifest()

    def poll_failure(self) -> None:
        """Fail-stop check: if any tile died, halt everything and re-raise."""
        for name, ts in self.tiles.items():
            if ts.error is not None:
                self.halt()
                raise RuntimeError(f"tile {name!r} failed") from ts.error

    def halt(self, timeout_s: float = 30.0) -> None:
        """Halt upstream-first so in-flight frags drain before consumers
        stop."""
        order = self._topo_order()
        for name in order:
            cnc = self._cncs.get(name)
            if cnc is None:
                continue
            cnc.signal(R.CNC_HALT)
            ts = self.tiles[name]
            if ts.thread is not None:
                ts.thread.join(timeout=timeout_s)

    def _topo_order(self) -> list[str]:
        """Tiles ordered producers-before-consumers (cycles broken by
        declaration order)."""
        order: list[str] = []
        seen: set[str] = set()

        def visit(name: str) -> None:
            if name in seen:
                return
            seen.add(name)
            for ln in self.tiles[name].ins:
                prod = self.links[ln[0]].producer
                if prod is not None and prod not in seen:
                    visit(prod)
            order.append(name)

        for name in self.tiles:
            visit(name)
        return order

    def metrics(self, tile_name: str) -> Metrics:
        return self._metrics[tile_name]

    def metrics_registry(self) -> dict[str, Metrics]:
        """Snapshot of every tile's Metrics (the metric tile's source)."""
        return dict(self._metrics)

    def profile_metrics(self) -> dict[str, Metrics]:
        """Per-tile profiler regions (disco/profile.py readers), empty
        when profiling is off."""
        return {name: p.m for name, p in self._profilers.items()}

    def close(self) -> None:
        if self.wksp is not None:
            self.wksp.unlink()
            self.wksp = None
