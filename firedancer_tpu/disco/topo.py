"""Declarative topology: links + tiles + workspace layout + a runner.

Reference model: src/disco/topo/fd_topo.h:28-230 (fd_topo_t = wksps,
links, tiles, objs; built by fd_topob_*) and fd_topo_run.c (join
workspaces → init → run loop).  The reference runs each tile as a
sandboxed PROCESS over hugetlbfs shared memory (fd_topo_run_tile_t);
this build supports both shapes over the same /dev/shm-backed objects:

  * runtime="thread" (default): one thread per tile in one interpreter
    — the shape the reference's own tests use (e.g.
    src/disco/dedup/test_dedup.c:654-660), bit-identical to the
    pre-process-runtime behavior, and what tier-1 runs.
  * runtime="process": one OS process per tile.  The parent builds the
    named workspace and publishes a boot manifest; each child
    re-attaches via tango.rings.Workspace.attach(), rebinds its
    mcache/dcache/fseq/cnc views and metrics/trace/profile regions by
    manifest name, and enters the same disco/mux.py run loop unchanged
    — the ring protocol is process-safe (fdtmc-verified, PR 3).  The
    control plane (boot acks, heartbeats, incarnation, boot-vs-run
    failure classification) lives entirely in shared-memory words
    (cnc + a per-tile pstat region), so the supervisor can watchdog,
    SIGKILL, and in-place restart a child with the same rejoin
    discipline as thread restarts.  This is what escapes the GIL:
    PROFILE.md round 8 measured ~94% of every tile's non-sleeping wall
    time as runnable-but-not-running in the threaded runtime.

Runtime selection: Topology(runtime=...) / start(mode=...) >
FDT_RUNTIME env > "thread".  Observer tiles that close over parent
state (metric/rpc) declare proc_safe=False and stay threads in the
parent even in process mode — they only read shared memory.

Fail-stop supervision mirrors run/run.c:264-270: any tile failure halts
the whole topology.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from firedancer_tpu.tango import rings as R

from .metrics import Metrics, MetricsSchema
from .mux import InLink, MuxCtx, OutLink, Tile, link_hist_names, run_loop
from .trace import SpanRing, TraceConfig, Tracer

#: per-tile process-control shm words ("pstat" region): the control
#: plane a child and its parent share beyond the cnc.  Single writer
#: per word: the parent owns INCARNATION (set before each spawn), the
#: child owns PID and BOOTED (its crash handler records whether
#: on_boot had completed, so the parent can classify FAIL as a
#: construction error vs a post-RUN crash without any Python-object
#: channel).
PSTAT_INCARNATION, PSTAT_PID, PSTAT_BOOTED = 0, 1, 2
#: elastic retirement (disco/elastic.py): the epoch a retired member
#: completed its drain at, mirrored here by the PARENT after it
#: observes the member's canonical drained marker in the shard-map
#: region (the region is the cross-runtime home; pstat exists only
#: under the process runtime)
PSTAT_DRAINED = 3
_PSTAT_BYTES = 64
#: per-tile faultinj cumulative-trigger state (TileFaults.bind_shm):
#: 2 counter words + up to 62 per-fault fired flags
_FSTAT_BYTES = 512

#: hot upgrade: serializes the scoped sys.path/FDT_SO_PATH mutation a
#: version-carrying spawn performs around Process.start (the spawn
#: method snapshots both into the child)
_SPAWN_ENV_LOCK = threading.Lock()


def _err_path(wksp_name: str, tile: str) -> str:
    """Child-crash report sidecar: the process analog of TileSpec.error
    (a traceback cannot cross the process boundary as an object)."""
    return f"/dev/shm/fdt_wksp_{wksp_name}.err_{tile}"


def _read_err(wksp_name: str | None, tile: str) -> str:
    if wksp_name is None:
        return ""
    try:
        with open(_err_path(wksp_name, tile)) as f:
            return f.read()[-4000:]
    except OSError:
        return ""


def device_assignments(spec, n_tiles: int) -> list[list[int]]:
    """Partition a `verify_devices` spec (auto | N | [ordinals]) across
    n_tiles seq-sharded verify replicas.

    Each replica gets a DISJOINT device-ordinal list so two workers
    never contend for one accelerator (the reference pins each
    wiredancer lane to one FPGA slot for the same reason).  With fewer
    devices than replicas the devices are shared round-robin — valid,
    just contended.  "auto" probes the jax local-device inventory AT
    BUILD TIME (the partition needs the count), which initializes and
    freezes the backend — a caller that must control the platform
    (the forced virtual CPU mesh) calls ensure_cpu_devices() first;
    host-only topologies should pass an explicit spec, not "auto".
    """
    assert n_tiles >= 1
    if spec in (None, 1, "off"):
        return [[0] for _ in range(n_tiles)]
    if spec == "auto":
        from firedancer_tpu.utils.hostdev import local_device_count

        indices = list(range(local_device_count()))
    elif isinstance(spec, int):
        indices = list(range(max(spec, 1)))
    else:
        indices = [int(d) for d in spec] or [0]
    if len(indices) < n_tiles:
        return [[indices[i % len(indices)]] for i in range(n_tiles)]
    return [indices[i::n_tiles] for i in range(n_tiles)]


@dataclass
class LinkSpec:
    name: str
    depth: int
    mtu: int = 0  # 0 = metadata-only link (no dcache)
    producer: str | None = None
    consumers: list[tuple[str, bool]] = field(default_factory=list)


@dataclass
class TileSpec:
    tile: Tile
    ins: list[tuple[str, bool]]  # (link name, reliable)
    outs: list[str]
    ctx: MuxCtx | None = None
    thread: threading.Thread | None = None
    #: process runtime: the tile's child process (multiprocessing
    #: handle).  None for thread tiles and proc_safe=False observers.
    proc: object | None = None
    error: BaseException | None = None
    #: elastic topology (disco/elastic.py): False = a PROVISIONED but
    #: inactive shard member — its rings/metrics/cnc exist (layout is
    #: fixed at build), but it is not spawned, supervised, or halted
    #: until Topology.add_shard activates it.  Its reliable in-fseqs
    #: are parked in the far seq future so producers never gate on it.
    active: bool = True
    #: hot code upgrade (fdt_upgrade): when set, this tile's NEXT
    #: process-runtime incarnation imports firedancer_tpu from this
    #: module tree (prepended to the child's sys.path at spawn) and/or
    #: loads this prebuilt native artifact (FDT_SO_PATH) instead of
    #: rebuilding.  None = the parent's own tree/.so.  Thread tiles
    #: cannot swap module trees (one interpreter); their hot upgrade is
    #: the mutate-based tile-object swap.
    version_root: str | None = None
    so_path: str | None = None


class UpgradeRefused(RuntimeError):
    """hot_upgrade pre-flight: the candidate version's ABI digest is
    neither the workspace word nor compat-approved — the running tile
    was NOT touched (zero downtime on refusal)."""

    def __init__(self, shm_digest: int, new_digest: int, tile: str):
        self.shm_digest = shm_digest
        self.new_digest = new_digest
        self.tile = tile
        super().__init__(
            f"hot upgrade of {tile!r} refused: candidate ABI digest "
            f"{new_digest:#018x} vs workspace {shm_digest:#018x} — "
            f"approve_version() it after an out-of-band compatibility "
            f"proof, or rebuild from a ring-compatible tree"
        )


class UpgradeRolledBack(RuntimeError):
    """hot_upgrade: the new-version incarnation failed to reach RUN;
    the tile was respawned on its OLD recipe (which reached RUN before
    this raised).  `cause` is the new version's boot failure."""

    def __init__(self, tile: str, cause: BaseException):
        self.tile = tile
        self.cause = cause
        super().__init__(
            f"hot upgrade of {tile!r} rolled back to the old "
            f"incarnation recipe: new version failed to boot ({cause!r})"
        )


class Topology:
    """Build links and tiles, then run them on threads.

    Usage:
        topo = Topology()
        topo.link("synth_verify", depth=1024, mtu=1280)
        topo.tile(SynthTile(...), outs=["synth_verify"])
        topo.tile(VerifyTile(...), ins=[("synth_verify", True)], outs=[...])
        topo.start(); ...; topo.halt()
    """

    def __init__(
        self,
        name: str | None = None,
        trace: TraceConfig | None = None,
        runtime: str | None = None,
        stem: str | None = None,
    ):
        self.name = name
        #: tile runtime: "thread" | "process" | None (resolve from the
        #: FDT_RUNTIME env at build/start).  Must be settled before
        #: build() — the process runtime adds workspace regions
        #: (per-tile arenas/pstat, per-dcache shm cursors).
        self.runtime = runtime
        #: data-plane inner loop: "python" | "native" | None (resolve
        #: from the FDT_STEM env at start).  "native" lets tiles with a
        #: registered native handler (Tile.native_handler) run their
        #: drain→handle→publish cycle in one GIL-released fdt_stem call
        #: per burst; tiles without one keep the Python loop either way.
        self.stem = stem
        self._runtime: str | None = None  # resolved at build()
        #: process runtime: fault-injection schedule that rides the
        #: spawn args so children reconstruct IDENTICAL injector
        #: behavior deterministically — (seed, [Fault, ...]).  Set by
        #: Supervisor.start (from its FaultInjector) or directly by
        #: chaos harnesses.
        self.faults_spec: tuple[int, list] | None = None
        #: loop kwargs captured at start() so the supervisor can
        #: respawn children with identical run-loop parameters
        self._loop_kw: dict = {}
        self.links: dict[str, LinkSpec] = {}
        self.tiles: dict[str, TileSpec] = {}
        self.wksp: R.Workspace | None = None
        # sample <= 0 means OFF (TraceConfig contract) — normalize here
        # so build() installs no tracer regardless of which entry point
        # (constructor arg or enable_trace) carried the config in
        self.trace = trace if trace is not None and trace.sample > 0 else None
        #: run-loop profiling (disco/profile.py): None = off; set via
        #: enable_profile() before build()
        self.profile = None
        #: flight recorder black boxes (disco/flight.py): None = off;
        #: set via enable_flight() before build()
        self.flight = None
        #: asserted SLOs (disco/slo.py SloConfig): None = none asserted.
        #: When set before build(), a shared `slo` gauge region is
        #: allocated (metrics_registry()["slo"]) and the config rides
        #: the manifest so attached monitors evaluate the same SLOs.
        self.slo = None
        #: elastic shard groups (disco/elastic.py): kind -> {"slot",
        #: "members" (tile names, member-index order), "producer",
        #: "base_active"}.  Declared via declare_shards() before build().
        self._shard_groups: dict[str, dict] = {}
        self._shardmap = None  # elastic.ShardMap, bound at build
        self._handshake = None  # handshake.Handshake, bound at build
        self._mcaches: dict[str, R.MCache] = {}
        self._dcaches: dict[str, R.DCache] = {}
        self._fseqs: dict[tuple[str, str], R.FSeq] = {}
        self._cncs: dict[str, R.CNC] = {}
        self._metrics: dict[str, Metrics] = {}
        self._schemas: dict[str, MetricsSchema] = {}
        self._tracers: dict[str, Tracer] = {}
        self._profilers: dict = {}
        self._flightboxes: dict = {}

    def enable_trace(self, sample: int = 64, depth: int = 1 << 14) -> None:
        """Turn on fdttrace span rings for every tile (must run before
        build()).  sample <= 0 disables — no tracer is installed and
        the hot path pays only the per-phase None checks."""
        assert self.wksp is None, "enable_trace before build()"
        self.trace = (
            TraceConfig(sample=sample, depth=depth) if sample > 0 else None
        )

    def enable_profile(self, on: bool = True) -> None:
        """Turn on the per-tile run-loop profiler (disco/profile.py):
        sampled wall/CPU phase attribution, GIL-wait fraction, and the
        scheduler-lag histogram, in per-tile workspace regions.  Must
        run before build(); off = one None check per loop hook."""
        assert self.wksp is None, "enable_profile before build()"
        self.profile = True if on else None

    def enable_flight(self, depth: int = 64, timeline_n: int = 256) -> None:
        """Allocate per-tile flight-recorder black boxes
        (disco/flight.py BlackBox) in the workspace.  Must run before
        build().  The boxes are written by a FlightRecorder's watcher
        thread, not by the tiles — enabling this costs the hot path
        nothing."""
        assert self.wksp is None, "enable_flight before build()"
        from .flight import FlightConfig

        self.flight = FlightConfig(depth=depth, timeline_n=timeline_n)

    # ---- declaration ----------------------------------------------------

    def link(self, name: str, depth: int, mtu: int = 0) -> None:
        assert name not in self.links, f"duplicate link {name!r}"
        self.links[name] = LinkSpec(name, depth, mtu)

    def tile(
        self,
        tile: Tile,
        ins: list[tuple[str, bool]] | None = None,
        outs: list[str] | None = None,
    ) -> None:
        name = tile.name
        assert name not in self.tiles, f"duplicate tile {name!r}"
        ins = list(ins or [])
        outs = list(outs or [])
        for ln, reliable in ins:
            self.links[ln].consumers.append((name, reliable))
        for ln in outs:
            spec = self.links[ln]
            assert spec.producer is None, f"link {ln!r} has two producers"
            spec.producer = name
        self.tiles[name] = TileSpec(tile, ins, outs)

    def declare_shards(
        self,
        kind: str,
        members: list[str],
        *,
        producer: str | None = None,
        producer_link: str | None = None,
        member_links: list[str] | None = None,
        active: int | None = None,
    ) -> None:
        """Declare an elastic shard group (disco/elastic.py): `members`
        are already-declared tiles, in member-index order; the first
        `active` (default: all) start live, the rest are PROVISIONED
        (rings and metrics built, fseqs parked) but not spawned until
        add_shard().

        producer/producer_link: the seq-sharded link's single producer
        tile and the link it writes — it appends flip-journal entries
        at every epoch it observes, which is what makes assignment a
        pure function of (seq, journal) across a membership flip.
        Omit both for producer-ASSIGNED kinds (bank shards: pack picks
        the out ring, so the mask alone gates the scheduler — pass
        `producer` without a link so it still acks epochs).

        member_links: each member's sharded in-link (default: the
        producer_link for every member — the quic_verify shape)."""
        from .elastic import MAX_KINDS, MAX_MEMBERS, ElasticBinding

        assert self.wksp is None, "declare_shards before build()"
        assert kind not in self._shard_groups, f"duplicate kind {kind!r}"
        assert len(members) <= MAX_MEMBERS
        slot = len(self._shard_groups)
        assert slot < MAX_KINDS
        n_active = len(members) if active is None else int(active)
        assert 1 <= n_active <= len(members)
        if member_links is None:
            member_links = [producer_link] * len(members)
        for i, name in enumerate(members):
            ts = self.tiles[name]
            assert getattr(ts.tile, "elastic", None) is None, (
                f"tile {name!r} already bound to a shard kind"
            )
            ts.tile.elastic = ElasticBinding(
                kind, slot, "member", index=i, link=member_links[i],
                base_active=n_active,
            )
            if i >= n_active:
                ts.active = False
        if producer is not None:
            pt = self.tiles[producer].tile
            assert getattr(pt, "elastic", None) is None, (
                f"tile {producer!r} already bound to a shard kind"
            )
            pt.elastic = ElasticBinding(
                kind, slot, "producer", link=producer_link,
                base_active=n_active,
            )
        self._shard_groups[kind] = {
            "slot": slot,
            "members": list(members),
            "producer": producer,
            "base_active": n_active,
        }

    def shardmap(self):
        """The built topology's elastic.ShardMap view (parent side)."""
        assert self._shardmap is not None, "no shard groups declared"
        return self._shardmap

    # ---- build ----------------------------------------------------------

    def _resolve_runtime(self, mode: str | None = None) -> str:
        rt = mode or self.runtime or os.environ.get("FDT_RUNTIME") or "thread"
        if rt not in ("thread", "process"):
            raise ValueError(
                f"unknown tile runtime {rt!r} (thread|process; from "
                f"start(mode=), Topology(runtime=), or FDT_RUNTIME)"
            )
        return rt

    def _resolve_stem(self, mode: str | None = None) -> str:
        sm = mode or self.stem or os.environ.get("FDT_STEM") or "python"
        if sm not in ("python", "native"):
            raise ValueError(
                f"unknown stem mode {sm!r} (python|native; from "
                f"start(stem=), Topology(stem=), or FDT_STEM)"
            )
        return sm

    @staticmethod
    def _spawn_method() -> str:
        """multiprocessing start method for tile children.  Default
        "spawn": a pristine interpreter per tile — no inherited GIL
        state, locks, or jax runtime; the child reconstructs everything
        from the manifest + pickled TileSpec, which is exactly what the
        fdtlint proc-safe-tile rule keeps honest.  FDT_SPAWN=fork opts
        into fork for import-cost-sensitive hosts (unsafe if the parent
        already initialized a device runtime)."""
        return os.environ.get("FDT_SPAWN", "spawn")

    def _tile_schema(self, ts: TileSpec) -> MetricsSchema:
        """The tile's own schema + base + the per-in-link latency
        attribution hists (qwait/svc/e2e per consumed link) the run
        loop records.  Everything that reads a tile's metrics region —
        build, manifest export, monitor, metric tile — must agree on
        this one layout.

        The link hists are WIDE (WIDE_HIST_BUCKETS + explicit overflow
        bucket, ISSUE 15): the 16-bucket domain capped every latency
        SLO at 2^16 µs (the documented fdtflight observability bound) —
        an e2e or queue-wait ceiling above 65.5 ms was rejected as
        unobservable.  slo.py derives its ceiling bound from the
        storage, so widening here lifts the bound to 2^24 µs (~16.8 s)
        with the overflow bucket catching the rest."""
        base = ts.tile.schema.with_base()
        link_hists = tuple(
            h for ln, _rel in ts.ins for h in link_hist_names(ln)
        )
        return MetricsSchema(
            base.counters, base.hists + link_hists,
            wide_hists=base.wide_hists + link_hists,
        )

    def _shared_regions(self) -> dict[str, int]:
        """Topology-wide shared regions declared by tiles
        (Tile.shared_wksp_footprints): {name: footprint}.  Tiles naming
        the same region must agree on its size — the whole point is
        that every bank shard maps ONE account table."""
        shared: dict[str, int] = {}
        for name, ts in self.tiles.items():
            for nm, fp in ts.tile.shared_wksp_footprints().items():
                if nm in shared and shared[nm] != fp:
                    raise ValueError(
                        f"shared region {nm!r}: tile {name!r} declares "
                        f"footprint {fp} but another tile declared "
                        f"{shared[nm]} (shards must agree)"
                    )
                shared[nm] = fp
        return shared

    def _footprint(self) -> int:
        from .handshake import HANDSHAKE_FOOTPRINT

        # version-handshake word region (every topology has one)
        total = 4096 + HANDSHAKE_FOOTPRINT + 256
        for fp in self._shared_regions().values():
            total += fp + 256
        for ls in self.links.values():
            total += R.MCache.footprint(ls.depth) + 256
            if ls.mtu:
                total += R.DCache.footprint(ls.mtu, ls.depth) + 256
            total += (R.FSeq.footprint() + 128) * max(len(ls.consumers), 1)
        for ts in self.tiles.values():
            total += R.CNC.footprint() + 128
            total += Metrics.footprint(self._tile_schema(ts)) + 256
            if not (self._runtime == "process" and ts.tile.proc_safe):
                # process-runtime children allocate tile state from
                # their arena (budgeted below), not the workspace —
                # budgeting both would double-size /dev/shm
                total += ts.tile.wksp_footprint() + 256
            if self.trace is not None:
                total += SpanRing.footprint(self.trace.depth) + 256
            if self.profile is not None:
                from .profile import PROFILE_SCHEMA

                total += Metrics.footprint(PROFILE_SCHEMA) + 256
            if self.flight is not None:
                from .flight import BlackBox, box_rec_words

                total += BlackBox.footprint(
                    self.flight.depth,
                    box_rec_words(len(ts.ins), len(ts.outs)),
                ) + 256
        if self.slo is not None:
            from .slo import slo_metrics_schema

            total += Metrics.footprint(slo_metrics_schema(self.slo)) + 256
        if self._shard_groups:
            from .elastic import SHARDMAP_FOOTPRINT, elastic_metrics_schema

            total += SHARDMAP_FOOTPRINT + 256
            total += Metrics.footprint(
                elastic_metrics_schema(list(self._shard_groups))
            ) + 256
        if self._runtime == "process":
            # process-runtime control plane + child-side allocation
            # arenas (ctx.alloc cannot bump an attached workspace).
            # proc_safe=False observers stay parent threads and use
            # none of it — budgeting theirs would just waste /dev/shm.
            for ls in self.links.values():
                if ls.mtu:
                    total += 64 + 128  # shm dcache cursor word
            for ts in self.tiles.values():
                if not ts.tile.proc_safe:
                    continue
                total += _PSTAT_BYTES + 128
                total += _FSTAT_BYTES + 128
                total += R.WkspArena.footprint(ts.tile.wksp_footprint())
                total += 256
        return total

    def build(self, runtime: str | None = None) -> None:
        assert self.wksp is None, "already built"
        self._runtime = self._resolve_runtime(runtime)
        if self._runtime == "process" and self.name is None:
            # children attach by name; auto-name anonymous topologies
            self.name = f"p{os.getpid()}_{os.urandom(3).hex()}"
        self.wksp = R.Workspace(self._footprint(), name=self.name)
        for ls in self.links.values():
            self._mcaches[ls.name] = R.MCache.create(
                self.wksp, f"mc_{ls.name}", ls.depth
            )
            if ls.mtu:
                self._dcaches[ls.name] = R.DCache.create(
                    self.wksp, f"dc_{ls.name}", ls.mtu, ls.depth
                )
            for cons, _rel in ls.consumers:
                self._fseqs[(ls.name, cons)] = R.FSeq.create(
                    self.wksp, f"fs_{ls.name}_{cons}"
                )
        if self._runtime == "process":
            # shm-backed dcache producer cursors: a restarted producer
            # CHILD must resume at its published chunk, not rewind to 0
            # over payloads in-flight frags still reference (thread
            # restarts keep the DCache object, so only the process
            # runtime needs the shared word)
            for ls in self.links.values():
                if ls.mtu:
                    self._dcaches[ls.name].bind_cursor(
                        self.wksp.alloc(f"dcur_{ls.name}", 64, align=64)
                    )
        # topology-wide shared regions (bank account table): allocated
        # HERE, before any tile boots and before the directory publish,
        # so process-runtime children can join them by name (an attached
        # workspace resolves, never allocates)
        for nm, fp in sorted(self._shared_regions().items()):
            self.wksp.alloc(f"shared_{nm}", fp)
        # version-handshake word (disco/handshake.py): written ONCE by
        # the building tree with its own ring-ABI digest, read by every
        # joining incarnation before it binds a ring.  Allocated before
        # any tile boots so process children can join it by name.
        from .handshake import HANDSHAKE_FOOTPRINT, Handshake

        self._handshake = Handshake(
            self.wksp.alloc("shared_handshake", HANDSHAKE_FOOTPRINT),
            join=False,
        )
        self._handshake.init(R.abi_digest())
        if self._shard_groups:
            # elastic shard map + gauge region: allocated before any
            # tile boots (children join both by name), initialized
            # before the first spawn so every epoch observer sees a
            # complete header
            from .elastic import (
                SHARDMAP_FOOTPRINT, ShardMap, elastic_metrics_schema,
            )

            self._shardmap = ShardMap(
                self.wksp.alloc("shared_shardmap", SHARDMAP_FOOTPRINT),
                join=False,
            )
            for kind, grp in self._shard_groups.items():
                mask = (1 << grp["base_active"]) - 1
                self._shardmap.init_kind(
                    grp["slot"], len(grp["members"]), mask
                )
            eschema = elastic_metrics_schema(list(self._shard_groups))
            emem = self.wksp.alloc(
                "metrics_elastic", Metrics.footprint(eschema)
            )
            # a pseudo-tile region like "slo": the metric tile renders
            # it as fdt_elastic_* gauges; parent-side reconfig code
            # (topology ops + ElasticController) is the single writer
            self._metrics["elastic"] = Metrics(emem, eschema)
            # park every inactive member's reliable in-fseqs in the far
            # seq future: cr_avail reads a consumer AHEAD of the
            # producer as fresh credit, so a provisioned-but-idle
            # member never backpressures the link, and activation lands
            # at the live head via consumer_rejoin's wrap-safe min
            for grp in self._shard_groups.values():
                for i, name in enumerate(grp["members"]):
                    if not self.tiles[name].active:
                        self._park_member_fseqs(name)
        # link ids: declaration-order small ints, shared with the span
        # events (u8 field) and the manifest's id -> name table
        link_ids = {ln: i for i, ln in enumerate(self.links)}
        assert len(link_ids) <= 256, "span events carry a u8 link id"
        for name, ts in self.tiles.items():
            self._cncs[name] = R.CNC.create(self.wksp, f"cnc_{name}")
            schema = self._tile_schema(ts)
            self._schemas[name] = schema
            mem = self.wksp.alloc(f"metrics_{name}", Metrics.footprint(schema))
            self._metrics[name] = Metrics(mem, schema)
            if self.trace is not None:
                ring = SpanRing(
                    self.wksp.alloc(
                        f"trace_{name}", SpanRing.footprint(self.trace.depth)
                    ),
                    self.trace.depth,
                    self.trace.sample,
                )
                self._tracers[name] = Tracer(
                    ring, self.trace.sample, name=name
                )
            if self.profile is not None:
                from .profile import PROFILE_SCHEMA, TileProfiler

                pmem = self.wksp.alloc(
                    f"profile_{name}", Metrics.footprint(PROFILE_SCHEMA)
                )
                self._profilers[name] = TileProfiler(
                    Metrics(pmem, PROFILE_SCHEMA)
                )
            if self.flight is not None:
                from .flight import BlackBox, box_rec_words

                rw = box_rec_words(len(ts.ins), len(ts.outs))
                bmem = self.wksp.alloc(
                    f"flight_{name}",
                    BlackBox.footprint(self.flight.depth, rw),
                )
                self._flightboxes[name] = BlackBox(
                    bmem, self.flight.depth, rw
                )
        if self._runtime == "process":
            for name, ts in self.tiles.items():
                if not ts.tile.proc_safe:
                    continue  # parent-thread observers use the wksp path
                self.wksp.alloc(f"pstat_{name}", _PSTAT_BYTES, align=64)
                # cumulative faultinj trigger state (ticks/frags/fired
                # flags) — survives child restarts so scripted faults
                # fire once, as in the threaded runtime
                self.wksp.alloc(f"fstat_{name}", _FSTAT_BYTES, align=64)
                self.wksp.alloc(
                    f"arena_{name}",
                    R.WkspArena.footprint(ts.tile.wksp_footprint()),
                )
        if self.slo is not None:
            from .slo import slo_metrics_schema

            sschema = slo_metrics_schema(self.slo)
            smem = self.wksp.alloc(
                "metrics_slo", Metrics.footprint(sschema)
            )
            # a pseudo-tile entry: the Prometheus metric tile renders it
            # as fdt_slo_* gauges; the flight recorder's watcher is the
            # single writer
            self._metrics["slo"] = Metrics(smem, sschema)
        for name, ts in self.tiles.items():
            tracer = self._tracers.get(name)
            ins = [
                InLink(
                    ln,
                    self._mcaches[ln],
                    self._dcaches.get(ln),
                    self._fseqs[(ln, name)],
                    reliable,
                    link_id=link_ids[ln],
                    h_qwait=f"qwait_us_{ln}",
                    h_svc=f"svc_us_{ln}",
                    h_e2e=f"e2e_us_{ln}",
                )
                for ln, reliable in ts.ins
            ]
            outs = [
                OutLink(
                    ln,
                    self._mcaches[ln],
                    self._dcaches.get(ln),
                    [
                        self._fseqs[(ln, cons)]
                        for cons, rel in self.links[ln].consumers
                        if rel
                    ],
                    link_id=link_ids[ln],
                    tracer=tracer,
                )
                for ln in ts.outs
            ]
            ts.ctx = MuxCtx(
                name, self._cncs[name], ins, outs, self._metrics[name],
                wksp=self.wksp,
            )
            ts.ctx.tracer = tracer
            ts.ctx.profiler = self._profilers.get(name)

    def export_manifest(self) -> None:
        """Publish the workspace directory + a monitor manifest (tile
        schemas, metrics/cnc alloc names, link fseq names) so a separate
        process can attach and observe (app/monitor.py).  No-op for
        anonymous (in-process) workspaces."""
        if self.wksp is None or self.wksp.name is None:
            return
        tiles = {}
        for name, ts in self.tiles.items():
            schema = self._schemas.get(name) or self._tile_schema(ts)
            tiles[name] = {
                "metrics": f"metrics_{name}",
                "cnc": f"cnc_{name}",
                "counters": list(schema.counters),
                "hists": list(schema.hists),
                # layout-affecting (wide hists store more buckets):
                # attached readers must reconstruct the same schema
                "wide_hists": list(schema.wide_hists),
                "ins": [ln for ln, _rel in ts.ins],
                "outs": list(ts.outs),
            }
        links = {
            ls.name: {
                "depth": ls.depth,
                "mcache": f"mc_{ls.name}",
                "consumers": [
                    {"tile": cons, "fseq": f"fs_{ls.name}_{cons}"}
                    for cons, _rel in ls.consumers
                ],
            }
            for ls in self.links.values()
        }
        extra = {"tiles": tiles, "links": links}
        # resolved stem mode (python|native): monitors key their
        # stem-coverage rows and the pinned-to-Python alarm off it
        extra["stem"] = self._loop_kw.get("stem") or self._resolve_stem()
        if self.trace is not None:
            # fdttrace attach surface: per-tile span ring alloc names +
            # the link id -> name table the u8 link field indexes
            extra["trace"] = {
                "sample": self.trace.sample,
                "depth": self.trace.depth,
                "links": list(self.links),
                "tiles": {name: f"trace_{name}" for name in self.tiles},
            }
        if self.profile is not None:
            # fdtflight attach surface: per-tile profiler regions
            extra["profile"] = {
                "tiles": {name: f"profile_{name}" for name in self.tiles},
            }
        if self.flight is not None:
            extra["flight"] = {
                "depth": self.flight.depth,
                "tiles": {name: f"flight_{name}" for name in self.tiles},
            }
        if self.slo is not None:
            # attached monitors evaluate the SAME objectives from the
            # same shared histograms (disco/slo.py SloEngine)
            extra["slo"] = {
                "config": self.slo.to_dict(),
                "metrics": "metrics_slo",
            }
        if self._shard_groups and self._shardmap is not None:
            # elastic attach surface: kinds, live membership, and the
            # gauge-region schema — REWRITTEN (atomic rename, see
            # publish_directory) on every add/retire so a child booting
            # mid-reconfig or an attached monitor never reads a torn
            # or stale membership table
            m = self._metrics.get("elastic")
            extra["elastic"] = {
                "metrics": "metrics_elastic",
                "counters": (
                    list(m.schema.counters) if m is not None else []
                ),
                "kinds": {
                    kind: {
                        "slot": grp["slot"],
                        "members": grp["members"],
                        "producer": grp["producer"],
                        "base_active": grp["base_active"],
                        "epoch": self._shardmap.epoch(grp["slot"]),
                        "active_mask": self._shardmap.mask(grp["slot"]),
                        "active": [
                            n
                            for j, n in enumerate(grp["members"])
                            if self.tiles[n].active
                            and (self._shardmap.mask(grp["slot"]) >> j)
                            & 1
                        ],
                    }
                    for kind, grp in self._shard_groups.items()
                },
            }
        if self._runtime == "process":
            extra["boot"] = self._boot_manifest()
        self.wksp.publish_directory(extra)

    def _boot_manifest(self) -> dict:
        """The child-side reconstruction contract: everything a spawned
        tile process needs to rebind its endpoints by name — link
        geometry (depth/mtu/ids, mcache/dcache/fseq alloc names, the
        shm dcache-cursor words), per-tile cnc/metrics/arena/pstat
        names, the flattened metrics schemas (including wide-hist
        widths — layout-affecting), and trace/profile enables.  Faultinj
        schedules and the replay window ride the spawn args instead
        (they are per-spawn, the manifest is per-build)."""
        link_ids = {ln: i for i, ln in enumerate(self.links)}
        links = {}
        for ls in self.links.values():
            links[ls.name] = {
                "id": link_ids[ls.name],
                "depth": ls.depth,
                "mtu": ls.mtu,
                "producer": ls.producer,
                "mcache": f"mc_{ls.name}",
                "dcache": f"dc_{ls.name}" if ls.mtu else None,
                "dcur": f"dcur_{ls.name}" if ls.mtu else None,
                "consumers": [
                    [cons, rel, f"fs_{ls.name}_{cons}"]
                    for cons, rel in ls.consumers
                ],
            }
        tiles = {}
        for name, ts in self.tiles.items():
            schema = self._schemas.get(name) or self._tile_schema(ts)
            proc = ts.tile.proc_safe  # observers have no child regions
            tiles[name] = {
                "ins": [[ln, rel] for ln, rel in ts.ins],
                "outs": list(ts.outs),
                "cnc": f"cnc_{name}",
                "metrics": f"metrics_{name}",
                "schema": {
                    "counters": list(schema.counters),
                    "hists": list(schema.hists),
                    "wide_hists": list(schema.wide_hists),
                },
                "arena": f"arena_{name}" if proc else None,
                "pstat": f"pstat_{name}" if proc else None,
                "fstat": f"fstat_{name}" if proc else None,
                "trace": f"trace_{name}" if self.trace is not None else None,
                "profile": (
                    f"profile_{name}" if self.profile is not None else None
                ),
                # hot upgrade: the module tree / native artifact the
                # NEXT incarnation of this tile runs (None = parent's)
                "version_root": ts.version_root,
                "so_path": ts.so_path,
            }
        return {
            "runtime": "process",
            "spawn": self._spawn_method(),
            "handshake": "shared_handshake",
            "links": links,
            "tiles": tiles,
            "trace": (
                {"sample": self.trace.sample, "depth": self.trace.depth}
                if self.trace is not None
                else None
            ),
        }

    # ---- run ------------------------------------------------------------

    def _tile_main(self, ts: TileSpec, loop_kw: dict) -> None:
        from firedancer_tpu.utils import log

        log.set_tile(ts.ctx.name)
        log.info("tile booting")
        try:
            run_loop(ts.tile, ts.ctx, **loop_kw)
            log.info("tile halted")
        except BaseException as e:  # noqa: BLE001 — fail-stop supervision
            import traceback

            log.err("tile failed: %r\n%s", e, traceback.format_exc())
            ts.error = e

    def start(
        self,
        boot_timeout_s: float = 600.0,
        mode: str | None = None,
        **loop_kw,
    ) -> None:
        # default boot budget is generous: tile on_boot warms device
        # compile caches, and first compiles are slow (tens of seconds)
        runtime = self._resolve_runtime(mode)
        if self.wksp is None:
            self.build(runtime=runtime)
        elif runtime != self._runtime:
            raise RuntimeError(
                f"topology built for runtime {self._runtime!r}; cannot "
                f"start as {runtime!r} (the process runtime changes the "
                f"workspace layout — set it before build())"
            )
        self._loop_kw = dict(loop_kw)
        # stem mode rides the loop kwargs: the same dict reaches thread
        # tiles, process children (spawn args) and supervisor respawns,
        # so every incarnation runs the same inner loop
        self._loop_kw["stem"] = self._resolve_stem(loop_kw.get("stem"))
        if runtime == "process":
            self._start_process(boot_timeout_s)
            return
        for name, ts in self.tiles.items():
            if ts.active:
                self._spawn_tile(name)
        # wait for every tile to reach RUN (or fail during boot)
        deadline = time.monotonic() + boot_timeout_s
        for name, ts in self.tiles.items():
            if not ts.active:
                continue
            while self._cncs[name].signal_query() == R.CNC_BOOT:
                if ts.error is not None:
                    self.halt()
                    raise ts.error
                if time.monotonic() > deadline:
                    self.halt()
                    raise TimeoutError(f"tile {name!r} stuck in BOOT")
                time.sleep(1e-3)
            if self._cncs[name].signal_query() == R.CNC_FAIL:
                # run_loop signals FAIL before the exception reaches
                # _tile_main, so give the error a moment to land
                if ts.thread is not None:
                    ts.thread.join(timeout=10.0)
                if not ts.ctx.booted:
                    # died DURING on_boot (bad config, missing device):
                    # that is a construction error — raise now.  A tile
                    # that reached RUN and then crashed (a race with
                    # fast-failing workloads) stays fail-stop via
                    # poll_failure, as before this supervision work.
                    self.halt()
                    if ts.error is not None:
                        raise ts.error
                    raise RuntimeError(
                        f"tile {name!r} failed during boot"
                    )
        # publish AFTER boot: tile on_boot workspace allocations (tcaches
        # etc.) must appear in the directory the monitor attaches to
        self.export_manifest()

    # ---- process runtime -------------------------------------------------

    def _start_process(self, boot_timeout_s: float) -> None:
        # publish BEFORE spawn: children reconstruct their endpoints
        # from the directory's boot manifest (child on_boot allocations
        # land in per-tile shm arenas, so no re-publish is needed for
        # monitors — the arena name tables live in shared memory)
        self.export_manifest()
        for name, ts in self.tiles.items():
            if ts.active:
                self._spawn_tile(name)
        deadline = time.monotonic() + boot_timeout_s
        for name, ts in self.tiles.items():
            if not ts.active:
                continue
            cnc = self._cncs[name]
            while cnc.signal_query() == R.CNC_BOOT:
                if ts.error is not None:  # proc_safe=False thread tile
                    self.halt()
                    raise ts.error
                p = ts.proc
                if p is not None and not p.is_alive():
                    # died before reaching RUN or FAIL (spawn/import
                    # crash): the err sidecar carries the traceback
                    err = _read_err(self.name, name)
                    rc = p.exitcode  # before halt() reaps/closes it
                    self.halt()
                    raise RuntimeError(
                        f"tile {name!r} process died during boot "
                        f"(exitcode {rc})"
                        + (f":\n{err}" if err else "")
                    )
                if time.monotonic() > deadline:
                    self.halt()
                    raise TimeoutError(f"tile {name!r} stuck in BOOT")
                time.sleep(1e-3)
            if cnc.signal_query() == R.CNC_FAIL:
                p = ts.proc
                if p is not None:
                    p.join(timeout=10.0)
                    booted = bool(self._pstat(name)[PSTAT_BOOTED])
                elif ts.thread is not None:
                    ts.thread.join(timeout=10.0)
                    booted = ts.ctx.booted
                else:
                    booted = False
                if not booted:
                    # construction error (bad config, missing device) —
                    # same classification as the thread runtime, read
                    # from the pstat shm word instead of ctx.booted
                    err = _read_err(self.name, name)
                    self.halt()
                    if ts.error is not None:
                        raise ts.error
                    raise RuntimeError(
                        f"tile {name!r} failed during boot"
                        + (f":\n{err}" if err else "")
                    )
        # re-publish after boot (atomic rename, safe under concurrent
        # attaches): parent-thread OBSERVER tiles' on_boot allocations
        # go to the workspace alloc table — the same post-boot
        # re-export invariant the thread runtime keeps.  Child-side
        # allocations need no re-export (arena name tables are in shm).
        self.export_manifest()

    def _pstat(self, name: str) -> np.ndarray:
        return self.wksp.view(f"pstat_{name}")[: 4 * 8].view(np.uint64)

    def tile_pid(self, name: str) -> int | None:
        """The tile's child pid (process runtime; None for threads)."""
        ts = self.tiles[name]
        if ts.proc is None:
            return None
        pid = int(self._pstat(name)[PSTAT_PID])
        return pid or ts.proc.pid

    def _spawn_tile(
        self, name: str, replay: int = 0, rejoin: bool | None = None
    ) -> None:
        """Spawn one tile in the resolved runtime (process children, or
        threads for proc_safe=False observers).  Shared by start() and
        the supervisor's restart path; `replay` is the reliable-link
        rejoin rewind the CHILD applies (tango.rings.consumer_rejoin)
        when its incarnation > 0.  `rejoin=True` forces the child-side
        ring rejoin even on a first incarnation — the elastic add_shard
        path, where a provisioned member's parked fseqs must resolve to
        the live producer head."""
        ts = self.tiles[name]
        ts.error = None
        if self._runtime != "process" or not ts.tile.proc_safe:
            t = threading.Thread(
                target=self._tile_main,
                args=(ts, self._loop_kw),
                name=f"tile:{name}",
            )
            t.daemon = True
            ts.thread = t
            t.start()
            return
        import multiprocessing as mp

        # fresh incarnation contract: parent owns the incarnation word,
        # child owns pid/booted — clear the child-owned words and the
        # stale crash report before the new incarnation starts
        pstat = self._pstat(name)
        pstat[PSTAT_INCARNATION] = np.uint64(ts.ctx.incarnation)
        pstat[PSTAT_PID] = 0
        pstat[PSTAT_BOOTED] = 0
        try:
            os.unlink(_err_path(self.name, name))
        except OSError:
            pass
        mpctx = mp.get_context(self._spawn_method())
        p = mpctx.Process(
            target=_tile_process_main,
            args=(
                self.name,
                name,
                ts.tile,
                self._loop_kw,
                ts.ctx.incarnation,
                replay,
                self.faults_spec,
                bool(rejoin) if rejoin is not None
                else ts.ctx.incarnation > 0,
            ),
            name=f"tile:{name}",
            daemon=True,
        )
        ts.proc = p
        if ts.version_root is None and ts.so_path is None:
            p.start()
            return
        # hot upgrade: the spawn method captures the parent's sys.path
        # in its preparation data and the environment at exec, so a
        # scoped mutation around start() is exactly "this child imports
        # firedancer_tpu from the new tree / loads the prebuilt .so".
        # Serialized: concurrent spawns must not see each other's tree.
        with _SPAWN_ENV_LOCK:
            import sys

            saved_env = os.environ.get("FDT_SO_PATH")
            if ts.version_root is not None:
                sys.path.insert(0, ts.version_root)
            if ts.so_path is not None:
                os.environ["FDT_SO_PATH"] = ts.so_path
            try:
                p.start()
            finally:
                if ts.version_root is not None:
                    sys.path.remove(ts.version_root)
                if ts.so_path is not None:
                    if saved_env is None:
                        os.environ.pop("FDT_SO_PATH", None)
                    else:
                        os.environ["FDT_SO_PATH"] = saved_env

    def _reap(self, ts: TileSpec, timeout_s: float) -> None:
        """Join a child with bounded escalation: HALT should have ended
        it; a survivor gets SIGTERM then SIGKILL, and the handle is
        always closed so no zombie outlives the topology (children that
        died mid-boot are reaped the same way — join on a dead process
        returns immediately)."""
        p = ts.proc
        if p is None:
            return
        p.join(timeout=timeout_s)
        if p.is_alive():
            p.terminate()
            p.join(timeout=5.0)
        if p.is_alive():
            p.kill()
            p.join(timeout=5.0)
        try:
            p.close()
        except ValueError:
            pass  # still alive after SIGKILL: unkillable (D-state); leak
        ts.proc = None

    def poll_failure(self) -> None:
        """Fail-stop check: if any tile died, halt everything and re-raise."""
        for name, ts in self.tiles.items():
            if not ts.active:
                continue
            if ts.error is not None:
                self.halt()
                raise RuntimeError(f"tile {name!r} failed") from ts.error
            p = ts.proc
            if p is None:
                continue
            sig = self._cncs[name].signal_query()
            if sig == R.CNC_FAIL or (sig == R.CNC_RUN and not p.is_alive()):
                err = _read_err(self.name, name)
                self.halt()
                raise RuntimeError(
                    f"tile {name!r} process failed"
                    + (f":\n{err}" if err else "")
                )

    # ---- elastic reconfiguration (disco/elastic.py) ----------------------

    def _park_member_fseqs(self, name: str) -> None:
        """Park an (inactive/reaped) member's reliable in-fseqs ahead of
        each producer so the link never gates on it; see build()."""
        from .elastic import PARK_OFFSET

        for ln, rel in self.tiles[name].ins:
            if not rel:
                continue
            fs = self._fseqs[(ln, name)]
            head = self._mcaches[ln].seq_query()
            fs.update(R.seq_u64(head + PARK_OFFSET))

    def _elastic_gauge(self, kind: str) -> None:
        m = self._metrics.get("elastic")
        if m is None or self._shardmap is None:
            return
        grp = self._shard_groups[kind]
        known = set(m.schema.counters)
        for key, v in (
            (f"{kind}_shards", self._shardmap.n_active(grp["slot"])),
            (f"{kind}_epoch", self._shardmap.epoch(grp["slot"])),
        ):
            if key in known:
                m.set(key, v)

    def _wait_run(self, name: str, timeout_s: float) -> None:
        """Wait for one (re)spawned tile to reach RUN; raise on a boot
        crash or timeout (the tile's error/err-sidecar attached).

        Deliberately NOT shared with start()'s boot-waits: those are
        fail-stop (any boot failure halts the WHOLE topology and
        classifies construction errors via pstat), while an elastic op
        failing to boot one member must leave the rest of the topology
        running and surface only its own error."""
        ts = self.tiles[name]
        cnc = self._cncs[name]
        deadline = time.monotonic() + timeout_s
        while cnc.signal_query() in (R.CNC_BOOT,):
            p = ts.proc
            if ts.error is not None:
                raise ts.error
            if p is not None and not p.is_alive():
                err = _read_err(self.name, name)
                raise RuntimeError(
                    f"tile {name!r} died during elastic boot"
                    + (f":\n{err}" if err else "")
                )
            if time.monotonic() > deadline:
                raise TimeoutError(f"tile {name!r} stuck in BOOT")
            time.sleep(1e-3)
        if cnc.signal_query() == R.CNC_FAIL:
            err = _read_err(self.name, name)
            if ts.error is not None:
                raise ts.error
            raise RuntimeError(
                f"tile {name!r} failed during elastic boot"
                + (f":\n{err}" if err else "")
            )

    def add_shard(
        self, kind: str, i: int | None = None, *, timeout_s: float = 300.0
    ) -> int:
        """Activate one provisioned member of an elastic shard group at
        RUNTIME: spawn its tile (thread or process), land its consumer
        cursors at the live producer head (consumer_rejoin unparks the
        far-future fseq), extend the boot manifest (atomic rename), and
        flip the shard-map epoch only AFTER the new member has rejoined
        its rings and reached RUN — so the first frag assigned to it
        finds it consuming.  Returns the member index."""
        grp = self._shard_groups[kind]
        smv = self.shardmap()
        mask = smv.mask(grp["slot"])
        if i is None:
            free = [
                j
                for j in range(len(grp["members"]))
                if not (mask >> j) & 1 and not self.tiles[
                    grp["members"][j]
                ].active
            ]
            if not free:
                raise RuntimeError(f"shard kind {kind!r}: no free member")
            i = free[0]
        name = grp["members"][i]
        ts = self.tiles[name]
        assert not ts.active and not (mask >> i) & 1, (
            f"member {name!r} already active"
        )
        ts.active = True
        is_proc = self._runtime == "process" and ts.tile.proc_safe
        try:
            if is_proc:
                # the CHILD rejoins at boot (rejoin=True even on the
                # first incarnation): consumer_rejoin reads the parked
                # fseq and lands at the producer head
                self._spawn_tile(name, rejoin=True)
            else:
                from .supervisor import rejoin_links

                rejoin_links(ts.ctx.ins, ts.ctx.outs, replay=0)
                self._spawn_tile(name)
            self._wait_run(name, timeout_s)
        except BaseException:
            ts.active = False
            self._park_member_fseqs(name)
            raise
        # flip AFTER the member is live: the producer's next burst
        # boundary appends the flip entry, and every seq it governs
        # lands on a consuming member
        smv.flip(grp["slot"], mask | (1 << i))
        self._elastic_gauge(kind)
        self.export_manifest()
        return i

    def retire_shard(
        self,
        kind: str,
        i: int,
        *,
        timeout_s: float = 300.0,
        replay: int = 0,
    ) -> None:
        """Retire one active member: drain -> handover -> reap.  The
        epoch flips first (no new seqs are assigned past the flip
        entry); the member then drains its in-flight window and
        publishes a DRAINED marker (the epoch) in the shard map; only
        then is it halted and reaped, its fseqs parked so the producer
        never gates on the corpse.  A member that dies mid-drain (chaos
        SIGKILL) is respawned — ring rejoin + `replay`, the crash-
        restart machinery — until the drain completes: the same
        zero-loss/zero-dup bar as crashes."""
        grp = self._shard_groups[kind]
        smv = self.shardmap()
        name = grp["members"][i]
        ts = self.tiles[name]
        assert ts.active and (smv.mask(grp["slot"]) >> i) & 1, (
            f"member {name!r} not active"
        )
        ep = smv.flip(grp["slot"], smv.mask(grp["slot"]) & ~(1 << i))
        self._elastic_gauge(kind)
        self.export_manifest()
        deadline = time.monotonic() + timeout_s
        while smv.drained(grp["slot"], i) < ep:
            if time.monotonic() > deadline:
                # ROLL BACK: the member is still running and was never
                # reaped — re-admit it under a fresh epoch so the mask
                # and ts.active stay consistent (a half-retired member
                # would otherwise wedge every future scale-out of this
                # kind) and surface the failure to the caller
                smv.flip(grp["slot"], smv.mask(grp["slot"]) | (1 << i))
                self._elastic_gauge(kind)
                self.export_manifest()
                raise TimeoutError(
                    f"member {name!r} failed to drain for epoch {ep}; "
                    f"membership rolled back"
                )
            self._revive_if_dead(name, replay)
            time.sleep(2e-3)
        # drained: deliberate halt (on_halt runs; halt-ack -> BOOT)
        self._cncs[name].signal(R.CNC_HALT)
        if ts.proc is not None:
            self._reap(ts, timeout_s=30.0)
        elif ts.thread is not None:
            ts.thread.join(timeout=30.0)
        ts.active = False
        if self._runtime == "process" and ts.tile.proc_safe:
            # observability mirror: the drained epoch into the pstat
            # words (the parent owns this word; the member's canonical
            # marker lives in the shard-map region, which works in both
            # runtimes)
            pstat = self._pstat(name)
            pstat[PSTAT_DRAINED] = np.uint64(ep)
        self._park_member_fseqs(name)
        self._elastic_gauge(kind)
        self.export_manifest()

    def _respawn_incarnation(
        self, name: str, replay: int, *, crashed: bool
    ) -> None:
        """The one reincarnation recipe shared by the elastic paths
        (mid-drain crash revival, rolling restart): thread-runtime ring
        rejoin with the standard skip accounting (process children
        rejoin themselves at boot), incarnation bump, BOOT signal,
        respawn.  `crashed` adds the crash-only steps (on_crash
        cleanup, the restarts counter) that a clean halt skips."""
        ts = self.tiles[name]
        ctx = ts.ctx
        is_proc = self._runtime == "process" and ts.tile.proc_safe
        if not is_proc:
            from .supervisor import rejoin_links

            metrics = self._metrics[name]

            def _account_skip(il, skipped):
                metrics.inc("overrun_frags", skipped)
                il.fseq.diag_add(0, skipped)

            rejoin_links(
                ctx.ins, ctx.outs, replay=replay, on_skip=_account_skip
            )
            if crashed:
                ts.tile.on_crash(ctx)
        ctx.interrupt.clear()
        ctx.booted = False
        ctx.incarnation += 1
        if crashed:
            self._metrics[name].inc("restarts")
        self._cncs[name].signal(R.CNC_BOOT)
        self._spawn_tile(name, replay=replay)

    def _revive_if_dead(self, name: str, replay: int) -> None:
        """Mid-drain crash recovery for a deliberately-retiring member
        (the supervisor stands back during commanded ops): respawn the
        dead incarnation through the ordinary rejoin path so the drain
        completes exactly-once."""
        ts = self.tiles[name]
        sig = self._cncs[name].signal_query()
        died = (
            not ts.proc.is_alive()
            if ts.proc is not None
            else ts.thread is not None and not ts.thread.is_alive()
        )
        if not died and sig != R.CNC_FAIL:
            return
        if ts.proc is not None:
            self._reap(ts, timeout_s=10.0)
        elif ts.thread is not None:
            ts.thread.join(timeout=10.0)
        self._respawn_incarnation(name, replay, crashed=True)

    def rolling_restart(
        self,
        name: str,
        *,
        mutate=None,
        replay: int = 0,
        timeout_s: float = 300.0,
    ) -> None:
        """Deliberately restart one tile under traffic: halt (on_halt
        drains), reap, optionally apply a config mutation to the tile
        object (`mutate(tile)` — the respawn pickles the mutated tile
        into the new child, which is what makes config reload and code
        hot-swap first-class), rejoin the rings, respawn, wait for RUN.
        Exactly-once across the restart rides the same replay +
        surviving-dedup discipline as crash restarts."""
        ts = self.tiles[name]
        assert ts.active, f"tile {name!r} is not active"
        cnc = self._cncs[name]
        cnc.signal(R.CNC_HALT)
        if ts.proc is not None:
            self._reap(ts, timeout_s=30.0)
        elif ts.thread is not None:
            ts.thread.join(timeout=30.0)
            ts.thread = None
        if mutate is not None:
            mutate(ts.tile)
        self._respawn_incarnation(name, replay, crashed=False)
        self._wait_run(name, timeout_s)
        self.export_manifest()

    # ---- hot code upgrade (fdt_upgrade) ---------------------------------

    def handshake(self):
        """The workspace's version-handshake view (disco/handshake.py),
        bound at build()."""
        assert self._handshake is not None, "build() first"
        return self._handshake

    def approve_version(self, digest: int) -> None:
        """Admit a foreign ABI digest into the workspace compat table —
        the operator's out-of-band ring-compatibility proof.  Joining
        incarnations carrying it pass the handshake thereafter."""
        self.handshake().approve(digest)

    def hot_upgrade(
        self,
        name: str,
        *,
        version_root: str | None = None,
        so_path: str | None = None,
        digest: int | None = None,
        mutate=None,
        replay: int = 0,
        timeout_s: float = 300.0,
    ) -> None:
        """Rolling restart into NEW CODE behind the same rings.

        Pre-flight: the candidate version's ring-ABI digest (`digest`
        if given, else probed via handshake.probe_digest — identity
        versions answer in-process) must be proven compatible with the
        workspace handshake word BEFORE the running tile is touched; a
        mismatch raises UpgradeRefused with both digests and zero
        downtime.  Accepted: halt → reap → stamp the version onto the
        tile spec (the next incarnation imports firedancer_tpu from
        `version_root` and loads `so_path`, see _spawn_tile) → mutate →
        respawn → wait RUN.  A new-version boot failure rolls back to
        the OLD recipe (old version fields, pre-mutate tile snapshot
        where picklable), respawns it, and raises UpgradeRolledBack —
        commanded-then-rollback, not a crash streak (the supervisor's
        breaker never sees it when bracketed via
        ElasticController.hot_upgrade).

        `version_root`/`so_path` are process-runtime contracts (one
        interpreter cannot swap module trees): thread tiles hot-upgrade
        via `mutate` swapping the tile object, still digest-gated.
        """
        ts = self.tiles[name]
        assert ts.active, f"tile {name!r} is not active"
        is_proc = self._runtime == "process" and ts.tile.proc_safe
        if (version_root is not None or so_path is not None) and not is_proc:
            raise ValueError(
                f"tile {name!r} runs in-process: version_root/so_path "
                f"need a process-runtime child (use mutate for a "
                f"thread-tile code swap)"
            )
        if digest is None:
            from .handshake import probe_digest

            digest = probe_digest(version_root, so_path)
        hs = self.handshake()
        if not hs.compatible(digest):
            raise UpgradeRefused(hs.digest(), digest, name)
        # snapshot the old recipe for rollback (tile snapshot is
        # best-effort: an unpicklable tile rolls back version fields
        # only, keeping the mutated object)
        import pickle

        old_version = (ts.version_root, ts.so_path)
        try:
            old_tile = pickle.dumps(ts.tile)
        except Exception:  # noqa: BLE001 — thread tiles may hold locks
            old_tile = None
        cnc = self._cncs[name]
        cnc.signal(R.CNC_HALT)
        if ts.proc is not None:
            self._reap(ts, timeout_s=30.0)
        elif ts.thread is not None:
            ts.thread.join(timeout=30.0)
            ts.thread = None
        if version_root is not None or so_path is not None:
            ts.version_root, ts.so_path = version_root, so_path
        try:
            if mutate is not None:
                mutate(ts.tile)
            self._respawn_incarnation(name, replay, crashed=False)
            self._wait_run(name, timeout_s)
        except BaseException as cause:  # noqa: BLE001 — rollback then raise
            if ts.proc is not None:
                self._reap(ts, timeout_s=10.0)
            elif ts.thread is not None:
                ts.thread.join(timeout=10.0)
                ts.thread = None
            ts.version_root, ts.so_path = old_version
            if old_tile is not None:
                ts.tile = pickle.loads(old_tile)
            self._respawn_incarnation(name, replay, crashed=False)
            self._wait_run(name, timeout_s)
            self.export_manifest()
            raise UpgradeRolledBack(name, cause) from cause
        self.export_manifest()

    def halt(self, timeout_s: float = 30.0) -> None:
        """Halt upstream-first so in-flight frags drain before consumers
        stop.  Process children are reaped with bounded SIGTERM→SIGKILL
        escalation (a child that died mid-boot is reaped the same way),
        so repeated bench runs never accumulate zombies."""
        order = self._topo_order()
        for name in order:
            cnc = self._cncs.get(name)
            if cnc is None or not self.tiles[name].active:
                continue
            cnc.signal(R.CNC_HALT)
            ts = self.tiles[name]
            if ts.proc is not None:
                self._reap(ts, timeout_s)
            if ts.thread is not None:
                ts.thread.join(timeout=timeout_s)

    def _topo_order(self) -> list[str]:
        """Tiles ordered producers-before-consumers (cycles broken by
        declaration order)."""
        order: list[str] = []
        seen: set[str] = set()

        def visit(name: str) -> None:
            if name in seen:
                return
            seen.add(name)
            for ln in self.tiles[name].ins:
                prod = self.links[ln[0]].producer
                if prod is not None and prod not in seen:
                    visit(prod)
            order.append(name)

        for name in self.tiles:
            visit(name)
        return order

    def metrics(self, tile_name: str) -> Metrics:
        return self._metrics[tile_name]

    def metrics_registry(self) -> dict[str, Metrics]:
        """Snapshot of every tile's Metrics (the metric tile's source)."""
        return dict(self._metrics)

    def profile_metrics(self) -> dict[str, Metrics]:
        """Per-tile profiler regions (disco/profile.py readers), empty
        when profiling is off."""
        return {name: p.m for name, p in self._profilers.items()}

    def tile_alloc_view(self, tile: str, name: str) -> np.ndarray:
        """Resolve a tile's ctx.alloc region by name from the PARENT
        (tests, benches): the per-tile shm arena in the process
        runtime, the workspace alloc table in the threaded one, the
        ctx-local buffer for anonymous thread topologies."""
        key = f"{tile}_{name}"
        if self._runtime == "process" and self.tiles[tile].tile.proc_safe:
            # join=True: read-only attach — never initialize the header
            # (that is the owning child's job; racing it would corrupt
            # the name table)
            return R.WkspArena(
                self.wksp.view(f"arena_{tile}"), join=True
            ).view(key)
        if self.wksp is not None and key in self.wksp._allocs:
            return self.wksp.view(key)
        return self.tiles[tile].ctx._local_allocs[key]

    def close(self) -> None:
        # reap stragglers first (failed starts, children dead mid-boot):
        # unlinking shm under a live child is POSIX-safe but the zombie
        # and its err sidecar must not outlive the topology
        for ts in self.tiles.values():
            if ts.proc is not None:
                self._reap(ts, timeout_s=1.0)
        if self.wksp is not None:
            self.wksp.unlink()
            self.wksp = None


# ---------------------------------------------------------------------------
# process-runtime child entrypoint
#
# Runs in a FRESH interpreter (spawn) or forked child: re-attach the named
# workspace, rebind every endpoint by boot-manifest name, rebuild the
# MuxCtx, rejoin the rings if this is a re-incarnation, and enter the
# SAME run loop the threaded runtime uses — the ring protocol itself is
# process-safe (fdtmc-verified), so nothing below the ctx changes.


def _tile_process_main(
    wksp_name: str,
    tile_name: str,
    tile: Tile,
    loop_kw: dict,
    incarnation: int,
    replay: int,
    faults_spec: tuple | None,
    rejoin: bool | None = None,
) -> None:
    import sys
    import traceback

    from firedancer_tpu.utils import log

    log.set_tile(tile_name)
    err_path = _err_path(wksp_name, tile_name)
    ctx = None
    cnc = None
    pstat = None
    try:
        ws, extra = R.Workspace.attach(wksp_name)
        boot = extra["boot"]
        links = boot["links"]
        t = boot["tiles"][tile_name]
        pstat = ws.view(t["pstat"])[: 4 * 8].view(np.uint64)
        pstat[PSTAT_PID] = os.getpid()
        # version handshake (disco/handshake.py): prove THIS
        # incarnation's ring-ABI digest against the workspace word
        # BEFORE binding a single ring — a mixed-version join is either
        # digest/compat-proven or refused right here (HandshakeRefused
        # lands in the err sidecar with both digests; exit code 2, a
        # construction failure).  The ring-handshake-rebind lint rule
        # pins that this check precedes the link construction below.
        if boot.get("handshake") is not None:
            from .handshake import check_join

            check_join(
                ws.view(boot["handshake"]), R.abi_digest(), tile=tile_name
            )
        mcaches: dict[str, R.MCache] = {}
        dcaches: dict[str, R.DCache] = {}

        def _mc(ln: str) -> R.MCache:
            if ln not in mcaches:
                mcaches[ln] = R.MCache(
                    ws.view(links[ln]["mcache"]), links[ln]["depth"],
                    join=True,
                )
            return mcaches[ln]

        def _dc(ln: str, producer: bool = False) -> R.DCache | None:
            spec = links[ln]
            if spec["dcache"] is None:
                return None
            if ln not in dcaches:
                dcaches[ln] = R.DCache(
                    ws.view(spec["dcache"]), spec["mtu"], spec["depth"]
                )
            dc = dcaches[ln]
            if producer and spec["dcur"] is not None:
                dc.bind_cursor(ws.view(spec["dcur"]))
            return dc

        cnc = R.CNC(ws.view(t["cnc"]), join=True)
        sch = t["schema"]
        schema = MetricsSchema(
            counters=tuple(sch["counters"]),
            hists=tuple(sch["hists"]),
            wide_hists=tuple(sch.get("wide_hists", ())),
        )
        metrics = Metrics(ws.view(t["metrics"]), schema)
        tracer = None
        if boot.get("trace") is not None and t["trace"] is not None:
            ring = SpanRing(ws.view(t["trace"]), join=True)
            tracer = Tracer(ring, boot["trace"]["sample"], name=tile_name)
        profiler = None
        if t["profile"] is not None:
            from .profile import PROFILE_SCHEMA, TileProfiler

            profiler = TileProfiler(
                Metrics(ws.view(t["profile"]), PROFILE_SCHEMA)
            )
        ins = [
            InLink(
                ln,
                _mc(ln),
                _dc(ln),
                R.FSeq(
                    ws.view(
                        next(
                            c[2]
                            for c in links[ln]["consumers"]
                            if c[0] == tile_name
                        )
                    ),
                    join=True,
                ),
                bool(rel),
                link_id=links[ln]["id"],
                h_qwait=f"qwait_us_{ln}",
                h_svc=f"svc_us_{ln}",
                h_e2e=f"e2e_us_{ln}",
            )
            for ln, rel in t["ins"]
        ]
        outs = [
            OutLink(
                ln,
                _mc(ln),
                _dc(ln, producer=True),
                [
                    R.FSeq(ws.view(c[2]), join=True)
                    for c in links[ln]["consumers"]
                    if c[1]
                ],
                link_id=links[ln]["id"],
                tracer=tracer,
            )
            for ln in t["outs"]
        ]
        ctx = MuxCtx(tile_name, cnc, ins, outs, metrics, wksp=ws)
        ctx.tracer = tracer
        ctx.profiler = profiler
        ctx.arena = R.WkspArena(ws.view(t["arena"]))
        ctx.incarnation = incarnation
        if faults_spec is not None:
            from .faultinj import FaultInjector

            seed, faults = faults_spec
            tf = FaultInjector(seed=seed, faults=faults).view(tile_name)
            # cumulative trigger state lives in shm so a restarted
            # incarnation does not re-fire already-fired faults
            tf.bind_shm(ws.view(t["fstat"]))
            ctx.faults = tf
        if rejoin if rejoin is not None else incarnation > 0:
            # ring rejoin runs IN the child (the dead incarnation's seqs
            # live in the shm fseqs/mcaches, so the repair is derivable
            # here) — same helper, and the same loss accounting, as the
            # thread runtime's supervisor-side rejoin.  An elastic
            # add_shard spawn forces rejoin on a FIRST incarnation: the
            # member's parked fseq resolves to the live producer head.
            from .supervisor import rejoin_links

            def _account_skip(il, skipped):
                metrics.inc("overrun_frags", skipped)
                il.fseq.diag_add(0, skipped)

            rejoin_links(
                ctx.ins, ctx.outs, replay=replay, on_skip=_account_skip
            )
        run_loop(tile, ctx, **loop_kw)
    except BaseException:  # noqa: BLE001 — fail-stop, reported via shm
        try:
            with open(err_path, "w") as f:
                f.write(traceback.format_exc())
        except OSError:
            pass
        booted = bool(ctx is not None and ctx.booted)
        if pstat is not None:
            pstat[PSTAT_BOOTED] = 1 if booted else 0
        # run_loop signals FAIL for its own exceptions; cover crashes
        # before/outside it so the parent's cnc wait always resolves
        if cnc is not None and cnc.signal_query() != R.CNC_FAIL:
            cnc.signal(R.CNC_FAIL)
        log.err("tile process failed: see %s", err_path)
        # exit code mirrors the thread runtime's boot/run classification
        sys.exit(2 if not booted else 1)
    else:
        if pstat is not None:
            pstat[PSTAT_BOOTED] = 1
