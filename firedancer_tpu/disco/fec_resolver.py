"""FEC set resolver: incoming shreds → validated, recovered entry data.

Behavior contract: src/disco/shred/fd_fec_resolver.c — for each
(slot, fec_set_idx) in flight: check every arriving shred's merkle proof
against the set's root (all shreds of a set commit to one root, carried
implicitly by proofs), reject mismatches, and once data_cnt distinct
shreds of the set are held, Reed-Solomon-recover the missing data shreds
and release the reassembled payload.  The root is established by the
first valid shred; the leader's signature over it is checked once per
set (host oracle here; the shred tile batches signature checks on the
device like verify does).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from firedancer_tpu.ballet import bmtree as BM
from firedancer_tpu.ballet import shred as SH
from firedancer_tpu.ops import reedsol as RS


def shred_merkle_root(s: SH.Shred, raw: bytes) -> bytes | None:
    """Recompute the set's merkle root from one shred's leaf + proof."""
    depth = SH.merkle_cnt(s.variant)
    cov_parity = 1115 - 20 * depth + SH.DATA_HEADER_SZ - 0x40
    if s.is_data:
        leaf_bytes = raw[0x40 : 0x40 + cov_parity]
        leaf_idx = s.idx - s.fec_set_idx
    else:
        leaf_bytes = raw[0x40 : SH.CODE_HEADER_SZ + cov_parity]
        assert s.data_cnt is not None
        leaf_idx = s.data_cnt + s.code_idx
    node = bytes(BM.hash_leaves([leaf_bytes], 20)[0])
    k = leaf_idx
    for sib in s.merkle_nodes:
        pair = [node, sib] if k % 2 == 0 else [sib, node]
        node = bytes(
            BM._merge_layer(
                np.stack([np.frombuffer(p, np.uint8) for p in pair]), 20
            )[0]
        )
        k >>= 1
    return node


@dataclass
class _SetState:
    root: bytes | None = None
    data: dict[int, bytes] = field(default_factory=dict)  # leaf idx -> raw
    parity: dict[int, bytes] = field(default_factory=dict)
    data_cnt: int | None = None
    parity_cnt: int | None = None
    done: bool = False


@dataclass
class FecSetResult:
    slot: int
    fec_set_idx: int
    data_shreds: list[bytes]  # raw wire bytes, recovered where needed
    payload: bytes  # concatenated entry-batch bytes
    recovered_cnt: int


class FecResolver:
    def __init__(self, *, verify_sig=None, max_in_flight: int = 1024):
        """verify_sig(sig, root, slot) -> bool, or None to skip (the
        tile layer batches these on device)."""
        self.verify_sig = verify_sig
        self.max_in_flight = max_in_flight
        self.sets: dict[tuple[int, int], _SetState] = {}
        self.rejected = 0

    def add_shred(self, raw: bytes) -> FecSetResult | None:
        s = SH.parse(raw)
        if s is None or not SH.merkle_cnt(s.variant):
            self.rejected += 1
            return None
        key = (s.slot, s.fec_set_idx)
        st = self.sets.get(key)
        if st is None:
            if len(self.sets) >= self.max_in_flight:
                # evict the oldest in-flight set (reference uses a small
                # LRU pool of in-progress sets)
                self.sets.pop(next(iter(self.sets)))
            st = self.sets[key] = _SetState()
        if st.done:
            return None

        root = shred_merkle_root(s, raw)
        if root is None:
            self.rejected += 1
            return None
        if st.root is None:
            if self.verify_sig is not None and not self.verify_sig(
                s.signature, root, s.slot
            ):
                self.rejected += 1
                return None
            st.root = root
        elif root != st.root:
            self.rejected += 1
            return None

        if s.is_data:
            st.data[s.idx - s.fec_set_idx] = raw
        else:
            st.data_cnt = s.data_cnt
            st.parity_cnt = s.code_cnt
            st.parity[s.code_idx] = raw

        return self._try_complete(key, st)

    def _try_complete(self, key, st: _SetState) -> FecSetResult | None:
        slot, fec_set_idx = key
        # complete via all data shreds (no parity needed): only possible
        # when a parity shred told us data_cnt, or the batch-complete flag
        # bounds the set
        if st.data_cnt is None:
            d = self._data_cnt_from_flags(st)
            if d is not None:
                st.data_cnt = d
        if st.data_cnt is None:
            return None
        if len(st.data) + len(st.parity) < st.data_cnt:
            return None

        depth = tree_depth = None
        any_raw = next(iter(st.data.values()), None) or next(
            iter(st.parity.values())
        )
        depth = SH.merkle_cnt(any_raw[0x40])
        cov = 1115 - 20 * depth + SH.DATA_HEADER_SZ - 0x40
        d_cnt = st.data_cnt
        p_cnt = st.parity_cnt if st.parity_cnt is not None else 0
        total = d_cnt + p_cnt

        recovered = 0
        if len(st.data) < d_cnt:
            # Reed-Solomon recovery over the covered regions
            mat = np.zeros((total, cov), np.uint8)
            present = np.zeros(total, bool)
            for i, raw in st.data.items():
                mat[i] = np.frombuffer(raw[0x40 : 0x40 + cov], np.uint8)
                present[i] = True
            for j, raw in st.parity.items():
                mat[d_cnt + j] = np.frombuffer(
                    raw[SH.CODE_HEADER_SZ : SH.CODE_HEADER_SZ + cov], np.uint8
                )
                present[d_cnt + j] = True
            out = RS.recover(mat, present, d_cnt)
            if out is None:
                return None
            for i in range(d_cnt):
                if i not in st.data:
                    raw = bytearray(SH.MIN_SZ)
                    raw[0x40 : 0x40 + cov] = out[i].tobytes()
                    # signature + proof are not reconstructable (they are
                    # outside the RS-covered region); zero is fine for
                    # replay since the set root was already authenticated
                    st.data[i] = bytes(raw)
                    recovered += 1

        data_shreds = [st.data[i] for i in range(d_cnt)]
        payload = bytearray()
        for raw in data_shreds:
            s = SH.parse(raw)
            if s is not None:
                payload += s.payload
            else:
                # recovered shred without proof bytes: parse just the
                # data header region
                import struct

                _, _, size = struct.unpack_from("<HBH", raw, 0x53)
                payload += raw[SH.DATA_HEADER_SZ : size]
        st.done = True
        self.sets.pop(key, None)
        return FecSetResult(slot, fec_set_idx, data_shreds, bytes(payload), recovered)

    @staticmethod
    def _data_cnt_from_flags(st: _SetState) -> int | None:
        """If the batch/slot-complete shred is present and all indices
        below it too, the data count is its index + 1."""
        for i, raw in st.data.items():
            flags = raw[0x55]
            if flags & (SH.FLAG_DATA_COMPLETE | SH.FLAG_SLOT_COMPLETE):
                if all(k in st.data for k in range(i + 1)):
                    return i + 1
        return None
