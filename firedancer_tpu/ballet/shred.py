"""Shred (block fragment) wire-format parser and builder.

Behavior contract: src/ballet/shred/fd_shred.{h,c} — 1228-byte max
packets: common header (signature 64B, variant, slot u64, idx u32,
version u16, fec_set_idx u32 at fixed offsets), then a data header
(parent_off u16, flags u8, size u16) or coding header (data_cnt u16,
code_cnt u16, idx u16), payload, zero padding, and for merkle variants a
trailing inclusion-proof of 20-byte nodes ending at byte 1203
(FD_SHRED_MIN_SZ).  Validation mirrors fd_shred_parse exactly.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

MAX_SZ = 1228
MIN_SZ = 1203
DATA_HEADER_SZ = 0x58
CODE_HEADER_SZ = 0x59

TYPE_LEGACY_DATA = 0xA0
TYPE_LEGACY_CODE = 0x50
TYPE_MERKLE_DATA = 0x80
TYPE_MERKLE_CODE = 0x40
TYPEMASK_DATA = TYPE_MERKLE_DATA
TYPEMASK_CODE = TYPE_MERKLE_CODE
TYPEMASK_LEGACY = 0x30

MERKLE_NODE_SZ = 20

FLAG_SLOT_COMPLETE = 0x80
FLAG_DATA_COMPLETE = 0x40
REF_TICK_MASK = 0x3F


def shred_type(variant: int) -> int:
    return variant & 0xF0


def merkle_cnt(variant: int) -> int:
    if shred_type(variant) & TYPEMASK_LEGACY:
        return 0
    return variant & 0x0F


def header_sz(variant: int) -> int:
    t = shred_type(variant)
    if t in (TYPE_MERKLE_DATA, TYPE_LEGACY_DATA):
        return DATA_HEADER_SZ
    if t in (TYPE_MERKLE_CODE, TYPE_LEGACY_CODE):
        return CODE_HEADER_SZ
    return 0


@dataclass(frozen=True)
class Shred:
    signature: bytes
    variant: int
    slot: int
    idx: int
    version: int
    fec_set_idx: int
    # data header (None for code shreds)
    parent_off: int | None = None
    flags: int | None = None
    size: int | None = None
    # code header (None for data shreds)
    data_cnt: int | None = None
    code_cnt: int | None = None
    code_idx: int | None = None
    payload: bytes = b""
    merkle_nodes: tuple[bytes, ...] = ()

    @property
    def is_data(self) -> bool:
        return bool(shred_type(self.variant) & TYPEMASK_DATA)

    @property
    def ref_tick(self) -> int:
        assert self.flags is not None
        return self.flags & REF_TICK_MASK


def parse(buf: bytes) -> Shred | None:
    """fd_shred_parse behavior: returns None on any malformation."""
    sz = len(buf)
    if sz < DATA_HEADER_SZ:
        return None
    variant = buf[0x40]
    t = shred_type(variant)
    if not (
        t == TYPE_MERKLE_DATA
        or t == TYPE_MERKLE_CODE
        or variant == 0xA5
        or variant == 0x5A
    ):
        return None
    hsz = header_sz(variant)
    proof_sz = merkle_cnt(variant) * MERKLE_NODE_SZ

    signature = buf[0:0x40]
    slot, idx, version, fec_set_idx = struct.unpack_from("<QIHI", buf, 0x41)

    if t & TYPEMASK_DATA:
        parent_off, flags, data_size = struct.unpack_from("<HBH", buf, 0x53)
        if data_size < hsz:
            return None
        payload_sz = data_size - hsz
        if t != TYPE_LEGACY_DATA and sz < MIN_SZ:
            return None
        effective_sz = MIN_SZ if t == TYPE_MERKLE_DATA else sz
        if effective_sz < hsz + proof_sz + payload_sz:
            return None
        zero_padding_sz = effective_sz - hsz - proof_sz - payload_sz
        if sz < hsz + payload_sz + zero_padding_sz + proof_sz:
            return None
        payload = buf[hsz : hsz + payload_sz]
        nodes = _proof_nodes(buf, t, proof_sz, sz)
        return Shred(
            signature, variant, slot, idx, version, fec_set_idx,
            parent_off=parent_off, flags=flags, size=data_size,
            payload=payload, merkle_nodes=nodes,
        )

    # code shred
    if hsz + proof_sz > MAX_SZ:
        return None
    payload_sz = MAX_SZ - hsz - proof_sz
    if sz < hsz + payload_sz + proof_sz:
        return None
    data_cnt, code_cnt, code_idx = struct.unpack_from("<HHH", buf, 0x53)
    payload = buf[hsz : hsz + payload_sz]
    nodes = _proof_nodes(buf, t, proof_sz, sz)
    return Shred(
        signature, variant, slot, idx, version, fec_set_idx,
        data_cnt=data_cnt, code_cnt=code_cnt, code_idx=code_idx,
        payload=payload, merkle_nodes=nodes,
    )


def _proof_nodes(buf: bytes, t: int, proof_sz: int, sz: int) -> tuple[bytes, ...]:
    if not proof_sz:
        return ()
    # merkle proof lives in [MIN_SZ - proof, MIN_SZ) for data shreds and
    # [MAX_SZ - proof, MAX_SZ) for code shreds (fd_shred.c comment)
    end = MIN_SZ if t == TYPE_MERKLE_DATA else MAX_SZ
    region = buf[end - proof_sz : end]
    return tuple(
        region[i : i + MERKLE_NODE_SZ]
        for i in range(0, proof_sz, MERKLE_NODE_SZ)
    )


def build_merkle_data(
    slot: int,
    idx: int,
    version: int,
    fec_set_idx: int,
    parent_off: int,
    flags: int,
    payload: bytes,
    merkle_nodes: list[bytes],
    signature: bytes = b"\0" * 64,
) -> bytes:
    """Serialize a merkle data shred (fixed MIN_SZ wire size)."""
    proof_sz = len(merkle_nodes) * MERKLE_NODE_SZ
    data_size = DATA_HEADER_SZ + len(payload)
    assert DATA_HEADER_SZ + len(payload) + proof_sz <= MIN_SZ
    variant = TYPE_MERKLE_DATA | len(merkle_nodes)
    out = bytearray(MIN_SZ)
    out[0:0x40] = signature
    out[0x40] = variant
    struct.pack_into("<QIHI", out, 0x41, slot, idx, version, fec_set_idx)
    struct.pack_into("<HBH", out, 0x53, parent_off, flags, data_size)
    out[DATA_HEADER_SZ : DATA_HEADER_SZ + len(payload)] = payload
    off = MIN_SZ - proof_sz
    for node in merkle_nodes:
        assert len(node) == MERKLE_NODE_SZ
        out[off : off + MERKLE_NODE_SZ] = node
        off += MERKLE_NODE_SZ
    return bytes(out)


def build_merkle_code(
    slot: int,
    idx: int,
    version: int,
    fec_set_idx: int,
    data_cnt: int,
    code_cnt: int,
    code_idx: int,
    payload: bytes,
    merkle_nodes: list[bytes],
    signature: bytes = b"\0" * 64,
) -> bytes:
    """Serialize a merkle coding shred (fixed MAX_SZ wire size)."""
    proof_sz = len(merkle_nodes) * MERKLE_NODE_SZ
    payload_sz = MAX_SZ - CODE_HEADER_SZ - proof_sz
    assert len(payload) == payload_sz, (len(payload), payload_sz)
    variant = TYPE_MERKLE_CODE | len(merkle_nodes)
    out = bytearray(MAX_SZ)
    out[0:0x40] = signature
    out[0x40] = variant
    struct.pack_into("<QIHI", out, 0x41, slot, idx, version, fec_set_idx)
    struct.pack_into("<HHH", out, 0x53, data_cnt, code_cnt, code_idx)
    out[CODE_HEADER_SZ : CODE_HEADER_SZ + payload_sz] = payload
    off = MAX_SZ - proof_sz
    for node in merkle_nodes:
        out[off : off + MERKLE_NODE_SZ] = node
        off += MERKLE_NODE_SZ
    return bytes(out)
