"""X25519 Diffie-Hellman (RFC 7748), host-side python-int Montgomery ladder.

The reference keeps X25519 beside Ed25519 (/root/reference/src/ballet/
ed25519/fd_x25519.c, behavior contract only).  Here it serves the TLS 1.3
handshake — control-plane work at handshake rates, so a constant-structure
(single fixed ladder, no data-dependent branches at the group level)
python-int implementation is the right tool; the batch TPU field kernels
are reserved for the verify data plane.
"""

from __future__ import annotations

P = 2**255 - 19
_A24 = 121665
BASE_POINT = (9).to_bytes(32, "little")


def _decode_scalar(k: bytes) -> int:
    assert len(k) == 32
    b = bytearray(k)
    b[0] &= 248
    b[31] &= 127
    b[31] |= 64
    return int.from_bytes(bytes(b), "little")


def _decode_u(u: bytes) -> int:
    assert len(u) == 32
    n = int.from_bytes(u, "little")
    return (n & ((1 << 255) - 1)) % P


def x25519(k: bytes, u: bytes) -> bytes:
    """Scalar-multiply: shared = k * u.  Returns 32-byte u-coordinate."""
    k_int = _decode_scalar(k)
    x1 = _decode_u(u)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in range(254, -1, -1):
        k_t = (k_int >> t) & 1
        if swap ^ k_t:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % P
        aa = a * a % P
        b = (x2 - z2) % P
        bb = b * b % P
        e = (aa - bb) % P
        c = (x3 + z3) % P
        d = (x3 - z3) % P
        da = d * a % P
        cb = c * b % P
        x3 = (da + cb) % P
        x3 = x3 * x3 % P
        z3 = (da - cb) % P
        z3 = x1 * (z3 * z3 % P) % P
        x2 = aa * bb % P
        z2 = e * ((aa + _A24 * e) % P) % P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    out = x2 * pow(z2, P - 2, P) % P
    return out.to_bytes(32, "little")


def public_key(secret: bytes) -> bytes:
    return x25519(secret, BASE_POINT)
