"""Block-packing scheduler — the TPU-native redesign of ballet/pack.

Reference model: /root/reference/src/ballet/pack/fd_pack.c — a treap of
pending txns ordered by reward/cost priority, account-conflict detection
via a hybrid bitset/hashmap (fd_pack_bitset.h), per-account write cost
caps, block CU budgets, and greedy microblock scheduling
(fd_pack_schedule_microblock_impl, fd_pack.c:742-953).

Deliberate redesign (SURVEY.md §7 phase 8): the data structures are dense
numpy arrays instead of intrusive treaps/maps —
  * priority ordering: argsort over the pending set per scheduling pass
    (pack emits microblocks every ~2ms; an O(P log P) vector sort at that
    cadence is cheaper than maintaining pointer structures in Python, and
    is batch/device-friendly)
  * conflict detection: pure bitset over `nbits` hashed account bits with
    NO exact-account fallback — hash collisions cause false-positive
    conflicts, never false negatives, so schedules stay correct and at
    worst a colliding txn waits for the next microblock (the reference's
    own bitset fast path has the same one-sided property; divergence: we
    skip its exact slow path entirely, trading rare spurious delay for a
    data-parallel test)
  * per-account writer cost caps are keyed by 64-bit account-key hashes
    (fdt_pack.c wc map), not exact keys — collisions merge cost buckets,
    which can only UNDER-admit (never violate the consensus cap); the
    reference keeps exact keys in a treap-side map
  * the hot paths (batch parse + estimate, greedy select + commit, lock
    release) are ONE native call each (tango/native/fdt_pack.c, GIL
    released): the Python layer does slot bookkeeping and policy only
  * the greedy select can also run on the device as a lax.scan prefilter
    over the top-K candidates (ops/pack_select.py); this engine commits
    the device's speculative picks through the same native commit path

Consensus constants (fd_pack.h:17-23) are preserved exactly.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from firedancer_tpu.tango import rings as R

from . import compute_budget as CB
from . import txn as T

MAX_COST_PER_BLOCK = 48_000_000
MAX_VOTE_COST_PER_BLOCK = 36_000_000
MAX_WRITE_COST_PER_ACCT = 12_000_000
FEE_PER_SIGNATURE = 5000
MAX_BANK_TILES = 62

#: max static writable keys an MTU payload can carry: 1232 - 65 (1 sig)
#: - 3 (header) - 1 (acct cu16) - 32 (blockhash) - 1 (instr cu16) leaves
#: 1130 bytes = 35 addresses.  The row must cover the true maximum:
#: fdt_txn_scan truncates hashes past this width, and a truncated
#: writable key would escape the per-account writer cost cap
#: (MAX_WRITE_COST_PER_ACCT, a consensus limit) -> over-admission
MAX_WRITERS = 35
#: same bound applies to readonly static keys (exact lock conflicts)
MAX_READERS = 35

_FREE, _PENDING, _INFLIGHT = 0, 1, 2

from . import base58 as _b58  # noqa: E402

#: the on-chain Vote program id (reference: fd_pack classifies txns whose
#: single instruction targets this program as "simple votes" and schedules
#: them through the dedicated vote lane, fd_pack.c pending_votes treap)
VOTE_PROGRAM_ID = _b58.decode("Vote111111111111111111111111111111111111111")
assert VOTE_PROGRAM_ID is not None and len(VOTE_PROGRAM_ID) == 32


def is_simple_vote(payload: bytes, desc: T.TxnDesc) -> bool:
    """Single-instruction txn invoking the Vote program (the reference's
    is_simple_vote_transaction shape test)."""
    if desc.instr_cnt != 1:
        return False
    ins = desc.instr[0]
    if ins.program_id >= desc.acct_addr_cnt:
        return False
    return bytes(desc.acct_addr(payload, ins.program_id)) == VOTE_PROGRAM_ID


def _hash_acct(key: bytes) -> int:
    """Account pubkey -> stable 64-bit hash (splitmix64 finalizer over the
    first 8 bytes XOR the last 8; must agree with fdt_pack.c acct_hash)."""
    x = int.from_bytes(key[:8], "little") ^ int.from_bytes(key[24:], "little")
    x &= (1 << 64) - 1
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & ((1 << 64) - 1)
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & ((1 << 64) - 1)
    x ^= x >> 31
    return x


@dataclass
class ScanResult:
    """Per-txn outputs of one fdt_txn_scan call (views, length n)."""

    ok: np.ndarray
    is_vote: np.ndarray
    fast: np.ndarray
    cost: np.ndarray
    rewards: np.ndarray
    cu_limit: np.ndarray
    tags: np.ndarray
    lamports: np.ndarray
    payer_off: np.ndarray
    src_off: np.ndarray
    dst_off: np.ndarray
    fee: np.ndarray
    bs_rw: np.ndarray | None = None
    bs_w: np.ndarray | None = None
    whash: np.ndarray | None = None
    w_cnt: np.ndarray | None = None
    rhash: np.ndarray | None = None
    r_cnt: np.ndarray | None = None
    trows: np.ndarray | None = None
    tszs: np.ndarray | None = None
    n_ok: int = 0


def txn_scan(
    rows: np.ndarray,
    szs: np.ndarray,
    *,
    in_off: int = 0,
    nbits: int = 0,
    with_bitsets: bool = False,
    with_trailer: bool = False,
    trows: np.ndarray | None = None,
) -> ScanResult:
    """Batch parse + validate + estimate n txns in one native call
    (fdt_txn_scan).  rows (n, width) u8; szs (n,) payload sizes.

    with_bitsets: also produce the pack conflict bitsets + writable-key
    hashes (requires nbits).  with_trailer: write payload+trailer into
    `trows` (defaults to in-place when rows has 16 bytes of slack)."""
    n, width = rows.shape
    szs32 = np.ascontiguousarray(szs, np.uint32)
    out = ScanResult(
        ok=np.zeros(n, np.uint8),
        is_vote=np.zeros(n, np.uint8),
        fast=np.zeros(n, np.uint8),
        cost=np.zeros(n, np.uint32),
        rewards=np.zeros(n, np.uint64),
        cu_limit=np.zeros(n, np.uint32),
        tags=np.zeros(n, np.uint64),
        lamports=np.zeros(n, np.uint64),
        payer_off=np.zeros(n, np.uint32),
        src_off=np.zeros(n, np.uint32),
        dst_off=np.zeros(n, np.uint32),
        fee=np.zeros(n, np.uint32),
    )
    W = nbits // 64 if with_bitsets else 0
    if with_bitsets:
        out.bs_rw = np.zeros((n, W), np.uint64)
        out.bs_w = np.zeros((n, W), np.uint64)
        out.whash = np.zeros((n, MAX_WRITERS), np.uint64)
        out.w_cnt = np.zeros(n, np.uint8)
        out.rhash = np.zeros((n, MAX_READERS), np.uint64)
        out.r_cnt = np.zeros(n, np.uint8)
    if with_trailer:
        out.trows = rows if trows is None else trows
        out.tszs = np.zeros(n, np.uint32)
    assert rows.flags.c_contiguous
    out.n_ok = int(
        R._lib.fdt_txn_scan(
            rows.ctypes.data, width, in_off, szs32.ctypes.data, n,
            nbits if with_bitsets else 0,
            out.ok.ctypes.data, out.is_vote.ctypes.data,
            out.fast.ctypes.data, out.cost.ctypes.data,
            out.rewards.ctypes.data, out.cu_limit.ctypes.data,
            out.tags.ctypes.data, out.lamports.ctypes.data,
            out.payer_off.ctypes.data, out.src_off.ctypes.data,
            out.dst_off.ctypes.data, out.fee.ctypes.data,
            out.bs_rw.ctypes.data if with_bitsets else None,
            out.bs_w.ctypes.data if with_bitsets else None,
            out.whash.ctypes.data if with_bitsets else None,
            out.w_cnt.ctypes.data if with_bitsets else None,
            MAX_WRITERS,
            out.rhash.ctypes.data if with_bitsets else None,
            out.r_cnt.ctypes.data if with_bitsets else None,
            MAX_READERS,
            out.trows.ctypes.data if with_trailer else None,
            out.trows.shape[1] if with_trailer else 0,
            out.tszs.ctypes.data if with_trailer else None,
        )
    )
    return out


@dataclass
class _Microblock:
    handle: int
    txn_idx: np.ndarray  # pool indices
    total_cost: int


class Pack:
    """Dense-array pack engine.  Single-writer (the pack tile)."""

    def __init__(
        self,
        depth: int,
        *,
        nbits: int = 1024,
        payload_width: int = T.MTU + 16,
        max_banks: int = 8,
        block_cost_limit: int = MAX_COST_PER_BLOCK,
        writer_cost_cap: int = MAX_WRITE_COST_PER_ACCT,
    ):
        assert nbits % 64 == 0
        self.depth = depth
        self.nbits = nbits
        self.W = nbits // 64
        self.max_banks = max_banks
        self.block_cost_limit = block_cost_limit
        self.writer_cost_cap = writer_cost_cap

        P = depth
        self.rows = np.zeros((P, payload_width), dtype=np.uint8)
        self.szs = np.zeros(P, dtype=np.uint16)
        self.rewards = np.zeros(P, dtype=np.uint64)
        self.cost = np.zeros(P, dtype=np.uint32)
        self.expires_at = np.zeros(P, dtype=np.uint64)
        self.state = np.zeros(P, dtype=np.uint8)
        self.sig_tag = np.zeros(P, dtype=np.uint64)
        self.is_vote = np.zeros(P, dtype=bool)
        # hashed account-conflict bitsets
        self.bs_rw = np.zeros((P, self.W), dtype=np.uint64)
        self.bs_w = np.zeros((P, self.W), dtype=np.uint64)
        # hashed writable/readonly account keys per txn (writer cost
        # caps + exact lock tables)
        self.whash = np.zeros((P, MAX_WRITERS), dtype=np.uint64)
        self.w_cnt = np.zeros(P, dtype=np.uint8)
        self.rhash = np.zeros((P, MAX_READERS), dtype=np.uint64)
        self.r_cnt = np.zeros(P, dtype=np.uint8)

        # hashed-bitset in-use state: kept ONLY for the speculative
        # device prefilter (ops/pack_select); the authoritative conflict
        # check is the exact lock tables below — a 1024-bit bloom
        # saturates under deep microblock pipelining and collapses fill
        # (measured round 5: 47 of 256 txns/microblock).  The in_use
        # masks stay zero now (nothing maintains them), so the prefilter
        # only resolves candidate-vs-candidate conflicts; the exact
        # commit re-checks everything it admits.
        self.in_use_rw = np.zeros(self.W, dtype=np.uint64)
        self.in_use_w = np.zeros(self.W, dtype=np.uint64)
        self.bit_ref_rw = np.zeros(nbits, dtype=np.int32)
        self.bit_ref_w = np.zeros(nbits, dtype=np.int32)

        # EXACT account locks across outstanding microblocks (reference:
        # fd_pack's acct_in_use map): open-addressing u64-hash ->
        # refcount, writable + readonly tables.  4*depth entries covers
        # realistic workloads (a few distinct keys per inflight txn) at
        # low load factor; a pathological many-account workload (up to
        # 35+35 keys/txn) can fill it, in which case lock_add FAILS
        # CLOSED — fill degrades, over-admission is impossible
        # (lock_table_load() exposes occupancy for monitors/tests).
        lock_cnt = 1 << max(14, (4 * depth - 1).bit_length())
        self._lock_mask = lock_cnt - 1
        self.lw_keys = np.zeros(lock_cnt, dtype=np.uint64)
        self.lw_vals = np.zeros(lock_cnt, dtype=np.int64)
        self.lr_keys = np.zeros(lock_cnt, dtype=np.uint64)
        self.lr_vals = np.zeros(lock_cnt, dtype=np.int64)

        # writer-cost map (hash-keyed open addressing, fdt_pack.c wc_*):
        # sized for a full block of minimum-cost txns' writable keys —
        # ~block_cost_limit/1500 CU admits ~32K txns, each with up to a
        # few writable keys, so 4x that keeps the load factor low (a full
        # map degrades to at-cap rejections, never a hang — wc_get bound)
        block_txn_cap = max(block_cost_limit // 1500, depth)
        map_cnt = 1 << max(14, (4 * block_txn_cap - 1).bit_length())
        self._wc_mask = map_cnt - 1
        self.wc_keys = np.zeros(map_cnt, dtype=np.uint64)
        self.wc_vals = np.zeros(map_cnt, dtype=np.int64)

        self.vote_cost_limit = MAX_VOTE_COST_PER_BLOCK

        # shared scheduler words (i64) — the native after-credit hook
        # (fdt_pack_sched, ISSUE 11) and the Python schedule/complete
        # path mutate the SAME state, so the two loops stay
        # interchangeable mid-run:
        #   [0] cumulative block cost   [1] cumulative vote cost
        #   [2] next microblock handle  [3] outstanding microblock count
        # [3] is also the O(1) answer to "any outstanding?" the block-
        # boundary check needs (the old dict scan was O(banks + mbs)
        # per after_credit call).
        self._sched_words = np.zeros(4, np.int64)

        # outstanding-microblock registry, dense + native-visible: one
        # entry per in-flight microblock (capacity P: every microblock
        # holds >= 1 distinct pool slot, so the registry can never
        # fill), with the pick-ORDERED txn list stored as a linked
        # chain through the pool slots themselves (mb_next) — exact
        # release order is part of the lock-table bit-parity contract.
        self.mb_used = np.zeros(P, np.uint8)
        self.mb_bank = np.zeros(P, np.int64)
        self.mb_handle = np.zeros(P, np.uint64)
        self.mb_head = np.full(P, -1, np.int64)
        self.mb_cnt = np.zeros(P, np.int64)
        self.mb_cost = np.zeros(P, np.int64)
        self.mb_next = np.full(P, -1, np.int64)

    # ---- queries --------------------------------------------------------

    @property
    def pending_cnt(self) -> int:
        return int((self.state == _PENDING).sum())

    @property
    def inflight_cnt(self) -> int:
        return int((self.state == _INFLIGHT).sum())

    # -- shared scheduler words (native/Python interchangeable state) --

    @property
    def cumulative_block_cost(self) -> int:
        return int(self._sched_words[0])

    @cumulative_block_cost.setter
    def cumulative_block_cost(self, v: int) -> None:
        self._sched_words[0] = v

    @property
    def cumulative_vote_cost(self) -> int:
        return int(self._sched_words[1])

    @cumulative_vote_cost.setter
    def cumulative_vote_cost(self, v: int) -> None:
        self._sched_words[1] = v

    @property
    def outstanding_cnt(self) -> int:
        """O(1) outstanding-microblock count, maintained by schedule /
        complete — the block-boundary check reads this every
        after_credit call (it used to scan the whole per-bank dict)."""
        return int(self._sched_words[3])

    def _mb_txns(self, m: int) -> np.ndarray:
        """Pick-ordered pool slots of registry entry m (chain walk)."""
        cnt = int(self.mb_cnt[m])
        idx = np.empty(cnt, np.int64)
        s = int(self.mb_head[m])
        for k in range(cnt):
            idx[k] = s
            s = int(self.mb_next[s])
        return idx

    @property
    def outstanding(self) -> dict[int, list[_Microblock]]:
        """Compat view of the registry: {bank: [_Microblock, ...]}.
        Materialized per access (registry-slot order); the O(1)
        existence check is `outstanding_cnt`."""
        obs: dict[int, list[_Microblock]] = {
            b: [] for b in range(self.max_banks)
        }
        for m in np.flatnonzero(self.mb_used != 0):
            obs[int(self.mb_bank[m])].append(
                _Microblock(
                    int(self.mb_handle[m]), self._mb_txns(int(m)),
                    int(self.mb_cost[m]),
                )
            )
        return obs

    def lock_table_load(self) -> float:
        """Occupancy of the fuller exact-lock table (0..1); near 1.0
        means lock_add is failing closed and fill is degrading."""
        cap = self._lock_mask + 1
        return max(
            int((self.lw_keys != 0).sum()), int((self.lr_keys != 0).sum())
        ) / cap

    def writer_cost(self, key: bytes) -> int:
        """Committed write cost against `key`'s hash bucket this block."""
        h = _hash_acct(key) or 1
        i = h & self._wc_mask
        for _ in range(self._wc_mask + 1):
            k = int(self.wc_keys[i])
            if k == h:
                return int(self.wc_vals[i])
            if k == 0:
                return 0
            i = (i + 1) & self._wc_mask
        return self.writer_cost_cap  # full map: at-cap (matches wc_get)

    # ---- insert ---------------------------------------------------------

    def insert_batch(
        self,
        rows: np.ndarray,
        szs: np.ndarray,
        *,
        expires_at: int = 0,
        scan: ScanResult | None = None,
    ) -> int:
        """Insert a batch of raw txns ((n, width) u8 + payload sizes) in
        one native scan + vectorized slot scatter.  Returns txns accepted
        (rejects: parse/estimate failures, pool full after the
        better-priority eviction policy).  `scan` reuses a caller's
        fdt_txn_scan result (must include bitsets)."""
        if scan is None:
            scan = txn_scan(rows, szs, nbits=self.nbits, with_bitsets=True)
        ok_idx = np.flatnonzero(scan.ok)
        if not len(ok_idx):
            return 0
        free = np.flatnonzero(self.state == _FREE)
        n_place = min(len(ok_idx), len(free))
        placed = n_place
        if n_place < len(ok_idx):
            # pool full: evict strictly-worse pending txns for the best of
            # the remainder (fd_pack_insert_txn_fini's priority eviction,
            # batch-generalized: best incoming paired with worst pending —
            # the pairing comparison is prefix-monotone, so the accepted
            # set is exactly the evictions the one-at-a-time policy makes)
            extra = ok_idx[n_place:]
            pr_new = scan.rewards[extra].astype(np.float64) / np.maximum(
                scan.cost[extra].astype(np.float64), 1.0
            )
            new_order = np.argsort(-pr_new, kind="stable")
            extra = extra[new_order]
            pending = np.flatnonzero(self.state == _PENDING)
            if len(pending):
                pr_old = self.rewards[pending].astype(
                    np.float64
                ) / np.maximum(self.cost[pending].astype(np.float64), 1.0)
                worst_order = pending[np.argsort(pr_old, kind="stable")]
                pr_old_sorted = np.sort(pr_old, kind="stable")
                k = min(len(extra), len(worst_order))
                take = np.flatnonzero(
                    pr_new[new_order][:k] > pr_old_sorted[:k]
                )
                if len(take):
                    slots = worst_order[take]
                    self.state[slots] = _FREE
                    self._scatter(
                        slots, rows, szs, extra[take], scan, expires_at
                    )
                    placed += len(take)
            ok_idx = ok_idx[:n_place]
        if n_place:
            self._scatter(free[:n_place], rows, szs, ok_idx, scan, expires_at)
        return placed

    def _scatter(self, slots, rows, szs, src, scan: ScanResult, expires_at):
        w = min(rows.shape[1], self.rows.shape[1])
        self.rows[slots, :w] = rows[src][:, :w]
        self.szs[slots] = szs[src]
        self.rewards[slots] = np.minimum(
            scan.rewards[src], np.uint64(0xFFFFFFFF)
        )
        self.cost[slots] = scan.cost[src]
        self.expires_at[slots] = expires_at
        self.sig_tag[slots] = scan.tags[src]
        self.is_vote[slots] = scan.is_vote[src].astype(bool)
        self.bs_rw[slots] = scan.bs_rw[src]
        self.bs_w[slots] = scan.bs_w[src]
        self.whash[slots] = scan.whash[src]
        self.w_cnt[slots] = scan.w_cnt[src]
        self.rhash[slots] = scan.rhash[src]
        self.r_cnt[slots] = scan.r_cnt[src]
        self.state[slots] = _PENDING

    def insert(
        self, payload: bytes, *, expires_at: int = 0, sig_tag: int = 0
    ) -> str:
        """Insert one txn.  Returns 'ok', 'parse', 'estimate', or 'full'
        (mirrors fd_pack_insert_txn_fini's reject reasons)."""
        row = np.zeros((1, len(payload)), np.uint8)
        row[0] = np.frombuffer(payload, np.uint8)
        szs = np.array([len(payload)], np.uint32)
        scan = txn_scan(row, szs, nbits=self.nbits, with_bitsets=True)
        if not scan.ok[0]:
            # distinguish the reject reason for the caller (one extra
            # Python parse on the cold path only)
            desc = T.parse(payload)
            if desc is None:
                return "parse"
            return "estimate"
        if sig_tag:
            scan.tags[0] = sig_tag
        placed = self.insert_batch(row, szs, expires_at=expires_at, scan=scan)
        return "ok" if placed else "full"

    # ---- scheduling -----------------------------------------------------

    def _order(self, cands: np.ndarray, scan_limit: int) -> np.ndarray:
        pr = self.rewards[cands].astype(np.float64) / np.maximum(
            self.cost[cands].astype(np.float64), 1.0
        )
        return np.ascontiguousarray(
            cands[np.argsort(-pr, kind="stable")][:scan_limit], np.int64
        )

    def _commit(
        self, order: np.ndarray, cu_limit: int, txn_limit: int,
        byte_limit: int,
    ) -> tuple[np.ndarray, int]:
        """Greedy select + commit (native, EXACT account locks):
        returns (picks, cu_used)."""
        if cu_limit <= 0 or txn_limit <= 0 or not len(order):
            return np.zeros(0, np.int64), 0
        picks = np.empty(min(len(order), txn_limit), np.int64)
        cu_used = np.zeros(1, np.int64)
        n = R._lib.fdt_pack_select_x(
            order.ctypes.data, len(order),
            self.whash.ctypes.data, self.w_cnt.ctypes.data, MAX_WRITERS,
            self.rhash.ctypes.data, self.r_cnt.ctypes.data, MAX_READERS,
            self.lw_keys.ctypes.data, self.lw_vals.ctypes.data,
            self._lock_mask,
            self.lr_keys.ctypes.data, self.lr_vals.ctypes.data,
            self._lock_mask,
            self.cost.ctypes.data, self.szs.ctypes.data, byte_limit,
            self.wc_keys.ctypes.data, self.wc_vals.ctypes.data,
            self._wc_mask, self.writer_cost_cap, cu_limit, txn_limit,
            picks.ctypes.data, cu_used.ctypes.data,
        )
        return picks[:n], int(cu_used[0])

    def _select_speculative(
        self, cands, cu_limit, txn_limit, scan_limit, device_select,
        sel_rw, sel_w,
    ) -> np.ndarray:
        """Device-speculative selection (ops/pack_select): returns a
        candidate pick ORDER; the native commit path re-enforces every
        exact budget before committing."""
        order = self._order(cands, scan_limit)
        cand_rw = self.bs_rw[order]
        cand_w = self.bs_w[order]
        costs = self.cost[order].astype(np.int64)
        K = len(order)
        if K < scan_limit:
            pad = scan_limit - K
            cand_rw = np.concatenate(
                [cand_rw, np.zeros((pad, self.W), np.uint64)]
            )
            cand_w = np.concatenate(
                [cand_w, np.zeros((pad, self.W), np.uint64)]
            )
            from firedancer_tpu.ops.pack_select import PAD_COST

            costs = np.concatenate([costs, np.full(pad, PAD_COST, np.int64)])
        take = np.asarray(
            device_select(
                cand_rw, cand_w, sel_rw.copy(), sel_w.copy(), costs,
                cu_limit, txn_limit,
            )
        )[:K]
        return np.ascontiguousarray(order[take], np.int64)

    def schedule_microblock(
        self,
        bank: int,
        *,
        cu_limit: int = 1_500_000,
        txn_limit: int = 31,
        vote_fraction: float = 0.25,
        now: int = 0,
        scan_limit: int = 1024,
        byte_limit: int = 0,
        device_select=None,
    ) -> _Microblock | None:
        """Greedy-select a non-conflicting microblock for `bank`
        (fd_pack_schedule_next_microblock behavior, fd_pack.c:1029 /
        742-953): VOTES FIRST with `vote_fraction` of the CU budget,
        capped by the per-block vote cost limit (MAX_VOTE_COST_PER_BLOCK,
        fd_pack.h:20), then non-votes with the remainder.  device_select,
        when given, is the TPU prefilter (ops/pack_select.select_noconflict)
        used speculatively; the native commit still enforces writer-cost
        caps and budgets exactly.  byte_limit bounds the encoded
        microblock size (0 = unbounded)."""
        if self.cumulative_block_cost >= self.block_cost_limit:
            return None
        cu_limit = min(
            cu_limit, self.block_cost_limit - self.cumulative_block_cost
        )
        pending = np.flatnonzero(self.state == _PENDING)
        if now:
            # expires_at == 0 means "no expiry requested"
            exp = self.expires_at[pending]
            live = (exp >= now) | (exp == 0)
            expired = pending[~live]
            if len(expired):
                self._release_slots(expired)
            pending = pending[live]
        if not len(pending):
            return None

        votes = pending[self.is_vote[pending]]
        nonvotes = pending[~self.is_vote[pending]]
        vote_budget = min(
            int(cu_limit * vote_fraction),
            self.vote_cost_limit - self.cumulative_vote_cost,
        )
        # votes also get only a vote_fraction share of the txn SLOTS while
        # non-votes are pending: cheap votes must not be able to fill all
        # 31 slots of every microblock on txn count alone (divergence note:
        # the reference splits CUs only; its slot pressure differs because
        # votes and non-votes come from separate treaps per call)
        vote_txn_limit = txn_limit
        if len(nonvotes):
            vote_txn_limit = max(1, int(txn_limit * vote_fraction))
        # vote lane always uses the host order: the candidate set is tiny
        vote_picks, vote_used = self._commit(
            self._order(votes, scan_limit), vote_budget, vote_txn_limit,
            byte_limit,
        ) if len(votes) else (np.zeros(0, np.int64), 0)
        # the byte budget spans the WHOLE microblock: the nonvote pass
        # only gets what the vote pass left (each txn costs sz + a
        # 2-byte length prefix on the wire)
        nv_byte_limit = byte_limit
        if byte_limit > 0 and len(vote_picks):
            nv_byte_limit = max(
                1,
                byte_limit - int(self.szs[vote_picks].sum())
                - 2 * len(vote_picks),
            )
        if device_select is not None and len(nonvotes):
            nv_order = self._select_speculative(
                nonvotes, cu_limit - vote_used, txn_limit, scan_limit,
                device_select, self.in_use_rw, self.in_use_w,
            )
        else:
            nv_order = self._order(nonvotes, scan_limit)
        nv_picks, nv_used = self._commit(
            nv_order, cu_limit - vote_used,
            txn_limit - len(vote_picks), nv_byte_limit,
        )
        picks = np.concatenate([vote_picks, nv_picks])
        if not len(picks):
            return None
        self.cumulative_vote_cost += vote_used
        total = vote_used + nv_used
        self.cumulative_block_cost += total
        self.state[picks] = _INFLIGHT
        # handles live in the u32 domain end to end: the completion sig
        # carries only 32 bits ((bank << 32) | handle), so the registry
        # stores and matches MASKED handles — a wrap can never strand an
        # outstanding microblock as unmatchable (collision would need
        # 2^32 simultaneous outstanding handles; the registry holds at
        # most P)
        handle = int(self._sched_words[2]) & 0xFFFFFFFF
        self._sched_words[2] += 1
        # registry record: lowest free entry (the order fdt_pack_sched
        # reproduces), pick-ordered slot chain
        m = int(np.flatnonzero(self.mb_used == 0)[0])
        self.mb_bank[m] = bank
        self.mb_handle[m] = np.uint64(handle)
        self.mb_head[m] = picks[0]
        self.mb_cnt[m] = len(picks)
        self.mb_cost[m] = total
        if len(picks) > 1:
            self.mb_next[picks[:-1]] = picks[1:]
        self.mb_next[picks[-1]] = -1
        self.mb_used[m] = 1
        self._sched_words[3] += 1
        return _Microblock(handle, picks, total)

    def microblock_complete(self, bank: int, handle: int) -> None:
        """Bank finished executing a microblock: release account locks and
        free the slots (fd_pack_microblock_complete, fd_pack.c:956)."""
        m = np.flatnonzero(
            (self.mb_used != 0)
            & (self.mb_bank == bank)
            & (self.mb_handle == np.uint64(handle & 0xFFFFFFFF))
        )
        if not len(m):
            raise KeyError(f"no outstanding microblock {handle} on bank {bank}")
        m = int(m[0])
        idx = self._mb_txns(m)
        self.mb_used[m] = 0
        self._sched_words[3] -= 1
        R._lib.fdt_pack_release_x(
            idx.ctypes.data, len(idx),
            self.whash.ctypes.data, self.w_cnt.ctypes.data, MAX_WRITERS,
            self.rhash.ctypes.data, self.r_cnt.ctypes.data, MAX_READERS,
            self.lw_keys.ctypes.data, self.lw_vals.ctypes.data,
            self._lock_mask,
            self.lr_keys.ctypes.data, self.lr_vals.ctypes.data,
            self._lock_mask,
        )
        self._release_slots(idx)

    def _release_slots(self, idx: np.ndarray) -> None:
        self.state[idx] = _FREE

    def end_block(self) -> None:
        """Slot boundary: reset block budgets and per-account write costs
        (fd_pack_end_block).  Outstanding microblocks must be completed
        first; pending txns carry over."""
        assert self.outstanding_cnt == 0
        self.wc_keys.fill(0)
        self.wc_vals.fill(0)
        self.cumulative_block_cost = 0
        self.cumulative_vote_cost = 0
