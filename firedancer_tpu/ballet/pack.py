"""Block-packing scheduler — the TPU-native redesign of ballet/pack.

Reference model: /root/reference/src/ballet/pack/fd_pack.c — a treap of
pending txns ordered by reward/cost priority, account-conflict detection
via a hybrid bitset/hashmap (fd_pack_bitset.h), per-account write cost
caps, block CU budgets, and greedy microblock scheduling
(fd_pack_schedule_microblock_impl, fd_pack.c:742-953).

Deliberate redesign (SURVEY.md §7 phase 8): the data structures are dense
numpy arrays instead of intrusive treaps/maps —
  * priority ordering: argsort over the pending set per scheduling pass
    (pack emits microblocks every ~2ms; an O(P log P) vector sort at that
    cadence is cheaper than maintaining pointer structures in Python, and
    is batch/device-friendly)
  * conflict detection: pure bitset over `nbits` hashed account bits with
    NO exact-account fallback — hash collisions cause false-positive
    conflicts, never false negatives, so schedules stay correct and at
    worst a colliding txn waits for the next microblock (the reference's
    own bitset fast path has the same one-sided property; divergence: we
    skip its exact slow path entirely, trading rare spurious delay for a
    data-parallel test)
  * the greedy select loop itself can run on the device as a lax.scan
    prefilter over the top-K candidates (ops/pack_select.py); this host
    engine commits the device's speculative picks after enforcing the
    caps that need exact per-account state (writer costs)

Consensus constants (fd_pack.h:17-23) are preserved exactly.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from . import compute_budget as CB
from . import txn as T

MAX_COST_PER_BLOCK = 48_000_000
MAX_VOTE_COST_PER_BLOCK = 36_000_000
MAX_WRITE_COST_PER_ACCT = 12_000_000
FEE_PER_SIGNATURE = 5000
MAX_BANK_TILES = 62

_FREE, _PENDING, _INFLIGHT = 0, 1, 2

from . import base58 as _b58  # noqa: E402

#: the on-chain Vote program id (reference: fd_pack classifies txns whose
#: single instruction targets this program as "simple votes" and schedules
#: them through the dedicated vote lane, fd_pack.c pending_votes treap)
VOTE_PROGRAM_ID = _b58.decode("Vote111111111111111111111111111111111111111")
assert VOTE_PROGRAM_ID is not None and len(VOTE_PROGRAM_ID) == 32


def is_simple_vote(payload: bytes, desc: T.TxnDesc) -> bool:
    """Single-instruction txn invoking the Vote program (the reference's
    is_simple_vote_transaction shape test)."""
    if desc.instr_cnt != 1:
        return False
    ins = desc.instr[0]
    if ins.program_id >= desc.acct_addr_cnt:
        return False
    return bytes(desc.acct_addr(payload, ins.program_id)) == VOTE_PROGRAM_ID


def _hash_acct(key: bytes) -> int:
    """Account pubkey -> stable 64-bit hash (splitmix64 finalizer over the
    first 8 bytes XOR the last 8; adversarial spread matters less than in
    the reference because collisions only delay, never corrupt)."""
    x = int.from_bytes(key[:8], "little") ^ int.from_bytes(key[24:], "little")
    x &= (1 << 64) - 1
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & ((1 << 64) - 1)
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & ((1 << 64) - 1)
    x ^= x >> 31
    return x


@dataclass
class _Microblock:
    handle: int
    txn_idx: np.ndarray  # pool indices
    total_cost: int


class Pack:
    """Dense-array pack engine.  Single-writer (the pack tile)."""

    def __init__(
        self,
        depth: int,
        *,
        nbits: int = 1024,
        payload_width: int = T.MTU + 16,
        max_banks: int = 8,
        block_cost_limit: int = MAX_COST_PER_BLOCK,
        writer_cost_cap: int = MAX_WRITE_COST_PER_ACCT,
    ):
        assert nbits % 64 == 0
        self.depth = depth
        self.nbits = nbits
        self.W = nbits // 64
        self.max_banks = max_banks
        self.block_cost_limit = block_cost_limit
        self.writer_cost_cap = writer_cost_cap

        P = depth
        self.rows = np.zeros((P, payload_width), dtype=np.uint8)
        self.szs = np.zeros(P, dtype=np.uint16)
        self.rewards = np.zeros(P, dtype=np.uint64)
        self.cost = np.zeros(P, dtype=np.uint32)
        self.expires_at = np.zeros(P, dtype=np.uint64)
        self.state = np.zeros(P, dtype=np.uint8)
        self.sig_tag = np.zeros(P, dtype=np.uint64)
        self.is_vote = np.zeros(P, dtype=bool)
        # hashed account-conflict bitsets
        self.bs_rw = np.zeros((P, self.W), dtype=np.uint64)
        self.bs_w = np.zeros((P, self.W), dtype=np.uint64)
        # exact writable-account keys per txn (for writer cost caps)
        self.writable_keys: list[list[bytes]] = [[] for _ in range(P)]

        # in-use state across outstanding microblocks
        self.in_use_rw = np.zeros(self.W, dtype=np.uint64)
        self.in_use_w = np.zeros(self.W, dtype=np.uint64)
        self.bit_ref_rw = np.zeros(nbits, dtype=np.int32)
        self.bit_ref_w = np.zeros(nbits, dtype=np.int32)

        self.writer_costs: dict[bytes, int] = {}
        self.cumulative_block_cost = 0
        self.cumulative_vote_cost = 0
        self.vote_cost_limit = MAX_VOTE_COST_PER_BLOCK
        self.outstanding: dict[int, list[_Microblock]] = {
            b: [] for b in range(max_banks)
        }
        self._next_handle = 0

    # ---- queries --------------------------------------------------------

    @property
    def pending_cnt(self) -> int:
        return int((self.state == _PENDING).sum())

    @property
    def inflight_cnt(self) -> int:
        return int((self.state == _INFLIGHT).sum())

    # ---- insert ---------------------------------------------------------

    def _bits_for(self, keys: list[bytes]) -> np.ndarray:
        bs = np.zeros(self.W, dtype=np.uint64)
        for k in keys:
            b = _hash_acct(k) % self.nbits
            bs[b >> 6] |= np.uint64(1) << np.uint64(b & 63)
        return bs

    def insert(
        self, payload: bytes, *, expires_at: int = 0, sig_tag: int = 0
    ) -> str:
        """Insert one txn.  Returns 'ok', 'parse', 'estimate', or 'full'
        (mirrors fd_pack_insert_txn_fini's reject reasons)."""
        desc = T.parse(payload)
        if desc is None:
            return "parse"
        est = CB.estimate(payload, desc)
        if not est.ok or est.cost == 0:
            return "estimate"

        free = np.flatnonzero(self.state == _FREE)
        if len(free):
            slot = int(free[0])
        else:
            # replacement policy: evict the worst pending txn if the new
            # one has strictly better priority (reference behavior:
            # fd_pack_insert_txn_fini's PRIORITY comparison + eviction)
            pending = np.flatnonzero(self.state == _PENDING)
            if not len(pending):
                return "full"
            pr = self.rewards[pending].astype(np.float64) / np.maximum(
                self.cost[pending].astype(np.float64), 1.0
            )
            worst = int(pending[np.argmin(pr)])
            if est.rewards / max(est.cost, 1) <= pr.min():
                return "full"
            slot = worst

        n = len(payload)
        self.rows[slot, :n] = np.frombuffer(payload, dtype=np.uint8)
        self.szs[slot] = n
        self.rewards[slot] = est.rewards
        self.cost[slot] = est.cost
        self.expires_at[slot] = expires_at
        self.sig_tag[slot] = sig_tag
        self.state[slot] = _PENDING
        self.is_vote[slot] = is_simple_vote(payload, desc)

        w_idx = desc.writable_idxs()
        keys_w = [bytes(desc.acct_addr(payload, j)) for j in w_idx]
        keys_all = [
            bytes(desc.acct_addr(payload, j)) for j in range(desc.acct_addr_cnt)
        ]
        self.writable_keys[slot] = keys_w
        self.bs_w[slot] = self._bits_for(keys_w)
        self.bs_rw[slot] = self._bits_for(keys_all)
        return "ok"

    # ---- scheduling -----------------------------------------------------

    def _select_pass(
        self, cands, cu_limit, txn_limit, scan_limit, device_select,
        sel_rw, sel_w,
    ) -> list[int]:
        """One greedy selection pass over `cands` (pool slots) against the
        running conflict state sel_rw/sel_w (mutated in place)."""
        if cu_limit <= 0 or txn_limit <= 0 or not len(cands):
            return []
        pr = self.rewards[cands].astype(np.float64) / np.maximum(
            self.cost[cands].astype(np.float64), 1.0
        )
        order = cands[np.argsort(-pr, kind="stable")][:scan_limit]
        cand_rw = self.bs_rw[order]
        cand_w = self.bs_w[order]
        costs = self.cost[order].astype(np.int64)

        if device_select is not None:
            # pad candidates to the fixed scan_limit shape so the jitted
            # select kernel compiles once; sentinel rows carry a cost above
            # any cu_limit, so they are never taken
            K = len(order)
            if K < scan_limit:
                pad = scan_limit - K
                cand_rw = np.concatenate(
                    [cand_rw, np.zeros((pad, self.W), np.uint64)]
                )
                cand_w = np.concatenate(
                    [cand_w, np.zeros((pad, self.W), np.uint64)]
                )
                from firedancer_tpu.ops.pack_select import PAD_COST

                costs = np.concatenate(
                    [costs, np.full(pad, PAD_COST, np.int64)]
                )
            take = np.asarray(
                device_select(
                    cand_rw, cand_w, sel_rw.copy(), sel_w.copy(), costs,
                    cu_limit, txn_limit,
                )
            )[:K]
            picks = [int(s) for s in order[take]]
            for slot in picks:
                sel_rw |= self.bs_rw[slot]
                sel_w |= self.bs_w[slot]
            return picks

        picks_l: list[int] = []
        cu_used = 0
        for j, slot in enumerate(order):
            c = int(costs[j])
            if cu_used + c > cu_limit:
                continue
            if (cand_w[j] & sel_rw).any() or (cand_rw[j] & sel_w).any():
                continue
            picks_l.append(int(slot))
            sel_rw |= cand_rw[j]
            sel_w |= cand_w[j]
            cu_used += c
            if len(picks_l) >= txn_limit:
                break
        return picks_l

    def schedule_microblock(
        self,
        bank: int,
        *,
        cu_limit: int = 1_500_000,
        txn_limit: int = 31,
        vote_fraction: float = 0.25,
        now: int = 0,
        scan_limit: int = 1024,
        device_select=None,
    ) -> _Microblock | None:
        """Greedy-select a non-conflicting microblock for `bank`
        (fd_pack_schedule_next_microblock behavior, fd_pack.c:1029 /
        742-953): VOTES FIRST with `vote_fraction` of the CU budget,
        capped by the per-block vote cost limit (MAX_VOTE_COST_PER_BLOCK,
        fd_pack.h:20), then non-votes with the remainder.  device_select,
        when given, is the TPU prefilter (ops/pack_select.select_noconflict)
        used speculatively; the host still enforces writer-cost caps and
        block budgets before committing."""
        if self.cumulative_block_cost >= self.block_cost_limit:
            return None
        cu_limit = min(
            cu_limit, self.block_cost_limit - self.cumulative_block_cost
        )
        pending = np.flatnonzero(self.state == _PENDING)
        if now:
            # expires_at == 0 means "no expiry requested"
            exp = self.expires_at[pending]
            live = (exp >= now) | (exp == 0)
            expired = pending[~live]
            if len(expired):
                self._release_slots(expired)
            pending = pending[live]
        if not len(pending):
            return None

        votes = pending[self.is_vote[pending]]
        nonvotes = pending[~self.is_vote[pending]]
        vote_budget = min(
            int(cu_limit * vote_fraction),
            self.vote_cost_limit - self.cumulative_vote_cost,
        )
        # votes also get only a vote_fraction share of the txn SLOTS while
        # non-votes are pending: cheap votes must not be able to fill all
        # 31 slots of every microblock on txn count alone (divergence note:
        # the reference splits CUs only; its slot pressure differs because
        # votes and non-votes come from separate treaps per call)
        vote_txn_limit = txn_limit
        if len(nonvotes):
            vote_txn_limit = max(1, int(txn_limit * vote_fraction))
        sel_rw = self.in_use_rw.copy()
        sel_w = self.in_use_w.copy()
        # vote lane always uses the host greedy loop: the candidate set is
        # tiny and the device prefilter's fixed scan_limit shape would pay
        # a full 1024-row scan for it
        vote_picks = self._select_pass(
            votes, vote_budget, vote_txn_limit, scan_limit, None,
            sel_rw, sel_w,
        )
        vote_cost = int(self.cost[vote_picks].sum()) if vote_picks else 0
        # device pass keeps the STATIC txn_limit (it is a static jit arg;
        # varying it would recompile); the host commit loop below enforces
        # the remaining dynamic slot budget
        nv_picks = self._select_pass(
            nonvotes, cu_limit - vote_cost, txn_limit,
            scan_limit, device_select, sel_rw, sel_w,
        )
        picks = vote_picks + nv_picks

        # host-side exact enforcement: writer cost caps (+ re-derive
        # budgets when the device speculated); votes enforce the vote
        # budget exactly
        final: list[int] = []
        cu_used = 0
        vote_used = 0
        for slot in picks:
            slot = int(slot)
            c = int(self.cost[slot])
            if cu_used + c > cu_limit:
                continue
            if self.is_vote[slot] and vote_used + c > vote_budget:
                continue
            over = False
            for k in self.writable_keys[slot]:
                if self.writer_costs.get(k, 0) + c > self.writer_cost_cap:
                    over = True
                    break
            if over:
                continue
            final.append(slot)
            cu_used += c
            if self.is_vote[slot]:
                vote_used += c
            if len(final) >= txn_limit:
                break
        if not final:
            return None
        self.cumulative_vote_cost += vote_used

        idx = np.array(final, dtype=np.int64)
        for slot in final:
            c = int(self.cost[slot])
            for k in self.writable_keys[slot]:
                self.writer_costs[k] = self.writer_costs.get(k, 0) + c
        # acquire bits with refcounts so overlapping reads across banks
        # release correctly
        for slot in final:
            self._bit_acquire(self.bs_rw[slot], self.bit_ref_rw)
            self._bit_acquire(self.bs_w[slot], self.bit_ref_w)
        self._rebuild_in_use()
        self.state[idx] = _INFLIGHT
        total = int(self.cost[idx].sum())
        self.cumulative_block_cost += total
        mb = _Microblock(self._next_handle, idx, total)
        self._next_handle += 1
        self.outstanding[bank].append(mb)
        return mb

    def _bit_acquire(self, bs: np.ndarray, ref: np.ndarray) -> None:
        bits = np.flatnonzero(
            (bs[:, None] >> np.arange(64, dtype=np.uint64)[None, :])
            & np.uint64(1)
        )
        ref[bits] += 1

    def _bit_release(self, bs: np.ndarray, ref: np.ndarray) -> None:
        bits = np.flatnonzero(
            (bs[:, None] >> np.arange(64, dtype=np.uint64)[None, :])
            & np.uint64(1)
        )
        ref[bits] -= 1

    def _rebuild_in_use(self) -> None:
        for ref, out in (
            (self.bit_ref_rw, "in_use_rw"),
            (self.bit_ref_w, "in_use_w"),
        ):
            live = ref > 0
            words = np.zeros(self.W, dtype=np.uint64)
            bits = np.flatnonzero(live)
            np.bitwise_or.at(
                words, bits >> 6, np.uint64(1) << (bits & 63).astype(np.uint64)
            )
            setattr(self, out, words)

    def microblock_complete(self, bank: int, handle: int) -> None:
        """Bank finished executing a microblock: release account locks and
        free the slots (fd_pack_microblock_complete, fd_pack.c:956)."""
        obs = self.outstanding[bank]
        for i, mb in enumerate(obs):
            if mb.handle == handle:
                break
        else:
            raise KeyError(f"no outstanding microblock {handle} on bank {bank}")
        obs.pop(i)
        for slot in mb.txn_idx:
            self._bit_release(self.bs_rw[slot], self.bit_ref_rw)
            self._bit_release(self.bs_w[slot], self.bit_ref_w)
        self._rebuild_in_use()
        self._release_slots(mb.txn_idx)

    def _release_slots(self, idx: np.ndarray) -> None:
        self.state[idx] = _FREE
        for slot in idx:
            self.writable_keys[int(slot)] = []

    def end_block(self) -> None:
        """Slot boundary: reset block budgets and per-account write costs
        (fd_pack_end_block).  Outstanding microblocks must be completed
        first; pending txns carry over."""
        assert all(not v for v in self.outstanding.values())
        self.writer_costs.clear()
        self.cumulative_block_cost = 0
        self.cumulative_vote_cost = 0
