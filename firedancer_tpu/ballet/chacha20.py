"""ChaCha20 block function + the Solana-compatible ChaCha20Rng.

Behavior contract: src/ballet/chacha20/fd_chacha20.c (block layout:
constants | key | counter-word | 3 nonce words) and fd_chacha20rng.h —
a rand_chacha-compatible RNG: the stream is successive 64-byte blocks
with the block index in the counter word, reads are 8-byte little-endian,
and ulong_roll is the widening-multiply rejection sampler with two zone
modes (MODE_MOD for leader schedule, MODE_SHIFT for Turbine).

Host-side: this seeds leader schedules and Turbine trees, not the packet
path.  The block function is vectorized numpy so a whole buffer of
blocks is produced per call.
"""

from __future__ import annotations

import numpy as np

MODE_MOD = 1
MODE_SHIFT = 2

_CONSTANTS = np.array(
    [0x61707865, 0x3320646E, 0x79622D32, 0x6B206574], dtype=np.uint32
)


def _rotl32(x, n):
    return (x << np.uint32(n)) | (x >> np.uint32(32 - n))


def _quarter(s, a, b, c, d):
    with np.errstate(over="ignore"):
        s[a] += s[b]
        s[d] = _rotl32(s[d] ^ s[a], 16)
        s[c] += s[d]
        s[b] = _rotl32(s[b] ^ s[c], 12)
        s[a] += s[b]
        s[d] = _rotl32(s[d] ^ s[a], 8)
        s[c] += s[d]
        s[b] = _rotl32(s[b] ^ s[c], 7)


def chacha20_blocks(key: bytes, counters: np.ndarray, nonce: bytes = b"\0" * 12) -> np.ndarray:
    """ChaCha20 keystream blocks for a batch of counter values.

    key: 32 bytes; counters: (N,) uint32; nonce: 12 bytes.
    Returns (N, 64) uint8."""
    assert len(key) == 32 and len(nonce) == 12
    n = len(counters)
    kw = np.frombuffer(key, dtype="<u4")
    nw = np.frombuffer(nonce, dtype="<u4")
    state = np.zeros((16, n), dtype=np.uint32)
    state[0:4] = _CONSTANTS[:, None]
    state[4:12] = kw[:, None]
    state[12] = np.asarray(counters, np.uint32)
    state[13:16] = nw[:, None]
    s = state.copy()
    for _ in range(10):  # 20 rounds = 10 double rounds
        _quarter(s, 0, 4, 8, 12)
        _quarter(s, 1, 5, 9, 13)
        _quarter(s, 2, 6, 10, 14)
        _quarter(s, 3, 7, 11, 15)
        _quarter(s, 0, 5, 10, 15)
        _quarter(s, 1, 6, 11, 12)
        _quarter(s, 2, 7, 8, 13)
        _quarter(s, 3, 4, 9, 14)
    with np.errstate(over="ignore"):
        s += state
    return np.ascontiguousarray(s.T).view(np.uint8).reshape(n, 64)


def chacha20_encrypt(key: bytes, counter0: int, nonce: bytes, data: bytes) -> bytes:
    """IETF ChaCha20 (RFC 8439) encrypt/decrypt (XOR keystream)."""
    n_blocks = (len(data) + 63) // 64
    ks = chacha20_blocks(
        key, np.arange(counter0, counter0 + n_blocks, dtype=np.uint32), nonce
    ).reshape(-1)[: len(data)]
    return bytes(np.frombuffer(data, np.uint8) ^ ks)


class ChaCha20Rng:
    """rand_chacha-compatible RNG (fd_chacha20rng semantics)."""

    BUF_BLOCKS = 8

    def __init__(self, key: bytes, mode: int = MODE_MOD):
        assert len(key) == 32
        self.key = key
        self.mode = mode
        self._buf = np.zeros(0, dtype=np.uint8)
        self._off = 0
        self._next_block = 0

    def _refill(self) -> None:
        idxs = np.arange(
            self._next_block, self._next_block + self.BUF_BLOCKS, dtype=np.uint32
        )
        self._buf = chacha20_blocks(self.key, idxs).reshape(-1)
        self._next_block += self.BUF_BLOCKS
        self._off = 0

    def next_u64(self) -> int:
        if self._off + 8 > len(self._buf):
            self._refill()
        v = int(self._buf[self._off : self._off + 8].view("<u8")[0])
        self._off += 8
        return v

    def roll(self, n: int) -> int:
        """Uniform in [0, n) via widening-multiply rejection
        (fd_chacha20rng_ulong_roll)."""
        assert 0 < n < 1 << 64
        if self.mode == MODE_MOD:
            zone = (1 << 64) - 1 - ((1 << 64) - n) % n
        else:
            zone = (n << (63 - (n.bit_length() - 1))) - 1
        while True:
            v = self.next_u64()
            res = v * n
            hi, lo = res >> 64, res & ((1 << 64) - 1)
            if lo <= zone:
                return hi
