"""AES-128/256 + AES-GCM, NumPy-vectorized over blocks.

The reference implements AES-GCM for QUIC packet protection with AES-NI +
GFNI assembly (/root/reference/src/ballet/aes/, behavior contract only).
TPU-native reality check: QUIC packet protection is control-plane work that
runs on the HOST next to the sockets — per-packet serial latency matters,
not batch throughput — so the right "native" here is vectorized NumPy over
the blocks of each packet (the block cipher rounds apply to all blocks of a
packet at once), not a device kernel.  GHASH uses 8-bit Shoup tables
(python ints) — the per-key 4 KB table mirrors the reference's gfni table
strategy at a scripting-language scale.

Tests cross-check against NIST CAVP-style vectors and the system
`cryptography` package.
"""

from __future__ import annotations

import hmac

import numpy as np

# ---------------------------------------------------------------------------
# S-box generation (derived, not pasted: multiplicative inverse + affine map)
# ---------------------------------------------------------------------------


def _gf_mul(a: int, b: int) -> int:
    r = 0
    for _ in range(8):
        if b & 1:
            r ^= a
        b >>= 1
        a <<= 1
        if a & 0x100:
            a ^= 0x11B
    return r


def _build_sbox() -> np.ndarray:
    inv = [0] * 256
    for i in range(1, 256):
        for j in range(1, 256):
            if _gf_mul(i, j) == 1:
                inv[i] = j
                break
    sbox = np.zeros(256, np.uint8)
    for i in range(256):
        x = inv[i]
        y = x
        for _ in range(4):
            y = ((y << 1) | (y >> 7)) & 0xFF
            x ^= y
        sbox[i] = x ^ 0x63
    return sbox


SBOX = _build_sbox()
XTIME = np.array(
    [((i << 1) ^ (0x1B if i & 0x80 else 0)) & 0xFF for i in range(256)],
    np.uint8,
)
_RCON = [1]
while len(_RCON) < 14:
    _RCON.append(_gf_mul(_RCON[-1], 2))

# ShiftRows permutation on the 16-byte column-major state (byte i = state
# row i%4, col i//4; AES shifts row r left by r columns)
_SHIFT = np.array(
    [(i + 4 * (i % 4)) % 16 for i in range(16)], np.int64
)


def key_expand(key: bytes) -> np.ndarray:
    """AES-128/256 key schedule -> (rounds+1, 16) u8 round keys."""
    nk = len(key) // 4
    assert nk in (4, 8), "AES-128 or AES-256 only"
    rounds = {4: 10, 8: 14}[nk]
    w = [list(key[4 * i : 4 * i + 4]) for i in range(nk)]
    for i in range(nk, 4 * (rounds + 1)):
        t = list(w[i - 1])
        if i % nk == 0:
            t = t[1:] + t[:1]
            t = [int(SBOX[b]) for b in t]
            t[0] ^= _RCON[i // nk - 1]
        elif nk == 8 and i % nk == 4:
            t = [int(SBOX[b]) for b in t]
        w.append([a ^ b for a, b in zip(w[i - nk], t)])
    ks = np.array(w, np.uint8).reshape(rounds + 1, 16)
    return ks


def encrypt_blocks(ks: np.ndarray, blocks: np.ndarray) -> np.ndarray:
    """(n, 16) u8 plaintext blocks -> (n, 16) u8 ciphertext, vectorized."""
    rounds = ks.shape[0] - 1
    s = blocks ^ ks[0]
    for r in range(1, rounds + 1):
        s = SBOX[s]
        s = s[:, _SHIFT]
        if r != rounds:
            # MixColumns on column-major quads
            a = s.reshape(-1, 4, 4)
            x = XTIME[a]
            b = np.empty_like(a)
            t = a[:, :, 0] ^ a[:, :, 1] ^ a[:, :, 2] ^ a[:, :, 3]
            b[:, :, 0] = a[:, :, 0] ^ t ^ XTIME[a[:, :, 0] ^ a[:, :, 1]]
            b[:, :, 1] = a[:, :, 1] ^ t ^ XTIME[a[:, :, 1] ^ a[:, :, 2]]
            b[:, :, 2] = a[:, :, 2] ^ t ^ XTIME[a[:, :, 2] ^ a[:, :, 3]]
            b[:, :, 3] = a[:, :, 3] ^ t ^ XTIME[a[:, :, 3] ^ a[:, :, 0]]
            del x
            s = b.reshape(-1, 16)
        s = s ^ ks[r]
    return s


def encrypt_block(ks: np.ndarray, block: bytes) -> bytes:
    return encrypt_blocks(ks, np.frombuffer(block, np.uint8)[None, :])[
        0
    ].tobytes()


# ---------------------------------------------------------------------------
# GHASH (GF(2^128), Shoup 8-bit tables over python ints)
# ---------------------------------------------------------------------------

_R = 0xE1 << 120


def _gf128_mul(x: int, y: int) -> int:
    """Bit-serial GF(2^128) multiply (table generation only)."""
    z = 0
    v = x
    for i in range(127, -1, -1):
        if (y >> i) & 1:
            z ^= v
        if v & 1:
            v = (v >> 1) ^ _R
        else:
            v >>= 1
    return z


class Ghash:
    """GHASH with a per-key 16x256 table: one lookup+xor per message byte."""

    def __init__(self, h: bytes):
        hi = int.from_bytes(h, "big")
        # The table entry for (pos, b) is H * (b << 8*(15-pos)) in
        # GF(2^128) — LINEAR in the bits of the integer operand.  So
        # instead of 4096 bit-serial multiplies (the per-AesGcm cost
        # that made a pure handshake flood expensive for US, not the
        # attacker), precompute the 128 single-bit products with one
        # conditional reduction step each, then build every row by
        # subset-xor.  Bit j of the operand contributes H halved
        # (127-j) times (the _gf128_mul loop order), so:
        p = [0] * 128
        v = hi
        for j in range(127, -1, -1):
            p[j] = v
            v = (v >> 1) ^ _R if v & 1 else v >> 1
        self.table = []
        for pos in range(16):
            base = 8 * (15 - pos)
            row = [0] * 256
            for b in range(1, 256):
                low = b & -b
                row[b] = row[b ^ low] ^ p[base + low.bit_length() - 1]
            self.table.append(row)

    def _mul_h(self, x: int) -> int:
        t = self.table
        acc = 0
        for pos in range(16):
            acc ^= t[pos][(x >> (8 * (15 - pos))) & 0xFF]
        return acc

    def digest(self, aad: bytes, ct: bytes) -> int:
        x = 0
        for buf in (aad, ct):
            for o in range(0, len(buf), 16):
                blk = buf[o : o + 16].ljust(16, b"\0")
                x = self._mul_h(x ^ int.from_bytes(blk, "big"))
        lens = (len(aad) * 8) << 64 | (len(ct) * 8)
        return self._mul_h(x ^ lens)


# ---------------------------------------------------------------------------
# AES-GCM
# ---------------------------------------------------------------------------


class AesGcm:
    """AES-GCM AEAD (96-bit IV), the QUIC packet-protection cipher."""

    def __init__(self, key: bytes):
        self.ks = key_expand(key)
        self.ghash = Ghash(encrypt_block(self.ks, b"\0" * 16))

    def _ctr(self, iv: bytes, n_blocks: int, ctr0: int) -> np.ndarray:
        ctrs = np.zeros((n_blocks, 16), np.uint8)
        ctrs[:, :12] = np.frombuffer(iv, np.uint8)
        cnt = (ctr0 + np.arange(n_blocks, dtype=np.uint64)).astype(">u4")
        ctrs[:, 12:] = cnt.view(np.uint8).reshape(-1, 4)
        return encrypt_blocks(self.ks, ctrs)

    def _tag(self, iv: bytes, aad: bytes, ct: bytes) -> bytes:
        s = self.ghash.digest(aad, ct)
        ek0 = self._ctr(iv, 1, 1)[0]
        return (
            int.from_bytes(ek0.tobytes(), "big") ^ s
        ).to_bytes(16, "big")

    def _xor_stream(self, iv: bytes, data: bytes) -> bytes:
        n = (len(data) + 15) // 16
        stream = self._ctr(iv, n, 2).reshape(-1)[: len(data)]
        return (np.frombuffer(data, np.uint8) ^ stream).tobytes()

    def encrypt(self, iv: bytes, plaintext: bytes, aad: bytes) -> bytes:
        """Returns ciphertext || 16-byte tag."""
        assert len(iv) == 12
        ct = self._xor_stream(iv, plaintext)
        return ct + self._tag(iv, aad, ct)

    def decrypt(self, iv: bytes, ct_tag: bytes, aad: bytes) -> bytes | None:
        """Returns plaintext, or None on tag mismatch."""
        assert len(iv) == 12
        if len(ct_tag) < 16:
            return None
        ct, tag = ct_tag[:-16], ct_tag[-16:]
        # constant-time compare: the attacker controls ct+tag on the QUIC
        # packet-protection path, so a short-circuit != would leak the
        # matching prefix length
        if not hmac.compare_digest(self._tag(iv, aad, ct), tag):
            return None
        return self._xor_stream(iv, ct)
