"""ComputeBudgetProgram instruction parsing + the pack cost model.

Behavior contract re-implemented from the reference's consensus-critical
rules (/root/reference/src/ballet/pack/fd_compute_budget_program.h and
fd_pack_cost.h):

  * instruction kinds: 0 RequestUnitsDeprecated (u32 cu, u32 fee),
    1 RequestHeapFrame (u32, 1KiB granular), 2 SetComputeUnitLimit (u32),
    3 SetComputeUnitPrice (u64 micro-lamports/CU); each at most once per
    txn (0 counts as both 2 and 3); any violation fails the txn
  * default CU limit: 200k per non-budget instruction, capped at 1.4M
  * priority reward: ceil(cu_limit * micro_lamports_per_cu / 1e6),
    saturating
  * cost model: 720/signature + 300/writable account + instr-data-bytes/4
    + built-in per-instruction costs (BPF programs cost their CU limit)

All constants below are consensus data, not code.
"""

from __future__ import annotations

from dataclasses import dataclass

from . import txn as T

# base58 decode of ComputeBudget111111111111111111111111111111
COMPUTE_BUDGET_PROGRAM_ID = bytes(
    [
        0x03, 0x06, 0x46, 0x6F, 0xE5, 0x21, 0x17, 0x32,
        0xFF, 0xEC, 0xAD, 0xBA, 0x72, 0xC3, 0x9B, 0xE7,
        0xBC, 0x8C, 0xE5, 0xBB, 0xC5, 0xF7, 0x12, 0x6B,
        0x2C, 0x43, 0x9B, 0x3A, 0x40, 0x00, 0x00, 0x00,
    ]
)

HEAP_FRAME_GRANULARITY = 1024
MICRO_LAMPORTS_PER_LAMPORT = 1_000_000
DEFAULT_INSTR_CU_LIMIT = 200_000
MAX_CU_LIMIT = 1_400_000

COST_PER_SIGNATURE = 720
COST_PER_WRITABLE_ACCT = 300
INV_COST_PER_INSTR_DATA_BYTE = 4

FEE_PER_SIGNATURE = 5000  # lamports

_FLAG_SET_CU = 0x01
_FLAG_SET_FEE = 0x02
_FLAG_SET_HEAP = 0x04
_FLAG_SET_TOTAL_FEE = 0x08

_U32_MAX = (1 << 32) - 1
_U64_MAX = (1 << 64) - 1


@dataclass
class BudgetState:
    flags: int = 0
    instr_cnt: int = 0
    compute_units: int = 0
    total_fee: int = 0
    heap_size: int = 0
    micro_lamports_per_cu: int = 0

    def parse_instr(self, data: bytes) -> bool:
        """Digest one ComputeBudgetProgram instruction; False = txn fails."""
        if len(data) < 5:
            return False
        kind = data[0]
        if kind == 0:
            if len(data) != 9:
                return False
            if self.flags & (_FLAG_SET_CU | _FLAG_SET_FEE):
                return False
            self.compute_units = int.from_bytes(data[1:5], "little")
            self.total_fee = int.from_bytes(data[5:9], "little")
            if self.compute_units > MAX_CU_LIMIT:
                return False
            self.flags |= _FLAG_SET_CU | _FLAG_SET_FEE | _FLAG_SET_TOTAL_FEE
        elif kind == 1:
            if len(data) != 5:
                return False
            if self.flags & _FLAG_SET_HEAP:
                return False
            self.heap_size = int.from_bytes(data[1:5], "little")
            if self.heap_size % HEAP_FRAME_GRANULARITY:
                return False
            self.flags |= _FLAG_SET_HEAP
        elif kind == 2:
            if len(data) != 5:
                return False
            if self.flags & _FLAG_SET_CU:
                return False
            self.compute_units = int.from_bytes(data[1:5], "little")
            if self.compute_units > MAX_CU_LIMIT:
                return False
            self.flags |= _FLAG_SET_CU
        elif kind == 3:
            if len(data) != 9:
                return False
            if self.flags & _FLAG_SET_FEE:
                return False
            self.micro_lamports_per_cu = int.from_bytes(data[1:9], "little")
            self.flags |= _FLAG_SET_FEE
        else:
            return False
        self.instr_cnt += 1
        return True

    def finalize(self, total_instr_cnt: int) -> tuple[int, int]:
        """(priority_rewards_lamports, cu_limit)."""
        if self.flags & _FLAG_SET_CU:
            cu_limit = self.compute_units
        else:
            cu_limit = (total_instr_cnt - self.instr_cnt) * DEFAULT_INSTR_CU_LIMIT
        cu_limit = min(cu_limit, MAX_CU_LIMIT)
        if self.flags & _FLAG_SET_TOTAL_FEE:
            rewards = self.total_fee
        else:
            # ceil(cu_limit * price / 1e6), saturating at u64 max (Python
            # ints don't overflow, so the reference's split-multiply dance
            # collapses to one expression)
            rewards = min(
                -(-cu_limit * self.micro_lamports_per_cu // MICRO_LAMPORTS_PER_LAMPORT),
                _U64_MAX,
            )
        return rewards, cu_limit


# built-in program costs (block_cost_limits.rs values mirrored by
# fd_pack_cost.h MAP_PERFECT_0..11, consensus constants); keyed by raw
# program id.  Programs not in this table are BPF: they cost their CU
# limit.  Without this table every native-program txn would fall through
# to the 200K default CU and a block would cap at ~240 txns.
def _pid(b58: str) -> bytes:
    from firedancer_tpu.ballet.base58 import decode_32

    return decode_32(b58)


BUILTIN_COSTS: dict[bytes, int] = {
    COMPUTE_BUDGET_PROGRAM_ID: 150,
    _pid("Stake11111111111111111111111111111111111111"): 750,
    _pid("Config1111111111111111111111111111111111111"): 450,
    _pid("Vote111111111111111111111111111111111111111"): 2100,
    bytes(32): 150,  # system program
    _pid("AddressLookupTab1e1111111111111111111111111"): 750,
    _pid("BPFLoaderUpgradeab1e11111111111111111111111"): 2370,
    _pid("BPFLoader1111111111111111111111111111111111"): 1140,
    _pid("BPFLoader2111111111111111111111111111111111"): 570,
    _pid("LoaderV411111111111111111111111111111111111"): 2000,
    _pid("KeccakSecp256k11111111111111111111111111111"): 720,
    _pid("Ed25519SigVerify111111111111111111111111111"): 720,
}


@dataclass(frozen=True)
class TxnEstimate:
    rewards: int  # lamports (saturated to u32 like the reference)
    cost: int  # total cost units charged against block/account budgets
    cu_limit: int
    ok: bool


def estimate(payload: bytes, desc: T.TxnDesc) -> TxnEstimate:
    """Rewards + cost for one parsed txn (fd_pack_estimate_rewards_and_compute
    behavior, /root/reference/src/ballet/pack/fd_pack.c:541-580)."""
    st = BudgetState()
    data_bytes = 0
    builtin_cost = 0
    bpf = False
    for ins in desc.instr:
        data_bytes += ins.data_sz
        prog = desc.acct_addr(payload, ins.program_id)
        if prog == COMPUTE_BUDGET_PROGRAM_ID:
            if not st.parse_instr(
                payload[ins.data_off : ins.data_off + ins.data_sz]
            ):
                return TxnEstimate(0, 0, 0, False)
            builtin_cost += BUILTIN_COSTS[bytes(prog)]
        elif bytes(prog) in BUILTIN_COSTS:
            builtin_cost += BUILTIN_COSTS[bytes(prog)]
        else:
            bpf = True
    adtl_rewards, cu_limit = st.finalize(desc.instr_cnt)
    sig_rewards = FEE_PER_SIGNATURE * desc.signature_cnt
    rewards = min(sig_rewards + adtl_rewards, _U32_MAX)
    writable_cnt = len(desc.writable_idxs()) + desc.addr_table_adtl_writable_cnt
    cost = (
        COST_PER_SIGNATURE * desc.signature_cnt
        + COST_PER_WRITABLE_ACCT * writable_cnt
        + data_bytes // INV_COST_PER_INSTR_DATA_BYTE
        + builtin_cost
        + (cu_limit if bpf else 0)
    )
    return TxnEstimate(rewards, cost, cu_limit, True)
