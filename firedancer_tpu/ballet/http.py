"""Minimal HTTP/1.1: sans-IO request/response codec + a tiny threaded
server and client.

Reference model: src/ballet/http/ (vendored picohttpparser serving the
metrics endpoint and downloading snapshots).  This build needs the same
two uses — the Prometheus metric tile (tiles/metric.py) and snapshot
transfer (flamenco/snapshot.py) — so the codec is written fresh and kept
deliberately small: request line + headers + content-length bodies, no
chunked encoding, no keep-alive pipelining beyond sequential reuse.
"""

from __future__ import annotations

import socket
import threading
from dataclasses import dataclass, field


@dataclass
class Request:
    method: str
    path: str
    version: str
    headers: dict[str, str] = field(default_factory=dict)
    body: bytes = b""


def parse_request(data: bytes) -> tuple[Request | None, int]:
    """(request, bytes consumed); (None, 0) if incomplete; raises
    ValueError on malformed input."""
    end = data.find(b"\r\n\r\n")
    if end < 0:
        if len(data) > 65536:
            raise ValueError("header block too large")
        return None, 0
    head = data[:end].decode("latin1")
    lines = head.split("\r\n")
    parts = lines[0].split(" ")
    if len(parts) != 3 or not parts[2].startswith("HTTP/"):
        raise ValueError("bad request line")
    req = Request(parts[0], parts[1], parts[2])
    for ln in lines[1:]:
        if ":" not in ln:
            raise ValueError("bad header")
        k, v = ln.split(":", 1)
        req.headers[k.strip().lower()] = v.strip()
    n = int(req.headers.get("content-length", "0"))
    if n < 0 or n > 1 << 30:
        raise ValueError("bad content-length")
    total = end + 4 + n
    if len(data) < total:
        return None, 0
    req.body = data[end + 4 : total]
    return req, total


def build_response(
    status: int = 200,
    body: bytes = b"",
    content_type: str = "text/plain; charset=utf-8",
    headers: dict[str, str] | None = None,
) -> bytes:
    reason = {200: "OK", 404: "Not Found", 400: "Bad Request",
              500: "Internal Server Error"}.get(status, "OK")
    h = {
        "Content-Type": content_type,
        "Content-Length": str(len(body)),
        "Connection": "close",
    }
    h.update(headers or {})
    head = f"HTTP/1.1 {status} {reason}\r\n" + "".join(
        f"{k}: {v}\r\n" for k, v in h.items()
    )
    return head.encode("latin1") + b"\r\n" + body


def parse_response(data: bytes) -> tuple[int, dict[str, str], bytes]:
    """Full response bytes -> (status, headers, body)."""
    end = data.find(b"\r\n\r\n")
    if end < 0:
        raise ValueError("incomplete response")
    lines = data[:end].decode("latin1").split("\r\n")
    status = int(lines[0].split(" ")[1])
    headers = {}
    for ln in lines[1:]:
        k, v = ln.split(":", 1)
        headers[k.strip().lower()] = v.strip()
    return status, headers, data[end + 4 :]


class HttpServer:
    """Threaded one-request-per-connection server (the metric tile's
    scrape endpoint; scrape cadence makes keep-alive irrelevant)."""

    def __init__(self, handler, addr=("127.0.0.1", 0)):
        """handler(Request) -> (status, body, content_type)"""
        self.handler = handler
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(addr)
        self.sock.listen(16)
        self.addr = self.sock.getsockname()
        self._stop = False
        self.thread = threading.Thread(
            target=self._serve, name="http", daemon=True
        )
        self.thread.start()

    def _serve(self) -> None:
        while not self._stop:
            try:
                conn, _peer = self.sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._one, args=(conn,), daemon=True
            ).start()

    def _one(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(5.0)
            buf = b""
            while True:
                try:
                    req, consumed = parse_request(buf)
                except ValueError:
                    conn.sendall(build_response(400, b"bad request\n"))
                    return
                if req is not None:
                    break
                chunk = conn.recv(65536)
                if not chunk:
                    return
                buf += chunk
            try:
                status, body, ctype = self.handler(req)
            except Exception:
                status, body, ctype = 500, b"internal error\n", "text/plain"
            if isinstance(body, (bytes, bytearray)):
                conn.sendall(build_response(status, bytes(body), ctype))
            else:
                # streamed body (iterator of byte chunks): CHUNKED
                # framing, O(chunk) memory on both ends.  Close-framing
                # would make a mid-stream server failure look like a
                # clean EOF to the client; the terminal 0-chunk is what
                # lets get_stream distinguish truncation from success.
                reason = {200: "OK"}.get(status, "OK")
                conn.sendall(
                    (
                        f"HTTP/1.1 {status} {reason}\r\n"
                        f"Content-Type: {ctype}\r\n"
                        f"Transfer-Encoding: chunked\r\n"
                        f"Connection: close\r\n\r\n"
                    ).encode("latin1")
                )
                for chunk in body:
                    if chunk:
                        conn.sendall(
                            f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n"
                        )
                conn.sendall(b"0\r\n\r\n")
        except OSError:
            pass
        finally:
            conn.close()

    def close(self) -> None:
        self._stop = True
        try:
            self.sock.close()
        except OSError:
            pass


def get(addr: tuple[str, int], path: str, timeout: float = 5.0) -> tuple[int, bytes]:
    """Tiny client: GET path -> (status, body)."""
    with socket.create_connection(addr, timeout=timeout) as s:
        s.sendall(
            f"GET {path} HTTP/1.1\r\nHost: {addr[0]}\r\n"
            f"Connection: close\r\n\r\n".encode()
        )
        data = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
    status, _h, body = parse_response(data)
    return status, body


def get_stream(addr: tuple[str, int], path: str, sink,
               timeout: float = 30.0) -> tuple[int, int]:
    """Streaming GET: body chunks go to sink(bytes) as they arrive —
    O(chunk) client memory (the snapshot download path; reference:
    fd_snapshot_http.c's incremental read state machine).  Returns
    (status, body_bytes)."""
    with socket.create_connection(addr, timeout=timeout) as s:
        s.sendall(
            f"GET {path} HTTP/1.1\r\nHost: {addr[0]}\r\n"
            f"Connection: close\r\n\r\n".encode()
        )
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = s.recv(65536)
            if not chunk:
                raise ValueError("connection closed before headers")
            buf += chunk
        head, rest = buf.split(b"\r\n\r\n", 1)
        lines = head.decode("latin1").split("\r\n")
        status = int(lines[0].split(" ")[1])
        headers = {}
        for ln in lines[1:]:
            k, v = ln.split(":", 1)
            headers[k.strip().lower()] = v.strip()
        if headers.get("transfer-encoding", "").lower() == "chunked":
            return status, _read_chunked(s, rest, sink)
        want = int(headers["content-length"]) if "content-length" in headers else None
        n = 0
        if rest:
            sink(rest)
            n += len(rest)
        while want is None or n < want:
            chunk = s.recv(262144)
            if not chunk:
                break
            sink(chunk)
            n += len(chunk)
        if want is not None and n != want:
            raise ValueError("short body")
        # want is None: close-framed legacy body — length UNVERIFIED
        # (our own streamed responses are chunked; only foreign servers
        # reach this path)
        return status, n


def _read_chunked(s, buf: bytes, sink) -> int:
    """Decode a chunked body; raises on truncation (the framing is what
    makes a mid-stream peer death detectable — the terminal 0-chunk
    never arrives)."""
    buf = bytearray(buf)
    n = 0

    def fill() -> None:
        blk = s.recv(262144)
        if not blk:
            raise ValueError("connection closed mid-chunk")
        buf.extend(blk)

    while True:
        while b"\r\n" not in buf:
            fill()
        line, _, rest = bytes(buf).partition(b"\r\n")
        buf = bytearray(rest)
        size = int(line.split(b";")[0], 16)
        while len(buf) < size + 2:
            fill()
        if size == 0:
            return n
        sink(bytes(buf[:size]))
        n += size
        if buf[size : size + 2] != b"\r\n":
            raise ValueError("bad chunk terminator")
        del buf[: size + 2]
