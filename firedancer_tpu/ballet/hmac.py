"""HMAC-SHA256 / HMAC-SHA512 over the batch hash kernels.

Behavior contract: src/ballet/hmac/fd_hmac.c (RFC 2104).  Built on the
device-batched SHA kernels (ops/sha256, ops/sha512), so a batch of MACs
is two batched hash dispatches."""

from __future__ import annotations

import numpy as np

from firedancer_tpu.ops import sha256 as S256
from firedancer_tpu.ops import sha512 as S512

_BLOCK = {"sha256": 64, "sha512": 128}
_OUT = {"sha256": 32, "sha512": 64}


def _hash_batch(algo: str, msgs: np.ndarray, lens: np.ndarray) -> np.ndarray:
    if algo == "sha256":
        return np.asarray(S256.sha256(msgs, lens))
    return np.asarray(S512.sha512(msgs, lens))


def hmac_batch(algo: str, keys: np.ndarray, msgs: np.ndarray, lens) -> np.ndarray:
    """Batch HMAC.  keys (B, key_len<=block) u8, msgs (B, W) u8, lens (B,).

    Returns (B, 32|64) u8.  Keys longer than the block must be pre-hashed
    by the caller (RFC 2104)."""
    block, out_sz = _BLOCK[algo], _OUT[algo]
    B = len(keys)
    lens = np.asarray(lens, np.int64)
    assert keys.shape[1] <= block
    k = np.zeros((B, block), np.uint8)
    k[:, : keys.shape[1]] = keys

    inner = np.zeros((B, block + msgs.shape[1]), np.uint8)
    inner[:, :block] = k ^ 0x36
    inner[:, block : block + msgs.shape[1]] = msgs
    # zero padding bytes beyond each row's len (msgs may carry garbage)
    col = np.arange(msgs.shape[1])[None, :]
    inner[:, block:] = np.where(col < lens[:, None], inner[:, block:], 0)
    ih = _hash_batch(algo, inner, (block + lens).astype(np.int32))

    outer = np.zeros((B, block + out_sz), np.uint8)
    outer[:, :block] = k ^ 0x5C
    outer[:, block:] = ih
    return _hash_batch(
        algo, outer, np.full(B, block + out_sz, np.int32)
    )


def hmac_sha256(key: bytes, msg: bytes) -> bytes:
    if len(key) > 64:
        key = bytes(_hash_batch("sha256", np.frombuffer(key, np.uint8)[None, :],
                                np.array([len(key)]))[0])
    out = hmac_batch(
        "sha256",
        np.frombuffer(key, np.uint8)[None, :],
        np.frombuffer(msg, np.uint8)[None, :] if msg else np.zeros((1, 0), np.uint8),
        np.array([len(msg)]),
    )
    return bytes(out[0])


def hmac_sha512(key: bytes, msg: bytes) -> bytes:
    if len(key) > 128:
        key = bytes(_hash_batch("sha512", np.frombuffer(key, np.uint8)[None, :],
                                np.array([len(key)]))[0])
    out = hmac_batch(
        "sha512",
        np.frombuffer(key, np.uint8)[None, :],
        np.frombuffer(msg, np.uint8)[None, :] if msg else np.zeros((1, 0), np.uint8),
        np.array([len(msg)]),
    )
    return bytes(out[0])
