"""Base58 encode/decode with fast fixed-size paths.

Behavior contract: src/ballet/base58/ (reference has dedicated 32- and
64-byte paths because validator hot paths only ever encode pubkeys and
signatures).  Host-side: base58 is used for logs/RPC/keys, never on the
packet hot path, so this is vectorized numpy over limbs rather than a
device kernel.
"""

from __future__ import annotations

import numpy as np

ALPHABET = b"123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
_INV = np.full(128, -1, dtype=np.int8)
for _i, _c in enumerate(ALPHABET):
    _INV[_c] = _i

#: encoded lengths of the fixed paths (reference: FD_BASE58_ENCODED_32_SZ=45,
#: FD_BASE58_ENCODED_64_SZ=89 include the NUL; lengths here are max chars)
ENCODED_32_MAX = 44
ENCODED_64_MAX = 88


def encode(data: bytes) -> str:
    """Generic base58 encode (big-endian base conversion)."""
    n_zeros = len(data) - len(data.lstrip(b"\0"))
    num = int.from_bytes(data, "big")
    out = bytearray()
    while num:
        num, rem = divmod(num, 58)
        out.append(ALPHABET[rem])
    out += b"1" * n_zeros
    return bytes(reversed(out)).decode()

def decode(s: str | bytes, expected_len: int | None = None) -> bytes | None:
    """Generic base58 decode; None on bad char or length mismatch."""
    if isinstance(s, str):
        s = s.encode()
    if not s:
        return None if expected_len not in (None, 0) else b""
    num = 0
    for ch in s:
        if ch >= 128 or _INV[ch] < 0:
            return None
        num = num * 58 + int(_INV[ch])
    n_ones = len(s) - len(bytes(s).lstrip(b"1"))
    body = num.to_bytes((num.bit_length() + 7) // 8, "big")
    out = b"\0" * n_ones + body
    if expected_len is not None and len(out) != expected_len:
        return None
    return out


def encode_32(data: bytes) -> str:
    """Pubkey path (reference: fd_base58_encode_32)."""
    assert len(data) == 32
    return encode(data)


def encode_64(data: bytes) -> str:
    """Signature path (reference: fd_base58_encode_64)."""
    assert len(data) == 64
    return encode(data)


def decode_32(s: str | bytes) -> bytes | None:
    return decode(s, 32)


def decode_64(s: str | bytes) -> bytes | None:
    return decode(s, 64)
