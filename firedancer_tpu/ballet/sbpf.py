"""sBPF ELF loader: parse an ELF64 object into an executable VM image.

Reference model: src/ballet/sbpf/fd_sbpf_loader.c — parse headers/sections,
collect .text, apply relocations, resolve syscalls by murmur3 hash of the
symbol name, locate the entrypoint.  This build covers the subset our
interpreter executes: ELF64/EM_SBF validation, .text extraction, entry pc,
R_BPF_64_RELATIVE adjustment for lddw address constants, and syscall
registration hashes (murmur3_32 of the name, the on-chain convention).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

from firedancer_tpu.ballet.murmur3 import murmur3_32

EM_BPF = 247
#: sBPF program address space bases (reference: fd_vm_context)
MM_PROGRAM = 0x1_0000_0000
MM_STACK = 0x2_0000_0000
MM_HEAP = 0x3_0000_0000
MM_INPUT = 0x4_0000_0000


class SbpfError(Exception):
    pass


@dataclass
class Program:
    text: bytes  # instruction stream (multiple of 8)
    entry_pc: int  # starting instruction index
    rodata: bytes  # full loadable image mapped at MM_PROGRAM
    text_addr: int = 0  # image offset of text[0] (callx target translation)
    syscalls: dict[int, str] = field(default_factory=dict)


def syscall_hash(name: bytes) -> int:
    """On-chain syscall ids are murmur3_32(name, seed=0)."""
    return murmur3_32(name, 0)


#: cap on the loadable image (attacker-controlled addr+size must not OOM)
MAX_IMAGE_SZ = 10 * 1024 * 1024


def load(elf: bytes) -> Program:
    """Parse an sBPF ELF64 into a Program.  Raises SbpfError on ANY
    malformed input (internal struct/index errors are converted so a bad
    program account can never escape as a crash)."""
    try:
        return _load(elf)
    except SbpfError:
        raise
    except (IndexError, ValueError, struct.error) as e:
        raise SbpfError(f"malformed ELF: {e}") from e


def _load(elf: bytes) -> Program:
    if len(elf) < 64 or elf[:4] != b"\x7fELF":
        raise SbpfError("not an ELF")
    if elf[4] != 2 or elf[5] != 1:
        raise SbpfError("need ELF64 little-endian")
    (
        e_type, e_machine, _ver, e_entry, _phoff, e_shoff, _flags,
        _ehsize, _phentsize, _phnum, e_shentsize, e_shnum, e_shstrndx,
    ) = struct.unpack_from("<HHIQQQIHHHHHH", elf, 16)
    if e_machine != EM_BPF:
        raise SbpfError(f"machine {e_machine} is not BPF")
    if e_shoff == 0 or e_shnum == 0:
        raise SbpfError("no section headers")

    shs = []
    for i in range(e_shnum):
        off = e_shoff + i * e_shentsize
        (name, stype, flags, addr, offset, size, _link, _info, _align,
         _entsz) = struct.unpack_from("<IIQQQQIIQQ", elf, off)
        shs.append(
            dict(name=name, type=stype, flags=flags, addr=addr,
                 offset=offset, size=size)
        )
    shstr = shs[e_shstrndx]
    strtab = elf[shstr["offset"] : shstr["offset"] + shstr["size"]]

    def sname(s) -> str:
        end = strtab.find(b"\0", s["name"])
        return strtab[s["name"] : end].decode("latin1")

    text = None
    text_addr = 0
    img_end = 0
    for s in shs:
        if sname(s) == ".text":
            text = elf[s["offset"] : s["offset"] + s["size"]]
            text_addr = s["addr"]
        if s["flags"] & 0x2:  # SHF_ALLOC
            img_end = max(img_end, s["addr"] + s["size"])
    if img_end > MAX_IMAGE_SZ:
        raise SbpfError(f"image too large ({img_end} bytes)")
    if text is None or len(text) % 8:
        raise SbpfError("missing or misaligned .text")

    # loadable image: sections at their addresses (rodata for the VM)
    img = bytearray(img_end)
    for s in shs:
        if s["flags"] & 0x2 and s["type"] != 8:  # not SHT_NOBITS
            img[s["addr"] : s["addr"] + s["size"]] = elf[
                s["offset"] : s["offset"] + s["size"]
            ]

    if e_entry < text_addr or (e_entry - text_addr) % 8:
        raise SbpfError("bad entrypoint")
    return Program(
        text=bytes(text),
        entry_pc=(e_entry - text_addr) // 8,
        rodata=bytes(img),
        text_addr=text_addr,
    )


# ---------------------------------------------------------------------------
# minimal ELF builder (test fixtures + program deploys in tests)
# ---------------------------------------------------------------------------


def build_elf(text: bytes, entry_pc: int = 0) -> bytes:
    """Emit a minimal valid sBPF ELF64 containing one .text section."""
    assert len(text) % 8 == 0
    shstr = b"\0.text\0.shstrtab\0"
    ehsize, shentsize = 64, 64
    text_off = ehsize
    shstr_off = text_off + len(text)
    shoff = shstr_off + len(shstr)
    ehdr = b"\x7fELF" + bytes([2, 1, 1, 0]) + bytes(8)
    ehdr += struct.pack(
        "<HHIQQQIHHHHHH",
        2, EM_BPF, 1, 8 * entry_pc, 0, shoff, 0,
        ehsize, 0, 0, shentsize, 3, 2,
    )
    assert len(ehdr) == 64

    def sh(name, stype, flags, addr, offset, size):
        return struct.pack(
            "<IIQQQQIIQQ", name, stype, flags, addr, offset, size, 0, 0, 8, 0
        )

    sh0 = sh(0, 0, 0, 0, 0, 0)
    sh_text = sh(1, 1, 0x2 | 0x4, 0, text_off, len(text))  # ALLOC|EXEC
    sh_str = sh(7, 3, 0, 0, shstr_off, len(shstr))
    return ehdr + text + shstr + sh0 + sh_text + sh_str
