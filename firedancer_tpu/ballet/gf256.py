"""GF(2^8) arithmetic + matrices for Reed-Solomon shred coding.

Field: GF(2^8) mod 0x11D, generator 2 — the field used by Solana's
reed-solomon-erasure backend and the reference's reedsol
(src/ballet/reedsol/; its FFT/PPT machinery is an O(n log n)
factorization of the same code).

The code matrix follows the reed-solomon-erasure construction: an
extended Vandermonde matrix V[r][c] = (α^r)^c made systematic by
right-multiplying with the inverse of its top k×k block, so data shreds
pass through unchanged and parity rows are the bottom n-k rows.

Everything here is small host-side setup (matrices are at most
134×67); the per-byte bulk work runs on the MXU via ops/reedsol.py.
"""

from __future__ import annotations

import numpy as np

POLY = 0x11D

EXP = np.zeros(512, dtype=np.uint8)
LOG = np.zeros(256, dtype=np.int32)
_x = 1
for _i in range(255):
    EXP[_i] = _x
    LOG[_x] = _i
    _x <<= 1
    if _x & 0x100:
        _x ^= POLY
EXP[255:510] = EXP[:255]


def mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(EXP[LOG[a] + LOG[b]])


def div(a: int, b: int) -> int:
    assert b != 0
    if a == 0:
        return 0
    return int(EXP[(LOG[a] - LOG[b]) % 255])


def inv(a: int) -> int:
    assert a != 0
    return int(EXP[(255 - LOG[a]) % 255])


def mat_mul(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product (small host matrices)."""
    n, k = A.shape
    k2, m = B.shape
    assert k == k2
    out = np.zeros((n, m), dtype=np.uint8)
    for i in range(n):
        for j in range(m):
            acc = 0
            for t in range(k):
                acc ^= mul(int(A[i, t]), int(B[t, j]))
            out[i, j] = acc
    return out


def mat_inv(A: np.ndarray) -> np.ndarray:
    """GF(2^8) Gauss-Jordan inversion; raises on singular."""
    n = len(A)
    a = A.astype(np.int32).copy()
    e = np.eye(n, dtype=np.int32)
    for col in range(n):
        piv = next((r for r in range(col, n) if a[r, col]), None)
        if piv is None:
            raise ValueError("singular matrix")
        if piv != col:
            a[[col, piv]] = a[[piv, col]]
            e[[col, piv]] = e[[piv, col]]
        iv = inv(int(a[col, col]))
        for j in range(n):
            a[col, j] = mul(int(a[col, j]), iv)
            e[col, j] = mul(int(e[col, j]), iv)
        for r in range(n):
            if r != col and a[r, col]:
                f = int(a[r, col])
                for j in range(n):
                    a[r, j] ^= mul(f, int(a[col, j]))
                    e[r, j] ^= mul(f, int(e[col, j]))
    return e.astype(np.uint8)


def vandermonde(rows: int, cols: int) -> np.ndarray:
    """V[r][c] = (α^r)^c = α^(r·c) (reed-solomon-erasure layout)."""
    out = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            out[r, c] = EXP[(r * c) % 255]
    return out


def code_matrix(data_cnt: int, total_cnt: int) -> np.ndarray:
    """Systematic (total × data) code matrix: top block = identity,
    bottom rows produce parity."""
    assert 0 < data_cnt <= total_cnt <= 255
    v = vandermonde(total_cnt, data_cnt)
    top_inv = mat_inv(v[:data_cnt])
    m = mat_mul(v, top_inv)
    assert (m[:data_cnt] == np.eye(data_cnt, dtype=np.uint8)).all()
    return m


def parity_matrix(data_cnt: int, parity_cnt: int) -> np.ndarray:
    """(parity × data) GF(2^8) matrix mapping data bytes to parity."""
    return code_matrix(data_cnt, data_cnt + parity_cnt)[data_cnt:]


def mul_bitmatrix(c: int) -> np.ndarray:
    """(8, 8) GF(2) matrix of y = c·x over the bits of x:
    column j = bits of c·2^j.  The bit-expansion that turns GF(2^8)
    matrix application into a pure GF(2) matmul (ops/reedsol.py)."""
    out = np.zeros((8, 8), dtype=np.uint8)
    for j in range(8):
        prod = mul(c, 1 << j)
        for i in range(8):
            out[i, j] = (prod >> i) & 1
    return out


def expand_bits(M: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix (P, D) -> GF(2) bit matrix (8P, 8D)."""
    P, D = M.shape
    out = np.zeros((8 * P, 8 * D), dtype=np.uint8)
    for p in range(P):
        for d in range(D):
            out[8 * p : 8 * p + 8, 8 * d : 8 * d + 8] = mul_bitmatrix(
                int(M[p, d])
            )
    return out
