"""Murmur3-32 (reference: src/ballet/murmur3/ — sBPF call target hashing).

Host-side; matches the x86_32 variant the reference implements."""

from __future__ import annotations

_M32 = 0xFFFFFFFF


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _M32


def murmur3_32(data: bytes, seed: int = 0) -> int:
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & _M32
    n = len(data)
    full = n & ~3
    for i in range(0, full, 4):
        k = int.from_bytes(data[i : i + 4], "little")
        k = (k * c1) & _M32
        k = _rotl32(k, 15)
        k = (k * c2) & _M32
        h ^= k
        h = _rotl32(h, 13)
        h = (h * 5 + 0xE6546B64) & _M32
    k = int.from_bytes(data[full:], "little")
    if k:
        k = (k * c1) & _M32
        k = _rotl32(k, 15)
        k = (k * c2) & _M32
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _M32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _M32
    h ^= h >> 16
    return h
