"""Binary SHA-256 Merkle tree (vector commitment for shreds / runtime).

Behavior contract: src/ballet/bmtree/fd_bmtree.{h,c} —
  * leaf  = SHA256(leaf_prefix  || data)[:hash_sz]
  * node  = SHA256(node_prefix || left || right)[:hash_sz]
  * a layer with an odd node count merges its last node with ITSELF
    (fd_bmtree_commit_fini's 1-child branch)
  * 20-byte nodes use the 26-byte long prefixes
    ("\\x00SOLANA_MERKLE_SHREDS_LEAF" / "\\x01...NODE"); 32-byte nodes use
    the 1-byte short prefixes 0x00/0x01 (fd_bmtree.h:133-142)

TPU-native design: the reference hashes node-by-node with an incremental
commit state; here every tree LAYER is one batched SHA-256 dispatch
(ops/sha256), so committing N leaves costs ~log2(N) device calls.
"""

from __future__ import annotations

import numpy as np

from firedancer_tpu.ops import sha256 as S

LEAF_PREFIX_LONG = b"\x00SOLANA_MERKLE_SHREDS_LEAF"
NODE_PREFIX_LONG = b"\x01SOLANA_MERKLE_SHREDS_NODE"
LEAF_PREFIX_SHORT = b"\x00"
NODE_PREFIX_SHORT = b"\x01"


def _prefixes(hash_sz: int) -> tuple[bytes, bytes]:
    if hash_sz == 20:
        return LEAF_PREFIX_LONG, NODE_PREFIX_LONG
    assert hash_sz == 32
    return LEAF_PREFIX_SHORT, NODE_PREFIX_SHORT


#: below this many messages a layer hashes on the HOST — a handful of
#: sha256 calls never amortizes a device dispatch (see ops/reedsol
#: HOST_MAX_BYTES for the same reasoning on the shred path)
HOST_MAX_MSGS = int(
    __import__("os").environ.get("FDT_BMTREE_HOST_MAX", "512")
)


def _sha_batch(msgs: np.ndarray, lens: np.ndarray) -> np.ndarray:
    if len(msgs) <= HOST_MAX_MSGS:
        import hashlib

        out = np.zeros((len(msgs), 32), np.uint8)
        for i in range(len(msgs)):
            out[i] = np.frombuffer(
                hashlib.sha256(msgs[i, : lens[i]].tobytes()).digest(),
                np.uint8,
            )
        return out
    return np.asarray(S.sha256(msgs, lens))


def hash_leaves(blobs: list[bytes], hash_sz: int = 20) -> np.ndarray:
    """Batch-hash leaf blobs -> (N, hash_sz) nodes."""
    leaf_prefix, _ = _prefixes(hash_sz)
    n = len(blobs)
    w = len(leaf_prefix) + max((len(b) for b in blobs), default=0)
    msgs = np.zeros((n, w), np.uint8)
    lens = np.zeros(n, np.int32)
    for i, b in enumerate(blobs):
        row = leaf_prefix + b
        msgs[i, : len(row)] = np.frombuffer(row, np.uint8)
        lens[i] = len(row)
    return _sha_batch(msgs, lens)[:, :hash_sz]


def _merge_layer(layer: np.ndarray, hash_sz: int) -> np.ndarray:
    """(N, hash_sz) -> (ceil(N/2), hash_sz), one batched dispatch."""
    _, node_prefix = _prefixes(hash_sz)
    n = len(layer)
    if n % 2:
        layer = np.concatenate([layer, layer[-1:]])  # odd: self-merge
    left, right = layer[0::2], layer[1::2]
    p = len(node_prefix)
    msgs = np.zeros((len(left), p + 2 * hash_sz), np.uint8)
    msgs[:, :p] = np.frombuffer(node_prefix, np.uint8)
    msgs[:, p : p + hash_sz] = left
    msgs[:, p + hash_sz :] = right
    lens = np.full(len(left), p + 2 * hash_sz, np.int32)
    return _sha_batch(msgs, lens)[:, :hash_sz]


def commit(blobs: list[bytes], hash_sz: int = 20) -> bytes:
    """Root commitment over the leaf blobs (fd_bmtree_commit_* one-shot)."""
    assert blobs, "empty tree has no root"
    layer = hash_leaves(blobs, hash_sz)
    layers = [layer]
    while len(layer) > 1:
        layer = _merge_layer(layer, hash_sz)
        layers.append(layer)
    return bytes(layer[0])


def layers_of(blobs: list[bytes], hash_sz: int = 20) -> list[np.ndarray]:
    layer = hash_leaves(blobs, hash_sz)
    out = [layer]
    while len(layer) > 1:
        layer = _merge_layer(layer, hash_sz)
        out.append(layer)
    return out


def inclusion_proof(blobs: list[bytes], idx: int, hash_sz: int = 20) -> list[bytes]:
    """Sibling path for leaf idx (bottom-up).  A missing sibling (odd
    tail) is the node itself, matching the self-merge rule."""
    proof = []
    layers = layers_of(blobs, hash_sz)
    for layer in layers[:-1]:
        sib = idx ^ 1
        proof.append(bytes(layer[sib]) if sib < len(layer) else bytes(layer[idx]))
        idx >>= 1
    return proof


def verify_inclusion(
    leaf_blob: bytes, idx: int, proof: list[bytes], root: bytes,
    hash_sz: int = 20,
) -> bool:
    node = bytes(hash_leaves([leaf_blob], hash_sz)[0])
    for sib in proof:
        pair = (node, sib) if idx % 2 == 0 else (sib, node)
        node = bytes(
            _merge_layer(
                np.stack([
                    np.frombuffer(pair[0], np.uint8),
                    np.frombuffer(pair[1], np.uint8),
                ]),
                hash_sz,
            )[0]
        )
        idx >>= 1
    return node == root
