"""Solana transaction wire-format parser and builder (host side).

Behavior contract: fd_txn_parse
(/root/reference/src/ballet/txn/fd_txn_parse.c, fd_txn.h, fd_compact_u16.h)
— re-implemented from the wire format spec with the same validation rules:

  * payload <= 1232 bytes (MTU)
  * 1 <= signature_cnt <= 127, stored identically as u8 and compact-u16
  * legacy (no version byte) and v0 (0x80-flagged version byte) messages
  * readonly_signed < signature_cnt (fee payer must be a writable signer)
  * signature_cnt <= acct_addr_cnt <= 128; sig_cnt + ro_unsigned <= acct cnt
  * <= 64 instructions, program_id index nonzero and in static-account range
  * v0 address-table lookups: >= 1 referenced account per table, totals
    bounded so static + looked-up accounts <= 128
  * every instruction account index < total referenced accounts
  * compact-u16 must be minimally encoded; trailing bytes rejected

The parser runs on the ingest host path (verify/dedup/pack tiles).  Batched
fixed-field extraction for the device (signature/pubkey/message slices) is in
`extract_sigverify_batch`, which the verify tile uses to build TPU batches.

This module is pure Python over bytes/numpy; per-txn parse runs on the
control path only (ingest tiles parse once, then every consumer reads the
trailer fields — tiles/wire.py — with vectorized gathers).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

MTU = 1232
SIGNATURE_SZ = 64
ACCT_ADDR_SZ = 32
BLOCKHASH_SZ = 32
SIG_MAX = 127
ACTUAL_SIG_MAX = 12
ACCT_ADDR_MAX = 128
ADDR_TABLE_LOOKUP_MAX = 127
INSTR_MAX = 64
MIN_SERIALIZED_SZ = 134

VLEGACY = 0xFF
V0 = 0x00


def cu16_decode(buf: bytes, i: int) -> Optional[Tuple[int, int]]:
    """Decode a compact-u16 at offset i -> (value, nbytes) or None.

    Minimal-encoding enforced (0x80 0x00 style paddings rejected), max 3
    bytes, value < 2^16.
    """
    n = len(buf)
    if i < n and not (buf[i] & 0x80):
        return buf[i], 1
    if i + 1 < n and not (buf[i + 1] & 0x80):
        if buf[i + 1] == 0:
            return None
        return (buf[i] & 0x7F) | (buf[i + 1] << 7), 2
    if i + 2 < n and not (buf[i + 2] & 0xFC):
        if buf[i + 2] == 0:
            return None
        return (buf[i] & 0x7F) | ((buf[i + 1] & 0x7F) << 7) | (buf[i + 2] << 14), 3
    return None


def cu16_encode(v: int) -> bytes:
    assert 0 <= v < 1 << 16
    if v < 0x80:
        return bytes([v])
    if v < 0x4000:
        return bytes([(v & 0x7F) | 0x80, v >> 7])
    return bytes([(v & 0x7F) | 0x80, ((v >> 7) & 0x7F) | 0x80, v >> 14])


@dataclass(frozen=True)
class Instr:
    program_id: int  # index into static account addrs
    acct_off: int
    acct_cnt: int
    data_off: int
    data_sz: int


@dataclass(frozen=True)
class AddrLut:
    addr_off: int  # offset of the 32-byte table address
    writable_off: int
    writable_cnt: int
    readonly_off: int
    readonly_cnt: int


@dataclass(frozen=True)
class TxnDesc:
    """Offset descriptor into the payload (fd_txn_t equivalent)."""

    transaction_version: int
    signature_cnt: int
    signature_off: int
    message_off: int
    readonly_signed_cnt: int
    readonly_unsigned_cnt: int
    acct_addr_cnt: int
    acct_addr_off: int
    recent_blockhash_off: int
    addr_table_lookup_cnt: int
    addr_table_adtl_writable_cnt: int
    addr_table_adtl_cnt: int
    instr_cnt: int
    instr: Tuple[Instr, ...] = ()
    address_tables: Tuple[AddrLut, ...] = ()
    #: serialized size of the parsed region (== len(payload) unless
    #: allow_trailing was set)
    sz: int = 0

    # -- account-category helpers (fd_txn_acct_iter equivalents) ----------

    @property
    def total_acct_cnt(self) -> int:
        return self.acct_addr_cnt + self.addr_table_adtl_cnt

    def signatures(self, payload: bytes) -> List[bytes]:
        o = self.signature_off
        return [
            payload[o + 64 * j : o + 64 * (j + 1)]
            for j in range(self.signature_cnt)
        ]

    def acct_addr(self, payload: bytes, j: int) -> bytes:
        o = self.acct_addr_off + 32 * j
        return payload[o : o + 32]

    def message(self, payload: bytes) -> bytes:
        return payload[self.message_off :]

    def recent_blockhash(self, payload: bytes) -> bytes:
        o = self.recent_blockhash_off
        return payload[o : o + 32]

    def is_writable(self, j: int) -> bool:
        """Writability of combined account index j: signer section, static
        unsigned section, then ALT lookups (writable section first)."""
        if j < self.signature_cnt:
            return j < self.signature_cnt - self.readonly_signed_cnt
        if j < self.acct_addr_cnt:
            return j < self.acct_addr_cnt - self.readonly_unsigned_cnt
        return j < self.acct_addr_cnt + self.addr_table_adtl_writable_cnt

    def writable_idxs(self) -> List[int]:
        return [j for j in range(self.acct_addr_cnt) if self.is_writable(j)]

    def readonly_idxs(self) -> List[int]:
        return [j for j in range(self.acct_addr_cnt) if not self.is_writable(j)]


def parse(payload: bytes, allow_zero_signatures: bool = False,
          allow_trailing: bool = False) -> Optional[TxnDesc]:
    """Parse + validate one serialized txn.  Returns None on any violation.

    Trailing bytes after the parsed region are rejected (the strict mode the
    ingress tiles use) unless allow_trailing is set (embedded-txn decode,
    e.g. the gossip vote CRDS datum); desc.sz is the consumed size.
    """
    n = len(payload)
    if n > MTU:
        return None
    azs = allow_zero_signatures
    if not azs and n < MIN_SERIALIZED_SZ:
        return None
    i = 0

    if n - i < 1:
        return None
    signature_cnt = payload[i]
    i += 1
    if not azs and not (1 <= signature_cnt <= SIG_MAX):
        return None
    if SIGNATURE_SZ * signature_cnt > n - i:
        return None
    signature_off = i
    i += SIGNATURE_SZ * signature_cnt

    message_off = i
    if n - i < 1:
        return None
    header_b0 = payload[i]
    i += 1
    if header_b0 & 0x80:
        transaction_version = header_b0 & 0x7F
        if transaction_version != V0:
            return None
        if n - i < 1 or payload[i] != signature_cnt:
            return None
        i += 1
    else:
        transaction_version = VLEGACY
        if header_b0 != signature_cnt:
            return None

    if n - i < 1:
        return None
    ro_signed_cnt = payload[i]
    i += 1
    if not azs and not ro_signed_cnt < signature_cnt:
        return None
    if n - i < 1:
        return None
    ro_unsigned_cnt = payload[i]
    i += 1

    dec = cu16_decode(payload, i)
    if dec is None:
        return None
    acct_addr_cnt, sz = dec
    i += sz
    if not (signature_cnt <= acct_addr_cnt <= ACCT_ADDR_MAX):
        return None
    if signature_cnt + ro_unsigned_cnt > acct_addr_cnt:
        return None

    if ACCT_ADDR_SZ * acct_addr_cnt > n - i:
        return None
    acct_addr_off = i
    i += ACCT_ADDR_SZ * acct_addr_cnt
    if BLOCKHASH_SZ > n - i:
        return None
    recent_blockhash_off = i
    i += BLOCKHASH_SZ

    dec = cu16_decode(payload, i)
    if dec is None:
        return None
    instr_cnt, sz = dec
    i += sz
    if instr_cnt > INSTR_MAX:
        return None
    if 3 * instr_cnt > n - i:
        return None
    if not azs and instr_cnt and acct_addr_cnt <= 1:
        return None

    max_acct = 0
    instrs = []
    for _ in range(instr_cnt):
        if 3 > n - i:
            return None
        program_id = payload[i]
        i += 1
        dec = cu16_decode(payload, i)
        if dec is None:
            return None
        acct_cnt, sz = dec
        i += sz
        if acct_cnt > n - i:
            return None
        acct_off = i
        for k in range(acct_cnt):
            max_acct = max(max_acct, payload[i + k])
        i += acct_cnt
        dec = cu16_decode(payload, i)
        if dec is None:
            return None
        data_sz, sz = dec
        i += sz
        if data_sz > n - i:
            return None
        data_off = i
        i += data_sz
        if not azs and not (0 < program_id < acct_addr_cnt):
            return None
        instrs.append(Instr(program_id, acct_off, acct_cnt, data_off, data_sz))

    addr_table_cnt = 0
    adtl_writable = 0
    adtl = 0
    luts = []
    if transaction_version == V0:
        dec = cu16_decode(payload, i)
        if dec is None:
            return None
        addr_table_cnt, sz = dec
        i += sz
        if addr_table_cnt > ADDR_TABLE_LOOKUP_MAX:
            return None
        if 34 * addr_table_cnt > n - i:
            return None
        for _ in range(addr_table_cnt):
            if ACCT_ADDR_SZ > n - i:
                return None
            addr_off = i
            i += ACCT_ADDR_SZ
            dec = cu16_decode(payload, i)
            if dec is None:
                return None
            writable_cnt, sz = dec
            i += sz
            if writable_cnt > n - i:
                return None
            writable_off = i
            i += writable_cnt
            dec = cu16_decode(payload, i)
            if dec is None:
                return None
            readonly_cnt, sz = dec
            i += sz
            if readonly_cnt > n - i:
                return None
            readonly_off = i
            i += readonly_cnt
            if writable_cnt > ACCT_ADDR_MAX - acct_addr_cnt:
                return None
            if readonly_cnt > ACCT_ADDR_MAX - acct_addr_cnt:
                return None
            if writable_cnt + readonly_cnt < 1:
                return None
            luts.append(
                AddrLut(addr_off, writable_off, writable_cnt, readonly_off,
                        readonly_cnt)
            )
            adtl_writable += writable_cnt
            adtl += writable_cnt + readonly_cnt

    if not allow_trailing and i != n:
        return None
    if acct_addr_cnt + adtl > ACCT_ADDR_MAX:
        return None
    # unconditional like the reference: with no instrs max_acct is 0, so a
    # zero-account txn is rejected even under allow_zero_signatures
    if max_acct >= acct_addr_cnt + adtl:
        return None

    return TxnDesc(
        transaction_version=transaction_version,
        signature_cnt=signature_cnt,
        signature_off=signature_off,
        message_off=message_off,
        readonly_signed_cnt=ro_signed_cnt,
        readonly_unsigned_cnt=ro_unsigned_cnt,
        acct_addr_cnt=acct_addr_cnt,
        acct_addr_off=acct_addr_off,
        recent_blockhash_off=recent_blockhash_off,
        addr_table_lookup_cnt=addr_table_cnt,
        addr_table_adtl_writable_cnt=adtl_writable,
        addr_table_adtl_cnt=adtl,
        instr_cnt=instr_cnt,
        instr=tuple(instrs),
        address_tables=tuple(luts),
        sz=i,
    )


# ---------------------------------------------------------------------------
# Builder (tests + synthetic load generation; analog of the reference's
# fddev benchg txn generator, src/app/fddev/tiles/fd_benchg.c behavior)
# ---------------------------------------------------------------------------


def build(
    signatures: Sequence[bytes],
    acct_addrs: Sequence[bytes],
    recent_blockhash: bytes,
    instrs: Sequence[Tuple[int, Sequence[int], bytes]],
    readonly_signed_cnt: int = 0,
    readonly_unsigned_cnt: int = 0,
    version: int = VLEGACY,
    address_tables: Sequence[Tuple[bytes, Sequence[int], Sequence[int]]] = (),
) -> bytes:
    """Serialize a txn.  instrs: (program_id_idx, acct_idxs, data)."""
    # parse() reads the count as a raw u8 (valid range where u8 == cu16)
    assert len(signatures) <= SIG_MAX, "signature count must fit a u8"
    out = bytearray()
    out += cu16_encode(len(signatures))
    for s in signatures:
        assert len(s) == 64
        out += s
    if version == V0:
        out += bytes([0x80, len(signatures)])
    else:
        out += bytes([len(signatures)])
    out += bytes([readonly_signed_cnt, readonly_unsigned_cnt])
    out += cu16_encode(len(acct_addrs))
    for a in acct_addrs:
        assert len(a) == 32
        out += a
    assert len(recent_blockhash) == 32
    out += recent_blockhash
    out += cu16_encode(len(instrs))
    for pid, accts, data in instrs:
        out += bytes([pid])
        out += cu16_encode(len(accts))
        out += bytes(accts)
        out += cu16_encode(len(data))
        out += data
    if version == V0:
        out += cu16_encode(len(address_tables))
        for addr, writable, readonly in address_tables:
            assert len(addr) == 32
            out += addr
            out += cu16_encode(len(writable))
            out += bytes(writable)
            out += cu16_encode(len(readonly))
            out += bytes(readonly)
    return bytes(out)


def message_bounds(desc: TxnDesc, payload_len: int) -> Tuple[int, int]:
    """(offset, length) of the signed message region."""
    return desc.message_off, payload_len - desc.message_off


def extract_sigverify_batch(
    payloads: Sequence[bytes],
    descs: Sequence[TxnDesc],
    max_msg_len: int = MTU,
):
    """Pack parsed txns into the verify kernel's batch arrays.

    Expands each txn into one lane PER SIGNATURE (signer pubkey j signs the
    same message with signature j — fd_txn_verify behavior,
    /root/reference/src/app/fdctl/run/tiles/fd_verify.h:43-88).

    Returns (msgs (N, max_msg_len) u8, lens (N,) i32, sigs (N, 64) u8,
    pubs (N, 32) u8, txn_idx (N,) i32 mapping lanes back to txns).
    """
    msgs, lens, sigs, pubs, idxs = [], [], [], [], []
    for t, (p, d) in enumerate(zip(payloads, descs)):
        m = d.message(p)
        # MTU-constrained bound on sigs any parseable txn can carry
        assert d.signature_cnt <= ACTUAL_SIG_MAX, "unreachable for MTU txns"
        for j in range(d.signature_cnt):
            msgs.append(m)
            lens.append(len(m))
            sigs.append(p[d.signature_off + 64 * j : d.signature_off + 64 * (j + 1)])
            pubs.append(d.acct_addr(p, j))
            idxs.append(t)
    n = len(msgs)
    msg_arr = np.zeros((n, max_msg_len), dtype=np.uint8)
    for k, m in enumerate(msgs):
        msg_arr[k, : len(m)] = np.frombuffer(m, dtype=np.uint8)
    return (
        msg_arr,
        np.asarray(lens, np.int32),
        np.frombuffer(b"".join(sigs), np.uint8).reshape(n, 64),
        np.frombuffer(b"".join(pubs), np.uint8).reshape(n, 32),
        np.asarray(idxs, np.int32),
    )
