"""Zstandard frame codec (RFC 8878), store-mode.

Reference model: src/ballet/zstd/ (a streaming wrapper over the vendored
zstd library, used by snapshot load).  This build implements the frame
format natively instead of vendoring: the compressor emits fully valid
zstd frames using raw and RLE blocks (RLE alone compresses the zero-heavy
account images snapshots are made of), and the decompressor handles raw
and RLE blocks with frame-header parsing and XXH64 content checksums.
FSE/Huffman entropy blocks (block type 2) are not implemented yet —
frames produced by other encoders at compression levels > store are
rejected loudly, never mis-decoded.

XXH64 is implemented from the public spec (derived constants: the five
primes are the standard xxhash primes).
"""

from __future__ import annotations

import struct

_MAGIC = 0xFD2FB528

_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0x85EBCA77C2B2AE63
_P5 = 0x27D4EB2F165667C5
_M64 = (1 << 64) - 1


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M64


def xxh64(data: bytes, seed: int = 0) -> int:
    """XXH64; dispatches to the native helper (tango/native/fdt_sha512.c)
    — the pure-python ladder below is the spec reference and fallback."""
    try:
        from firedancer_tpu.tango import rings as R

        return int(R._lib.fdt_xxh64(bytes(data), len(data), seed))
    except ImportError:
        pass
    return _xxh64_py(data, seed)


def _xxh64_py(data: bytes, seed: int = 0) -> int:
    n = len(data)
    i = 0
    if n >= 32:
        v1 = (seed + _P1 + _P2) & _M64
        v2 = (seed + _P2) & _M64
        v3 = seed
        v4 = (seed - _P1) & _M64
        while i + 32 <= n:
            for j, v in enumerate((v1, v2, v3, v4)):
                (lane,) = struct.unpack_from("<Q", data, i + 8 * j)
                v = (v + lane * _P2) & _M64
                v = _rotl(v, 31)
                v = (v * _P1) & _M64
                if j == 0:
                    v1 = v
                elif j == 1:
                    v2 = v
                elif j == 2:
                    v3 = v
                else:
                    v4 = v
            i += 32
        h = (
            _rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12) + _rotl(v4, 18)
        ) & _M64
        for v in (v1, v2, v3, v4):
            v = (v * _P2) & _M64
            v = _rotl(v, 31)
            v = (v * _P1) & _M64
            h = ((h ^ v) * _P1 + _P4) & _M64
    else:
        h = (seed + _P5) & _M64
    h = (h + n) & _M64
    while i + 8 <= n:
        (lane,) = struct.unpack_from("<Q", data, i)
        k = _rotl((lane * _P2) & _M64, 31) * _P1 & _M64
        h = ((_rotl(h ^ k, 27) * _P1) + _P4) & _M64
        i += 8
    if i + 4 <= n:
        (lane,) = struct.unpack_from("<I", data, i)
        h = ((_rotl(h ^ (lane * _P1 & _M64), 23) * _P2) + _P3) & _M64
        i += 4
    while i < n:
        h = (_rotl(h ^ (data[i] * _P5 & _M64), 11) * _P1) & _M64
        i += 1
    h ^= h >> 33
    h = (h * _P2) & _M64
    h ^= h >> 29
    h = (h * _P3) & _M64
    h ^= h >> 32
    return h


class Xxh64Stream:
    """Incremental XXH64 (spec streaming form): O(1) state — four lane
    accumulators over 32-byte stripes plus a <32-byte tail buffer."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.v = [
            (seed + _P1 + _P2) & _M64, (seed + _P2) & _M64,
            seed, (seed - _P1) & _M64,
        ]
        self.tail = bytearray()
        self.total = 0

    def update(self, data: bytes) -> "Xxh64Stream":
        self.total += len(data)
        buf = self.tail + data
        n = (len(buf) // 32) * 32
        v1, v2, v3, v4 = self.v
        for i in range(0, n, 32):
            lanes = struct.unpack_from("<QQQQ", buf, i)
            v1 = (_rotl((v1 + lanes[0] * _P2) & _M64, 31) * _P1) & _M64
            v2 = (_rotl((v2 + lanes[1] * _P2) & _M64, 31) * _P1) & _M64
            v3 = (_rotl((v3 + lanes[2] * _P2) & _M64, 31) * _P1) & _M64
            v4 = (_rotl((v4 + lanes[3] * _P2) & _M64, 31) * _P1) & _M64
        self.v = [v1, v2, v3, v4]
        self.tail = bytearray(buf[n:])
        return self

    def digest(self) -> int:
        if self.total < 32:
            return _xxh64_py(bytes(self.tail), self.seed)
        v1, v2, v3, v4 = self.v
        h = (
            _rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12) + _rotl(v4, 18)
        ) & _M64
        for v in (v1, v2, v3, v4):
            v = (_rotl((v * _P2) & _M64, 31) * _P1) & _M64
            h = ((h ^ v) * _P1 + _P4) & _M64
        h = (h + self.total) & _M64
        data, i, n = bytes(self.tail), 0, len(self.tail)
        while i + 8 <= n:
            (lane,) = struct.unpack_from("<Q", data, i)
            k = _rotl((lane * _P2) & _M64, 31) * _P1 & _M64
            h = ((_rotl(h ^ k, 27) * _P1) + _P4) & _M64
            i += 8
        if i + 4 <= n:
            (lane,) = struct.unpack_from("<I", data, i)
            h = ((_rotl(h ^ (lane * _P1 & _M64), 23) * _P2) + _P3) & _M64
            i += 4
        while i < n:
            h = (_rotl(h ^ (data[i] * _P5 & _M64), 11) * _P1) & _M64
            i += 1
        h ^= h >> 33
        h = (h * _P2) & _M64
        h ^= h >> 29
        h = (h * _P3) & _M64
        h ^= h >> 32
        return h


_MAX_BLOCK = (1 << 17)  # 128 KiB


def compress(data: bytes) -> bytes:
    """One zstd frame: single-segment, content size + checksum present,
    raw blocks with RLE detection per 128 KiB block."""
    out = bytearray(struct.pack("<I", _MAGIC))
    # frame header descriptor: FCS 8-byte (11b), single-segment, checksum
    out.append(0b11_1_0_0_1_00)
    out += struct.pack("<Q", len(data))
    n = len(data)
    if n == 0:
        out += struct.pack("<I", 1)[:3]  # last=1, type raw, size 0
    off = 0
    while off < n:
        blk = data[off : off + _MAX_BLOCK]
        off += len(blk)
        last = 1 if off >= n else 0
        if len(blk) > 1 and blk.count(blk[0]) == len(blk):
            hdr = last | (1 << 1) | (len(blk) << 3)  # RLE
            out += struct.pack("<I", hdr)[:3]
            out.append(blk[0])
        else:
            hdr = last | (0 << 1) | (len(blk) << 3)  # raw
            out += struct.pack("<I", hdr)[:3]
            out += blk
    out += struct.pack("<I", xxh64(data) & 0xFFFFFFFF)
    return bytes(out)


class ZstdError(ValueError):
    pass


class StreamCompressor:
    """Incremental zstd frame writer (store-mode blocks), O(block) memory.

    The frame header omits the content size (streaming producers don't
    know it) and the checksum (computing xxh64 would need the whole
    stream; snapshot integrity is carried by the accounts-hash manifest
    gate instead).  Usage: out += write(chunk)...; out += finish().
    """

    def __init__(self):
        #: window descriptor: exponent 7 -> window log 17 (= _MAX_BLOCK)
        self._header = struct.pack("<I", _MAGIC) + bytes([0b00_0_0_0_0_00, 7 << 3])
        self._buf = bytearray()
        self._done = False

    def _block(self, blk: bytes, last: int) -> bytes:
        if len(blk) > 1 and blk.count(blk[0]) == len(blk):
            hdr = last | (1 << 1) | (len(blk) << 3)  # RLE
            return struct.pack("<I", hdr)[:3] + blk[:1]
        return struct.pack("<I", last | (len(blk) << 3))[:3] + blk

    def write(self, data: bytes) -> bytes:
        assert not self._done
        out = bytearray()
        if self._header:
            out += self._header
            self._header = b""
        self._buf += data
        while len(self._buf) > _MAX_BLOCK:
            out += self._block(bytes(self._buf[:_MAX_BLOCK]), 0)
            del self._buf[:_MAX_BLOCK]
        return bytes(out)

    def finish(self) -> bytes:
        assert not self._done
        self._done = True
        out = bytearray(self._header)
        out += self._block(bytes(self._buf), 1)
        self._buf.clear()
        return bytes(out)


class StreamDecompressor:
    """Incremental zstd frame reader for store-mode frames, O(block)
    memory: feed() compressed bytes, collect returned plaintext.  Sets
    .eof after the last block (+ checksum when the frame carries one)."""

    def __init__(self):
        self._buf = bytearray()
        self._state = "header"
        self._checksum = False
        self._fcs = None
        self._out_len = 0
        self._hash_parts: Xxh64Stream | None = None
        self.eof = False

    def feed(self, data: bytes) -> bytes:
        self._buf += data
        out = bytearray()
        while True:
            if self._state == "header":
                if len(self._buf) < 6:
                    break
                if struct.unpack_from("<I", self._buf, 0)[0] != _MAGIC:
                    raise ZstdError("bad magic")
                fhd = self._buf[4]
                off = 5
                single = (fhd >> 5) & 1
                self._checksum = bool((fhd >> 2) & 1)
                did_sz = (0, 1, 2, 4)[fhd & 3]
                fcs_flag = fhd >> 6
                if not single:
                    off += 1
                off += did_sz
                fcs_sz = {0: (1 if single else 0), 1: 2, 2: 4, 3: 8}[fcs_flag]
                if len(self._buf) < off + fcs_sz:
                    break
                if fcs_sz:
                    self._fcs = int.from_bytes(
                        self._buf[off : off + fcs_sz], "little"
                    )
                    if fcs_flag == 1:
                        self._fcs += 256
                    off += fcs_sz
                if self._checksum:
                    self._hash_parts = Xxh64Stream()
                del self._buf[:off]
                self._state = "block"
            elif self._state == "block":
                if len(self._buf) < 3:
                    break
                hdr = int.from_bytes(self._buf[:3], "little")
                last, btype, bsize = hdr & 1, (hdr >> 1) & 3, hdr >> 3
                if btype == 0:
                    need = 3 + bsize
                    if len(self._buf) < need:
                        break
                    blk = bytes(self._buf[3:need])
                elif btype == 1:
                    need = 4
                    if len(self._buf) < need:
                        break
                    blk = self._buf[3:4] * bsize
                elif btype == 2:
                    raise ZstdError(
                        "entropy-coded block: streaming decoder handles "
                        "store-mode frames only"
                    )
                else:
                    raise ZstdError("reserved block type")
                del self._buf[:need]
                out += blk
                self._out_len += len(blk)
                if self._hash_parts is not None:
                    self._hash_parts.update(bytes(blk))
                if last:
                    self._state = "checksum" if self._checksum else "done"
            elif self._state == "checksum":
                if len(self._buf) < 4:
                    break
                (want,) = struct.unpack_from("<I", self._buf, 0)
                got = self._hash_parts.digest() & 0xFFFFFFFF
                if got != want:
                    raise ZstdError("content checksum mismatch")
                del self._buf[:4]
                self._state = "done"
            else:  # done
                if self._fcs is not None and self._fcs != self._out_len:
                    raise ZstdError("content size mismatch")
                self.eof = True
                break
        return bytes(out)


def decompress(frame: bytes) -> bytes:
    """Decode one zstd frame (raw + RLE blocks; entropy-coded blocks from
    external encoders raise ZstdError)."""
    if len(frame) < 5 or struct.unpack_from("<I", frame, 0)[0] != _MAGIC:
        raise ZstdError("bad magic")
    fhd = frame[4]
    off = 5
    single = (fhd >> 5) & 1
    checksum = (fhd >> 2) & 1
    did_sz = (0, 1, 2, 4)[fhd & 3]
    fcs_flag = fhd >> 6
    if not single:
        off += 1  # window descriptor
    off += did_sz
    fcs = None
    fcs_sz = {0: (1 if single else 0), 1: 2, 2: 4, 3: 8}[fcs_flag]
    if fcs_sz:
        fcs = int.from_bytes(frame[off : off + fcs_sz], "little")
        if fcs_flag == 1:
            fcs += 256
        off += fcs_sz
    out = bytearray()
    while True:
        if off + 3 > len(frame):
            raise ZstdError("truncated block header")
        hdr = int.from_bytes(frame[off : off + 3], "little")
        off += 3
        last, btype, bsize = hdr & 1, (hdr >> 1) & 3, hdr >> 3
        if btype == 0:  # raw
            if off + bsize > len(frame):
                raise ZstdError("truncated raw block")
            out += frame[off : off + bsize]
            off += bsize
        elif btype == 1:  # RLE
            if off >= len(frame):
                raise ZstdError("truncated rle block")
            out += frame[off : off + 1] * bsize
            off += 1
        elif btype == 2:
            # entropy-coded block (FSE/Huffman): not decoded natively yet
            # — delegate the whole frame to the zstandard module when the
            # environment provides one, else fail loudly (never
            # mis-decode).  decompressobj handles frames without a
            # content-size field (streaming producers); foreign errors
            # are wrapped into this module's type.
            try:
                import zstandard  # noqa: PLC0415
            except ImportError:
                raise ZstdError(
                    "entropy-coded block: native decoder handles "
                    "store-mode frames only and no zstandard module is "
                    "available"
                ) from None
            try:
                return zstandard.ZstdDecompressor().decompressobj().decompress(
                    frame
                )
            except zstandard.ZstdError as e:
                raise ZstdError(f"delegated decode failed: {e}") from None
        else:
            raise ZstdError("reserved block type")
        if last:
            break
    if checksum:
        if off + 4 > len(frame):
            raise ZstdError("missing checksum")
        (want,) = struct.unpack_from("<I", frame, off)
        if xxh64(bytes(out)) & 0xFFFFFFFF != want:
            raise ZstdError("content checksum mismatch")
    if fcs is not None and fcs != len(out):
        raise ZstdError("content size mismatch")
    return bytes(out)
