"""Weighted sampling (leader schedule / Turbine tree).

Behavior contract: src/ballet/wsample/fd_wsample.c — sample x uniform in
[0, total_unremoved_weight) via the rng's roll, then pick the element
whose cumulative-weight interval contains x, in insertion order (the
reference's left-sum radix tree computes exactly this mapping in O(log
n); here a numpy cumsum + searchsorted does the same in O(log n) per
query after O(n) prep, with O(n) weight updates on removal — fine for
the thousands-of-validators scale this is used at).
"""

from __future__ import annotations

import numpy as np

EMPTY = (1 << 64) - 1  # FD_WSAMPLE_EMPTY


class WSample:
    def __init__(self, rng, weights, restore_enabled: bool = True):
        """rng: ChaCha20Rng (or anything with .roll(n)); weights: ints > 0
        in insertion order (for leader schedule: stake-descending)."""
        self.rng = rng
        self._w0 = np.asarray(weights, dtype=np.uint64)
        assert (self._w0 > 0).all()
        self.restore_enabled = restore_enabled
        self._w = self._w0.copy()
        self._rebuild()

    def _rebuild(self) -> None:
        self._cum = np.cumsum(self._w, dtype=np.uint64)
        self.unremoved_weight = int(self._cum[-1]) if len(self._w) else 0

    def _map(self, x: int) -> int:
        # first i with cum[i] > x
        return int(np.searchsorted(self._cum, x, side="right"))

    def sample(self) -> int:
        if not self.unremoved_weight:
            return EMPTY
        return self._map(self.rng.roll(self.unremoved_weight))

    def sample_many(self, cnt: int) -> list[int]:
        return [self.sample() for _ in range(cnt)]

    def sample_and_remove(self) -> int:
        if not self.unremoved_weight:
            return EMPTY
        i = self._map(self.rng.roll(self.unremoved_weight))
        self._w[i] = 0
        self._rebuild()
        return i

    def sample_and_remove_many(self, cnt: int) -> list[int]:
        return [self.sample_and_remove() for _ in range(cnt)]

    def remove_idx(self, i: int) -> None:
        self._w[i] = 0
        self._rebuild()

    def restore_all(self) -> None:
        assert self.restore_enabled
        self._w = self._w0.copy()
        self._rebuild()
