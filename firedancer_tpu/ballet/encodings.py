"""Base64 and hex codecs (reference: src/ballet/base64/, src/ballet/hex/).

Thin, correct host-side implementations — these feed RPC/snapshot/log
paths, not the packet hot path."""

from __future__ import annotations

import base64 as _b64
import binascii

B64_STD = "std"
B64_URL = "url"


def base64_encode(data: bytes, variant: str = B64_STD) -> str:
    f = _b64.standard_b64encode if variant == B64_STD else _b64.urlsafe_b64encode
    return f(data).decode()


def base64_decode(s: str | bytes, variant: str = B64_STD) -> bytes | None:
    f = _b64.standard_b64decode if variant == B64_STD else _b64.urlsafe_b64decode
    try:
        if isinstance(s, str):
            s = s.encode()
        # strict: reject non-alphabet chars (python is lenient by default)
        _b64.b64decode(s, validate=True) if variant == B64_STD else None
        return f(s)
    except (binascii.Error, ValueError):
        return None


def hex_encode(data: bytes) -> str:
    return data.hex()


def hex_decode(s: str) -> bytes | None:
    try:
        return bytes.fromhex(s)
    except ValueError:
        return None
