"""secp256k1 public-key recovery — the secp256k1 precompile's core.

Behavior contract: the reference vendors libsecp256k1 under
src/ballet/secp256k1/ and exposes fd_secp256k1_recover (pubkey recovery
from a 32-byte digest + 64-byte signature + recovery id), consumed by
the Keccak-Secp256k1 native program and the sol_secp256k1_recover
syscall.  This build needs correctness at precompile-instruction rates
(a handful per txn), not bulk throughput, so the curve math is direct
affine arithmetic over python ints; the batch-verify hot path stays
ed25519-on-TPU.
"""

from __future__ import annotations

# curve: y^2 = x^3 + 7 over F_P, group order N
P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
G = (
    0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798,
    0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8,
)


def _add(p1, p2):
    """Affine point addition; None is the identity."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        m = (3 * x1 * x1) * pow(2 * y1, P - 2, P) % P
    else:
        m = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (m * m - x1 - x2) % P
    return (x3, (m * (x1 - x3) - y1) % P)


def _mul(k: int, pt):
    acc = None
    while k:
        if k & 1:
            acc = _add(acc, pt)
        pt = _add(pt, pt)
        k >>= 1
    return acc


def _lift_x(x: int, odd: bool):
    """Point with the given x and y parity, or None if x is not on the
    curve."""
    if x >= P:
        return None
    ysq = (pow(x, 3, P) + 7) % P
    y = pow(ysq, (P + 1) // 4, P)
    if y * y % P != ysq:
        return None
    if (y & 1) != odd:
        y = P - y
    return (x, y)


def recover(digest: bytes, sig: bytes, recid: int):
    """Recover the signing public key -> 64-byte x||y, or None.

    digest: the 32-byte message hash; sig: r(32) || s(32) big-endian;
    recid: 0..3 (bit 0 = R.y parity, bit 1 = R.x overflowed the order).
    Standard ECDSA recovery: Q = r^-1 (s*R - e*G).
    """
    if len(digest) != 32 or len(sig) != 64 or not 0 <= recid <= 3:
        return None
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    if not (1 <= r < N and 1 <= s < N):
        return None
    x = r + (recid >> 1) * N
    R = _lift_x(x, bool(recid & 1))
    if R is None:
        return None
    e = int.from_bytes(digest, "big") % N
    rinv = pow(r, N - 2, N)
    neg_eg = _mul((N - e) % N, G)
    q = _mul(rinv, _add(_mul(s, R), neg_eg))
    if q is None:
        return None
    return q[0].to_bytes(32, "big") + q[1].to_bytes(32, "big")


def sign(digest: bytes, secret: int, k: int):
    """Deterministic-k test helper -> (sig64, recid).  NOT a hardened
    signer (no RFC 6979): exists so the precompile tests can mint valid
    signatures without a second library."""
    R = _mul(k, G)
    r = R[0] % N
    s = pow(k, N - 2, N) * (
        (int.from_bytes(digest, "big") % N + r * secret) % N
    ) % N
    recid = (R[1] & 1) | (2 if R[0] >= N else 0)
    if s > N // 2:  # low-s normalization flips the recovery parity
        s = N - s
        recid ^= 1
    return (
        r.to_bytes(32, "big") + s.to_bytes(32, "big"),
        recid,
    )


def pubkey_of(secret: int) -> bytes:
    q = _mul(secret, G)
    return q[0].to_bytes(32, "big") + q[1].to_bytes(32, "big")


def eth_address(pubkey64: bytes) -> bytes:
    """keccak256(x || y)[12:] — the 20-byte address the precompile
    compares against."""
    from firedancer_tpu.ops.keccak256 import digest_host

    return digest_host(pubkey64)[12:]
