"""SipHash-1-3 (reference: src/ballet/siphash13/ — hashmap seeding).

Host-side (seeding/cheap hashing only).  Batch variant vectorized in
numpy uint64 for bulk keying."""

from __future__ import annotations

import numpy as np

_M = (1 << 64) - 1


def _rotl(x, b):
    return ((x << np.uint64(b)) | (x >> np.uint64(64 - b))) & np.uint64(_M)


def _round(v0, v1, v2, v3):
    v0 = (v0 + v1) & np.uint64(_M)
    v1 = _rotl(v1, 13)
    v1 ^= v0
    v0 = _rotl(v0, 32)
    v2 = (v2 + v3) & np.uint64(_M)
    v3 = _rotl(v3, 16)
    v3 ^= v2
    v0 = (v0 + v3) & np.uint64(_M)
    v3 = _rotl(v3, 21)
    v3 ^= v0
    v2 = (v2 + v1) & np.uint64(_M)
    v1 = _rotl(v1, 17)
    v1 ^= v2
    v2 = _rotl(v2, 32)
    return v0, v1, v2, v3


def siphash13(k0: int, k1: int, data: bytes) -> int:
    """SipHash-1-3 of data under key (k0, k1) -> u64."""
    with np.errstate(over="ignore"):
        v0 = np.uint64(0x736F6D6570736575 ^ k0)
        v1 = np.uint64(0x646F72616E646F6D ^ k1)
        v2 = np.uint64(0x6C7967656E657261 ^ k0)
        v3 = np.uint64(0x7465646279746573 ^ k1)
        n = len(data)
        tail = (n & 0xFF) << 56
        full = n & ~7
        words = np.frombuffer(data[:full], dtype="<u8")
        last = int.from_bytes(data[full:], "little") | tail
        for m in words:
            v3 ^= m
            v0, v1, v2, v3 = _round(v0, v1, v2, v3)  # 1 compression round
            v0 ^= m
        m = np.uint64(last)
        v3 ^= m
        v0, v1, v2, v3 = _round(v0, v1, v2, v3)
        v0 ^= m
        v2 ^= np.uint64(0xFF)
        for _ in range(3):  # 3 finalization rounds
            v0, v1, v2, v3 = _round(v0, v1, v2, v3)
        return int(v0 ^ v1 ^ v2 ^ v3)
