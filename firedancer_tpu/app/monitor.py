"""Monitor: observe a running topology from OUTSIDE its process.

Reference model: src/app/fdctl/monitor/monitor.c:233 — periodically
snapshot every tile's cnc heartbeat/signal and metrics shared memory plus
every link's fseq, render the diffs.  This build attaches to the named
workspace via its published directory (tango.rings.Workspace.attach) and
reads the same single-writer regions the tiles write lock-free.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from firedancer_tpu.disco.metrics import Metrics, MetricsSchema
from firedancer_tpu.tango import rings as R

_SIGNAMES = {0: "BOOT", 1: "RUN", 2: "HALT", 3: "FAIL"}


@dataclass
class TileView:
    name: str
    metrics: Metrics
    cnc: R.CNC


class Monitor:
    """Attach-and-read view of a named topology workspace."""

    def __init__(self, wksp_name: str):
        self.wksp, extra = R.Workspace.attach(wksp_name)
        self.tiles: dict[str, TileView] = {}
        for name, t in extra.get("tiles", {}).items():
            schema = MetricsSchema(
                counters=tuple(t["counters"]), hists=tuple(t["hists"])
            )
            # schema comes pre-flattened (with_base applied by the topo)
            m = Metrics(self.wksp.view(t["metrics"]), schema)
            self.tiles[name] = TileView(
                name, m, R.CNC(self.wksp.view(t["cnc"]), join=True)
            )
        self.links = extra.get("links", {})

    def snapshot(self) -> dict:
        """One consistent-enough read of every tile's state."""
        out = {}
        for name, tv in self.tiles.items():
            out[name] = {
                "signal": _SIGNAMES.get(
                    tv.cnc.signal_query(), str(tv.cnc.signal_query())
                ),
                "heartbeat": tv.cnc.heartbeat_query(),
                "counters": {
                    c: tv.metrics.counter(c)
                    for c in tv.metrics.schema.counters
                },
            }
        for lname, ls in self.links.items():
            seqs = {}
            for c in ls["consumers"]:
                fs = R.FSeq(self.wksp.view(c["fseq"]), join=True)
                seqs[c["tile"]] = fs.query()
            out.setdefault("_links", {})[lname] = seqs
        return out

    def render(self, prev: dict | None, cur: dict, dt: float) -> str:
        """Tile table with in/out rates (frags/s) since the last snapshot."""
        lines = [
            f"{'tile':>10} {'state':>5} {'in/s':>12} {'out/s':>12} "
            f"{'in_frags':>12} {'out_frags':>12}"
        ]
        for name, row in cur.items():
            if name == "_links":
                continue
            c = row["counters"]
            if prev is not None and name in prev:
                p = prev[name]["counters"]
                rin = (c["in_frags"] - p["in_frags"]) / dt
                rout = (c["out_frags"] - p["out_frags"]) / dt
            else:
                rin = rout = 0.0
            lines.append(
                f"{name:>10} {row['signal']:>5} {rin:12,.0f} {rout:12,.0f} "
                f"{c['in_frags']:12,} {c['out_frags']:12,}"
            )
        return "\n".join(lines)

    def run(self, interval_s: float = 1.0, iterations: int | None = None):
        """Print live rates until interrupted (fdctl monitor behavior)."""
        prev = None
        i = 0
        while iterations is None or i < iterations:
            cur = self.snapshot()
            print(self.render(prev, cur, interval_s))
            print()
            prev = cur
            i += 1
            if iterations is None or i < iterations:
                time.sleep(interval_s)
