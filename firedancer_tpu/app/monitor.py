"""Monitor: observe a running topology from OUTSIDE its process.

Reference model: src/app/fdctl/monitor/monitor.c:233 — periodically
snapshot every tile's cnc heartbeat/signal and metrics shared memory plus
every link's fseq, render the diffs.  This build attaches to the named
workspace via its published directory (tango.rings.Workspace.attach) and
reads the same single-writer regions the tiles write lock-free.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from firedancer_tpu.disco.metrics import (
    Metrics,
    MetricsSchema,
    device_rows,
    hist_percentile,
)
from firedancer_tpu.tango import rings as R

#: the per-in-link latency-attribution hist prefixes the run loop
#: records (disco.mux.LINK_HIST_KINDS) — the monitor renders these as
#: per-hop percentile rows
_LAT_PREFIXES = ("qwait_us_", "svc_us_", "e2e_us_")

_SIGNAMES = {0: "BOOT", 1: "RUN", 2: "HALT", 3: "FAIL"}


def _hist_delta(cur: dict, prev: dict | None) -> dict:
    """Windowed hist: cur - prev per bucket (both are cumulative
    monotone snapshots of the same region).  No prev -> cumulative."""
    if not prev or not prev.get("count"):
        return cur
    return {
        "count": cur.get("count", 0) - prev.get("count", 0),
        "sum": cur.get("sum", 0) - prev.get("sum", 0),
        "buckets": [
            a - b
            for a, b in zip(cur.get("buckets", []), prev.get("buckets", []))
        ],
    }


@dataclass
class TileView:
    name: str
    metrics: Metrics
    cnc: R.CNC


class Monitor:
    """Attach-and-read view of a named topology workspace."""

    def __init__(self, wksp_name: str):
        self.wksp, extra = R.Workspace.attach(wksp_name)
        self.tiles: dict[str, TileView] = {}
        for name, t in extra.get("tiles", {}).items():
            schema = MetricsSchema(
                counters=tuple(t["counters"]), hists=tuple(t["hists"])
            )
            # schema comes pre-flattened (with_base applied by the topo)
            m = Metrics(self.wksp.view(t["metrics"]), schema)
            self.tiles[name] = TileView(
                name, m, R.CNC(self.wksp.view(t["cnc"]), join=True)
            )
        self.links = extra.get("links", {})

    #: heartbeat older than this is flagged as stale (reference monitor
    #: renders heartbeat diffs; a stuck tile stops beating long before
    #: the fail-stop supervisor sees it die)
    STALE_HEARTBEAT_NS = 2_000_000_000

    def snapshot(self) -> dict:
        """One consistent-enough read of every tile's state."""
        import time as _t

        now = _t.monotonic_ns()
        out = {}
        for name, tv in self.tiles.items():
            hb = tv.cnc.heartbeat_query()
            out[name] = {
                "signal": _SIGNAMES.get(
                    tv.cnc.signal_query(), str(tv.cnc.signal_query())
                ),
                "heartbeat": hb,
                "stale": bool(hb) and now - hb > self.STALE_HEARTBEAT_NS,
                "counters": {
                    c: tv.metrics.counter(c)
                    for c in tv.metrics.schema.counters
                },
                # per-hop latency attribution hists (queue-wait /
                # service / end-to-end per in-link)
                "lat_hists": {
                    h: tv.metrics.hist(h)
                    for h in tv.metrics.schema.hists
                    if h.startswith(_LAT_PREFIXES)
                },
            }
        for lname, ls in self.links.items():
            prod_seq = None
            if "mcache" in ls:
                mc = R.MCache(
                    self.wksp.view(ls["mcache"]), ls["depth"], join=True
                )
                prod_seq = mc.seq_query()
            seqs = {}
            for c in ls["consumers"]:
                fs = R.FSeq(self.wksp.view(c["fseq"]), join=True)
                cseq = fs.query()
                seqs[c["tile"]] = {
                    "seq": cseq,
                    # consumer lag behind the producer cursor, in frags
                    "lag": None
                    if prod_seq is None
                    else max(prod_seq - cseq, 0),
                }
            out.setdefault("_links", {})[lname] = {
                "produced": prod_seq,
                "consumers": seqs,
            }
        return out

    def alarms(self, snap: dict) -> list[str]:
        """Stale heartbeats, failed tiles, and supervisor degradation
        state (circuit breaker open / restart churn), as alarm lines."""
        out = []
        for name, row in snap.items():
            if name == "_links":
                continue
            c = row.get("counters", {})
            if c.get("degraded"):
                out.append(
                    f"ALARM {name}: degraded (supervisor circuit breaker "
                    f"open after {c.get('restarts', 0)} restarts)"
                )
                continue
            if row["signal"] == "FAIL":
                out.append(f"ALARM {name}: FAIL signal")
            elif row.get("stale"):
                out.append(f"ALARM {name}: heartbeat stale")
            if c.get("fallback_batches"):
                out.append(
                    f"NOTE {name}: {c['fallback_batches']} batches on the "
                    f"host fallback path"
                )
            # per-device fault domains (the verify pool): a quarantined /
            # stalled / dead device alarms as `verify0_dev3_degraded`
            # style lines — one device degrading is NOT tile degradation
            for i, row in sorted(device_rows(c).items()):
                if row.get("degraded"):
                    out.append(
                        f"ALARM {name}_dev{i}_degraded: device quarantined "
                        f"(landed {row.get('landed', 0)}, failed "
                        f"{row.get('failed', 0)})"
                    )
        return out

    def render(self, prev: dict | None, cur: dict, dt: float) -> str:
        """Tile table with in/out rates (frags/s), %backpressure, and
        per-hop latency percentiles since the last snapshot."""
        lines = [
            f"{'tile':>10} {'state':>5} {'in/s':>12} {'out/s':>12} "
            f"{'in_frags':>12} {'out_frags':>12} {'bp%':>6}"
        ]
        for name, row in cur.items():
            if name == "_links":
                continue
            c = row["counters"]
            if prev is not None and name in prev:
                p = prev[name]["counters"]
                rin = (c["in_frags"] - p["in_frags"]) / dt
                rout = (c["out_frags"] - p["out_frags"]) / dt
                d_bp = c.get("backpressure_iters", 0) - p.get(
                    "backpressure_iters", 0
                )
                d_loop = c.get("loop_iters", 0) - p.get("loop_iters", 0)
            else:
                rin = rout = 0.0
                d_bp = c.get("backpressure_iters", 0)
                d_loop = c.get("loop_iters", 0)
            # %backpressure: share of loop iterations spent with zero
            # credits (stalled behind a slow reliable consumer) in the
            # window — every backpressure iteration also counts in
            # loop_iters, so the ratio is direct
            bp_pct = 100.0 * d_bp / max(d_loop, 1)
            flag = " STALE" if row.get("stale") else ""
            if c.get("degraded"):
                flag += " DEGRADED"
            elif c.get("restarts"):
                flag += f" restarts={c['restarts']}"
            lines.append(
                f"{name:>10} {row['signal']:>5} {rin:12,.0f} {rout:12,.0f} "
                f"{c['in_frags']:12,} {c['out_frags']:12,} {bp_pct:5.1f}%"
                f"{flag}"
            )
            # per-hop latency sub-rows: queue-wait / end-to-end
            # percentiles per in-link (the qwait/svc/e2e hists the run
            # loop records in the compressed-µs domain), windowed
            # against the previous snapshot like bp% — a regression
            # hours into a run must move the displayed p99 within one
            # refresh, not be pinned by cumulative history
            links = sorted(
                {
                    h[len("qwait_us_"):]
                    for h in row.get("lat_hists", {})
                    if h.startswith("qwait_us_")
                }
            )
            p_hists = (
                prev[name].get("lat_hists", {})
                if prev is not None and name in prev
                else {}
            )
            for ln in links:
                hq = _hist_delta(
                    row["lat_hists"].get(f"qwait_us_{ln}", {}),
                    p_hists.get(f"qwait_us_{ln}"),
                )
                he = _hist_delta(
                    row["lat_hists"].get(f"e2e_us_{ln}", {}),
                    p_hists.get(f"e2e_us_{ln}"),
                )
                if not hq.get("count") and not he.get("count"):
                    continue
                lines.append(
                    f"{'':>10}   lat {ln}: "
                    f"qwait p50={hist_percentile(hq, 50):,.0f}us "
                    f"p99={hist_percentile(hq, 99):,.0f}us | "
                    f"e2e p50={hist_percentile(he, 50):,.0f}us "
                    f"p99={hist_percentile(he, 99):,.0f}us"
                )
            # device-pool health sub-rows (tiles exporting dev{i}_*
            # counters — the multi-device verify scale-out)
            devs = device_rows(c)
            if len(devs) > 1 or any(
                r.get("degraded") for r in devs.values()
            ):
                for i, r in sorted(devs.items()):
                    dflag = " DEGRADED" if r.get("degraded") else ""
                    lines.append(
                        f"{'':>10}   dev{i}: depth={r.get('depth', 0)} "
                        f"inflight={r.get('inflight', 0)} "
                        f"landed={r.get('landed', 0):,} "
                        f"failed={r.get('failed', 0)}{dflag}"
                    )
        for lname, ls in cur.get("_links", {}).items():
            for tile, s in ls["consumers"].items():
                if s["lag"]:
                    lines.append(
                        f"{'':>10} link {lname} -> {tile}: lag {s['lag']:,}"
                    )
        lines.extend(self.alarms(cur))
        return "\n".join(lines)

    def run(self, interval_s: float = 1.0, iterations: int | None = None):
        """Print live rates until interrupted (fdctl monitor behavior)."""
        prev = None
        i = 0
        while iterations is None or i < iterations:
            cur = self.snapshot()
            print(self.render(prev, cur, interval_s))
            print()
            prev = cur
            i += 1
            if iterations is None or i < iterations:
                time.sleep(interval_s)
